"""tools/selfcheck.py as the tier-1 seam against tool rot: discovery sees
every --self-test-capable tool and the full toolbox passes in subprocesses
(argument parsing, imports, exit codes — the operator-facing surface)."""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(__file__))
TOOL = os.path.join(REPO, "tools", "selfcheck.py")


def _run(*args, timeout=420):
    return subprocess.run([sys.executable, TOOL, *args],
                          capture_output=True, text=True, timeout=timeout)


def test_selfcheck_self_test():
    res = _run("--self-test", timeout=120)
    assert res.returncode == 0, res.stdout + res.stderr
    assert "self-test OK" in res.stdout


def test_discovery_sees_the_toolbox():
    res = _run("--list", timeout=60)
    assert res.returncode == 0, res.stderr
    tools = set(res.stdout.split())
    assert {"trace_summary.py", "trace_merge.py", "fleet_scrape.py",
            "bench_compare.py", "chaos_matrix.py", "device_profile.py",
            "loadtime.py", "churn.py", "crashmatrix.py",
            "aggsig_bench.py", "soak.py"} <= tools
    # the eight ad-hoc probe scripts device_profile.py consolidates are gone
    assert not any(t.startswith(("relay_probe", "exp_10k")) for t in tools)
    assert "selfcheck.py" not in tools


def test_unknown_only_errors():
    res = _run("--only", "no_such_tool", timeout=60)
    assert res.returncode == 2
    assert "unknown tools" in res.stderr


def test_full_toolbox_passes():
    """Every tools/*.py --self-test, each in a fresh subprocess. This IS
    the CI guard the satellite asks for: any tool rot fails tier-1."""
    res = _run()
    assert res.returncode == 0, res.stdout + res.stderr
    lines = [l for l in res.stdout.splitlines() if l.startswith("PASS ")]
    assert len(lines) >= 11, res.stdout
    assert "FAIL" not in res.stdout
