"""Randomized manifest generator (reference test/e2e/generator/generate.go).

Fast tier: determinism, validity, and coverage of the sampled space across
many seeds. Nightly tier (-m nightly): actually run one generated net
through the full runner stage pipeline.
"""

import random

import pytest


from tendermint_tpu.e2e import Manifest, Runner
from tendermint_tpu.e2e.generate import doc_to_toml, generate, generate_one


def test_generate_deterministic():
    a = generate(seed=7, count=4)
    b = generate(seed=7, count=4)
    assert [t for _, _, t in a] == [t for _, _, t in b]
    c = generate(seed=8, count=4)
    assert [t for _, _, t in a] != [t for _, _, t in c]


def test_generate_all_validate():
    """Every sampled manifest passes Manifest validation (the generator must
    respect the same constraints the loader enforces)."""
    for seed in range(40):
        for _name, m, _toml in generate(seed=seed, count=3):
            assert sum(1 for n in m.nodes if n.mode == "validator") >= 2
            # perturbed nets keep quorum: validator0 is never perturbed
            v0 = next(n for n in m.nodes if n.name == "validator0")
            assert not v0.perturb and not v0.misbehaviors


def test_generate_covers_the_space():
    """Across seeds the sampler actually hits each dimension (a generator
    that never emits a state-sync joiner tests nothing)."""
    seen = set()
    for seed in range(60):
        for _name, m, _toml in generate(seed=seed, count=3):
            for n in m.nodes:
                if n.mempool_version == "v2":
                    seen.add("mempool-v2")
                if n.privval == "tcp":
                    seen.add("privval-tcp")
                if n.state_sync:
                    seen.add("state-sync")
                if n.start_at > 0:
                    seen.add("late-join")
                if n.mode == "full":
                    seen.add("full-node")
                for p in n.perturb:
                    seen.add(f"perturb-{p}")
                if n.misbehaviors:
                    seen.add("misbehavior")
    missing = {
        "mempool-v2", "privval-tcp", "state-sync", "late-join", "full-node",
        "misbehavior", "perturb-kill", "perturb-restart", "perturb-pause",
        "perturb-disconnect",
    } - seen
    assert not missing, f"sampler never produced: {sorted(missing)}"


def test_toml_round_trip_preserves_structure():
    rng = random.Random(3)
    for idx in range(10):
        _name, doc = generate_one(rng, idx)
        from tendermint_tpu.libs import toml_compat

        parsed = toml_compat.loads(doc_to_toml(doc))
        assert parsed["chain_id"] == doc["chain_id"]
        assert set(parsed["node"]) == set(doc["node"])
        for name, node in doc["node"].items():
            for k, v in node.items():
                if k == "misbehaviors":
                    assert {int(h): m for h, m in parsed["node"][name][k].items()} \
                        == {int(h): m for h, m in v.items()}
                else:
                    assert parsed["node"][name][k] == v


@pytest.mark.nightly
def test_generated_net_runs(tmp_path):
    """Nightly tier: one seeded net through the real runner pipeline."""
    pytest.importorskip(
        "cryptography",
        reason="the subprocess net's TCP transport needs the optional "
               "'cryptography' package (absent in slim containers)")
    _name, manifest, _toml = generate(seed=11, count=1)[0]
    r = Runner(manifest, str(tmp_path / "net"), base_port=29480)
    r.run()
