"""PEX reactor + address book: discovery across a TCP net, book persistence,
bias/eviction, request-flood defense
(reference p2p/pex/pex_reactor.go, addrbook.go).
"""

import asyncio

import pytest

pytest.importorskip("cryptography", reason="needs the optional 'cryptography' package (absent in slim containers)")

from tendermint_tpu import crypto
from tendermint_tpu.p2p import NetAddress, NodeInfo, NodeKey, Switch, TCPTransport
from tendermint_tpu.p2p.pex import (
    AddrBook,
    PEXReactor,
    decode_pex_msg,
    encode_pex_addrs,
    encode_pex_request,
)
from tests.test_p2p_tcp import EchoReactor


def test_addrbook_buckets_persistence(tmp_path):
    path = str(tmp_path / "addrbook.json")
    book = AddrBook(path)
    a1 = NetAddress("aa" * 20, "127.0.0.1", 1001)
    a2 = NetAddress("bb" * 20, "127.0.0.1", 1002)
    assert book.add_address(a1, src_id="src")
    assert not book.add_address(a1)  # dup
    assert book.add_address(a2)
    book.mark_good(a1.id)
    book.mark_attempt(a2)
    book.save()

    book2 = AddrBook(path)
    assert book2.size() == 2
    assert book2._addrs[a1.id].bucket == "old"
    assert book2._addrs[a2.id].attempts == 1
    # old-bucket bias in selections
    sel = book2.get_selection(1)
    assert sel and sel[0].id == a1.id


def test_pex_wire_round_trip():
    addrs = [NetAddress("cc" * 20, "10.0.0.1", 26656),
             NetAddress("dd" * 20, "10.0.0.2", 26657)]
    kind, payload = decode_pex_msg(encode_pex_addrs(addrs))
    assert kind == "addrs" and payload == addrs
    kind, _ = decode_pex_msg(encode_pex_request())
    assert kind == "request"


def _mk_switch(seed, book=None, target=10, **pex_kw):
    nk = NodeKey(crypto.Ed25519PrivKey.generate(seed))
    er = EchoReactor()
    pex = PEXReactor(book or AddrBook(), target_outbound=target,
                     ensure_interval=0.1, request_interval=0.2, **pex_kw)
    descs = er.get_channels() + pex.get_channels()
    info = NodeInfo(node_id=nk.id, network="pex-net",
                    channels=bytes(d.id for d in descs))
    sw = Switch(nk.id, transport=TCPTransport(nk, info, descs))
    sw.add_reactor("ECHO", er)
    sw.add_reactor("PEX", pex)
    return sw, pex, nk


def test_pex_discovers_peers_transitively():
    """C knows only B; B knows A; via PEX, C learns A's address and dials it
    (the reference's peer-discovery loop)."""
    async def run():
        sw_a, pex_a, nk_a = _mk_switch(b"\xd1" * 32)
        sw_b, pex_b, nk_b = _mk_switch(b"\xd2" * 32)
        sw_c, pex_c, nk_c = _mk_switch(b"\xd3" * 32)
        for sw in (sw_a, sw_b, sw_c):
            await sw.start()
        addr_a = await sw_a.listen("127.0.0.1", 0)
        addr_b = await sw_b.listen("127.0.0.1", 0)
        await sw_c.listen("127.0.0.1", 0)
        try:
            assert await sw_b.dial_peer(addr_a)
            assert await sw_c.dial_peer(addr_b)
            # C should learn about A from B and connect
            for _ in range(600):
                if nk_a.id in sw_c.peers:
                    break
                await asyncio.sleep(0.02)
            assert nk_a.id in sw_c.peers, "PEX did not discover A"
            assert pex_c.book.has(nk_a.id)
        finally:
            for sw in (sw_c, sw_b, sw_a):
                await sw.stop()
    asyncio.run(run())


def test_addrbook_eviction_under_flood():
    """Adversarial address flooding: the new bucket is capped; eviction
    prefers most-failed never-succeeded entries, and proven (old-bucket)
    addresses are never evicted by floods (addrbook.go eviction)."""
    from tendermint_tpu.p2p.pex import NEW_BUCKET_CAP

    book = AddrBook(strict=False)
    good = NetAddress("aa" * 20, "10.0.0.1", 1)
    book.add_address(good, src_id="me")
    book.mark_good(good.id)
    # a failed address is the preferred eviction victim
    bad = NetAddress("bb" * 20, "10.0.0.2", 2)
    book.add_address(bad)
    book.mark_attempt(bad)
    book.mark_attempt(bad)
    # flood with unique addresses from one source
    for i in range(NEW_BUCKET_CAP + 50):
        a = NetAddress(f"{i:040x}", f"10.{(i >> 16) & 255}.{(i >> 8) & 255}.{i & 255}",
                       1000 + (i % 1000))
        book.add_address(a, src_id="attacker")
    # bounded: never grows past cap + old entries
    n_new = sum(1 for k in book._addrs.values() if k.bucket == "new")
    assert n_new <= NEW_BUCKET_CAP
    assert book._addrs[good.id].bucket == "old"  # survivor
    assert bad.id not in book._addrs  # most-failed got evicted first


def test_addrbook_strict_rejects_unroutable_and_self():
    book = AddrBook(strict=True)
    book.add_our_address("cc" * 20)
    assert not book.add_address(NetAddress("cc" * 20, "1.2.3.4", 1))  # self
    assert not book.add_address(NetAddress("dd" * 20, "0.0.0.0", 1))
    assert not book.add_address(NetAddress("ee" * 20, "", 1))
    assert book.add_address(NetAddress("ff" * 20, "127.0.0.1", 1))


def test_seed_mode_serves_and_disconnects():
    """A seed-mode node hands inbound peers an address selection and hangs
    up; its crawler re-dials book addresses to keep them fresh
    (pex_reactor.go seed branch + crawlPeersRoutine)."""
    from tests.test_pex import _mk_switch  # self-import for clarity

    async def run():
        # seed knows A; client dials seed, must learn A and get disconnected
        sw_a, pex_a, nk_a = _mk_switch(b"\xe1" * 32)
        sw_seed, pex_seed, nk_seed = _mk_switch(
            b"\xe2" * 32, seed_mode=True, seed_disconnect_wait=0.3)
        sw_c, pex_c, nk_c = _mk_switch(b"\xe3" * 32)
        for sw in (sw_a, sw_seed, sw_c):
            await sw.start()
        addr_a = await sw_a.listen("127.0.0.1", 0)
        addr_seed = await sw_seed.listen("127.0.0.1", 0)
        await sw_c.listen("127.0.0.1", 0)
        try:
            pex_seed.book.add_address(addr_a, src_id="op")
            assert await sw_c.dial_peer(addr_seed)
            # the client's ensure-peers loop requests; the seed answers
            for _ in range(600):
                if pex_c.book.has(nk_a.id):
                    break
                await asyncio.sleep(0.02)
            assert pex_c.book.has(nk_a.id), "client never learned A from seed"
            # and the seed hangs up shortly after serving
            for _ in range(600):
                if nk_seed.id not in sw_c.peers:
                    break
                await asyncio.sleep(0.02)
            assert nk_seed.id not in sw_c.peers, "seed kept the conn open"
        finally:
            for sw in (sw_c, sw_seed, sw_a):
                await sw.stop()

    asyncio.run(run())
