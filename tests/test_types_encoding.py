"""Wire-format parity tests for the types layer.

Golden vectors lifted from the reference's own test suite
(types/vote_test.go:60-133 TestVoteSignBytesTestVectors) — byte-for-byte.
"""

import pytest

from tendermint_tpu.libs import protowire as pw
from tendermint_tpu.types import (
    BlockID,
    BlockIDFlag,
    PartSetHeader,
    SignedMsgType,
    Validator,
    ValidatorSet,
    Vote,
    ZERO_TIME_NS,
)
from tendermint_tpu.types.block import Commit, CommitSig, Consensus, Header
from tendermint_tpu.types.canonical import vote_sign_bytes
from tendermint_tpu.types.proposal import Proposal
from tendermint_tpu import crypto


# --- golden vectors: reference types/vote_test.go:60 -----------------------

GOLDEN_VOTE_SIGN_BYTES = [
    # (chain_id, type, height, round, expected hex)
    ("", SignedMsgType.UNKNOWN, 0, 0,
     bytes([0xd, 0x2a, 0xb, 0x8, 0x80, 0x92, 0xb8, 0xc3, 0x98, 0xfe, 0xff, 0xff, 0xff, 0x1])),
    ("", SignedMsgType.PRECOMMIT, 1, 1,
     bytes([0x21, 0x8, 0x2,
            0x11, 0x1, 0x0, 0x0, 0x0, 0x0, 0x0, 0x0, 0x0,
            0x19, 0x1, 0x0, 0x0, 0x0, 0x0, 0x0, 0x0, 0x0,
            0x2a, 0xb, 0x8, 0x80, 0x92, 0xb8, 0xc3, 0x98, 0xfe, 0xff, 0xff, 0xff, 0x1])),
    ("", SignedMsgType.PREVOTE, 1, 1,
     bytes([0x21, 0x8, 0x1,
            0x11, 0x1, 0x0, 0x0, 0x0, 0x0, 0x0, 0x0, 0x0,
            0x19, 0x1, 0x0, 0x0, 0x0, 0x0, 0x0, 0x0, 0x0,
            0x2a, 0xb, 0x8, 0x80, 0x92, 0xb8, 0xc3, 0x98, 0xfe, 0xff, 0xff, 0xff, 0x1])),
    ("", SignedMsgType.UNKNOWN, 1, 1,
     bytes([0x1f,
            0x11, 0x1, 0x0, 0x0, 0x0, 0x0, 0x0, 0x0, 0x0,
            0x19, 0x1, 0x0, 0x0, 0x0, 0x0, 0x0, 0x0, 0x0,
            0x2a, 0xb, 0x8, 0x80, 0x92, 0xb8, 0xc3, 0x98, 0xfe, 0xff, 0xff, 0xff, 0x1])),
    ("test_chain_id", SignedMsgType.UNKNOWN, 1, 1,
     bytes([0x2e,
            0x11, 0x1, 0x0, 0x0, 0x0, 0x0, 0x0, 0x0, 0x0,
            0x19, 0x1, 0x0, 0x0, 0x0, 0x0, 0x0, 0x0, 0x0,
            0x2a, 0xb, 0x8, 0x80, 0x92, 0xb8, 0xc3, 0x98, 0xfe, 0xff, 0xff, 0xff, 0x1,
            0x32, 0xd]) + b"test_chain_id"),
]


def test_vote_sign_bytes_golden_vectors():
    for i, (chain_id, t, h, r, want) in enumerate(GOLDEN_VOTE_SIGN_BYTES):
        got = vote_sign_bytes(chain_id, t, h, r, BlockID(), ZERO_TIME_NS)
        assert got == want, f"vector #{i}: {got.hex()} != {want.hex()}"


def test_zero_time_timestamp_encoding():
    # Go's zero time encodes as seconds=-62135596800 (10-byte varint).
    assert pw.timestamp(ZERO_TIME_NS) == bytes(
        [0x8, 0x80, 0x92, 0xb8, 0xc3, 0x98, 0xfe, 0xff, 0xff, 0xff, 0x1])


def test_varint_negative_matches_go():
    # -1 as int64 varint = 10 bytes of 0xff... + 0x01
    assert pw.encode_varint(-1) == b"\xff" * 9 + b"\x01"


# --- roundtrips -------------------------------------------------------------

def _mk_block_id(seed: bytes = b"\x01") -> BlockID:
    return BlockID(seed * 32, PartSetHeader(2, b"\x02" * 32))


def test_vote_proto_roundtrip():
    v = Vote(SignedMsgType.PRECOMMIT, 7, 2, _mk_block_id(), 1_700_000_000_123_456_789,
             b"\xaa" * 20, 3, b"\xbb" * 64)
    assert Vote.decode(v.encode()) == v


def test_proposal_proto_roundtrip():
    p = Proposal(9, 1, -1, _mk_block_id(), 1_700_000_000_000_000_001, b"\xcc" * 64)
    got = Proposal.decode(p.encode())
    assert got == p


def test_commit_proto_roundtrip_and_hash_stable():
    sigs = [
        CommitSig.new_for_block(b"\x01" * 64, b"\x0a" * 20, 1_700_000_000_000_000_000),
        CommitSig.new_absent(),
        CommitSig(BlockIDFlag.NIL, b"\x0b" * 20, 1_700_000_000_000_000_002, b"\x02" * 64),
    ]
    c = Commit(5, 0, _mk_block_id(), sigs)
    got = Commit.decode(c.encode())
    assert got.height == c.height and got.round == c.round
    assert got.block_id == c.block_id
    assert [s.block_id_flag for s in got.signatures] == [s.block_id_flag for s in sigs]
    assert got.hash() == c.hash()


def test_header_proto_roundtrip_and_hash():
    h = Header(
        version=Consensus(11, 1), chain_id="test-chain", height=3,
        time_ns=1_700_000_000_000_000_000, last_block_id=_mk_block_id(),
        last_commit_hash=b"\x01" * 32, data_hash=b"\x02" * 32,
        validators_hash=b"\x03" * 32, next_validators_hash=b"\x04" * 32,
        consensus_hash=b"\x05" * 32, app_hash=b"\x06" * 32,
        last_results_hash=b"\x07" * 32, evidence_hash=b"\x08" * 32,
        proposer_address=b"\x09" * 20,
    )
    got = Header.decode(h.encode())
    assert got == h
    assert h.hash() is not None and len(h.hash()) == 32
    # hash must change when a committed field changes
    from dataclasses import replace

    h2 = replace(h, app_hash=b"\x10" * 32)
    assert h2.hash() != h.hash()
    # in-place mutation must invalidate the hash memo, not serve stale bytes
    before = h.hash()
    h.app_hash = b"\x11" * 32
    assert h.hash() != before
    h.app_hash = b"\x06" * 32
    assert h.hash() == before


def test_header_hash_nil_without_validators_hash():
    assert Header(height=1).hash() is None


def test_validator_set_roundtrip():
    privs = [crypto.Ed25519PrivKey.generate(bytes([i]) * 32) for i in range(4)]
    vals = [Validator(p.pub_key().address(), p.pub_key(), 10 + i) for i, p in enumerate(privs)]
    vs = ValidatorSet(vals)
    got = ValidatorSet.decode(vs.encode())
    assert [v.address for v in got.validators] == [v.address for v in vs.validators]
    assert got.hash() == vs.hash()


def test_commit_vote_sign_bytes_matches_vote():
    # commit.vote_sign_bytes must equal the sign bytes of the reconstructed vote
    bid = _mk_block_id()
    cs = CommitSig.new_for_block(b"\x01" * 64, b"\x0a" * 20, 1_700_000_000_000_000_000)
    c = Commit(5, 0, bid, [cs])
    v = c.get_vote(0)
    assert c.vote_sign_bytes("chain", 0) == v.sign_bytes("chain")


def test_commit_nil_vote_sign_bytes_use_zero_block_id():
    bid = _mk_block_id()
    cs = CommitSig(BlockIDFlag.NIL, b"\x0b" * 20, 1_700_000_000_000_000_000, b"\x02" * 64)
    c = Commit(5, 0, bid, [cs])
    sb = c.vote_sign_bytes("chain", 0)
    want = vote_sign_bytes("chain", SignedMsgType.PRECOMMIT, 5, 0, BlockID(),
                           1_700_000_000_000_000_000)
    assert sb == want
