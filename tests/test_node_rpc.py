"""Node assembly + RPC surface: a full node built from Config (node.py),
serving JSON-RPC/WS (rpc/), driven through the public HTTP client — and a
CLI-generated multi-process localnet (BASELINE config #4 shape).
(reference node/node.go:706, rpc/core/routes.go, cmd/tendermint/)
"""

import asyncio
import base64
import json
import os
import subprocess
import sys
import time
import urllib.request

import pytest

pytest.importorskip("cryptography", reason="needs the optional 'cryptography' package (absent in slim containers)")

from tendermint_tpu.config import Config, test_config
from tendermint_tpu.p2p import NodeKey
from tendermint_tpu.privval.file_pv import FilePV
from tendermint_tpu.types import GenesisDoc, GenesisValidator

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _mk_node(tmp_path, rpc: bool = True, backend: str = "mem"):
    from tendermint_tpu import crypto
    from tendermint_tpu.node import Node

    home = str(tmp_path / "home")
    cfg = test_config(home)
    cfg.base.chain_id = "rpc-chain"
    cfg.base.db_backend = backend
    cfg.p2p.laddr = "tcp://127.0.0.1:0"
    cfg.rpc.laddr = "tcp://127.0.0.1:0" if rpc else ""
    os.makedirs(os.path.join(home, "config"), exist_ok=True)
    os.makedirs(os.path.join(home, "data"), exist_ok=True)
    if os.path.exists(cfg.priv_validator_key_file()):
        pv = FilePV.load(cfg.priv_validator_key_file(),
                         cfg.priv_validator_state_file())
    else:
        pv = FilePV.generate(cfg.priv_validator_key_file(),
                             cfg.priv_validator_state_file())
        pv.save()
    nk = NodeKey(crypto.Ed25519PrivKey.generate(b"\x51" * 32))
    # sub-second test blocks with the default time_iota_ms=1000 make chain
    # time outrun the wall clock (+1s per block, BFT-time monotonicity —
    # the reference behaves identically); a 1ms iota keeps header times
    # real so light-client clock-drift checks hold in fast tests
    from tendermint_tpu.types.params import default_consensus_params

    params = default_consensus_params()
    params.block.time_iota_ms = 1
    genesis = GenesisDoc(chain_id="rpc-chain",
                         genesis_time_ns=1_700_000_000_000_000_000,
                         consensus_params=params,
                         validators=[GenesisValidator(pv.get_pub_key(), 10)])
    return Node(cfg, pv, nk, genesis)


def test_node_serves_rpc_end_to_end(tmp_path):
    async def run():
        node = _mk_node(tmp_path)
        await node.start()
        try:
            from tendermint_tpu.rpc.client import HTTPClient

            port = node.rpc_server.bound_port
            client = HTTPClient(f"http://127.0.0.1:{port}")

            # wait for a few blocks
            for _ in range(300):
                st = await client.status()
                if int(st["sync_info"]["latest_block_height"]) >= 2:
                    break
                await asyncio.sleep(0.05)
            assert int(st["sync_info"]["latest_block_height"]) >= 2
            assert st["node_info"]["network"] == "rpc-chain"

            # block / commit / validators / blockchain / genesis
            blk = await client.block(2)
            assert blk["block"]["header"]["height"] == "2"
            cmt = await client.commit(1)
            assert cmt["signed_header"]["header"]["height"] == "1"
            assert cmt["canonical"] is True
            vals = await client.validators()
            assert vals["total"] == "1"
            bc = await client.call("blockchain")
            assert int(bc["last_height"]) >= 2
            gen = await client.call("genesis")
            assert gen["genesis"]["chain_id"] == "rpc-chain"
            ni = await client.call("net_info")
            assert ni["listening"] is True

            # broadcast_tx_commit round-trips through consensus
            res = await client.broadcast_tx_commit(b"k1=v1")
            assert res["deliver_tx"]["code"] == 0
            assert int(res["height"]) > 0

            # the kvstore now answers abci_query (on the query connection)
            q = await client.abci_query("", b"k1")
            assert base64.b64decode(q["response"]["value"]) == b"v1"

            # indexer: tx lookup + search + block_search (kv backend)
            import hashlib
            txh = hashlib.sha256(b"k1=v1").hexdigest()
            txr = await client.call("tx", hash=txh)
            assert txr["tx_result"]["code"] == 0
            assert base64.b64decode(txr["tx"]) == b"k1=v1"
            sr = await client.call("tx_search",
                                   query=f"tx.height={txr['height']}")
            assert int(sr["total_count"]) >= 1
            bs = await client.call("block_search", query="height EXISTS")
            assert int(bs["total_count"]) >= 1

            # websocket subscription sees new blocks
            sub = await client.subscribe("tm.event='NewBlock'")
            got = await asyncio.wait_for(sub.__anext__(), 10)
            assert got["data"]["type"] == "tendermint/event/NewBlock"

            await client.close()
        finally:
            await node.stop()
    asyncio.run(run())


def test_node_restart_resumes_chain(tmp_path):
    """Stop at height >= 2, rebuild from the same home dir, chain continues
    (WAL + handshake replay through the node path, node.go restart shape)."""
    async def run():
        node = _mk_node(tmp_path, rpc=False, backend="sqlite")
        await node.start()
        try:
            for _ in range(300):
                if node.consensus_state.state.last_block_height >= 2:
                    break
                await asyncio.sleep(0.05)
            assert node.consensus_state.state.last_block_height >= 2
        finally:
            await node.stop()
        h1 = node.consensus_state.state.last_block_height

        node2 = _mk_node(tmp_path, rpc=False, backend="sqlite")
        # same data dir => same chain; must resume past h1, not restart at 0
        assert node2.initial_state.last_block_height >= h1 - 1
        await node2.start()
        try:
            for _ in range(300):
                if node2.consensus_state.state.last_block_height >= h1 + 1:
                    break
                await asyncio.sleep(0.05)
            assert node2.consensus_state.state.last_block_height >= h1 + 1
        finally:
            await node2.stop()
    asyncio.run(run())


@pytest.mark.slow
def test_cli_testnet_four_process_localnet(tmp_path):
    """BASELINE config #4 shape: `testnet --v 4` + four `start` processes
    produce a block-producing localnet; invariants checked over RPC
    (app-hash agreement at a common height)."""
    out = str(tmp_path / "tnet")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    base_port = 28700
    subprocess.run(
        [sys.executable, "-m", "tendermint_tpu.cmd", "testnet", "--v", "4",
         "--output-dir", out, "--chain-id", "cli-e2e",
         "--starting-port", str(base_port)],
        check=True, env=env, cwd=REPO, capture_output=True, timeout=120)

    procs = []
    try:
        for i in range(4):
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "tendermint_tpu.cmd",
                 "--home", os.path.join(out, f"node{i}"),
                 "start", "--log-level", "warning"],
                env=env, cwd=REPO,
                stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT))

        def rpc(i, path):
            url = f"http://127.0.0.1:{base_port + 2 * i + 1}/{path}"
            with urllib.request.urlopen(url, timeout=5) as r:
                return json.load(r)["result"]

        deadline = time.time() + 90
        heights = [0] * 4
        while time.time() < deadline:
            try:
                heights = [int(rpc(i, "status")["sync_info"]
                               ["latest_block_height"]) for i in range(4)]
                if min(heights) >= 3:
                    break
            except Exception:
                pass
            time.sleep(1.0)
        assert min(heights) >= 3, f"localnet stuck: {heights}"

        hashes = {rpc(i, "commit?height=2")["signed_header"]["header"]["app_hash"]
                  for i in range(4)}
        assert len(hashes) == 1, hashes
    finally:
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
    # all four made progress and agreed; CLI + config + TCP + RPC end-to-end


def test_metrics_endpoint(tmp_path):
    """Prometheus /metrics (reference node.go:962 + per-module metrics.go)."""
    async def run():
        node = _mk_node(tmp_path)
        node.config.instrumentation.prometheus = True
        node.config.instrumentation.prometheus_listen_addr = "127.0.0.1:0"
        await node.start()
        try:
            import aiohttp

            for _ in range(300):
                if node.consensus_state.state.last_block_height >= 2:
                    break
                await asyncio.sleep(0.05)
            async with aiohttp.ClientSession() as s:
                async with s.get(
                        f"http://127.0.0.1:{node.metrics_port}/metrics") as r:
                    text = await r.text()
            assert "tendermint_consensus_height " in text
            height_line = [l for l in text.splitlines()
                           if l.startswith("tendermint_consensus_height ")][0]
            assert int(float(height_line.split()[-1])) >= 2
            assert "tendermint_consensus_validators 1" in text
            assert "tendermint_state_block_processing_time_count" in text
            assert "tendermint_consensus_block_interval_seconds_bucket" in text
            # the verification/apply-plane sets are registered even when the
            # series are idle (this node self-proposes, it doesn't fast-sync)
            assert "# TYPE tendermint_crypto_batch_size histogram" in text
            assert ("# TYPE tendermint_blocksync_stage_seconds histogram"
                    in text)
        finally:
            await node.stop()
    asyncio.run(run())


def test_rollback_one_height(tmp_path):
    """(state/rollback.go) the node re-applies the last block after rollback."""
    async def run():
        node = _mk_node(tmp_path, rpc=False, backend="sqlite")
        await node.start()
        try:
            for _ in range(300):
                if node.consensus_state.state.last_block_height >= 3:
                    break
                await asyncio.sleep(0.05)
        finally:
            await node.stop()
        h = node.consensus_state.state.last_block_height

        from tendermint_tpu.node import _make_db
        from tendermint_tpu.state.rollback import rollback_state
        from tendermint_tpu.state.store import StateStore
        from tendermint_tpu.store import BlockStore

        cfg = node.config
        bs = BlockStore(_make_db("sqlite", cfg.db_dir(), "blockstore"))
        ss = StateStore(_make_db("sqlite", cfg.db_dir(), "state"))
        # block store may be one ahead of the state store (stop mid-commit):
        # rollback's early-return path covers that; otherwise it goes back one
        prev = ss.load().last_block_height
        rolled_h, app_hash = rollback_state(bs, ss)
        assert rolled_h in (prev, prev - 1)
        assert ss.load().last_block_height == rolled_h

        # the node restarts and catches back up past h
        node2 = _mk_node(tmp_path, rpc=False, backend="sqlite")
        await node2.start()
        try:
            for _ in range(300):
                if node2.consensus_state.state.last_block_height >= h + 1:
                    break
                await asyncio.sleep(0.05)
            assert node2.consensus_state.state.last_block_height >= h + 1
        finally:
            await node2.stop()
    asyncio.run(run())
