"""Per-tx lifecycle tracker (libs/txlife.py): stage monotonicity, hash
sampling determinism, ring/active bounds, terminal semantics, the real
mempool/consensus integration, and the 4-node in-proc net putting
tx_commit_latency observations on every node's registry."""

import asyncio
import hashlib
import json

from tendermint_tpu.libs.metrics import MempoolMetrics, NodeMetrics, Registry
from tendermint_tpu.libs.trace import tracer
from tendermint_tpu.libs.txlife import STAGES, TxLifecycle


def _key(i) -> bytes:
    return hashlib.sha256(b"tx-%d" % i).digest()


def _drive_committed(tl, key, height=5):
    tl.mark(key, "rpc_received")
    tl.mark(key, "checktx_done", outcome="accepted")
    tl.mark(key, "mempool_admitted")
    tl.mark(key, "first_gossip")
    tl.mark(key, "proposal_included", height=height)
    tl.mark(key, "committed", height=height)


def test_stage_monotonicity_and_seal():
    tl = TxLifecycle(sample_rate=1.0)
    m = MempoolMetrics(Registry())
    tl.metrics = m
    _drive_committed(tl, _key(1), height=7)
    snap = tl.snapshot()
    (rec,) = snap["records"]
    assert rec["terminal"] == "committed" and rec["height"] == 7
    # the acceptance shape: every stage from rpc_received through
    # committed present, stamps monotonic in arrival order
    assert [mk[0] for mk in rec["marks"]] == [
        "rpc_received", "checktx_done", "mempool_admitted", "first_gossip",
        "proposal_included", "committed"]
    times = [t for _, t in rec["marks"]]
    assert times == sorted(times)
    assert all(d >= 0 for d in rec["durations"].values())
    # durations and total_s are independently rounded to 1 us in the
    # JSON view: allow half-ulp-per-stage accumulation
    assert sum(rec["durations"].values()) <= rec["total_s"] + 1e-5
    # both lifecycle histograms observed
    for stage in ("rpc_received", "checktx_done", "mempool_admitted",
                  "first_gossip", "proposal_included", "committed"):
        assert m.tx_stage_seconds.count_value(stage) == 1, stage
    assert m.tx_commit_latency_seconds.count_value() == 1
    assert snap["active"] == 0 and snap["sealed_total"] == 1
    json.dumps(snap)  # the RPC /tx_timeline + debugdump contract


def test_duplicate_marks_first_wins_and_rechecks_count():
    tl = TxLifecycle(sample_rate=1.0)
    k = _key(2)
    tl.mark(k, "rpc_received")
    tl.mark(k, "checktx_done", outcome="accepted")
    tl.mark(k, "checktx_done", outcome="accepted")  # dup: ignored
    tl.mark(k, "mempool_admitted")
    tl.mark(k, "rechecked", outcome="accepted")
    tl.mark(k, "rechecked", outcome="accepted")  # rechecks repeat + count
    tl.mark(k, "committed", height=3)
    (rec,) = tl.snapshot()["records"]
    assert [mk[0] for mk in rec["marks"]].count("checktx_done") == 1
    assert rec["rechecks"] == 2


def test_sampling_deterministic_by_tx_hash():
    a = TxLifecycle(sample_rate=0.5)
    b = TxLifecycle(sample_rate=0.5)
    keys = [_key(i) for i in range(400)]
    picks_a = [a.sampled(k) for k in keys]
    picks_b = [b.sampled(k) for k in keys]
    # two trackers (two nodes) sample the SAME txs — that is what lets
    # trace_merge correlate one tx across a fleet
    assert picks_a == picks_b
    frac = sum(picks_a) / len(picks_a)
    assert 0.35 < frac < 0.65, frac
    # an unsampled tx never opens a record
    unsampled = [k for k, p in zip(keys, picks_a) if not p][0]
    a.mark(unsampled, "rpc_received")
    assert a.snapshot()["active"] == 0
    # rate 0 disables, rate 1 takes everything
    assert not TxLifecycle(sample_rate=0.0).sampled(keys[0])
    assert all(TxLifecycle(sample_rate=1.0).sampled(k) for k in keys)


def test_ring_and_active_bounds():
    tl = TxLifecycle(sample_rate=1.0, ring_capacity=8, active_capacity=16)
    for i in range(50):
        _drive_committed(tl, _key(1000 + i))
    snap = tl.snapshot(10 ** 6)
    assert len(snap["records"]) == 8
    assert snap["sealed_total"] == 50
    # active-map overflow: records evicted oldest-first, closed as "lost"
    tl2 = TxLifecycle(sample_rate=1.0, ring_capacity=8, active_capacity=16)
    for i in range(40):
        tl2.mark(_key(2000 + i), "rpc_received")
    snap2 = tl2.snapshot(10 ** 6)
    assert snap2["active"] == 16
    assert snap2["evicted_total"] == 24
    assert all(r["terminal"] == "lost" for r in snap2["records"])


def test_rejected_tx_terminal_stage():
    tl = TxLifecycle(sample_rate=1.0)
    m = MempoolMetrics(Registry())
    tl.metrics = m
    k = _key(3)
    tl.mark(k, "rpc_received")
    tl.mark(k, "checktx_done", outcome="rejected")
    (rec,) = tl.snapshot()["records"]
    assert rec["terminal"] == "rejected"
    assert [mk[0] for mk in rec["marks"]] == ["rpc_received",
                                              "checktx_done"]
    # a rejected tx never observes commit latency
    assert m.tx_commit_latency_seconds.count_value() == 0
    assert m.tx_stage_seconds.count_value("checktx_done") == 1
    # post-seal marks for the dead key are no-ops (no reopened record)
    tl.mark(k, "committed", height=9)
    assert tl.snapshot()["active"] == 0 and tl.snapshot()["sealed_total"] == 1


def test_retry_of_sealed_tx_leaves_no_phantom_record():
    """A client retrying an already-committed tx reopens a record at
    rpc_received; the mempool's cache-dup path must discard it — a retry
    storm must not evict genuine in-flight records. The live original of
    a duplicate broadcast survives untouched."""
    from tendermint_tpu.abci.example.kvstore import KVStoreApplication
    from tendermint_tpu.mempool import CListMempool
    from tendermint_tpu.mempool.clist_mempool import ErrTxInCache
    from tendermint_tpu.proxy import AppConns, local_client_creator

    conns = AppConns(local_client_creator(KVStoreApplication()))
    conns.start()
    try:
        mp = CListMempool(conns.mempool)
        tl = TxLifecycle(sample_rate=1.0)
        mp.txlife = tl
        raw = b"retry=1"
        key = hashlib.sha256(raw).digest()
        # first broadcast: admitted, record live
        tl.mark(key, "rpc_received")
        mp.check_tx(raw)
        # committed out-of-band: record sealed, tx stays cache-blocked
        import tendermint_tpu.abci.types as abci

        mp.update(2, [raw], [abci.ResponseCheckTx(code=0)])
        assert tl.snapshot()["sealed_total"] == 1
        # the retry: rpc_received reopens, cache-dup must discard it
        tl.mark(key, "rpc_received")
        try:
            mp.check_tx(raw)
            raise AssertionError("expected cache-dup rejection")
        except ErrTxInCache:
            pass
        assert tl.snapshot()["active"] == 0, tl.snapshot()
        # retry of the committed tx against a FULL mempool: the capacity
        # check fires before the cache check — still no bogus sealed
        # "rejected" record over the original's committed lifecycle
        from tendermint_tpu.mempool.clist_mempool import MempoolError

        mp._max_txs = 0
        tl.mark(key, "rpc_received")
        try:
            mp.check_tx(raw)
            raise AssertionError("expected full-mempool rejection")
        except MempoolError:
            pass
        assert tl.snapshot()["active"] == 0
        assert tl.snapshot()["sealed_total"] == 1  # only the commit record
        # a genuinely NEW tx rejected at capacity DOES record the rejection
        tl.mark(hashlib.sha256(b"fresh=1").digest(), "rpc_received")
        try:
            mp.check_tx(b"fresh=1")
            raise AssertionError("expected full-mempool rejection")
        except MempoolError:
            pass
        assert tl.snapshot()["sealed_total"] == 2
        assert tl.tail(1)[0]["terminal"] == "rejected"
        mp._max_txs = 5000

        # a LIVE duplicate: the original record (past rpc_received) stays
        raw2 = b"retry=2"
        key2 = hashlib.sha256(raw2).digest()
        tl.mark(key2, "rpc_received")
        mp.check_tx(raw2)
        tl.mark(key2, "rpc_received")  # duplicate broadcast, same tx live
        try:
            mp.check_tx(raw2)
            raise AssertionError("expected cache-dup rejection")
        except ErrTxInCache:
            pass
        assert tl.snapshot()["active"] == 1
    finally:
        conns.stop()


def test_non_entry_stage_never_opens_a_record():
    tl = TxLifecycle(sample_rate=1.0)
    tl.mark(_key(4), "committed", height=2)
    tl.mark(_key(4), "first_gossip")
    assert tl.snapshot() == tl.snapshot()
    assert tl.snapshot()["active"] == 0 and tl.snapshot()["sealed_total"] == 0


def test_trace_spans_emitted_on_seal():
    tl = TxLifecycle(sample_rate=1.0)
    tracer.clear()
    tracer.enable()
    try:
        _drive_committed(tl, _key(5), height=11)
    finally:
        tracer.disable()
    spans = [e for e in tracer.events() if e["name"].startswith("tx_")]
    tracer.clear()
    assert [e["name"] for e in spans] == [
        "tx_rpc_received", "tx_checktx_done", "tx_mempool_admitted",
        "tx_first_gossip", "tx_proposal_included", "tx_committed"]
    for e in spans:
        assert e["ph"] == "X" and e["args"]["height"] == 11
    # spans tile the lifecycle: each starts where the previous ended
    for a, b in zip(spans, spans[1:]):
        assert abs((a["ts"] + a["dur"]) - b["ts"]) < 1.0  # us


def test_mempool_integration_admit_reject_flush():
    """The real CListMempool against a kvstore app: lifecycle marks at
    checktx/admission, reason-labeled rejections, and the flush() depth
    gauge fix (historically left stale)."""
    from tendermint_tpu.abci.example.kvstore import KVStoreApplication
    from tendermint_tpu.mempool import CListMempool
    from tendermint_tpu.mempool.clist_mempool import (
        ErrTxInCache,
        MempoolError,
    )
    from tendermint_tpu.proxy import AppConns, local_client_creator

    conns = AppConns(local_client_creator(KVStoreApplication()))
    conns.start()
    try:
        mp = CListMempool(conns.mempool, max_txs=2, max_tx_bytes=64)
        m = MempoolMetrics(Registry())
        tl = TxLifecycle(sample_rate=1.0)
        tl.metrics = m
        mp.metrics = m
        mp.txlife = tl

        mp.check_tx(b"a=1")
        mp.check_tx(b"b=2")
        assert m.admitted_txs_total.value() == 2
        assert m.size.value() == 2 and m.size_bytes.value() == 6
        assert m.checktx_latency_seconds.count_value() == 2
        # lifecycle: both admitted txs carry checktx_done+mempool_admitted
        assert tl.snapshot()["active"] == 2

        # full → reason="full", lifecycle sealed rejected
        try:
            mp.check_tx(b"c=3")
            raise AssertionError("expected full-mempool rejection")
        except MempoolError:
            pass
        assert m.failed_txs.value("full") == 1
        # too-large → reason="too-large"
        try:
            mp.check_tx(b"d=" + b"x" * 100)
            raise AssertionError("expected too-large rejection")
        except MempoolError:
            pass
        assert m.failed_txs.value("too-large") == 1
        # duplicate → reason="cache-dup", and the ORIGINAL record stays
        # live (capacity raised first: the full check precedes the cache)
        mp._max_txs = 3
        try:
            mp.check_tx(b"a=1")
            raise AssertionError("expected cache-dup rejection")
        except ErrTxInCache:
            pass
        assert m.failed_txs.value("cache-dup") == 1
        assert tl.snapshot()["active"] == 2  # originals not sealed by dup
        rejected = [r for r in tl.snapshot()["records"]
                    if r["terminal"] == "rejected"]
        assert len(rejected) == 2  # full + too-large

        # the satellite fix: flush() updates BOTH depth gauges and counts
        # the evictions — no more stale size gauge after unsafe_flush
        mp.flush()
        assert m.size.value() == 0 and m.size_bytes.value() == 0
        assert m.evicted_txs_total.value("flush") == 2
    finally:
        conns.stop()


def test_app_reject_reason_and_latency_series():
    """An app-rejecting CheckTx lands reason="app-reject" and seals the
    lifecycle record rejected."""
    from tendermint_tpu.abci import types as abci
    from tendermint_tpu.abci.example.kvstore import KVStoreApplication
    from tendermint_tpu.mempool import CListMempool
    from tendermint_tpu.proxy import AppConns, local_client_creator

    class Rejecting(KVStoreApplication):
        def check_tx(self, req):
            return abci.ResponseCheckTx(code=1, log="no")

    conns = AppConns(local_client_creator(Rejecting()))
    conns.start()
    try:
        mp = CListMempool(conns.mempool)
        m = MempoolMetrics(Registry())
        tl = TxLifecycle(sample_rate=1.0)
        mp.metrics = m
        mp.txlife = tl
        res = mp.check_tx(b"bad=1")
        assert res.code == 1
        assert m.failed_txs.value("app-reject") == 1
        (rec,) = tl.snapshot()["records"]
        assert rec["terminal"] == "rejected"
    finally:
        conns.stop()


def test_app_exception_leaves_no_phantom_record():
    """A broken ABCI connection (check_tx raising) under a broadcast
    storm must not leak one never-closed rpc_received record per
    attempt."""
    from tendermint_tpu.abci.example.kvstore import KVStoreApplication
    from tendermint_tpu.mempool import CListMempool
    from tendermint_tpu.proxy import AppConns, local_client_creator

    class Broken(KVStoreApplication):
        def check_tx(self, req):
            raise RuntimeError("app connection lost")

    conns = AppConns(local_client_creator(Broken()))
    conns.start()
    try:
        mp = CListMempool(conns.mempool)
        tl = TxLifecycle(sample_rate=1.0)
        mp.txlife = tl
        for i in range(5):
            raw = b"storm=%d" % i
            tl.mark(hashlib.sha256(raw).digest(), "rpc_received")
            try:
                mp.check_tx(raw)
                raise AssertionError("expected app exception")
            except RuntimeError:
                pass
        assert tl.snapshot()["active"] == 0, tl.snapshot()
    finally:
        conns.stop()


def test_single_validator_full_lifecycle_rpc_to_commit():
    """The real state machine end-to-end: a tx entering through the
    mempool (the RPC hook's next hop) is stamped through
    proposal_included and committed with monotonic stamps — the
    acceptance criterion's stage chain, minus only the rpc_received mark
    the HTTP layer adds."""
    from test_consensus_single import build_node, wait_for_height

    async def run():
        cs, mempool, app, event_bus, pv, _ = build_node()
        m = MempoolMetrics(Registry())
        tl = TxLifecycle(sample_rate=1.0)
        tl.metrics = m
        mempool.metrics = m
        mempool.txlife = tl
        await cs.start()
        try:
            raw = b"life=1"
            tl.mark(hashlib.sha256(raw).digest(), "rpc_received")
            mempool.check_tx(raw)
            await wait_for_height(event_bus, cs, 3)
        finally:
            await cs.stop()
        committed = [r for r in tl.snapshot(100)["records"]
                     if r["terminal"] == "committed"]
        assert committed, tl.snapshot()
        (rec,) = committed
        stages = [mk[0] for mk in rec["marks"]]
        assert stages[:3] == ["rpc_received", "checktx_done",
                              "mempool_admitted"]
        assert "proposal_included" in stages and "committed" in stages
        times = [t for _, t in rec["marks"]]
        assert times == sorted(times)
        assert rec["height"] is not None and rec["height"] >= 1
        assert m.tx_commit_latency_seconds.count_value() == 1
        assert m.tx_stage_seconds.count_value("proposal_included") == 1

    asyncio.run(run())


def test_four_node_net_commit_latency_on_every_registry():
    """The acceptance shape, in-process: a real 4-validator net where one
    node ingests a tx — EVERY node's registry must observe
    tendermint_mempool_tx_commit_latency_seconds (followers stamp from
    checktx_done at gossip receipt through proposal_included at
    complete-proposal decode to committed)."""
    from test_consensus_net import make_net, wait_all_height

    from tendermint_tpu.p2p import InProcNetwork

    async def run():
        nodes = make_net(4)
        metrics, trackers = [], []
        for nd in nodes:
            m = MempoolMetrics(Registry())
            tl = TxLifecycle(sample_rate=1.0)
            tl.metrics = m
            nd.mempool.metrics = m
            nd.mempool.txlife = tl
            metrics.append(m)
            trackers.append(tl)
        net = InProcNetwork()
        for nd in nodes:
            net.add_switch(nd.switch)
        for nd in nodes:
            await nd.start()
        await net.connect_all()
        try:
            await wait_all_height(nodes, 2)
            nodes[0].mempool.check_tx(b"fleet=1")
            h0 = min(nd.cs.state.last_block_height for nd in nodes)
            await wait_all_height(nodes, h0 + 2)
        finally:
            for nd in nodes:
                await nd.stop()
        gossiped = 0
        for i, (m, tl) in enumerate(zip(metrics, trackers)):
            assert m.tx_commit_latency_seconds.count_value() >= 1, i
            committed = [r for r in tl.tail(100)
                         if r["terminal"] == "committed"]
            assert committed, (i, tl.snapshot())
            rec = committed[0]
            stages = [mk[0] for mk in rec["marks"]]
            assert "checktx_done" in stages and "committed" in stages
            assert "proposal_included" in stages, (i, stages)
            times = [t for _, t in rec["marks"]]
            assert times == sorted(times)
            gossiped += sum(1 for r in tl.tail(100)
                            for mk in r["marks"] if mk[0] == "first_gossip")
            text = "\n".join(m.tx_commit_latency_seconds.render())
            assert "tendermint_mempool_tx_commit_latency_seconds_count" \
                in text
        # somebody forwarded the tx (node0 at minimum)
        assert gossiped > 0

    asyncio.run(run())


def test_tx_timeline_rpc_route():
    """GET /tx_timeline through the Environment handler: the tracker's
    snapshot verbatim, and a graceful empty shape with no tracker."""
    from types import SimpleNamespace

    from tendermint_tpu.rpc.core import Environment

    tl = TxLifecycle(sample_rate=1.0)
    _drive_committed(tl, _key(9), height=4)
    node = SimpleNamespace(mempool=SimpleNamespace(txlife=tl))

    async def run():
        env = Environment(node)
        doc = await env.tx_timeline(limit=5)
        assert doc["sealed_total"] == 1
        assert doc["records"][0]["terminal"] == "committed"
        json.dumps(doc)
        bare = Environment(SimpleNamespace(mempool=SimpleNamespace()))
        doc2 = await bare.tx_timeline()
        assert doc2["enabled"] is False and doc2["records"] == []

    asyncio.run(run())


def test_node_metrics_carries_lifecycle_series():
    """NodeMetrics registers the grown mempool set + RPCMetrics without
    name collisions, and renders the new series names."""
    nm = NodeMetrics()
    nm.mempool.tx_stage_seconds.labels("committed").observe(0.2)
    nm.mempool.tx_commit_latency_seconds.observe(1.0)
    nm.mempool.failed_txs.labels("full").inc()
    nm.mempool.size_bytes.set(123)
    nm.rpc.request_seconds.labels("status", "ok").observe(0.01)
    nm.rpc.requests_in_flight.set(0)
    text = nm.registry.render()
    for needle in (
            'tendermint_mempool_tx_stage_seconds_bucket',
            "tendermint_mempool_tx_commit_latency_seconds_count",
            'tendermint_mempool_failed_txs{reason="full"}',
            "tendermint_mempool_size_bytes 123",
            'tendermint_rpc_request_seconds_count{endpoint="status",'
            'outcome="ok"}'):
        assert needle in text, needle
