"""Multi-process e2e perturbations (reference test/e2e/runner/perturb.go:28-66
kill/pause/restart + post-run invariant checks over RPC): a CLI-generated
localnet survives a SIGKILL'd validator, keeps making progress on 3/4 power,
and the restarted node catches back up; app hashes agree across all nodes.
"""

import json
import os
import signal
import subprocess
import sys
import time
import urllib.request

import pytest

pytest.importorskip(
    "cryptography",
    reason="the multi-process net's TCP transport needs the optional "
           "'cryptography' package (absent in slim containers)")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BASE_PORT = 28800


def _rpc(i, path, base_port=BASE_PORT):
    url = f"http://127.0.0.1:{base_port + 2 * i + 1}/{path}"
    with urllib.request.urlopen(url, timeout=5) as r:
        return json.load(r)["result"]


def _heights(n, base_port=BASE_PORT):
    out = []
    for i in range(n):
        try:
            out.append(int(_rpc(i, "status", base_port)["sync_info"]
                           ["latest_block_height"]))
        except Exception:
            out.append(-1)
    return out


def _spawn(env, out, i):
    return subprocess.Popen(
        [sys.executable, "-m", "tendermint_tpu.cmd",
         "--home", os.path.join(out, f"node{i}"),
         "start", "--log-level", "warning"],
        env=env, cwd=REPO,
        stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT)


def _testnet_env(out, base_port):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    subprocess.run(
        [sys.executable, "-m", "tendermint_tpu.cmd", "testnet", "--v", "4",
         "--output-dir", out, "--chain-id", "perturb-e2e",
         "--starting-port", str(base_port)],
        check=True, env=env, cwd=REPO, capture_output=True, timeout=120)
    return env


@pytest.mark.slow
def test_kill_and_restart_validator(tmp_path):
    out = str(tmp_path / "tnet")
    env = _testnet_env(out, BASE_PORT)

    procs = {i: _spawn(env, out, i) for i in range(4)}
    try:
        # phase 1: all four make progress
        deadline = time.time() + 90
        while time.time() < deadline:
            hs = _heights(4)
            if min(hs) >= 2:
                break
            time.sleep(1)
        assert min(_heights(4)) >= 2, f"no initial progress: {_heights(4)}"

        # perturbation: SIGKILL node 3 (perturb.go "kill")
        procs[3].send_signal(signal.SIGKILL)
        procs[3].wait(timeout=10)
        h_at_kill = max(_heights(3))

        # liveness on 3/4 voting power
        deadline = time.time() + 90
        while time.time() < deadline:
            hs = _heights(3)
            if min(hs) >= h_at_kill + 3:
                break
            time.sleep(1)
        assert min(_heights(3)) >= h_at_kill + 3, \
            f"net stalled after kill: {_heights(3)}"

        # restart: the node recovers via WAL/handshake replay and catches up
        procs[3] = _spawn(env, out, 3)
        deadline = time.time() + 120
        while time.time() < deadline:
            hs = _heights(4)
            if hs[3] >= h_at_kill + 3:
                break
            time.sleep(1)
        assert _heights(4)[3] >= h_at_kill + 3, \
            f"restarted node did not catch up: {_heights(4)}"

        # invariant: app-hash agreement at a common height (test/e2e/tests)
        common = min(_heights(4)) - 1
        hashes = {_rpc(i, f"commit?height={common}")["signed_header"]
                  ["header"]["app_hash"] for i in range(4)}
        assert len(hashes) == 1, hashes
    finally:
        for p in procs.values():
            if p.poll() is None:
                p.terminate()
        for p in procs.values():
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()


@pytest.mark.slow
def test_pause_and_resume_validator(tmp_path):
    """perturb.go "pause": SIGSTOP one validator — the net keeps committing
    on 3/4 power, and after SIGCONT the frozen node (whose peers never saw
    it exit) rejoins and catches up; app hashes agree everywhere."""
    base_port = BASE_PORT + 100  # keep clear of the kill test's TIME_WAIT
    out = str(tmp_path / "tnet")
    env = _testnet_env(out, base_port)

    procs = {i: _spawn(env, out, i) for i in range(4)}
    try:
        # phase 1: all four make progress
        deadline = time.time() + 90
        while time.time() < deadline:
            if min(_heights(4, base_port)) >= 2:
                break
            time.sleep(1)
        assert min(_heights(4, base_port)) >= 2, \
            f"no initial progress: {_heights(4, base_port)}"

        # perturbation: freeze node 3 mid-flight (no exit, no FIN — its
        # sockets stay open, the hard case for peer bookkeeping)
        procs[3].send_signal(signal.SIGSTOP)
        h_at_pause = max(_heights(3, base_port))

        # liveness on 3/4 voting power while one validator is frozen
        deadline = time.time() + 90
        while time.time() < deadline:
            if min(_heights(3, base_port)) >= h_at_pause + 3:
                break
            time.sleep(1)
        assert min(_heights(3, base_port)) >= h_at_pause + 3, \
            f"net stalled while paused: {_heights(3, base_port)}"

        # resume: the thawed node rejoins without a restart and catches up
        procs[3].send_signal(signal.SIGCONT)
        target = max(_heights(3, base_port)) + 2
        deadline = time.time() + 120
        while time.time() < deadline:
            if _heights(4, base_port)[3] >= target:
                break
            time.sleep(1)
        assert _heights(4, base_port)[3] >= target, \
            f"resumed node did not catch up: {_heights(4, base_port)}"
        assert procs[3].poll() is None, "paused node died instead of rejoining"

        # invariant: app-hash agreement at a common height
        common = min(_heights(4, base_port)) - 1
        hashes = {_rpc(i, f"commit?height={common}", base_port)
                  ["signed_header"]["header"]["app_hash"] for i in range(4)}
        assert len(hashes) == 1, hashes
    finally:
        for p in procs.values():
            if p.poll() is None:
                p.send_signal(signal.SIGCONT)  # can't terminate a stopped proc
                p.terminate()
        for p in procs.values():
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
