"""Multi-process e2e perturbations (reference test/e2e/runner/perturb.go:28-66
kill/pause/restart + post-run invariant checks over RPC): a CLI-generated
localnet survives a SIGKILL'd validator, keeps making progress on 3/4 power,
and the restarted node catches back up; app hashes agree across all nodes.
"""

import json
import os
import signal
import subprocess
import sys
import time
import urllib.request

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BASE_PORT = 28800


def _rpc(i, path):
    url = f"http://127.0.0.1:{BASE_PORT + 2 * i + 1}/{path}"
    with urllib.request.urlopen(url, timeout=5) as r:
        return json.load(r)["result"]


def _heights(n):
    out = []
    for i in range(n):
        try:
            out.append(int(_rpc(i, "status")["sync_info"]["latest_block_height"]))
        except Exception:
            out.append(-1)
    return out


def _spawn(env, out, i):
    return subprocess.Popen(
        [sys.executable, "-m", "tendermint_tpu.cmd",
         "--home", os.path.join(out, f"node{i}"),
         "start", "--log-level", "warning"],
        env=env, cwd=REPO,
        stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT)


@pytest.mark.slow
def test_kill_and_restart_validator(tmp_path):
    out = str(tmp_path / "tnet")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    subprocess.run(
        [sys.executable, "-m", "tendermint_tpu.cmd", "testnet", "--v", "4",
         "--output-dir", out, "--chain-id", "perturb-e2e",
         "--starting-port", str(BASE_PORT)],
        check=True, env=env, cwd=REPO, capture_output=True, timeout=120)

    procs = {i: _spawn(env, out, i) for i in range(4)}
    try:
        # phase 1: all four make progress
        deadline = time.time() + 90
        while time.time() < deadline:
            hs = _heights(4)
            if min(hs) >= 2:
                break
            time.sleep(1)
        assert min(_heights(4)) >= 2, f"no initial progress: {_heights(4)}"

        # perturbation: SIGKILL node 3 (perturb.go "kill")
        procs[3].send_signal(signal.SIGKILL)
        procs[3].wait(timeout=10)
        h_at_kill = max(_heights(3))

        # liveness on 3/4 voting power
        deadline = time.time() + 90
        while time.time() < deadline:
            hs = _heights(3)
            if min(hs) >= h_at_kill + 3:
                break
            time.sleep(1)
        assert min(_heights(3)) >= h_at_kill + 3, \
            f"net stalled after kill: {_heights(3)}"

        # restart: the node recovers via WAL/handshake replay and catches up
        procs[3] = _spawn(env, out, 3)
        deadline = time.time() + 120
        while time.time() < deadline:
            hs = _heights(4)
            if hs[3] >= h_at_kill + 3:
                break
            time.sleep(1)
        assert _heights(4)[3] >= h_at_kill + 3, \
            f"restarted node did not catch up: {_heights(4)}"

        # invariant: app-hash agreement at a common height (test/e2e/tests)
        common = min(_heights(4)) - 1
        hashes = {_rpc(i, f"commit?height={common}")["signed_header"]
                  ["header"]["app_hash"] for i in range(4)}
        assert len(hashes) == 1, hashes
    finally:
        for p in procs.values():
            if p.poll() is None:
                p.terminate()
        for p in procs.values():
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
