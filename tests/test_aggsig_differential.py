"""Differential accept/reject parity: the SAME commit scenario, built on an
ed25519 chain (CommitSig list, batched per-signature verify) and on a BLS
aggregated chain (signer bitmap + one 48-byte aggregate, one pairing), must
produce the same verdict from every verify_commit* mode.  Plus the two
scheme-plane invariants that frame the A/B: default chains stay
byte-identical to the pre-scheme-plane artifacts, and BLS keys enter a
validator set only through the proof-of-possession gate."""

import pytest

from tendermint_tpu import crypto
from tendermint_tpu.crypto import schemes
from tendermint_tpu.crypto import bls12381 as bls
from tendermint_tpu.libs.bits import BitArray
from tendermint_tpu.types import (
    GenesisDoc,
    GenesisValidator,
    MockPV,
    Validator,
    ValidatorSet,
)
from tendermint_tpu.types.basic import (
    BlockID,
    BlockIDFlag,
    PartSetHeader,
    SignedMsgType,
)
from tendermint_tpu.types.block import AggregatedCommit, Commit, CommitSig
from tendermint_tpu.types.errors import (
    ErrNotEnoughVotingPowerSigned,
    ErrWrongSignature,
)
from tendermint_tpu.types.params import (
    ConsensusParams,
    SignatureParams,
    ValidatorParams,
)
from tendermint_tpu.types.vote import Vote
from tendermint_tpu.types.vote_set import VoteSet

N = 6
HEIGHT = 9
BID = BlockID(b"\x11" * 32, PartSetHeader(1, b"\x22" * 32))
NIL = BlockID()


class Rig:
    """One chain: privvals, validator set, and a commit builder."""

    def __init__(self, chain_id, scheme):
        self.chain_id = chain_id
        if scheme == "bls12381":
            schemes.register_chain(chain_id, SignatureParams("bls12381", True))
            self.pvs = [MockPV(crypto.Bls12381PrivKey.generate(
                b"diff" + bytes([i]) * 4)) for i in range(N)]
        else:
            self.pvs = [MockPV(crypto.Ed25519PrivKey.generate(
                bytes([0x40 + i]) * 32)) for i in range(N)]
        self.val_set = ValidatorSet([
            Validator(pv.get_pub_key().address(), pv.get_pub_key(), 10)
            for pv in self.pvs])
        # MockPV order != address-sorted set order: map pv -> set index
        self.idx_of = {pv.get_pub_key().address():
                       self.val_set.get_by_address(
                           pv.get_pub_key().address())[0]
                       for pv in self.pvs}

    def make_commit(self, block_voters, nil_voters=()):
        """Assemble via the real VoteSet path (what consensus runs)."""
        vs = VoteSet(self.chain_id, HEIGHT, 0, SignedMsgType.PRECOMMIT,
                     self.val_set)
        for pv in self.pvs:
            addr = pv.get_pub_key().address()
            idx = self.idx_of[addr]
            if idx in block_voters:
                bid = BID
            elif idx in nil_voters:
                bid = NIL
            else:
                continue
            v = Vote(SignedMsgType.PRECOMMIT, HEIGHT, 0, bid,
                     1_700_000_000_000_000_000 + idx, addr, idx, b"")
            pv.sign_vote(self.chain_id, v)
            added = vs.add_vote(v)
            assert added, (self.chain_id, idx)
        return vs.make_commit()

    def verify_all_modes(self, commit):
        self.val_set.verify_commit(self.chain_id, BID, HEIGHT, commit)
        self.val_set.verify_commit_light(self.chain_id, BID, HEIGHT, commit)
        self.val_set.verify_commit_light_trusting(
            self.chain_id, commit, (1, 3), commit_vals=self.val_set)


@pytest.fixture
def rigs():
    try:
        yield Rig("diff-ed", "ed25519"), Rig("diff-bls", "bls12381")
    finally:
        schemes.reset()
        bls.reset()


def _rejects(fn, *errs):
    with pytest.raises(errs or (ErrWrongSignature,
                                ErrNotEnoughVotingPowerSigned)):
        fn()


def test_valid_full_commit_accepted_by_both(rigs):
    ed, bl = rigs
    all_idx = set(range(N))
    c_ed = ed.make_commit(all_idx)
    c_bl = bl.make_commit(all_idx)
    assert not hasattr(c_ed, "agg_sig")
    assert hasattr(c_bl, "agg_sig")
    ed.verify_all_modes(c_ed)
    bl.verify_all_modes(c_bl)
    # and the aggregated wire form is a fraction of the CommitSig list
    assert len(c_bl.encode()) < len(c_ed.encode()) / 3


def test_one_bad_signature_rejected_by_both(rigs):
    ed, bl = rigs
    c_ed = ed.make_commit(set(range(N)))
    cs = c_ed.signatures[0]
    c_ed.signatures[0] = CommitSig(cs.block_id_flag, cs.validator_address,
                                   cs.timestamp_ns, bytes(64))
    _rejects(lambda: ed.val_set.verify_commit(ed.chain_id, BID, HEIGHT, c_ed),
             ErrWrongSignature)

    c_bl = bl.make_commit(set(range(N)))
    c_bl = AggregatedCommit(
        c_bl.height, c_bl.round, c_bl.block_id, [], signers=c_bl.signers,
        agg_sig=bytes([c_bl.agg_sig[0] ^ 0x01]) + c_bl.agg_sig[1:],
        timestamp_ns=c_bl.timestamp_ns)
    _rejects(lambda: bl.val_set.verify_commit(bl.chain_id, BID, HEIGHT, c_bl),
             ErrWrongSignature)


def test_sub_quorum_rejected_by_both(rigs):
    """3/6 of the power behind the block (50% <= 2/3): both planes must
    reject, whatever error-shape each one raises first."""
    ed, bl = rigs
    voters = {0, 1, 2}
    # VoteSet refuses to even assemble without maj23 — build directly, the
    # shape a byzantine proposer could ship
    sigs = []
    for idx in range(N):
        if idx not in voters:
            sigs.append(CommitSig.new_absent())
            continue
        pv = next(p for p in ed.pvs
                  if ed.idx_of[p.get_pub_key().address()] == idx)
        v = Vote(SignedMsgType.PRECOMMIT, HEIGHT, 0, BID,
                 1_700_000_000_000_000_000, pv.get_pub_key().address(),
                 idx, b"")
        pv.sign_vote(ed.chain_id, v)
        sigs.append(CommitSig.new_for_block(v.signature, v.validator_address,
                                            v.timestamp_ns))
    c_ed = Commit(HEIGHT, 0, BID, sigs)
    _rejects(lambda: ed.val_set.verify_commit(ed.chain_id, BID, HEIGHT, c_ed),
             ErrNotEnoughVotingPowerSigned)

    bls_sigs, signers = [], BitArray(N)
    for idx in sorted(voters):
        pv = next(p for p in bl.pvs
                  if bl.idx_of[p.get_pub_key().address()] == idx)
        v = Vote(SignedMsgType.PRECOMMIT, HEIGHT, 0, BID,
                 1_700_000_000_000_000_000, pv.get_pub_key().address(),
                 idx, b"")
        pv.sign_vote(bl.chain_id, v)
        bls_sigs.append(v.signature)
        signers.set_index(idx, True)
    c_bl = AggregatedCommit(HEIGHT, 0, BID, [], signers=signers,
                            agg_sig=bls.aggregate(bls_sigs),
                            timestamp_ns=1_700_000_000_000_000_000)
    _rejects(lambda: bl.val_set.verify_commit(bl.chain_id, BID, HEIGHT, c_bl),
             ErrNotEnoughVotingPowerSigned)


def test_duplicate_signer_rejected_by_both(rigs):
    """One validator's signature occupying two slots: slot 1's pubkey can't
    verify slot 0's vote on the ed side; on the BLS side the bitmap claims a
    key whose signature is not in the aggregate, so the pairing fails."""
    ed, bl = rigs
    c_ed = ed.make_commit(set(range(N)))
    dup = c_ed.signatures[0]
    c_ed.signatures[1] = CommitSig(dup.block_id_flag, dup.validator_address,
                                   dup.timestamp_ns, dup.signature)
    _rejects(lambda: ed.val_set.verify_commit(ed.chain_id, BID, HEIGHT, c_ed))

    msg_sigs = {}
    for pv in bl.pvs:
        idx = bl.idx_of[pv.get_pub_key().address()]
        v = Vote(SignedMsgType.PRECOMMIT, HEIGHT, 0, BID,
                 1_700_000_000_000_000_000, pv.get_pub_key().address(),
                 idx, b"")
        pv.sign_vote(bl.chain_id, v)
        msg_sigs[idx] = v.signature
    # fold validator 0 in twice, drop validator 1, but leave 1's bit set
    doubled = [msg_sigs[0], msg_sigs[0]] + [msg_sigs[i] for i in range(2, N)]
    signers = BitArray(N)
    for i in range(N):
        signers.set_index(i, True)
    c_bl = AggregatedCommit(HEIGHT, 0, BID, [], signers=signers,
                            agg_sig=bls.aggregate(doubled),
                            timestamp_ns=1_700_000_000_000_000_000)
    _rejects(lambda: bl.val_set.verify_commit(bl.chain_id, BID, HEIGHT, c_bl),
             ErrWrongSignature)


def test_nil_vote_mix_parity(rigs):
    """5 block + 1 nil (50/60 > 40 needed): both accept — the ed plane
    verifies the nil signature without tallying it, the BLS plane leaves the
    nil voter out of the bitmap.  4 block + 2 nil (40 <= 40): both reject."""
    ed, bl = rigs
    ed.verify_all_modes(ed.make_commit(set(range(5)), nil_voters={5}))
    bl.verify_all_modes(bl.make_commit(set(range(5)), nil_voters={5}))

    # 4 block + 2 nil never reaches +2/3, so the VoteSet refuses to even
    # assemble it — build the commits directly, as a byzantine proposer would
    def signed_vote(rig, pv, idx, bid):
        v = Vote(SignedMsgType.PRECOMMIT, HEIGHT, 0, bid,
                 1_700_000_000_000_000_000, pv.get_pub_key().address(),
                 idx, b"")
        pv.sign_vote(rig.chain_id, v)
        return v

    sigs = [None] * N
    for pv in ed.pvs:
        idx = ed.idx_of[pv.get_pub_key().address()]
        v = signed_vote(ed, pv, idx, BID if idx < 4 else NIL)
        sigs[idx] = CommitSig(
            BlockIDFlag.COMMIT if idx < 4 else BlockIDFlag.NIL,
            v.validator_address, v.timestamp_ns, v.signature)
    c_ed = Commit(HEIGHT, 0, BID, sigs)
    _rejects(lambda: ed.val_set.verify_commit(ed.chain_id, BID, HEIGHT, c_ed),
             ErrNotEnoughVotingPowerSigned)

    bls_sigs, signers = [], BitArray(N)
    for pv in bl.pvs:
        idx = bl.idx_of[pv.get_pub_key().address()]
        if idx >= 4:
            continue  # nil voters stay out of the bitmap
        bls_sigs.append(signed_vote(bl, pv, idx, BID).signature)
        signers.set_index(idx, True)
    c_bl = AggregatedCommit(HEIGHT, 0, BID, [], signers=signers,
                            agg_sig=bls.aggregate(bls_sigs),
                            timestamp_ns=1_700_000_000_000_000_000)
    _rejects(lambda: bl.val_set.verify_commit(bl.chain_id, BID, HEIGHT, c_bl),
             ErrNotEnoughVotingPowerSigned)


def test_param_off_artifacts_are_byte_identical():
    """A chain that never opts in must produce EXACTLY the pre-scheme-plane
    bytes: no genesis JSON section, plain Commit from the VoteSet, and an
    unregistered chain id resolves to the ed25519 default."""
    assert schemes.for_chain("never-registered").is_default
    assert not schemes.aggregated("never-registered")

    pvs = [MockPV(crypto.Ed25519PrivKey.generate(bytes([0x50 + i]) * 32))
           for i in range(4)]
    gen = GenesisDoc(
        chain_id="plain-chain", genesis_time_ns=1_700_000_000_000_000_000,
        validators=[GenesisValidator(pv.get_pub_key(), 10) for pv in pvs])
    gen.validate_and_complete()
    js = gen.to_json()
    assert '"signature"' not in js
    assert "bls" not in js
    # and the JSON round-trips without inventing a scheme section
    assert '"signature"' not in GenesisDoc.from_json(js).to_json()

    val_set = ValidatorSet([
        Validator(pv.get_pub_key().address(), pv.get_pub_key(), 10)
        for pv in pvs])
    vs = VoteSet("plain-chain", HEIGHT, 0, SignedMsgType.PRECOMMIT, val_set)
    for pv in pvs:
        addr = pv.get_pub_key().address()
        idx, _ = val_set.get_by_address(addr)
        v = Vote(SignedMsgType.PRECOMMIT, HEIGHT, 0, BID,
                 1_700_000_000_000_000_000 + idx, addr, idx, b"")
        pv.sign_vote("plain-chain", v)
        assert vs.add_vote(v)
    commit = vs.make_commit()
    assert type(commit) is Commit
    assert not hasattr(commit, "agg_sig")
    rt = Commit.decode(commit.encode())
    assert rt.encode() == commit.encode()
    assert type(rt) is Commit


def test_validator_update_pop_gate_rogue_key_regression():
    """The genesis PoP gate must also cover keys entering via ABCI validator
    updates (EndBlock/InitChain): on an aggregated chain with a dynamic
    validator set, an unchecked admission is exactly the rogue-key attack
    surface — pk* - sum(honest pks) would forge fast-aggregate commits."""
    from tendermint_tpu.abci import types as abci
    from tendermint_tpu.state.execution import validate_validator_updates

    params = ConsensusParams(validator=ValidatorParams(["bls12381"]),
                             signature=SignatureParams("bls12381", True))
    try:
        k1 = crypto.Bls12381PrivKey.generate(b"upd" + b"\x01" * 4)
        k2 = crypto.Bls12381PrivKey.generate(b"upd" + b"\x02" * 4)

        validate_validator_updates(
            [abci.ValidatorUpdate("bls12381", k1.pub_key().bytes(), 10,
                                  pop=k1.pop())], params)
        assert bls.is_registered(k1.pub_key().bytes())

        # no pop → refused, never registered
        with pytest.raises(ValueError, match="proof of possession"):
            validate_validator_updates(
                [abci.ValidatorUpdate("bls12381", k2.pub_key().bytes(), 10)],
                params)
        # a pop lifted from ANOTHER key must not stand in
        with pytest.raises(ValueError, match="proof of possession"):
            validate_validator_updates(
                [abci.ValidatorUpdate("bls12381", k2.pub_key().bytes(), 10,
                                      pop=k1.pop())], params)
        assert not bls.is_registered(k2.pub_key().bytes())

        # deletion (power 0) needs no pop
        validate_validator_updates(
            [abci.ValidatorUpdate("bls12381", k2.pub_key().bytes(), 0)],
            params)

        # an already-registered key STILL needs its pop on later updates:
        # the verdict must not depend on in-process registration state
        # (a restarted node has an empty set and must agree)
        with pytest.raises(ValueError, match="proof of possession"):
            validate_validator_updates(
                [abci.ValidatorUpdate("bls12381", k1.pub_key().bytes(), 20)],
                params)
        validate_validator_updates(
            [abci.ValidatorUpdate("bls12381", k1.pub_key().bytes(), 20,
                                  pop=k1.pop())], params)
    finally:
        bls.reset()


def test_validator_update_pop_wire_roundtrip():
    """The pop field survives the ABCI proto codec (ResponseEndBlock)."""
    from tendermint_tpu.abci import types as abci
    from tendermint_tpu.abci.proto_codec import decode_response, encode_response
    from tendermint_tpu.libs import protowire as pw

    k = crypto.Bls12381PrivKey.generate(b"wire" + b"\x03" * 4)
    resp = abci.ResponseEndBlock(validator_updates=[
        abci.ValidatorUpdate("bls12381", k.pub_key().bytes(), 7, pop=k.pop()),
        abci.ValidatorUpdate("ed25519", b"\x11" * 32, 3),  # pop absent
    ])
    frame, _ = pw.read_length_delimited(encode_response("end_block", resp))
    _method, rt = decode_response(frame)
    assert rt.validator_updates[0].pop == k.pop()
    assert rt.validator_updates[0].pub_key_bytes == k.pub_key().bytes()
    assert rt.validator_updates[1].pop == b""


def test_aggregated_commit_time_window():
    """timestamp_ns is covered by no signature, so each validator bounds it
    subjectively before prevoting (consensus.state.check_aggregated_commit_time):
    within drift of its own recorded precommit times, never ahead of the
    local clock by more than drift."""
    from tendermint_tpu.consensus.state import check_aggregated_commit_time

    now = 1_700_000_000_000_000_000
    drift = 10_000_000_000  # 10s
    commit = AggregatedCommit(HEIGHT, 0, BID, [], signers=BitArray(N),
                              agg_sig=b"\x01" * 48, timestamp_ns=now)

    # in-window vs recorded precommit times
    seen = [now - 2_000_000_000, now, now + 1_000_000_000]
    check_aggregated_commit_time(commit, seen, now, drift)
    # no recorded votes (catching up): only the clock bound applies
    check_aggregated_commit_time(commit, [], now, drift)

    # proposer-invented future time: beyond clock drift
    commit.timestamp_ns = now + drift + 1
    with pytest.raises(ValueError, match="ahead of local time"):
        check_aggregated_commit_time(commit, seen, now, drift)

    # inside clock drift but outside the recorded-precommit window
    commit.timestamp_ns = now + drift - 1
    with pytest.raises(ValueError, match="outside the window"):
        check_aggregated_commit_time(commit, [now - 30_000_000_000], now, drift)
    # ... and a past time far below anything we saw is refused too
    commit.timestamp_ns = now - 60_000_000_000
    with pytest.raises(ValueError, match="outside the window"):
        check_aggregated_commit_time(commit, seen, now, drift)


def test_trusting_batched_aggregated_commit_vals_across_valset_change():
    """Aggregated entries of verify_commit_light_trusting_batched may carry
    the commit-height validator set as a 5th tuple element: whenever the
    trusted set differs from the commit's signer bitmap (any valset change
    between trusted and commit height) the pairing needs THAT set, exactly
    like the non-batched path with commit_vals (light/verifier.py
    verify_non_adjacent)."""
    from tendermint_tpu.types.canonical import vote_sign_bytes as vsb
    from tendermint_tpu.types.errors import ErrInvalidCommitSignatures
    from tendermint_tpu.types.validator_set import (
        verify_commit_light_trusting_batched,
    )

    try:
        trust = (1, 3)
        pks = [crypto.Bls12381PrivKey.generate(b"lbat" + bytes([i]) * 4)
               for i in range(5)]
        commit_vals = ValidatorSet([
            Validator(k.pub_key().address(), k.pub_key(), 10) for k in pks])
        msg = vsb("agg-batched", SignedMsgType.PRECOMMIT, HEIGHT, 0, BID, 0)
        signers = BitArray(5)
        for i in range(5):
            signers.set_index(i, True)
        commit = AggregatedCommit(
            HEIGHT, 0, BID, [], signers=signers,
            agg_sig=bls.aggregate([k.sign(msg) for k in pks]),
            timestamp_ns=1_700_000_000_000_000_000)

        # trusted set = commit set minus one validator: a different size,
        # the shape every bisection step with a valset change produces
        trusted = ValidatorSet([
            Validator(k.pub_key().address(), k.pub_key(), 10)
            for k in pks[:4]])

        # plain ed25519 entry rides the same batch, unaffected
        ed = Rig("agg-batched-ed", "ed25519")
        ed_commit = ed.make_commit(set(range(N)))

        results = verify_commit_light_trusting_batched([
            (trusted, "agg-batched", commit, trust, commit_vals),
            (ed.val_set, ed.chain_id, ed_commit, trust),
            (trusted, "agg-batched", commit, trust),  # no commit_vals: size mismatch
        ])
        assert results[0] is None
        assert results[1] is None
        assert isinstance(results[2], ErrInvalidCommitSignatures)

        # exact parity with the sequential path, both ways
        trusted.verify_commit_light_trusting("agg-batched", commit, trust,
                                             commit_vals=commit_vals)
        with pytest.raises(ErrInvalidCommitSignatures):
            trusted.verify_commit_light_trusting("agg-batched", commit, trust)
    finally:
        schemes.reset()
        bls.reset()


def test_genesis_pop_gate_rogue_key_regression():
    """A BLS validator enters genesis only with a proof of possession for
    ITS key: a missing pop, a replayed pop, and a wrong-scheme key must all
    refuse validate_and_complete."""
    try:
        pks = [crypto.Bls12381PrivKey.generate(b"gen" + bytes([i]) * 4)
               for i in range(4)]
        params = ConsensusParams(
            validator=ValidatorParams(["bls12381"]),
            signature=SignatureParams("bls12381", True))

        def gen(validators):
            return GenesisDoc(chain_id="bls-gen",
                              genesis_time_ns=1_700_000_000_000_000_000,
                              consensus_params=params, validators=validators)

        good = [GenesisValidator(k.pub_key(), 10, pop=k.pop()) for k in pks]
        gen(good).validate_and_complete()
        for k in pks:
            assert bls.is_registered(k.pub_key().bytes())

        bls.reset()
        missing = [GenesisValidator(pks[0].pub_key(), 10)]
        with pytest.raises(ValueError, match="proof of possession"):
            gen(missing).validate_and_complete()

        # the rogue-key shape: an attacker who computed a key to cancel the
        # honest apk cannot also produce a pop (no knowledge of its sk) —
        # a pop lifted from ANOTHER key must not stand in
        bls.reset()
        replayed = [GenesisValidator(pks[0].pub_key(), 10, pop=pks[0].pop()),
                    GenesisValidator(pks[1].pub_key(), 10, pop=pks[0].pop())]
        with pytest.raises(ValueError, match="possession"):
            gen(replayed).validate_and_complete()
        assert not bls.is_registered(pks[1].pub_key().bytes())

        bls.reset()
        wrong_scheme = [GenesisValidator(
            crypto.Ed25519PrivKey.generate(b"\x01" * 32).pub_key(), 10)]
        with pytest.raises(ValueError, match="bls12381"):
            gen(wrong_scheme).validate_and_complete()
    finally:
        schemes.reset()
        bls.reset()
