"""Operator surface: debug dump bundle, offline WAL replay, compact-db,
reindex-event, and the ops RPC routes (dump_consensus_state, check_tx,
genesis_chunked, unsafe routes gating) — reference
cmd/tendermint/commands/debug/, replay.go, rpc/core/routes.go.
"""

import argparse
import asyncio
import base64
import json
import os

import pytest

pytest.importorskip("cryptography", reason="needs the optional 'cryptography' package (absent in slim containers)")

from tests.test_node_rpc import _mk_node


def _ns(**kw):
    return argparse.Namespace(**kw)


def test_ops_routes_and_debug_bundle(tmp_path, capsys):
    from tendermint_tpu.cmd import cmd_compact_db, cmd_debug, cmd_replay
    from tendermint_tpu.rpc.client import HTTPClient

    node = _mk_node(tmp_path, backend="sqlite")
    home = node.config.root_dir
    node.config.save()  # the debug/replay CLI loads config.toml from disk
    node.genesis.save_as(node.config.genesis_file())

    async def run():
        await node.start()
        try:
            rpc = HTTPClient(f"http://127.0.0.1:{node.rpc_server.bound_port}")
            await rpc.call("broadcast_tx_sync",
                           tx=base64.b64encode(b"ops=1").decode())
            for _ in range(600):
                st = await rpc.status()
                if int(st["sync_info"]["latest_block_height"]) >= 3:
                    break
                await asyncio.sleep(0.05)

            # dump_consensus_state: full round state with vote bit-arrays
            dump = await rpc.call("dump_consensus_state")
            assert "round_state" in dump and "peers" in dump
            assert int(dump["round_state"]["height"]) >= 3
            assert isinstance(dump["round_state"]["height_vote_set"], list)

            # check_tx runs CheckTx without mutating the mempool
            before = int((await rpc.call("num_unconfirmed_txs"))["total"])
            res = await rpc.call(
                "check_tx", tx=base64.b64encode(b"probe=1").decode())
            assert res["code"] == 0
            after = int((await rpc.call("num_unconfirmed_txs"))["total"])
            assert after == before

            # genesis_chunked round-trips the genesis doc
            g = await rpc.call("genesis_chunked", chunk=0)
            doc = json.loads(base64.b64decode(g["data"]))
            assert doc["chain_id"] == "rpc-chain"

            # unsafe routes are NOT served without rpc.unsafe
            from tendermint_tpu.rpc.core import RPCError
            with pytest.raises(RPCError):
                await rpc.call("unsafe_flush_mempool")

            # debug dump against the live node (in a thread: the CLI's
            # blocking HTTP must not stall the node's own event loop)
            out_dir = str(tmp_path / "bundle")
            rc = await asyncio.to_thread(cmd_debug, _ns(
                home=home, output_dir=out_dir, action="dump",
                rpc_laddr=f"tcp://127.0.0.1:{node.rpc_server.bound_port}",
                pid=0))
            assert rc == 0
            for f in ("status.json", "dump_consensus_state.json",
                      "config.toml", "wal_tail.jsonl"):
                assert os.path.exists(os.path.join(out_dir, f)), f
            with open(os.path.join(out_dir, "dump_consensus_state.json")) as f:
                bundle = json.load(f)
            assert "round_state" in bundle["result"]
            # the WAL tail alone shows consensus progress (wedge diagnosis)
            with open(os.path.join(out_dir, "wal_tail.jsonl")) as f:
                types = [json.loads(line)["type"] for line in f]
            assert "end_height" in types

            await rpc.close()
        finally:
            await node.stop()

    asyncio.run(run())

    # offline replay over the same home: handshake + WAL tail
    rc = cmd_replay(_ns(home=home, console=False))
    assert rc == 0
    out = capsys.readouterr().out
    assert "handshake replayed chain to height" in out

    # compact-db over the sqlite stores
    rc = cmd_compact_db(_ns(home=home))
    assert rc == 0
    assert "blockstore.db" in capsys.readouterr().out


def test_reindex_event(tmp_path, capsys):
    from tendermint_tpu.cmd import cmd_reindex_event
    from tendermint_tpu.rpc.client import HTTPClient

    node = _mk_node(tmp_path, backend="sqlite")
    home = node.config.root_dir
    node.config.save()

    async def run():
        await node.start()
        try:
            rpc = HTTPClient(f"http://127.0.0.1:{node.rpc_server.bound_port}")
            await rpc.call("broadcast_tx_sync",
                           tx=base64.b64encode(b"ridx=1").decode())
            for _ in range(600):
                st = await rpc.status()
                if int(st["sync_info"]["latest_block_height"]) >= 3:
                    break
                await asyncio.sleep(0.05)
            await rpc.close()
        finally:
            await node.stop()

    asyncio.run(run())
    rc = cmd_reindex_event(_ns(home=home))
    assert rc == 0
    out = capsys.readouterr().out
    assert "reindexed" in out and "reindexed 0" not in out


def test_openapi_spec_covers_every_route():
    """rpc/openapi.yaml (reference rpc/openapi/openapi.yaml) must document
    every served route — including the unsafe tier and the WS-only
    subscribe/unsubscribe — so the spec can't silently drift from ROUTES."""
    import re

    from tendermint_tpu.rpc.core import ROUTES, UNSAFE_ROUTES

    spec_path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tendermint_tpu", "rpc", "openapi.yaml")
    text = open(spec_path).read()
    documented = set(re.findall(r"^  /([a-z_]+):", text, re.M))
    missing = (set(ROUTES) | set(UNSAFE_ROUTES)) - documented
    assert not missing, f"openapi.yaml missing routes: {sorted(missing)}"
    assert {"subscribe", "unsubscribe"} <= documented
