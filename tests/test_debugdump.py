"""SIGUSR1 in-process dump for wedged nodes (reference keeps a pprof
listener for this, node/node.go:896; debug/kill.go snapshots goroutines).

The key property: the dump must work when the asyncio loop CANNOT serve a
callback — so the wedge here is a loop thread stuck in a pure-Python spin
inside a loop callback, and the dump still captures its stack.
"""

import asyncio
import os
import signal
import threading
import time

from tendermint_tpu.libs import debugdump


def test_dump_captures_wedged_loop(tmp_path):
    loop = asyncio.new_event_loop()
    wedged = threading.Event()
    release = threading.Event()

    async def innocent_task():
        await asyncio.sleep(300)  # parked task: must appear in tasks.txt

    def wedge_forever():
        # a loop callback that never returns: the loop cannot process
        # anything else (loop.add_signal_handler would never fire)
        wedged.set()
        while not release.is_set():
            time.sleep(0.01)

    def run_loop():
        asyncio.set_event_loop(loop)
        loop.create_task(innocent_task(), name="innocent-sleeper")
        loop.call_soon(wedge_forever)
        loop.run_forever()

    t = threading.Thread(target=run_loop, daemon=True)
    t.start()
    assert wedged.wait(5), "loop thread failed to wedge"

    out = debugdump.write_dump(str(tmp_path / "dump"), loop=loop)

    threads = open(os.path.join(out, "threads.txt")).read()
    assert "wedge_forever" in threads, "wedged callback stack missing"
    tasks = open(os.path.join(out, "tasks.txt")).read()
    assert "innocent-sleeper" in tasks or "innocent_task" in tasks

    release.set()
    loop.call_soon_threadsafe(loop.stop)
    t.join(timeout=5)
    loop.close()


def test_signal_handler_writes_bundle(tmp_path):
    home = str(tmp_path / "home")
    os.makedirs(home)
    debugdump.install(home)
    assert debugdump.installed_home() == home
    os.kill(os.getpid(), signal.SIGUSR1)
    # synchronous handler: the bundle exists by the time kill() returns
    deadline = time.time() + 5
    dumps = []
    while time.time() < deadline and not dumps:
        dumps = [d for d in os.listdir(home) if d.startswith("debug-")]
        time.sleep(0.05)
    assert dumps, "no dump directory created"
    threads = open(os.path.join(home, dumps[0], "threads.txt")).read()
    assert "test_signal_handler_writes_bundle" in threads
    signal.signal(signal.SIGUSR1, signal.SIG_DFL)


def test_dump_includes_node_state(tmp_path):
    class _RS:
        height, round, step = 7, 1, "prevote"

    class _CS:
        rs = _RS()

    class _Switch:
        peers = {"ab12": object()}

    class _Node:
        consensus_state = _CS()
        switch = _Switch()

    out = debugdump.write_dump(str(tmp_path / "dump"), node=_Node())
    state = open(os.path.join(out, "node_state.txt")).read()
    assert "height=7" in state and "prevote" in state
    assert "ab12" in state


def test_dump_includes_device_snapshot(tmp_path):
    """device.json: phase totals + last-N segment records + compile-cache
    fingerprint status land in every bundle (jax inventory only when jax is
    already imported — a dump must not pay a cold backend init)."""
    import json

    from tendermint_tpu.crypto import phases

    phases.reset()
    phases.count_host("sync", 3)
    out = debugdump.write_dump(str(tmp_path / "dump"))
    doc = json.load(open(os.path.join(out, "device.json")))
    assert doc["phase_totals"]["host_batches"] == 1
    assert doc["phase_totals"]["host_sigs"] == 3
    assert isinstance(doc["recent_segments"], list)
    assert "compile_cache" in doc
    # this test process imported jax (conftest pin): inventory present
    assert doc.get("jax_backend") == "cpu"
    assert len(doc.get("devices", [])) == 8
    phases.reset()


def test_dump_includes_txlife_snapshot(tmp_path):
    """txlife.json: a node carrying the tx lifecycle tracker bundles its
    snapshot — terminal records and the in-flight depth at dump time."""
    import hashlib
    import json

    from tendermint_tpu.libs.txlife import TxLifecycle

    tl = TxLifecycle(sample_rate=1.0)
    k = hashlib.sha256(b"dump-tx").digest()
    tl.mark(k, "rpc_received")
    tl.mark(k, "checktx_done", outcome="accepted")
    tl.mark(k, "mempool_admitted")
    tl.mark(k, "committed", height=4)

    class _Mempool:
        txlife = tl

    class _Node:
        mempool = _Mempool()

    out = debugdump.write_dump(str(tmp_path / "dump"), node=_Node())
    doc = json.load(open(os.path.join(out, "txlife.json")))
    assert doc["sealed_total"] == 1
    assert doc["records"][0]["terminal"] == "committed"
    assert doc["records"][0]["height"] == 4
