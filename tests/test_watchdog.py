"""Consensus stall watchdog (consensus/watchdog.py): injected fault →
observable degradation. A stuck height must raise consensus_stalled_total
exactly once per episode, leave a debugdump bundle behind, and re-arm only
after the height moves again.
"""

import asyncio
import os
import types

from tendermint_tpu.consensus.watchdog import ConsensusWatchdog
from tendermint_tpu.libs.metrics import ConsensusMetrics, Registry


class _FakeCS:
    """The two attributes the watchdog reads: state.last_block_height and
    rs (for the round/step in the CRITICAL log line)."""

    def __init__(self, height=1):
        self.state = types.SimpleNamespace(last_block_height=height)
        self.rs = types.SimpleNamespace(height=height, round=0, step="propose")


def test_stall_fires_once_per_episode_and_rearms(tmp_path):
    async def run():
        cs = _FakeCS()
        m = ConsensusMetrics(Registry())
        wd = ConsensusWatchdog(cs, stall_timeout_s=0.2, metrics=m,
                               dump_dir=str(tmp_path), dump_node=None,
                               check_interval_s=0.05)
        await wd.start()
        # height frozen: one episode, and only one, however long it lasts
        await asyncio.sleep(0.6)
        assert wd.stalls == 1
        assert m.consensus_stalled_total.value() == 1
        assert wd.last_dump_path is not None
        assert os.path.exists(wd.last_dump_path)

        # progress clears the episode but does NOT count a new one
        cs.state.last_block_height = 2
        await asyncio.sleep(0.15)
        assert wd.stalls == 1

        # a second freeze is a second episode
        await asyncio.sleep(0.5)
        assert wd.stalls == 2
        assert m.consensus_stalled_total.value() == 2
        await wd.stop()

    asyncio.run(run())


def test_no_stall_while_height_advances(tmp_path):
    async def run():
        cs = _FakeCS()
        wd = ConsensusWatchdog(cs, stall_timeout_s=0.3,
                               dump_dir=str(tmp_path), dump_node=None,
                               check_interval_s=0.05)
        await wd.start()
        for h in range(2, 10):
            cs.state.last_block_height = h
            await asyncio.sleep(0.08)
        assert wd.stalls == 0
        assert wd.last_dump_path is None
        await wd.stop()

    asyncio.run(run())


def test_stop_cancels_cleanly(tmp_path):
    async def run():
        wd = ConsensusWatchdog(_FakeCS(), stall_timeout_s=5.0,
                               dump_dir=str(tmp_path),
                               check_interval_s=0.05)
        await wd.start()
        await asyncio.sleep(0.1)
        await wd.stop()
        assert wd._task is None

    asyncio.run(run())


def test_dump_failure_does_not_kill_the_watchdog():
    """debugdump failing (bad dir) must not take the watchdog loop down —
    the metric is the alertable signal, the bundle is best-effort."""
    async def run():
        cs = _FakeCS()
        m = ConsensusMetrics(Registry())
        wd = ConsensusWatchdog(cs, stall_timeout_s=0.1, metrics=m,
                               dump_dir="/nonexistent/definitely/not/here",
                               check_interval_s=0.05)
        await wd.start()
        await asyncio.sleep(0.3)
        assert wd.stalls == 1
        assert m.consensus_stalled_total.value() == 1
        # loop survived: progress + a second freeze still counts
        cs.state.last_block_height = 2
        await asyncio.sleep(0.1)
        await asyncio.sleep(0.25)
        assert wd.stalls == 2
        await wd.stop()

    asyncio.run(run())
