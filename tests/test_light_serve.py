"""Light-client serving plane: pure planning math, cache/limiter semantics,
and the coalescer differential — coalesced verdicts must be byte-identical
(exception type AND message) to the scalar light/verifier.verify spec across
valid, bad-signature, rotated-set, expired-trust, and BLS aggregated
batches, with and without an armed device.batch_verify fault."""

import asyncio
import json

import pytest

from tendermint_tpu import crypto
from tendermint_tpu.crypto import bls12381 as bls
from tendermint_tpu.crypto import schemes
from tendermint_tpu.libs.faults import faults
from tendermint_tpu.light import verifier
from tendermint_tpu.light.serve import (
    ClientLimiter,
    HeaderCache,
    ServeProvider,
    ShedError,
    TokenBucket,
    VerifyCoalescer,
    VerifyRequest,
    bisection_skeleton,
    fanout_queue_plan,
    plan_flushes,
)
from tendermint_tpu.types import MockPV, Validator, ValidatorSet
from tendermint_tpu.types.basic import BlockID, PartSetHeader, SignedMsgType
from tendermint_tpu.types.block import Consensus, Header
from tendermint_tpu.types.light_block import LightBlock, SignedHeader
from tendermint_tpu.types.params import SignatureParams
from tendermint_tpu.types.vote import Vote
from tendermint_tpu.types.vote_set import VoteSet

from tests.test_light_client import (  # noqa: F401  (chain builders)
    CHAIN,
    T0,
    _keys,
    _mk_chain,
    _resign,
    _val_set,
)

NOW = T0 + 100 * 1_000_000_000


# -- pure planning math ------------------------------------------------------

def test_bisection_skeleton_orders_shallowest_first():
    sk = bisection_skeleton(1, 17)
    assert sk[0] == 9  # the root midpoint
    assert sk[1:3] == [5, 13]  # its children, breadth-first
    assert len(sk) == len(set(sk))
    assert all(1 < h < 17 for h in sk)
    # degenerate spans plan nothing
    assert bisection_skeleton(5, 5) == []
    assert bisection_skeleton(5, 6) == []
    # cap bounds the plan
    assert len(bisection_skeleton(1, 10_000, cap=8)) == 8


def test_plan_flushes_deadline_and_size_triggers():
    # 3 requests inside one deadline window: one flush at t0+deadline
    assert plan_flushes([0.0, 0.001, 0.002], 0.005, 64) == [(0.005, 3)]
    # size trigger fires early: batch closes at its max_batch'th arrival
    assert plan_flushes([0.0, 0.001, 0.002], 0.005, 2) == \
        [(0.001, 2), (0.007, 1)]
    # a gap larger than the deadline opens a new batch
    assert plan_flushes([0.0, 1.0], 0.005, 64) == [(0.005, 1), (1.005, 1)]
    with pytest.raises(ValueError):
        plan_flushes([], 0.005, 0)


def test_fanout_queue_plan_bounds_and_evicts():
    assert fanout_queue_plan(10, 10, 4) == (0, False)
    assert fanout_queue_plan(10, 7, 4) == (3, False)
    assert fanout_queue_plan(10, 0, 4) == (4, True)  # capped + evicted
    with pytest.raises(ValueError):
        fanout_queue_plan(1, 0, 0)


def test_token_bucket_refills_on_injected_clock():
    t = [0.0]
    tb = TokenBucket(rate=1.0, burst=2.0, clock=lambda: t[0])
    assert tb.allow() and tb.allow() and not tb.allow()
    t[0] = 1.0
    assert tb.allow() and not tb.allow()


def test_header_cache_lru_and_pinned_eviction():
    c = HeaderCache(capacity=3)
    c.put(1, "a")
    c.put(2, "b", pinned=True)
    c.put(3, "c")
    assert c.get(1) == "a"  # 1 now most-recent
    c.put(4, "d")  # evicts 3 (oldest UNPINNED; 2 is pinned)
    assert c.peek(3) is None and c.peek(2) == "b"
    assert c.stats["evictions"] == 1
    # all-pinned: capacity still a hard bound, oldest pin goes
    c2 = HeaderCache(capacity=2)
    c2.put(1, "a", pinned=True)
    c2.put(2, "b", pinned=True)
    c2.put(3, "c", pinned=True)
    assert len(c2) == 2 and c2.peek(1) is None
    assert c2.pinned_count() == 2
    # peek never touches accounting
    before = dict(c2.stats)
    c2.peek(2)
    assert c2.stats == before


class _StubScoreboard:
    def __init__(self, ban_after=3):
        self.strikes = {}
        self.ban_after = ban_after
        self.reasons = []

    def banned(self, pid):
        return self.strikes.get(pid, 0) >= self.ban_after

    def record_failure(self, pid, reason="error", severe=False):
        self.strikes[pid] = self.strikes.get(pid, 0) + 1
        self.reasons.append(reason)

    def record_success(self, pid):
        self.strikes[pid] = 0


def test_client_limiter_sheds_are_reason_labeled_and_ban():
    t = [0.0]
    sb = _StubScoreboard(ban_after=3)
    lim = ClientLimiter(rate=1.0, burst=2.0, scoreboard=sb,
                        clock=lambda: t[0])
    lim.admit("c1")
    lim.admit("c1")
    for _ in range(3):  # empty bucket: rate sheds accumulate strikes
        with pytest.raises(ShedError) as ei:
            lim.admit("c1")
        assert ei.value.reason == "client-rate"
    with pytest.raises(ShedError) as ei:  # banned now
        lim.admit("c1")
    assert ei.value.reason == "banned"
    assert lim.stats == {"admitted": 2, "rate_sheds": 3, "ban_sheds": 1}
    assert sb.reasons == ["rate"] * 3
    # other clients unaffected; rate<=0 disables limiting entirely
    lim.admit("c2")
    ClientLimiter(rate=0.0, burst=1.0).admit("anyone")


# -- the coalescer differential ---------------------------------------------

def _req(blocks, trusted_h, h, period=3600.0, now=NOW, drift=10.0,
         trust_level=(1, 3), key=None):
    return VerifyRequest(
        blocks[trusted_h].signed_header, blocks[trusted_h].validator_set,
        blocks[h].signed_header, blocks[h].validator_set,
        period, now, drift, trust_level, cache_key=key)


def _scalar_verdict(req):
    try:
        verifier.verify(req.trusted_sh, req.trusted_vals, req.untrusted_sh,
                        req.untrusted_vals, req.trusting_period_s, req.now_ns,
                        req.max_clock_drift_s, req.trust_level)
        return None
    except Exception as e:  # noqa: BLE001 — the verdict IS the exception
        return e


def _coalesce(reqs, backend=None, flush_max=None):
    """Run every request through ONE coalescer concurrently; return the
    per-request results (None or exception instance)."""

    async def run():
        co = VerifyCoalescer(flush_deadline_s=0.01,
                             flush_max=flush_max or max(len(reqs), 1),
                             backend=backend)
        try:
            return await asyncio.gather(
                *[co.submit(r) for r in reqs], return_exceptions=True), co
        finally:
            co.stop()

    return asyncio.run(run())


def _assert_verdict_parity(reqs, results):
    for req, got in zip(reqs, results):
        want = _scalar_verdict(req)
        if want is None:
            assert got is None, f"coalesced rejected what scalar accepts: {got!r}"
        else:
            assert type(got) is type(want), (got, want)
            assert str(got) == str(want), (got, want)


def _mixed_ed25519_batch():
    """One batch covering every verdict class the scalar spec produces."""
    a, b = _keys(0x30, 4), _keys(0x40, 4)
    rot = _mk_chain([a, a, a, a, b, b, b, b, b, b], 10)  # rotation at 5
    keys = _keys(0x80, 4)
    stable = _mk_chain([keys], 8)

    import copy
    bad_sig = copy.deepcopy(stable)
    bad_sig[6].signed_header.commit.signatures[0].signature = b"\x00" * 64
    bad_vals = copy.deepcopy(stable)
    bad_vals[6] = LightBlock(bad_vals[6].signed_header,
                             _val_set(_keys(0x90, 4)))  # wrong untrusted set

    return [
        _req(stable, 1, 8),                       # valid non-adjacent
        _req(stable, 4, 5),                       # valid adjacent
        _req(bad_sig, 1, 6),                      # ErrInvalidHeader(bad sig)
        _req(bad_vals, 1, 6),                     # valset hash mismatch
        _req(stable, 1, 8, period=1.0),           # ErrOldHeaderExpired
        _req(rot, 1, 10),                         # ErrNewValSetCantBeTrusted
        _req(stable, 2, 7),                       # another valid span
    ]


def test_coalesced_verdicts_match_scalar_ed25519():
    reqs = _mixed_ed25519_batch()
    results, co = _coalesce(reqs)
    _assert_verdict_parity(reqs, results)
    assert co.stats["flushes"] >= 1
    assert co.stats["batched_sigs"] > 0  # the device batch actually ran


def test_coalesced_verdicts_match_scalar_host_backend():
    reqs = _mixed_ed25519_batch()
    results, _ = _coalesce(reqs, backend="host")
    _assert_verdict_parity(reqs, results)


def _mk_bls_chain(chain_id, pvs, n_heights):
    """Aggregated-commit chain via the real VoteSet path (make_commit emits
    AggregatedCommit for a registered BLS chain)."""
    vals = ValidatorSet([
        Validator(pv.get_pub_key().address(), pv.get_pub_key(), 10)
        for pv in pvs])
    blocks = {}
    last_bid = BlockID(b"", PartSetHeader())
    for h in range(1, n_heights + 1):
        header = Header(
            version=Consensus(), chain_id=chain_id, height=h,
            time_ns=T0 + h * 1_000_000_000, last_block_id=last_bid,
            last_commit_hash=b"\x01" * 32, data_hash=b"\x02" * 32,
            validators_hash=vals.hash(), next_validators_hash=vals.hash(),
            consensus_hash=b"\x03" * 32, app_hash=b"\x04" * 32,
            last_results_hash=b"\x05" * 32, evidence_hash=b"\x06" * 32,
            proposer_address=pvs[0].get_pub_key().address())
        bid = BlockID(header.hash(), PartSetHeader(1, b"\x07" * 32))
        vs = VoteSet(chain_id, h, 0, SignedMsgType.PRECOMMIT, vals)
        for pv in pvs:
            addr = pv.get_pub_key().address()
            idx, _ = vals.get_by_address(addr)
            v = Vote(SignedMsgType.PRECOMMIT, h, 0, bid,
                     header.time_ns + 1000 + idx, addr, idx, b"")
            pv.sign_vote(chain_id, v)
            assert vs.add_vote(v)
        commit = vs.make_commit()
        assert hasattr(commit, "agg_sig"), "BLS chain must aggregate"
        blocks[h] = LightBlock(SignedHeader(header, commit), vals)
        last_bid = bid
    return blocks


def test_coalesced_verdicts_match_scalar_bls_aggregated():
    chain_id = "lightserve-bls"
    schemes.register_chain(chain_id, SignatureParams("bls12381", True))
    try:
        pvs = [MockPV(crypto.Bls12381PrivKey.generate(
            b"lsrv" + bytes([i]) * 4)) for i in range(4)]
        blocks = _mk_bls_chain(chain_id, pvs, 6)
        import copy
        bad = copy.deepcopy(blocks)
        sh = bad[5].signed_header
        c = sh.commit
        c.agg_sig = bytes([c.agg_sig[0] ^ 0x01]) + c.agg_sig[1:]
        reqs = [
            _req(blocks, 1, 6),          # valid skip over aggregated commits
            _req(blocks, 3, 4),          # valid adjacent
            _req(bad, 1, 5),             # tampered aggregate: rejected
            _req(blocks, 1, 6, period=1.0),  # expired
        ]
        results, co = _coalesce(reqs)
        _assert_verdict_parity(reqs, results)
        # aggregated commits pair inline: nothing enters the ed25519 batch
        assert co.stats["batched_sigs"] == 0
    finally:
        schemes.reset()
        bls.reset()


def test_coalesced_parity_survives_armed_device_fault():
    """With lightserve traffic mid-flight, an armed device.batch_verify
    fault degrades the batched call to host verify — verdicts must stay
    byte-identical to the scalar spec."""
    pytest.importorskip("jax")
    reqs = _mixed_ed25519_batch()
    faults.configure("device.batch_verify@1", seed=7)
    try:
        results, co = _coalesce(reqs, backend="jax")
    finally:
        faults.reset()
    _assert_verdict_parity(reqs, results)
    assert co.stats["batched_sigs"] > 0


# -- coalescer mechanics -----------------------------------------------------

def test_coalescer_dedup_and_verdict_cache():
    keys = _keys(0xA0, 4)
    blocks = _mk_chain([keys], 6)
    req = lambda: _req(blocks, 1, 5, key=("k", 1, 5))  # noqa: E731

    async def run():
        co = VerifyCoalescer(flush_deadline_s=0.005, flush_max=64)
        try:
            r = await asyncio.gather(*[co.submit(req()) for _ in range(8)])
            assert all(v is None for v in r)
            assert co.stats["requests"] == 8
            assert co.stats["verified_requests"] == 1  # one shared verify
            assert co.stats["coalesced_dupes"] == 7
            # across flushes: the verdict cache answers without a flush
            flushes = co.stats["flushes"]
            assert await co.submit(req()) is None
            assert co.stats["verdict_cache_hits"] == 1
            assert co.stats["flushes"] == flushes
        finally:
            co.stop()

    asyncio.run(run())


def test_coalescer_size_trigger_and_queue_full_shed():
    keys = _keys(0xB0, 4)
    blocks = _mk_chain([keys], 6)

    async def run():
        # size trigger: deadline is far out, yet flush_max completes us
        co = VerifyCoalescer(flush_deadline_s=30.0, flush_max=2)
        try:
            r = await asyncio.wait_for(
                asyncio.gather(co.submit(_req(blocks, 1, 5)),
                               co.submit(_req(blocks, 2, 6))), timeout=5.0)
            assert r == [None, None]
            assert co.stats["largest_flush"] == 2
        finally:
            co.stop()

        # queue-full: an explicit reason-labeled shed, never a stall
        co2 = VerifyCoalescer(flush_deadline_s=30.0, flush_max=64,
                              queue_limit=1)
        t1 = asyncio.ensure_future(co2.submit(_req(blocks, 1, 5)))
        await asyncio.sleep(0)  # let it enqueue
        with pytest.raises(ShedError) as ei:
            await co2.submit(_req(blocks, 2, 6))
        assert ei.value.reason == "queue-full"
        assert co2.stats["sheds"] == 1
        co2.stop()  # shutdown fails the queued request explicitly too
        with pytest.raises(ShedError) as ei:
            await t1
        assert ei.value.reason == "shutdown"

    asyncio.run(run())


def test_coalescer_survives_cancelled_clients():
    """A client that gives up must not poison the shared verification."""
    keys = _keys(0xC0, 4)
    blocks = _mk_chain([keys], 6)

    async def run():
        co = VerifyCoalescer(flush_deadline_s=0.005, flush_max=64)
        try:
            k = ("same", 1, 5)
            t1 = asyncio.ensure_future(co.submit(_req(blocks, 1, 5, key=k)))
            t2 = asyncio.ensure_future(co.submit(_req(blocks, 1, 5, key=k)))
            await asyncio.sleep(0)
            t1.cancel()
            assert await asyncio.wait_for(t2, timeout=5.0) is None
        finally:
            co.stop()

    asyncio.run(run())


# -- ServeProvider + tamper seam --------------------------------------------

def test_serve_provider_caches_and_tampers_only_when_armed():
    keys = _keys(0xD0, 4)
    blocks = _mk_chain([keys], 6)
    forged = _resign(
        {h: LightBlock(SignedHeader(lb.signed_header.header,
                                    lb.signed_header.commit),
                       lb.validator_set) for h, lb in
         _mk_chain([keys], 6).items()}, keys)

    async def run():
        p = ServeProvider(CHAIN, blocks, forged={4: forged[4]}, name="w1")
        lb = await p.light_block(4)
        assert lb is blocks[4]  # disarmed: honest block, never the forgery
        await p.light_block(4)
        assert p.cache.stats["hits"] == 1
        assert (await p.light_block(0)).signed_header.header.height == 6
        from tendermint_tpu.light.provider import ErrLightBlockNotFound
        with pytest.raises(ErrLightBlockNotFound):
            await p.light_block(99)
        assert p.id() == "w1"

        faults.configure("lightserve.lying_server@1", seed=3)
        try:
            assert (await p.light_block(4)) is forged[4]
            assert (await p.light_block(3)) is blocks[3]  # not forged
        finally:
            faults.reset()

    asyncio.run(run())


# -- the serving plane in-proc: a 64-client fleet ----------------------------

class _BlockStoreStub:
    def __init__(self, blocks):
        self.blocks = blocks

    def height(self):
        return max(self.blocks)

    def load_block_meta(self, h):
        from types import SimpleNamespace
        lb = self.blocks.get(h)
        return None if lb is None else SimpleNamespace(
            header=lb.signed_header.header)

    def load_block_commit(self, h):
        lb = self.blocks.get(h)
        return None if lb is None else lb.signed_header.commit

    load_seen_commit = load_block_commit


class _StateStoreStub:
    def __init__(self, blocks):
        self.blocks = blocks

    def load_validators(self, h):
        lb = self.blocks.get(h)
        return None if lb is None else lb.validator_set


def _mk_plane(blocks, **overrides):
    from tendermint_tpu.config import LightServeConfig
    from tendermint_tpu.light.serve import LightServePlane

    cfg = LightServeConfig()
    cfg.trusting_period_s = 10 * 365 * 24 * 3600.0  # chain fixture is 2023
    for k, v in overrides.items():
        setattr(cfg, k, v)
    return LightServePlane(block_store=_BlockStoreStub(blocks),
                           state_store=_StateStoreStub(blocks),
                           chain_id=CHAIN, config=cfg)


def test_plane_serves_64_concurrent_clients():
    """The tier-1 fleet: >=64 concurrent clients against one serving plane
    — every verdict accepted, verification coalesced far below request
    count, header cache + skeleton prefetch shared across the fleet."""
    blocks = _mk_chain([_keys(0x10, 4)], 10)
    plane = _mk_plane(blocks)

    async def run():
        try:
            async def one(i):
                if i % 2:
                    return await plane.serve_verify(
                        8, 1 + (i % 3), client_id=f"c{i}")
                return plane.serve_header(8, trusted_height=1,
                                          client_id=f"c{i}")

            results = await asyncio.gather(*[one(i) for i in range(64)])
            for i, res in enumerate(results):
                if i % 2:
                    assert res is None, f"client {i} rejected: {res!r}"
                else:
                    assert res["signed_header"]["header"]["height"] == "8"
                    assert res["canonical"] is True
        finally:
            plane.stop()

    asyncio.run(run())
    st = plane.status()
    co = st["coalescer"]
    assert co["requests"] == 32 and co["flushes"] >= 1
    assert co["verified_requests"] <= 6  # 3 distinct spans, maybe 2 flushes
    assert co["coalesced_dupes"] + co["verdict_cache_hits"] >= 26
    assert st["cache"]["hits"] >= 30  # 31 of 32 header asks hit memory
    assert st["served"]["prefetched"] > 0 and st["cache"]["pinned"] > 0
    assert st["served"]["headers_served"] == 32
    assert st["served"]["verifies_served"] == 32


def test_plane_verify_rejections_and_admission():
    keys = _keys(0x70, 4)
    blocks = _mk_chain([keys], 6)

    async def run():
        plane = _mk_plane(blocks)
        try:
            # spec rejections surface as the scalar exception instance
            err = await plane.serve_verify(5, 1)
            assert err is None
            with pytest.raises(KeyError):  # malformed span
                await plane.serve_verify(1, 5)
        finally:
            plane.stop()

        # admission: a hammering client is shed with labeled reasons and
        # banned by abuse scoring; a polite client keeps being served
        plane2 = _mk_plane(blocks, per_client_rate=0.001,
                           per_client_burst=2, abuse_ban_threshold=3)
        try:
            reasons = []
            for _ in range(8):
                try:
                    plane2.serve_header(2, client_id="abuser")
                except ShedError as e:
                    reasons.append(e.reason)
            assert reasons.count("client-rate") == 3
            assert reasons.count("banned") == 3
            doc = plane2.serve_header(2, client_id="polite")
            assert doc["signed_header"]["header"]["height"] == "2"
            assert plane2.limiter.stats["rate_sheds"] == 3
            assert plane2.limiter.stats["ban_sheds"] == 3
        finally:
            plane2.stop()

    asyncio.run(run())


# -- ws fan-out: frame parity + slow-consumer eviction -----------------------

def test_ws_frame_byte_parity():
    aiohttp = pytest.importorskip("aiohttp")  # noqa: F841
    from tendermint_tpu.rpc.server import _render_ws_frame, _rpc_response

    for id_, query, data, events in [
        (1, "tm.event = 'NewBlock'", {"height": "5"}, {"tx.hash": ["ab"]}),
        ("sub-2", "tm.event = 'Tx'", {"k": [1, 2, {"n": None}]}, {}),
        (None, "q with \"quotes\" and \\u00e9", {"s": "v\n"}, {"e": []}),
    ]:
        frag = json.dumps({"data": data, "events": events})
        assert _render_ws_frame(id_, query, frag) == json.dumps(
            _rpc_response(id_, result={"query": query, "data": data,
                                       "events": events}))


def test_ws_fanout_evicts_never_reading_socket():
    pytest.importorskip("aiohttp")
    from tendermint_tpu.rpc.server import _WsFanout

    class NeverReadingWS:
        def __init__(self):
            self.closed_with = None
            self.sent = 0
            self._stall = asyncio.Event()

        async def send_str(self, text):
            await self._stall.wait()  # a consumer that never drains

        async def close(self, code=None, message=b""):
            self.closed_with = (code, message)

    async def run():
        ws = NeverReadingWS()
        evictions = [0]
        fan = _WsFanout(ws, maxsize=4,
                        on_evict=lambda: evictions.__setitem__(
                            0, evictions[0] + 1))
        ok = [fan.enqueue(f"frame-{i}") for i in range(6)]
        assert ok == [True] * 4 + [False, False]
        assert fan.evicted and evictions[0] == 1
        assert not fan.enqueue("late")  # dropped, no second eviction
        assert evictions[0] == 1
        for _ in range(10):
            if ws.closed_with is not None:
                break
            await asyncio.sleep(0.01)
        from aiohttp import WSCloseCode
        assert ws.closed_with == (WSCloseCode.TRY_AGAIN_LATER,
                                  b"slow consumer")
        fan.stop()

    asyncio.run(run())
