"""Apply-plane pipeline: ordering, failure isolation, lookahead
invalidation, and per-window store write-batching
(blockchain/reactor.py stage A/B pipeline; ISSUE 2 tentpole)."""

import asyncio
import time

import pytest

from tendermint_tpu import crypto
from tendermint_tpu.abci.example.kvstore import KVStoreApplication
from tendermint_tpu.blockchain import BlockchainReactor, BlockPool
from tendermint_tpu.blockchain.reactor import FatalSyncError, VERIFY_WINDOW
from tendermint_tpu.libs.db import MemDB
from tendermint_tpu.proxy import AppConns, local_client_creator
from tendermint_tpu.state import BlockExecutor, StateStore, state_from_genesis
from tendermint_tpu.state.execution import EmptyEvidencePool, NoOpMempool
from tendermint_tpu.store import BlockStore
from tendermint_tpu.types import (
    BlockID,
    GenesisDoc,
    GenesisValidator,
    MockPV,
    SignedMsgType,
    Vote,
    VoteSet,
)
from tendermint_tpu.types.block import Commit

CHAIN_ID = "pipe-chain"


@pytest.fixture(scope="module")
def chain():
    """A 41-block committed chain (40 appliable windows' worth + the commit
    carrier) plus its genesis, built once per module."""
    pv = MockPV(crypto.Ed25519PrivKey.generate(b"\x31" * 32))
    genesis = GenesisDoc(
        chain_id=CHAIN_ID, genesis_time_ns=1_700_000_000_000_000_000,
        validators=[GenesisValidator(pv.get_pub_key(), 10)])
    state = state_from_genesis(genesis)
    app = KVStoreApplication()
    conns = AppConns(local_client_creator(app))
    conns.start()
    state_store = StateStore(MemDB())
    state_store.save(state)
    block_store = BlockStore(MemDB())
    executor = BlockExecutor(state_store, conns.consensus, NoOpMempool(),
                             EmptyEvidencePool(), block_store)
    blocks = []
    last_commit = Commit(0, 0, BlockID(), [])
    for h in range(1, 42):
        proposer = state.validators.get_proposer().address
        block, parts = state.make_block(h, [f"h{h}=v".encode()], last_commit,
                                        [], proposer)
        bid = BlockID(block.hash(), parts.header())
        vs = VoteSet(state.chain_id, h, 0, SignedMsgType.PRECOMMIT,
                     state.validators)
        v = Vote(SignedMsgType.PRECOMMIT, h, 0, bid, block.header.time_ns + 1,
                 state.validators.validators[0].address, 0)
        pv.sign_vote(state.chain_id, v)
        vs.add_vote(v)
        blocks.append(block)
        state, _ = executor.apply_block(state, bid, block)
        last_commit = vs.make_commit()
    conns.stop()
    yield genesis, blocks


def _fresh_reactor(genesis):
    app = KVStoreApplication()
    conns = AppConns(local_client_creator(app))
    conns.start()
    state = state_from_genesis(genesis)
    state_store = StateStore(MemDB())
    state_store.save(state)
    block_store = BlockStore(MemDB())
    executor = BlockExecutor(state_store, conns.consensus, NoOpMempool(),
                             EmptyEvidencePool(), block_store)
    reactor = BlockchainReactor(state, executor, block_store, fast_sync=True)
    reactor.pool = BlockPool(1)
    return reactor, conns


def _fill_pool(reactor, blocks, upto):
    reactor.pool.set_peer_range("src", 1, upto)
    filled = True
    while filled:
        reqs = reactor.pool.schedule_requests()
        filled = bool(reqs)
        for pid, h in reqs:
            reactor.pool.add_block(pid, blocks[h - 1])


def test_pipeline_ordering_and_no_early_commit(chain, monkeypatch):
    """Window N+1's stage A runs while window N applies, but commits
    nothing: store and state advance only through the strictly-ordered
    apply stage."""
    monkeypatch.setenv("TMTPU_BATCH_BACKEND", "host")
    genesis, blocks = chain
    reactor, conns = _fresh_reactor(genesis)
    events = []  # (kind, height, t)

    real_stage_a = reactor._stage_a

    def spy_stage_a(window, pairs, *a, **kw):
        start_h = pairs[0][0].header.height
        events.append(("prepare_start", start_h, time.perf_counter()))
        assert reactor.store.height() < start_h
        out = real_stage_a(window, pairs, *a, **kw)
        # the prepared window must not have committed anything: while its
        # stage A runs, only the PREVIOUS window may be applying, so the
        # store never reaches this window's heights before apply consumes
        # the prepared verdicts
        assert reactor.store.height() < start_h
        events.append(("prepare_end", start_h, time.perf_counter()))
        return out

    real_apply = reactor.block_exec.apply_block

    def spy_apply(state, block_id, block):
        events.append(("apply", block.header.height, time.perf_counter()))
        return real_apply(state, block_id, block)

    monkeypatch.setattr(reactor, "_stage_a", spy_stage_a)
    monkeypatch.setattr(reactor.block_exec, "apply_block", spy_apply)

    async def drive():
        _fill_pool(reactor, blocks, 41)
        while reactor.blocks_synced < 40:
            before = reactor.blocks_synced
            await reactor._process_window()
            assert reactor.blocks_synced > before
    asyncio.run(drive())
    conns.stop()

    applies = [(h, t) for k, h, t in events if k == "apply"]
    assert [h for h, _t in applies] == list(range(1, 41)), \
        "apply order must be strictly sequential"
    st = reactor.stage_breakdown()
    assert st["pipelined_windows"] >= 1, "lookahead never engaged"
    # the metric set the breakdown derives from carries per-stage series
    m = reactor.metrics
    assert m.stage_seconds.count_value("hash") >= 1
    assert m.stage_seconds.count_value("verify") >= 1
    assert m.stage_seconds.count_value("exec") >= 40  # one per block
    assert m.stage_seconds.count_value("store") >= 40
    assert st["abci_s"] > 0 and st["hash_s"] > 0
    # window 2's prepare started before window 1 finished applying
    prep2_start = next(t for k, h, t in events
                       if k == "prepare_start" and h == VERIFY_WINDOW + 1)
    last_apply_w1 = next(t for h, t in applies if h == VERIFY_WINDOW)
    assert prep2_start < last_apply_w1, \
        "window N+1 prepare did not overlap window N apply"
    # and its verdicts were consumed only after window 1 fully applied
    prep2_end = next(t for k, h, t in events
                     if k == "prepare_end" and h == VERIFY_WINDOW + 1)
    first_apply_w2 = next(t for h, t in applies if h == VERIFY_WINDOW + 1)
    assert first_apply_w2 > prep2_end


def test_failed_window_aborts_lookahead(chain, monkeypatch):
    """A deterministic apply fault in window N surfaces as FatalSyncError,
    persists exactly the blocks applied before it, and discards window
    N+1's prepared results."""
    monkeypatch.setenv("TMTPU_BATCH_BACKEND", "host")
    genesis, blocks = chain
    reactor, conns = _fresh_reactor(genesis)
    real_apply = reactor.block_exec.apply_block
    boom_at = VERIFY_WINDOW + 4  # mid window 2

    def failing_apply(state, block_id, block):
        if block.header.height == boom_at:
            raise RuntimeError("app corrupted")
        return real_apply(state, block_id, block)

    monkeypatch.setattr(reactor.block_exec, "apply_block", failing_apply)

    async def drive():
        _fill_pool(reactor, blocks, 41)
        await reactor._process_window()          # window 1 ok, prepares 2
        assert reactor.blocks_synced == VERIFY_WINDOW
        with pytest.raises(FatalSyncError):
            await reactor._process_window()      # window 2 hits the fault
    asyncio.run(drive())

    assert reactor.blocks_synced == boom_at - 1
    # the window's writes up to (and including, store-ahead-by-one: save
    # precedes apply, as in the unpipelined loop) the faulting block were
    # flushed; state stops at the last applied height and nothing PAST the
    # fault ever landed — handshake replay reconciles the one-block gap
    assert reactor.store.height() == boom_at
    assert reactor.store.load_block(boom_at - 1) is not None
    assert reactor.store.load_block(boom_at + 1) is None
    assert reactor.block_exec.state_store.load().last_block_height \
        == boom_at - 1
    # the lookahead slot did not outlive the fault
    assert reactor._prepared is None
    conns.stop()


def test_stale_lookahead_discarded_after_redo(chain, monkeypatch):
    """pool.redo between prepare and consume (bad peer mid-sync) must
    invalidate the prepared window instead of applying stale blocks."""
    monkeypatch.setenv("TMTPU_BATCH_BACKEND", "host")
    genesis, blocks = chain
    reactor, conns = _fresh_reactor(genesis)

    async def drive():
        _fill_pool(reactor, blocks, 41)
        await reactor._process_window()  # applies window 1, prepares window 2
        assert reactor.blocks_synced == VERIFY_WINDOW
        assert reactor._prepared is not None
        # the provider turns out bad: every outstanding block is dropped
        reactor.pool.redo(reactor.pool.height)
        await reactor._process_window()  # must not apply the stale window
        assert reactor.blocks_synced == VERIFY_WINDOW
        assert reactor._prepared is None
        assert reactor.metrics.stale_window_discards_total.value() >= 1
        # re-downloaded blocks (same content, new objects) resync cleanly
        _fill_pool(reactor, blocks, 41)
        while reactor.blocks_synced < 40:
            await reactor._process_window()
    asyncio.run(drive())
    assert reactor.store.height() == 40
    conns.stop()


def test_window_batch_one_write_batch_per_window(chain, monkeypatch):
    """All store writes of a window land in one DB write-batch per store,
    and reads inside the scope observe the staged writes."""
    monkeypatch.setenv("TMTPU_BATCH_BACKEND", "host")
    genesis, blocks = chain

    class CountingDB(MemDB):
        def __init__(self):
            super().__init__()
            self.batches = 0
            self.singles = 0

        def set(self, key, value):
            self.singles += 1
            super().set(key, value)

        def write_batch(self, sets, deletes=None):
            self.batches += 1
            with self._lock:
                for k, v in sets:
                    super(CountingDB, self).set(k, v)
                for k in deletes or []:
                    super().delete(k)

    app = KVStoreApplication()
    conns = AppConns(local_client_creator(app))
    conns.start()
    state = state_from_genesis(genesis)
    sdb, bdb = CountingDB(), CountingDB()
    state_store = StateStore(sdb)
    state_store.save(state)
    block_store = BlockStore(bdb)
    executor = BlockExecutor(state_store, conns.consensus, NoOpMempool(),
                             EmptyEvidencePool(), block_store)
    reactor = BlockchainReactor(state, executor, block_store, fast_sync=True)
    reactor.pool = BlockPool(1)

    async def drive():
        _fill_pool(reactor, blocks, 18)  # exactly one window + carrier
        sdb.singles = sdb.batches = bdb.singles = bdb.batches = 0
        await reactor._process_window()
    asyncio.run(drive())

    assert reactor.blocks_synced == VERIFY_WINDOW
    # one flush per store for the whole window, nothing written singly
    assert bdb.batches == 1 and bdb.singles == 0
    assert sdb.batches == 1 and sdb.singles == 0
    # and the flushed data is complete: a fresh store view loads every block
    fresh = BlockStore(bdb)
    for h in range(1, VERIFY_WINDOW + 1):
        assert fresh.load_block(h) is not None
    conns.stop()


def test_fast_sync_telemetry_series_and_spans(chain, monkeypatch):
    """ISSUE 3 acceptance shape: after a windowed fast sync the shared
    registry exposes non-zero tendermint_crypto_* and tendermint_blocksync_*
    series, and the span tracer captured the pipeline's spans."""
    monkeypatch.setenv("TMTPU_BATCH_BACKEND", "host")
    from tendermint_tpu.crypto import batch as crypto_batch
    from tendermint_tpu.libs.metrics import NodeMetrics
    from tendermint_tpu.libs.trace import tracer

    genesis, blocks = chain
    reactor, conns = _fresh_reactor(genesis)
    nm = NodeMetrics("tendermint")
    # the node's wiring, replicated: module hook + reactor metric set
    monkeypatch.setattr(crypto_batch, "metrics", nm.crypto)
    reactor.metrics = nm.blocksync
    tracer.clear()
    tracer.enable()
    try:
        async def drive():
            _fill_pool(reactor, blocks, 41)
            while reactor.blocks_synced < 40:  # >= 2 windows
                await reactor._process_window()
        asyncio.run(drive())
    finally:
        tracer.disable()
    assert reactor.blocks_synced == 40

    text = nm.registry.render()
    scalar_light = nm.crypto.routing_decisions_total.value("scalar", "light")
    assert scalar_light >= 2, text  # one batched light verify per window
    assert nm.crypto.batch_size.count_value("scalar", "light") >= 2
    assert nm.crypto.verify_latency_seconds.sum_value("scalar", "light") > 0
    assert nm.blocksync.stage_seconds.count_value("exec") == 40
    assert int(nm.blocksync.pipelined_windows_total.value()) + \
        int(nm.blocksync.inline_windows_total.value()) >= 2
    assert ('tendermint_crypto_routing_decisions_total'
            '{plane="light",route="scalar"}') in text
    assert 'tendermint_blocksync_stage_seconds_count{stage="exec"} 40' in text

    names = {e["name"] for e in tracer.events()}
    assert {"verify_window", "apply_window", "apply_block",
            "batch_verify"} <= names, names
