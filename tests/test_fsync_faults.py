"""fsyncgate semantics under injected storage faults: a failed WAL fsync
is fatal (exit by default, FsyncError for in-process harnesses) because a
record whose fsync failed must NEVER be treated as durable; a failed DB
write-batch applies nothing and keeps the staged window intact. The crash
matrix re-runs the consensus machine with an injected fsync failure at
EVERY sync boundary and proves restart always replays to a consistent
height — no record handled-but-not-durable.
"""

import asyncio
import os
import subprocess
import sys

import pytest

from tendermint_tpu.consensus.replay import catchup_replay
from tendermint_tpu.consensus.wal import FSYNC_EXIT_CODE, WAL, FsyncError
from tendermint_tpu.libs.db import BufferedDB, MemDB, SQLiteDB
from tendermint_tpu.libs.faults import faults
from tendermint_tpu.libs.metrics import ConsensusMetrics, Registry
from tendermint_tpu.privval.file_pv import FilePV

from test_crash_recovery import TARGET_HEIGHT, _boot

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def raise_policy(monkeypatch):
    """In-process harnesses can't take os._exit; surface FsyncError."""
    monkeypatch.setattr(WAL, "fsync_error_policy", "raise")


# -- WAL ---------------------------------------------------------------------

def test_wal_fsync_fault_raises_and_counts(tmp_path, raise_policy):
    wal = WAL(str(tmp_path / "cs.wal"))
    m = ConsensusMetrics(Registry())
    wal.metrics = m
    faults.configure("wal.fsync*1")
    with pytest.raises(FsyncError) as ei:
        wal.write_sync("round_step", {"height": 1})
    # BaseException on purpose: a defensive `except Exception` anywhere in
    # the consensus loop must NOT be able to swallow it and carry on
    assert not isinstance(ei.value, Exception)
    assert m.wal_fsync_errors_total.value() == 1.0
    # the site is exhausted: the WAL keeps working after a restart-style
    # reopen (the failed record's bytes were appended+flushed, so replay
    # decides its fate from the file, not from in-memory state)
    wal.close()
    wal2 = WAL(str(tmp_path / "cs.wal"))
    wal2.write_sync("round_step", {"height": 2})
    wal2.close()


def test_wal_fsync_fault_exits_process_by_default(tmp_path):
    """Default policy: the process dies with the sysexits EX_IOERR code —
    the subprocess-node analog of the reference's panic, and what the e2e
    runner's fault manifests produce."""
    code = (
        "from tendermint_tpu.consensus.wal import WAL\n"
        f"wal = WAL({str(tmp_path / 'sub.wal')!r})\n"
        "print('UNREACHABLE')\n"
    )
    env = dict(os.environ, TMTPU_FAULTS="wal.fsync*1",
               PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""))
    r = subprocess.run([sys.executable, "-c", code], env=env,
                       capture_output=True, text=True, timeout=120)
    assert r.returncode == FSYNC_EXIT_CODE, (r.returncode, r.stderr)
    assert "UNREACHABLE" not in r.stdout


def test_group_commit_fsync_fault_keeps_batch_replayable(tmp_path,
                                                         raise_policy):
    """A group whose commit-fsync fails: every record of the batch was
    appended and flushed BEFORE the fsync, so a restart replays the whole
    batch from the file — the crash loses durability, never framing."""
    path = str(tmp_path / "grp.wal")
    wal = WAL(path)
    # armed after the constructor's sync, so the group-exit fsync is the
    # site's first evaluation
    faults.configure("wal.fsync*1")
    with pytest.raises(FsyncError):
        with wal.group():
            for h in (1, 2, 3):
                wal.write_sync("round_step", {"height": h})
    wal.close()
    replayed = [m.data["height"] for m in WAL(path).iter_messages()
                if m.type == "round_step"]
    assert replayed == [1, 2, 3]
    # a torn tail on top: truncate into the last record — replay stops
    # cleanly at the previous boundary instead of erroring
    faults.reset()
    with open(path, "rb") as f:
        raw = f.read()
    with open(path, "wb") as f:
        f.write(raw[:-3])
    torn = [m.data["height"] for m in WAL(path).iter_messages()
            if m.type == "round_step"]
    assert torn == [1, 2]


def test_crash_at_every_fsync_boundary(tmp_path, raise_policy):
    """The acceptance matrix: inject an fsync failure at the K-th sync
    boundary (group commits included) for every K until the chain outruns
    the crash point; restart from the same storage each time. Heights
    never regress and the chain reaches the target — proving no record
    was ever handled on the strength of a failed fsync."""
    FilePV.generate(str(tmp_path / "pv_key.json"),
                    str(tmp_path / "pv_state.json")).save()

    async def run():
        wal_path = str(tmp_path / "cs.wal")
        boundary = 0
        last_height = 0
        crashes = 0
        while True:
            faults.configure(f"wal.fsync*1+{boundary}")
            try:
                wal = WAL(wal_path)
            except FsyncError:
                # boundary 0 is the fresh WAL's own end_height-0 sync
                crashes += 1
                boundary += 1
                continue
            cs = _boot(tmp_path, wal)
            catchup_replay(cs, cs.rs.height)
            crash = {}
            orig = cs.receive_routine

            async def guarded():
                try:
                    await orig()
                except FsyncError as e:
                    crash["err"] = e

            cs.receive_routine = guarded
            await cs.start()
            try:
                for _ in range(600):
                    if crash:
                        status = "crashed"
                        break
                    if cs.state.last_block_height >= TARGET_HEIGHT:
                        status = "done"
                        break
                    await asyncio.sleep(0.02)
                else:
                    raise AssertionError(
                        f"no progress and no crash at boundary {boundary} "
                        f"(h={cs.state.last_block_height})")
            finally:
                faults.reset()  # stop() fsyncs; the armed site is spent anyway
                await cs.stop()
            height = cs.state.last_block_height
            assert height >= last_height, (
                f"height regressed after fsync crash {boundary}: "
                f"{height} < {last_height}")
            last_height = height
            if status == "done":
                break
            crashes += 1
            boundary += 1
            assert boundary < 400, "fsync crash matrix did not converge"
        assert crashes >= 3, f"only {crashes} fsync boundaries before target"
        assert last_height >= TARGET_HEIGHT

    asyncio.run(run())


# -- DB write batches --------------------------------------------------------

def test_buffered_flush_fault_preserves_staged_window(tmp_path):
    base = MemDB()
    buf = BufferedDB(base)
    buf.set(b"k1", b"v1")
    buf.set(b"k2", b"v2")
    buf.delete(b"gone")
    assert buf.pending() == 3
    faults.configure("db.write_batch*1")
    with pytest.raises(OSError):
        buf.flush()
    # handled-but-not-durable guard: nothing applied, nothing dropped
    assert base.get(b"k1") is None
    assert buf.pending() == 3
    assert buf.get(b"k1") == b"v1"  # read-through still serves the window
    # site exhausted: the retry commits the SAME window
    buf.flush()
    assert base.get(b"k1") == b"v1" and base.get(b"k2") == b"v2"
    assert buf.pending() == 0


def test_sqlite_write_batch_fault_is_all_or_nothing(tmp_path):
    db = SQLiteDB(str(tmp_path / "kv.db"))
    faults.configure("db.write_batch*1")
    with pytest.raises(OSError):
        db.write_batch([(b"a", b"1"), (b"b", b"2")])
    assert db.get(b"a") is None and db.get(b"b") is None
    db.write_batch([(b"a", b"1"), (b"b", b"2")])
    assert db.get(b"a") == b"1" and db.get(b"b") == b"2"
