"""State sync end-to-end: a fresh node bootstraps from another node's app
snapshot, verified through the light-client state provider, then catches up
via fast sync and serves the synced app state
(reference statesync/syncer.go:145, stateprovider.go:39, node/node.go:648).
"""

import asyncio
import os

import pytest

pytest.importorskip("cryptography", reason="needs the optional 'cryptography' package (absent in slim containers)")

from tendermint_tpu import crypto
from tendermint_tpu.abci.example.kvstore import SnapshotKVStoreApplication
from tendermint_tpu.config import test_config
from tendermint_tpu.node import Node
from tendermint_tpu.p2p import NodeKey
from tendermint_tpu.privval.file_pv import FilePV
from tendermint_tpu.types import GenesisDoc, GenesisValidator
from tendermint_tpu.types.params import BlockParams, ConsensusParams

CHAIN = "ss-chain"


def _mk(tmp_path, name, genesis, pv, seed, app, statesync_cfg=None,
        persistent_peers=""):
    home = str(tmp_path / name)
    cfg = test_config(home)
    cfg.base.chain_id = CHAIN
    cfg.p2p.laddr = "tcp://127.0.0.1:0"
    cfg.rpc.laddr = "tcp://127.0.0.1:0"
    cfg.p2p.persistent_peers = persistent_peers
    cfg.base.fast_sync = bool(persistent_peers)
    if statesync_cfg:
        for k, v in statesync_cfg.items():
            setattr(cfg.statesync, k, v)
    os.makedirs(os.path.join(home, "config"), exist_ok=True)
    os.makedirs(os.path.join(home, "data"), exist_ok=True)
    nk = NodeKey(crypto.Ed25519PrivKey.generate(seed))
    return Node(cfg, pv, nk, genesis, app=app)


def test_state_sync_bootstrap(tmp_path):
    async def run():
        pv = FilePV.generate("", "")
        # time_iota_ms=1: test blocks are faster than the default 1s iota,
        # which would march BFT time into the future and trip the light
        # client's clock-drift check (state.go:2204 voteTime semantics)
        genesis = GenesisDoc(chain_id=CHAIN,
                             genesis_time_ns=1_700_000_000_000_000_000,
                             validators=[GenesisValidator(pv.get_pub_key(), 10)],
                             consensus_params=ConsensusParams(
                                 block=BlockParams(time_iota_ms=1)))

        serve_app = SnapshotKVStoreApplication(interval=4)
        node_a = _mk(tmp_path, "a", genesis, pv, b"\xa1" * 32, serve_app)
        await node_a.start()
        try:
            from tendermint_tpu.rpc.client import HTTPClient

            a_rpc = f"http://127.0.0.1:{node_a.rpc_server.bound_port}"
            client = HTTPClient(a_rpc)
            # commit some txs and run past two snapshot heights (4, 8) + 2
            await client.broadcast_tx_commit(b"ska=va")
            await client.broadcast_tx_commit(b"skb=vb")
            for _ in range(600):
                st = await client.status()
                if int(st["sync_info"]["latest_block_height"]) >= 11:
                    break
                await asyncio.sleep(0.05)
            assert serve_app._snapshots, "server app produced no snapshots"

            # trust root: header hash at height 1 from the serving node
            cmt = await client.commit(1)
            trust_hash = cmt["signed_header"]["header"]["app_hash"]  # placeholder
            # the light client wants the header HASH; recompute from provider
            from tendermint_tpu.light.provider import HTTPProvider

            lb1 = await HTTPProvider(CHAIN, client).light_block(1)
            trust_hash = lb1.signed_header.header.hash().hex()

            pv_b = FilePV.generate("", "")
            fresh_app = SnapshotKVStoreApplication(interval=4)
            node_b = _mk(
                tmp_path, "b", genesis, pv_b, b"\xb2" * 32, fresh_app,
                statesync_cfg={
                    "enable": True,
                    "rpc_servers": [a_rpc, a_rpc],
                    "trust_height": 1,
                    "trust_hash": trust_hash,
                    "trust_period": 10 * 365 * 24 * 3600.0,
                    "discovery_time": 0.5,
                },
                persistent_peers=f"{node_a.node_key.id}@127.0.0.1:"
                                 f"{node_a.listen_addr.port}")
            await node_b.start()
            try:
                # B must restore a snapshot (app height jumps to >= 4 without
                # replaying blocks 1..h) and then fast-sync to the tip
                for _ in range(600):
                    if node_b.fatal_event.is_set():
                        raise AssertionError(f"fatal: {node_b.fatal_error}")
                    if (node_b.blockchain_reactor.synced.is_set()
                            and node_b.consensus_state.state.last_block_height >= 11):
                        break
                    await asyncio.sleep(0.05)
                assert node_b.consensus_state.state.last_block_height >= 11, \
                    node_b.consensus_state.state.last_block_height
                # the synced app has the kv state without ever seeing the txs
                assert fresh_app.state.get("ska") == "va"
                assert fresh_app.state.get("skb") == "vb"
                # and the block store never saw the pre-snapshot blocks
                assert node_b.block_store.load_block(1) is None
                assert node_b.block_store.height() >= 11
            finally:
                await node_b.stop()
            await client.close()
        finally:
            await node_a.stop()

    asyncio.run(run())


def test_state_sync_falls_back_to_fast_sync_when_no_snapshots(tmp_path):
    """ErrNoSnapshots is survivable: a fresh node whose statesync finds no
    viable snapshot (the serving app never produced one) must NOT set
    fatal_error — it logs, counts the fallback, and fast-syncs the chain
    from genesis instead (ISSUE 8 acceptance)."""
    async def run():
        pv = FilePV.generate("", "")
        genesis = GenesisDoc(chain_id=CHAIN,
                             genesis_time_ns=1_700_000_000_000_000_000,
                             validators=[GenesisValidator(pv.get_pub_key(), 10)],
                             consensus_params=ConsensusParams(
                                 block=BlockParams(time_iota_ms=1)))

        # interval=0: the serving app NEVER snapshots, so discovery is
        # guaranteed to come up empty no matter how long B asks
        serve_app = SnapshotKVStoreApplication(interval=0)
        node_a = _mk(tmp_path, "a", genesis, pv, b"\xa7" * 32, serve_app)
        await node_a.start()
        try:
            from tendermint_tpu.rpc.client import HTTPClient

            a_rpc = f"http://127.0.0.1:{node_a.rpc_server.bound_port}"
            client = HTTPClient(a_rpc)
            await client.broadcast_tx_commit(b"fka=va")
            for _ in range(600):
                st = await client.status()
                if int(st["sync_info"]["latest_block_height"]) >= 5:
                    break
                await asyncio.sleep(0.05)

            from tendermint_tpu.light.provider import HTTPProvider

            lb1 = await HTTPProvider(CHAIN, client).light_block(1)
            trust_hash = lb1.signed_header.header.hash().hex()

            pv_b = FilePV.generate("", "")
            fresh_app = SnapshotKVStoreApplication(interval=0)
            node_b = _mk(
                tmp_path, "b", genesis, pv_b, b"\xb8" * 32, fresh_app,
                statesync_cfg={
                    "enable": True,
                    "rpc_servers": [a_rpc, a_rpc],
                    "trust_height": 1,
                    "trust_hash": trust_hash,
                    "trust_period": 10 * 365 * 24 * 3600.0,
                    "discovery_time": 0.2,
                    "discovery_attempts": 2,
                },
                persistent_peers=f"{node_a.node_key.id}@127.0.0.1:"
                                 f"{node_a.listen_addr.port}")
            await node_b.start()
            try:
                for _ in range(600):
                    assert not node_b.fatal_event.is_set(), \
                        f"fallback must not be fatal: {node_b.fatal_error}"
                    if (node_b.blockchain_reactor.synced.is_set()
                            and node_b.consensus_state.state.last_block_height >= 5):
                        break
                    await asyncio.sleep(0.05)
                assert not node_b.fatal_event.is_set(), node_b.fatal_error
                assert node_b.consensus_state.state.last_block_height >= 5
                # it REPLAYED the chain (fast sync from genesis): block 1 is
                # in the store, unlike a snapshot bootstrap
                assert node_b.block_store.load_block(1) is not None
                assert fresh_app.state.get("fka") == "va"
                assert node_b.metrics.statesync.fallbacks_total.value() == 1
            finally:
                await node_b.stop()
            await client.close()
        finally:
            await node_a.stop()

    asyncio.run(run())
