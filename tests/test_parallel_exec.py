"""Optimistic parallel block execution (state/parallel.py): byte-parity vs
the serial spec, conflict-closure correctness, fallback gating, the response/
event ordering contract, and crash recovery mid-parallel-apply.

Every parity test runs the SAME block through two twin rigs — one with
``execution.version = "v0"`` (serial spec) and one with ``"v1"`` (parallel) —
and asserts the persisted ABCIResponses JSON, app hash, last_results_hash,
and final app state are byte-identical.
"""

import threading
import time

import pytest

from tendermint_tpu import crypto
from tendermint_tpu.abci import types as abci
from tendermint_tpu.abci.application import Application
from tendermint_tpu.abci.example.kvstore import (KVStoreApplication,
                                                 MerkleKVStoreApplication)
from tendermint_tpu.config import ExecutionConfig
from tendermint_tpu.libs import fail
from tendermint_tpu.libs.db import MemDB
from tendermint_tpu.libs.faults import faults
from tendermint_tpu.mempool.ingest import conflict_hint, make_signed_tx
from tendermint_tpu.proxy import AppConns, local_client_creator
from tendermint_tpu.state import BlockExecutor, StateStore, state_from_genesis
from tendermint_tpu.state.execution import EmptyEvidencePool, NoOpMempool
from tendermint_tpu.state.parallel import (ParallelExecutor, SpecView, TxLog,
                                           conflict_closure, conflict_groups)
from tendermint_tpu.store import BlockStore
from tendermint_tpu.types import (BlockID, GenesisDoc, GenesisValidator,
                                  MockPV, SignedMsgType, Vote, VoteSet)
from tendermint_tpu.types import events as tme
from tendermint_tpu.types.block import Commit

CHAIN_ID = "parallel-test"

SENDERS = [crypto.Ed25519PrivKey.generate(bytes([i]) * 32) for i in range(1, 9)]
VAL_KEYS = [crypto.Ed25519PrivKey.generate(bytes([100 + i]) * 32)
            for i in range(6)]


def _rig(version, app_cls=MerkleKVStoreApplication, workers=4,
         min_parallel_txs=2):
    pv = MockPV(crypto.Ed25519PrivKey.generate(b"\x11" * 32))
    genesis = GenesisDoc(chain_id=CHAIN_ID,
                         genesis_time_ns=1_700_000_000_000_000_000,
                         validators=[GenesisValidator(pv.get_pub_key(), 10)])
    state = state_from_genesis(genesis)
    app = app_cls()
    conns = AppConns(local_client_creator(app))
    conns.start()
    ss = StateStore(MemDB())
    ss.save(state)
    ex = BlockExecutor(ss, conns.consensus, NoOpMempool(),
                       EmptyEvidencePool(), BlockStore(MemDB()),
                       exec_config=ExecutionConfig(
                           version=version, workers=workers,
                           min_parallel_txs=min_parallel_txs))
    return pv, state, ex, ss, app


def _apply_one(version, txs, app_cls=MerkleKVStoreApplication):
    """Apply one block of `txs` at height 1; return the parity tuple."""
    pv, state, ex, ss, app = _rig(version, app_cls)
    proposer = state.validators.get_proposer().address
    block, parts = state.make_block(1, txs, Commit(0, 0, BlockID(), []),
                                    [], proposer)
    bid = BlockID(block.hash(), parts.header())
    state, _ = ex.apply_block(state, bid, block)
    return (ss.load_abci_responses(1).to_json(), state.app_hash,
            state.last_results_hash, dict(app.state), app.tx_count,
            dict(app.validators)), ex


def assert_parity(txs, app_cls=MerkleKVStoreApplication):
    serial, _ = _apply_one("v0", txs, app_cls)
    parallel, ex = _apply_one("v1", txs, app_cls)
    assert serial == parallel
    return ex._parallel


# -- differential suite ------------------------------------------------------


def test_parity_disjoint_senders():
    txs = [make_signed_tx(SENDERS[i % 8], f"s{i}=v{i}".encode(), nonce=i)
           for i in range(32)]
    p = assert_parity(txs)
    assert p.last_groups == 8
    assert p.last_conflicted == 0


def test_parity_same_key_conflict_storm():
    # every tx writes the same key: one giant group, strictly serial order
    txs = [f"hot=v{i}".encode() for i in range(40)]
    p = assert_parity(txs)
    assert p.last_groups == 1


def test_parity_val_txs_crossing_groups():
    # validator updates interleaved with kv writes; each val pubkey distinct
    # (duplicate addresses in one block are rejected by update validation)
    txs = []
    for i, vk in enumerate(VAL_KEYS[:4]):
        txs.append(f"val:{vk.pub_key().bytes().hex()}!{i + 1}".encode())
        txs.append(f"k{i}=x".encode())
        txs.append(make_signed_tx(SENDERS[i], f"w{i}=y".encode(), nonce=i))
    assert_parity(txs)


def test_parity_unparseable_barrier_groups():
    txs = [b"a=1", bytes([0xff, 0xfe, 1]), b"b=2", b"val:zznothex!5",
           b"c=3", bytes(6), b"noequals", b"d=4"]
    assert_parity(txs)


def test_parity_mixed_seeded_workload():
    import random
    rng = random.Random(3)
    vals = iter(VAL_KEYS)
    txs = []
    for i in range(50):
        r = rng.random()
        if r < 0.4:
            sk = SENDERS[rng.randrange(8)]
            txs.append(make_signed_tx(sk, f"s{i}=v{rng.random()}".encode(),
                                      nonce=i))
        elif r < 0.7:
            txs.append(f"shared{rng.randrange(5)}=x{i}".encode())
        elif r < 0.76:
            try:
                pk = next(vals).pub_key()
                txs.append(f"val:{pk.bytes().hex()}!{rng.randrange(1, 20)}"
                           .encode())
            except StopIteration:
                txs.append(f"v{i}=z".encode())
        elif r < 0.9:
            txs.append(bytes([rng.randrange(256) for _ in range(12)]))
        else:
            txs.append(b"val:zznothex!5")
    rng.shuffle(txs)
    assert_parity(txs)


def test_parity_exec_conflict_fault_forces_reexec():
    """exec.conflict mis-assigns txs to chaos lanes; validation + serial
    re-exec must still land on the exact serial bytes."""
    txs = [f"val:{VAL_KEYS[0].pub_key().bytes().hex()}!7".encode(), b"q=1",
           make_signed_tx(SENDERS[0], b"w=2", nonce=0),
           b"val:zznothex!5", bytes([250, 251, 1]),
           f"val:{VAL_KEYS[1].pub_key().bytes().hex()}!9".encode(), b"q=2"]
    serial, _ = _apply_one("v0", txs)
    faults.configure("exec.conflict", seed=5)
    try:
        parallel, ex = _apply_one("v1", txs)
    finally:
        faults.reset()
    assert serial == parallel
    assert ex._parallel.last_conflicted > 0  # the fault actually bit


def test_parity_plain_kvstore_app():
    # the non-merkle kvstore takes the same speculation protocol
    txs = [f"k{i % 5}=v{i}".encode() for i in range(20)]
    assert_parity(txs, app_cls=KVStoreApplication)


def test_parity_multi_height():
    """3 heights through both rigs; app hash chains forward identically."""
    outs = {}
    for version in ("v0", "v1"):
        pv, state, ex, ss, app = _rig(version)
        last_commit = Commit(0, 0, BlockID(), [])
        for h in range(1, 4):
            proposer = state.validators.get_proposer().address
            txs = ([f"h{h}k{i % 3}=v{i}".encode() for i in range(8)]
                   + [make_signed_tx(SENDERS[i], f"sh{h}={i}".encode(),
                                     nonce=h * 10 + i) for i in range(4)])
            block, parts = state.make_block(h, txs, last_commit, [], proposer)
            bid = BlockID(block.hash(), parts.header())
            state, _ = ex.apply_block(state, bid, block)
            vs = VoteSet(state.chain_id, h, 0, SignedMsgType.PRECOMMIT,
                         state.validators)
            v = Vote(SignedMsgType.PRECOMMIT, h, 0, bid,
                     block.header.time_ns + 1,
                     state.validators.validators[0].address, 0)
            pv.sign_vote(state.chain_id, v)
            vs.add_vote(v)
            last_commit = vs.make_commit()
        outs[version] = (state.app_hash, state.last_results_hash,
                         dict(app.state), app.tx_count,
                         [ss.load_abci_responses(h).to_json()
                          for h in range(1, 4)])
    assert outs["v0"] == outs["v1"]


# -- conflict machinery units ------------------------------------------------


def test_conflict_hint_classes():
    sk = SENDERS[0]
    assert conflict_hint(make_signed_tx(sk, b"a=1", nonce=0)) == \
        ("sender", sk.pub_key().bytes().hex())
    assert conflict_hint(b"a=1") == ("key", "a")
    assert conflict_hint(b"noequals") == ("key", "noequals")
    assert conflict_hint(bytes([0xff, 0xfe])) == ("barrier", "")
    assert conflict_hint(b"val:aa!1") == ("barrier", "")


def test_conflict_groups_preserve_block_order():
    txs = [b"a=1", b"b=1", b"a=2", b"c=1", b"b=2"]
    assert conflict_groups(txs) == [[0, 2], [1, 4], [3]]


def _log(idx, keys):
    log = TxLog(idx)
    log.keys = set(keys)
    return log


def test_conflict_closure_fixpoint():
    # key a is cross-group -> every a-toucher conflicts; their OTHER keys
    # (b via tx 2, c via tx 3) join the closure and drag tx 1 in too;
    # group 2's private key d stays clean
    logs = [_log(0, {("kv", "a")}),
            _log(1, {("kv", "b")}),
            _log(2, {("kv", "a"), ("kv", "b")}),
            _log(3, {("kv", "a"), ("kv", "c")}),
            _log(4, {("kv", "d")})]
    group_of = {0: 0, 1: 1, 2: 1, 3: 0, 4: 2}
    ct, ck = conflict_closure(logs, group_of)
    assert ct == {0, 1, 2, 3}
    assert {("kv", "a"), ("kv", "b"), ("kv", "c")} <= ck
    assert 4 not in ct and ("kv", "d") not in ck


def test_spec_view_read_through_and_overlay():
    class FakeApp:
        def spec_read(self, space, key):
            return "base" if (space, key) == ("kv", "a") else None

    view = SpecView(FakeApp())
    view.begin_tx(0)
    assert view.read("kv", "a") == "base"
    view.write("kv", "a", "new")
    assert view.read("kv", "a") == "new"
    assert ("kv", "a") in view.logs[0].keys
    assert ("set", "kv", "a", "new", None) in view.logs[0].ops


# -- fallback gating ---------------------------------------------------------


def test_small_block_falls_back_to_serial():
    pv, state, ex, ss, app = _rig("v1", min_parallel_txs=10)
    proposer = state.validators.get_proposer().address
    block, parts = state.make_block(1, [b"a=1", b"b=2"],
                                    Commit(0, 0, BlockID(), []), [], proposer)
    bid = BlockID(block.hash(), parts.header())
    state, _ = ex.apply_block(state, bid, block)
    assert app.state == {"a": "1", "b": "2"}
    assert ex._parallel.last_groups == 0  # never speculated


def test_unsupported_app_falls_back():
    class NoSpecApp(Application):
        """parallel_exec_supported stays False."""

        def __init__(self):
            self.seen = []

        def deliver_tx(self, req):
            self.seen.append(req.tx)
            return abci.ResponseDeliverTx(code=0)

    pv, state, ex, ss, app = _rig("v1", app_cls=NoSpecApp)
    proposer = state.validators.get_proposer().address
    txs = [f"t{i}".encode() for i in range(8)]
    block, parts = state.make_block(1, txs, Commit(0, 0, BlockID(), []),
                                    [], proposer)
    bid = BlockID(block.hash(), parts.header())
    state, _ = ex.apply_block(state, bid, block)
    assert app.seen == txs  # serial path ran, in order


def test_v0_never_builds_parallel_executor():
    _, _, ex, _, _ = _rig("v0")
    assert ex._parallel is None


# -- ordering contract (state/store.py ABCIResponses) ------------------------


def test_response_ordering_contract():
    """deliver_txs[i] answers block.data.txs[i], and EventDataTx fires in
    index order — under parallel execution with cross-group conflicts."""
    txs = [f"k{i % 3}=v{i}".encode() for i in range(12)]  # 3 colliding lanes
    pv, state, ex, ss, app = _rig("v1")
    from tendermint_tpu.types.event_bus import EventBus, EventDataTx
    bus = EventBus()
    ex.event_bus = bus
    sub = bus.subscribe("order-test", tme.QUERY_TX)
    proposer = state.validators.get_proposer().address
    block, parts = state.make_block(1, txs, Commit(0, 0, BlockID(), []),
                                    [], proposer)
    bid = BlockID(block.hash(), parts.header())
    state, _ = ex.apply_block(state, bid, block)

    resp = ss.load_abci_responses(1)
    assert len(resp.deliver_txs) == len(txs)
    for i, r in enumerate(resp.deliver_txs):
        # kvstore tags each response with the tx's own key attribute
        attrs = {a.key: a.value for ev in r.events for a in ev.attributes}
        assert attrs[b"key"] == txs[i].split(b"=", 1)[0]

    seen = []
    while not sub.queue.empty():
        msg = sub.queue.get_nowait()
        if isinstance(msg.data, EventDataTx):
            seen.append((msg.data.index, msg.data.tx))
    assert seen == [(i, tx) for i, tx in enumerate(txs)]


# -- proxy lock split --------------------------------------------------------


def test_query_does_not_block_on_consensus_apply():
    """A query on the query connection completes while a slow deliver_tx
    holds the consensus (writer) lock."""
    gate = threading.Event()

    class SlowApp(KVStoreApplication):
        parallel_exec_supported = False  # force the serial locked path

        def deliver_tx(self, req):
            gate.wait(timeout=5.0)
            return super().deliver_tx(req)

    app = SlowApp()
    conns = AppConns(local_client_creator(app))
    conns.start()
    app.state["probe"] = "1"

    done = threading.Event()

    def writer():
        conns.consensus.deliver_tx(abci.RequestDeliverTx(tx=b"x=1"))
        done.set()

    t = threading.Thread(target=writer, daemon=True)
    t.start()
    time.sleep(0.05)  # writer is now parked inside deliver_tx
    t0 = time.monotonic()
    res = conns.query.query(abci.RequestQuery(data=b"probe", path="/store"))
    elapsed = time.monotonic() - t0
    gate.set()
    t.join(timeout=5.0)
    assert done.is_set()
    assert res.value == b"1"
    assert elapsed < 1.0  # returned while the writer still held its lock


def test_zero_arg_creator_still_works():
    app = KVStoreApplication()
    calls = []

    def creator():
        from tendermint_tpu.abci.client import LocalClient
        calls.append(1)
        return LocalClient(app, threading.RLock())

    conns = AppConns(creator)
    conns.start()
    assert len(calls) == 4
    assert conns.query.echo("hi") == "hi"


# -- crash mid-parallel-apply ------------------------------------------------


def test_crash_at_before_exec_block_parallel_replays_identically():
    """Kill at execution.before_exec_block under v1, then recover: replaying
    the same block lands on the exact bytes the serial spec produces."""
    txs = [f"k{i % 4}=v{i}".encode() for i in range(16)]
    serial, _ = _apply_one("v0", txs)

    pv, state, ex, ss, app = _rig("v1")
    proposer = state.validators.get_proposer().address
    block, parts = state.make_block(1, txs, Commit(0, 0, BlockID(), []),
                                    [], proposer)
    bid = BlockID(block.hash(), parts.header())
    fail.arm_raise("execution.before_exec_block")
    with pytest.raises(fail.KilledAtFailPoint):
        ex.apply_block(state, bid, block)
    assert fail.killed_at() == "execution.before_exec_block"
    # nothing durable happened: no responses, app untouched
    assert ss.load_abci_responses(1) is None
    assert app.tx_count == 0

    # recovery: a fresh executor (same stores/app — the kill fired before
    # any app mutation) replays the block to the exact serial bytes
    state2, _ = ex.apply_block(state, bid, block)
    got = (ss.load_abci_responses(1).to_json(), state2.app_hash,
           state2.last_results_hash, dict(app.state), app.tx_count,
           dict(app.validators))
    assert got == serial
