"""The multi-device sharded streaming verifier
(crypto/ed25519_jax/multidevice.py) on the forced 8-way host CPU mesh
(tests/conftest.py pins ``--xla_force_host_platform_device_count=8``):

* deterministic shard planning (pure function of batch/lane geometry);
* byte-parity of verdicts vs the single-device ``batch_verify_stream``
  layout on mixed valid/invalid batches — including under a one-lane
  breaker-open degradation, where the sick lane's segments re-shard to
  healthy peers with zero dropped signatures;
* per-device ``crypto_device_dispatch_total`` series and phase records;
* the per-lane fault-site family (``device.lane.<label>``) and the lane
  breaker registry;
* the columnar sign-bytes fast path (types/canonical
  vote_sign_bytes_columns_batch -> crypto/signcols.SignColumns ->
  prepare_sparse_stream), differentially against the row-materialized
  encoder and the dense packer's preimage bytes.

Device work runs through shape-identical STUB kernels
(tools/device_profile.install_stub_kernels): per-device-ordinal executables
of the real ed25519 kernel take minutes to compile on CPU, and the stub
verdict is a deterministic PER-ITEM function of the packed wire bytes — so
verdict parity across sharding layouts exercises exactly the packing,
sharding, ordering, and re-sharding machinery the real kernels would see.
(Real-kernel byte-parity of the sparse/dense wire formats is covered by
tests/test_sparse_verify.py on the default device.)
"""

import numpy as np
import pytest

import jax

from tendermint_tpu.crypto import phases
from tendermint_tpu.crypto.breaker import (
    OPEN,
    lane_breaker,
    lane_breakers,
    reset_lane_breakers,
)
from tendermint_tpu.crypto.ed25519_jax import multidevice as MD
from tendermint_tpu.crypto.ed25519_jax import verify as V
from tendermint_tpu.libs.faults import faults
from tendermint_tpu.libs.metrics import DeviceMetrics, Registry
from tendermint_tpu.libs.toolbox import load_tool

device_profile = load_tool("device_profile")


@pytest.fixture
def stub_kernels():
    restore = device_profile.install_stub_kernels(V)
    yield
    restore()


@pytest.fixture
def device_metrics():
    m = DeviceMetrics(Registry("t"))
    phases.set_device_metrics(m)
    phases.reset()
    yield m
    phases.set_device_metrics(None)
    phases.reset()


def _workload(n, seed=7, invalid_every=11):
    """Dissimilar equal-length messages (dense wire format — the stub
    dense kernel's verdict is per-item, so it is invariant to segment
    layout) with host-invalid rows mixed in: bad lengths, non-canonical
    s — the ok-mask plane rides along with the kernel verdicts."""
    rng = np.random.default_rng(seed)
    pks = [rng.bytes(32) for _ in range(n)]
    msgs = [rng.bytes(120) for _ in range(n)]
    sigs = [rng.bytes(63) + b"\x00" for _ in range(n)]  # s < L
    for i in range(0, n, invalid_every):
        sigs[i] = sigs[i][:32] + b"\xff" * 32  # s >= L: host reject
    pks[3] = pks[3][:31]                       # bad pk length
    sigs[5] = sigs[5][:63]                     # bad sig length
    return pks, msgs, sigs


def _single_device(pks, msgs, sigs, chunk=V.LANE, columns=None):
    """Single-device segmented reference verdicts (pool not engaged)."""
    if columns is not None:
        return V._verify_segmented(pks, msgs, sigs, chunk, columns=columns)
    return V._verify_segmented(pks, msgs, sigs, chunk)


# -- planning -----------------------------------------------------------------

def test_plan_segments_deterministic_and_exact():
    for k, lanes, sc in [(16, 8, 10), (100, 8, 10), (3, 8, 10), (8, 4, 2),
                         (1, 2, 10), (64, 7, 5)]:
        plan = MD.plan_segments(k, lanes, sc)
        assert plan == MD.plan_segments(k, lanes, sc)  # pure
        sizes = [s for s, _ in plan]
        assert sum(sizes) == k
        assert all(1 <= s <= sc for s in sizes)
        assert [l for _, l in plan] == [i % lanes for i in range(len(plan))]
        if k >= 2 * lanes:
            # every lane gets at least two segments: per-lane pipelining
            assert len(plan) >= 2 * lanes
    assert MD.plan_segments(0, 4, 10) == []


def test_pool_disabled_by_env(monkeypatch):
    monkeypatch.setenv(MD.ENV_DEVICES, "1")
    MD.reset_pool()
    assert MD.pool() is None
    monkeypatch.setenv(MD.ENV_DEVICES, "4")
    MD.reset_pool()
    p = MD.pool()
    assert p is not None and len(p.lanes) == 4
    MD.reset_pool()


def test_seg_chunks_from_cost_model():
    doc = {"results": {"fixed_dispatch_ms": {"min": 80.0},
                       "transfer": {"bandwidth_mbps": 10.0}}}
    # 2048 sigs * 300 B ~ 0.59 MB -> ~59 ms/chunk; 9x80ms => ~13 chunks
    sc = MD._seg_chunks_from_cost_model(doc)
    assert 10 <= sc <= 16
    # local chip: tiny fixed cost -> floor of 2
    doc["results"]["fixed_dispatch_ms"]["min"] = 0.05
    assert MD._seg_chunks_from_cost_model(doc) == 2
    # bandwidth below the ladder's noise floor -> None (caller defaults)
    doc["results"]["transfer"]["bandwidth_mbps"] = None
    assert MD._seg_chunks_from_cost_model(doc) is None
    assert MD._seg_chunks_from_cost_model({}) is None


# -- verdict parity -----------------------------------------------------------

def test_parity_mixed_batch_vs_single_device(stub_kernels):
    pks, msgs, sigs = _workload(1024)
    want = _single_device(pks, msgs, sigs)
    assert 0 < want.sum() < len(pks)  # genuinely mixed accept/reject
    md = MD.MultiDeviceStream(devices=jax.devices()[:4], min_sigs=0)
    got = md.verify(pks, msgs, sigs, chunk=V.LANE)
    np.testing.assert_array_equal(got, want)


def test_parity_through_windowed_submission(stub_kernels):
    """More segments than the 2-per-lane submission window (seg_chunks=1,
    2 lanes, 10 chunks -> 10 segments > window 4): the refill path must
    reassemble in order with the same verdicts."""
    pks, msgs, sigs = _workload(1280, seed=29)
    want = _single_device(pks, msgs, sigs)
    md = MD.MultiDeviceStream(devices=jax.devices()[:2], min_sigs=0,
                              seg_chunks=1)
    got = md.verify(pks, msgs, sigs, chunk=V.LANE)
    np.testing.assert_array_equal(got, want)
    assert sum(r["sigs"] for r in phases.recent_segments()) >= 1280


def test_stream_entry_routes_through_pool(monkeypatch, stub_kernels,
                                          device_metrics):
    pks, msgs, sigs = _workload(768, seed=9)
    want = _single_device(pks, msgs, sigs)
    monkeypatch.setattr(V, "SEG_MIN_SIGS", 256)
    monkeypatch.setenv(MD.ENV_DEVICES, "4")
    monkeypatch.setenv(MD.ENV_MIN_SIGS, "256")
    MD.reset_pool()
    try:
        got = V.batch_verify_stream(pks, msgs, sigs, chunk=V.LANE)
        np.testing.assert_array_equal(got, want)
        used = [i for i in range(8)
                if device_metrics.device_dispatch_total.value(f"cpu:{i}")]
        assert len(used) >= 2, "segments never sharded across devices"
        for i in used:
            assert device_metrics.device_inflight.value(f"cpu:{i}") == 0
    finally:
        MD.reset_pool()


def test_columns_ride_the_pool(stub_kernels):
    """SignColumns slices follow their segments through the lanes and the
    verdicts stay identical to the single-device layout of the SAME
    columnar representation."""
    from tendermint_tpu.types.basic import BlockID, PartSetHeader, SignedMsgType
    from tendermint_tpu.types.canonical import (
        vote_sign_bytes_batch,
        vote_sign_bytes_columns_batch,
    )

    n = 512
    bid = BlockID(b"\xaa" * 32, PartSetHeader(1, b"\xbb" * 32))
    # constant seconds, nanos varints of equal width (5 bytes)
    ts = [1_700_000_000_500_000_000 + 1000 * i for i in range(n)]
    cols = vote_sign_bytes_columns_batch(
        "chain-md", SignedMsgType.PRECOMMIT, 7, 0, [bid] * n, ts)
    assert cols is not None
    msgs = vote_sign_bytes_batch(
        "chain-md", SignedMsgType.PRECOMMIT, 7, 0, [bid] * n, ts)
    rng = np.random.default_rng(5)
    pks = [rng.bytes(32) for _ in range(n)]
    sigs = [rng.bytes(63) + b"\x00" for _ in range(n)]
    want = _single_device(pks, msgs, sigs, columns=cols)
    md = MD.MultiDeviceStream(devices=jax.devices()[:3], min_sigs=0)
    got = md.verify(pks, msgs, sigs, chunk=V.LANE, columns=cols)
    np.testing.assert_array_equal(got, want)


# -- degradation --------------------------------------------------------------

def test_one_sick_lane_degrades_and_resharding_drops_nothing(
        monkeypatch, stub_kernels, device_metrics):
    monkeypatch.setenv("TMTPU_DEVICE_BREAKER_THRESHOLD", "2")
    reset_lane_breakers()
    pks, msgs, sigs = _workload(1280, seed=13)
    want = _single_device(pks, msgs, sigs)
    faults.configure(MD.LANE_SITE_PREFIX + "cpu:1")  # every dispatch fails
    md = MD.MultiDeviceStream(devices=jax.devices()[:4], min_sigs=0)
    got = md.verify(pks, msgs, sigs, chunk=V.LANE)
    np.testing.assert_array_equal(got, want)  # zero dropped signatures
    assert md.stats["resharded_segments"] >= 1
    assert faults.fires(MD.LANE_SITE_PREFIX + "cpu:1") >= 2
    assert lane_breaker("cpu:1").state == OPEN
    # the sick lane never dispatched (its site raises before packing)
    assert device_metrics.device_dispatch_total.value("cpu:1") == 0
    healthy = [i for i in (0, 2, 3)
               if device_metrics.device_dispatch_total.value(f"cpu:{i}")]
    assert len(healthy) >= 2
    for i in range(4):
        assert device_metrics.device_inflight.value(f"cpu:{i}") == 0

    # second call: the OPEN breaker excludes the lane up front — no new
    # fault evaluations, verdicts still byte-identical
    fired = faults.fires(MD.LANE_SITE_PREFIX + "cpu:1")
    got2 = md.verify(pks, msgs, sigs, chunk=V.LANE)
    np.testing.assert_array_equal(got2, want)
    assert faults.fires(MD.LANE_SITE_PREFIX + "cpu:1") == fired


def test_all_lanes_sick_raises_and_batchverifier_survives(
        monkeypatch, stub_kernels):
    monkeypatch.setenv("TMTPU_DEVICE_BREAKER_THRESHOLD", "1")
    reset_lane_breakers()
    labels = [f"cpu:{i}" for i in range(3)]
    faults.configure(",".join(MD.LANE_SITE_PREFIX + l for l in labels))
    md = MD.MultiDeviceStream(devices=jax.devices()[:3], min_sigs=0)
    pks, msgs, sigs = _workload(512, seed=17)
    with pytest.raises(MD.AllLanesFailed):
        md.verify(pks, msgs, sigs, chunk=V.LANE)

    # ...and through BatchVerifier the same failure is a host fallback,
    # never a caller-visible error — byte-identical verdicts
    from tendermint_tpu.crypto import Ed25519PrivKey
    from tendermint_tpu.crypto.batch import BatchVerifier, stats

    reset_lane_breakers()  # breakers tripped above; fresh pool health
    monkeypatch.setenv(MD.ENV_DEVICES, "3")
    monkeypatch.setenv(MD.ENV_MIN_SIGS, "64")
    monkeypatch.setattr(V, "SEG_MIN_SIGS", 64)
    MD.reset_pool()
    try:
        n = 2304  # > the 2048-chunk so the stream path engages
        bv = BatchVerifier(backend="jax", plane="votes")
        for i in range(n):
            sk = Ed25519PrivKey.generate(i.to_bytes(4, "big") * 8)
            m = b"md-fallback-%d" % i
            bv.add(sk.pub_key(), m, sk.sign(m))
        before = stats["device_errors"]
        ok, per = bv.verify()
        assert ok and per.all()  # host fallback, byte-identical verdicts
        assert stats["device_errors"] == before + 1
    finally:
        MD.reset_pool()


def test_lane_breaker_registry(monkeypatch):
    monkeypatch.setenv("TMTPU_DEVICE_BREAKER_THRESHOLD", "5")
    monkeypatch.setenv("TMTPU_DEVICE_BREAKER_COOLDOWN_S", "0.25")
    reset_lane_breakers()
    b = lane_breaker("tpu:3")
    assert lane_breaker("tpu:3") is b  # per-label singleton
    assert b.failure_threshold == 5 and b.cooldown_s == 0.25
    assert b.name == "device:tpu:3"
    # peek() is read-only: repeated peeks on OPEN never admit a probe
    for _ in range(5):
        b.record_failure()
    assert b.state == OPEN
    b._opened_at = b._clock() - 1.0  # cooldown elapsed
    assert b.peek() and b.peek()
    assert b.state == OPEN and not b._probe_in_flight
    assert "tpu:3" in lane_breakers()
    reset_lane_breakers()
    assert "tpu:3" not in lane_breakers()


def test_lane_fault_sites_are_known_family(caplog):
    import logging

    from tendermint_tpu.libs.faults import FaultPlane, is_known_site

    assert is_known_site("device.lane.tpu:7")
    assert is_known_site("device.batch_verify")
    assert not is_known_site("device.lanes.tpu:7")
    plane = FaultPlane()
    with caplog.at_level(logging.WARNING, logger="tmtpu.faults"):
        plane.configure_from_env(
            {"TMTPU_FAULTS": "device.lane.cpu:2@0.5"})
    assert not any("no production code consults" in r.message
                   for r in caplog.records)


# -- columnar sign-bytes ------------------------------------------------------

def test_sign_columns_match_row_encoder():
    from tendermint_tpu.types.basic import BlockID, PartSetHeader, SignedMsgType
    from tendermint_tpu.types.canonical import (
        vote_sign_bytes_batch,
        vote_sign_bytes_columns_batch,
    )

    bid = BlockID(b"\x11" * 32, PartSetHeader(3, b"\x22" * 32))
    # timestamps straddling a second boundary but with equal varint widths
    ts = [1_700_000_001_000_000_500 + 7 * i for i in range(300)]
    rows = vote_sign_bytes_batch(
        "col-chain", SignedMsgType.PRECOMMIT, 42, 1, [bid] * 300, ts)
    cols = vote_sign_bytes_columns_batch(
        "col-chain", SignedMsgType.PRECOMMIT, 42, 1, [bid] * 300, ts)
    assert cols is not None and len(cols) == 300
    assert cols.rows() == rows                       # bulk materialization
    assert [cols[i] for i in (0, 7, 299)] == \
        [rows[i] for i in (0, 7, 299)]               # row indexing
    sub = cols.subset([5, 0, 123])
    assert list(sub) == [rows[5], rows[0], rows[123]]
    assert list(cols.slice(10, 13)) == rows[10:13]

    # ragged structures bail to None instead of producing a wrong template
    nil_bid = BlockID(b"", PartSetHeader(0, b""))
    assert vote_sign_bytes_columns_batch(
        "col-chain", SignedMsgType.PRECOMMIT, 42, 1, [bid, nil_bid],
        ts[:2]) is None                              # nil vote mixes in
    assert vote_sign_bytes_columns_batch(
        "col-chain", SignedMsgType.PRECOMMIT, 42, 1, [bid] * 2,
        [1_700_000_000_000_000_000, 5]) is None      # varint widths differ


def test_commit_columns_memo_and_verify_commit_light(monkeypatch):
    """The VerifyCommitLight plane hands the commit's SignColumns to the
    verifier, and the outcome matches the row path exactly."""
    from tendermint_tpu.crypto import Ed25519PrivKey
    from tendermint_tpu.crypto import batch as B
    from tendermint_tpu.types.basic import BlockID, BlockIDFlag, PartSetHeader
    from tendermint_tpu.types.block import Commit, CommitSig
    from tendermint_tpu.types.validator import Validator
    from tendermint_tpu.types.validator_set import ValidatorSet

    monkeypatch.setenv("TMTPU_BATCH_BACKEND", "host")  # no kernel compiles
    n = 40  # > 32 engages the batched sign-bytes + columns path
    keys = [Ed25519PrivKey.generate(bytes([i + 1]) * 32) for i in range(n)]
    vals = [Validator(k.pub_key().address(), k.pub_key(), 10, 0)
            for k in keys]
    vs = ValidatorSet(vals)
    bid = BlockID(b"\x77" * 32, PartSetHeader(1, b"\x88" * 32))
    commit = Commit(height=9, round=0, block_id=bid, signatures=[
        CommitSig(BlockIDFlag.COMMIT, v.address,
                  1_700_000_000_500_000_000 + 1000 * i, b"")
        for i, v in enumerate(vs.validators)])
    chain = "cols-commit"
    sb = commit.vote_sign_bytes_all(chain)
    by_addr = {k.pub_key().address(): k for k in keys}
    for i, cs in enumerate(commit.signatures):
        cs.signature = by_addr[cs.validator_address].sign(sb[i])

    cols = commit.vote_sign_bytes_columns(chain)
    assert cols is not None
    assert commit.vote_sign_bytes_columns(chain) is cols  # memoized
    assert cols.rows() == sb                              # byte parity

    seen = {}
    orig = B.BatchVerifier.verify

    def spy(self):
        seen["columns"] = self._columns
        return orig(self)

    monkeypatch.setattr(B.BatchVerifier, "verify", spy)
    vs.verify_commit_light(chain, bid, 9, commit)  # must not raise
    assert seen["columns"] is not None and len(seen["columns"]) == n


def test_sparse_from_columns_matches_dense_blocks():
    """The columnar sparse wire format must assemble the SAME SHA preimage
    message bytes as the dense packer — checked with a numpy mirror of the
    on-device _assemble_blocks, no kernel involved."""
    from tendermint_tpu.types.basic import BlockID, PartSetHeader, SignedMsgType
    from tendermint_tpu.types.canonical import (
        vote_sign_bytes_batch,
        vote_sign_bytes_columns_batch,
    )

    n, chunk = 300, 128
    bid = BlockID(b"\x09" * 32, PartSetHeader(2, b"\x0a" * 32))
    # constant seconds, nanos varints of equal width (5 bytes)
    ts = [1_700_000_000_500_000_000 + 1_000_000 * i for i in range(n)]
    msgs = vote_sign_bytes_batch(
        "dense-chain", SignedMsgType.PRECOMMIT, 5, 0, [bid] * n, ts)
    cols = vote_sign_bytes_columns_batch(
        "dense-chain", SignedMsgType.PRECOMMIT, 5, 0, [bid] * n, ts)
    assert cols is not None
    rng = np.random.default_rng(11)
    pks = [rng.bytes(32) for _ in range(n)]
    sigs = [rng.bytes(63) + b"\x00" for _ in range(n)]

    built = V._sparse_from_columns(cols, chunk)
    assert built is not None
    templates, ccols, diff_vals, mlens, k, pad = built
    assert templates.shape[0] == k and diff_vals.shape[0] == pad

    # numpy mirror of _assemble_blocks: template + diff scatter, mlen
    # mask, 0x80 pad marker, BE bitlen in the last 8 bytes
    mlen_max = templates.shape[1]
    m = np.repeat(templates, chunk, axis=0).astype(np.uint8)   # (pad, MLEN)
    m[np.arange(pad)[:, None], ccols[None, :]] = diff_vals
    full_mlens = np.zeros(pad, np.int64)
    full_mlens[:n] = mlens
    iota = np.arange(mlen_max)[None, :]
    m = np.where(iota < full_mlens[:, None], m, 0).astype(np.uint8)
    m[np.arange(pad), full_mlens] = 0x80
    bitlen = (full_mlens + 64) * 8
    nblk = (64 + full_mlens + 17 + 127) // 128
    last = nblk * 128 - 64
    for b_i in range(8):
        m[np.arange(pad), last - 1 - b_i] = (bitlen >> (8 * b_i)) & 0xFF

    # dense reference for the REAL rows: bytes 64.. of each row's padded
    # preimage are exactly the assembled message region
    blocks_w, _nblk, _s, _ok = V.prepare_batch(pks, msgs, sigs)
    dense = np.frombuffer(blocks_w.astype(">u4").tobytes(),
                          dtype=np.uint8).reshape(n, -1)
    np.testing.assert_array_equal(m[:n, :dense.shape[1] - 64],
                                  dense[:, 64:])


def test_pack_scratch_reuse_is_stateless():
    """Repacking different batches through the same worker's scratch must
    never leak bytes between calls (shrink after grow is the risky case)."""
    big = _workload(512, seed=1)
    small = _workload(256, seed=2)
    first = V._pack_stream_dense(*big, 128)
    ref_small = V._pack_stream_dense(*small, 128)
    again_big = V._pack_stream_dense(*big, 128)
    for a, b in zip(first[0], again_big[0]):
        np.testing.assert_array_equal(a, b)
    fresh_small = V._pack_stream_dense(*small, 128)
    for a, b in zip(ref_small[0], fresh_small[0]):
        np.testing.assert_array_equal(a, b)
    np.testing.assert_array_equal(first[1], again_big[1])


def test_phase_records_carry_lane_labels(stub_kernels, device_metrics):
    pks, msgs, sigs = _workload(512, seed=23)
    md = MD.MultiDeviceStream(devices=jax.devices()[:2], min_sigs=0)
    md.verify(pks, msgs, sigs, chunk=V.LANE)
    recs = phases.recent_segments()
    assert recs, "no phase records from a multi-device call"
    labels = {r["device"] for r in recs}
    assert labels <= {"cpu:0", "cpu:1"} and len(labels) == 2
    assert sum(r["sigs"] for r in recs) == 512
    tot = phases.phase_totals()
    assert tot["pipelined_calls"] >= 1
