"""Iterative merkle equivalence: the bottom-up level-buffer implementation
in crypto/merkle.py must be byte-identical — roots AND proofs — to the
reference's recursive split-point formulation (crypto/merkle/tree.go:9),
over randomized leaf sets including the 0, 1, and non-power-of-two counts
where the two tree shapes could plausibly diverge."""

import hashlib
import random

from tendermint_tpu.crypto import merkle


# -- the old recursive implementation, kept verbatim as the test oracle ------

def _rec_leaf(item: bytes) -> bytes:
    return hashlib.sha256(b"\x00" + item).digest()


def _rec_inner(left: bytes, right: bytes) -> bytes:
    return hashlib.sha256(b"\x01" + left + right).digest()


def _split_point(n: int) -> int:
    k = 1
    while k * 2 < n:
        k *= 2
    return k


def _recursive_root(items) -> bytes:
    n = len(items)
    if n == 0:
        return hashlib.sha256(b"").digest()
    if n == 1:
        return _rec_leaf(items[0])
    k = _split_point(n)
    return _rec_inner(_recursive_root(items[:k]), _recursive_root(items[k:]))


def _recursive_aunts(items, index) -> list:
    """Aunt list for items[index], leaf->root, built by the recursive
    split — the exact shape Proof.compute_root consumes."""
    n = len(items)
    if n == 1:
        return []
    k = _split_point(n)
    if index < k:
        return _recursive_aunts(items[:k], index) + [_recursive_root(items[k:])]
    return _recursive_aunts(items[k:], index - k) + [_recursive_root(items[:k])]


# n = 0, 1, 2 are the base cases; primes / 2^k±1 exercise every odd-promote
# level shape; larger sizes cover deep trees
SIZES = [0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 11, 13, 15, 16, 17, 31, 32, 33,
         63, 64, 65, 100, 127, 128, 129, 255, 256, 257, 1000]


def _leaf_sets():
    rng = random.Random(0xC0FFEE)
    for n in SIZES:
        yield n, [rng.randbytes(rng.randrange(0, 200)) for _ in range(n)]


def test_root_matches_recursive_reference():
    for n, items in _leaf_sets():
        assert merkle.hash_from_byte_slices(items) == _recursive_root(items), \
            f"root diverged at n={n}"


def test_proofs_match_recursive_reference_and_verify():
    for n, items in _leaf_sets():
        if n == 0:
            assert merkle.proofs_from_byte_slices(items) == []
            continue
        root = _recursive_root(items)
        proofs = merkle.proofs_from_byte_slices(items)
        assert len(proofs) == n
        for i, p in enumerate(proofs):
            assert p.total == n and p.index == i
            assert p.leaf_hash == _rec_leaf(items[i])
            assert p.aunts == _recursive_aunts(items, i), \
                f"aunts diverged at n={n}, i={i}"
            assert p.verify(root, items[i])
            if n > 1:  # a proof must not verify against a sibling's leaf
                assert not p.verify(root, items[(i + 1) % n])


def test_degenerate_leaves():
    # empty and duplicate leaves still produce the reference trees
    for items in ([b""], [b"", b""], [b"x"] * 7, [b""] * 12):
        assert merkle.hash_from_byte_slices(items) == _recursive_root(items)
        root = _recursive_root(items)
        for i, p in enumerate(merkle.proofs_from_byte_slices(items)):
            assert p.verify(root, items[i])


def test_header_hash_memo_invalidates_on_mutation():
    """The Header.hash memo must never outlive a field write (tamper
    detection depends on recomputation)."""
    from tendermint_tpu.types.block import Header

    h = Header(chain_id="c", height=3, validators_hash=b"\x01" * 32,
               proposer_address=b"\x02" * 20)
    first = h.hash()
    assert h.hash() == first  # memo hit
    h.app_hash = b"\x09" * 32
    assert h.hash() != first
    h.app_hash = b""
    assert h.hash() == first


def test_validator_set_hash_memo_tracks_membership():
    from tendermint_tpu import crypto
    from tendermint_tpu.types import Validator, ValidatorSet

    privs = [crypto.Ed25519PrivKey.generate(bytes([i + 1]) * 32)
             for i in range(4)]
    vals = [Validator(p.pub_key().address(), p.pub_key(), 10)
            for p in privs]
    vs = ValidatorSet(vals)
    h0 = vs.hash()
    # priority rotation must NOT change the hash (it is not committed)
    vs.increment_proposer_priority(3)
    assert vs.hash() == h0
    # copies carry the memo and stay equal
    assert vs.copy().hash() == h0
    # membership changes must invalidate
    vs.update_with_change_set([Validator(vals[0].address, vals[0].pub_key, 99)])
    assert vs.hash() != h0
    # and the recomputed hash matches a from-scratch set with the same power
    fresh = ValidatorSet([Validator(v.address, v.pub_key, 99 if i == 0 else 10)
                          for i, v in enumerate(vals)])
    assert vs.hash() == fresh.hash()
