"""Untrusted-peer scoreboard (libs/peerscore.py): ban-after-K strikes,
severe (proven-lie) instant bans, exponential backoff with seeded jitter,
success-resets, eligibility filtering, and metric accounting — the shared
substrate under statesync chunk blame, blocksync _punish, and light-client
witness cross-checks.
"""

import pytest

from tendermint_tpu.libs.metrics import Registry
from tendermint_tpu.libs.peerscore import PeerScoreboard


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def test_ban_after_k_consecutive_failures():
    sb = PeerScoreboard(ban_threshold=3)
    assert not sb.record_failure("p", "timeout")
    assert not sb.record_failure("p", "timeout")
    assert sb.record_failure("p", "timeout")
    assert sb.banned("p")
    # further failures keep reporting banned, no state explosion
    assert sb.record_failure("p", "timeout")
    assert sb.snapshot()["p"]["ban_reason"] == "timeout"


def test_severe_failure_bans_instantly():
    sb = PeerScoreboard(ban_threshold=5)
    assert sb.record_failure("liar", "rejected_chunk", severe=True)
    assert sb.banned("liar")
    assert sb.snapshot()["liar"]["ban_reason"] == "rejected_chunk"


def test_success_resets_consecutive_count():
    sb = PeerScoreboard(ban_threshold=2)
    sb.record_failure("p", "timeout")
    sb.record_success("p")
    assert not sb.record_failure("p", "timeout")  # back to strike 1
    assert not sb.banned("p")
    sb.record_failure("p", "timeout")
    assert sb.banned("p")
    # success cannot un-ban
    sb.record_success("p")
    assert sb.banned("p")


def test_exponential_backoff_with_clock():
    clock = FakeClock()
    sb = PeerScoreboard(ban_threshold=10, backoff_base_s=1.0, jitter=0.0,
                        clock=clock)
    sb.record_failure("p")
    assert sb.in_backoff("p")
    assert sb.eligible(["p"]) == []
    assert sb.eligible(["p"], allow_backoff=True) == ["p"]
    clock.t = 1.01
    assert not sb.in_backoff("p")
    assert sb.eligible(["p"]) == ["p"]
    # second consecutive failure doubles the wait
    sb.record_failure("p")
    clock.t += 1.5
    assert sb.in_backoff("p")
    clock.t += 0.6
    assert not sb.in_backoff("p")


def test_backoff_capped_at_max():
    clock = FakeClock()
    sb = PeerScoreboard(ban_threshold=100, backoff_base_s=1.0,
                        backoff_max_s=4.0, jitter=0.0, clock=clock)
    for _ in range(10):
        sb.record_failure("p")
    assert sb.snapshot()["p"]["backoff_remaining_s"] <= 4.0


def test_jitter_is_seeded_deterministic():
    def schedule(seed):
        clock = FakeClock()
        sb = PeerScoreboard(ban_threshold=50, seed=seed, clock=clock)
        out = []
        for _ in range(8):
            sb.record_failure("p")
            out.append(sb.snapshot()["p"]["backoff_remaining_s"])
        return out

    assert schedule(7) == schedule(7)
    assert schedule(7) != schedule(8)


def test_eligible_preserves_order_and_skips_banned():
    sb = PeerScoreboard(ban_threshold=1)
    sb.record_failure("b", "x")  # banned (threshold 1)
    assert sb.eligible(["a", "b", "c"]) == ["a", "c"]
    assert sb.eligible(["a", "b", "c"], allow_backoff=True) == ["a", "c"]
    assert sb.ban_count() == 1


def test_metrics_counters():
    reg = Registry("t")
    bans = reg.counter("sync", "peer_bans_total", "bans", ["reason"])
    retries = reg.counter("sync", "sync_retries_total", "retries")
    sb = PeerScoreboard(ban_threshold=2, bans_counter=bans,
                        retries_counter=retries)
    sb.record_failure("p", "bad_chunk")
    assert bans.value("bad_chunk") == 0
    sb.record_failure("p", "bad_chunk")
    assert bans.value("bad_chunk") == 1
    # already banned: no double count
    sb.record_failure("p", "bad_chunk")
    assert bans.value("bad_chunk") == 1
    sb.note_retry()
    sb.note_retry()
    assert retries.value() == 2


def test_forget_and_reset():
    sb = PeerScoreboard(ban_threshold=1)
    sb.record_failure("p", "x")
    assert sb.banned("p")
    sb.forget("p")
    assert not sb.banned("p")
    sb.record_failure("q", "x")
    sb.reset()
    assert sb.snapshot() == {}


def test_threshold_must_be_positive():
    with pytest.raises(ValueError):
        PeerScoreboard(ban_threshold=0)
