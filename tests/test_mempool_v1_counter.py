"""The v1 priority mempool's ordering/eviction/TTL semantics, now folded
into the sharded-lane eviction policy (mempool/ingest.py ShardedMempool —
the standalone priority_mempool module is gone), plus the counter example
app (reference abci/example/counter)."""

import pytest

from tendermint_tpu.abci import types as abci
from tendermint_tpu.abci.application import Application
from tendermint_tpu.abci.example.counter import CounterApplication
from tendermint_tpu.mempool.clist_mempool import ErrTxInCache, MempoolError
from tendermint_tpu.mempool.ingest import ShardedMempool
from tendermint_tpu.proxy import AppConns, local_client_creator


class PrioApp(Application):
    """Assigns priority = first byte of the tx (the app-priority seam
    unsigned txs fall back to; signed txs carry their envelope fee)."""

    def check_tx(self, req):
        if req.tx == b"":
            return abci.ResponseCheckTx(code=1, log="empty")
        return abci.ResponseCheckTx(code=0, priority=req.tx[0], gas_wanted=1)


def _mk(maxtxs=3, **kw):
    conns = AppConns(local_client_creator(PrioApp()))
    conns.start()
    return ShardedMempool(conns.mempool, max_txs=maxtxs, lanes=4, **kw)


def test_priority_ordering_and_reap():
    mp = _mk(maxtxs=10)
    for tx in (b"\x05low", b"\x50mid", b"\xa0high"):
        assert mp.check_tx(tx).code == 0
    # merged reap across lanes: priority desc, arrival asc
    assert mp.reap_max_txs(10) == [b"\xa0high", b"\x50mid", b"\x05low"]
    # byte/gas caps respected (skip-what-doesn't-fit, v1 semantics)
    assert mp.reap_max_bytes_max_gas(7, -1) == [b"\xa0high"]
    assert len(mp.reap_max_bytes_max_gas(-1, 2)) == 2


def test_eviction_of_lower_priority_when_full():
    mp = _mk(maxtxs=3)
    for tx in (b"\x10a", b"\x20b", b"\x30c"):
        assert mp.check_tx(tx).code == 0
    # lower-priority incoming is rejected outright (explicit full error)
    with pytest.raises(MempoolError, match="full"):
        mp.check_tx(b"\x01z")
    assert mp.size() == 3
    # higher-priority incoming evicts the lowest resident
    assert mp.check_tx(b"\x99hi").code == 0
    assert mp.size() == 3
    txs = mp.reap_max_txs(10)
    assert b"\x99hi" in txs and b"\x10a" not in txs
    # the evicted tx left the dedup cache too (not a cache-dup rejection):
    # resubmitting it fails on capacity again, not ErrTxInCache
    with pytest.raises(MempoolError, match="full"):
        mp.check_tx(b"\x10a")


def test_equal_priority_is_fifo():
    """Ties break by arrival order — with flat priorities the merged reap
    degenerates to the v0 FIFO, whatever lane each tx landed in."""
    mp = _mk(maxtxs=10)
    txs = [b"\x20" + bytes([i]) * 3 for i in range(6)]
    for tx in txs:
        assert mp.check_tx(tx).code == 0
    assert mp.reap_max_txs(-1) == txs


def test_update_removes_committed_and_rechecks():
    mp = _mk(maxtxs=10)
    mp.check_tx(b"\x10a")
    mp.check_tx(b"\x20b")
    mp.lock()
    try:
        mp.update(2, [b"\x10a"], [abci.ResponseCheckTx(code=0)])
    finally:
        mp.unlock()
    assert mp.reap_max_txs(10) == [b"\x20b"]
    # committed tx stays cached: re-adding is rejected
    with pytest.raises(ErrTxInCache):
        mp.check_tx(b"\x10a")
    assert mp.size() == 1


def test_ttl_expiry_purges_on_update():
    mp = _mk(maxtxs=10, ttl_num_blocks=2)
    mp._height = 5
    assert mp.check_tx(b"\x10old").code == 0
    mp.lock()
    try:
        mp.update(8, [], [])  # height 8: admitted at 5, ttl 2 -> expired
    finally:
        mp.unlock()
    assert mp.size() == 0
    # and purged from the cache, so it may be resubmitted
    assert mp.check_tx(b"\x10old").code == 0


def test_counter_app_serial_semantics():
    app = CounterApplication(serial=True)
    conns = AppConns(local_client_creator(app))
    conns.start()
    c = conns.consensus
    # correct nonce order accepted
    for i in range(3):
        assert c.deliver_tx(abci.RequestDeliverTx(
            tx=i.to_bytes(8, "big"))).code == 0
    # replay and skip rejected
    assert c.deliver_tx(abci.RequestDeliverTx(
        tx=(1).to_bytes(8, "big"))).code == 2
    assert c.deliver_tx(abci.RequestDeliverTx(
        tx=(9).to_bytes(8, "big"))).code == 2
    # CheckTx rejects stale nonces
    assert conns.mempool.check_tx(abci.RequestCheckTx(
        tx=(0).to_bytes(8, "big"))).code == 2
    assert conns.mempool.check_tx(abci.RequestCheckTx(
        tx=(5).to_bytes(8, "big"))).code == 0
    assert c.commit().data == (3).to_bytes(8, "big")
