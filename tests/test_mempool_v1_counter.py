"""v1 priority mempool semantics (reference mempool/v1/mempool.go) and the
counter example app (reference abci/example/counter).
"""

from tendermint_tpu.abci import types as abci
from tendermint_tpu.abci.application import Application
from tendermint_tpu.abci.example.counter import CounterApplication
from tendermint_tpu.mempool.priority_mempool import PriorityMempool
from tendermint_tpu.proxy import AppConns, local_client_creator


class PrioApp(Application):
    """Assigns priority = first byte of the tx."""

    def check_tx(self, req):
        if req.tx == b"":
            return abci.ResponseCheckTx(code=1, log="empty")
        return abci.ResponseCheckTx(code=0, priority=req.tx[0], gas_wanted=1)


def _mk(maxtxs=3):
    conns = AppConns(local_client_creator(PrioApp()))
    conns.start()
    return PriorityMempool(conns.mempool, max_txs=maxtxs)


def test_priority_ordering_and_reap():
    mp = _mk(maxtxs=10)
    for tx in (b"\x05low", b"\x50mid", b"\xa0high"):
        assert mp.check_tx(tx).code == 0
    assert mp.reap_max_txs(10) == [b"\xa0high", b"\x50mid", b"\x05low"]
    # byte/gas caps respected
    assert mp.reap_max_bytes_max_gas(5, -1) == [b"\xa0high"]
    assert len(mp.reap_max_bytes_max_gas(-1, 2)) == 2


def test_eviction_of_lower_priority_when_full():
    mp = _mk(maxtxs=3)
    for tx in (b"\x10a", b"\x20b", b"\x30c"):
        assert mp.check_tx(tx).code == 0
    # lower-priority incoming is rejected outright
    assert mp.check_tx(b"\x01z").code != 0
    assert mp.size() == 3
    # higher-priority incoming evicts the lowest resident
    assert mp.check_tx(b"\x99hi").code == 0
    assert mp.size() == 3
    txs = mp.reap_max_txs(10)
    assert b"\x99hi" in txs and b"\x10a" not in txs


def test_update_removes_committed_and_rechecks():
    mp = _mk(maxtxs=10)
    mp.check_tx(b"\x10a")
    mp.check_tx(b"\x20b")
    mp.update(2, [b"\x10a"])
    assert mp.reap_max_txs(10) == [b"\x20b"]
    # committed tx stays cached: re-adding is a no-op
    assert mp.check_tx(b"\x10a").log == "tx already in cache"
    assert mp.size() == 1


def test_counter_app_serial_semantics():
    app = CounterApplication(serial=True)
    conns = AppConns(local_client_creator(app))
    conns.start()
    c = conns.consensus
    # correct nonce order accepted
    for i in range(3):
        assert c.deliver_tx(abci.RequestDeliverTx(
            tx=i.to_bytes(8, "big"))).code == 0
    # replay and skip rejected
    assert c.deliver_tx(abci.RequestDeliverTx(
        tx=(1).to_bytes(8, "big"))).code == 2
    assert c.deliver_tx(abci.RequestDeliverTx(
        tx=(9).to_bytes(8, "big"))).code == 2
    # CheckTx rejects stale nonces
    assert conns.mempool.check_tx(abci.RequestCheckTx(
        tx=(0).to_bytes(8, "big"))).code == 2
    assert conns.mempool.check_tx(abci.RequestCheckTx(
        tx=(5).to_bytes(8, "big"))).code == 0
    assert c.commit().data == (3).to_bytes(8, "big")
