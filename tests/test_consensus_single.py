"""Single-validator consensus: the state machine must produce blocks over the
kvstore app end-to-end (SURVEY.md §7 stage 5 definition-of-done), and recover
across restart via WAL + handshake replay.
"""

import asyncio

import pytest

from tendermint_tpu import crypto
from tendermint_tpu.abci.example.kvstore import KVStoreApplication
from tendermint_tpu.consensus import ConsensusState, WAL
from tendermint_tpu.consensus.config import test_consensus_config
from tendermint_tpu.consensus.replay import Handshaker, catchup_replay
from tendermint_tpu.libs.db import MemDB, SQLiteDB
from tendermint_tpu.mempool import CListMempool
from tendermint_tpu.proxy import AppConns, local_client_creator
from tendermint_tpu.state import BlockExecutor, StateStore, state_from_genesis
from tendermint_tpu.state.execution import EmptyEvidencePool
from tendermint_tpu.store import BlockStore
from tendermint_tpu.types import GenesisDoc, GenesisValidator, MockPV
from tendermint_tpu.types.event_bus import EventBus
from tendermint_tpu.types import events as tme

CHAIN_ID = "single-chain"


def build_node(tmp_path=None, app=None, pv=None, db_factory=MemDB, wal=None):
    pv = pv or MockPV(crypto.Ed25519PrivKey.generate(b"\x33" * 32))
    genesis = GenesisDoc(
        chain_id=CHAIN_ID,
        genesis_time_ns=1_700_000_000_000_000_000,
        validators=[GenesisValidator(pv.get_pub_key(), 10)],
    )
    app = app or KVStoreApplication()
    conns = AppConns(local_client_creator(app))
    conns.start()
    state_store = StateStore(db_factory())
    block_store = BlockStore(db_factory())
    state = state_from_genesis(genesis)

    handshaker = Handshaker(state_store, state, block_store, genesis)
    state = handshaker.handshake(conns.consensus, conns.query)
    state_store.save(state)

    mempool = CListMempool(conns.mempool)
    event_bus = EventBus()
    block_exec = BlockExecutor(state_store, conns.consensus, mempool,
                               EmptyEvidencePool(), block_store, event_bus)
    cs = ConsensusState(test_consensus_config(), state, block_exec, block_store,
                        wal=wal)
    cs.set_priv_validator(pv)
    cs.set_event_bus(event_bus)
    mempool.tx_available_callbacks.append(cs.notify_txs_available)
    return cs, mempool, app, event_bus, pv, (state_store, block_store, genesis, conns)


async def wait_for_height(event_bus: EventBus, cs: ConsensusState, height: int,
                          timeout: float = 10.0):
    sub = event_bus.subscribe(f"test-wait-{height}", tme.QUERY_NEW_BLOCK)
    try:
        while True:
            msg = await asyncio.wait_for(sub.next(), timeout)
            if msg.data.block.header.height >= height:
                return
    finally:
        event_bus.unsubscribe_all(f"test-wait-{height}")


def test_single_validator_produces_blocks():
    async def run():
        cs, mempool, app, event_bus, pv, _ = build_node()
        await cs.start()
        try:
            mempool.check_tx(b"alpha=1")
            await wait_for_height(event_bus, cs, 3)
        finally:
            await cs.stop()
        assert cs.state.last_block_height >= 3
        assert app.state.get("alpha") == "1"
        # the tx was committed and removed from mempool
        assert mempool.size() == 0

    asyncio.run(run())


def test_single_validator_commits_txs_across_heights():
    async def run():
        cs, mempool, app, event_bus, pv, _ = build_node()
        await cs.start()
        try:
            mempool.check_tx(b"k1=a")
            await wait_for_height(event_bus, cs, 1)
            mempool.check_tx(b"k2=b")
            mempool.check_tx(b"k3=c")
            await wait_for_height(event_bus, cs, cs.state.last_block_height + 2)
        finally:
            await cs.stop()
        assert app.state == {"k1": "a", "k2": "b", "k3": "c"}

    asyncio.run(run())


def test_wal_written_and_replayable(tmp_path):
    async def run():
        wal = WAL(str(tmp_path / "cs.wal"))
        cs, mempool, app, event_bus, pv, _ = build_node(wal=wal)
        await cs.start()
        try:
            mempool.check_tx(b"x=y")
            await wait_for_height(event_bus, cs, 2)
        finally:
            await cs.stop()
        committed = cs.state.last_block_height
        # WAL has end-height records for every committed height
        wal2 = WAL(str(tmp_path / "cs.wal"))
        for h in range(1, committed + 1):
            assert wal2.search_for_end_height(h), f"missing ENDHEIGHT {h}"
        # and messages after the last end-height replay into a fresh machine
        msgs = wal2.messages_after_end_height(committed)
        assert isinstance(msgs, list)

    asyncio.run(run())


def test_restart_recovers_via_handshake(tmp_path):
    async def run():
        dbs = {}

        def db_factory(name_counter=[0]):
            # stable SQLite files so the "restart" sees the same data
            idx = name_counter[0]
            name_counter[0] += 1
            path = str(tmp_path / f"db{idx}.db")
            db = SQLiteDB(path)
            return db

        pv = MockPV(crypto.Ed25519PrivKey.generate(b"\x44" * 32))
        cs, mempool, app, event_bus, _, extras = build_node(pv=pv, db_factory=db_factory)
        await cs.start()
        mempool.check_tx(b"persist=me")
        await wait_for_height(event_bus, cs, 2)
        await cs.stop()
        committed = cs.state.last_block_height
        state_store, block_store, genesis, conns = extras

        # "restart": fresh app at height 0, same stores → handshake replays
        app2 = KVStoreApplication()
        conns2 = AppConns(local_client_creator(app2))
        conns2.start()
        prev_state = state_store.load()
        handshaker = Handshaker(state_store, prev_state, block_store, genesis)
        state2 = handshaker.handshake(conns2.consensus, conns2.query)
        assert handshaker.n_blocks == committed  # replayed every block
        assert app2.height == committed
        assert app2.state.get("persist") == "me"
        assert state2.last_block_height == committed

    asyncio.run(run())


def test_timeout_ticker_ignores_earlier_hrs():
    """ticker.go:94: a schedule for an earlier-or-equal (H,R,S) must NOT
    cancel/replace a pending later-step timeout (liveness regression guard)."""
    asyncio.run(_run_ticker_guard())


async def _run_ticker_guard():
    from tendermint_tpu.consensus.round_state import RoundStep

    cs, mempool, app, event_bus, pv, _ = build_node()
    try:
        # pending: (h=5, r=1, PRECOMMIT_WAIT), long duration so it stays pending
        cs._schedule_timeout(30.0, 5, 1, RoundStep.PRECOMMIT_WAIT)
        pending = cs._pending_timeout
        assert (pending.height, pending.round, pending.step) == (5, 1, int(RoundStep.PRECOMMIT_WAIT))
        task = cs._timeout_task

        # earlier height / earlier round / earlier-or-equal step: all ignored
        cs._schedule_timeout(0.001, 4, 9, RoundStep.COMMIT)
        cs._schedule_timeout(0.001, 5, 0, RoundStep.COMMIT)
        cs._schedule_timeout(0.001, 5, 1, RoundStep.PROPOSE)
        cs._schedule_timeout(0.001, 5, 1, RoundStep.PRECOMMIT_WAIT)
        assert cs._pending_timeout is pending
        assert cs._timeout_task is task and not task.cancelled()

        # later step / later round / later height: replace
        cs._schedule_timeout(30.0, 5, 1, RoundStep.COMMIT)
        assert cs._pending_timeout.step == int(RoundStep.COMMIT)
        cs._schedule_timeout(30.0, 5, 2, RoundStep.NEW_ROUND)
        assert cs._pending_timeout.round == 2
        cs._schedule_timeout(30.0, 6, 0, RoundStep.NEW_HEIGHT)
        assert cs._pending_timeout.height == 6
        assert task.cancelled() or task.done() or cs._timeout_task is not task
    finally:
        cs._timeout_task and cs._timeout_task.cancel()
        await asyncio.sleep(0)
