"""Device circuit breaker (crypto/breaker.py): the state machine alone,
then threaded through BatchVerifier and the vote micro-batcher under
injected device faults — the PR's acceptance assertion: after N injected
consecutive failures there are ZERO device-route attempts while OPEN
(proved via metrics), the host path keeps producing identical verdicts,
and a half-open probe restores the device route once injection stops.
"""

import asyncio

import numpy as np
import pytest

from tendermint_tpu.crypto import Ed25519PrivKey
from tendermint_tpu.crypto import batch as batch_mod
from tendermint_tpu.crypto import breaker as breaker_mod
from tendermint_tpu.crypto.batch import BatchVerifier
from tendermint_tpu.crypto.breaker import (
    CLOSED,
    HALF_OPEN,
    OPEN,
    CircuitBreaker,
    classify_device_error,
    device_breaker,
)
from tendermint_tpu.libs.faults import InjectedFault, faults
from tendermint_tpu.libs.metrics import CryptoMetrics, Registry


class FakeClock:
    def __init__(self):
        self.t = 100.0

    def __call__(self):
        return self.t


# -- state machine -----------------------------------------------------------

def test_trips_open_after_threshold_consecutive_failures():
    cb = CircuitBreaker("t", failure_threshold=3, cooldown_s=30.0,
                        clock=FakeClock())
    assert cb.state == CLOSED
    cb.record_failure()
    cb.record_failure()
    assert cb.state == CLOSED and cb.allow()
    cb.record_failure()
    assert cb.state == OPEN
    assert not cb.allow() and cb.stats["rejections"] == 1


def test_success_resets_consecutive_count():
    cb = CircuitBreaker("t", failure_threshold=2, clock=FakeClock())
    cb.record_failure()
    cb.record_success()  # streak broken
    cb.record_failure()
    assert cb.state == CLOSED  # 1+1 non-consecutive != threshold 2


def test_half_open_single_probe_and_verdicts():
    clock = FakeClock()
    cb = CircuitBreaker("t", failure_threshold=1, cooldown_s=10.0,
                        clock=clock)
    cb.record_failure()
    assert cb.state == OPEN and not cb.allow()
    clock.t += 10.0
    # cooldown elapsed: exactly ONE probe admitted
    assert cb.allow() and cb.state == HALF_OPEN
    assert not cb.allow()  # second caller mid-probe: rejected
    # failed probe: straight back to OPEN for a fresh cooldown
    cb.record_failure()
    assert cb.state == OPEN and not cb.allow()
    clock.t += 10.0
    assert cb.allow() and cb.state == HALF_OPEN
    cb.record_success()
    assert cb.state == CLOSED and cb.allow()


def test_transition_metrics_and_state_gauge():
    m = CryptoMetrics(Registry())
    breaker_mod.set_breaker_metrics(m)
    try:
        clock = FakeClock()
        cb = CircuitBreaker("mtest", failure_threshold=1, cooldown_s=5.0,
                            clock=clock)
        cb.record_failure()
        assert m.breaker_state.value("mtest") == 1.0  # open
        clock.t += 5.0
        cb.allow()
        assert m.breaker_state.value("mtest") == 2.0  # half-open
        cb.record_success()
        assert m.breaker_state.value("mtest") == 0.0  # closed
        assert m.breaker_transitions_total.value("mtest", "closed", "open") == 1.0
        assert m.breaker_transitions_total.value("mtest", "open", "half_open") == 1.0
        assert m.breaker_transitions_total.value("mtest", "half_open", "closed") == 1.0
    finally:
        breaker_mod.set_breaker_metrics(None)


def test_classify_device_error_taxonomy():
    assert classify_device_error(InjectedFault("s")) == "injected"
    assert classify_device_error(RuntimeError("XLA compilation failed")) == \
        "compile_error"
    assert classify_device_error(RuntimeError("device wedged")) == \
        "runtime_error"


# -- BatchVerifier integration ----------------------------------------------

def _signed(n, seed=0):
    out = []
    for i in range(n):
        pk = Ed25519PrivKey.generate(bytes([(seed * 29 + i) % 251 + 1]) * 32)
        msg = f"breaker msg {i}".encode()
        out.append((pk.pub_key(), msg, pk.sign(msg)))
    return out


def _verify_cases(bv, cases, corrupt=None):
    for i, (pub, msg, sig) in enumerate(cases):
        if corrupt is not None and i == corrupt:
            sig = sig[:-1] + bytes([sig[-1] ^ 1])
        bv.add(pub, msg, sig)
    return bv.verify()


@pytest.fixture
def breaker_knobs():
    """Shrink the singleton's trip/cooldown knobs for the test and restore
    them after (conftest's autouse fixture resets STATE, not tuning)."""
    thr, cd = device_breaker.failure_threshold, device_breaker.cooldown_s
    m = CryptoMetrics(Registry())
    batch_mod.set_crypto_metrics(m)
    breaker_mod.set_breaker_metrics(m)
    try:
        device_breaker.failure_threshold = 3
        device_breaker.cooldown_s = 60.0  # tests rewind _opened_at instead
        yield m
    finally:
        device_breaker.failure_threshold, device_breaker.cooldown_s = thr, cd
        batch_mod.set_crypto_metrics(None)
        breaker_mod.set_breaker_metrics(None)


def test_batch_verifier_breaker_cycle(breaker_knobs):
    """Injected device faults → host fallback with identical verdicts →
    breaker opens (zero device attempts, via metrics) → half-open probe
    restores the device route when injection stops."""
    m = breaker_knobs
    cases = _signed(8)
    bv = BatchVerifier(backend="jax", plane="votes")
    faults.configure("device.batch_verify")  # every device attempt raises

    # 3 consecutive device failures: each falls back to host with correct
    # verdicts (one corrupted sig per batch must still be caught)
    for k in range(3):
        ok, per = _verify_cases(bv, cases, corrupt=k)
        assert not ok and per.sum() == 7 and not per[k]
        assert m.device_fallbacks_total.value("injected") == float(k + 1)
    assert device_breaker.state == OPEN

    # OPEN: zero device-route attempts — no new device routing decisions,
    # no new injected-fault fallbacks (the site is never evaluated), only
    # breaker_open fallbacks; verdicts stay correct on host
    injected_fires = faults.fires("device.batch_verify")
    for k in range(4):
        ok, per = _verify_cases(bv, cases)
        assert ok and per.all()
    assert m.routing_decisions_total.value("device", "votes") == 0.0
    assert faults.fires("device.batch_verify") == injected_fires
    assert m.device_fallbacks_total.value("breaker_open") == 4.0
    assert device_breaker.state == OPEN

    # injection stops, cooldown elapses (rewound deterministically rather
    # than slept): the half-open probe rides the device and CLOSES the
    # breaker; the device route is live again
    faults.reset()
    device_breaker._opened_at = (device_breaker._clock()
                                 - device_breaker.cooldown_s - 1.0)
    ok, per = _verify_cases(bv, cases)
    assert ok and per.all()
    assert device_breaker.state == CLOSED
    assert m.routing_decisions_total.value("device", "votes") == 1.0
    ok, per = _verify_cases(bv, cases, corrupt=2)
    assert not ok and per.sum() == 7
    assert m.routing_decisions_total.value("device", "votes") == 2.0


def test_batch_verifier_host_backend_never_touches_breaker():
    faults.configure("device.batch_verify")
    bv = BatchVerifier(backend="host")
    ok, per = _verify_cases(bv, _signed(4))
    assert ok and per.all()
    assert faults.fires("device.batch_verify") == 0
    assert device_breaker.state == CLOSED


# -- vote micro-batcher integration ------------------------------------------

def test_vote_batcher_injected_flush_falls_back_and_feeds_breaker():
    """An armed device.vote_flush site fails the flush ON the executor
    thread; every pending preverify future still resolves with the right
    verdict (host re-verify), and the shared breaker counts the failure."""
    from tendermint_tpu.crypto.vote_batcher import BatchVoteVerifier

    thr = device_breaker.failure_threshold
    device_breaker.failure_threshold = 2
    try:
        faults.configure("device.vote_flush")
        verifier = BatchVoteVerifier(min_device_batch=2, deadline_s=0.01,
                                     device_timeout_s=600.0)

        async def run():
            # fresh signatures per round — the batcher's verdict cache
            # would otherwise serve round 2 without a flush
            for round_ in range(2):
                cases = _signed(4, seed=5 + round_)
                results = await asyncio.gather(*(
                    verifier.preverify(pub, msg,
                                       sig if i != 1 else
                                       sig[:-1] + bytes([sig[-1] ^ 1]))
                    for i, (pub, msg, sig) in enumerate(cases)))
                assert results == [True, False, True, True], (round_, results)

        asyncio.run(run())
        assert verifier.stats["device_errors"] == 2
        assert verifier.stats["device_batches"] == 0
        assert device_breaker.state == OPEN
        # OPEN: the next flush never evaluates the device site
        fires = faults.fires("device.vote_flush")

        async def run_open():
            results = await asyncio.gather(*(
                verifier.preverify(pub, msg, sig)
                for pub, msg, sig in _signed(3, seed=9)))
            assert all(results)

        asyncio.run(run_open())
        assert faults.fires("device.vote_flush") == fires
        assert verifier.stats["breaker_rejections"] >= 1
    finally:
        device_breaker.failure_threshold = thr
