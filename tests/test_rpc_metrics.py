"""RPC server telemetry (rpc/server.py + RPCMetrics): per-endpoint
latency/outcome series, request/response size histograms, in-flight drain
on handler exceptions, the unknown-method cardinality guard, websocket
subscriber gauge, and the slow-request log knob — against a real aiohttp
RPCServer over a stub node (no cryptography/tomllib needed, so the suite
runs in slim containers too)."""

import asyncio
import json
import logging
from types import SimpleNamespace

import pytest

pytest.importorskip("aiohttp", reason="RPC server needs aiohttp")

from tendermint_tpu.libs.metrics import RPCMetrics, Registry
from tendermint_tpu.libs.txlife import TxLifecycle
from tendermint_tpu.rpc.server import RPCServer
from tendermint_tpu.types.event_bus import EventBus


def _stub_node():
    """The minimal node surface RPCServer + the handlers under test touch.
    block_store is None on purpose: the `block` route then raises inside
    its handler — the in-flight-drain-on-exception probe."""
    from tendermint_tpu.abci.example.kvstore import KVStoreApplication
    from tendermint_tpu.mempool import CListMempool
    from tendermint_tpu.proxy import AppConns, local_client_creator

    conns = AppConns(local_client_creator(KVStoreApplication()))
    conns.start()
    mempool = CListMempool(conns.mempool)
    mempool.txlife = TxLifecycle(sample_rate=1.0)
    node = SimpleNamespace(
        config=SimpleNamespace(rpc=SimpleNamespace(
            laddr="tcp://127.0.0.1:0", max_body_bytes=1000000,
            unsafe=False)),
        mempool=mempool,
        block_store=None,
        event_bus=EventBus(),
        _conns=conns,
    )
    return node


async def _serve():
    node = _stub_node()
    server = RPCServer(node)
    metrics = RPCMetrics(Registry())
    server.metrics = metrics
    await server.start("tcp://127.0.0.1:0")
    return node, server, metrics


async def _teardown(node, server):
    await server.stop()
    node._conns.stop()


def test_per_endpoint_series_outcomes_and_sizes():
    import aiohttp

    async def run():
        node, server, m = await _serve()
        base = f"http://127.0.0.1:{server.bound_port}"
        try:
            async with aiohttp.ClientSession() as s:
                # GET URI route → ok outcome
                async with s.get(base + "/health") as r:
                    assert (await r.json())["result"] == {}
                # POST JSON-RPC route → ok outcome + body sizes observed
                payload = {"jsonrpc": "2.0", "id": 1,
                           "method": "broadcast_tx_sync",
                           "params": {"tx": "aGk="}}
                async with s.post(base + "/", json=payload) as r:
                    doc = await r.json()
                assert doc["result"]["code"] == 0
                # handler exception (block_store is None) → error outcome,
                # NOT a transport-level failure
                async with s.get(base + "/block?height=3") as r:
                    doc = await r.json()
                assert "error" in doc
                # unknown method: one shared label, no cardinality mint
                async with s.post(base + "/", json={
                        "jsonrpc": "2.0", "id": 2,
                        "method": "gimme_keys"}) as r:
                    assert "error" in await r.json()
        finally:
            await _teardown(node, server)
        assert m.request_seconds.count_value("health", "ok") == 1
        assert m.request_seconds.count_value("broadcast_tx_sync", "ok") == 1
        assert m.request_seconds.count_value("block", "error") == 1
        assert m.request_seconds.count_value("unknown", "error") == 1
        # in-flight drained through BOTH the ok and the exception paths
        assert m.requests_in_flight.value() == 0
        # sizes: both POST bodies and GET path+query observed, plus every
        # serialized response
        assert m.request_size_bytes.count_value() >= 4
        assert m.response_size_bytes.count_value() >= 4
        assert m.request_size_bytes.sum_value() > 0
        assert m.response_size_bytes.sum_value() > 0
        # the lifecycle front door: broadcast_tx_sync marked rpc_received
        # and the tx went through checktx/admission
        snap = node.mempool.txlife.snapshot()
        assert snap["active"] == 1
        text = "\n".join(m.request_seconds.render())
        assert 'endpoint="broadcast_tx_sync"' in text

    asyncio.run(run())


def test_inflight_gauge_tracks_concurrent_requests():
    """A slow handler holds the in-flight gauge up while it runs; the
    gauge drains to zero afterwards even when the handler raises."""
    import aiohttp

    async def run():
        node, server, m = await _serve()
        base = f"http://127.0.0.1:{server.bound_port}"
        release = asyncio.Event()
        seen = {}

        async def slow_health():
            seen["inflight"] = m.requests_in_flight.value()
            await release.wait()
            raise RuntimeError("boom after the await")

        server.env.health = slow_health
        try:
            async with aiohttp.ClientSession() as s:
                task = asyncio.create_task(s.get(base + "/health"))
                for _ in range(100):
                    if seen:
                        break
                    await asyncio.sleep(0.01)
                assert seen.get("inflight") == 1.0, seen
                release.set()
                async with await task as r:
                    assert "error" in await r.json()
        finally:
            await _teardown(node, server)
        assert m.requests_in_flight.value() == 0
        assert m.request_seconds.count_value("health", "error") == 1

    asyncio.run(run())


def test_websocket_subscriber_gauge():
    import aiohttp

    async def run():
        node, server, m = await _serve()
        base = f"http://127.0.0.1:{server.bound_port}"
        try:
            async with aiohttp.ClientSession() as s:
                async with s.ws_connect(base + "/websocket") as ws:
                    await ws.send_json({"jsonrpc": "2.0", "id": 1,
                                        "method": "subscribe",
                                        "params": {"query":
                                                   "tm.event='NewBlock'"}})
                    msg = json.loads((await ws.receive()).data)
                    assert msg["result"] == {}
                    assert m.websocket_subscribers.value() == 1
            # connection closed: gauge drains
            for _ in range(100):
                if m.websocket_subscribers.value() == 0:
                    break
                await asyncio.sleep(0.01)
            assert m.websocket_subscribers.value() == 0
        finally:
            await _teardown(node, server)

    asyncio.run(run())


def test_tx_timeline_served_over_http():
    import aiohttp

    async def run():
        node, server, m = await _serve()
        base = f"http://127.0.0.1:{server.bound_port}"
        try:
            async with aiohttp.ClientSession() as s:
                payload = {"jsonrpc": "2.0", "id": 1,
                           "method": "broadcast_tx_sync",
                           "params": {"tx": "dGw9MQ=="}}  # "tl=1"
                async with s.post(base + "/", json=payload) as r:
                    assert (await r.json())["result"]["code"] == 0
                async with s.get(base + "/tx_timeline?limit=5") as r:
                    doc = (await r.json())["result"]
            assert doc["enabled"] is True and doc["active"] == 1
            assert m.request_seconds.count_value("tx_timeline", "ok") == 1
        finally:
            await _teardown(node, server)

    asyncio.run(run())


def test_slow_request_log_knob(caplog):
    import aiohttp

    async def run():
        node, server, m = await _serve()
        server.slow_ms = 0.000001  # everything is "slow"
        base = f"http://127.0.0.1:{server.bound_port}"
        try:
            with caplog.at_level(logging.WARNING, logger="tmtpu.rpc"):
                async with aiohttp.ClientSession() as s:
                    async with s.get(base + "/health") as r:
                        await r.json()
        finally:
            await _teardown(node, server)
        assert any("slow rpc health" in rec.message
                   for rec in caplog.records), caplog.records

    asyncio.run(run())


def test_disabled_metrics_server_still_serves():
    """metrics=None (a server wired outside a Node) must not cost or
    crash anything."""
    import aiohttp

    async def run():
        node = _stub_node()
        server = RPCServer(node)
        assert server.metrics is None
        await server.start("tcp://127.0.0.1:0")
        base = f"http://127.0.0.1:{server.bound_port}"
        try:
            async with aiohttp.ClientSession() as s:
                async with s.get(base + "/health") as r:
                    assert (await r.json())["result"] == {}
        finally:
            await _teardown(node, server)

    asyncio.run(run())
