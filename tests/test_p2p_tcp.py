"""TCP transport stack: SecretConnection (STS handshake, AEAD frames),
Merlin transcript, NodeInfo handshake, MConnection mux, Switch dial/accept —
and the 4-validator consensus net running over real sockets
(reference p2p/conn/secret_connection.go, p2p/transport.go, p2p/switch.go).
"""

import asyncio

import pytest

pytest.importorskip("cryptography", reason="needs the optional 'cryptography' package (absent in slim containers)")

from tendermint_tpu import crypto
from tendermint_tpu.libs.merlin import Transcript
from tendermint_tpu.p2p import (
    ChannelDescriptor,
    NetAddress,
    NodeInfo,
    NodeKey,
    Reactor,
    Switch,
    TCPTransport,
)
from tendermint_tpu.p2p.conn.secret_connection import SecretConnection


def test_merlin_transcript_matches_upstream_vector():
    """The canonical merlin test vector (merlin-rust transcript.rs): proves
    byte-compatibility with gtank/merlin used by the reference."""
    t = Transcript(b"test protocol")
    t.append_message(b"some label", b"some data")
    c = t.challenge_bytes(b"challenge", 32)
    assert c.hex() == \
        "d5a21972d0d5fe320c0d263fac7fffb8145aa640af6e9bca177c03c7efcf0615"


def _spawn_pair():
    """Two SecretConnections over a real localhost socket."""
    async def run():
        k1 = crypto.Ed25519PrivKey.generate(b"\x01" * 32)
        k2 = crypto.Ed25519PrivKey.generate(b"\x02" * 32)
        server_side = {}
        served = asyncio.Event()

        async def on_conn(reader, writer):
            server_side["sc"] = await SecretConnection.make(reader, writer, k2)
            served.set()

        server = await asyncio.start_server(on_conn, "127.0.0.1", 0)
        port = server.sockets[0].getsockname()[1]
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        sc1 = await SecretConnection.make(reader, writer, k1)
        await asyncio.wait_for(served.wait(), 5)
        sc2 = server_side["sc"]
        return k1, k2, sc1, sc2, server
    return run


def test_secret_connection_sts_and_frames():
    async def run():
        k1, k2, sc1, sc2, server = await _spawn_pair()()
        # mutual authentication
        assert sc1.remote_pubkey.bytes() == k2.pub_key().bytes()
        assert sc2.remote_pubkey.bytes() == k1.pub_key().bytes()
        # small message
        await sc1.write(b"hello")
        assert await sc2.read() == b"hello"
        # multi-frame message (> 1024)
        big = bytes(range(256)) * 17  # 4352 bytes
        await sc2.write(big)
        got = await sc1.read_exactly(len(big))
        assert got == big
        server.close()
    asyncio.run(run())


class EchoReactor(Reactor):
    CH = 0x77

    def __init__(self):
        super().__init__("ECHO")
        self.got = asyncio.Queue()

    def get_channels(self):
        return [ChannelDescriptor(self.CH, priority=5, send_queue_capacity=10)]

    async def receive(self, channel_id, peer, msg_bytes):
        await self.got.put((peer.id, msg_bytes))


def _mk_tcp_switch(seed: bytes, network: str = "test-net"):
    nk = NodeKey(crypto.Ed25519PrivKey.generate(seed))
    er = EchoReactor()
    info = NodeInfo(node_id=nk.id, network=network,
                    channels=bytes([EchoReactor.CH]))
    transport = TCPTransport(nk, info, er.get_channels())
    sw = Switch(nk.id, transport=transport)
    sw.add_reactor("ECHO", er)
    return sw, er, nk


def test_tcp_switch_dial_accept_and_mux():
    async def run():
        sw1, er1, nk1 = _mk_tcp_switch(b"\x11" * 32)
        sw2, er2, nk2 = _mk_tcp_switch(b"\x12" * 32)
        await sw1.start()
        await sw2.start()
        addr1 = await sw1.listen("127.0.0.1", 0)
        assert await sw2.dial_peer(addr1)
        # wait for sw1 to register the inbound peer
        for _ in range(100):
            if sw1.peers:
                break
            await asyncio.sleep(0.01)
        assert nk2.id in sw1.peers and nk1.id in sw2.peers

        # message both ways through the MConnection mux
        assert sw2.peers[nk1.id].try_send(EchoReactor.CH, b"ping-from-2")
        pid, msg = await asyncio.wait_for(er1.got.get(), 5)
        assert (pid, msg) == (nk2.id, b"ping-from-2")
        big = b"\xab" * 5000  # multi-packet message
        assert sw1.peers[nk2.id].try_send(EchoReactor.CH, big)
        pid, msg = await asyncio.wait_for(er2.got.get(), 5)
        assert (pid, msg) == (nk1.id, big)

        await sw2.stop()
        await sw1.stop()
    asyncio.run(run())


def test_tcp_rejects_network_mismatch():
    async def run():
        sw1, _, _ = _mk_tcp_switch(b"\x21" * 32, network="chain-A")
        sw2, _, nk2 = _mk_tcp_switch(b"\x22" * 32, network="chain-B")
        await sw1.start()
        await sw2.start()
        addr1 = await sw1.listen("127.0.0.1", 0)
        assert not await sw2.dial_peer(addr1)
        assert not sw2.peers
        await sw2.stop()
        await sw1.stop()
    asyncio.run(run())


def test_tcp_rejects_id_spoof():
    async def run():
        sw1, _, nk1 = _mk_tcp_switch(b"\x31" * 32)
        sw2, _, _ = _mk_tcp_switch(b"\x32" * 32)
        await sw1.start()
        await sw2.start()
        addr1 = await sw1.listen("127.0.0.1", 0)
        wrong = NetAddress("ab" * 20, addr1.host, addr1.port)
        assert not await sw2.dial_peer(wrong)
        await sw2.stop()
        await sw1.stop()
    asyncio.run(run())


def test_persistent_peer_reconnects():
    async def run():
        sw1, _, nk1 = _mk_tcp_switch(b"\x41" * 32)
        sw2, _, nk2 = _mk_tcp_switch(b"\x42" * 32)
        await sw1.start()
        await sw2.start()
        addr1 = await sw1.listen("127.0.0.1", 0)
        sw2.dial_peers_async([addr1], persistent=True)
        for _ in range(200):
            if nk1.id in sw2.peers:
                break
            await asyncio.sleep(0.01)
        assert nk1.id in sw2.peers

        # kill the connection from sw1's side; sw2 must redial
        await sw1.stop_peer_for_error(sw1.peers[nk2.id], "test kill")
        for _ in range(600):
            if nk2.id in sw1.peers and nk1.id in sw2.peers:
                break
            await asyncio.sleep(0.01)
        assert nk1.id in sw2.peers, "persistent peer did not reconnect"
        await sw2.stop()
        await sw1.stop()
    asyncio.run(run())


def test_four_validator_consensus_over_tcp():
    """VERDICT task 4 done-criterion: the consensus net runs over real TCP
    sockets (SecretConnection + MConnection), not just in-proc."""
    from tests.test_consensus_net import Node, make_net, wait_all_height

    async def run():
        nodes = make_net(4)
        switches = []
        for i, nd in enumerate(nodes):
            nk = NodeKey(crypto.Ed25519PrivKey.generate(bytes([0x90 + i]) * 32))
            descs = []
            for r in nd.switch.reactors.values():
                descs.extend(r.get_channels())
            info = NodeInfo(node_id=nk.id, network="net-chain",
                            channels=bytes(d.id for d in descs))
            transport = TCPTransport(nk, info, descs)
            sw = Switch(nk.id, transport=transport)
            # re-register the same reactor objects on the TCP switch
            for name, r in nd.switch.reactors.items():
                r.switch = None
                sw.add_reactor(name, r)
            nd.switch = sw
            switches.append(sw)
        addrs = []
        for nd in nodes:
            await nd.switch.start()
            addrs.append(await nd.switch.listen("127.0.0.1", 0))
        for nd in nodes:
            await nd.cs.start()
        # full mesh dial
        for i, nd in enumerate(nodes):
            nd.switch.dial_peers_async(addrs[:i], persistent=True)
        try:
            await wait_all_height(nodes, 3, timeout=60.0)
        finally:
            for nd in nodes:
                await nd.cs.stop()
                await nd.switch.stop()
        heights = [nd.cs.state.last_block_height for nd in nodes]
        assert min(heights) >= 3, heights
        hashes = {nd.block_store.load_block_meta(2).header.hash() for nd in nodes}
        assert len(hashes) == 1
    asyncio.run(run())


def test_fuzzed_connection_drops_and_passes():
    """FuzzedConnection (reference p2p/fuzz.go): probabilistic write drops;
    prob 0 passes everything, prob 1 drops everything silently."""
    from tendermint_tpu.p2p.fuzz import FuzzConnConfig, FuzzedConnection

    async def run():
        _k1, _k2, sc1, sc2, server = await _spawn_pair()()
        # prob 0: transparent
        f0 = FuzzedConnection(sc1, FuzzConnConfig(prob_drop_rw=0.0, seed=1))
        await f0.write(b"pass")
        assert await sc2.read() == b"pass"
        # prob 1: every write silently dropped
        f1 = FuzzedConnection(sc1, FuzzConnConfig(prob_drop_rw=1.0, seed=1))
        await f1.write(b"dropped")
        assert f1.dropped_writes == 1
        await f0.write(b"after")   # the transport itself is still healthy
        assert await sc2.read() == b"after"
        server.close()
    asyncio.run(run())
