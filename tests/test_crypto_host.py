"""Host ed25519 + merkle tests: RFC 8032 vectors, OpenSSL cross-check,
adversarial acceptance cases (the spec the TPU path must match)."""

import hashlib
import os
import random

import pytest

from tendermint_tpu.crypto import (
    Ed25519PrivKey,
    Ed25519PubKey,
    address_hash,
)
from tendermint_tpu.crypto import ed25519 as ed
from tendermint_tpu.crypto import merkle

# (seed, pub, msg, sig) — RFC 8032 §7.1 TEST 1-3
RFC8032_VECTORS = [
    (
        "9d61b19deffd5a60ba844af492ec2cc44449c5697b326919703bac031cae7f60",
        "d75a980182b10ab7d54bfed3c964073a0ee172f3daa62325af021a68f707511a",
        "",
        "e5564300c360ac729086e2cc806e828a84877f1eb8e5d974d873e065224901555fb8821590a33bacc61e39701cf9b46bd25bf5f0595bbe24655141438e7a100b",
    ),
    (
        "4ccd089b28ff96da9db6c346ec114e0f5b8a319f35aba624da8cf6ed4fb8a6fb",
        "3d4017c3e843895a92b70aa74d1b7ebc9c982ccf2ec4968cc0cd55f12af4660c",
        "72",
        "92a009a9f0d4cab8720e820b5f642540a2b27b5416503f8fb3762223ebdb69da085ac1e43e15996e458f3613d0f11d8c387b2eaeb4302aeeb00d291612bb0c00",
    ),
    (
        "c5aa8df43f9f837bedb7442f31dcb7b166d38535076f094b85ce3a2e0b4458f7",
        "fc51cd8e6218a1a38da47ed00230f0580816ed13ba3303ac5deb911548908025",
        "af82",
        "6291d657deec24024827e69c3abe01a30ce548a284743a445e3680d7db5ac3ac18ff9b538d16f290ae67f760984dc6594a7c15e9716ed28dc027beceea1ec40a",
    ),
]


@pytest.mark.parametrize("seed,pub,msg,sig", RFC8032_VECTORS)
def test_rfc8032_vectors(seed, pub, msg, sig):
    seed, pub, msg, sig = (bytes.fromhex(x) for x in (seed, pub, msg, sig))
    assert ed.pubkey_from_seed(seed) == pub
    assert ed.sign(seed + pub, msg) == sig
    assert ed.verify(pub, msg, sig)
    assert not ed.verify(pub, msg + b"x", sig)


def test_sign_verify_roundtrip_random():
    rng = random.Random(7)
    for _ in range(20):
        priv, pub = ed.keygen(bytes(rng.randrange(256) for _ in range(32)))
        msg = bytes(rng.randrange(256) for _ in range(rng.randrange(0, 200)))
        sig = ed.sign(priv, msg)
        assert ed.verify(pub, msg, sig)
        bad = bytearray(sig)
        bad[rng.randrange(64)] ^= 1 << rng.randrange(8)
        assert not ed.verify(pub, msg, bytes(bad))


def test_cross_check_openssl():
    cryptography = pytest.importorskip("cryptography")
    from cryptography.hazmat.primitives.asymmetric.ed25519 import Ed25519PrivateKey

    rng = random.Random(13)
    for _ in range(10):
        seed = bytes(rng.randrange(256) for _ in range(32))
        osk = Ed25519PrivateKey.from_private_bytes(seed)
        opub = osk.public_key().public_bytes_raw()
        msg = bytes(rng.randrange(256) for _ in range(50))
        osig = osk.sign(msg)
        assert ed.pubkey_from_seed(seed) == opub
        assert ed.sign(seed + opub, msg) == osig
        assert ed.verify(opub, msg, osig)


def test_rejects_noncanonical_s():
    priv, pub = ed.keygen(b"\x01" * 32)
    msg = b"hello"
    sig = ed.sign(priv, msg)
    s = int.from_bytes(sig[32:], "little")
    bad = sig[:32] + (s + ed.L).to_bytes(32, "little")
    assert not ed.verify(pub, msg, bad)
    # also via the PubKey interface (OpenSSL path must agree)
    assert not Ed25519PubKey(pub).verify_signature(msg, bad)
    assert Ed25519PubKey(pub).verify_signature(msg, sig)


def test_rejects_noncanonical_pubkey():
    # y >= p: craft encoding of p+3 (y=p+3 is < 2^255, not a canonical field elt)
    bad_pub = (ed.P + 3).to_bytes(32, "little")
    assert not ed.verify(bad_pub, b"m", b"\x00" * 64)
    assert not Ed25519PubKey(bad_pub).verify_signature(b"m", b"\x00" * 64)


def test_rejects_off_curve_pubkey():
    # find a y whose x^2 has no root
    y = 2
    while True:
        enc = y.to_bytes(32, "little")
        if ed._pt_decode(enc) is None:
            break
        y += 1
    assert not ed.verify(enc, b"m", b"\x00" * 64)


def test_pubkey_interface_matches_reference_shapes():
    pk = Ed25519PrivKey.generate(b"\x02" * 32)
    pub = pk.pub_key()
    assert len(pk.bytes()) == 64
    assert len(pub.bytes()) == 32
    assert pub.address() == hashlib.sha256(pub.bytes()).digest()[:20]
    sig = pk.sign(b"msg")
    assert len(sig) == 64
    assert pub.verify_signature(b"msg", sig)


# --- merkle ----------------------------------------------------------------

def test_merkle_empty_and_single():
    assert merkle.hash_from_byte_slices([]) == hashlib.sha256(b"").digest()
    item = b"tx1"
    assert merkle.hash_from_byte_slices([item]) == hashlib.sha256(b"\x00" + item).digest()


def test_merkle_two():
    a, b = b"a", b"b"
    la = hashlib.sha256(b"\x00" + a).digest()
    lb = hashlib.sha256(b"\x00" + b).digest()
    assert merkle.hash_from_byte_slices([a, b]) == hashlib.sha256(b"\x01" + la + lb).digest()


@pytest.mark.parametrize("n", [1, 2, 3, 5, 8, 13, 100])
def test_merkle_proofs(n):
    items = [f"item{i}".encode() for i in range(n)]
    root = merkle.hash_from_byte_slices(items)
    proofs = merkle.proofs_from_byte_slices(items)
    assert len(proofs) == n
    for i, pr in enumerate(proofs):
        assert pr.verify(root, items[i])
        if n > 1:
            assert not pr.verify(root, items[(i + 1) % n])
        assert not pr.verify(os.urandom(32), items[i])


def test_secp256k1_sign_verify_address():
    """secp256k1 key type (reference crypto/secp256k1): 33B compressed pub,
    RIPEMD160(SHA256(pub)) address, 64B low-S signatures."""
    import pytest

    pytest.importorskip("cryptography", reason="needs the optional 'cryptography' package (absent in slim containers)")
    from tendermint_tpu.crypto.secp256k1 import (
        Secp256k1PrivKey,
        Secp256k1PubKey,
        _N,
    )

    priv = Secp256k1PrivKey.generate(b"determinism")
    pub = priv.pub_key()
    assert len(pub.bytes()) == 33 and pub.bytes()[0] in (2, 3)
    assert len(pub.address()) == 20

    sig = priv.sign(b"hello")
    assert len(sig) == 64
    assert pub.verify_signature(b"hello", sig)
    assert not pub.verify_signature(b"hello!", sig)
    assert not pub.verify_signature(b"hello", sig[:-1] + b"\x00")

    # high-S malleated twin must be rejected (btcec convention)
    import hashlib
    r = int.from_bytes(sig[:32], "big")
    s = int.from_bytes(sig[32:], "big")
    high_s = _N - s
    mall = r.to_bytes(32, "big") + high_s.to_bytes(32, "big")
    assert not pub.verify_signature(b"hello", mall)

    # round-trip through bytes
    pub2 = Secp256k1PubKey(pub.bytes())
    assert pub2.verify_signature(b"hello", sig)
    assert pub2.address() == pub.address()

    # deterministic generate from seed
    assert Secp256k1PrivKey.generate(b"determinism").bytes() == priv.bytes()
