"""VoteSet tally semantics (reference types/vote_set.go): dedup, conflicts,
2/3 majority, peer maj23, MakeCommit."""

import pytest

from tendermint_tpu import crypto
from tendermint_tpu.types import (
    BlockID,
    PartSetHeader,
    SignedMsgType,
    ValidatorSet,
    Vote,
    VoteSet,
)
from tendermint_tpu.types.errors import ErrVoteConflictingVotes
from tendermint_tpu.types.validator import new_validator

CHAIN_ID = "test_chain_id"
BID = BlockID(b"\x01" * 32, PartSetHeader(1, b"\x02" * 32))
OTHER = BlockID(b"\x03" * 32, PartSetHeader(1, b"\x04" * 32))


@pytest.fixture
def net():
    privs = [crypto.Ed25519PrivKey.generate(bytes([i + 1]) * 32) for i in range(4)]
    vals = [new_validator(p.pub_key(), 10) for p in privs]
    vs = ValidatorSet(vals)
    by_addr = {p.pub_key().address(): p for p in privs}
    ordered = [by_addr[v.address] for v in vs.validators]
    return vs, ordered


def mk_vote(vs, privs, idx, block_id, ts=1_700_000_000_000_000_000,
            type_=SignedMsgType.PRECOMMIT, height=1, round_=0):
    val = vs.validators[idx]
    v = Vote(type_, height, round_, block_id, ts, val.address, idx)
    v.signature = privs[idx].sign(v.sign_bytes(CHAIN_ID))
    return v


def test_two_thirds_majority(net):
    vs, privs = net
    voteset = VoteSet(CHAIN_ID, 1, 0, SignedMsgType.PRECOMMIT, vs)
    for i in range(2):
        assert voteset.add_vote(mk_vote(vs, privs, i, BID))
    assert not voteset.has_two_thirds_majority()
    assert voteset.add_vote(mk_vote(vs, privs, 2, BID))
    maj, ok = voteset.two_thirds_majority()
    assert ok and maj == BID


def test_duplicate_vote_not_added(net):
    vs, privs = net
    voteset = VoteSet(CHAIN_ID, 1, 0, SignedMsgType.PRECOMMIT, vs)
    v = mk_vote(vs, privs, 0, BID)
    assert voteset.add_vote(v)
    assert voteset.add_vote(v) is False


def test_conflicting_vote_raises(net):
    vs, privs = net
    voteset = VoteSet(CHAIN_ID, 1, 0, SignedMsgType.PRECOMMIT, vs)
    assert voteset.add_vote(mk_vote(vs, privs, 0, BID))
    with pytest.raises(ErrVoteConflictingVotes):
        voteset.add_vote(mk_vote(vs, privs, 0, OTHER))


def test_bad_signature_rejected(net):
    vs, privs = net
    voteset = VoteSet(CHAIN_ID, 1, 0, SignedMsgType.PRECOMMIT, vs)
    v = mk_vote(vs, privs, 0, BID)
    v.signature = bytes([v.signature[0] ^ 1]) + v.signature[1:]
    from tendermint_tpu.types.errors import ErrVoteInvalidSignature

    with pytest.raises(ErrVoteInvalidSignature):
        voteset.add_vote(v)


def test_nil_votes_count_toward_any_but_not_block(net):
    vs, privs = net
    voteset = VoteSet(CHAIN_ID, 1, 0, SignedMsgType.PRECOMMIT, vs)
    for i in range(3):
        voteset.add_vote(mk_vote(vs, privs, i, BlockID()))
    assert voteset.has_two_thirds_any()
    maj, ok = voteset.two_thirds_majority()
    assert ok and maj.is_zero()  # 2/3 for nil IS a majority decision (for nil)


def test_make_commit_excludes_other_block_sigs(net):
    vs, privs = net
    voteset = VoteSet(CHAIN_ID, 1, 0, SignedMsgType.PRECOMMIT, vs)
    for i in range(3):
        voteset.add_vote(mk_vote(vs, privs, i, BID))
    # validator 3 voted for another block — conflicting with nothing (first vote)
    voteset.add_vote(mk_vote(vs, privs, 3, OTHER))
    commit = voteset.make_commit()
    assert commit.block_id == BID
    assert commit.signatures[3].absent()
    assert sum(1 for s in commit.signatures if s.for_block()) == 3
    # commit verifies against the set
    vs.verify_commit(CHAIN_ID, BID, 1, commit)


def test_peer_maj23_tracks_conflicting_block(net):
    vs, privs = net
    voteset = VoteSet(CHAIN_ID, 1, 0, SignedMsgType.PRECOMMIT, vs)
    voteset.set_peer_maj23("peer1", OTHER)
    # conflicting second vote for OTHER is now tracked (peer claims maj23)
    assert voteset.add_vote(mk_vote(vs, privs, 0, BID))
    with pytest.raises(ErrVoteConflictingVotes):
        voteset.add_vote(mk_vote(vs, privs, 0, OTHER))
    # the vote was recorded under OTHER despite the conflict
    ba = voteset.bit_array_by_block_id(OTHER)
    assert ba is not None and ba.get_index(0)


def test_wrong_height_rejected(net):
    vs, privs = net
    voteset = VoteSet(CHAIN_ID, 1, 0, SignedMsgType.PRECOMMIT, vs)
    from tendermint_tpu.types.vote_set import VoteSetError

    with pytest.raises(VoteSetError):
        voteset.add_vote(mk_vote(vs, privs, 0, BID, height=2))
