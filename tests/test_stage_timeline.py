"""Per-height consensus stage timeline (consensus/timeline.py): monotonic
marks across a multi-round height, ring bounds, metrics + trace emission,
and the real single-validator state machine populating it end-to-end."""

import asyncio

from tendermint_tpu.consensus.timeline import STAGES, StageTimeline
from tendermint_tpu.libs.metrics import ConsensusMetrics, Registry
from tendermint_tpu.libs.trace import tracer


def _drive_height(tl, h, round_=0):
    tl.begin_height(h)
    tl.note_wire_proposal(h)
    for stage in STAGES:
        tl.mark(h, round_, stage)


def test_marks_monotonic_and_durations_sum():
    tl = StageTimeline()
    _drive_height(tl, 5)
    (rec,) = tl.tail(10)
    assert rec["height"] == 5 and rec["sealed"]
    # marks are wall-clock monotonic in arrival order
    times = [t for _, _, t in rec["marks"]]
    assert times == sorted(times)
    # every stage present, each duration >= 0, and the chain of stage
    # intervals never exceeds the height total (allow half-ulp-per-stage
    # rounding accumulation: each duration rounds to 1e-6 independently)
    assert set(rec["durations"]) == set(STAGES)
    assert all(d >= 0 for d in rec["durations"].values())
    assert sum(rec["durations"].values()) <= \
        rec["total_s"] + 1e-6 * (len(rec["durations"]) + 1)
    # the reactor's wire mark rides along without entering the durations
    assert ["proposal_wire"] == [m[0] for m in rec["marks"]
                                 if m[0] not in STAGES]


def test_multi_round_height_last_mark_wins():
    tl = StageTimeline()
    tl.begin_height(7)
    # round 0 gets a proposal and a prevote, then dies; round 2 commits
    tl.mark(7, 0, "proposal_received")
    tl.mark(7, 0, "prevote_sent")
    tl.mark(7, 2, "proposal_received")
    tl.mark(7, 2, "prevote_sent")
    tl.mark(7, 2, "prevote_quorum")
    tl.mark(7, 2, "precommit_sent")
    tl.mark(7, 2, "precommit_quorum")
    tl.mark(7, 2, "commit_finalized")
    (rec,) = tl.tail(1)
    assert rec["round"] == 2
    # both rounds' marks are retained in arrival order...
    assert [m[1] for m in rec["marks"] if m[0] == "proposal_received"] \
        == [0, 2]
    # ...and still monotonic across the round change
    times = [t for _, _, t in rec["marks"]]
    assert times == sorted(times)
    assert set(rec["durations"]) == set(STAGES)


def test_ring_bounded_and_unsealed_heights_pushed():
    tl = StageTimeline(capacity=8)
    for h in range(1, 20):
        _drive_height(tl, h)
    assert len(tl.tail(100)) == 8
    assert [r["height"] for r in tl.tail(3)] == [17, 18, 19]
    assert tl.heights_sealed == 19
    # a height overtaken without commit (fast sync) lands unsealed
    tl.begin_height(30)
    tl.mark(30, 0, "proposal_received")
    tl.begin_height(31)
    rec = tl.tail(1)[0]
    assert rec["height"] == 30 and not rec["sealed"]
    assert "durations" not in rec
    # stale marks for an older height are ignored
    tl.mark(30, 0, "prevote_sent")
    assert tl.snapshot()["current"]["height"] == 31


def test_metrics_emission_on_seal():
    tl = StageTimeline()
    m = ConsensusMetrics(Registry())
    tl.metrics = m
    _drive_height(tl, 2)
    _drive_height(tl, 3)
    for stage in STAGES:
        assert m.stage_seconds.count_value(stage) == 2, stage
        assert m.stage_seconds.sum_value(stage) >= 0.0
    text = "\n".join(m.stage_seconds.render())
    assert 'tendermint_consensus_stage_seconds_bucket' in text
    assert 'stage="commit_finalized"' in text


def test_trace_spans_emitted_on_seal():
    tl = StageTimeline()
    tracer.clear()
    tracer.enable()
    try:
        _drive_height(tl, 9)
    finally:
        tracer.disable()
    stage_events = [e for e in tracer.events()
                    if e["name"].startswith("stage_")]
    tracer.clear()
    assert [e["name"] for e in stage_events] == \
        [f"stage_{s}" for s in STAGES]
    for e in stage_events:
        assert e["ph"] == "X" and e["dur"] >= 0
        assert e["args"]["height"] == 9
    # spans tile the height: each starts where the previous ended
    for a, b in zip(stage_events, stage_events[1:]):
        assert abs((a["ts"] + a["dur"]) - b["ts"]) < 1.0  # us


def test_snapshot_shape_and_limit():
    tl = StageTimeline()
    for h in range(1, 6):
        _drive_height(tl, h)
    tl.begin_height(6)
    tl.mark(6, 0, "proposal_received")
    snap = tl.snapshot(limit=2)
    assert snap["heights_sealed"] == 5
    assert [r["height"] for r in snap["heights"]] == [4, 5]
    assert snap["current"]["height"] == 6 and not snap["current"]["sealed"]
    import json

    json.dumps(snap)  # RPC/debugdump contract: JSON-safe as-is


def test_four_node_net_stage_histograms_all_six_stages():
    """The acceptance shape, in-process: a real 4-validator net must put
    tendermint_consensus_stage_seconds{stage} observations on every node's
    registry for all six stages, and non-proposer nodes must additionally
    carry the reactor's proposal_wire mark."""
    from test_consensus_net import make_net, wait_all_height

    from tendermint_tpu.p2p import InProcNetwork

    async def run():
        nodes = make_net(4)
        metrics = []
        for nd in nodes:
            m = ConsensusMetrics(Registry())
            nd.cs.timeline.metrics = m
            metrics.append(m)
        net = InProcNetwork()
        for nd in nodes:
            net.add_switch(nd.switch)
        for nd in nodes:
            await nd.start()
        await net.connect_all()
        try:
            await wait_all_height(nodes, 3)
        finally:
            for nd in nodes:
                await nd.stop()
        wire_marks = 0
        for nd, m in zip(nodes, metrics):
            text = "\n".join(m.stage_seconds.render())
            for stage in STAGES:
                assert m.stage_seconds.count_value(stage) >= 2, \
                    (nd.idx, stage)
                assert f'stage="{stage}"' in text
            wire_marks += sum(
                1 for rec in nd.cs.timeline.tail(100)
                for mk in rec["marks"] if mk[0] == "proposal_wire")
        # proposals reach 3 of 4 nodes over the wire each height
        assert wire_marks > 0

    asyncio.run(run())


def test_single_validator_chain_populates_timeline():
    """The real state machine end-to-end: marks land at the right stages
    and the sealed records carry a full commit decomposition."""
    from test_consensus_single import build_node, wait_for_height

    async def run():
        cs, mempool, app, event_bus, pv, _ = build_node()
        m = ConsensusMetrics(Registry())
        cs.timeline.metrics = m
        await cs.start()
        try:
            mempool.check_tx(b"tl=1")
            await wait_for_height(event_bus, cs, 3)
        finally:
            await cs.stop()
        recs = [r for r in cs.timeline.tail(100) if r["sealed"]]
        assert len(recs) >= 2
        for rec in recs:
            stages = {mk[0] for mk in rec["marks"]}
            # a single validator proposes to itself: every stage fires
            # (proposal_received via the internal ProposalMessage path)
            assert {"proposal_received", "prevote_sent", "prevote_quorum",
                    "precommit_sent", "precommit_quorum",
                    "commit_finalized"} <= stages
            times = [t for _, _, t in rec["marks"]]
            assert times == sorted(times)
            assert rec["total_s"] >= 0
        assert m.stage_seconds.count_value("commit_finalized") == len(recs)

    asyncio.run(run())


def test_disabled_timeline_records_nothing():
    """WAL catchup replay (consensus/replay.py) disables the timeline:
    replayed messages arrive microseconds apart and would seal one garbage
    stage_seconds record per restart."""
    tl = StageTimeline()
    m = ConsensusMetrics(Registry())
    tl.metrics = m
    tl.enabled = False
    _drive_height(tl, 3)
    assert tl.tail(10) == [] and tl.heights_sealed == 0
    assert m.stage_seconds.count_value("commit_finalized") == 0
    # re-enabled (replay done): the first live mark opens a fresh record
    tl.enabled = True
    tl.mark(3, 1, "precommit_quorum")
    tl.mark(3, 1, "commit_finalized")
    (rec,) = tl.tail(10)
    assert rec["sealed"] and rec["height"] == 3
    assert set(rec["durations"]) == {"precommit_quorum", "commit_finalized"}
