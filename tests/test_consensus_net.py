"""In-process multi-validator consensus networks (the reference's key test
trick, consensus/common_test.go + reactor_test.go: N real state machines over
a mock transport, no TCP).
"""

import asyncio

import pytest

from tendermint_tpu import crypto
from tendermint_tpu.abci.example.kvstore import KVStoreApplication
from tendermint_tpu.consensus import ConsensusState
from tendermint_tpu.consensus.config import test_consensus_config
from tendermint_tpu.consensus.reactor import ConsensusReactor
from tendermint_tpu.consensus.replay import Handshaker
from tendermint_tpu.libs.db import MemDB
from tendermint_tpu.mempool import CListMempool
from tendermint_tpu.mempool.reactor import MempoolReactor
from tendermint_tpu.p2p import InProcNetwork, Switch
from tendermint_tpu.proxy import AppConns, local_client_creator
from tendermint_tpu.state import BlockExecutor, StateStore, state_from_genesis
from tendermint_tpu.state.execution import EmptyEvidencePool
from tendermint_tpu.store import BlockStore
from tendermint_tpu.types import GenesisDoc, GenesisValidator, MockPV
from tendermint_tpu.types.event_bus import EventBus
from tendermint_tpu.types import events as tme

CHAIN_ID = "net-chain"


class Node:
    def __init__(self, idx, pv, genesis):
        self.idx = idx
        self.pv = pv
        self.app = KVStoreApplication()
        self.conns = AppConns(local_client_creator(self.app))
        self.conns.start()
        self.state_store = StateStore(MemDB())
        self.block_store = BlockStore(MemDB())
        state = state_from_genesis(genesis)
        state = Handshaker(self.state_store, state, self.block_store,
                           genesis).handshake(self.conns.consensus, self.conns.query)
        self.state_store.save(state)
        self.mempool = CListMempool(self.conns.mempool)
        self.event_bus = EventBus()
        self.block_exec = BlockExecutor(self.state_store, self.conns.consensus,
                                        self.mempool, EmptyEvidencePool(),
                                        self.block_store, self.event_bus)
        self.cs = ConsensusState(test_consensus_config(), state, self.block_exec,
                                 self.block_store)
        self.cs.set_priv_validator(pv)
        self.cs.set_event_bus(self.event_bus)
        self.mempool.tx_available_callbacks.append(self.cs.notify_txs_available)
        self.switch = Switch(f"node{idx}")
        self.cs_reactor = ConsensusReactor(self.cs)
        self.switch.add_reactor("CONSENSUS", self.cs_reactor)
        self.mp_reactor = MempoolReactor(self.mempool, gossip_sleep=0.005)
        self.switch.add_reactor("MEMPOOL", self.mp_reactor)

    async def start(self):
        await self.switch.start()
        await self.cs.start()

    async def stop(self):
        await self.cs.stop()
        await self.switch.stop()


def make_net(n):
    pvs = [MockPV(crypto.Ed25519PrivKey.generate(bytes([0x60 + i]) * 32))
           for i in range(n)]
    genesis = GenesisDoc(
        chain_id=CHAIN_ID, genesis_time_ns=1_700_000_000_000_000_000,
        validators=[GenesisValidator(pv.get_pub_key(), 10) for pv in pvs])
    nodes = [Node(i, pv, genesis) for i, pv in enumerate(pvs)]
    return nodes


async def wait_all_height(nodes, height, timeout=30.0):
    async def one(node):
        sub = node.event_bus.subscribe("netwait", tme.QUERY_NEW_BLOCK)
        try:
            while node.cs.state.last_block_height < height:
                await sub.next()
        finally:
            node.event_bus.unsubscribe_all("netwait")

    await asyncio.wait_for(asyncio.gather(*(one(nd) for nd in nodes)), timeout)


def test_four_validator_net_makes_progress():
    async def run():
        nodes = make_net(4)
        net = InProcNetwork()
        for nd in nodes:
            net.add_switch(nd.switch)
        for nd in nodes:
            await nd.start()
        await net.connect_all()
        try:
            await wait_all_height(nodes, 3)
        finally:
            for nd in nodes:
                await nd.stop()
        heights = [nd.cs.state.last_block_height for nd in nodes]
        assert min(heights) >= 3, heights
        # all nodes agree on block 2's hash
        hashes = {nd.block_store.load_block_meta(2).header.hash() for nd in nodes}
        assert len(hashes) == 1

    asyncio.run(run())


def test_tx_gossip_and_commit_all_nodes():
    async def run():
        nodes = make_net(4)
        net = InProcNetwork()
        for nd in nodes:
            net.add_switch(nd.switch)
        for nd in nodes:
            await nd.start()
        await net.connect_all()
        try:
            # submit the tx at ONE node; gossip must spread it, consensus commit it
            nodes[2].mempool.check_tx(b"gossip=works")
            deadline = asyncio.get_event_loop().time() + 30
            while True:
                if all(nd.app.state.get("gossip") == "works" for nd in nodes):
                    break
                if asyncio.get_event_loop().time() > deadline:
                    raise AssertionError(
                        f"tx not committed everywhere: "
                        f"{[nd.app.state for nd in nodes]}")
                await asyncio.sleep(0.05)
        finally:
            for nd in nodes:
                await nd.stop()

    asyncio.run(run())


def test_progress_with_one_node_down():
    async def run():
        # 4 validators, one never starts: 3/4 = 75% > 2/3 → progress
        nodes = make_net(4)
        net = InProcNetwork()
        live = nodes[:3]
        for nd in live:
            net.add_switch(nd.switch)
        for nd in live:
            await nd.start()
        await net.connect_all()
        try:
            await wait_all_height(live, 2, timeout=60)
        finally:
            for nd in live:
                await nd.stop()
        assert all(nd.cs.state.last_block_height >= 2 for nd in live)

    asyncio.run(run())


def test_late_node_catches_up():
    async def run():
        nodes = make_net(4)
        net = InProcNetwork()
        late = nodes[3]
        for nd in nodes[:3]:
            net.add_switch(nd.switch)
        for nd in nodes[:3]:
            await nd.start()
        await net.connect_all()
        try:
            await wait_all_height(nodes[:3], 3, timeout=60)
            # now bring in the late node: catchup gossip must feed it old
            # block parts + commit votes
            net.add_switch(late.switch)
            await late.start()
            for other in nodes[:3]:
                await net.connect(late.switch.node_id, other.switch.node_id)
            await wait_all_height([late], 3, timeout=60)
        finally:
            for nd in nodes:
                await nd.stop()
        assert late.cs.state.last_block_height >= 3

    asyncio.run(run())


def test_vote_path_takes_device_batches():
    """VERDICT task 2 counter-assertion: with a low device threshold, the
    gossiped-vote hot loop must provably verify on the batched device path
    (device_sigs > 0) and the single-writer loop must consume cached
    verdicts (cache_hits > 0), while consensus still makes progress."""
    # Proves the HOT LOOP #1 plumbing end-to-end: concurrent preverify
    # calls micro-batch onto the device kernel, and the single-writer-side
    # VoteSet.add_vote consumes cached verdicts without re-verifying.
    # (A full 4-node net with a forced device threshold is not viable under
    # CPU-XLA — one kernel execution outlasts the test consensus timeouts —
    # but the reactor wiring exercised by the net tests above routes through
    # exactly this verifier; on real TPU hardware the device path engages
    # whenever >= min_device_batch votes are pending.)
    from tendermint_tpu.crypto.vote_batcher import BatchVoteVerifier
    from tendermint_tpu.types import Validator, ValidatorSet
    from tendermint_tpu.types.basic import BlockID, PartSetHeader, SignedMsgType
    from tendermint_tpu.types.vote import Vote
    from tendermint_tpu.types.vote_set import VoteSet

    n = 4
    pvs = [MockPV(crypto.Ed25519PrivKey.generate(bytes([0x70 + i]) * 32))
           for i in range(n)]
    val_set = ValidatorSet([Validator(pv.get_pub_key().address(), pv.get_pub_key(), 10)
                            for pv in pvs])
    # device_timeout_s far above first-call tracing time: this test asserts
    # ROUTING (the flush must ride the device), not the liveness fallback —
    # that is covered by test_vote_batcher_liveness.py
    verifier = BatchVoteVerifier(min_device_batch=2, deadline_s=0.02,
                                 device_timeout_s=600.0)
    vote_set = VoteSet(CHAIN_ID, 5, 0, SignedMsgType.PRECOMMIT, val_set,
                       verifier=verifier)
    bid = BlockID(b"\x11" * 32, PartSetHeader(1, b"\x22" * 32))
    votes = []
    for i, pv in enumerate(pvs):
        addr = pv.get_pub_key().address()
        idx, _val = val_set.get_by_address(addr)
        vote = Vote(SignedMsgType.PRECOMMIT, 5, 0, bid,
                    1_700_000_000_000_000_000 + i, addr, idx, b"")
        pv.sign_vote(CHAIN_ID, vote)
        votes.append(vote)

    async def run():
        # concurrent preverify (what the per-peer reactor tasks do)
        results = await asyncio.gather(*(
            verifier.preverify(val_set.validators[v.validator_index].pub_key,
                               v.sign_bytes(CHAIN_ID), v.signature)
            for v in votes))
        assert all(results)
        # single-writer side: add_vote must consume cached verdicts
        for v in votes:
            assert vote_set.add_vote(v)

    asyncio.run(run())
    assert verifier.stats["device_batches"] >= 1, dict(verifier.stats)
    assert verifier.stats["device_sigs"] == n, dict(verifier.stats)
    assert verifier.stats["cache_hits"] == n, dict(verifier.stats)
    assert verifier.stats["sync_host_sigs"] == 0, dict(verifier.stats)
    assert vote_set.has_two_thirds_majority()


def test_byzantine_double_prevote_produces_evidence():
    """Maverick-style byzantine hook (reference test/maverick/consensus/
    misbehavior.go double-prevote): one validator equivocates at height 2;
    honest nodes detect the conflicting votes, pool DuplicateVoteEvidence,
    and commit it in a block — while the chain keeps making progress."""
    from tendermint_tpu.types.evidence import DuplicateVoteEvidence

    async def run():
        nodes = make_net(4)
        nodes[0].cs.misbehaviors[2] = "double-prevote"
        # evidence needs real pools: swap EmptyEvidencePool for real ones
        from tendermint_tpu.evidence.pool import EvidencePool
        from tendermint_tpu.libs.db import MemDB

        for nd in nodes:
            pool = EvidencePool(MemDB(), nd.state_store, nd.block_store)
            nd.cs.evpool = pool
            nd.block_exec.evpool = pool
            nd.evidence_pool = pool
        net = InProcNetwork()
        for nd in nodes:
            net.add_switch(nd.switch)
        for nd in nodes:
            await nd.start()
        await net.connect_all()
        byz_addr = nodes[0].pv.get_pub_key().address()

        def evidence_committed():
            # some honest node committed the duplicate-vote evidence
            for nd in nodes[1:]:
                for h in range(2, nd.block_store.height() + 1):
                    blk = nd.block_store.load_block(h)
                    for ev in (blk.evidence if blk else []):
                        if isinstance(ev, DuplicateVoteEvidence):
                            assert ev.vote_a.validator_address == byz_addr
                            return True
            return False

        try:
            # enough heights for gossip to surface the conflict and for the
            # next proposer to include the pooled evidence — WHICH height
            # that is varies with timing, so wait for the commit itself
            # rather than racing a fixed height
            await wait_all_height(nodes, 8, timeout=90.0)
            deadline = asyncio.get_running_loop().time() + 90.0
            while not evidence_committed():
                if asyncio.get_running_loop().time() > deadline:
                    break
                await asyncio.sleep(0.25)
        finally:
            for nd in nodes:
                await nd.stop()
        assert evidence_committed(), "duplicate-vote evidence never committed"

    asyncio.run(run())
