"""Byzantine commit-metrics accounting (reference state.go recordMetrics):
the gauges count EQUIVOCATING VALIDATORS and their power — DuplicateVote
evidence resolves the validator through the current set, LightClientAttack
carries its list, and a validator appearing in several items counts once."""

import numpy as np
import pytest

pytest.importorskip("cryptography", reason="needs the optional 'cryptography' package (absent in slim containers)")

from cryptography.hazmat.primitives.asymmetric.ed25519 import Ed25519PrivateKey

from tendermint_tpu import crypto
from tendermint_tpu.types import Validator, ValidatorSet


def _mk_vs(n=4, seed=2):
    rng = np.random.default_rng(seed)
    vals = []
    for i in range(n):
        sk = Ed25519PrivateKey.from_private_bytes(
            bytes(rng.integers(0, 256, 32, dtype=np.uint8)))
        pub = crypto.Ed25519PubKey(sk.public_key().public_bytes_raw())
        vals.append(Validator(pub.address(), pub, 10 * (i + 1)))
    return ValidatorSet(vals)


class _Gauge:
    def __init__(self):
        self.value = None

    def set(self, v):
        self.value = v


def _run_metrics_block(vs, evidence):
    """Drive ConsensusState._record_commit_metrics's evidence accounting
    through a minimal stand-in (the full method needs a live round state;
    the evidence loop is the code under test)."""
    from types import SimpleNamespace

    from tendermint_tpu.consensus.state import ConsensusState

    byz_count, byz_power = _Gauge(), _Gauge()
    # replicate the loop by calling the real method with a stubbed self
    class _M(SimpleNamespace):
        pass

    m = _M(
        height=_Gauge(), rounds=_Gauge(), validators=_Gauge(),
        validators_power=_Gauge(), committed_height=_Gauge(),
        latest_block_height=_Gauge(), num_txs=_Gauge(),
        block_size_bytes=_Gauge(), byzantine_validators=byz_count,
        byzantine_validators_power=byz_power,
        total_txs=SimpleNamespace(inc=lambda *_: None),
        block_interval_seconds=SimpleNamespace(observe=lambda *_: None),
    )
    rs = SimpleNamespace(round=0, validators=vs, last_validators=None,
                         proposal_block_parts=None)
    header = SimpleNamespace(height=9, time_ns=0)
    block = SimpleNamespace(header=header, last_commit=None,
                            data=SimpleNamespace(txs=[]), evidence=evidence)
    fake_self = SimpleNamespace(metrics=m, rs=rs, priv_validator=None,
                                state=SimpleNamespace(last_block_time_ns=0))
    ConsensusState._record_commit_metrics(fake_self, block)
    return byz_count.value, byz_power.value


def test_duplicate_vote_resolves_power_through_valset():
    from types import SimpleNamespace

    vs = _mk_vs()
    target = vs.validators[2]  # power 30
    ev = SimpleNamespace(
        vote_a=SimpleNamespace(validator_address=target.address),
        byzantine_validators=None)
    count, power = _run_metrics_block(vs, [ev])
    assert count == 1 and power == target.voting_power


def test_validators_deduped_across_evidence_items():
    from types import SimpleNamespace

    vs = _mk_vs()
    v1, v2 = vs.validators[0], vs.validators[1]
    dup = SimpleNamespace(vote_a=SimpleNamespace(validator_address=v1.address),
                          byzantine_validators=None)
    lca = SimpleNamespace(byzantine_validators=[
        SimpleNamespace(address=v1.address, voting_power=v1.voting_power),
        SimpleNamespace(address=v2.address, voting_power=v2.voting_power),
    ])
    lca2 = SimpleNamespace(byzantine_validators=[
        SimpleNamespace(address=v2.address, voting_power=v2.voting_power),
    ])
    count, power = _run_metrics_block(vs, [dup, lca, lca2])
    # v1 and v2 each counted once despite appearing in multiple items
    assert count == 2
    assert power == v1.voting_power + v2.voting_power


def test_unknown_duplicate_voter_counts_without_power():
    from types import SimpleNamespace

    vs = _mk_vs()
    ev = SimpleNamespace(
        vote_a=SimpleNamespace(validator_address=b"\xaa" * 20),
        byzantine_validators=None)
    count, power = _run_metrics_block(vs, [ev])
    assert count == 1 and power == 0
