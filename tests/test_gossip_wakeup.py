"""Event-driven live consensus plane: gossip wakeups and the shared
wire-encode cache (consensus/reactor.py + consensus/msgs.py).

A vote or block part arriving mid-sleep must wake the relevant per-peer
gossip routine immediately — latency bounded well under the configured
``peer_gossip_sleep_duration`` fallback cap — and the encode cache must
serve byte-identical wire messages to what a direct ``encode_msg`` call
produces.
"""

import asyncio
import time

import pytest

from tendermint_tpu.consensus.msgs import (
    BlockPartMessageWire,
    NewRoundStepMessage,
    ProposalMessageWire,
    VoteMessageWire,
    WireEncodeCache,
    decode_msg,
    encode_msg,
)
from tendermint_tpu.consensus.reactor import ConsensusReactor
from tendermint_tpu.consensus.round_state import RoundStep
from tendermint_tpu.libs.bits import BitArray
from tendermint_tpu.libs.metrics import NodeMetrics
from tendermint_tpu.p2p import DATA_CHANNEL, VOTE_CHANNEL
from tendermint_tpu.p2p.base import Peer
from tendermint_tpu.types.basic import BlockID, PartSetHeader, SignedMsgType
from tendermint_tpu.types.part_set import PartSet
from tendermint_tpu.types.proposal import Proposal
from tendermint_tpu.types.vote import Vote

from tests.test_consensus_single import CHAIN_ID, build_node

# the fallback cap: a polling loop would stall this long; wakeups must beat
# it by an order of magnitude
SLOW_SLEEP = 5.0
WAKE_BUDGET = 1.5  # generous for a loaded CI box, still 3x under the cap


# --- encode cache ----------------------------------------------------------

def _mk_vote(h=1, r=0, idx=0, sig=b"\x01" * 64):
    return Vote(SignedMsgType.PREVOTE, h, r,
                BlockID(b"\x01" * 32, PartSetHeader(1, b"\x02" * 32)),
                1_700_000_000_000_000_000, b"\xaa" * 20, idx, sig)


class TestWireEncodeCache:
    def test_identical_bytes_and_hit_accounting(self):
        cache = WireEncodeCache()
        vote = _mk_vote(sig=b"\x11" * 64)
        direct = encode_msg(VoteMessageWire(vote))
        assert cache.vote(vote) == direct
        assert cache.vote(vote) == direct
        assert cache.stats == {"hits": 1, "misses": 1}
        # round-trip through the real decoder
        decoded = decode_msg(cache.vote(vote))
        assert isinstance(decoded, VoteMessageWire)
        assert decoded.vote.signature == vote.signature

        parts = PartSet.from_data(b"block-bytes " * 100, part_size=256)
        part = parts.get_part(0)
        psh = parts.header()
        direct = encode_msg(BlockPartMessageWire(1, 0, part))
        assert cache.block_part(1, 0, psh.hash, part) == direct
        assert cache.block_part(1, 0, psh.hash, part) == direct

        prop = Proposal(1, 0, -1, BlockID(b"\x03" * 32, psh),
                        1_700_000_000_000_000_000, b"\x22" * 64)
        assert cache.proposal(prop) == encode_msg(ProposalMessageWire(prop))

    def test_signature_keys_distinguish_equivocations(self):
        # two votes identical except the signed content (and so the
        # signature) must NOT share an entry
        cache = WireEncodeCache()
        a, b = _mk_vote(sig=b"\xaa" * 64), _mk_vote(sig=b"\xbb" * 64)
        assert cache.vote(a) != cache.vote(b)
        assert cache.stats["misses"] == 2 and cache.stats["hits"] == 0

    def test_lru_bound_and_height_prune(self):
        cache = WireEncodeCache(max_entries=4)
        for i in range(8):
            cache.vote(_mk_vote(h=i + 1, sig=bytes([i]) * 64))
        assert len(cache) == 4
        assert cache.prune_below(8) == 3  # heights 5..7 dropped, 8 kept
        assert len(cache) == 1


# --- wakeup latency --------------------------------------------------------

class _RecordingPeer(Peer):
    def __init__(self, peer_id="peer0"):
        super().__init__(peer_id)
        self.sent = []
        self.got = asyncio.Event()

    def try_send(self, channel_id, msg):
        self.sent.append((channel_id, msg))
        self.got.set()
        return True

    send = try_send

    def is_running(self):
        return True

    async def stop(self):
        pass


async def _reactor_with_idle_peer(sleep=SLOW_SLEEP):
    """A real ConsensusState (not started — rs is driven by hand) behind a
    reactor with one recording peer whose round state matches ours, so the
    gossip routines settle into their waker idle."""
    cs, mempool, app, bus, pv, extras = build_node()
    cs.config.peer_gossip_sleep_duration = sleep
    cs.metrics = NodeMetrics(f"t_wake_{time.monotonic_ns()}").consensus
    reactor = ConsensusReactor(cs)
    reactor.set_metrics(cs.metrics)
    peer = _RecordingPeer()
    reactor.init_peer(peer)
    await reactor.add_peer(peer)
    ps = reactor._peer_states[peer.id]
    ps.apply_new_round_step(NewRoundStepMessage(
        height=cs.rs.height, round=0, step=int(RoundStep.PROPOSE),
        seconds_since_start_time=0, last_commit_round=-1))
    reactor._wake_peer(peer.id)
    await asyncio.sleep(0.3)  # both routines are now parked on their wakers
    peer.sent.clear()
    peer.got.clear()
    return cs, reactor, peer, ps


def test_vote_arriving_mid_sleep_wakes_votes_routine():
    async def run():
        cs, reactor, peer, ps = await _reactor_with_idle_peer()
        try:
            # the state machine accepts our own prevote and notifies
            # listeners — exactly what _add_vote does
            vote = cs._sign_vote(SignedMsgType.PREVOTE, b"", PartSetHeader())
            assert cs.rs.votes.add_vote(vote, "")
            t0 = time.monotonic()
            for listener in cs.vote_listeners:
                listener(vote)
            await asyncio.wait_for(peer.got.wait(), WAKE_BUDGET)
            elapsed = time.monotonic() - t0
            assert elapsed < WAKE_BUDGET < SLOW_SLEEP
            sent_votes = [decode_msg(m) for ch, m in peer.sent
                          if ch == VOTE_CHANNEL]
            assert any(isinstance(m, VoteMessageWire)
                       and m.vote.signature == vote.signature
                       for m in sent_votes)
            assert cs.metrics.gossip_wakeups_total.value("votes") >= 1
        finally:
            await reactor.remove_peer(peer, "done")
            await reactor.stop()

    asyncio.run(run())


def test_block_part_arriving_mid_sleep_wakes_data_routine():
    async def run():
        cs, reactor, peer, ps = await _reactor_with_idle_peer()
        try:
            parts = PartSet.from_data(b"proposal block bytes " * 64,
                                      part_size=512)
            # the peer advertises the matching part-set header with no parts
            ps.prs.proposal_block_part_set_header = parts.header()
            ps.prs.proposal_block_parts = BitArray(parts.total)
            t0 = time.monotonic()
            # the state machine stores the parts and fires the data
            # listeners — exactly what _add_proposal_block_part does
            cs.rs.proposal_block_parts = parts
            for listener in cs.proposal_data_listeners:
                listener()
            await asyncio.wait_for(peer.got.wait(), WAKE_BUDGET)
            assert time.monotonic() - t0 < WAKE_BUDGET < SLOW_SLEEP
            sent_parts = [decode_msg(m) for ch, m in peer.sent
                          if ch == DATA_CHANNEL]
            assert any(isinstance(m, BlockPartMessageWire) for m in sent_parts)
            assert cs.metrics.gossip_wakeups_total.value("data") >= 1
        finally:
            await reactor.remove_peer(peer, "done")
            await reactor.stop()

    asyncio.run(run())


def test_fallback_poll_still_ticks_and_counts():
    async def run():
        cs, reactor, peer, ps = await _reactor_with_idle_peer(sleep=0.05)
        try:
            # no events at all: the routines must still iterate on the
            # fallback cap (catchup/maj23-style timing semantics) and the
            # poll counter must attribute those iterations
            await asyncio.sleep(0.5)
            polls = (cs.metrics.gossip_polls_total.value("data")
                     + cs.metrics.gossip_polls_total.value("votes"))
            assert polls >= 2, polls
        finally:
            await reactor.remove_peer(peer, "done")
            await reactor.stop()

    asyncio.run(run())


# --- end-to-end: a live net exercises wakeups and the encode cache ---------

def test_net_run_hits_wakeups_and_encode_cache():
    from tests.test_consensus_net import make_net, wait_all_height
    from tendermint_tpu.p2p import InProcNetwork

    async def run():
        nodes = make_net(4)
        metrics = []
        for i, nd in enumerate(nodes):
            nm = NodeMetrics(f"t_net_{i}")
            nd.cs.metrics = nm.consensus
            nd.cs_reactor.set_metrics(nm.consensus)
            metrics.append(nm.consensus)
        net = InProcNetwork()
        for nd in nodes:
            net.add_switch(nd.switch)
        for nd in nodes:
            await nd.start()
        await net.connect_all()
        try:
            await wait_all_height(nodes, 3)
        finally:
            for nd in nodes:
                await nd.stop()
        wakeups = sum(m.gossip_wakeups_total.value(r)
                      for m in metrics for r in ("data", "votes"))
        assert wakeups > 0, "no event-driven gossip wakeups fired in a live net"
        cache_hits = sum(nd.cs_reactor._encode_cache.stats["hits"]
                         for nd in nodes)
        cache_misses = sum(nd.cs_reactor._encode_cache.stats["misses"]
                           for nd in nodes)
        # 4 fully-meshed nodes: the same vote/part goes to 3 peers, so the
        # shared cache must be serving repeat encodes
        assert cache_hits > 0, (cache_hits, cache_misses)

    asyncio.run(run())
