"""Light-client serving plane over live RPC: an in-process node serving a
64+ client fleet through /light_verify (coalesced into shared device
batches) and /light_header (bisection-aware cache + prefetch), plus the
per-client admission plane shedding an abuser with reason-labeled errors."""

import asyncio

import pytest

pytest.importorskip("cryptography", reason="needs the optional 'cryptography' package (absent in slim containers)")

from tests.test_node_rpc import _mk_node  # noqa: E402

FLEET = 64


async def _wait_height(client, h, tries=600):
    for _ in range(tries):
        st = await client.status()
        if int(st["sync_info"]["latest_block_height"]) >= h:
            return
        await asyncio.sleep(0.05)
    raise AssertionError(f"node never reached height {h}")


def test_light_serve_fleet_end_to_end(tmp_path):
    """>=64 concurrent clients verify the same span: every verdict comes
    back accepted, the coalescer actually batched (flushes recorded, dupes
    shared), and header serving hit the bisection-aware cache."""

    async def run():
        from tendermint_tpu.rpc.client import HTTPClient
        from tendermint_tpu.rpc.core import RPCError

        node = _mk_node(tmp_path)
        await node.start()
        try:
            port = node.rpc_server.bound_port
            client = HTTPClient(f"http://127.0.0.1:{port}")
            await _wait_height(client, 9)

            # the fleet: every client trusting-verifies height 8 against a
            # small set of trusted heights, plus header fetches declaring
            # the span (which prefetches the bisection skeleton)
            async def one(i):
                if i % 2:
                    return await client.call(
                        "light_verify", height=8,
                        trusted_height=1 + (i % 3), client=f"c{i}")
                return await client.call(
                    "light_header", height=8, trusted_height=1,
                    client=f"c{i}")

            results = await asyncio.gather(*[one(i) for i in range(FLEET)])
            for i, doc in enumerate(results):
                if i % 2:
                    assert doc["verified"] is True and doc["height"] == "8"
                else:
                    assert doc["signed_header"]["header"]["height"] == "8"
                    assert doc["canonical"] is True

            st = await client.call("lightserve_status")
            co = st["coalescer"]
            assert co["requests"] >= FLEET // 2
            assert co["flushes"] >= 1
            # the whole point: far fewer verifications than requests
            assert co["verified_requests"] < co["requests"]
            assert co["coalesced_dupes"] + co["verdict_cache_hits"] > 0
            cache = st["cache"]
            assert cache["hits"] > 0  # the fleet shared cached headers
            assert st["served"]["prefetched"] > 0  # skeleton got pinned
            assert cache["pinned"] > 0

            # malformed span: explicit error, not a stall
            with pytest.raises(RPCError) as ei:
                await client.call("light_verify", height=2, trusted_height=8)
            assert ei.value.code == -32603

            # GET URI route serves the same doc
            doc = await client.call("light_header", height=3)
            assert doc["signed_header"]["header"]["height"] == "3"
            await client.close()
        finally:
            await node.stop()

    asyncio.run(run())


def test_light_serve_rate_limit_sheds_abuser(tmp_path):
    """A hammering client gets reason-labeled RPC errors (client-rate, then
    banned via abuse scoring) while a polite client keeps being served."""

    async def run():
        from tendermint_tpu.node import Node
        from tendermint_tpu.rpc.client import HTTPClient
        from tendermint_tpu.rpc.core import RPCError

        orig = _mk_node(tmp_path)
        cfg = orig.config
        cfg.lightserve.per_client_rate = 0.001  # bucket never refills in-test
        cfg.lightserve.per_client_burst = 2
        cfg.lightserve.abuse_ban_threshold = 3
        node = Node(cfg, orig.priv_validator, orig.node_key, orig.genesis)
        await node.start()
        try:
            port = node.rpc_server.bound_port
            client = HTTPClient(f"http://127.0.0.1:{port}")
            await _wait_height(client, 3)

            reasons = []
            for _ in range(8):
                try:
                    await client.call("light_header", height=2,
                                      client="abuser")
                except RPCError as e:
                    assert e.code == -32005
                    reasons.append(e.data)
            assert reasons.count("client-rate") >= 3
            assert "banned" in reasons  # abuse scoring escalated
            # the ban sticks even after the bucket would readmit
            with pytest.raises(RPCError) as ei:
                await client.call("light_header", height=2, client="abuser")
            assert ei.value.data == "banned"

            # a polite client is untouched by the abuser's ban
            doc = await client.call("light_header", height=2, client="polite")
            assert doc["signed_header"]["header"]["height"] == "2"

            st = await client.call("lightserve_status")
            assert st["limiter"]["rate_sheds"] >= 3
            assert st["limiter"]["ban_sheds"] >= 1
            await client.close()
        finally:
            await node.stop()

    asyncio.run(run())
