"""Manifest-driven e2e matrix (reference test/e2e/pkg/manifest.go:11,
test/e2e/runner/main.go): TOML manifests → subprocess testnets → staged
load/perturb/wait → post-run invariants over RPC.

Three CI manifests cover the cross-feature combos the reference's nightly
generator exists for: mixed mempool versions + remote signer + kill/restart,
state-sync join + kill, and a byzantine double-prevote producing committed
evidence.
"""

import os

import pytest

pytest.importorskip(
    "cryptography",
    reason="the subprocess net's TCP transport needs the optional "
           "'cryptography' package (absent in slim containers)")

from tendermint_tpu.e2e import Manifest, Runner

MANIFESTS = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tendermint_tpu", "e2e", "manifests")


def _run(name: str, tmp_path, base_port: int) -> Runner:
    m = Manifest.load(os.path.join(MANIFESTS, name))
    r = Runner(m, str(tmp_path / "net"), base_port=base_port)
    r.run()
    return r


@pytest.mark.slow
def test_manifest_basic(tmp_path):
    """Mixed mempool versions, tcp privval, kill + restart perturbations."""
    _run("ci-basic.toml", tmp_path, 29100)


@pytest.mark.slow
def test_manifest_statesync_kill(tmp_path):
    """A snapshot-restoring joiner while a validator dies (the statesync x
    perturbation combo VERDICT r3 called out)."""
    _run("ci-statesync.toml", tmp_path, 29140)


@pytest.mark.slow
def test_manifest_byzantine_evidence(tmp_path):
    """Double-prevote at height 3 must surface as committed evidence."""
    _run("ci-byzantine.toml", tmp_path, 29180)


@pytest.mark.slow
def test_manifest_crash_recovery(tmp_path):
    """A validator dies ONCE at a WAL durability boundary (one-shot
    fail_point) and its supervisor relaunches it with bounded backoff —
    the subprocess variant of tools/crashmatrix.py. The run's invariants
    (heights, app hashes, txs everywhere) prove the recovery."""
    r = _run("ci-crash.toml", tmp_path, 29220)
    sup = r.supervisors["crasher"]
    assert sup.restarts >= 1, "the fail point never killed the crasher"
    assert not sup.gave_up, "recovery read as a crash loop"


def test_manifest_validation():
    with pytest.raises(ValueError):
        Manifest.from_doc({"node": {}})  # no nodes
    with pytest.raises(ValueError):
        Manifest.from_doc(  # statesync node at genesis
            {"node": {"a": {"mode": "validator"},
                      "b": {"state_sync": True}}})
    with pytest.raises(ValueError):
        Manifest.from_doc(  # unknown perturbation
            {"node": {"a": {"mode": "validator", "perturb": ["explode"]}}})
    m = Manifest.load(os.path.join(MANIFESTS, "ci-statesync.toml"))
    assert any(n.state_sync for n in m.nodes)
