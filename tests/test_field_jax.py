"""Differential tests: JAX GF(2^255-19) limb arithmetic vs Python ints."""

import random

import numpy as np
import pytest

from tendermint_tpu.crypto.ed25519_jax import field as F

P = F.P_INT


def _pack(vals):
    """list[int] -> (17, N) device array."""
    import jax.numpy as jnp

    arr = np.stack([F.int_to_limbs(v % P) for v in vals], axis=1)
    return jnp.asarray(arr)


def _unpack(a):
    arr = np.asarray(a)
    return [F.limbs_to_int(arr[:, i]) for i in range(arr.shape[1])]


# values that stress carries, folds and the canonical boundary
EDGE = [0, 1, 2, 19, 38, 2**15 - 1, 2**15, 2**255 - 20, P - 1, P - 2,
        2**254, 2**255 - 1 - 19, 12345678901234567890]


def _rand_vals(n, seed):
    rng = random.Random(seed)
    return [rng.randrange(P) for _ in range(n)]


def test_pack_roundtrip():
    vals = EDGE + _rand_vals(50, 1)
    assert _unpack(_pack(vals)) == [v % P for v in vals]


def test_bytes_to_limbs_roundtrip():
    vals = EDGE + _rand_vals(50, 2)
    b = np.stack([
        np.frombuffer((v % P).to_bytes(32, "little"), dtype=np.uint8) for v in vals
    ])
    limbs = F.bytes_to_limbs(b)
    assert [F.limbs_to_int(limbs[:, i]) for i in range(len(vals))] == [v % P for v in vals]
    back = F.limbs_to_bytes(limbs)
    assert np.array_equal(back, b)


@pytest.mark.parametrize("op,pyop", [
    (F.add, lambda a, b: (a + b) % P),
    (F.sub, lambda a, b: (a - b) % P),
    (F.mul, lambda a, b: (a * b) % P),
])
def test_binary_ops(op, pyop):
    avals = EDGE + _rand_vals(64, 3)
    bvals = list(reversed(EDGE)) + _rand_vals(64, 4)
    out = _unpack(F.freeze(op(_pack(avals), _pack(bvals))))
    assert out == [pyop(a, b) for a, b in zip(avals, bvals)]


def test_mul_chain_stays_normalized():
    # repeated muls/adds/subs must preserve the limb invariant
    vals = _rand_vals(32, 5)
    a = _pack(vals)
    acc = [v for v in vals]
    x = a
    for i in range(20):
        x = F.mul(x, a) if i % 3 else F.sub(F.add(x, x), a)
        acc = [((v * w) if i % 3 else (2 * v - w)) % P for v, w in zip(acc, vals)]
    assert _unpack(F.freeze(x)) == acc
    assert int(np.asarray(x).max()) <= 2**15 + 2


def test_neg_sqr_mul_small():
    vals = EDGE + _rand_vals(20, 6)
    a = _pack(vals)
    assert _unpack(F.freeze(F.neg(a))) == [(-v) % P for v in vals]
    assert _unpack(F.freeze(F.sqr(a))) == [v * v % P for v in vals]
    assert _unpack(F.freeze(F.mul_small(a, 121666))) == [v * 121666 % P for v in vals]


def test_freeze_canonical_unique():
    # adversarial: limb patterns with redundancy (value >= p, limbs near 2^15)
    import jax.numpy as jnp

    raws = [
        np.full(17, 2**15 - 1, dtype=np.uint32),        # 2^255 - 1
        F.int_to_limbs(P - 1) + np.array([19] + [0] * 16, dtype=np.uint32),  # == p+18
        np.full(17, 2**20, dtype=np.uint32),            # big columns
        F.P_LIMBS.copy(),                               # exactly p
        F.TWO_P_LIMBS.copy(),                           # exactly 2p
    ]
    arr = jnp.asarray(np.stack(raws, axis=1))
    out = np.asarray(F.freeze(arr))
    expect = [F.limbs_to_int(r) % P for r in raws]
    assert [F.limbs_to_int(out[:, i]) for i in range(len(raws))] == expect
    assert out.max() < 2**15


def test_inverse_and_pow():
    vals = [1, 2, P - 1] + _rand_vals(20, 7)
    a = _pack(vals)
    inv = _unpack(F.freeze(F.inverse(a)))
    assert inv == [pow(v, P - 2, P) for v in vals]
    p58 = _unpack(F.freeze(F.pow_p58(a)))
    assert p58 == [pow(v, (P - 5) // 8, P) for v in vals]


def test_eq_is_zero_parity():
    a = _pack([0, 5, P - 1])
    z = np.asarray(F.is_zero(a))
    assert list(z) == [True, False, False]
    assert list(np.asarray(F.parity(a))) == [0, 1, 0]
