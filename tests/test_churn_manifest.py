"""Manifest-level churn plane: topology knobs (full_mesh / sparse / seed),
the per-node start_at/stop_at churn schedule, quorum-drain validation, the
runner's topology-aware persistent-peer wiring, and the generator's new
axes. Pure parsing/wiring — runs in slim containers (no TCP transport)."""

import os

import pytest

from tendermint_tpu.e2e.generate import doc_to_toml, generate
from tendermint_tpu.e2e.manifest import Manifest
from tendermint_tpu.libs import toml_compat
from tendermint_tpu.p2p.inproc import sparse_edges


def _doc(**top):
    doc = {"node": {f"validator{i}": {"mode": "validator"}
                    for i in range(4)}}
    doc.update(top)
    return doc


# -- manifest fields + validation ---------------------------------------------

def test_topology_defaults_and_round_trip():
    m = Manifest.from_doc(_doc())
    assert (m.topology, m.sparse_degree, m.topology_seed) \
        == ("full_mesh", 3, 0)
    m = Manifest.from_doc(_doc(topology="sparse", sparse_degree=2,
                               topology_seed=9))
    assert (m.topology, m.sparse_degree, m.topology_seed) == ("sparse", 2, 9)


def test_unknown_topology_rejected():
    with pytest.raises(ValueError, match="unknown topology"):
        Manifest.from_doc(_doc(topology="star"))
    with pytest.raises(ValueError, match="sparse_degree"):
        Manifest.from_doc(_doc(topology="sparse", sparse_degree=0))


def test_stop_at_churn_schedule_fields():
    doc = _doc()
    doc["node"]["full0"] = {"mode": "full", "stop_at": 7}
    doc["node"]["sync0"] = {"mode": "full", "start_at": 4, "stop_at": 9}
    m = Manifest.from_doc(doc)
    by = {n.name: n for n in m.nodes}
    assert by["full0"].stop_at == 7
    assert (by["sync0"].start_at, by["sync0"].stop_at) == (4, 9)


def test_stop_before_start_rejected():
    doc = _doc()
    doc["node"]["sync0"] = {"mode": "full", "start_at": 5, "stop_at": 5}
    with pytest.raises(ValueError, match="must exceed"):
        Manifest.from_doc(doc)
    doc["node"]["sync0"] = {"mode": "full", "start_at": -1}
    with pytest.raises(ValueError, match=">= 0"):
        Manifest.from_doc(doc)


def test_churn_quorum_drain_rejected():
    """Validators scheduled to leave may not take >=1/3 of genesis power
    with them — the schedule itself would stall the net."""
    doc = _doc()
    doc["node"]["validator3"]["stop_at"] = 8
    doc["node"]["validator2"]["stop_at"] = 9
    with pytest.raises(ValueError, match="drains quorum"):
        Manifest.from_doc(doc)
    # one leaving validator out of four holds 1/4 < 1/3: fine
    del doc["node"]["validator2"]["stop_at"]
    m = Manifest.from_doc(doc)
    assert any(n.stop_at for n in m.nodes)


def test_seed_topology_needs_a_seed_node():
    with pytest.raises(ValueError, match="seed_node = true"):
        Manifest.from_doc(_doc(topology="seed"))
    doc = _doc(topology="seed")
    doc["node"]["seed0"] = {"mode": "full", "seed_node": True}
    m = Manifest.from_doc(doc)
    assert [n.name for n in m.nodes if n.seed_node] == ["seed0"]
    # seed_node outside seed topology is a config smell: rejected
    doc2 = _doc()
    doc2["node"]["seed0"] = {"mode": "full", "seed_node": True}
    with pytest.raises(ValueError, match='topology = "seed"'):
        Manifest.from_doc(doc2)
    # a seed node can't churn — it anchors discovery
    doc3 = _doc(topology="seed")
    doc3["node"]["seed0"] = {"mode": "full", "seed_node": True, "stop_at": 5}
    with pytest.raises(ValueError, match="can't churn"):
        Manifest.from_doc(doc3)


# -- runner wiring (no processes launched) ------------------------------------

def _runner_for(doc):
    from tendermint_tpu.e2e.runner import Runner

    m = Manifest.from_doc(doc)
    r = Runner(m, root="/nonexistent-churn-test")  # no setup() call
    r.node_ids = {n.name: f"id-{n.name}" for n in m.nodes}
    return m, r

def test_runner_full_mesh_peers():
    m, r = _runner_for(_doc())
    nm = m.nodes[0]
    peers = {p.name for p in r._peers_of(nm)}
    assert peers == {n.name for n in m.nodes} - {nm.name}


def test_runner_sparse_peers_match_shared_graph():
    """The subprocess runner derives persistent peers from the SAME
    seeded graph the in-proc plane builds — one topology, two planes."""
    doc = _doc(topology="sparse", sparse_degree=2, topology_seed=4)
    for i in range(4):
        doc["node"][f"full{i}"] = {"mode": "full"}
    m, r = _runner_for(doc)
    names = [n.name for n in m.nodes]
    edges = sparse_edges(names, degree=2, seed=4)
    for nm in m.nodes:
        want = {b if a == nm.name else a
                for a, b in edges if nm.name in (a, b)}
        assert {p.name for p in r._peers_of(nm)} == want
    # sparse really is sparse at this size
    assert len(edges) < len(names) * (len(names) - 1) // 2


def test_runner_seed_topology_no_persistent_peers():
    doc = _doc(topology="seed")
    doc["node"]["seed0"] = {"mode": "full", "seed_node": True}
    m, r = _runner_for(doc)
    for nm in m.nodes:
        assert r._peers_of(nm) == []


# -- generator ----------------------------------------------------------------

def test_generator_emits_topology_and_stop_at_and_validates():
    """Across many seeds the generator samples sparse topologies and
    stop_at schedules, and every emitted manifest round-trips through the
    TOML writer+parser and validates."""
    saw_sparse = saw_stop = False
    for seed in range(40):
        for _name, m, toml_text in generate(seed, 3):
            again = Manifest.from_doc(toml_compat.loads(toml_text))
            assert again.topology == m.topology
            saw_sparse |= m.topology == "sparse"
            saw_stop |= any(n.stop_at for n in m.nodes)
    assert saw_sparse, "generator never sampled a sparse topology"
    assert saw_stop, "generator never sampled a stop_at leave"


def test_doc_to_toml_writes_topology_keys():
    doc = _doc(topology="sparse", sparse_degree=2, topology_seed=7)
    doc["chain_id"] = "t"
    text = doc_to_toml(doc)
    assert 'topology = "sparse"' in text
    assert "sparse_degree = 2" in text
    parsed = toml_compat.loads(text)
    assert parsed["topology_seed"] == 7
