"""Evidence pool: verification, pooling, gossip, and block inclusion end-to-end."""

import asyncio

import pytest

from tendermint_tpu.abci import types as abci
from tendermint_tpu.evidence import EvidencePool, verify_duplicate_vote
from tendermint_tpu.evidence.pool import _PENDING_PREFIX
from tendermint_tpu.evidence.reactor import EvidenceReactor
from tendermint_tpu.libs.db import MemDB
from tendermint_tpu.types import DuplicateVoteEvidence, SignedMsgType, Vote
from tendermint_tpu.types.basic import BlockID, PartSetHeader

import sys
import os

sys.path.insert(0, os.path.dirname(__file__))
from test_consensus_net import Node, make_net, wait_all_height  # noqa: E402
from tendermint_tpu.p2p import InProcNetwork  # noqa: E402

BID_A = BlockID(b"\x01" * 32, PartSetHeader(1, b"\x02" * 32))
BID_B = BlockID(b"\x03" * 32, PartSetHeader(1, b"\x04" * 32))


def make_conflicting_votes(nodes, height):
    """Two signed precommits for different blocks at `height` by validator 0."""
    node = nodes[0]
    chain_id = node.cs.state.chain_id
    val_set = node.state_store.load_validators(height)
    val = val_set.validators[0]
    idx, _ = val_set.get_by_address(val.address)
    signer = next(nd for nd in nodes
                  if nd.pv.get_pub_key().address() == val.address)
    meta = node.block_store.load_block_meta(height)
    ts = meta.header.time_ns

    votes = []
    for bid in (BID_A, BID_B):
        v = Vote(SignedMsgType.PRECOMMIT, height, 0, bid, ts, val.address, idx)
        signer.pv.sign_vote(chain_id, v)
        votes.append(v)
    return votes, val_set, ts


def attach_pool(node):
    pool = EvidencePool(MemDB(), node.state_store, node.block_store)
    pool.set_state(node.cs.state)
    node.block_exec.evpool = pool
    node.cs.evpool = pool
    return pool


def test_duplicate_vote_evidence_verify_and_pool():
    async def run():
        nodes = make_net(4)
        net = InProcNetwork()
        for nd in nodes:
            net.add_switch(nd.switch)
        pools = [attach_pool(nd) for nd in nodes]
        reactors = []
        for nd, pool in zip(nodes, pools):
            r = EvidenceReactor(pool, gossip_sleep=0.01)
            nd.switch.add_reactor("EVIDENCE", r)
            reactors.append(r)
        for nd in nodes:
            await nd.start()
        await net.connect_all()
        try:
            await wait_all_height(nodes, 2)
            # byzantine: validator of node 0 signed two conflicting precommits at h=1
            (va, vb), val_set, ts = make_conflicting_votes(nodes, 1)
            ev = DuplicateVoteEvidence.new(va, vb, ts, val_set)
            verify_duplicate_vote(ev, nodes[0].cs.state.chain_id, val_set)
            # keep pool state fresh before adding
            for nd, pool in zip(nodes, pools):
                pool.set_state(nd.cs.state)
            pools[1].add_evidence(ev)
            assert pools[1].is_pending(ev)

            # gossip spreads it, proposers include it, block commits it
            deadline = asyncio.get_event_loop().time() + 30
            while True:
                if all(p.is_committed(ev) for p in pools):
                    break
                if asyncio.get_event_loop().time() > deadline:
                    states = [(p.is_pending(ev), p.is_committed(ev)) for p in pools]
                    raise AssertionError(f"evidence not committed everywhere: {states}")
                await asyncio.sleep(0.05)
            # committed evidence pruned from pending
            assert all(not p.is_pending(ev) for p in pools)
        finally:
            for nd in nodes:
                await nd.stop()

    asyncio.run(run())


def test_consensus_reports_conflicting_votes_to_pool():
    async def run():
        nodes = make_net(4)
        net = InProcNetwork()
        for nd in nodes:
            net.add_switch(nd.switch)
        pools = [attach_pool(nd) for nd in nodes]
        for nd in nodes:
            await nd.start()
        await net.connect_all()
        try:
            await wait_all_height(nodes, 2)
            target = nodes[1]
            # inject two conflicting signed votes for the CURRENT height into
            # node 1's machine: VoteSet raises ErrVoteConflictingVotes, the
            # state machine reports to the pool's consensus buffer
            h = target.cs.rs.height
            chain_id = target.cs.state.chain_id
            val_set = target.cs.rs.validators
            byz_node = nodes[0]
            val = val_set.validators[0]
            # find which node's pv is validator index 0
            byz = next(nd for nd in nodes
                       if nd.pv.get_pub_key().address() == val.address)
            from tendermint_tpu.consensus.state import VoteMessage

            for bid in (BID_A, BID_B):
                v = Vote(SignedMsgType.PRECOMMIT, h, 0, bid,
                         1_800_000_000_000_000_000, val.address, 0)
                byz.pv.sign_vote(chain_id, v)
                await target.cs.add_peer_msg(VoteMessage(v), "byzpeer")
            deadline = asyncio.get_event_loop().time() + 10
            while not pools[1]._consensus_buffer:
                if asyncio.get_event_loop().time() > deadline:
                    raise AssertionError("conflicting votes never reported")
                await asyncio.sleep(0.02)
        finally:
            for nd in nodes:
                await nd.stop()

    asyncio.run(run())
