"""tools/fleet_scrape.py: Prometheus text parsing, cluster rollups
(min/median/max per series, cluster blocks/min from the height MAX,
wakeups per peer link), live endpoint addition, and the CLI self-test."""

import os
import subprocess
import sys
import time

TOOL = os.path.join(os.path.dirname(os.path.dirname(__file__)),
                    "tools", "fleet_scrape.py")


def _mod():
    sys.path.insert(0, os.path.dirname(TOOL))
    try:
        import fleet_scrape

        return fleet_scrape
    finally:
        sys.path.pop(0)


def test_self_test_passes():
    res = subprocess.run([sys.executable, TOOL, "--self-test"],
                         capture_output=True, text=True, timeout=60)
    assert res.returncode == 0, res.stdout + res.stderr
    assert "self-test OK" in res.stdout


def test_parse_metrics_skips_buckets_and_comments():
    fs = _mod()
    text = "\n".join([
        "# HELP tendermint_consensus_height x",
        "# TYPE tendermint_consensus_height gauge",
        "tendermint_consensus_height 42",
        'tendermint_crypto_batch_size_bucket{le="4",plane="light"} 7',
        'tendermint_crypto_batch_size_sum{plane="light"} 99.5',
        'tendermint_consensus_gossip_wakeups_total{routine="data"} 12',
        "garbage line without value collapses",
        "tendermint_bad_value nan-ish",  # float('nan-ish') raises -> skip
    ])
    out = fs.parse_metrics(text)
    assert out["tendermint_consensus_height"] == 42.0
    assert out['tendermint_crypto_batch_size_sum{plane="light"}'] == 99.5
    assert out['tendermint_consensus_gossip_wakeups_total'
               '{routine="data"}'] == 12.0
    assert not any("_bucket" in k for k in out)
    assert "tendermint_bad_value" not in out


def test_rollup_from_injected_samples():
    """Rollup math without HTTP: samples injected straight into the
    scraper's first/last stores (the exact shape sweep() records)."""
    fs = _mod()
    sc = fs.FleetScraper({})
    t0 = time.time() - 30.0
    heights_first = {"a": 10.0, "b": 10.0, "c": 9.0}
    heights_last = {"a": 24.0, "b": 25.0, "c": 20.0}
    for n in ("a", "b", "c"):
        first = {"tendermint_consensus_committed_height": heights_first[n],
                 'tendermint_consensus_gossip_wakeups_total'
                 '{routine="data"}': 100.0}
        last = {"tendermint_consensus_committed_height": heights_last[n],
                'tendermint_consensus_gossip_wakeups_total'
                '{routine="data"}': 160.0}
        sc.first[n] = (t0, first)
        sc.last[n] = (t0 + 30.0, last)
    roll = sc.rollup()
    hs = roll["series"]["tendermint_consensus_committed_height"]
    assert (hs["min"], hs["median"], hs["max"]) == (20.0, 24.0, 25.0)
    # cluster truth: max(25) - max(10) = 15 blocks over 30s -> 30/min
    assert roll["cluster_blocks_per_min"] == 30.0
    assert roll["cluster_height"] == 25.0
    # 3 nodes x +60 wakeups over 6 directed links
    assert roll["wakeups_per_peer_link"] == 30.0


def test_add_endpoint_and_dead_node_degrade():
    fs = _mod()
    sc = fs.FleetScraper({"gone": "http://127.0.0.1:9/metrics"},
                         interval_s=0.05)
    assert sc.sweep() == 0
    assert sc.errors == 1
    sc.add_endpoint("also-gone", "http://127.0.0.1:9/metrics")
    assert sc.sweep() == 0
    assert sc.errors == 3
    roll = sc.rollup()
    assert roll["n_nodes"] == 0 and roll["scrape_errors"] == 3
    assert roll["wakeups_per_peer_link"] == 0.0
    assert "cluster_blocks_per_min" not in roll


def test_write_is_atomic(tmp_path):
    fs = _mod()
    sc = fs.FleetScraper({})
    path = str(tmp_path / "fleet.json")
    sc.write(path)
    import json

    assert json.load(open(path))["n_nodes"] == 0
    assert not os.path.exists(path + ".tmp")
