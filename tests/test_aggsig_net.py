"""Aggregated-commit mode end to end: a 4-validator in-proc net on a BLS
chain (signature params in genesis, proofs of possession gating every key)
must reach height >= 3 with hash-identical blocks whose last_commits are
one 48-byte aggregate + signer bitmap — and a node restarting over its
aggregated block store + WAL must handshake-replay cleanly and keep
committing."""

import asyncio

import pytest
from test_consensus_net import Node, wait_all_height

from tendermint_tpu import crypto
from tendermint_tpu.abci.example.kvstore import KVStoreApplication
from tendermint_tpu.consensus import ConsensusState, WAL
from tendermint_tpu.consensus.config import test_consensus_config
from tendermint_tpu.consensus.replay import Handshaker, catchup_replay
from tendermint_tpu.libs.db import SQLiteDB
from tendermint_tpu.mempool import CListMempool
from tendermint_tpu.p2p import InProcNetwork
from tendermint_tpu.proxy import AppConns, local_client_creator
from tendermint_tpu.state import BlockExecutor, StateStore, state_from_genesis
from tendermint_tpu.state.execution import EmptyEvidencePool
from tendermint_tpu.store import BlockStore
from tendermint_tpu.types import GenesisDoc, GenesisValidator, MockPV
from tendermint_tpu.types.event_bus import EventBus
from tendermint_tpu.types.params import (
    ConsensusParams,
    SignatureParams,
    ValidatorParams,
)

CHAIN_ID = "aggnet-chain"


def agg_test_config():
    """test_consensus_config with round timeouts scaled for BLS: a scalar
    pairing costs ~40-100ms of GIL-bound bigint math, and 4 in-proc nodes
    verifying every gossiped vote + the proposal's aggregated commit can
    outlast the 80ms ed25519-tuned propose timeout — nodes then prevote nil
    before the proposal validates and the net livelocks through rounds."""
    cfg = test_consensus_config()
    cfg.timeout_propose = 1.0
    cfg.timeout_propose_delta = 0.5
    cfg.timeout_prevote = 0.4
    cfg.timeout_prevote_delta = 0.2
    cfg.timeout_precommit = 0.4
    cfg.timeout_precommit_delta = 0.2
    return cfg


def bls_genesis(pvs, chain_id=CHAIN_ID):
    gen = GenesisDoc(
        chain_id=chain_id, genesis_time_ns=1_700_000_000_000_000_000,
        consensus_params=ConsensusParams(
            validator=ValidatorParams(["bls12381"]),
            signature=SignatureParams("bls12381", True)),
        validators=[GenesisValidator(pv.get_pub_key(), 10,
                                     pop=pv.priv_key.pop())
                    for pv in pvs])
    gen.validate_and_complete()  # registers every pop (rogue-key gate)
    return gen


def make_bls_net(n):
    pvs = [MockPV(crypto.Bls12381PrivKey.generate(b"aggnet" + bytes([i]) * 2))
           for i in range(n)]
    genesis = bls_genesis(pvs)
    nodes = [Node(i, pv, genesis) for i, pv in enumerate(pvs)]
    for nd in nodes:
        nd.cs.config = agg_test_config()
    return nodes


def test_aggregated_net_reaches_height_3():
    async def run():
        nodes = make_bls_net(4)
        net = InProcNetwork()
        for nd in nodes:
            net.add_switch(nd.switch)
        for nd in nodes:
            await nd.start()
        await net.connect_all()
        try:
            await wait_all_height(nodes, 3, timeout=60)
        finally:
            for nd in nodes:
                await nd.stop()
        heights = [nd.cs.state.last_block_height for nd in nodes]
        assert min(heights) >= 3, heights
        # every node stored the SAME block 2...
        hashes = {nd.block_store.load_block_meta(2).header.hash()
                  for nd in nodes}
        assert len(hashes) == 1
        # ...and its successor's last_commit is the aggregated form: one
        # 48-byte BLS point + a signer bitmap, not a CommitSig list
        for nd in nodes:
            blk = nd.block_store.load_block(3)
            lc = blk.last_commit
            assert hasattr(lc, "agg_sig"), type(lc)
            assert len(lc.agg_sig) == 48
            assert lc.signers.size() == 4
            assert sum(1 for i in range(4) if lc.signers.get_index(i)) >= 3
            # the stored seen-commit round-trips through the store too
            seen = nd.block_store.load_seen_commit(
                nd.block_store.height())
            assert hasattr(seen, "agg_sig")

    asyncio.run(run())


def _boot_single(tmp_path, pv, genesis, wal_path):
    app = KVStoreApplication()
    conns = AppConns(local_client_creator(app))
    conns.start()
    state_store = StateStore(SQLiteDB(str(tmp_path / "state.db")))
    block_store = BlockStore(SQLiteDB(str(tmp_path / "blocks.db")))
    state = state_store.load() or state_from_genesis(genesis)
    state = Handshaker(state_store, state, block_store, genesis).handshake(
        conns.consensus, conns.query)
    state_store.save(state)
    mempool = CListMempool(conns.mempool)
    bus = EventBus()
    bx = BlockExecutor(state_store, conns.consensus, mempool,
                       EmptyEvidencePool(), block_store, bus)
    cs = ConsensusState(test_consensus_config(), state, bx, block_store,
                        wal=WAL(wal_path))
    cs.set_priv_validator(pv)
    cs.set_event_bus(bus)
    return cs


async def _run_to_height(cs, target, ticks=600):
    await cs.start()
    try:
        for _ in range(ticks):
            if cs.state.last_block_height >= target:
                return cs.state.last_block_height
            await asyncio.sleep(0.02)
        raise AssertionError(f"stalled at {cs.state.last_block_height}")
    finally:
        await cs.stop()


def test_aggregated_wal_handshake_replay(tmp_path):
    """Restart over an aggregated chain's durable artifacts: the block
    store holds AggregatedCommits, the WAL holds the votes that formed
    them — handshake + catchup_replay must restore the state machine and
    the node must keep committing past its pre-restart height."""
    pv = MockPV(crypto.Bls12381PrivKey.generate(b"aggwal" + b"\x07" * 2))
    genesis = bls_genesis([pv], chain_id="aggwal-chain")
    wal_path = str(tmp_path / "cs.wal")

    async def first_life():
        cs = _boot_single(tmp_path, pv, genesis, wal_path)
        catchup_replay(cs, cs.rs.height)
        return await _run_to_height(cs, 3)

    h1 = asyncio.run(first_life())
    assert h1 >= 3

    async def second_life():
        cs = _boot_single(tmp_path, pv, genesis, wal_path)
        # the replayed state must already be at the pre-restart height,
        # proven out of aggregated commits alone
        assert cs.state.last_block_height >= h1
        lc = cs.block_store.load_block(h1).last_commit
        assert hasattr(lc, "agg_sig")
        catchup_replay(cs, cs.rs.height)
        return await _run_to_height(cs, h1 + 1)

    h2 = asyncio.run(second_life())
    assert h2 >= h1 + 1


def test_genesis_roundtrip_preserves_aggregation(tmp_path):
    """Aggregated-chain genesis survives its JSON round trip: scheme params,
    pops, and key types all intact (what a real node would boot from)."""
    pvs = [MockPV(crypto.Bls12381PrivKey.generate(b"gjson" + bytes([i]) * 3))
           for i in range(4)]
    gen = bls_genesis(pvs, chain_id="aggjson-chain")
    path = str(tmp_path / "genesis.json")
    gen.save_as(path)
    rt = GenesisDoc.from_file(path)
    assert rt.consensus_params.signature.scheme == "bls12381"
    assert rt.consensus_params.signature.aggregate_commits
    assert [v.pub_key.bytes() for v in rt.validators] == \
        [v.pub_key.bytes() for v in gen.validators]
    assert all(v.pop for v in rt.validators)
    assert rt.hash() == gen.hash()
