"""Degraded-network consensus plane, tier-1 units: seeded link-profile
planner (wan/gray/asym), per-direction heal/redial policy preservation
(seeded replay), quorum-loss planner invariants, watchdog halt
classification from live vote bitmaps, seeded clock skew, adaptive round
timeouts (determinism + clamp + spec-mode pinning), and round-escalation
determinism — same seed + same profile schedule ⇒ identical per-height
round counts and round_advances_total{reason} composition, both timeout
modes.
"""

import asyncio
import os
import sys
import types

import pytest

from tendermint_tpu.consensus.config import (AdaptiveTimeouts,
                                             ConsensusConfig,
                                             test_consensus_config)
from tendermint_tpu.consensus.watchdog import ConsensusWatchdog
from tendermint_tpu.libs.bits import BitArray
from tendermint_tpu.libs.faults import FaultPlane
from tendermint_tpu.libs.metrics import ConsensusMetrics, Registry
from tendermint_tpu.p2p import InProcNetwork
from tendermint_tpu.p2p.inproc import (LINK_PROFILES, LinkPolicy,
                                       plan_link_profiles)
from tendermint_tpu.p2p.switch import Switch

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- link-profile planner ----------------------------------------------------

def test_link_profile_planner_deterministic_and_shaped():
    ids = ["n0", "n1", "n2", "n3"]
    for profile in ("wan", "gray"):
        plan = plan_link_profiles(ids, profile, seed=3)
        assert plan == plan_link_profiles(ids, profile, seed=3)
        # symmetric profiles degrade EVERY directed link, both ways
        assert len(plan) == len(ids) * (len(ids) - 1)
        for (src, dst), knobs in plan.items():
            assert (dst, src) in plan
            assert knobs["profile"] == profile
            for k, v in LINK_PROFILES[profile].items():
                assert knobs[k] == v


def test_link_profile_asym_degrades_one_direction_per_pair():
    ids = ["n0", "n1", "n2", "n3"]
    plan = plan_link_profiles(ids, "asym", seed=3)
    assert plan == plan_link_profiles(ids, "asym", seed=3)
    # exactly one direction per unordered pair; the reverse stays clean
    # (absent from the plan entirely)
    assert len(plan) == len(ids) * (len(ids) - 1) // 2
    for (src, dst) in plan:
        assert (dst, src) not in plan
    # the planner RNG picks the degraded direction: seed-sensitive
    assert any(plan_link_profiles(ids, "asym", seed=s) != plan
               for s in (4, 5, 6))


def test_unknown_link_profile_rejected_everywhere():
    with pytest.raises(ValueError, match="unknown link profile"):
        plan_link_profiles(["a", "b"], "wann")
    # the e2e manifest mirrors the same grammar: a typo'd profile would
    # run the net clean and pass the degradation cell vacuously
    from tendermint_tpu.e2e.manifest import Manifest

    with pytest.raises(ValueError, match="unknown link profile"):
        Manifest.from_doc({"link_profile": "wann",
                           "node": {"a": {"mode": "validator"}}})
    m = Manifest.from_doc({"link_profile": "gray",
                           "node": {"a": {"mode": "validator"}}})
    assert m.link_profile == "gray"


def test_link_policy_jitter_seeded_and_bounded():
    knobs = dict(LINK_PROFILES["wan"])
    p1 = LinkPolicy("a", "b", seed=11, **knobs)
    p2 = LinkPolicy("a", "b", seed=11, **knobs)
    s1 = [p1.plan() for _ in range(300)]
    assert s1 == [p2.plan() for _ in range(300)]
    lo, hi = knobs["delay_s"], knobs["delay_s"] + knobs["jitter_s"]
    for fates in s1:
        if fates is None:
            continue
        for d in fates:
            assert lo <= d < hi + 0.005  # + reorder hold ceiling


# -- per-direction heal / redial (satellite: heal + reconnect audit) ---------

def _bare_net(*ids):
    net = InProcNetwork()
    for i in ids:
        net.add_switch(Switch(i))
    return net


def test_heal_asym_restores_only_degraded_direction_preserves_rng():
    """Healing a one-way partition must unblock exactly the blocked
    direction and leave the surviving direction's LinkPolicy object — and
    its RNG stream position — untouched (seeded replay holds across the
    block/heal cycle)."""
    async def run():
        net = _bare_net("a", "b")
        await net.connect_all()
        pol_ba = net.set_link_policy("b", "a", seed=9, drop_p=0.3)
        ref = LinkPolicy("b", "a", seed=9, drop_p=0.3)
        stream = [pol_ba.plan() for _ in range(50)]

        assert net.partition_oneway(["a"], ["b"]) == 1
        assert net.links[("a", "b")].policy.blocked
        assert net.links[("b", "a")].policy is pol_ba
        assert not pol_ba.blocked
        stream += [pol_ba.plan() for _ in range(50)]

        assert net.heal(group_a=["a"]) == 1  # only the blocked direction
        assert not net.links[("a", "b")].policy.blocked
        assert net.links[("b", "a")].policy is pol_ba
        stream += [pol_ba.plan() for _ in range(100)]
        # the surviving direction replayed ONE uninterrupted seeded stream
        assert stream == [ref.plan() for _ in range(200)]
        await net.stop()

    asyncio.run(run())


def test_reconnect_missing_carries_policies_per_direction():
    """A redial after the receiver drops the link (stop_peer_for_error)
    must rewire each direction with ITS OWN surviving policy object: the
    blocked direction stays blocked, the seeded-lossy reverse continues
    its RNG stream exactly where the severed link left it."""
    async def run():
        net = _bare_net("a", "b")
        await net.connect_all()
        pol_ba = net.set_link_policy("b", "a", seed=9, drop_p=0.3)
        ref = LinkPolicy("b", "a", seed=9, drop_p=0.3)
        stream = [pol_ba.plan() for _ in range(80)]
        assert net.partition_oneway(["a"], ["b"]) == 1

        sw_b = net.switches["b"]
        await sw_b.stop_peer_for_error(sw_b.peers["a"], "test sever")
        assert not net.connected("a", "b")
        assert await net.reconnect_missing() == 1
        assert net.connected("a", "b")
        assert net.links[("a", "b")].policy.blocked   # one-way cut survives
        assert net.links[("b", "a")].policy is pol_ba  # same object...
        stream += [pol_ba.plan() for _ in range(120)]  # ...same stream
        assert stream == [ref.plan() for _ in range(200)]
        await net.stop()

    asyncio.run(run())


def test_apply_profile_attaches_exactly_the_planned_links():
    async def run():
        net = _bare_net("a", "b", "c")
        await net.connect_all()
        plan = plan_link_profiles(["a", "b", "c"], "asym", seed=5)
        assert net.apply_profile("asym", seed=5) == len(plan) == 3
        for (src, dst), peer in net.links.items():
            if (src, dst) in plan:
                assert peer.policy is not None
                assert peer.policy.profile == "asym"
            else:
                assert peer.policy is None  # the clean reverse direction
        await net.stop()

    asyncio.run(run())


# -- quorum-loss planner (tools/quorum_loss.py via the toolbox) --------------

def test_quorum_loss_planner_invariants():
    from tendermint_tpu.libs.toolbox import load_tool

    ql = load_tool("quorum_loss")
    p1 = ql.plan_quorum_loss(7, windows=4)
    assert p1 == ql.plan_quorum_loss(7, windows=4)
    assert p1 != ql.plan_quorum_loss(8, windows=4)
    for ev in p1["events"]:
        # >1/3 of the power isolated, never the whole set, bounded hold
        assert ev["isolated_power"] * 3 > ev["total_power"]
        assert 0 < len(ev["isolate"]) < p1["n_validators"]
        assert 2.5 <= ev["hold_s"] <= 4.0
    # weighted powers: a >2/3 whale alone kills quorum
    pw = ql.plan_quorum_loss(3, windows=2, n_validators=4,
                             powers=[70, 10, 10, 10])
    for ev in pw["events"]:
        assert ev["isolated_power"] * 3 > ev["total_power"]
        assert len(ev["isolate"]) < 4


# -- watchdog halt classification --------------------------------------------

def _fake_cs(total_powers, prevote_idx, precommit_idx, round_=0, height=5):
    """A consensus-state stand-in exposing exactly what classify_halt
    reads: rs.votes.{prevotes,precommits}(round) with .sum/.bit_array(),
    and rs.validators with .total_voting_power()/.validators."""
    n = len(total_powers)

    def vote_set(idx_set):
        bits = BitArray(n)
        for i in idx_set:
            bits.set_index(i, True)
        return types.SimpleNamespace(
            sum=sum(total_powers[i] for i in idx_set),
            bit_array=lambda b=bits: b)

    vals = types.SimpleNamespace(
        total_voting_power=lambda: sum(total_powers),
        validators=[types.SimpleNamespace(address=bytes([i]) * 20,
                                          voting_power=total_powers[i])
                    for i in range(n)])
    pv, pc = vote_set(prevote_idx), vote_set(precommit_idx)
    rs = types.SimpleNamespace(
        height=height, round=round_, step="prevote", validators=vals,
        votes=types.SimpleNamespace(prevotes=lambda r: pv,
                                    precommits=lambda r: pc))
    return types.SimpleNamespace(
        rs=rs, state=types.SimpleNamespace(last_block_height=height - 1))


def test_classify_halt_quorum_lost_on_prevote_stage():
    cs = _fake_cs([10, 10, 10, 10], prevote_idx={0, 1}, precommit_idx=set())
    wd = ConsensusWatchdog(cs, stall_timeout_s=99, dump_node=None)
    reason, detail = wd.classify_halt()
    assert reason == "quorum_lost"
    assert detail["blocking_stage"] == "prevote"
    assert detail["missing_power"] == 20
    rows = {r["index"]: r for r in detail["validators"]}
    assert rows[0]["prevote"] and not rows[2]["prevote"]


def test_classify_halt_cut_between_quorums_is_still_quorum_loss():
    """A cut landing AFTER the polka but before the precommit quorum
    leaves a full prevote set behind — the blocking stage is then the
    precommit set, and the missing power is measured there."""
    cs = _fake_cs([10, 10, 10, 10], prevote_idx={0, 1, 2, 3},
                  precommit_idx={0, 1})
    wd = ConsensusWatchdog(cs, stall_timeout_s=99, dump_node=None)
    reason, detail = wd.classify_halt()
    assert reason == "quorum_lost"
    assert detail["blocking_stage"] == "precommit"
    assert detail["missing_power"] == 20
    assert detail["prevote_power"] == 40


def test_classify_halt_generic_stall_when_quorum_present():
    # everyone's votes are in — whatever is stuck, it is not quorum loss
    cs = _fake_cs([10, 10, 10, 10], prevote_idx={0, 1, 2, 3},
                  precommit_idx={0, 1, 2})
    wd = ConsensusWatchdog(cs, stall_timeout_s=99, dump_node=None)
    reason, detail = wd.classify_halt()
    assert reason == "stalled"
    assert detail["missing_power"] == 10
    # and an uninspectable round state degrades to a generic stall
    bare = types.SimpleNamespace(
        rs=None, state=types.SimpleNamespace(last_block_height=1))
    wd2 = ConsensusWatchdog(bare, stall_timeout_s=99, dump_node=None)
    assert wd2.classify_halt() == ("stalled", {})


# -- seeded clock skew -------------------------------------------------------

def test_clock_skew_deterministic_per_ident_and_bounded():
    fp = FaultPlane().configure("clock.skew", seed=21)
    a = fp.skew_ns("clock.skew", "node-a")
    b = fp.skew_ns("clock.skew", "node-b")
    assert a != b  # different idents, different offsets
    assert abs(a) <= 500_000_000 and abs(b) <= 500_000_000
    # pure function of (seed, site, ident): re-consultation and a fresh
    # plane with the same seed both return the identical offset
    assert fp.skew_ns("clock.skew", "node-a") == a
    assert FaultPlane().configure("clock.skew",
                                  seed=21).skew_ns("clock.skew", "node-a") == a
    assert FaultPlane().configure("clock.skew",
                                  seed=22).skew_ns("clock.skew", "node-a") != a
    # @prob scales the magnitude window instead of gating firing
    half = FaultPlane().configure("clock.skew@0.5", seed=21)
    assert abs(half.skew_ns("clock.skew", "node-a")) <= 250_000_000
    # unarmed site: zero skew
    assert FaultPlane().skew_ns("clock.skew", "node-a") == 0


def test_vote_time_monotone_under_negative_skew():
    """BFT-time monotonicity: a node whose skewed clock reads BEFORE the
    locked block's timestamp must still stamp votes at least time_iota
    past that block (state.go voteTime) — the max() guard, exercised at
    the _vote_time_ns seam with a real skew magnitude."""
    from tendermint_tpu.consensus.state import ConsensusState

    now = 1_700_000_000_000_000_000
    iota_ms = 10
    cs = types.SimpleNamespace(
        clock_skew_ns=-400_000_000,
        _now_ns=lambda: now - 400_000_000,
        rs=types.SimpleNamespace(
            locked_block=types.SimpleNamespace(
                header=types.SimpleNamespace(time_ns=now)),
            proposal_block=None),
        state=types.SimpleNamespace(
            consensus_params=types.SimpleNamespace(
                block=types.SimpleNamespace(time_iota_ms=iota_ms))))
    t = ConsensusState._vote_time_ns(cs)
    assert t == now + iota_ms * 1_000_000  # floor wins over the slow clock
    # a fast clock past the floor stamps its own (skewed) now
    cs._now_ns = lambda: now + 300_000_000
    assert ConsensusState._vote_time_ns(cs) == now + 300_000_000


# -- adaptive round timeouts -------------------------------------------------

def test_adaptive_timeouts_deterministic_and_clamped():
    cfg = test_consensus_config()
    cfg.timeout_mode = "adaptive"
    a, b = AdaptiveTimeouts(cfg), AdaptiveTimeouts(cfg)
    stream = [{"proposal_received": 0.02 + 0.001 * i,
               "prevote_sent": 0.001, "prevote_quorum": 0.004,
               "precommit_sent": 0.001, "precommit_quorum": 0.003}
              for i in range(20)]
    for obs in stream:
        a.observe(obs)
        b.observe(obs)
    # same observation stream → bit-identical timeout schedule
    for kind in ("propose", "prevote", "precommit"):
        for r in range(6):
            assert a.timeout(kind, r) == b.timeout(kind, r)
    assert a.snapshot() == b.snapshot()
    # clamp: never below spec, never above spec * max_scale; the per-round
    # delta escalation is the spec delta untouched
    for kind in ("propose", "prevote", "precommit"):
        spec = getattr(cfg, f"timeout_{kind}")
        delta = getattr(cfg, f"timeout_{kind}_delta")
        t0 = a.timeout(kind, 0)
        assert spec <= t0 <= spec * cfg.adaptive_max_scale
        assert a.timeout(kind, 3) == pytest.approx(t0 + 3 * delta)
    # a huge observation saturates at the ceiling
    sat = AdaptiveTimeouts(cfg)
    sat.observe({"proposal_received": 1e6})
    assert sat.timeout("propose", 0) == \
        cfg.timeout_propose * cfg.adaptive_max_scale


def test_adaptive_starts_at_spec_and_spec_mode_unchanged():
    """Differential pinning: before any observation adaptive sits exactly
    on the spec schedule, and spec mode never constructs a controller."""
    cfg = test_consensus_config()
    cfg.timeout_mode = "adaptive"
    at = AdaptiveTimeouts(cfg)
    for kind, spec_fn in (("propose", cfg.propose), ("prevote", cfg.prevote),
                          ("precommit", cfg.precommit)):
        for r in range(4):
            assert at.timeout(kind, r) == spec_fn(r)
    # missing stages (non-validator seals) leave the class untouched
    at.observe({})
    assert at.ewma == {"propose": None, "prevote": None, "precommit": None}
    assert at.heights_observed == 1
    # mode validation is strict
    bad = ConsensusConfig(timeout_mode="magic")
    with pytest.raises(ValueError, match="unknown timeout_mode"):
        bad.validate_timeout_mode()


# -- round-escalation determinism (satellite: both timeout modes) ------------

def _escalation_run(profile: str, seed: int, mode: str, heights: int = 12):
    """Deterministic escalation driver: the seeded LinkPolicy fate stream
    for ``profile`` decides each round's proposal delivery; the configured
    timeout schedule (spec or adaptive) decides whether the round
    escalates. Returns (per-height round counts, round_advances_total
    composition) — pure in (profile, seed, mode)."""
    cfg = test_consensus_config()
    cfg.timeout_mode = mode
    cfg.validate_timeout_mode()
    adaptive = AdaptiveTimeouts(cfg) if mode == "adaptive" else None

    def timeout(kind, r):
        if adaptive is not None:
            return adaptive.timeout(kind, r)
        return getattr(cfg, kind)(r)

    pol = LinkPolicy("proposer", "val", seed=seed, **LINK_PROFILES[profile])
    m = ConsensusMetrics(Registry())
    rounds = []
    for _h in range(heights):
        r = 0
        while True:
            fates = pol.plan()  # this round's proposal on the gray link
            delay = min(fates) if fates else None
            if delay is not None and delay <= timeout("timeout_propose"
                                                      .replace("timeout_", ""),
                                                      r):
                break
            m.round_advances_total.labels("timeout_propose").inc()
            r += 1
        m.rounds_per_height.observe(r + 1)
        rounds.append(r + 1)
        if adaptive is not None:
            adaptive.observe({"proposal_received": delay,
                              "prevote_sent": 0.001,
                              "prevote_quorum": 0.003,
                              "precommit_sent": 0.001,
                              "precommit_quorum": 0.003})
    comp = {reason: m.round_advances_total.value(reason)
            for reason in ("timeout_propose", "timeout_prevote",
                           "timeout_precommit", "polka_skip")}
    return rounds, comp


@pytest.mark.parametrize("mode", ["spec", "adaptive"])
def test_round_escalation_deterministic_per_seed(mode):
    r1, c1 = _escalation_run("gray", seed=7, mode=mode)
    r2, c2 = _escalation_run("gray", seed=7, mode=mode)
    assert r1 == r2, "same seed+profile diverged in per-height rounds"
    assert c1 == c2, "round_advances_total composition diverged"
    # gray's 60% loss forces real escalations, so the test is not vacuous
    assert c1["timeout_propose"] > 0
    assert max(r1) > 1
    # the schedule is seed-sensitive
    assert (r1, c1) != (_escalation_run("gray", seed=8, mode=mode))


def test_round_escalation_adaptive_never_escalates_more_than_spec():
    """Adaptive only RAISES the round-0 baseline toward observed reality
    (clamped at spec floor), so under one identical fate stream it can
    only absorb delays spec mode escalates on — never the reverse."""
    rs, cs_ = _escalation_run("gray", seed=7, mode="spec")
    ra, ca = _escalation_run("gray", seed=7, mode="adaptive")
    assert sum(ra) <= sum(rs)
    assert ca["timeout_propose"] <= cs_["timeout_propose"]
