"""tools/trace_merge.py: cross-node trace correlation — real tracer
exports (libs/trace.py set_identity headers) merged onto one wall clock,
per-node tracks, and the commit-skew report. Runs the tool both imported
and as a subprocess so CLI plumbing is covered too."""

import json
import os
import subprocess
import sys
import time

TOOLS = os.path.join(os.path.dirname(os.path.dirname(__file__)), "tools")
TOOL = os.path.join(TOOLS, "trace_merge.py")


def _mod():
    sys.path.insert(0, TOOLS)
    try:
        import trace_merge

        return trace_merge
    finally:
        sys.path.pop(0)


def _run(*args):
    return subprocess.run([sys.executable, TOOL, *args],
                          capture_output=True, text=True, timeout=60)


def test_self_test_passes():
    res = _run("--self-test")
    assert res.returncode == 0, res.stderr
    assert "self-test OK" in res.stdout


def _node_trace(path, node_id, heights, commit_offset_s):
    """A REAL tracer export: identity header + stage spans laid down via
    the same complete() call the timeline uses at seal."""
    from tendermint_tpu.libs.trace import Tracer

    t = Tracer(enabled=True)
    t.set_identity(node_id)
    base = time.perf_counter() * 1e6
    for i, h in enumerate(heights):
        end = base + (i + 1) * 1_000_000.0 + commit_offset_s * 1e6
        t.complete("stage_prevote_quorum", end - 9000.0, 5000.0,
                   height=h, round=0)
        t.complete("stage_commit_finalized", end - 2000.0, 2000.0,
                   height=h, round=0)
    return t.write(path)


def test_merge_real_tracer_exports(tmp_path):
    tm = _mod()
    p0 = _node_trace(str(tmp_path / "t0.json"), "node0", [4, 5, 6], 0.0)
    p1 = _node_trace(str(tmp_path / "t1.json"), "node1", [4, 5, 6], 0.030)
    docs = [(tm.node_label(tm.load_trace(p), p), tm.load_trace(p))
            for p in (p0, p1)]
    merged = tm.merge(docs)
    assert merged["aligned"] is True
    assert merged["nodes"] == ["node0", "node1"]
    meta = [e for e in merged["traceEvents"] if e.get("ph") == "M"]
    assert {m["args"]["name"] for m in meta} == {"node0", "node1"}
    assert {e["pid"] for e in merged["traceEvents"]} == {1, 2}
    report = tm.skew_report(docs)
    assert report["heights"] == 3
    # both tracers run in THIS process (one wall clock): the injected 30ms
    # offset must come back, modulo the per-call clock-sampling jitter of
    # set_identity (two clocks read non-atomically)
    assert 25.0 < report["mean_spread_ms"] < 35.0, report
    assert all(r["first"] == "node0" and r["last"] == "node1"
               for r in report["per_height"])
    for s in report["slowest_stage_per_node"].values():
        assert s["slowest_stage"] == "prevote_quorum"


def test_cli_merge_and_skew(tmp_path):
    p0 = _node_trace(str(tmp_path / "a.json"), "node-a", [2, 3], 0.0)
    p1 = _node_trace(str(tmp_path / "b.json"), "node-b", [2, 3], 0.050)
    out = str(tmp_path / "merged.json")
    res = _run(p0, p1, "--out", out)
    assert res.returncode == 0, res.stderr
    assert "wrote merged trace for 2 nodes" in res.stdout
    assert "node-a -> node-b" in res.stdout
    doc = json.load(open(out))
    assert doc["displayTimeUnit"] == "ms"
    assert doc["aligned"] is True
    # the merged file is itself a valid trace_summary input
    res2 = subprocess.run(
        [sys.executable, os.path.join(TOOLS, "trace_summary.py"),
         "--json", out], capture_output=True, text=True, timeout=60)
    assert res2.returncode == 0, res2.stderr
    assert "stage_commit_finalized" in json.loads(res2.stdout)
    # JSON skew report
    res3 = _run(p0, p1, "--json")
    report = json.loads(res3.stdout)
    assert report["heights"] == 2 and report["max_spread_ms"] > 0


def test_single_file_errors():
    res = _run("only-one.json")
    assert res.returncode != 0
