"""Differential tests for the sparse template wire format: on-device SHA
preimage assembly must be byte-identical to the dense prepare_batch path
and to the host spec (the reference's scalar verify semantics,
crypto/ed25519/ed25519.go:148-155).

The sparse path exists because commit/vote batches share almost the whole
message (types/canonical.go sign-bytes differ only in timestamp bytes), so
shipping a template + differing columns cuts host->device transfer ~2.5x.
"""

import numpy as np
import pytest
pytest.importorskip("cryptography", reason="needs the optional 'cryptography' package (absent in slim containers)")

from cryptography.hazmat.primitives.asymmetric.ed25519 import Ed25519PrivateKey

from tendermint_tpu.crypto import ed25519 as host
from tendermint_tpu.crypto.ed25519_jax import verify as V


def _mk_corpus(n=300, seed=3):
    rng = np.random.default_rng(seed)
    base = bytes(rng.integers(0, 256, 120, dtype=np.uint8))
    pks, msgs, sigs = [], [], []
    for i in range(n):
        priv = Ed25519PrivateKey.from_private_bytes(
            bytes(rng.integers(0, 256, 32, dtype=np.uint8)))
        m = bytearray(base)
        m[40:48] = int(i).to_bytes(8, "little")
        if i % 7 == 0:
            m = m[:100 + (i % 19)]  # length variation within one bucket
        m = bytes(m)
        s = priv.sign(m)
        if i % 11 == 0:
            s = s[:32] + bytes(32)  # corrupt scalar -> reject
        if i % 13 == 0:
            m = m[:1] + bytes([m[1] ^ 1]) + m[2:]  # tamper -> reject
        pks.append(priv.public_key().public_bytes_raw())
        msgs.append(m)
        sigs.append(s)
    return pks, msgs, sigs


def test_sparse_matches_dense_and_host():
    pks, msgs, sigs = _mk_corpus()
    n = len(pks)
    truth = np.array([host.verify(p, m, s)
                      for p, m, s in zip(pks, msgs, sigs)])
    assert truth.sum() not in (0, n)  # corpus mixes accepts and rejects

    sp = V.prepare_sparse_stream(pks, msgs, sigs, chunk=128)
    assert sp is not None, "vote-like corpus must take the sparse path"
    args, ok = sp
    v_sparse = np.asarray(
        V._verify_sparse_stream_kernel(*args)).reshape(-1)[:n] & ok
    v_dense = V.batch_verify(pks, msgs, sigs)
    np.testing.assert_array_equal(v_dense, truth)
    np.testing.assert_array_equal(v_sparse, truth)

    # the public stream entry routes through sparse and agrees
    v_stream = V.batch_verify_stream(pks, msgs, sigs, chunk=128)
    np.testing.assert_array_equal(v_stream, truth)


def test_sparse_rejects_bad_lengths_and_noncanonical():
    pks, msgs, sigs = _mk_corpus(n=140, seed=9)
    # malformed inputs the host path rejects before any curve math
    sigs[0] = sigs[0][:63]          # short sig
    pks[1] = pks[1] + b"\x00"       # long pk
    sigs[2] = sigs[2][:32] + (host.L).to_bytes(32, "little")  # s == L
    sigs[3] = sigs[3][:32] + b"\xff" * 32                     # s >> L
    truth = np.array([host.verify(p, m, s)
                      for p, m, s in zip(pks, msgs, sigs)])
    assert not truth[:4].any()
    v = V.batch_verify_stream(pks, msgs, sigs, chunk=128)
    np.testing.assert_array_equal(v, truth)


def test_dissimilar_messages_fall_back_to_dense():
    rng = np.random.default_rng(1)
    pks, msgs, sigs = [], [], []
    for _ in range(64):
        priv = Ed25519PrivateKey.from_private_bytes(
            bytes(rng.integers(0, 256, 32, dtype=np.uint8)))
        m = bytes(rng.integers(0, 256, 120, dtype=np.uint8))
        pks.append(priv.public_key().public_bytes_raw())
        msgs.append(m)
        sigs.append(priv.sign(m))
    assert V.prepare_sparse_stream(pks, msgs, sigs, chunk=128) is None
    assert V.batch_verify_stream(pks, msgs, sigs, chunk=128).all()


def test_pk_device_cache_reuses_buffer():
    pks, msgs, sigs = _mk_corpus(n=128, seed=5)
    V._PK_DEVICE_CACHE.clear()
    sp1 = V.prepare_sparse_stream(pks, msgs, sigs, chunk=128)
    assert sp1 is not None and len(V._PK_DEVICE_CACHE) == 1
    buf1 = sp1[0][5]
    # same keys again (fast-sync: same valset every block) -> same buffer
    sp2 = V.prepare_sparse_stream(pks, msgs, sigs, chunk=128)
    assert sp2[0][5] is buf1
    # verdicts unaffected by the cache hit
    n = len(pks)
    v1 = np.asarray(V._verify_sparse_stream_kernel(*sp1[0])).reshape(-1)[:n] & sp1[1]
    truth = np.array([host.verify(p, m, s)
                      for p, m, s in zip(pks, msgs, sigs)])
    np.testing.assert_array_equal(v1, truth)
