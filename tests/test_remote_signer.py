"""Remote signer: the node-side SignerClient over a listener endpoint, the
key-side SignerServer dialing in, double-sign protection enforced remotely
(reference privval/signer_client.go, signer_listener_endpoint.go).
"""

import asyncio
import threading

import pytest

pytest.importorskip("cryptography", reason="needs the optional 'cryptography' package (absent in slim containers)")

from tendermint_tpu.privval.file_pv import DoubleSignError, FilePV
from tendermint_tpu.privval.signer import (
    RemoteSignerError,
    SignerClient,
    SignerListenerEndpoint,
    SignerServer,
)
from tendermint_tpu.types.basic import BlockID, PartSetHeader, SignedMsgType
from tendermint_tpu.types.proposal import Proposal
from tendermint_tpu.types.vote import Vote

CHAIN = "signer-chain"


def test_remote_signer_round_trip():
    pv = FilePV.generate("", "")
    endpoint = SignerListenerEndpoint("127.0.0.1", 0)
    server = SignerServer(pv, CHAIN, ("127.0.0.1", endpoint.port))
    server.start()
    try:
        endpoint.wait_for_signer(timeout=10.0)
        client = SignerClient(endpoint, CHAIN)

        # pubkey round-trips
        assert client.get_pub_key().bytes() == pv.get_pub_key().bytes()
        assert client.ping()

        # vote signing matches local signing semantics
        bid = BlockID(b"\x0a" * 32, PartSetHeader(1, b"\x0b" * 32))
        vote = Vote(SignedMsgType.PREVOTE, 5, 0, bid,
                    1_700_000_000_000_000_000,
                    pv.get_pub_key().address(), 0, b"")
        client.sign_vote(CHAIN, vote)
        assert vote.signature
        assert pv.get_pub_key().verify_signature(
            vote.sign_bytes(CHAIN), vote.signature)

        # proposal signing
        prop = Proposal(6, 0, -1, bid, 1_700_000_000_000_000_001)
        client.sign_proposal(CHAIN, prop)
        assert pv.get_pub_key().verify_signature(
            prop.sign_bytes(CHAIN), prop.signature)

        # double-sign protection holds ACROSS the socket: conflicting vote
        # at the same HRS is refused by the remote FilePV
        conflicting = Vote(SignedMsgType.PREVOTE, 5, 0,
                           BlockID(b"\xff" * 32, PartSetHeader(1, b"\x0b" * 32)),
                           1_700_000_000_000_000_002,
                           pv.get_pub_key().address(), 0, b"")
        with pytest.raises(RemoteSignerError):
            client.sign_vote(CHAIN, conflicting)
    finally:
        server.stop()
        endpoint.close()


def test_signer_connection_is_encrypted_and_pinned():
    """The privval link rides SecretConnection; pinning the wrong signer key
    must refuse the connection (advisor r3: plaintext privval TCP). The
    accept loop drops the bad conn and keeps accepting rather than
    crashing node startup."""
    from tendermint_tpu.crypto import Ed25519PrivKey

    pv = FilePV.generate("", "")
    signer_key = Ed25519PrivKey.generate()
    wrong_key = Ed25519PrivKey.generate()

    # wrong pinned key: endpoint rejects the conn; wait deadline expires
    endpoint = SignerListenerEndpoint(
        "127.0.0.1", 0,
        expected_signer_key=wrong_key.pub_key().bytes())
    server = SignerServer(pv, CHAIN, ("127.0.0.1", endpoint.port),
                          conn_key=signer_key)
    server.start()
    try:
        with pytest.raises(RemoteSignerError):
            endpoint.wait_for_signer(timeout=2.5)
    finally:
        server.stop()
        endpoint.close()

    # right pinned key: serves normally
    endpoint = SignerListenerEndpoint(
        "127.0.0.1", 0,
        expected_signer_key=signer_key.pub_key().bytes())
    server = SignerServer(pv, CHAIN, ("127.0.0.1", endpoint.port),
                          conn_key=signer_key)
    server.start()
    try:
        endpoint.wait_for_signer(timeout=10.0)
        client = SignerClient(endpoint, CHAIN)
        assert client.get_pub_key().bytes() == pv.get_pub_key().bytes()
    finally:
        server.stop()
        endpoint.close()
