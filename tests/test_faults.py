"""The deterministic fault-injection plane (libs/faults.py) and the
fail-point kill switch (libs/fail.py): grammar, per-site seeded streams
(a chaos run must replay EXACTLY from its spec+seed), trigger modifiers,
metric accounting, and the named/threaded fail-point forms.
"""

import os
import subprocess
import sys
import threading

import pytest

from tendermint_tpu.libs import fail
from tendermint_tpu.libs.faults import (
    ENV_SEED,
    ENV_SPEC,
    FaultPlane,
    InjectedFault,
    faults,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- grammar -----------------------------------------------------------------

def test_spec_grammar_modifiers():
    fp = FaultPlane().configure("a,b@0.5,c*3,d+2,e@0.25*4+1")
    counts = fp.counts()
    assert set(counts) == {"a", "b", "c", "d", "e"}
    # bare site: fires every evaluation
    assert all(fp.fire("a") for _ in range(10))
    # count-limited: exactly 3 fires then quiet
    fires = sum(fp.fire("c") for _ in range(10))
    assert fires == 3 and fp.fires("c") == 3
    # skip: first 2 evaluations never fire
    assert [fp.fire("d") for _ in range(4)] == [False, False, True, True]


def test_spec_grammar_rejects_garbage():
    for bad in ("a@1.5", "a@-0.1", "a*-1", "a+-1", "@0.5", "a@x", "a*x"):
        with pytest.raises(ValueError):
            FaultPlane().configure(bad)


def test_unknown_site_never_fires():
    fp = FaultPlane().configure("a")
    assert not fp.fire("b")
    assert fp.fires("b") == 0


def test_disabled_plane_is_inert():
    fp = FaultPlane()
    assert not fp.enabled
    assert not fp.fire("anything")
    fp.inject("anything")  # no-op, must not raise


# -- determinism -------------------------------------------------------------

def test_probabilistic_site_replays_exactly():
    seq1 = [FaultPlane().configure("s@0.3", seed=7).fire("s")
            for _ in range(1)]
    fp1 = FaultPlane().configure("s@0.3", seed=7)
    fp2 = FaultPlane().configure("s@0.3", seed=7)
    seq1 = [fp1.fire("s") for _ in range(200)]
    seq2 = [fp2.fire("s") for _ in range(200)]
    assert seq1 == seq2
    assert 20 < sum(seq1) < 100  # actually probabilistic, not degenerate
    # a different seed yields a different schedule
    fp3 = FaultPlane().configure("s@0.3", seed=8)
    assert seq1 != [fp3.fire("s") for _ in range(200)]


def test_sites_draw_independent_streams():
    """Interleaving evaluations of OTHER sites must not perturb a site's
    own schedule — per-site RNGs are the whole point."""
    fp1 = FaultPlane().configure("x@0.4,y@0.4", seed=3)
    solo = FaultPlane().configure("x@0.4", seed=3)
    interleaved = []
    for _ in range(100):
        interleaved.append(fp1.fire("x"))
        fp1.fire("y")
    assert interleaved == [solo.fire("x") for _ in range(100)]


# -- injection ---------------------------------------------------------------

def test_inject_raises_default_and_custom():
    fp = FaultPlane().configure("site*1")
    with pytest.raises(InjectedFault) as ei:
        fp.inject("site")
    assert ei.value.site == "site"
    fp.configure("site*1")
    with pytest.raises(OSError):
        fp.inject("site", lambda s: OSError(5, f"injected at {s}"))
    # count exhausted: quiet again
    fp.inject("site")


def test_env_configuration():
    fp = FaultPlane().configure_from_env(
        {ENV_SPEC: "a@0.5,b*2", ENV_SEED: "11"})
    assert fp.enabled and fp.seed == 11 and set(fp.counts()) == {"a", "b"}
    # empty env leaves the plane untouched
    fp2 = FaultPlane().configure_from_env({})
    assert not fp2.enabled


def test_reset_disarms():
    fp = FaultPlane().configure("a")
    assert fp.fire("a")
    fp.reset()
    assert not fp.enabled and not fp.fire("a") and fp.spec == ""


def test_singleton_metrics_accounting():
    from tendermint_tpu.libs import faults as faults_mod
    from tendermint_tpu.libs.metrics import FaultMetrics, Registry

    fm = FaultMetrics(Registry())
    faults_mod.set_fault_metrics(fm)
    try:
        faults.configure("m.site*2")
        assert faults.fire("m.site") and faults.fire("m.site")
        assert not faults.fire("m.site")
        assert fm.faults_injected_total.value("m.site") == 2.0
    finally:
        faults_mod.set_fault_metrics(None)
        faults.reset()


def test_fire_is_thread_safe_under_count_limit():
    """N threads hammering a *K site must fire exactly K times total."""
    fp = FaultPlane().configure("t*50")
    hits = []

    def worker():
        for _ in range(100):
            if fp.fire("t"):
                hits.append(1)

    threads = [threading.Thread(target=worker) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(hits) == 50


# -- fail.py: the kill switch ------------------------------------------------

def test_fail_point_counter_thread_safe(monkeypatch):
    """Concurrent fail points must each get a distinct index — a racy
    double-increment would make the crash matrix skip boundaries."""
    monkeypatch.delenv("TMTPU_FAIL_INDEX", raising=False)
    monkeypatch.setenv("TMTPU_FAIL_INDEX", "100000")  # armed, unreachable
    fail.reset()
    threads = [threading.Thread(
        target=lambda: [fail.fail_point() for _ in range(500)])
        for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert fail.counter() == 4000


def test_fail_point_named_kills_subprocess():
    """TMTPU_FAIL_POINT=<site> dies at that named point, regardless of how
    many anonymous points were passed on the way."""
    code = (
        "from tendermint_tpu.libs.fail import fail_point\n"
        "fail_point()\n"
        "fail_point('other.site')\n"
        "fail_point('target.site')\n"
        "print('SURVIVED')\n"
    )
    env = dict(os.environ, TMTPU_FAIL_POINT="target.site",
               PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""))
    env.pop("TMTPU_FAIL_INDEX", None)
    r = subprocess.run([sys.executable, "-c", code], env=env,
                       capture_output=True, text=True, timeout=60)
    assert r.returncode == 1, r.stderr
    assert "target.site" in r.stderr and "SURVIVED" not in r.stdout
    # without the env the same script survives all three points
    env.pop("TMTPU_FAIL_POINT")
    r2 = subprocess.run([sys.executable, "-c", code], env=env,
                        capture_output=True, text=True, timeout=60)
    assert r2.returncode == 0 and "SURVIVED" in r2.stdout


def test_manifest_validates_fault_spec():
    from tendermint_tpu.e2e.manifest import NodeManifest

    nm = NodeManifest(name="v0", faults="wal.fsync*1+3", faults_seed=9)
    nm.validate()
    bad = NodeManifest(name="v1", faults="wal.fsync@9")
    with pytest.raises(ValueError, match="bad faults spec"):
        bad.validate()
    # a typo'd site name arms nothing and the chaos run passes vacuously —
    # the manifest is the operator seam, so it rejects unknown sites hard
    typo = NodeManifest(name="v2", faults="wal.fsycn*1")
    with pytest.raises(ValueError, match="unknown fault site"):
        typo.validate()


def test_armed_is_lock_free_membership():
    fp = FaultPlane().configure("wal.fsync@0.0")
    assert fp.armed("wal.fsync")          # armed even at prob 0
    assert not fp.armed("db.write_batch")
    assert not fp.armed("wal.fsync") or fp.fire("wal.fsync") is False
    fp.reset()
    assert not fp.armed("wal.fsync")


# -- content corruption (mutate) ---------------------------------------------

def test_mutate_disabled_passthrough():
    fp = FaultPlane()
    data = b"payload-bytes"
    assert fp.mutate("net.corrupt", data) is data


def test_mutate_flips_exactly_one_bit_when_armed():
    fp = FaultPlane().configure("net.corrupt", seed=3)
    data = bytes(range(64))
    out = fp.mutate("net.corrupt", data)
    assert out != data and len(out) == len(data)
    diffs = [(a ^ b) for a, b in zip(data, out) if a != b]
    assert len(diffs) == 1 and bin(diffs[0]).count("1") == 1
    assert fp.fires("net.corrupt") == 1


def test_mutate_schedule_replays_exactly():
    def run(seed):
        fp = FaultPlane().configure("net.corrupt@0.5*8", seed=seed)
        return [fp.mutate("net.corrupt", bytes(32)) for _ in range(40)]

    assert run(11) == run(11)
    assert run(11) != run(12)
    # the cap bounds the corrupted count deterministically
    fp = FaultPlane().configure("net.corrupt@0.5*8", seed=11)
    corrupted = sum(fp.mutate("net.corrupt", bytes(32)) != bytes(32)
                    for _ in range(100))
    assert corrupted == 8 == fp.fires("net.corrupt")


def test_mutate_empty_payload_untouched():
    fp = FaultPlane().configure("net.corrupt")
    assert fp.mutate("net.corrupt", b"") == b""
    assert fp.fires("net.corrupt") == 0  # nothing to lie about, no fire


def test_mutate_unarmed_site_does_not_draw():
    """A mutate on site A must not perturb site B's stream (per-site RNGs)."""
    fp = FaultPlane().configure("a@0.5,b@0.5", seed=5)
    seq_b = [fp.fire("b") for _ in range(20)]
    fp2 = FaultPlane().configure("a@0.5,b@0.5", seed=5)
    for _ in range(30):
        fp2.mutate("a", b"xx")
    assert [fp2.fire("b") for _ in range(20)] == seq_b


def test_adversarial_sites_in_catalog():
    from tendermint_tpu.libs.faults import KNOWN_SITES

    for site in ("net.corrupt", "statesync.lying_snapshot",
                 "statesync.lying_chunk", "blocksync.bad_block"):
        assert site in KNOWN_SITES
