"""Torn-write fault plane (faults.tear) + WAL repair-on-open + the
crash-at-a-durability-boundary fast subset: torn WAL tails repaired so
appended records are never stranded, torn privval state refused at load,
torn db windows retried whole, a mid-group-commit kill replaying the
durable prefix, and MempoolWAL replay staying idempotent over torn lines.
"""

import json
import os
import struct
import subprocess
import sys
import zlib

import pytest

from tendermint_tpu.consensus.wal import WAL
from tendermint_tpu.libs.db import BufferedDB, MemDB, SQLiteDB
from tendermint_tpu.libs.faults import FaultPlane, faults

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# --- the tear primitive ------------------------------------------------------

class TestTearPrimitive:
    def test_disabled_passthrough(self):
        plane = FaultPlane()
        assert plane.tear("wal.torn_write", b"abc") == b"abc"
        assert plane.tear_index("db.torn_write", 5) is None

    def test_tear_is_strictly_partial(self):
        plane = FaultPlane().configure("t.site", seed=3)
        data = b"A" * 100
        torn = plane.tear("t.site", data)
        assert torn != data
        # prefix of the original plus (possibly) garbage; the original
        # payload never survives whole
        cut = 0
        while cut < min(len(torn), len(data)) and torn[cut] == data[cut]:
            cut += 1
        assert cut < len(data)

    def test_deterministic_per_seed(self):
        # the i-th draw of a site replays identically for a seed
        p1 = FaultPlane().configure("s", seed=7)
        p2 = FaultPlane().configure("s", seed=7)
        for _ in range(10):
            assert p1.tear("s", b"x" * 33) == p2.tear("s", b"x" * 33)
            assert p1.tear_index("s", 20) == p2.tear_index("s", 20)
        # and a different seed produces a different schedule
        a = [FaultPlane().configure("s", seed=7).tear("s", bytes(64))
             for _ in range(1)]
        b = [FaultPlane().configure("s", seed=8).tear("s", bytes(64))
             for _ in range(1)]
        assert a != b

    def test_tear_index_bounds(self):
        plane = FaultPlane().configure("s", seed=1)
        for n in (1, 2, 17):
            cut = plane.tear_index("s", n)
            assert cut is not None and 0 <= cut < n
        assert plane.tear_index("s", 0) is None

    def test_empty_payload_passthrough(self):
        plane = FaultPlane().configure("s", seed=1)
        assert plane.tear("s", b"") == b""

    def test_new_sites_are_known(self):
        from tendermint_tpu.libs.faults import is_known_site

        for site in ("wal.torn_write", "db.torn_write",
                     "privval.torn_state", "mempool.wal_torn"):
            assert is_known_site(site), site


# --- WAL repair-on-open ------------------------------------------------------

class TestWALRepair:
    def _records(self, path):
        return [m.data["height"] for m in WAL(path, repair=False)
                .iter_messages() if m.type == "end_height"]

    def test_clean_open_repairs_nothing(self, tmp_path):
        path = str(tmp_path / "w.wal")
        wal = WAL(path)
        wal.write_end_height(1, 1)
        wal.close()
        wal2 = WAL(path)
        assert wal2.repairs == 0 and wal2.repaired_bytes == 0

    def test_garbage_tail_truncated_and_appends_replayable(self, tmp_path):
        """The stranded-records regression: garbage after the last good
        record used to swallow every subsequent append at replay time."""
        path = str(tmp_path / "w.wal")
        wal = WAL(path)
        wal.write_end_height(1, 1)
        wal.close()
        good_size = os.path.getsize(path)
        with open(path, "ab") as f:
            f.write(b"\xde\xad\xbe\xef garbage")
        # without repair, an append after the garbage is stranded
        assert self._records(path) == [0, 1]
        wal2 = WAL(path)  # repair-on-open
        assert wal2.repairs == 1
        assert wal2.repaired_bytes == os.path.getsize(path) - good_size \
            or os.path.getsize(path) >= good_size
        wal2.write_end_height(2, 2)
        wal2.close()
        assert self._records(path) == [0, 1, 2]

    def test_torn_frame_tail_truncated(self, tmp_path):
        """A partial frame (valid-looking header, short payload) — the
        exact shape faults.tear leaves — is repaired the same way."""
        path = str(tmp_path / "w.wal")
        wal = WAL(path)
        wal.write_end_height(1, 1)
        wal.close()
        payload = json.dumps({"time_ns": 9, "type": "end_height",
                              "data": {"height": 2}}).encode()
        crc = zlib.crc32(payload) & 0xFFFFFFFF
        frame = struct.pack(">II", crc, len(payload)) + payload
        with open(path, "ab") as f:
            f.write(frame[:len(frame) // 2])   # torn mid-payload
        wal2 = WAL(path)
        assert wal2.repairs == 1
        wal2.write_end_height(3, 3)
        wal2.close()
        assert self._records(path) == [0, 1, 3]

    def test_armed_tear_site_end_to_end(self, tmp_path):
        """Arm the production byte-emit site: the torn append never
        replays whole, and a reopen + append keeps the log usable."""
        path = str(tmp_path / "w.wal")
        wal = WAL(path)
        for h in range(1, 4):
            wal.write_end_height(h, h)
        faults.configure("wal.torn_write*1", seed=5)
        wal.write_end_height(4, 4)
        assert faults.fires("wal.torn_write") == 1
        faults.reset()
        wal.close()
        replayed = self._records(path)
        assert replayed[:4] == [0, 1, 2, 3] and 4 not in replayed
        wal2 = WAL(path)
        wal2.write_end_height(5, 5)
        wal2.close()
        assert self._records(path)[-1] == 5

    def test_corrupt_mid_file_not_silently_truncated_by_reader(self, tmp_path):
        """iter_messages (read path) still stops at corruption without
        modifying the file — only an append-mode open repairs."""
        path = str(tmp_path / "w.wal")
        wal = WAL(path)
        wal.write_end_height(1, 1)
        wal.write_end_height(2, 2)
        wal.close()
        raw = bytearray(open(path, "rb").read())
        raw[-1] ^= 0xFF
        open(path, "wb").write(bytes(raw))
        size = os.path.getsize(path)
        assert self._records(path) == [0, 1]
        assert os.path.getsize(path) == size  # repair=False never truncates


def test_group_kill_commits_nothing_posthumously(tmp_path):
    """The in-proc kill (KilledAtFailPoint) must behave like process
    death inside a group: the context exit flushes NOTHING — otherwise
    the mid-group-commit boundary is vacuously durable."""
    from tendermint_tpu.libs import fail

    path = str(tmp_path / "k.wal")
    wal = WAL(path)
    wal.write_end_height(1, 1)            # durable pre-group record
    size0 = os.path.getsize(path)
    fail.arm_raise("wal.mid_group_commit")
    with pytest.raises(fail.KilledAtFailPoint):
        with wal.group():
            wal.write_end_height(2, 2)
            wal.write_end_height(3, 3)    # 2nd group record -> boundary
    assert fail.killed_at() == "wal.mid_group_commit"
    # the batch stayed in the userspace buffer: no posthumous flush
    assert os.path.getsize(path) == size0
    # a later group on the same (still-live-in-test) handle works again
    fail.reset()
    with wal.group():
        wal.write_end_height(4, 4)
    wal.close()
    heights = [m.data["height"] for m in WAL(path).iter_messages()
               if m.type == "end_height"]
    assert heights[-1] == 4


def test_mid_group_commit_kill_replays_durable_prefix(tmp_path):
    """Kill a subprocess at the wal.mid_group_commit fail point: records
    appended before the kill that reached the OS replay; the batch's
    unflushed remainder is gone; repair-on-open + a fresh append work."""
    path = str(tmp_path / "g.wal")
    script = f"""
import os
from tendermint_tpu.consensus.wal import WAL
wal = WAL({path!r})
wal.write_end_height(1, 1)          # durable pre-group record
with wal.group():
    wal.write_end_height(2, 2)      # appended, flush pending
    wal.write_end_height(3, 3)      # second group record -> fail point
raise SystemExit("fail point should have killed us")
"""
    env = dict(os.environ, TMTPU_FAIL_POINT="wal.mid_group_commit",
               JAX_PLATFORMS="cpu")
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run([sys.executable, "-c", script], env=env, cwd=REPO,
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == 1, (proc.returncode, proc.stderr)
    assert "wal.mid_group_commit" in proc.stderr
    # replay: the pre-group record is there; the group's records died
    # buffered (os._exit discards userspace buffers — the SIGKILL analog)
    heights = [m.data["height"] for m in WAL(path, repair=False)
               .iter_messages() if m.type == "end_height"]
    assert heights[:2] == [0, 1], heights
    assert 3 not in heights
    # restart appends cleanly after repair-on-open
    wal = WAL(path)
    wal.write_end_height(9, 9)
    wal.close()
    heights = [m.data["height"] for m in WAL(path).iter_messages()
               if m.type == "end_height"]
    assert heights[-1] == 9


# --- torn db window ----------------------------------------------------------

class TestTornDBWindow:
    def test_memdb_window_retried_whole(self):
        base = MemDB()
        buf = BufferedDB(base)
        keys = [b"k%02d" % i for i in range(20)]
        for k in keys:
            buf.set(k, b"v" + k)
        faults.configure("db.torn_write*1", seed=2)
        with pytest.raises(OSError):
            buf.flush()
        fired = faults.fires("db.torn_write")
        faults.reset()
        assert fired == 1
        # a PREFIX may have landed (torn), but the staged window survives
        # and the disarmed retry lands every record (idempotent upserts)
        assert buf.pending() > 0
        buf.flush()
        for k in keys:
            assert base.get(k) == b"v" + k, f"record lost across retry: {k}"

    def test_sqlite_window_rolls_back_then_retried_whole(self, tmp_path):
        base = SQLiteDB(str(tmp_path / "t.db"))
        buf = BufferedDB(base)
        keys = [b"s%02d" % i for i in range(20)]
        for k in keys:
            buf.set(k, b"v" + k)
        faults.configure("db.torn_write*1", seed=2)
        with pytest.raises(OSError):
            buf.flush()
        faults.reset()
        # transactional base: the torn batch left NOTHING behind
        assert all(base.get(k) is None for k in keys)
        buf.flush()
        for k in keys:
            assert base.get(k) == b"v" + k
        base.close()


# --- torn privval state ------------------------------------------------------

class TestTornPrivvalState:
    def _pv(self, tmp_path, seed=b"\x11"):
        from tendermint_tpu.privval.file_pv import FilePV

        key = str(tmp_path / "pv_key.json")
        state = str(tmp_path / "pv_state.json")
        pv = FilePV.generate(key, state, seed=seed * 32)
        pv.save()
        return pv, key, state

    def _vote(self, h):
        from tendermint_tpu.types import (BlockID, PartSetHeader,
                                          SignedMsgType, Vote)

        bid = BlockID(b"\x01" * 32, PartSetHeader(1, b"\x02" * 32))
        return Vote(SignedMsgType.PREVOTE, h, 0, bid,
                    1_700_000_000_000_000_000, b"\xaa" * 20, 0)

    def test_torn_state_refused_with_actionable_error(self, tmp_path):
        from tendermint_tpu.privval.file_pv import (CorruptSignStateError,
                                                    FilePV)

        pv, key, state = self._pv(tmp_path)
        pv.sign_vote("chain", self._vote(1))
        faults.configure("privval.torn_state*1", seed=4)
        pv.sign_vote("chain", self._vote(2))   # the save is torn
        faults.reset()
        with pytest.raises(CorruptSignStateError) as ei:
            FilePV.load(key, state)
        msg = str(ei.value)
        assert state in msg and "double-sign" in msg

    def test_corrupt_state_never_silently_resets(self, tmp_path):
        from tendermint_tpu.privval.file_pv import (CorruptSignStateError,
                                                    FilePV)

        pv, key, state = self._pv(tmp_path, seed=b"\x12")
        pv.sign_vote("chain", self._vote(5))
        with open(state, "w") as f:
            f.write('{"height": ')  # torn json
        with pytest.raises(CorruptSignStateError):
            FilePV.load(key, state)

    def test_missing_state_file_warns_loudly(self, tmp_path, caplog):
        import logging

        from tendermint_tpu.privval.file_pv import FilePV

        pv, key, state = self._pv(tmp_path, seed=b"\x13")
        pv.sign_vote("chain", self._vote(3))
        os.unlink(state)
        with caplog.at_level(logging.WARNING, logger="tmtpu.privval"):
            pv2 = FilePV.load(key, state)
        assert pv2.last_sign_state.height == 0
        assert any("absent" in r.message for r in caplog.records)

    def test_atomic_write_survives_normal_save_load(self, tmp_path):
        from tendermint_tpu.privval.file_pv import FilePV

        pv, key, state = self._pv(tmp_path, seed=b"\x14")
        pv.sign_vote("chain", self._vote(7))
        pv2 = FilePV.load(key, state)
        assert pv2.last_sign_state.height == 7


# --- torn mempool WAL --------------------------------------------------------

class TestTornMempoolWAL:
    def _mempool(self, wal_dir=None):
        from tendermint_tpu.abci.example.kvstore import KVStoreApplication
        from tendermint_tpu.mempool import CListMempool
        from tendermint_tpu.mempool.clist_mempool import init_mempool_wal
        from tendermint_tpu.proxy import AppConns, local_client_creator

        conns = AppConns(local_client_creator(KVStoreApplication()))
        conns.start()
        mp = CListMempool(conns.mempool, max_txs=10000)
        if wal_dir is not None:
            init_mempool_wal(mp, wal_dir)
        return mp, conns

    def test_partial_tail_never_merges_with_next_append(self, tmp_path):
        """Repair-on-open: a newline-less torn tail must be truncated at
        the next open — appending after it would merge two hex lines into
        one (often still-valid!) bogus tx and lose the real one."""
        from tendermint_tpu.mempool.clist_mempool import (MempoolWAL,
                                                          init_mempool_wal)
        from tendermint_tpu.mempool.ingest import replay_mempool_wal

        wal_dir = str(tmp_path / "mwal")
        mp, conns = self._mempool(wal_dir)
        try:
            mp.check_tx(b"aa=1")
            mp._wal.close()
        finally:
            conns.stop()
        path = os.path.join(wal_dir, "wal")
        with open(path, "ab") as f:
            f.write(b"beef")          # torn line, no newline
        # reopen via the production path; the torn fragment is truncated
        MempoolWAL(wal_dir).close()
        assert open(path, "rb").read().endswith(b"\n")
        mp2, conns2 = self._mempool(wal_dir)
        try:
            mp2.check_tx(b"bb=2")     # appended post-repair
            mp2._wal.close()
        finally:
            conns2.stop()
        fresh, conns3 = self._mempool()
        try:
            replayed, _ = replay_mempool_wal(fresh, wal_dir)
            assert replayed == 2
            txs = {bytes(tx) for tx in fresh.reap_max_txs(10)}
            assert txs == {b"aa=1", b"bb=2"}, txs  # no merged bogus tx
        finally:
            conns3.stop()

    def test_torn_line_skipped_and_replay_idempotent(self, tmp_path):
        from tendermint_tpu.mempool.ingest import replay_mempool_wal

        wal_dir = str(tmp_path / "mwal")
        mp, conns = self._mempool(wal_dir)
        try:
            for i in range(8):
                mp.check_tx(b"tx%02d=v" % i)
            # tear the LAST line (the tail a crash would tear)
            faults.configure("mempool.wal_torn*1", seed=6)
            mp.check_tx(b"torn-tail=v")
            assert faults.fires("mempool.wal_torn") == 1
            faults.reset()
            mp._wal.close()
        finally:
            conns.stop()

        fresh, conns2 = self._mempool()
        try:
            replayed1, skipped1 = replay_mempool_wal(fresh, wal_dir)
            assert replayed1 >= 8  # the intact prefix re-admits
            # idempotency: a second replay admits NOTHING new
            replayed2, skipped2 = replay_mempool_wal(fresh, wal_dir)
            assert replayed2 == 0
            assert skipped2 >= replayed1
        finally:
            conns2.stop()
