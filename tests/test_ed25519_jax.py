"""Differential tests: batched TPU/JAX ed25519 verify vs the host spec.

Byte-identical accept/reject is the contract (SURVEY.md north star):
every decision of ed25519_jax.batch_verify must equal
tendermint_tpu.crypto.ed25519.verify on the same inputs.
"""

import random

import numpy as np
import pytest

from tendermint_tpu.crypto import ed25519 as ed
from tendermint_tpu.crypto.ed25519_jax import batch_verify


def _differential(cases):
    pks = [c[0] for c in cases]
    msgs = [c[1] for c in cases]
    sigs = [c[2] for c in cases]
    got = batch_verify(pks, msgs, sigs)
    want = np.array([ed.verify(p, m, s) for p, m, s in cases])
    assert got.dtype == bool
    mismatches = [
        (i, bool(got[i]), bool(want[i])) for i in range(len(cases)) if got[i] != want[i]
    ]
    assert not mismatches, f"decision mismatches: {mismatches}"
    return want


def _valid_cases(n, seed, msg_len=40):
    rng = random.Random(seed)
    out = []
    for _ in range(n):
        priv, pub = ed.keygen(bytes(rng.randrange(256) for _ in range(32)))
        msg = bytes(rng.randrange(256) for _ in range(msg_len))
        out.append((pub, msg, ed.sign(priv, msg)))
    return out


def test_valid_batch():
    want = _differential(_valid_cases(32, seed=1))
    assert want.all()  # sanity: these really are valid sigs


def test_corrupted_batch():
    rng = random.Random(2)
    cases = []
    for pub, msg, sig in _valid_cases(24, seed=3):
        which = rng.randrange(3)
        if which == 0:
            b = bytearray(sig)
            b[rng.randrange(64)] ^= 1 << rng.randrange(8)
            sig = bytes(b)
        elif which == 1:
            msg = msg + b"!"
        else:
            b = bytearray(pub)
            b[rng.randrange(32)] ^= 1 << rng.randrange(8)
            pub = bytes(b)
        cases.append((pub, msg, sig))
    want = _differential(cases)
    assert not want.all()  # most should be rejected


def test_adversarial_batch():
    """Non-canonical s, non-canonical y, small-order keys, zero sig, identity."""
    priv, pub = ed.keygen(b"\x07" * 32)
    msg = b"adversarial"
    sig = ed.sign(priv, msg)
    s_int = int.from_bytes(sig[32:], "little")

    cases = [
        (pub, msg, sig),                                             # valid
        (pub, msg, sig[:32] + (s_int + ed.L).to_bytes(32, "little")),  # s >= L
        ((ed.P + 1).to_bytes(32, "little"), msg, sig),               # y = p+1 >= p
        ((ed.P - 1).to_bytes(32, "little"), msg, sig),               # canonical y, likely off-curve
        (b"\x01" + b"\x00" * 31, msg, sig),                          # y=1: identity point A
        (b"\x00" * 32, msg, sig),                                    # y=0 small-order candidate
        (pub, msg, b"\x00" * 64),                                    # zero signature
        (pub, msg, (b"\x01" + b"\x00" * 31) + b"\x00" * 32),         # R = identity enc, s=0
        (pub, b"", sig),                                             # truncated msg
        (pub, msg, sig[:32] + (ed.L - 1).to_bytes(32, "little")),    # s = L-1 canonical
        # sign-bit variants
        (bytes(pub[:31]) + bytes([pub[31] ^ 0x80]), msg, sig),       # flipped A sign
        (bytes([sig[0] ^ 0x01]) + sig[1:], msg, sig),                # corrupt R (len 64 kept below)
    ]
    # fix the last case's signature structure (msg arg mistake guard)
    cases[-1] = (pub, msg, bytes([sig[0] ^ 0x01]) + sig[1:])
    _differential(cases)


def test_identity_pubkey_with_forged_sig():
    """A = identity: [s]B - [h]*identity = [s]B; R = [s]B encoding passes the
    cofactorless equation. Both paths must AGREE (this is the kind of edge
    where implementations diverge)."""
    id_pub = b"\x01" + b"\x00" * 31  # y=1, x=0: the identity point
    msg = b"forged"
    s = 12345
    sB = ed._pt_mul(s, (ed.B[0], ed.B[1], 1, ed.B[0] * ed.B[1] % ed.P))
    sig = ed._pt_encode(sB) + s.to_bytes(32, "little")
    _differential([(id_pub, msg, sig)])


def test_large_batch_and_padding():
    cases = _valid_cases(5, seed=9)  # pads 5 -> 64
    bad = list(cases[2])
    bad[2] = bad[2][:63] + bytes([bad[2][63] ^ 0x40])
    cases[2] = tuple(bad)
    _differential(cases)


def test_empty_batch():
    assert batch_verify([], [], []).shape == (0,)


def test_wrong_lengths():
    priv, pub = ed.keygen(b"\x09" * 32)
    sig = ed.sign(priv, b"m")
    _differential([
        (pub[:31], b"m", sig),
        (pub, b"m", sig[:63]),
        (pub + b"\x00", b"m", sig),
        (pub, b"m", sig + b"\x00"),
    ])
