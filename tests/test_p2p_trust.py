"""Peer trust metric + store (reference p2p/trust/{metric,store}.go).

Time is injected so interval rollover is deterministic; the adversarial
case — a flapping peer racking up errors until quarantined, then paroled
after the ban window — is the behavior the switch wiring relies on.
"""

from tendermint_tpu.libs.db import MemDB
from tendermint_tpu.p2p.trust import (
    DEFAULT_BAN_THRESHOLD,
    TrustMetric,
    TrustMetricStore,
)


class Clock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def test_fresh_peer_is_trusted():
    m = TrustMetric(now=Clock())
    assert m.value() == 1.0


def test_good_events_keep_trust_high():
    clk = Clock()
    m = TrustMetric(interval=60, now=clk)
    for _ in range(10):
        m.record_good()
        clk.advance(30)
    assert m.value() > 0.9


def test_bad_events_sink_trust():
    clk = Clock()
    m = TrustMetric(interval=60, now=clk)
    for _ in range(6):
        m.record_bad(5)
        m.record_good(1)
        clk.advance(60)
    assert m.value() < 0.4


def test_downward_trend_penalized():
    clk = Clock()
    good = TrustMetric(interval=60, now=clk)
    flap = TrustMetric(interval=60, now=clk)
    for _ in range(5):
        good.record_good(5)
        flap.record_good(5)
        clk.advance(60)
    # same history, but one starts failing NOW
    flap.record_bad(10)
    assert flap.value() < good.value()


def test_long_idle_does_not_loop():
    clk = Clock()
    m = TrustMetric(interval=60, now=clk)
    m.record_good()
    clk.advance(60 * 60 * 24 * 30)  # a month idle
    assert 0.0 <= m.value() <= 1.0  # and returns promptly


def test_store_quarantines_flapping_peer_and_paroles():
    clk = Clock()
    store = TrustMetricStore(db=MemDB(), interval=60, ban_duration=600,
                             now=clk)
    pid = "flappy"
    assert not store.banned(pid)
    # errors across several intervals sink the score below the threshold
    for _ in range(8):
        store.peer_bad(pid, 5)
        clk.advance(60)
    assert store.value(pid) < DEFAULT_BAN_THRESHOLD
    assert store.banned(pid)
    # parole after the ban window, with a fresh metric
    clk.advance(601)
    assert not store.banned(pid)
    assert store.value(pid) == 1.0


def test_store_persists_across_restart():
    clk = Clock()
    db = MemDB()
    store = TrustMetricStore(db=db, interval=60, now=clk)
    for _ in range(8):
        store.peer_bad("bad-peer", 5)
        clk.advance(60)
    store.peer_good("good-peer", 3)
    assert store.banned("bad-peer")
    store.save()

    store2 = TrustMetricStore(db=db, interval=60, now=clk)
    assert store2.banned("bad-peer")
    assert store2.value("good-peer") > 0.9
    # ban expiry survives the reload as a remaining-duration, then lapses
    clk.advance(10_000)
    assert not store2.banned("bad-peer")


def test_switch_quarantines_flapping_peer():
    """Switch wiring: repeated stop_peer_for_error sinks the peer's score
    until the switch refuses to re-add or re-dial it (reference consults
    the trust store on reconnect decisions)."""
    import asyncio

    from tendermint_tpu.p2p.switch import Switch

    class FakePeer:
        def __init__(self, pid):
            self.id = pid
            self.stopped = 0

        def bind(self, sw):
            pass

        def start(self):
            pass

        async def stop(self):
            self.stopped += 1

    async def run():
        clk = Clock()
        store = TrustMetricStore(db=MemDB(), interval=60, ban_duration=600,
                                 now=clk)
        sw = Switch("self-node", trust_store=store)
        sw._running = True
        for _ in range(10):
            p = FakePeer("flappy")
            sw.peers[p.id] = p
            await sw.stop_peer_for_error(p, "bad message")
            clk.advance(60)
        assert store.banned("flappy")
        # inbound connection from the quarantined peer is refused
        p = FakePeer("flappy")
        await sw._on_inbound_peer(p)
        assert p.stopped == 1 and "flappy" not in sw.peers
        # a well-behaved peer is unaffected
        good = FakePeer("steady")
        await sw._on_inbound_peer(good)
        assert "steady" in sw.peers

    asyncio.run(run())
