"""PartSet split/reassemble with merkle proofs; evidence encode/hash roundtrips."""

import os

import pytest

from tendermint_tpu import crypto
from tendermint_tpu.types import (
    BlockID,
    DuplicateVoteEvidence,
    PartSetHeader,
    SignedMsgType,
    ValidatorSet,
    Vote,
)
from tendermint_tpu.types.evidence import (
    decode_evidence_list,
    encode_evidence_list,
    evidence_list_hash,
)
from tendermint_tpu.types.part_set import Part, PartSet
from tendermint_tpu.types.validator import new_validator

CHAIN_ID = "test_chain_id"


def test_part_set_roundtrip():
    data = os.urandom(250_000)  # 4 parts at 64KiB
    ps = PartSet.from_data(data)
    assert ps.total == 4 and ps.is_complete()
    # reassemble via a fresh part set fed through add_part
    ps2 = PartSet.from_header(ps.header())
    assert not ps2.is_complete()
    for i in range(ps.total):
        assert ps2.add_part(ps.get_part(i))
    assert ps2.is_complete()
    assert ps2.get_reader() == data


def test_part_set_rejects_tampered_part():
    data = os.urandom(100_000)
    ps = PartSet.from_data(data)
    ps2 = PartSet.from_header(ps.header())
    p = ps.get_part(0)
    bad = Part(0, p.bytes_[:-1] + b"\x00", p.proof)
    with pytest.raises(ValueError, match="invalid proof"):
        ps2.add_part(bad)


def test_part_set_duplicate_part_is_noop():
    data = os.urandom(1000)
    ps = PartSet.from_data(data)
    ps2 = PartSet.from_header(ps.header())
    assert ps2.add_part(ps.get_part(0))
    assert ps2.add_part(ps.get_part(0)) is False


def test_part_proto_roundtrip():
    data = os.urandom(70_000)
    ps = PartSet.from_data(data)
    p = ps.get_part(1)
    got = Part.decode(p.encode())
    assert got.index == p.index and got.bytes_ == p.bytes_
    assert got.proof.compute_root() == p.proof.compute_root()


def _mk_dve():
    privs = [crypto.Ed25519PrivKey.generate(bytes([i + 1]) * 32) for i in range(3)]
    vals = [new_validator(p.pub_key(), 10) for p in privs]
    vs = ValidatorSet(vals)
    by_addr = {p.pub_key().address(): p for p in privs}
    val = vs.validators[0]
    priv = by_addr[val.address]

    def vote(bid_seed):
        bid = BlockID(bid_seed * 32, PartSetHeader(1, b"\x09" * 32))
        v = Vote(SignedMsgType.PRECOMMIT, 10, 0, bid, 1_700_000_000_000_000_000,
                 val.address, 0)
        v.signature = priv.sign(v.sign_bytes(CHAIN_ID))
        return v

    ev = DuplicateVoteEvidence.new(vote(b"\x01"), vote(b"\x02"), 1_700_000_001_000_000_000, vs)
    return ev, vs


def test_duplicate_vote_evidence_roundtrip_and_hash():
    ev, vs = _mk_dve()
    assert ev is not None
    ev.validate_basic()
    lst = decode_evidence_list(encode_evidence_list([ev]))
    assert len(lst) == 1
    got = lst[0]
    assert got.hash() == ev.hash()
    assert got.vote_a.signature == ev.vote_a.signature
    assert evidence_list_hash([ev]) == evidence_list_hash(lst)


def test_dve_vote_ordering_by_block_key():
    ev, _ = _mk_dve()
    assert ev.vote_a.block_id.key() < ev.vote_b.block_id.key()


def test_proof_op_chain_verification():
    """ProofOp chains (reference crypto/merkle/proof_op.go + proof_value.go):
    an app-store value proven through chained merkle trees, verified via the
    ProofRuntime against the outer root and the URL-encoded key path."""
    from tendermint_tpu.crypto.merkle import (
        Proof,
        ProofOp,
        ValueOp,
        default_proof_runtime,
        key_path,
        leaf_hash,
        proofs_from_byte_slices,
        hash_from_byte_slices,
    )
    import hashlib

    from tendermint_tpu.crypto.merkle import _encode_byte_slice

    # inner "store" tree leaves: encodeByteSlice(key)||encodeByteSlice(vhash)
    # (proof_value.go — length-prefixed, reference-compatible)
    items = []
    kvs = [(b"alpha", b"1"), (b"beta", b"2"), (b"gamma/3", b"3")]
    for k, v in kvs:
        items.append(_encode_byte_slice(k)
                     + _encode_byte_slice(hashlib.sha256(v).digest()))
    root = hash_from_byte_slices(items)
    proofs = proofs_from_byte_slices(items)

    prt = default_proof_runtime()
    key, value = kvs[1]
    op = ValueOp(key, proofs[1])
    # happy path
    prt.verify_value([op.proof_op()], root, key_path(key), value)
    # wrong value fails
    with pytest.raises(ValueError):
        prt.verify_value([op.proof_op()], root, key_path(key), b"99")
    # wrong key path fails
    with pytest.raises(ValueError):
        prt.verify_value([op.proof_op()], root, key_path(b"alpha"), value)
    # wrong root fails
    with pytest.raises(ValueError):
        prt.verify_value([op.proof_op()], b"\x00" * 32, key_path(key), value)
    # keypath with special chars round-trips the URL encoding
    k3, v3 = kvs[2]
    op3 = ValueOp(k3, proofs[2])
    prt.verify_value([op3.proof_op()], root, key_path(k3), v3)
