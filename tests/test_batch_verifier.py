"""BatchVerifier seam + regression tests for review findings."""

import numpy as np
import pytest

from tendermint_tpu.crypto import Ed25519PrivKey, Ed25519PubKey
from tendermint_tpu.crypto import ed25519 as ed
from tendermint_tpu.crypto import merkle
from tendermint_tpu.crypto.batch import BatchVerifier


def _signed(n, seed=0):
    out = []
    for i in range(n):
        pk = Ed25519PrivKey.generate(bytes([seed * 31 + i % 251 + 1]) * 32)
        msg = f"msg {i}".encode()
        out.append((pk.pub_key(), msg, pk.sign(msg)))
    return out


@pytest.mark.parametrize("backend", ["jax", "host"])
def test_batch_verifier_backends_agree(backend):
    bv = BatchVerifier(backend=backend)
    cases = _signed(20)
    for pub, msg, sig in cases:
        bv.add(pub, msg, sig)
    ok, per = bv.verify()
    assert ok and per.all() and len(per) == 20
    # corrupt one
    for i, (pub, msg, sig) in enumerate(cases):
        bv.add(pub, msg, sig if i != 7 else sig[:-1] + bytes([sig[-1] ^ 1]))
    ok, per = bv.verify()
    assert not ok and per.sum() == 19 and not per[7]
    # verifier reset after verify()
    assert len(bv) == 0
    ok, per = bv.verify()
    assert ok and per.shape == (0,)


def test_openssl_path_rejects_x0_sign1_pubkeys():
    """Regression (consensus-split): x=0 with sign bit 1 encodings must be
    rejected by the OpenSSL fast path, matching the strict spec + TPU path."""
    for y in (1, ed.P - 1):
        pub = (y | 1 << 255).to_bytes(32, "little")
        s = 7
        sB = ed._pt_mul(s, (ed.B[0], ed.B[1], 1, ed.B[0] * ed.B[1] % ed.P))
        sig = ed._pt_encode(sB) + s.to_bytes(32, "little")
        assert not ed.verify(pub, b"forged", sig)
        assert not Ed25519PubKey(pub).verify_signature(b"forged", sig)
        from tendermint_tpu.crypto.ed25519_jax import batch_verify

        assert not batch_verify([pub], [b"forged"], [sig])[0]
    # the unset-sign siblings are legitimately decodable points — paths agree
    for y in (1, ed.P - 1):
        pub = y.to_bytes(32, "little")
        assert ed._pt_decode(pub) is not None


def test_merkle_adversarial_proof_returns_false():
    """Regression (DoS): huge total/aunts must be rejected, not recurse."""
    items = [b"leaf"]
    root = merkle.hash_from_byte_slices(items)
    evil = merkle.Proof(
        total=2**5000, index=0, leaf_hash=merkle.leaf_hash(b"leaf"),
        aunts=[b"\x00" * 32] * 5000,
    )
    assert evil.verify(root, b"leaf") is False


def test_batch_verify_length_mismatch_raises():
    from tendermint_tpu.crypto.ed25519_jax import batch_verify

    with pytest.raises(ValueError):
        batch_verify([b"\x00" * 32], [], [b"\x00" * 64])
