"""BaseService lifecycle semantics (reference libs/service/service.go:97
TestBaseService* in service_test.go): start/stop idempotency errors, quit
signaling, reset re-arming, failed-start rollback."""

import asyncio

import pytest

from tendermint_tpu.libs.service import (
    AlreadyStarted,
    AlreadyStopped,
    BaseService,
    NotStarted,
    ServiceError,
)


class Recorder(BaseService):
    def __init__(self, fail_start=False):
        super().__init__("recorder")
        self.events = []
        self.fail_start = fail_start

    async def on_start(self):
        if self.fail_start:
            raise RuntimeError("boom")
        self.events.append("start")

    async def on_stop(self):
        self.events.append("stop")


def test_start_stop_cycle_and_errors():
    async def run():
        s = Recorder()
        assert not s.is_running() and "new" in str(s)
        await s.start()
        assert s.is_running()
        with pytest.raises(AlreadyStarted):
            await s.start()
        await s.stop()
        assert not s.is_running() and "stopped" in str(s)
        with pytest.raises(AlreadyStopped):
            await s.stop()
        with pytest.raises(AlreadyStopped):
            await s.start()  # stopped services need reset first
        assert s.events == ["start", "stop"]

    asyncio.run(run())


def test_wait_unblocks_on_stop():
    async def run():
        s = Recorder()
        await s.start()
        waiter = asyncio.create_task(s.wait())
        await asyncio.sleep(0)
        assert not waiter.done()
        await s.stop()
        await asyncio.wait_for(waiter, 1)

    asyncio.run(run())


def test_reset_rearms():
    async def run():
        s = Recorder()
        with pytest.raises(ServiceError):
            await s.reset()  # not stopped yet
        await s.start()
        with pytest.raises(ServiceError):
            await s.reset()  # running
        await s.stop()
        await s.reset()
        await s.start()
        assert s.is_running()
        assert s.events == ["start", "stop", "start"]

    asyncio.run(run())


def test_failed_start_rolls_back():
    async def run():
        s = Recorder(fail_start=True)
        with pytest.raises(RuntimeError):
            await s.start()
        assert not s.is_running()
        s.fail_start = False
        await s.start()  # recoverable
        assert s.is_running()
        with pytest.raises(NotStarted):
            await Recorder().wait()

    asyncio.run(run())
