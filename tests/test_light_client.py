"""Light client: pure verifier rules, bisection over a validator-rotating
chain, trusting-period expiry, and witness divergence detection
(reference light/verifier.go, light/client.go, light/detector.go).
"""

import asyncio

import pytest

from tendermint_tpu import crypto
from tendermint_tpu.light import (
    LightClient,
    TrustOptions,
    verify_adjacent,
    verify_non_adjacent,
)
from tendermint_tpu.light.client import DivergenceError
from tendermint_tpu.light.provider import MockProvider
from tendermint_tpu.light.verifier import (
    ErrInvalidHeader,
    ErrNewValSetCantBeTrusted,
    ErrOldHeaderExpired,
)
from tendermint_tpu.types import MockPV, Validator, ValidatorSet
from tendermint_tpu.types.basic import BlockID, BlockIDFlag, PartSetHeader, SignedMsgType
from tendermint_tpu.types.block import Commit, CommitSig, Consensus, Header
from tendermint_tpu.types.light_block import LightBlock, SignedHeader
from tendermint_tpu.types.vote import Vote

CHAIN = "light-chain"
T0 = 1_700_000_000_000_000_000


def _val_set(keys):
    return ValidatorSet([Validator(k.get_pub_key().address(), k.get_pub_key(), 10)
                         for k in keys])


def _mk_chain(key_sets, n_heights):
    """Build a signed header chain; key_sets[h-1] = pv list for height h."""
    blocks = {}
    last_bid = BlockID(b"", PartSetHeader())
    for h in range(1, n_heights + 1):
        keys = key_sets[min(h - 1, len(key_sets) - 1)]
        next_keys = key_sets[min(h, len(key_sets) - 1)]
        vals, next_vals = _val_set(keys), _val_set(next_keys)
        header = Header(
            version=Consensus(), chain_id=CHAIN, height=h,
            time_ns=T0 + h * 1_000_000_000,
            last_block_id=last_bid,
            last_commit_hash=b"\x01" * 32, data_hash=b"\x02" * 32,
            validators_hash=vals.hash(),
            next_validators_hash=next_vals.hash(),
            consensus_hash=b"\x03" * 32, app_hash=b"\x04" * 32,
            last_results_hash=b"\x05" * 32, evidence_hash=b"\x06" * 32,
            proposer_address=keys[0].get_pub_key().address(),
        )
        bid = BlockID(header.hash(), PartSetHeader(1, b"\x07" * 32))
        commit = _sign_commit(vals, keys, h, bid, header.time_ns)
        blocks[h] = LightBlock(SignedHeader(header, commit), vals)
        last_bid = bid
    return blocks


def _keys(seed, n):
    return [MockPV(crypto.Ed25519PrivKey.generate(bytes([seed + i]) * 32))
            for i in range(n)]


def test_verify_adjacent_and_rules():
    keys = _keys(0x10, 4)
    blocks = _mk_chain([keys], 3)
    now = T0 + 100 * 1_000_000_000
    period = 3600.0

    verify_adjacent(blocks[1].signed_header, blocks[2].signed_header,
                    blocks[2].validator_set, period, now, 10.0)

    # tampered header fails
    bad = blocks[2].signed_header
    import copy
    bad2 = copy.deepcopy(bad)
    bad2.header.app_hash = b"\xff" * 32
    with pytest.raises(Exception):
        verify_adjacent(blocks[1].signed_header, bad2,
                        blocks[2].validator_set, period, now, 10.0)

    # expired trusted header
    with pytest.raises(ErrOldHeaderExpired):
        verify_adjacent(blocks[1].signed_header, blocks[2].signed_header,
                        blocks[2].validator_set, 1.0, now, 10.0)


def test_verify_non_adjacent_trusting():
    keys = _keys(0x20, 4)
    blocks = _mk_chain([keys], 10)
    now = T0 + 100 * 1_000_000_000
    # same validator set throughout: skipping from 1 to 10 succeeds
    verify_non_adjacent(blocks[1].signed_header, blocks[1].validator_set,
                        blocks[10].signed_header, blocks[10].validator_set,
                        3600.0, now, 10.0)


def test_verify_non_adjacent_rotated_set_cant_be_trusted():
    a, b = _keys(0x30, 4), _keys(0x40, 4)
    # full rotation at height 5: heights 1-4 signed by A, 5+ by B
    blocks = _mk_chain([a, a, a, a, b, b, b, b, b, b], 10)
    now = T0 + 100 * 1_000_000_000
    with pytest.raises(ErrNewValSetCantBeTrusted):
        verify_non_adjacent(blocks[1].signed_header, blocks[1].validator_set,
                            blocks[10].signed_header, blocks[10].validator_set,
                            3600.0, now, 10.0)


def test_client_bisection_through_rotation():
    a, b = _keys(0x50, 4), _keys(0x60, 4)
    key_sets = [a, a, a, a, b, b, b, b, b, b]
    blocks = _mk_chain(key_sets, 10)
    primary = MockProvider(CHAIN, blocks)
    witness = MockProvider(CHAIN, blocks)
    now = T0 + 100 * 1_000_000_000

    async def run():
        client = LightClient(
            CHAIN,
            TrustOptions(3600.0, 1, blocks[1].signed_header.header.hash()),
            primary, [witness])
        lb = await client.verify_light_block_at_height(10, now_ns=now)
        assert lb.signed_header.header.height == 10
        # bisection stored intermediate trusted blocks
        assert client.store.latest_height() == 10
        assert len(client.store.heights()) >= 2

    asyncio.run(run())


def test_client_detects_divergent_witness():
    keys = _keys(0x70, 4)
    blocks = _mk_chain([keys], 6)
    # witness serves a forked chain (different app hash from height 4 on)
    forged_keys = _keys(0x70, 4)  # same keys — a real equivocation fork
    forked = _mk_chain([forged_keys], 6)
    for h in range(1, 7):
        forked[h].signed_header.header.app_hash = b"\xee" * 32
        # re-sign the forged chain
    forked = _resign(forked, forged_keys)

    primary = MockProvider(CHAIN, blocks)
    witness = MockProvider(CHAIN, forked)
    now = T0 + 100 * 1_000_000_000

    async def run():
        client = LightClient(
            CHAIN, TrustOptions(3600.0, 1, blocks[1].signed_header.header.hash()),
            primary, [witness])
        with pytest.raises(DivergenceError):
            await client.verify_light_block_at_height(5, now_ns=now)
        assert witness.evidence, "divergence must be reported to the witness"

    asyncio.run(run())


def _sign_commit(vals, keys, h, bid, time_ns):
    """Commit with signatures in VALIDATOR-SET order (sorted), as the real
    consensus produces them."""
    by_addr = {pv.get_pub_key().address(): pv for pv in keys}
    sigs = []
    for i, val in enumerate(vals.validators):
        pv = by_addr[val.address]
        vote = Vote(SignedMsgType.PRECOMMIT, h, 0, bid, time_ns + 1000 + i,
                    val.address, i, b"")
        pv.sign_vote(CHAIN, vote)
        sigs.append(CommitSig(BlockIDFlag.COMMIT, vote.validator_address,
                              vote.timestamp_ns, vote.signature))
    return Commit(h, 0, bid, sigs)


def _resign(blocks, keys):
    """Recompute hashes/commits after tampering (building a forked chain)."""
    out = {}
    last_bid = BlockID(b"", PartSetHeader())
    for h in sorted(blocks):
        lb = blocks[h]
        lb.signed_header.header.last_block_id = last_bid
        hdr = lb.signed_header.header
        bid = BlockID(hdr.hash(), PartSetHeader(1, b"\x07" * 32))
        commit = _sign_commit(lb.validator_set, keys, h, bid, hdr.time_ns)
        out[h] = LightBlock(SignedHeader(hdr, commit), lb.validator_set)
        last_bid = bid
    return out


def test_light_client_against_live_node(tmp_path):
    """HTTPProvider + LightClient against a real node over RPC: the decode
    path (ns-exact times, hashes) must reproduce header hashes bit-exactly."""
    pytest.importorskip("cryptography", reason="needs the optional 'cryptography' package (absent in slim containers)")
    from tests.test_node_rpc import _mk_node
    from tendermint_tpu.light.provider import HTTPProvider
    from tendermint_tpu.rpc.client import HTTPClient

    async def run():
        node = _mk_node(tmp_path)
        await node.start()
        try:
            client = HTTPClient(f"http://127.0.0.1:{node.rpc_server.bound_port}")
            for _ in range(300):
                st = await client.status()
                if int(st["sync_info"]["latest_block_height"]) >= 4:
                    break
                await asyncio.sleep(0.05)
            provider = HTTPProvider("rpc-chain", client)
            lb1 = await provider.light_block(1)
            lb1.validate_basic("rpc-chain")  # hash recomputation must match
            # genesis time in the test fixture is 2023; keep it unexpired
            lc = LightClient(
                "rpc-chain",
                TrustOptions(10 * 365 * 24 * 3600.0, 1,
                             lb1.signed_header.header.hash()),
                provider, [])
            lb4 = await lc.verify_light_block_at_height(4)
            assert lb4.signed_header.header.height == 4
            await client.close()
        finally:
            await node.stop()

    asyncio.run(run())


def test_verify_chain_batched_parity():
    """verify_chain_batched must make the same accept/reject decisions as
    stepwise verify(), with all signatures in one batch."""
    from tendermint_tpu.light.verifier import verify_chain_batched

    keys = _keys(0x80, 4)
    blocks = _mk_chain([keys], 8)
    now = T0 + 100 * 1_000_000_000
    chain = [blocks[h] for h in range(2, 9)]

    # happy path
    verify_chain_batched(blocks[1], chain, 3600.0, now, 10.0)

    # corrupt one signature mid-chain: same error as the stepwise path
    import copy
    bad_chain = copy.deepcopy(chain)
    sigs = bad_chain[3].signed_header.commit.signatures
    sigs[0].signature = b"\x00" * 64
    with pytest.raises(ErrInvalidHeader):
        verify_chain_batched(blocks[1], bad_chain, 3600.0, now, 10.0)

    # expired trust fails identically
    with pytest.raises(ErrOldHeaderExpired):
        verify_chain_batched(blocks[1], chain, 1.0, now, 10.0)


def test_light_proxy_verifies_primary(tmp_path):
    """Light proxy (reference light/proxy): commit/block/validators answers
    are verified against light-client state; a lying primary is rejected."""
    pytest.importorskip("cryptography", reason="needs the optional 'cryptography' package (absent in slim containers)")
    from tests.test_node_rpc import _mk_node
    from tendermint_tpu.light.provider import HTTPProvider
    from tendermint_tpu.light.proxy import LightProxy
    from tendermint_tpu.rpc.client import HTTPClient

    async def run():
        node = _mk_node(tmp_path)
        await node.start()
        proxy = None
        try:
            rpc = HTTPClient(f"http://127.0.0.1:{node.rpc_server.bound_port}")
            for _ in range(300):
                st = await rpc.status()
                if int(st["sync_info"]["latest_block_height"]) >= 4:
                    break
                await asyncio.sleep(0.05)
            provider = HTTPProvider("rpc-chain", rpc)
            lb1 = await provider.light_block(1)
            lc = LightClient(
                "rpc-chain",
                TrustOptions(10 * 365 * 24 * 3600.0, 1,
                             lb1.signed_header.header.hash()),
                provider, [])
            proxy = LightProxy(lc, rpc)
            port = await proxy.start()

            client = HTTPClient(f"http://127.0.0.1:{port}")
            cmt = await client.commit(3)
            assert cmt["signed_header"]["header"]["height"] == "3"
            blk = await client.block(3)
            assert blk["block"]["header"]["height"] == "3"
            vals = await client.validators(3)
            assert vals["total"] == "1"
            st = await client.status()  # forwarded route
            assert st["node_info"]["network"] == "rpc-chain"

            # a lying primary: tamper with the proxy's forwarded answer by
            # pointing it at a client that alters block data
            class LyingClient:
                def __init__(self, inner):
                    self.inner = inner

                async def block(self, height=None):
                    doc = await self.inner.block(height)
                    doc["block"]["data"]["txs"] = ["bGllcw=="]  # "lies"
                    return doc

                def __getattr__(self, name):
                    return getattr(self.inner, name)

            proxy.rpc = LyingClient(rpc)
            from tendermint_tpu.rpc.core import RPCError as _E

            with pytest.raises(_E):
                await client.block(3)
            await client.close()
            await rpc.close()
        finally:
            if proxy is not None:
                await proxy.stop()
            await node.stop()

    asyncio.run(run())


def test_light_proxy_verifies_abci_query(tmp_path):
    """abci_query through the proxy is proof-verified against the
    light-client app hash (reference light/rpc/client.go ABCIQuery →
    merkle ProofRuntime): honest answers pass, a forged value and a
    missing proof are rejected."""
    import base64 as b64mod

    pytest.importorskip("cryptography", reason="needs the optional 'cryptography' package (absent in slim containers)")
    from tests.test_node_rpc import _mk_node
    from tendermint_tpu.light.provider import HTTPProvider
    from tendermint_tpu.light.proxy import LightProxy
    from tendermint_tpu.rpc.client import HTTPClient

    async def run():
        node = _mk_node(tmp_path)
        # swap the app for the merkle-proof kvstore BEFORE start
        node_app = node.app
        from tendermint_tpu.abci.example.kvstore import (
            MerkleKVStoreApplication,
        )
        assert not isinstance(node_app, MerkleKVStoreApplication)
        proxy = None
        try:
            await node.start()
            rpc = HTTPClient(f"http://127.0.0.1:{node.rpc_server.bound_port}")
            # the default _mk_node app is plain kvstore (no proofs): the
            # proxy must REJECT its unproven answers
            await rpc.call("broadcast_tx_sync",
                           tx=b64mod.b64encode(b"k1=v1").decode())
            for _ in range(600):
                st = await rpc.status()
                if int(st["sync_info"]["latest_block_height"]) >= 3:
                    break
                await asyncio.sleep(0.05)
            provider = HTTPProvider("rpc-chain", rpc)
            lb1 = await provider.light_block(1)
            lc = LightClient(
                "rpc-chain",
                TrustOptions(10 * 365 * 24 * 3600.0, 1,
                             lb1.signed_header.header.hash()),
                provider, [])
            proxy = LightProxy(lc, rpc)
            port = await proxy.start()
            client = HTTPClient(f"http://127.0.0.1:{port}")

            from tendermint_tpu.rpc.core import RPCError as _E

            with pytest.raises(_E):  # plain kvstore serves no proofs
                await client.abci_query("", b"k1")
            await client.close()
            await rpc.close()
        finally:
            if proxy is not None:
                await proxy.stop()
            await node.stop()

    asyncio.run(run())


def test_light_proxy_merkle_query_end_to_end(tmp_path):
    """With the merkle kvstore app the proxy serves proof-verified queries;
    a lying primary forging the value is rejected."""
    import base64 as b64mod

    pytest.importorskip("cryptography", reason="needs the optional 'cryptography' package (absent in slim containers)")
    from tests.test_node_rpc import _mk_node
    from tendermint_tpu.light.provider import HTTPProvider
    from tendermint_tpu.light.proxy import LightProxy
    from tendermint_tpu.node import Node
    from tendermint_tpu.rpc.client import HTTPClient

    async def run():
        # build the node over the merkle app
        orig = _mk_node(tmp_path)
        cfg = orig.config
        cfg.base.proxy_app = "kvstore-merkle"
        node = Node(cfg, orig.priv_validator, orig.node_key, orig.genesis)
        proxy = None
        try:
            await node.start()
            rpc = HTTPClient(f"http://127.0.0.1:{node.rpc_server.bound_port}")
            await rpc.call("broadcast_tx_sync",
                           tx=b64mod.b64encode(b"k1=v1").decode())
            for _ in range(600):
                st = await rpc.status()
                if int(st["sync_info"]["latest_block_height"]) >= 4:
                    break
                await asyncio.sleep(0.05)
            provider = HTTPProvider("rpc-chain", rpc)
            lb1 = await provider.light_block(1)
            lc = LightClient(
                "rpc-chain",
                TrustOptions(10 * 365 * 24 * 3600.0, 1,
                             lb1.signed_header.header.hash()),
                provider, [])
            proxy = LightProxy(lc, rpc)
            port = await proxy.start()
            client = HTTPClient(f"http://127.0.0.1:{port}")

            doc = await client.abci_query("", b"k1")
            assert b64mod.b64decode(doc["response"]["value"]) == b"v1"

            # lying primary: forge the value; the proof must not verify
            class LyingClient:
                def __init__(self, inner):
                    self.inner = inner

                async def abci_query(self, path, data, height=0, prove=False):
                    doc = await self.inner.abci_query(
                        path, data, height=height, prove=prove)
                    doc["response"]["value"] = b64mod.b64encode(
                        b"forged").decode()
                    return doc

                def __getattr__(self, name):
                    return getattr(self.inner, name)

            from tendermint_tpu.rpc.core import RPCError as _E

            proxy.rpc = LyingClient(rpc)
            with pytest.raises(_E):
                await client.abci_query("", b"k1")
            await client.close()
            await rpc.close()
        finally:
            if proxy is not None:
                await proxy.stop()
            await node.stop()

    asyncio.run(run())
