"""libs/slo.py unit surface: the spec line grammar, sliding-window
evaluation (merged breach runs, per-node grouping), attribution against a
seeded chaos schedule — a named plane/node/stage or the loud first-class
``unattributed`` — and the wall-clock-stripped fingerprints soak
determinism diffs rely on."""

import pytest

from tendermint_tpu.libs import slo


# -- spec grammar -------------------------------------------------------------

def test_spec_parse_good():
    spec = slo.SLOSpec.parse(
        "# comment\n"
        "commit_latency p99 <= 2.5 window=30\n"
        "\n"
        "caughtup max <= 60\n"
        "rss_bytes slope <= 8388608\n")
    assert [o.name for o in spec.objectives] == [
        "commit_latency_p99", "caughtup_max", "rss_bytes_slope"]
    assert spec.objectives[0].window_s == 30.0
    assert spec.objectives[1].window_s == 0.0  # whole-run
    assert spec.as_dicts()[0]["threshold"] == 2.5


def test_spec_default_covers_the_soak_objectives():
    names = {o.name for o in slo.SLOSpec.default().objectives}
    assert {"commit_latency_p99", "caughtup_max", "queue_full_sheds_count",
            "rss_bytes_slope", "wal_bytes_slope", "ring_depth_max",
            "metric_series_max"} == names


@pytest.mark.parametrize("bad", [
    "x p99 <=\n",              # missing threshold
    "x p42 <= 1\n",            # unknown aggregator
    "x p99 ~ 1\n",             # unknown op
    "x p99 <= one\n",          # non-numeric threshold
    "x p99 <= 1 win=3\n",      # bad trailing field
])
def test_spec_parse_rejects_malformed_lines(bad):
    with pytest.raises(ValueError):
        slo.SLOSpec.parse(bad)


# -- sliding-window evaluation ------------------------------------------------

def _engine(text):
    return slo.SLOEngine(slo.SLOSpec.parse(text))


def test_latency_spike_trips_only_windows_hugging_it():
    eng = _engine("lat p99 <= 1.0 window=10\n")
    for t in range(61):
        eng.feed("lat", float(t), 5.0 if 30 <= t <= 32 else 0.2,
                 node="val0")
    breaches = eng.evaluate()
    assert len(breaches) == 1, breaches
    b = breaches[0]
    assert b["objective"] == "lat_p99" and b["node"] == "val0"
    w0, w1 = b["window"]
    assert w0 <= 30 and w1 >= 32          # the merged run covers the spike
    assert w1 - w0 <= 30                  # ...but not the whole hour of data
    assert b["observed"] == 5.0


def test_clean_streams_raise_no_breaches():
    eng = _engine("lat p99 <= 1.0 window=10\n")
    for t in range(61):
        eng.feed("lat", float(t), 0.2, node="val0")
    assert eng.evaluate() == []


def test_count_objective_sums_event_deltas():
    eng = _engine("sheds count <= 0\n")
    eng.feed_many("sheds", [(0.0, 0.0), (5.0, 0.0), (10.0, 2.0)],
                  node="full0")
    breaches = eng.evaluate()
    assert len(breaches) == 1
    assert breaches[0]["observed"] == 2.0
    assert breaches[0]["node"] == "full0"


def test_slope_flags_leaks_and_clamps_shrinkage():
    eng = _engine("rss slope <= 10\n")
    eng.feed_many("rss", [(float(t), 1000.0 + 64.0 * t)
                          for t in range(30)], node="leaky")
    eng.feed_many("rss", [(float(t), 1000.0) for t in range(30)],
                  node="flat")
    eng.feed_many("rss", [(float(t), 1000.0 - 64.0 * t)
                          for t in range(30)], node="gc")
    breaches = eng.evaluate()
    assert [b["node"] for b in breaches] == ["leaky"]
    assert breaches[0]["observed"] == pytest.approx(64.0)


def test_per_node_grouping_keeps_breaches_separate():
    eng = _engine("lat max <= 1.0 window=10\n")
    for t in range(21):
        eng.feed("lat", float(t), 0.2, node="ok")
        eng.feed("lat", float(t), 9.0, node="sad")
    nodes = {b["node"] for b in eng.evaluate()}
    assert nodes == {"sad"}


# -- attribution --------------------------------------------------------------

def test_attribution_picks_the_concentrated_window():
    # two planes armed concurrently: the broad churn window covers the
    # breach too, but the nested corrupt window is more concentrated
    schedule = [
        {"t0": 0.0, "t1": 60.0, "plane": "churn", "node": "full0"},
        {"t0": 27.0, "t1": 41.0, "plane": "corrupt", "node": None,
         "detail": "net.corrupt@0.05"},
    ]
    att = slo.attribute({"window": [28.0, 40.0], "node": "val1"},
                        schedule, total_span=120.0)
    assert att["plane"] == "corrupt"
    assert att["node"] == "val1"          # breach node wins when ev has none
    assert att["detail"] == "net.corrupt@0.05"


def test_attribution_coverage_gate_rejects_glancing_overlap():
    # the armed window brushes <1/3 of the breach: loudly unattributed
    schedule = [{"t0": 0.0, "t1": 31.0, "plane": "corrupt", "node": None}]
    att = slo.attribute({"window": [30.0, 60.0], "node": "val0"},
                        schedule, total_span=120.0)
    assert att["plane"] == "unattributed"
    assert att["node"] == "val0"


def test_attribution_global_breach_stays_unattributed():
    # a whole-run breach (the leak-slope shape) must not pin on whichever
    # plane happened to be armed longest
    schedule = [{"t0": 10.0, "t1": 110.0, "plane": "churn", "node": "full0"}]
    att = slo.attribute({"window": [0.0, 115.0], "node": "leaky"},
                        schedule, total_span=120.0)
    assert att["plane"] == "unattributed"


def test_attribution_point_breach_by_containment():
    # zero-span breach (a kill-to-caught-up point stream): any armed
    # window containing the instant qualifies
    schedule = [{"t0": 20.0, "t1": 40.0, "plane": "crash", "node": "full1"}]
    att = slo.attribute({"window": [25.0, 25.0], "node": "full1"}, schedule)
    assert att["plane"] == "crash" and att["node"] == "full1"


def test_attribution_names_the_slowest_stage():
    schedule = [{"t0": 20.0, "t1": 40.0, "plane": "partition",
                 "node": "full0"}]
    stages = [{"t0": 0.0, "t1": 24.0, "stage": "proposal_received"},
              {"t0": 24.0, "t1": 40.0, "stage": "precommit_quorum"}]
    att = slo.attribute({"window": [22.0, 38.0], "node": "val0"},
                        schedule, stages=stages, total_span=120.0)
    assert att["plane"] == "partition"
    assert att["stage"] == "precommit_quorum"   # 14 s overlap beats 2 s


def test_attribute_all_annotates_in_place():
    breaches = [{"objective": "lat_p99", "window": [10.0, 20.0],
                 "node": "val0"}]
    out = slo.attribute_all(breaches, [], total_span=60.0)
    assert out is breaches
    assert breaches[0]["attribution"]["plane"] == "unattributed"


# -- fingerprints -------------------------------------------------------------

def test_breach_fingerprint_strips_wall_clock():
    def mk(w0, w1, observed):
        return {"objective": "lat_p99", "node": "val0",
                "window": [w0, w1], "observed": observed,
                "attribution": {"plane": "corrupt", "stage": "unknown"}}
    assert (slo.breach_fingerprint([mk(10.0, 20.0, 5.1)])
            == slo.breach_fingerprint([mk(11.3, 22.7, 6.9)]))
    other = {"objective": "rss_slope", "node": "val0",
             "window": [10.0, 20.0], "observed": 5.1,
             "attribution": {"plane": "unattributed", "stage": "unknown"}}
    assert (slo.breach_fingerprint([mk(10.0, 20.0, 5.1)])
            != slo.breach_fingerprint([other]))


def test_schedule_fingerprint_is_content_addressed():
    ev = [{"t0": 1.0, "t1": 2.0, "plane": "corrupt"}]
    assert (slo.schedule_fingerprint(ev)
            == slo.schedule_fingerprint([dict(ev[0])]))
    assert (slo.schedule_fingerprint(ev)
            != slo.schedule_fingerprint(
                [{"t0": 1.0, "t1": 3.0, "plane": "corrupt"}]))
