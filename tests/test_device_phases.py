"""Device-plane phase telemetry (crypto/phases.py + the ed25519_jax
dispatcher wiring): per-segment pack/dispatch/fetch stamps tile the segment
span exactly, host-routed batches count with zero device phases, the live
plane's flushes land with plane="live", per-device series appear under the
forced 8-device CPU mesh, height tags ride the seg_* tracer spans, and the
device_profile PROFILE JSON validates against its own schema."""

import asyncio
import time

import numpy as np
import pytest

from tendermint_tpu.crypto import ed25519 as host
from tendermint_tpu.crypto import phases
from tendermint_tpu.crypto.ed25519_jax import verify as V
from tendermint_tpu.libs.metrics import DeviceMetrics, Registry


class _FakeDev:
    def __init__(self, arr):
        self._arr = arr

    def __array__(self, dtype=None, copy=None):
        return self._arr


@pytest.fixture
def device_metrics():
    m = DeviceMetrics(Registry("t"))
    phases.set_device_metrics(m)
    phases.reset()
    yield m
    phases.set_device_metrics(None)
    phases.reset()


def _workload(n, seed=3):
    rng = np.random.default_rng(seed)
    pks = [rng.bytes(32) for _ in range(n)]
    msgs = [rng.bytes(40) for _ in range(n)]
    sigs = [rng.bytes(63) + b"\x00" for _ in range(n)]  # s < L
    return pks, msgs, sigs


def _fake_dispatch(pks, msgs, sigs, chunk):
    time.sleep(0.005)            # "pack"
    phases.mark_pack_done()      # the stamp _dispatch_stream places
    time.sleep(0.002)            # "dispatch"
    k = -(-len(pks) // chunk)
    return _FakeDev(np.ones(k * chunk, bool)), np.ones(len(pks), bool)


def test_segment_phases_tile_the_span(monkeypatch, device_metrics):
    """pack_s + dispatch_s + fetch_s equals the segment's end-to-end span
    (monotonic stamps, no gaps), per-phase histograms observe once per
    segment, and the pipeline-overlap gauge lands in (0, 1]."""
    monkeypatch.setattr(V, "_dispatch_stream", _fake_dispatch)
    monkeypatch.setattr(V, "SEG_MIN_SIGS", 256)
    n, chunk = 512, V.LANE  # 4 chunks -> segments [2, 2]
    out = V._verify_segmented([b"\x01" * 32] * n, [b"m"] * n,
                              [b"\x02" * 64] * n, chunk)
    assert out.all()
    recs = phases.recent_segments()
    assert len(recs) == 2
    for r in recs:
        span = r["t_end"] - r["t0"]
        assert abs(r["pack_s"] + r["dispatch_s"] + r["fetch_s"] - span) < 1e-6
        assert r["pack_s"] >= 0.004  # the fake's sleeps are attributed
        assert r["dispatch_s"] >= 0.001
        assert r["plane"] == "sync" and r["height"] is None
        assert r["sigs"] == 256 and r["n_segs"] == 2
    m = device_metrics
    for phase in ("pack", "dispatch", "fetch"):
        assert m.segment_phase_seconds.count_value(phase, "sync") == 2
    assert m.segment_sigs.count_value("sync") == 2
    ratio = m.pipeline_overlap_ratio.value()
    assert 0.0 < ratio <= 1.0
    tot = phases.phase_totals()
    assert tot["segments"] == 2 and tot["sigs"] == n
    assert tot["pack_s"] >= 0.008


def test_real_device_batch_records_segment(device_metrics):
    """An actual (tiny) kernel dispatch records one segment with nonzero
    pack and fetch phases and the real device label; in-flight drains."""
    pks, msgs, sigs = _workload(4)
    out = V.batch_verify(pks, msgs, sigs)
    assert out.shape == (4,)  # garbage sigs: verdicts False, phases real
    recs = phases.recent_segments()
    assert len(recs) == 1
    r = recs[0]
    assert r["sigs"] == 4 and r["pack_s"] > 0 and r["fetch_s"] > 0
    assert r["device"] != "host"
    m = device_metrics
    assert m.device_dispatch_total.value(r["device"]) == 1
    assert m.device_inflight.value(r["device"]) == 0


def test_height_tag_rides_tracer_spans(monkeypatch, device_metrics):
    from tendermint_tpu.libs.trace import tracer

    monkeypatch.setattr(V, "_dispatch_stream", _fake_dispatch)
    monkeypatch.setattr(V, "SEG_MIN_SIGS", 256)
    tracer.clear()
    tracer.enable()
    try:
        with phases.telemetry(height=42):
            V._verify_segmented([b"\x01" * 32] * 512, [b"m"] * 512,
                                [b"\x02" * 64] * 512, V.LANE)
    finally:
        tracer.disable()
    by_name = {}
    for ev in tracer.events():
        by_name.setdefault(ev["name"], []).append(ev)
    for name in ("seg_pack", "seg_dispatch", "seg_fetch"):
        assert len(by_name.get(name, [])) == 2, name
        assert all(e["args"]["height"] == 42 for e in by_name[name])
    # spans abut: pack end == dispatch start == fetch start - dispatch dur
    ev_p, ev_d = by_name["seg_pack"][0], by_name["seg_dispatch"][0]
    assert abs(ev_p["ts"] + ev_p["dur"] - ev_d["ts"]) < 1.0  # us
    assert recs_height_all_42(phases.recent_segments())


def recs_height_all_42(recs):
    return all(r["height"] == 42 for r in recs)


def test_scalar_batches_count_with_zero_device_phases(device_metrics):
    """Host-routed (route=scalar) batches record no phase observations but
    land on the device plane's ledger as device="host"."""
    from tendermint_tpu.crypto import Ed25519PubKey
    from tendermint_tpu.crypto.batch import BatchVerifier

    pk = host.pubkey_from_seed(b"\x07" * 32)
    bv = BatchVerifier(backend="host", plane="light")
    bv.add(Ed25519PubKey(pk), b"msg", b"\x00" * 64)
    all_ok, out = bv.verify()
    assert not all_ok and not out[0]
    m = device_metrics
    assert m.device_dispatch_total.value("host") == 1
    for phase in ("pack", "dispatch", "fetch"):
        for plane in ("sync", "live", "light"):
            assert m.segment_phase_seconds.count_value(phase, plane) == 0
    tot = phases.phase_totals()
    assert tot["host_batches"] == 1 and tot["host_sigs"] == 1
    assert tot["segments"] == 0


def test_vote_flush_lands_on_live_plane(device_metrics):
    """The vote micro-batcher's device flush routes through the same phase
    instrumentation with plane="live" (set inside the executor thunk —
    contextvars don't cross run_in_executor)."""
    from tendermint_tpu.crypto import Ed25519PubKey
    from tendermint_tpu.crypto.vote_batcher import BatchVoteVerifier

    seeds = [bytes([i]) * 32 for i in range(4)]
    items = []
    for sd in seeds:
        pk = host.pubkey_from_seed(sd)
        msg = b"vote-" + sd[:4]
        items.append((Ed25519PubKey(pk), msg, host.sign(sd + pk, msg)))

    async def run():
        bvv = BatchVoteVerifier(min_device_batch=2, deadline_s=0.005)
        futs = [asyncio.ensure_future(bvv.preverify(pub, m, s))
                for pub, m, s in items]
        return await asyncio.gather(*futs)

    assert all(asyncio.run(run()))
    m = device_metrics
    assert m.segment_phase_seconds.count_value("pack", "live") >= 1
    assert m.segment_sigs.count_value("live") >= 1
    recs = [r for r in phases.recent_segments() if r["plane"] == "live"]
    assert recs and recs[-1]["sigs"] == 4


def test_host_vote_flush_counts_live(device_metrics):
    """A sub-threshold (host) flush records zero device phases but counts
    as a live-plane host batch."""
    from tendermint_tpu.crypto import Ed25519PubKey
    from tendermint_tpu.crypto.vote_batcher import BatchVoteVerifier

    sd = b"\x09" * 32
    pk = host.pubkey_from_seed(sd)
    sig = host.sign(sd + pk, b"m")

    async def run():
        bvv = BatchVoteVerifier(min_device_batch=64, deadline_s=0.005)
        return await bvv.preverify(Ed25519PubKey(pk), b"m", sig)

    assert asyncio.run(run())
    assert device_metrics.segment_phase_seconds.count_value(
        "pack", "live") == 0
    assert device_metrics.device_dispatch_total.value("host") == 1


def test_sharded_mesh_emits_per_device_series(device_metrics):
    """Under the forced 8-device CPU mesh (conftest's
    xla_force_host_platform_device_count=8), a sharded dispatch counts
    every mesh device and the record carries the device list."""
    from tendermint_tpu.crypto.ed25519_jax.sharded import (
        batch_verify_sharded,
        make_mesh,
    )

    pks, msgs, sigs = _workload(16, seed=11)
    mesh = make_mesh(8)
    verdict, total = batch_verify_sharded(pks, msgs, sigs, mesh=mesh)
    assert verdict.shape == (16,) and total == int(verdict.sum())
    m = device_metrics
    for i in range(8):
        assert m.device_dispatch_total.value(f"cpu:{i}") == 1, i
        assert m.device_inflight.value(f"cpu:{i}") == 0, i
    rec = phases.recent_segments()[-1]
    assert rec["device"] == "mesh[8]"
    assert len(rec["devices"]) == 8
    assert rec["pack_s"] > 0 and rec["fetch_s"] > 0
    for phase in ("pack", "dispatch", "fetch"):
        assert m.segment_phase_seconds.count_value(phase, "sync") == 1


def test_failed_fetch_drains_inflight_gauge(monkeypatch, device_metrics):
    """A fetch raising after a successful dispatch must not leave
    crypto_device_inflight stuck above zero for already-dispatched
    segments (the gauge's only decrement used to live in fetched())."""

    class _BrokenDev:
        def __array__(self, dtype=None, copy=None):
            raise RuntimeError("relay dropped the fetch")

    def fake_dispatch(pks, msgs, sigs, chunk):
        phases.mark_pack_done()
        return _BrokenDev(), np.ones(len(pks), bool)

    monkeypatch.setattr(V, "_dispatch_stream", fake_dispatch)
    monkeypatch.setattr(V, "SEG_MIN_SIGS", 256)
    with pytest.raises(RuntimeError, match="relay dropped"):
        # every segment dispatches (gauge +1 each); segment 0's fetch blows
        V._verify_segmented([b"\x01" * 32] * 512, [b"m"] * 512,
                            [b"\x02" * 64] * 512, V.LANE)
    assert device_metrics.device_inflight.value(V._device_label()) == 0
    assert phases.recent_segments() == []  # no phase rows from garbage


def test_abandon_before_dispatch_blocks_late_increment(device_metrics):
    """A segment abandoned while its worker is still packing (sibling
    fetch raised) must reject the worker's LATE dispatched() — otherwise
    the gauge increments with nobody left to drain it."""
    rec = phases.Segment(sigs=1, chunk=128, device="cpu:0").begin()
    rec.abandon()          # call aborted pre-dispatch
    rec.dispatched()       # orphaned worker finishes packing anyway
    m = device_metrics
    assert m.device_inflight.value("cpu:0") == 0
    assert m.device_dispatch_total.value("cpu:0") == 0
    rec.fetched()          # and a late fetch is a no-op too
    assert m.segment_sigs.count_value("sync") == 0


def test_segments_get_distinct_trace_tracks():
    """Concurrent calls (live flush under a sync window) must not share a
    synthetic span track — overlapping slices on one track render as
    mis-nested garbage in Perfetto."""
    a = phases.Segment(sigs=1, chunk=128)
    b = phases.Segment(sigs=1, chunk=128)
    assert a.track != b.track
    assert a.track >= phases._SEG_TRACK_BASE


def test_phase_breakdown_interval_union_math():
    """Hand-computable two-segment pipeline: exposed pack + exposed
    dispatch + in-flight union tile the wall exactly; overlapped host work
    is excluded from the exposed shares but kept in the raw totals."""
    recs = [
        # seg 0: pack [0,1], dispatch [1,1.5], in-flight [1.5,5]
        {"t0": 0.0, "pack_s": 1.0, "dispatch_s": 0.5, "fetch_s": 3.5,
         "t_end": 5.0, "wait_s": 3.0, "sigs": 10},
        # seg 1: pack [1.5,2.5] (hidden behind seg 0's flight),
        # dispatch [2.5,3.0] (hidden), in-flight [3,8]
        {"t0": 1.5, "pack_s": 1.0, "dispatch_s": 0.5, "fetch_s": 5.0,
         "t_end": 8.0, "wait_s": 2.0, "sigs": 10},
    ]
    bd = phases.phase_breakdown(recs, 0.0, 8.0)
    assert abs(bd["device_share"] - 6.5 / 8.0) < 1e-9
    assert abs(bd["pack_share_exposed"] - 1.0 / 8.0) < 1e-9
    assert abs(bd["dispatch_share_exposed"] - 0.5 / 8.0) < 1e-9
    assert abs(bd["accounted_share"] - 1.0) < 1e-9
    assert abs(bd["overlap_ratio"] - 6.5 / 8.5) < 1e-9
    assert bd["pack_s"] == 2.0 and bd["sigs"] == 20
    assert abs(bd["pack_share_total"] - 2.0 / 8.0) < 1e-9


def test_stream_single_dispatch_also_records(monkeypatch, device_metrics):
    """batch_verify_stream's non-segmented leaf (chunk < n < SEG_MIN_SIGS)
    records exactly one segment."""
    monkeypatch.setattr(V, "_dispatch_stream", _fake_dispatch)
    out = V.batch_verify_stream([b"\x01" * 32] * 200, [b"m"] * 200,
                                [b"\x02" * 64] * 200, chunk=V.LANE)
    assert out.all()
    recs = phases.recent_segments()
    assert len(recs) == 1 and recs[0]["sigs"] == 200
    assert recs[0]["n_segs"] == 1


def test_device_profile_schema_and_micro_sweep():
    """The PROFILE JSON a real (stub-kernel) sweep emits validates against
    the tool's own schema, and the sweep restores the module knobs."""
    from tendermint_tpu.libs.toolbox import load_tool

    dp = load_tool("device_profile")
    old = (V.SEG_CHUNKS, V.SEG_MIN_SIGS, V._verify_kernel)
    res = dp.run_sweep(sigs=256, chunks=[128], seg_chunks=[2],
                       workload="synthetic", runs=1, seg_min_sigs=0)
    assert (V.SEG_CHUNKS, V.SEG_MIN_SIGS, V._verify_kernel) == old
    doc = dp.make_doc("sweep", {"sigs": 256}, res)
    assert dp.validate_profile(doc) == []
    row = doc["results"]["table"][0]
    assert row["sigs_per_sec"] > 0 and row["segments"] >= 2
    # a mutilated doc is rejected with a pointed error
    del doc["results"]["table"][0]["sigs_per_sec"]
    errs = dp.validate_profile(doc)
    assert errs and "sigs_per_sec" in errs[0]
    # and cross-kind required keys are enforced
    bad = dp.make_doc("cost-model", {}, {"transfer": {}})
    assert any("fixed_dispatch_ms" in e for e in dp.validate_profile(bad))
