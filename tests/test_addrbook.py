"""AddrBook under churn: persistence round-trips, eviction order, corrupted
book files, and the shared-scoreboard integration (mark_bad strikes the
sync planes' ledger; banned/backing-off peers are never picked or
advertised — PEX can't keep redialing a peer blocksync severe-banned).
"""

import json
import logging
import os

from tendermint_tpu.libs.peerscore import PeerScoreboard
from tendermint_tpu.p2p.netaddress import NetAddress
from tendermint_tpu.p2p.pex import NEW_BUCKET_CAP, AddrBook


def _addr(i, port=26656):
    return NetAddress(f"peer{i:04d}", f"10.0.{i // 256}.{i % 256}", port)


# -- persistence --------------------------------------------------------------

def test_save_load_round_trip(tmp_path):
    path = str(tmp_path / "addrbook.json")
    book = AddrBook(path)
    for i in range(5):
        assert book.add_address(_addr(i), src_id="seed")
    book.mark_good("peer0000")          # graduates to the old bucket
    book.mark_attempt(_addr(1))
    book.save()

    loaded = AddrBook(path)
    assert loaded.size() == 5
    assert loaded.has("peer0000") and loaded.has("peer0004")
    assert loaded._addrs["peer0000"].bucket == "old"
    assert loaded._addrs["peer0001"].attempts == 1
    assert loaded._addrs["peer0002"].bucket == "new"
    # a second round-trip is stable
    loaded.save()
    again = AddrBook(path)
    assert {k: (v.bucket, v.attempts) for k, v in again._addrs.items()} \
        == {k: (v.bucket, v.attempts) for k, v in loaded._addrs.items()}


def test_corrupted_book_loads_empty_with_warning(tmp_path, caplog):
    """A truncated/garbled book file must load as empty-with-warning —
    never crash node start, never half-load."""
    for i, payload in enumerate((
            b"{\"addrs\": [{\"id\": \"x\", \"ho",          # truncated JSON
            b"\x00\x01\x02 not json at all",                # binary garbage
            b"[1, 2, 3]",                                   # wrong shape
            json.dumps({"addrs": [
                {"id": "good", "host": "1.2.3.4", "port": 1},
                {"id": "bad-entry"},                        # missing fields
            ]}).encode(),
    )):
        path = str(tmp_path / f"book{i}.json")
        with open(path, "wb") as f:
            f.write(payload)
        with caplog.at_level(logging.WARNING, logger="tmtpu.p2p.pex"):
            caplog.clear()
            book = AddrBook(path)
        assert book.size() == 0, f"case {i} half-loaded"
        assert any("unreadable" in r.message for r in caplog.records), \
            f"case {i} loaded silently"
        # the damaged book still works (and can be re-saved over the junk)
        assert book.add_address(_addr(1))
        book.save()
        assert AddrBook(path).size() == 1


def test_missing_file_is_not_an_error(tmp_path):
    book = AddrBook(str(tmp_path / "never-written.json"))
    assert book.size() == 0


# -- eviction -----------------------------------------------------------------

def test_new_bucket_eviction_order():
    """At the cap, the most-failed never-succeeded address is evicted
    first; proven (old-bucket) addresses are untouched."""
    book = AddrBook(strict=False)
    for i in range(NEW_BUCKET_CAP):
        assert book.add_address(_addr(i))
    # peer0001 has failed 5 times: the designated victim
    for _ in range(5):
        book.mark_attempt(_addr(1))
    book.mark_good("peer0000")  # old bucket: not an eviction candidate
    # graduating peer0000 freed a new-bucket slot: this add fills it back
    # to the cap without evicting anyone
    assert book.add_address(_addr(NEW_BUCKET_CAP + 1))
    assert book.has("peer0001")
    # at the cap again: the next add evicts the most-failed new entry
    assert book.add_address(_addr(NEW_BUCKET_CAP + 2))
    assert not book.has("peer0001"), "most-failed entry survived eviction"
    assert book.has("peer0000")
    assert book.has(f"peer{NEW_BUCKET_CAP + 2:04d}")
    # ...and the next eviction takes the next-most-failed
    for _ in range(3):
        book.mark_attempt(_addr(2))
    assert book.add_address(_addr(NEW_BUCKET_CAP + 3))
    assert not book.has("peer0002")


def test_duplicates_self_and_unroutable_refused():
    book = AddrBook(strict=True)
    book.add_our_address("me")
    assert not book.add_address(NetAddress("me", "1.2.3.4", 1))
    assert book.add_address(_addr(1))
    assert not book.add_address(_addr(1))  # duplicate
    assert not book.add_address(NetAddress("z", "0.0.0.0", 1))
    assert not book.add_address(NetAddress("z", "1.2.3.4", 0))


# -- scoreboard integration ---------------------------------------------------

def test_mark_bad_strikes_shared_scoreboard():
    sb = PeerScoreboard(ban_threshold=3, name="blocksync")
    book = AddrBook(strict=False, scoreboard=sb)
    book.add_address(_addr(1))
    book.mark_bad("peer0001", reason="bad_block")
    assert not book.has("peer0001")
    # severe strike: banned instantly, with the reason recorded
    assert sb.banned("peer0001")
    assert sb.snapshot()["peer0001"]["ban_reason"] == "bad_block"


def test_banned_peers_never_picked_or_advertised():
    """A peer blocksync severe-banned is invisible to pick_address AND
    get_selection, even while its address is still in the book."""
    sb = PeerScoreboard(ban_threshold=1, name="blocksync")
    book = AddrBook(strict=False, scoreboard=sb)
    for i in range(6):
        book.add_address(_addr(i))
        book.mark_good(f"peer{i:04d}")
    sb.record_failure("peer0002", "bad_block", severe=True)
    assert sb.banned("peer0002")
    for _ in range(50):
        pick = book.pick_address()
        assert pick is not None and pick.id != "peer0002"
    for _ in range(10):
        assert "peer0002" not in {a.id for a in book.get_selection()}
    # the entry itself survives (bans are the scoreboard's verdict; the
    # address may be re-admitted if the ledger is reset)
    assert book.has("peer0002")


def test_backoff_excludes_then_readmits():
    """A backing-off (not banned) peer is excluded until its window ends —
    driven through a fake clock so the test owns time."""
    clock = [100.0]
    sb = PeerScoreboard(ban_threshold=5, backoff_base_s=10.0, jitter=0.0,
                        clock=lambda: clock[0])
    book = AddrBook(strict=False, scoreboard=sb)
    book.add_address(_addr(1))
    book.add_address(_addr(2))
    sb.record_failure("peer0001", "timeout")
    assert sb.in_backoff("peer0001")
    for _ in range(20):
        assert book.pick_address().id == "peer0002"
    assert {a.id for a in book.get_selection()} == {"peer0002"}
    clock[0] += 11.0  # backoff expired: re-admitted
    assert not sb.in_backoff("peer0001")
    assert "peer0001" in {book.pick_address().id for _ in range(50)}


def test_all_usable_excluded_returns_none():
    sb = PeerScoreboard(ban_threshold=1)
    book = AddrBook(strict=False, scoreboard=sb)
    book.add_address(_addr(1))
    sb.record_failure("peer0001", "lies", severe=True)
    assert book.pick_address() is None
    assert book.get_selection() == []


def test_book_without_scoreboard_unchanged():
    book = AddrBook(strict=False)
    book.add_address(_addr(1))
    book.mark_bad("peer0001")
    assert not book.has("peer0001")
    book.add_address(_addr(2))
    assert book.pick_address().id == "peer0002"
