"""Out-of-process ABCI: socket server/client round-trips incl. Header transport."""

import pytest

from tendermint_tpu.abci import types as abci
from tendermint_tpu.abci.client import SocketClient
from tendermint_tpu.abci.example.kvstore import KVStoreApplication
from tendermint_tpu.abci.server import ABCIServer
from tendermint_tpu.types.block import Consensus, Header


@pytest.fixture
def server_client(tmp_path):
    app = KVStoreApplication()
    srv = ABCIServer(f"unix://{tmp_path}/abci.sock", app)
    srv.start()
    client = SocketClient(f"unix://{tmp_path}/abci.sock")
    yield app, client
    client.close()
    srv.stop()


def test_echo_info(server_client):
    app, client = server_client
    assert client.echo("ping") == "ping"
    info = client.info(abci.RequestInfo(version="x"))
    assert info.last_block_height == 0


def test_deliver_and_commit(server_client):
    app, client = server_client
    res = client.deliver_tx(abci.RequestDeliverTx(tx=b"sock=et"))
    assert res.is_ok()
    assert res.events and res.events[0].type == "app"
    assert isinstance(res.events[0].attributes[0], abci.EventAttribute)
    commit = client.commit()
    assert commit.data == (1).to_bytes(8, "big")
    assert app.state["sock"] == "et"


def test_begin_block_header_crosses_socket(server_client):
    app, client = server_client

    seen = {}
    orig = app.begin_block

    def spy(req):
        seen["header"] = req.header
        return orig(req)

    app.begin_block = spy
    header = Header(version=Consensus(11, 0), chain_id="sock-chain", height=9,
                    validators_hash=b"\x01" * 32, proposer_address=b"\x02" * 20)
    client.begin_block(abci.RequestBeginBlock(
        hash=b"\x03" * 32, header=header,
        last_commit_info=abci.LastCommitInfo(round=1, votes=[
            abci.VoteInfo(abci.ABCIValidator(b"\x04" * 20, 10), True)])))
    got = seen["header"]
    assert isinstance(got, Header)
    assert got.chain_id == "sock-chain" and got.height == 9
    assert got.validators_hash == header.validators_hash


def test_query_roundtrip(server_client):
    app, client = server_client
    client.deliver_tx(abci.RequestDeliverTx(tx=b"k=v"))
    res = client.query(abci.RequestQuery(data=b"k", path="/store"))
    assert res.value == b"v" and res.log == "exists"


def test_error_reported_not_fatal(server_client):
    app, client = server_client

    def boom(req):
        raise RuntimeError("kaboom")

    app.query = boom
    from tendermint_tpu.abci.client import ABCIClientError

    with pytest.raises(ABCIClientError, match="kaboom"):
        client.query(abci.RequestQuery(data=b"k"))
    # connection still usable
    assert client.echo("still-alive") == "still-alive"
