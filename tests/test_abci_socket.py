"""Out-of-process ABCI: socket server/client round-trips incl. Header transport."""

import pytest

from tendermint_tpu.abci import types as abci
from tendermint_tpu.abci.client import SocketClient
from tendermint_tpu.abci.example.kvstore import KVStoreApplication
from tendermint_tpu.abci.server import ABCIServer
from tendermint_tpu.types.block import Consensus, Header


@pytest.fixture
def server_client(tmp_path):
    app = KVStoreApplication()
    srv = ABCIServer(f"unix://{tmp_path}/abci.sock", app)
    srv.start()
    client = SocketClient(f"unix://{tmp_path}/abci.sock")
    yield app, client
    client.close()
    srv.stop()


def test_echo_info(server_client):
    app, client = server_client
    assert client.echo("ping") == "ping"
    info = client.info(abci.RequestInfo(version="x"))
    assert info.last_block_height == 0


def test_deliver_and_commit(server_client):
    app, client = server_client
    res = client.deliver_tx(abci.RequestDeliverTx(tx=b"sock=et"))
    assert res.is_ok()
    assert res.events and res.events[0].type == "app"
    assert isinstance(res.events[0].attributes[0], abci.EventAttribute)
    commit = client.commit()
    assert commit.data == (1).to_bytes(8, "big")
    assert app.state["sock"] == "et"


def test_begin_block_header_crosses_socket(server_client):
    app, client = server_client

    seen = {}
    orig = app.begin_block

    def spy(req):
        seen["header"] = req.header
        return orig(req)

    app.begin_block = spy
    header = Header(version=Consensus(11, 0), chain_id="sock-chain", height=9,
                    validators_hash=b"\x01" * 32, proposer_address=b"\x02" * 20)
    client.begin_block(abci.RequestBeginBlock(
        hash=b"\x03" * 32, header=header,
        last_commit_info=abci.LastCommitInfo(round=1, votes=[
            abci.VoteInfo(abci.ABCIValidator(b"\x04" * 20, 10), True)])))
    got = seen["header"]
    assert isinstance(got, Header)
    assert got.chain_id == "sock-chain" and got.height == 9
    assert got.validators_hash == header.validators_hash


def test_query_roundtrip(server_client):
    app, client = server_client
    client.deliver_tx(abci.RequestDeliverTx(tx=b"k=v"))
    res = client.query(abci.RequestQuery(data=b"k", path="/store"))
    assert res.value == b"v" and res.log == "exists"


def test_error_reported_not_fatal(server_client):
    app, client = server_client

    def boom(req):
        raise RuntimeError("kaboom")

    app.query = boom
    from tendermint_tpu.abci.client import ABCIClientError

    with pytest.raises(ABCIClientError, match="kaboom"):
        client.query(abci.RequestQuery(data=b"k"))
    # connection still usable
    assert client.echo("still-alive") == "still-alive"


def test_response_deliver_tx_gogoproto_golden_vector():
    """ResponseDeliverTx deterministic encoding must match gogoproto bytes
    exactly — it feeds LastResultsHash (reference types/results.go:22).
    Vector hand-derived from proto wire rules for
    {code:5, data:"abc", gas_wanted:100, gas_used:90}: field 1 varint 5,
    field 2 bytes "abc", field 5 varint 100, field 6 varint 90 (log/info/
    events/codespace excluded from the deterministic form, results.go
    deterministicResponseDeliverTx)."""
    r = abci.ResponseDeliverTx(code=5, data=b"abc", log="nondet", info="x",
                               gas_wanted=100, gas_used=90)
    expected = bytes([0x08, 0x05,              # 1: varint 5
                      0x12, 0x03, 0x61, 0x62, 0x63,  # 2: "abc"
                      0x28, 0x64,              # 5: varint 100
                      0x30, 0x5A])             # 6: varint 90
    assert r.deterministic_encode() == expected
    # zero-value: empty encoding (gogoproto omits defaults)
    assert abci.ResponseDeliverTx().deterministic_encode() == b""


def test_proto_codec_round_trips():
    """Request/Response envelopes round-trip bit-exactly through the
    reference wire format (proto/tendermint/abci/types.proto oneof)."""
    from tendermint_tpu.abci.proto_codec import (
        decode_request,
        decode_response,
        encode_request,
        encode_response,
    )
    from tendermint_tpu.libs import protowire as pw

    cases = [
        ("info", abci.RequestInfo(version="0.34.24", block_version=11,
                                  p2p_version=8)),
        ("check_tx", abci.RequestCheckTx(tx=b"k=v",
                                         type=abci.CHECK_TX_TYPE_RECHECK)),
        ("deliver_tx", abci.RequestDeliverTx(tx=b"\x00\xffdata")),
        ("query", abci.RequestQuery(data=b"key", path="/store", height=7,
                                    prove=True)),
        ("end_block", abci.RequestEndBlock(height=42)),
        ("offer_snapshot", abci.RequestOfferSnapshot(
            snapshot=abci.Snapshot(10, 1, 3, b"h" * 32, b"meta"),
            app_hash=b"a" * 32)),
        ("load_snapshot_chunk", abci.RequestLoadSnapshotChunk(10, 1, 2)),
        ("apply_snapshot_chunk", abci.RequestApplySnapshotChunk(
            index=1, chunk=b"chunk", sender="peer1")),
    ]
    for method, req in cases:
        framed = encode_request(method, req)
        ln, pos = pw.decode_varint(framed, 0)
        m2, req2 = decode_request(framed[pos:pos + ln])
        assert m2 == method
        assert req2 == req, (method, req2, req)

    resp_cases = [
        ("info", abci.ResponseInfo(data="app", version="1", app_version=2,
                                   last_block_height=5,
                                   last_block_app_hash=b"\x01" * 8)),
        ("check_tx", abci.ResponseCheckTx(code=1, log="bad", gas_wanted=3,
                                          priority=9, sender="s")),
        ("deliver_tx", abci.ResponseDeliverTx(code=0, data=b"out",
                                              gas_used=12)),
        ("commit", abci.ResponseCommit(data=b"apphash", retain_height=3)),
        ("offer_snapshot", abci.ResponseOfferSnapshot(
            result=abci.OFFER_SNAPSHOT_ACCEPT)),
        ("apply_snapshot_chunk", abci.ResponseApplySnapshotChunk(
            result=abci.APPLY_SNAPSHOT_CHUNK_RETRY, refetch_chunks=[1, 2],
            reject_senders=["bad"])),
    ]
    for method, resp in resp_cases:
        framed = encode_response(method, resp)
        ln, pos = pw.decode_varint(framed, 0)
        m2, resp2 = decode_response(framed[pos:pos + ln])
        assert m2 == method
        assert resp2 == resp, (method, resp2, resp)
