"""Span tracer (libs/trace.py): ring bounding, disabled-path cost, Chrome
trace-event export shape."""

import json
import threading

from tendermint_tpu.libs.trace import Tracer, _NOOP_SPAN


def test_disabled_tracer_is_noop_singleton():
    t = Tracer(capacity=8, enabled=False)
    s1 = t.span("a", height=1)
    s2 = t.span("b")
    # zero-allocation path: the SAME shared object every call, no state
    assert s1 is s2 is _NOOP_SPAN
    with s1:
        pass
    t.instant("c")
    assert t.events() == []


def test_span_records_complete_event():
    t = Tracer(capacity=8, enabled=True)
    with t.span("verify_window", height=7, n=3):
        pass
    (ev,) = t.events()
    assert ev["name"] == "verify_window"
    assert ev["ph"] == "X"
    assert ev["dur"] >= 0
    assert ev["ts"] > 0
    assert ev["args"] == {"height": 7, "n": 3}
    assert isinstance(ev["pid"], int) and isinstance(ev["tid"], int)


def test_ring_buffer_bounded():
    t = Tracer(capacity=16, enabled=True)
    for i in range(100):
        with t.span("s", i=i):
            pass
    evs = t.events()
    assert len(evs) == 16
    # oldest fell off the front: only the newest 16 survive
    assert [e["args"]["i"] for e in evs] == list(range(84, 100))
    assert [e["args"]["i"] for e in t.tail(4)] == [96, 97, 98, 99]


def test_chrome_trace_export_roundtrip(tmp_path):
    t = Tracer(enabled=True)
    with t.span("apply_block", height=1):
        pass
    t.instant("vote_flush", n=5)
    path = t.write(str(tmp_path / "trace.json"))
    data = json.loads(open(path).read())
    assert data["displayTimeUnit"] == "ms"
    names = [e["name"] for e in data["traceEvents"]]
    assert names == ["apply_block", "vote_flush"]
    inst = data["traceEvents"][1]
    assert inst["ph"] == "i" and inst["s"] == "t"
    # event timestamps are monotonic within a thread
    assert data["traceEvents"][0]["ts"] <= inst["ts"]


def test_enable_disable_clear():
    t = Tracer(enabled=False)
    t.enable()
    with t.span("a"):
        pass
    t.disable()
    with t.span("b"):
        pass
    assert [e["name"] for e in t.events()] == ["a"]
    t.clear()
    assert t.events() == []


def test_threaded_appends_all_land():
    t = Tracer(capacity=4096, enabled=True)

    def work():
        for i in range(200):
            with t.span("w", i=i):
                pass

    threads = [threading.Thread(target=work) for _ in range(4)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    assert len(t.events()) == 800
