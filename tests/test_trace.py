"""Span tracer (libs/trace.py): ring bounding, disabled-path cost, Chrome
trace-event export shape."""

import json
import threading

from tendermint_tpu.libs.trace import Tracer, _NOOP_SPAN


def test_disabled_tracer_is_noop_singleton():
    t = Tracer(capacity=8, enabled=False)
    s1 = t.span("a", height=1)
    s2 = t.span("b")
    # zero-allocation path: the SAME shared object every call, no state
    assert s1 is s2 is _NOOP_SPAN
    with s1:
        pass
    t.instant("c")
    assert t.events() == []


def test_span_records_complete_event():
    t = Tracer(capacity=8, enabled=True)
    with t.span("verify_window", height=7, n=3):
        pass
    (ev,) = t.events()
    assert ev["name"] == "verify_window"
    assert ev["ph"] == "X"
    assert ev["dur"] >= 0
    assert ev["ts"] > 0
    assert ev["args"] == {"height": 7, "n": 3}
    assert isinstance(ev["pid"], int) and isinstance(ev["tid"], int)


def test_ring_buffer_bounded():
    t = Tracer(capacity=16, enabled=True)
    for i in range(100):
        with t.span("s", i=i):
            pass
    evs = t.events()
    assert len(evs) == 16
    # oldest fell off the front: only the newest 16 survive
    assert [e["args"]["i"] for e in evs] == list(range(84, 100))
    assert [e["args"]["i"] for e in t.tail(4)] == [96, 97, 98, 99]


def test_chrome_trace_export_roundtrip(tmp_path):
    t = Tracer(enabled=True)
    with t.span("apply_block", height=1):
        pass
    t.instant("vote_flush", n=5)
    path = t.write(str(tmp_path / "trace.json"))
    data = json.loads(open(path).read())
    assert data["displayTimeUnit"] == "ms"
    names = [e["name"] for e in data["traceEvents"]]
    assert names == ["apply_block", "vote_flush"]
    inst = data["traceEvents"][1]
    assert inst["ph"] == "i" and inst["s"] == "t"
    # event timestamps are monotonic within a thread
    assert data["traceEvents"][0]["ts"] <= inst["ts"]


def test_enable_disable_clear():
    t = Tracer(enabled=False)
    t.enable()
    with t.span("a"):
        pass
    t.disable()
    with t.span("b"):
        pass
    assert [e["name"] for e in t.events()] == ["a"]
    t.clear()
    assert t.events() == []


def test_dropped_counter_counts_ring_overflow():
    t = Tracer(capacity=16, enabled=True)
    for i in range(20):
        with t.span("s", i=i):
            pass
    assert t.dropped == 4
    assert t.chrome_trace()["dropped"] == 4
    t.clear()
    assert t.dropped == 0
    # the metrics hook sees every drop (NodeMetrics.trace_dropped_events_total)
    from tendermint_tpu.libs.metrics import NodeMetrics

    m = NodeMetrics()
    t.drop_counter = m.trace_dropped_events_total
    for i in range(18):
        t.instant("x", i=i)
    assert m.trace_dropped_events_total.value() == 2
    assert "tendermint_trace_dropped_events_total 2" in m.registry.render()


def test_identity_header_and_process_name_metadata():
    t = Tracer(capacity=8, enabled=True)
    with t.span("a"):
        pass
    # without identity: plain container, no metadata event
    doc = t.chrome_trace()
    assert "node_id" not in doc
    assert all(e.get("ph") != "M" for e in doc["traceEvents"])
    t.set_identity("node3")
    doc = t.chrome_trace()
    assert doc["node_id"] == "node3"
    assert doc["epoch_unix_s"] > 0 and doc["epoch_perf_us"] > 0
    meta = doc["traceEvents"][0]
    assert meta["ph"] == "M" and meta["args"]["name"] == "node3"
    # the wall<->perf epoch pair describes ONE instant: converting the
    # span's perf ts through it lands within a second of now
    import time

    ev = doc["traceEvents"][1]
    wall_s = doc["epoch_unix_s"] + (ev["ts"] - doc["epoch_perf_us"]) / 1e6
    assert abs(wall_s - time.time()) < 1.0


def test_complete_records_explicit_span():
    t = Tracer(capacity=8, enabled=True)
    t.complete("stage_prevote_quorum", 1000.0, 250.0, height=5, round=1)
    (ev,) = t.events()
    assert ev["ph"] == "X" and ev["ts"] == 1000.0 and ev["dur"] == 250.0
    assert ev["args"] == {"height": 5, "round": 1}
    t.disable()
    t.complete("ignored", 0.0, 1.0)
    assert len(t.events()) == 1


def test_threaded_appends_all_land():
    t = Tracer(capacity=4096, enabled=True)

    def work():
        for i in range(200):
            with t.span("w", i=i):
                pass

    threads = [threading.Thread(target=work) for _ in range(4)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    assert len(t.events()) == 800
