"""Window-batched light-client verification + batched sign-bytes encoder.

verify_commit_light_trusting_batched must replay the exact semantics of the
sequential verify_commit_light_trusting (reference validator_set.go:775),
and canonical.vote_sign_bytes_batch must be byte-identical to the per-index
encoder — it is the host-side cost floor of the batched device path.
"""

import numpy as np
import pytest
pytest.importorskip("cryptography", reason="needs the optional 'cryptography' package (absent in slim containers)")

from cryptography.hazmat.primitives.asymmetric.ed25519 import Ed25519PrivateKey

from tendermint_tpu import crypto
from tendermint_tpu.types import Validator, ValidatorSet
from tendermint_tpu.types.basic import (
    BlockID,
    BlockIDFlag,
    PartSetHeader,
    SignedMsgType,
)
from tendermint_tpu.types.block import Commit, CommitSig
from tendermint_tpu.types.canonical import vote_sign_bytes, vote_sign_bytes_batch
from tendermint_tpu.types.validator_set import (
    ErrNotEnoughVotingPowerSigned,
    ErrWrongSignature,
    verify_commit_light_trusting_batched,
)

CHAIN = "light-batched-test"


def _mk_val_set(n, seed=7):
    rng = np.random.default_rng(seed)
    keys, vals = {}, []
    for _ in range(n):
        sk = Ed25519PrivateKey.from_private_bytes(
            bytes(rng.integers(0, 256, 32, dtype=np.uint8)))
        pub = crypto.Ed25519PubKey(sk.public_key().public_bytes_raw())
        keys[pub.address()] = sk
        vals.append(Validator(pub.address(), pub, 10))
    return ValidatorSet(vals), keys


def _sign_commit(vs, keys, height, nil_every=0):
    bid = BlockID(height.to_bytes(8, "big") * 4, PartSetHeader(1, b"\x02" * 32))
    sigs = []
    for i, v in enumerate(vs.validators):
        ts = 1_700_000_000_000_000_000 + height * 1_000_000 + i
        flag = (BlockIDFlag.NIL if nil_every and i % nil_every == 0
                else BlockIDFlag.COMMIT)
        cs_bid = bid if flag == BlockIDFlag.COMMIT else BlockID()
        from tendermint_tpu.types.canonical import vote_sign_bytes as vsb

        msg = vsb(CHAIN, SignedMsgType.PRECOMMIT, height, 0, cs_bid, ts)
        sigs.append(CommitSig(flag, v.address, ts, keys[v.address].sign(msg)))
    return Commit(height, 0, bid, sigs), bid


def test_vote_sign_bytes_batch_matches_per_index():
    bid = BlockID(b"\x11" * 32, PartSetHeader(3, b"\x22" * 32))
    zero = BlockID()
    rows = [
        (bid, 1_700_000_000_123_456_789),
        (zero, 0),                       # zero time: Timestamp still emitted
        (bid, 1),                        # 1ns: nanos varint only
        (zero, 2_000_000_000_000_000_000),
        (bid, 999_999_999),              # sub-second boundary
    ]
    got = vote_sign_bytes_batch(CHAIN, SignedMsgType.PRECOMMIT, 77, 2,
                                [r[0] for r in rows], [r[1] for r in rows])
    want = [vote_sign_bytes(CHAIN, SignedMsgType.PRECOMMIT, 77, 2, b, t)
            for b, t in rows]
    assert got == want


def test_commit_vote_sign_bytes_all_matches_and_memoizes(monkeypatch):
    vs, keys = _mk_val_set(12)
    commit, _bid = _sign_commit(vs, keys, 9, nil_every=5)
    all_sb = commit.vote_sign_bytes_all(CHAIN)
    assert all_sb == [commit.vote_sign_bytes(CHAIN, i)
                      for i in range(len(vs.validators))]
    assert commit.vote_sign_bytes_all(CHAIN) is all_sb  # memo hit
    assert commit.vote_sign_bytes_all("other") != all_sb  # keyed by chain


def test_trusting_batched_matches_sequential(monkeypatch):
    monkeypatch.setenv("TMTPU_BATCH_BACKEND", "host")
    vs, keys = _mk_val_set(20)
    trust = (1, 3)
    commits = [_sign_commit(vs, keys, h)[0] for h in range(2, 7)]

    # happy path: every entry None, sequential agrees
    entries = [(vs, CHAIN, c, trust) for c in commits]
    assert all(e is None for e in verify_commit_light_trusting_batched(entries))
    for c in commits:
        vs.verify_commit_light_trusting(CHAIN, c, trust)

    # corrupt an EARLY-POSITION signature of commit 2: that entry errors,
    # neighbors unaffected
    bad = commits[2]
    sig = bytearray(bad.signatures[0].signature)
    sig[0] ^= 1
    bad.signatures[0].signature = bytes(sig)
    results = verify_commit_light_trusting_batched(entries)
    assert isinstance(results[2], ErrWrongSignature)
    assert all(r is None for i, r in enumerate(results) if i != 2)
    with pytest.raises(ErrWrongSignature):
        vs.verify_commit_light_trusting(CHAIN, bad, trust)

    # a LATE corrupt signature past the trust-level early exit is never
    # examined — batched must preserve the early-exit semantics
    late = commits[3]
    sig = bytearray(late.signatures[-1].signature)
    sig[0] ^= 1
    late.signatures[-1].signature = bytes(sig)
    results = verify_commit_light_trusting_batched(entries)
    assert results[3] is None
    vs.verify_commit_light_trusting(CHAIN, late, trust)


def test_trusting_batched_insufficient_power_and_zero_denominator():
    vs, keys = _mk_val_set(9)
    commit, _ = _sign_commit(vs, keys, 3)
    # strip most signatures to absent: not enough power for 2/3 trust
    for i in range(1, 9):
        commit.signatures[i] = CommitSig.new_absent()
    results = verify_commit_light_trusting_batched(
        [(vs, CHAIN, commit, (2, 3)), (vs, CHAIN, commit, (1, 0))])
    assert isinstance(results[0], ErrNotEnoughVotingPowerSigned)
    assert isinstance(results[1], ValueError)


def test_trusting_batched_foreign_addresses_skipped():
    """Signatures from validators outside the trusted set don't count
    (the light client's core trust rule)."""
    vs, keys = _mk_val_set(8)
    other_vs, other_keys = _mk_val_set(8, seed=99)
    commit, _ = _sign_commit(other_vs, other_keys, 4)
    results = verify_commit_light_trusting_batched(
        [(vs, CHAIN, commit, (1, 3))])
    assert isinstance(results[0], ErrNotEnoughVotingPowerSigned)
