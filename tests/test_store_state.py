"""BlockStore/StateStore/db + ABCI kvstore + BlockExecutor integration."""

import pytest

from tendermint_tpu import crypto
from tendermint_tpu.abci import types as abci
from tendermint_tpu.abci.example.kvstore import KVStoreApplication
from tendermint_tpu.libs.db import MemDB, SQLiteDB, _prefix_end
from tendermint_tpu.proxy import AppConns, local_client_creator
from tendermint_tpu.state import (
    ABCIResponses,
    BlockExecutor,
    State,
    StateStore,
    state_from_genesis,
)
from tendermint_tpu.state.execution import EmptyEvidencePool, NoOpMempool
from tendermint_tpu.store import BlockStore
from tendermint_tpu.types import (
    BlockID,
    GenesisDoc,
    GenesisValidator,
    MockPV,
    SignedMsgType,
    Vote,
    VoteSet,
)
from tendermint_tpu.types.vote_set import vote_to_commit_sig
from tendermint_tpu.types.block import Commit

CHAIN_ID = "test-chain"


def test_db_prefix_iteration(tmp_path):
    for db in (MemDB(), SQLiteDB(str(tmp_path / "t.db"))):
        db.set(b"a:1", b"x")
        db.set(b"a:2", b"y")
        db.set(b"b:1", b"z")
        assert [k for k, _ in db.iterate_prefix(b"a:")] == [b"a:1", b"a:2"]
        assert [k for k, _ in db.iterate(reverse=True)][0] == b"b:1"
        db.write_batch([(b"c:1", b"w")], [b"a:1"])
        assert db.get(b"a:1") is None and db.get(b"c:1") == b"w"


def test_prefix_end_edge():
    assert _prefix_end(b"a\xff") == b"b"
    assert _prefix_end(b"\xff\xff") is None


@pytest.fixture
def chain():
    pv = MockPV(crypto.Ed25519PrivKey.generate(b"\x11" * 32))
    genesis = GenesisDoc(
        chain_id=CHAIN_ID,
        genesis_time_ns=1_700_000_000_000_000_000,
        validators=[GenesisValidator(pv.get_pub_key(), 10)],
    )
    state = state_from_genesis(genesis)
    app = KVStoreApplication()
    conns = AppConns(local_client_creator(app))
    conns.start()
    state_store = StateStore(MemDB())
    block_store = BlockStore(MemDB())
    executor = BlockExecutor(state_store, conns.consensus, NoOpMempool(),
                             EmptyEvidencePool(), block_store)
    state_store.save(state)
    return pv, state, executor, state_store, block_store, app


def make_commit_for(state: State, pv: MockPV, block, parts) -> Commit:
    bid = BlockID(block.hash(), parts.header())
    vs = VoteSet(state.chain_id, block.header.height, 0, SignedMsgType.PRECOMMIT,
                 state.validators)
    val = state.validators.validators[0]
    v = Vote(SignedMsgType.PRECOMMIT, block.header.height, 0, bid,
             block.header.time_ns + 1, val.address, 0)
    pv.sign_vote(state.chain_id, v)
    vs.add_vote(v)
    return vs.make_commit()


def test_apply_blocks_and_stores(chain):
    pv, state, executor, state_store, block_store, app = chain
    last_commit = None
    for h in range(1, 4):
        proposer = state.validators.get_proposer().address
        txs = [f"k{h}=v{h}".encode()]
        if h == 1:
            commit = Commit(0, 0, BlockID(), [])
        else:
            commit = last_commit
        block, parts = state.make_block(h, txs, commit, [], proposer)
        bid = BlockID(block.hash(), parts.header())
        new_state, _ = executor.apply_block(state, bid, block)
        seen = make_commit_for(state, pv, block, parts)
        block_store.save_block(block, parts, seen)
        last_commit = seen
        state = new_state

    assert state.last_block_height == 3
    assert app.height == 3
    assert app.state == {"k1": "v1", "k2": "v2", "k3": "v3"}
    # app hash feeds forward
    assert state.app_hash == (3).to_bytes(8, "big")

    # stores are consistent
    assert block_store.height() == 3 and block_store.base() == 1
    blk2 = block_store.load_block(2)
    assert blk2 is not None and blk2.data.txs == [b"k2=v2"]
    assert block_store.load_block_by_hash(blk2.hash()).header.height == 2
    assert block_store.load_seen_commit(3) is not None
    # canonical commit for h=2 was stored when saving block 3
    assert block_store.load_block_commit(2).height == 2

    # state store reload
    st2 = state_store.load()
    assert st2.last_block_height == 3
    assert st2.validators.hash() == state.validators.hash()
    assert state_store.load_validators(2) is not None
    resp = state_store.load_abci_responses(2)
    assert resp is not None and len(resp.deliver_txs) == 1 and resp.deliver_txs[0].is_ok()


def test_validate_block_rejects_wrong_app_hash(chain):
    pv, state, executor, *_ = chain
    proposer = state.validators.get_proposer().address
    block, parts = state.make_block(1, [b"a=b"], Commit(0, 0, BlockID(), []), [], proposer)
    block.header.app_hash = b"\x01" * 8
    block.header.data_hash = b""
    block.fill_header()
    with pytest.raises(ValueError, match="AppHash"):
        executor.validate_block(state, block)


def test_block_store_prune(chain):
    pv, state, executor, state_store, block_store, app = chain
    last_commit = Commit(0, 0, BlockID(), [])
    for h in range(1, 6):
        proposer = state.validators.get_proposer().address
        block, parts = state.make_block(h, [], last_commit, [], proposer)
        bid = BlockID(block.hash(), parts.header())
        state, _ = executor.apply_block(state, bid, block)
        seen = make_commit_for(state_store.load() or state, pv, block, parts)
        # note: state already advanced; sign with the original set (single val)
        block_store.save_block(block, parts, seen)
        last_commit = seen
    assert block_store.prune_blocks(4) == 3
    assert block_store.base() == 4
    assert block_store.load_block(2) is None
    assert block_store.load_block(5) is not None


def test_kvstore_validator_update_tx(chain):
    pv, state, executor, state_store, block_store, app = chain
    newpv = MockPV(crypto.Ed25519PrivKey.generate(b"\x22" * 32))
    pub_hex = newpv.get_pub_key().bytes().hex()
    proposer = state.validators.get_proposer().address
    tx = f"val:{pub_hex}!7".encode()
    block, parts = state.make_block(1, [tx], Commit(0, 0, BlockID(), []), [], proposer)
    bid = BlockID(block.hash(), parts.header())
    new_state, _ = executor.apply_block(state, bid, block)
    # validator set now has 2 members at the height after next
    assert new_state.next_validators.size() == 2
    assert new_state.validators.size() == 1
