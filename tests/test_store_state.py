"""BlockStore/StateStore/db + ABCI kvstore + BlockExecutor integration."""

import pytest

from tendermint_tpu import crypto
from tendermint_tpu.abci import types as abci
from tendermint_tpu.abci.example.kvstore import KVStoreApplication
from tendermint_tpu.libs.db import MemDB, SQLiteDB, _prefix_end
from tendermint_tpu.proxy import AppConns, local_client_creator
from tendermint_tpu.state import (
    ABCIResponses,
    BlockExecutor,
    State,
    StateStore,
    state_from_genesis,
)
from tendermint_tpu.state.execution import EmptyEvidencePool, NoOpMempool
from tendermint_tpu.store import BlockStore
from tendermint_tpu.types import (
    BlockID,
    GenesisDoc,
    GenesisValidator,
    MockPV,
    SignedMsgType,
    Vote,
    VoteSet,
)
from tendermint_tpu.types.vote_set import vote_to_commit_sig
from tendermint_tpu.types.block import Commit

CHAIN_ID = "test-chain"


def test_db_prefix_iteration(tmp_path):
    for db in (MemDB(), SQLiteDB(str(tmp_path / "t.db"))):
        db.set(b"a:1", b"x")
        db.set(b"a:2", b"y")
        db.set(b"b:1", b"z")
        assert [k for k, _ in db.iterate_prefix(b"a:")] == [b"a:1", b"a:2"]
        assert [k for k, _ in db.iterate(reverse=True)][0] == b"b:1"
        db.write_batch([(b"c:1", b"w")], [b"a:1"])
        assert db.get(b"a:1") is None and db.get(b"c:1") == b"w"


def test_prefix_end_edge():
    assert _prefix_end(b"a\xff") == b"b"
    assert _prefix_end(b"\xff\xff") is None


@pytest.fixture
def chain():
    pv = MockPV(crypto.Ed25519PrivKey.generate(b"\x11" * 32))
    genesis = GenesisDoc(
        chain_id=CHAIN_ID,
        genesis_time_ns=1_700_000_000_000_000_000,
        validators=[GenesisValidator(pv.get_pub_key(), 10)],
    )
    state = state_from_genesis(genesis)
    app = KVStoreApplication()
    conns = AppConns(local_client_creator(app))
    conns.start()
    state_store = StateStore(MemDB())
    block_store = BlockStore(MemDB())
    executor = BlockExecutor(state_store, conns.consensus, NoOpMempool(),
                             EmptyEvidencePool(), block_store)
    state_store.save(state)
    return pv, state, executor, state_store, block_store, app


def make_commit_for(state: State, pv: MockPV, block, parts) -> Commit:
    bid = BlockID(block.hash(), parts.header())
    vs = VoteSet(state.chain_id, block.header.height, 0, SignedMsgType.PRECOMMIT,
                 state.validators)
    val = state.validators.validators[0]
    v = Vote(SignedMsgType.PRECOMMIT, block.header.height, 0, bid,
             block.header.time_ns + 1, val.address, 0)
    pv.sign_vote(state.chain_id, v)
    vs.add_vote(v)
    return vs.make_commit()


def test_apply_blocks_and_stores(chain):
    pv, state, executor, state_store, block_store, app = chain
    last_commit = None
    for h in range(1, 4):
        proposer = state.validators.get_proposer().address
        txs = [f"k{h}=v{h}".encode()]
        if h == 1:
            commit = Commit(0, 0, BlockID(), [])
        else:
            commit = last_commit
        block, parts = state.make_block(h, txs, commit, [], proposer)
        bid = BlockID(block.hash(), parts.header())
        new_state, _ = executor.apply_block(state, bid, block)
        seen = make_commit_for(state, pv, block, parts)
        block_store.save_block(block, parts, seen)
        last_commit = seen
        state = new_state

    assert state.last_block_height == 3
    assert app.height == 3
    assert app.state == {"k1": "v1", "k2": "v2", "k3": "v3"}
    # app hash feeds forward
    assert state.app_hash == (3).to_bytes(8, "big")

    # stores are consistent
    assert block_store.height() == 3 and block_store.base() == 1
    blk2 = block_store.load_block(2)
    assert blk2 is not None and blk2.data.txs == [b"k2=v2"]
    assert block_store.load_block_by_hash(blk2.hash()).header.height == 2
    assert block_store.load_seen_commit(3) is not None
    # canonical commit for h=2 was stored when saving block 3
    assert block_store.load_block_commit(2).height == 2

    # state store reload
    st2 = state_store.load()
    assert st2.last_block_height == 3
    assert st2.validators.hash() == state.validators.hash()
    assert state_store.load_validators(2) is not None
    resp = state_store.load_abci_responses(2)
    assert resp is not None and len(resp.deliver_txs) == 1 and resp.deliver_txs[0].is_ok()


def test_validate_block_rejects_wrong_app_hash(chain):
    pv, state, executor, *_ = chain
    proposer = state.validators.get_proposer().address
    block, parts = state.make_block(1, [b"a=b"], Commit(0, 0, BlockID(), []), [], proposer)
    block.header.app_hash = b"\x01" * 8
    block.header.data_hash = b""
    block.fill_header()
    with pytest.raises(ValueError, match="AppHash"):
        executor.validate_block(state, block)


def test_block_store_prune(chain):
    pv, state, executor, state_store, block_store, app = chain
    last_commit = Commit(0, 0, BlockID(), [])
    for h in range(1, 6):
        proposer = state.validators.get_proposer().address
        block, parts = state.make_block(h, [], last_commit, [], proposer)
        bid = BlockID(block.hash(), parts.header())
        state, _ = executor.apply_block(state, bid, block)
        seen = make_commit_for(state_store.load() or state, pv, block, parts)
        # note: state already advanced; sign with the original set (single val)
        block_store.save_block(block, parts, seen)
        last_commit = seen
    assert block_store.prune_blocks(4) == 3
    assert block_store.base() == 4
    assert block_store.load_block(2) is None
    assert block_store.load_block(5) is not None


def test_kvstore_validator_update_tx(chain):
    pv, state, executor, state_store, block_store, app = chain
    newpv = MockPV(crypto.Ed25519PrivKey.generate(b"\x22" * 32))
    pub_hex = newpv.get_pub_key().bytes().hex()
    proposer = state.validators.get_proposer().address
    tx = f"val:{pub_hex}!7".encode()
    block, parts = state.make_block(1, [tx], Commit(0, 0, BlockID(), []), [], proposer)
    bid = BlockID(block.hash(), parts.header())
    new_state, _ = executor.apply_block(state, bid, block)
    # validator set now has 2 members at the height after next
    assert new_state.next_validators.size() == 2
    assert new_state.validators.size() == 1


def _mk_pointer_valset(n=5, seed=3, base_power=10):
    import numpy as np
    import pytest

    pytest.importorskip("cryptography", reason="needs the optional 'cryptography' package (absent in slim containers)")
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PrivateKey,
    )

    from tendermint_tpu.types import Validator, ValidatorSet

    rng = np.random.default_rng(seed)
    vals = []
    for i in range(n):
        sk = Ed25519PrivateKey.from_private_bytes(
            bytes(rng.integers(0, 256, 32, dtype=np.uint8)))
        pub = crypto.Ed25519PubKey(sk.public_key().public_bytes_raw())
        vals.append(Validator(pub.address(), pub, base_power + i))
    return ValidatorSet(vals)


def test_validators_change_pointer_dedup():
    """(store.go:289 saveValidatorsInfo / :249 loadValidators) unchanged
    heights persist only a pointer; loads follow it and roll proposer
    priorities forward, so the loaded set matches what a per-height full
    write would have stored."""
    import json

    from tendermint_tpu.state.store import _validators_key

    vs = _mk_pointer_valset()
    ss = StateStore(MemDB())
    ss._save_validators(4, vs)  # change height: full set
    for h in range(5, 10):      # unchanged heights: pointer only
        rolled = vs.copy_increment_proposer_priority(h - 4)
        ss._save_validators(h, rolled, last_changed=4)
        raw = json.loads(ss._db.get(_validators_key(h)).decode())
        assert "set" not in raw and raw["last_changed"] == 4

    for h in range(4, 10):
        want = vs.copy_increment_proposer_priority(h - 4) if h > 4 else vs
        got = ss.load_validators(h)
        assert got is not None
        assert [v.address for v in got.validators] == \
            [v.address for v in want.validators]
        assert [v.proposer_priority for v in got.validators] == \
            [v.proposer_priority for v in want.validators]
        assert got.get_proposer().address == want.get_proposer().address
    # a dangling pointer (pruned target) degrades to None, not a crash
    ss._db.set(_validators_key(11), json.dumps({"last_changed": 2}).encode())
    assert ss.load_validators(11) is None


def test_pointer_to_pointer_is_materialized():
    """Rollback can rewrite change heights so a save's natural pointer
    target is itself a pointer — the save must materialize a full set
    instead of writing an unresolvable chain (round-5 review finding)."""
    import json

    from tendermint_tpu.state.store import _validators_key

    vs = _mk_pointer_valset(seed=8)
    ss = StateStore(MemDB())
    ss._save_validators(2, vs)                     # full at 2
    ss._save_validators(5, vs, last_changed=2)     # pointer 5 -> 2
    # a later save claims last_changed=5, but 5 is a pointer: materialize
    rolled = vs.copy_increment_proposer_priority(4)
    ss._save_validators(6, rolled, last_changed=5)
    raw = json.loads(ss._db.get(_validators_key(6)).decode())
    assert "set" in raw
    got = ss.load_validators(6)
    assert [v.proposer_priority for v in got.validators] == \
        [v.proposer_priority for v in rolled.validators]


def test_prune_keeps_validator_checkpoint():
    """Pruning below a pointer's change height must not orphan it: a full
    checkpoint materializes at the retain height and later pointers clamp
    to it (store.go lastStoredHeightFor semantics)."""
    from tendermint_tpu.state.store import _validators_key

    vs = _mk_pointer_valset(n=4, seed=9, base_power=7)
    ss = StateStore(MemDB())
    ss._save_validators(2, vs)  # change height
    for h in range(3, 12):
        ss._save_validators(h, vs, last_changed=2)

    expect_at_8 = ss.load_validators(8)
    ss.prune_states(6)  # drops heights < 6, incl. the full record at 2

    assert ss._db.get(_validators_key(2)) is None
    # heights >= 6 still resolve, through the checkpoint at 6
    got = ss.load_validators(8)
    assert got is not None
    assert [v.proposer_priority for v in got.validators] == \
        [v.proposer_priority for v in expect_at_8.validators]
    assert got.get_proposer().address == expect_at_8.get_proposer().address


def test_saves_after_prune_stay_pointers_via_checkpoint():
    """After pruning drops a change-height record, later saves clamp their
    pointer to the checkpoint instead of permanently materializing full
    sets (round-5 review finding: the per-block encode cost must not come
    back after the first prune)."""
    import json

    from tendermint_tpu.state.store import _validators_key

    vs = _mk_pointer_valset(seed=12)
    ss = StateStore(MemDB())
    ss._save_validators(2, vs)
    for h in range(3, 8):
        ss._save_validators(h, vs.copy_increment_proposer_priority(h - 2),
                            last_changed=2)
    ss.prune_states(6)  # change-height record at 2 is gone; checkpoint at 6

    for h in range(8, 12):
        rolled = vs.copy_increment_proposer_priority(h - 2)
        ss._save_validators(h, rolled, last_changed=2)
        raw = json.loads(ss._db.get(_validators_key(h)).decode())
        assert "set" not in raw and raw["last_changed"] == 6, raw
        got = ss.load_validators(h)
        assert [v.proposer_priority for v in got.validators] == \
            [v.proposer_priority for v in rolled.validators]


def test_resave_at_checkpoint_height_keeps_full_set():
    """A re-save AT the checkpoint height with a stale change height
    (rollback/crash-replay) must not clamp into a self-pointer that
    overwrites the checkpoint's materialized full set (round-5 review
    finding — reproduced as load_validators returning None forever)."""
    import json

    from tendermint_tpu.state.store import _validators_key

    vs = _mk_pointer_valset(seed=21)
    ss = StateStore(MemDB())
    ss._save_validators(2, vs)
    for h in range(3, 8):
        ss._save_validators(h, vs.copy_increment_proposer_priority(h - 2),
                            last_changed=2)
    ss.prune_states(6)

    # replay re-saves height 6 claiming the pruned change height
    rolled6 = vs.copy_increment_proposer_priority(4)
    ss._save_validators(6, rolled6, last_changed=2)
    raw = json.loads(ss._db.get(_validators_key(6)).decode())
    assert "set" in raw, "checkpoint full set must survive the re-save"
    for h in (6, 7):
        got = ss.load_validators(h)
        assert got is not None
    # and a save BELOW the checkpoint (rollback past it) materializes
    ss._save_validators(5, vs.copy_increment_proposer_priority(3),
                        last_changed=2)
    raw5 = json.loads(ss._db.get(_validators_key(5)).decode())
    assert "set" in raw5
    assert ss.load_validators(5) is not None


def test_materialization_does_not_mask_prune_floor():
    """Round-5 review repro: change@84, pointers 85+, prune@95, then
    interval materialization advances past a retained height — loads for
    retained heights must keep resolving through the prune floor's full
    record (the materialization marker must never imply data loss)."""
    from tendermint_tpu.state import store as st

    vs = _mk_pointer_valset(seed=33)
    ss = StateStore(MemDB())
    ss._save_validators(84, vs)
    for h in range(85, 100):
        ss._save_validators(h, vs.copy_increment_proposer_priority(h - 84),
                            last_changed=84)
    ss.prune_states(95)
    # keep saving; force an interval materialization past height 97
    for h in range(100, 100 + st._VALS_MATERIALIZE_INTERVAL + 2):
        ss._save_validators(h, vs.copy_increment_proposer_priority(h - 84),
                            last_changed=84)
    assert ss._db.get(st._VALS_MATERIALIZED_KEY) is not None
    # retained heights below the materialization point still load
    for h in (95, 97, 99, 100):
        got = ss.load_validators(h)
        assert got is not None, f"height {h} unloadable"
        want = vs.copy_increment_proposer_priority(h - 84)
        assert [v.proposer_priority for v in got.validators] == \
            [v.proposer_priority for v in want.validators]
    # and pruned heights are honestly gone
    assert ss.load_validators(90) is None


def _mk_host_valset(n=4, power=10, seed=0x40):
    """Like _mk_pointer_valset but built on the host crypto backend only —
    runs in containers without the `cryptography` package."""
    from tendermint_tpu.types import Validator, ValidatorSet

    privs = [crypto.Ed25519PrivKey.generate(bytes([seed + i]) * 32)
             for i in range(n)]
    return ValidatorSet([Validator(p.pub_key().address(), p.pub_key(), power)
                         for p in privs])


def test_prune_checkpoint_written_only_after_full_record_confirmed():
    """Regression (ISSUE 2 satellite): prune_states must not advance the
    validator checkpoint when materializing the retain-height record fails —
    a checkpoint floor pointing at a non-full record makes every retained
    height unloadable."""
    import json

    from tendermint_tpu.state import store as st

    vs = _mk_host_valset()
    ss = StateStore(MemDB())
    ss._save_validators(2, vs)
    for h in range(3, 10):
        ss._save_validators(h, vs.copy_increment_proposer_priority(h - 2),
                            last_changed=2)
    # sabotage: the pointer target vanishes (interrupted earlier prune), so
    # materialization at the retain height cannot succeed
    ss._db.delete(st._validators_key(2))
    ss._full_record_cache = None
    ss.prune_states(6)
    assert ss._db.get(st._VALS_CHECKPOINT_KEY) is None, \
        "checkpoint advanced over a dangling pointer"
    # the happy path still writes it
    ss2 = StateStore(MemDB())
    ss2._save_validators(2, vs)
    for h in range(3, 10):
        ss2._save_validators(h, vs.copy_increment_proposer_priority(h - 2),
                             last_changed=2)
    ss2.prune_states(6)
    assert ss2._db.get(st._VALS_CHECKPOINT_KEY) == b"6"
    raw = json.loads(ss2._db.get(st._validators_key(6)).decode())
    assert "set" in raw


def test_load_validators_falls_back_to_declared_change_height():
    """Regression (ISSUE 2 satellite): when the checkpoint/materialization
    marker resolves a pointer onto a height that holds NO full record (stale
    marker, interrupted write), load_validators must fall back to the
    pointer's own declared last_changed instead of reporting the height
    unloadable."""
    import json

    from tendermint_tpu.state import store as st

    vs = _mk_host_valset(seed=0x50)
    ss = StateStore(MemDB())
    ss._save_validators(2, vs)  # the only full record
    ss._db.set(st._validators_key(9),
               json.dumps({"last_changed": 2}).encode())
    # stale marker: claims a materialized record at 7 that never landed
    ss._db.set(st._VALS_MATERIALIZED_KEY, b"7")
    got = ss.load_validators(9)
    assert got is not None, "stale marker made a retained height unloadable"
    want = vs.copy_increment_proposer_priority(7)
    assert [v.proposer_priority for v in got.validators] == \
        [v.proposer_priority for v in want.validators]
    # same through a stale checkpoint
    ss._db.set(st._VALS_CHECKPOINT_KEY, b"8")
    got = ss.load_validators(9)
    assert got is not None


def test_full_record_cache_serves_pristine_copies():
    """The one-slot decode cache must hand out independent copies: a caller
    mutating its loaded set (priority rolls) must not leak into later
    loads."""
    vs = _mk_host_valset(seed=0x60)
    ss = StateStore(MemDB())
    ss._save_validators(2, vs)
    for h in range(3, 8):
        ss._save_validators(h, vs.copy_increment_proposer_priority(h - 2),
                            last_changed=2)
    a = ss.load_validators(5)
    a.increment_proposer_priority(10)  # caller-side mutation
    b = ss.load_validators(5)
    want = vs.copy_increment_proposer_priority(3)
    assert [v.proposer_priority for v in b.validators] == \
        [v.proposer_priority for v in want.validators]


def test_buffered_db_read_through_and_single_flush():
    from tendermint_tpu.libs.db import BufferedDB

    base = MemDB()
    base.set(b"a", b"1")
    base.set(b"b", b"2")
    buf = BufferedDB(base)
    buf.set(b"c", b"3")
    buf.delete(b"a")
    buf.set(b"b", b"22")
    # read-through sees staged writes, base does not
    assert buf.get(b"c") == b"3" and buf.get(b"a") is None
    assert buf.get(b"b") == b"22"
    assert base.get(b"c") is None and base.get(b"a") == b"1"
    assert [k for k, _ in buf.iterate()] == [b"b", b"c"]
    assert [v for _, v in buf.iterate()] == [b"22", b"3"]
    buf.flush()
    assert base.get(b"c") == b"3" and base.get(b"a") is None
    assert base.get(b"b") == b"22"
    assert buf.pending() == 0


def test_state_store_window_batch_reads_own_writes():
    """Pointer records written inside a window batch must be visible to
    loads later in the same window (apply_block loads height-1's set)."""
    vs = _mk_host_valset(seed=0x70)
    ss = StateStore(MemDB())
    with ss.window_batch():
        ss._save_validators(2, vs)
        for h in range(3, 6):
            ss._save_validators(h, vs.copy_increment_proposer_priority(h - 2),
                                last_changed=2)
        got = ss.load_validators(4)
        assert got is not None
        # reentrancy: a nested scope joins the outer batch
        with ss.window_batch():
            assert ss.load_validators(5) is not None
    # flushed: visible without the buffer
    assert ss.load_validators(5) is not None


def _churn_valset(round_: int, n: int = 4):
    """A validator set for rotation round `round_` built on the repo's own
    ed25519 (no optional deps): a sliding window over a deterministic key
    pool, so every round the composition really changes."""
    from tendermint_tpu.types import Validator, ValidatorSet

    vals = []
    for i in range(round_, round_ + n):
        pub = crypto.Ed25519PrivKey.generate(
            bytes([0x30 + (i % 64)]) * 32).pub_key()
        vals.append(Validator(pub.address(), pub, 10))
    return ValidatorSet(vals)


def test_prune_states_under_continuous_validator_churn():
    """The churn acceptance path for the prune-checkpointed validator
    storage: the set rotates EVERY height for 60 heights while
    prune_states runs concurrently (per save, like a retention-configured
    node), and load_validators must resolve the CORRECT composition at
    every retained height — change pointers, interval materialization,
    prune-floor checkpoints and the rotation all interleaving."""
    ss = StateStore(MemDB())
    retain_window = 9
    expected = {}  # height -> set of validator addresses
    for h in range(1, 61):
        vs = _churn_valset(h)
        ss._save_validators(h, vs, last_changed=h)  # rotates every height
        expected[h] = {v.address for v in vs.validators}
        if h > retain_window:
            ss.prune_states(h - retain_window)
        floor = max(1, h - retain_window)
        for rh in range(floor, h + 1):
            got = ss.load_validators(rh)
            assert got is not None, \
                f"retained height {rh} unloadable at tip {h}"
            assert {v.address for v in got.validators} == expected[rh], \
                f"wrong composition at {rh} (tip {h})"
        # pruned heights are really gone (no silent unbounded growth)
        if floor > 2:
            assert ss.load_validators(floor - 2) is None


def test_prune_states_churn_with_pointer_runs():
    """Same stress with CHANGE-POINTER runs between rotations (the set
    holds still for a few heights, then flips): pointers must keep
    resolving across prune floors that land mid-run."""
    ss = StateStore(MemDB())
    retain_window = 7
    expected = {}
    change_h, current = 1, _churn_valset(0)
    for h in range(1, 50):
        if h % 5 == 0:  # rotation every 5th height
            current, change_h = _churn_valset(h), h
        rolled = current.copy_increment_proposer_priority(h - change_h) \
            if h > change_h else current
        ss._save_validators(h, rolled, last_changed=change_h)
        expected[h] = {v.address for v in current.validators}
        if h > retain_window:
            ss.prune_states(h - retain_window)
        for rh in range(max(1, h - retain_window), h + 1):
            got = ss.load_validators(rh)
            assert got is not None, f"height {rh} unloadable at tip {h}"
            assert {v.address for v in got.validators} == expected[rh]
