"""Vote-batcher liveness: a device flush that stalls (cold XLA compile on a
fresh node, relay hang) must NOT wedge consensus — the batch re-verifies on
the host within device_timeout_s and later flushes stay host-side until the
device call completes. Found via a SIGUSR1 stack dump of a localnet node
stuck at one height with every _preverify_and_forward task pending."""

import asyncio
import threading
import time

import numpy as np
import pytest

pytest.importorskip("cryptography", reason="needs the optional 'cryptography' package (absent in slim containers)")

from cryptography.hazmat.primitives.asymmetric.ed25519 import Ed25519PrivateKey

from tendermint_tpu import crypto
from tendermint_tpu.crypto import vote_batcher


def _mk_votes(n, seed=5):
    rng = np.random.default_rng(seed)
    out = []
    for i in range(n):
        sk = Ed25519PrivateKey.from_private_bytes(
            bytes(rng.integers(0, 256, 32, dtype=np.uint8)))
        pub = crypto.Ed25519PubKey(sk.public_key().public_bytes_raw())
        msg = b"vote-%d" % i
        out.append((pub, msg, sk.sign(msg)))
    return out


def test_stalled_device_flush_falls_back_to_host(monkeypatch):
    release = threading.Event()
    calls = []

    def stuck_kernel(pks, msgs, sigs, chunk=2048):
        calls.append(len(pks))
        release.wait(30)  # simulates a cold compile: far beyond the timeout
        return np.ones(len(pks), dtype=bool)

    import tendermint_tpu.crypto.ed25519_jax as ed_jax

    monkeypatch.setattr(ed_jax, "batch_verify_stream", stuck_kernel)

    async def run():
        bv = vote_batcher.BatchVoteVerifier(
            min_device_batch=4, deadline_s=0.005, device_timeout_s=0.3)
        votes = _mk_votes(8)
        t0 = time.monotonic()
        results = await asyncio.gather(
            *(bv.preverify(p, m, s) for p, m, s in votes))
        elapsed = time.monotonic() - t0
        assert all(results)
        assert elapsed < 5, f"preverify blocked {elapsed:.1f}s on the stall"
        assert bv.stats["device_timeouts"] == 1
        assert bv.stats["host_sigs"] == 8
        assert bv._device_warming  # device path parked until the call ends

        # while warming, new flushes go straight to host (no second stall)
        more = _mk_votes(8, seed=6)
        results = await asyncio.gather(
            *(bv.preverify(p, m, s) for p, m, s in more))
        assert all(results) and len(calls) == 1

        # device call completes -> the device path re-arms
        release.set()
        for _ in range(100):
            if not bv._device_warming:
                break
            await asyncio.sleep(0.05)
        assert not bv._device_warming

    asyncio.run(run())


def test_fast_device_flush_still_rides_device(monkeypatch):
    def instant_kernel(pks, msgs, sigs, chunk=2048):
        from tendermint_tpu.crypto import ed25519 as host

        return np.array([host.verify(p, m, s)
                         for p, m, s in zip(pks, msgs, sigs)])

    import tendermint_tpu.crypto.ed25519_jax as ed_jax

    monkeypatch.setattr(ed_jax, "batch_verify_stream", instant_kernel)

    async def run():
        bv = vote_batcher.BatchVoteVerifier(
            min_device_batch=4, deadline_s=0.005, device_timeout_s=3.0)
        votes = _mk_votes(6, seed=9)
        bad = list(votes[0])
        bad[2] = bytes(64)  # one invalid signature: verdict must be False
        votes[0] = tuple(bad)
        results = await asyncio.gather(
            *(bv.preverify(p, m, s) for p, m, s in votes))
        assert results[0] is False or results[0] == False  # noqa: E712
        assert all(results[1:])
        assert bv.stats["device_batches"] == 1
        assert bv.stats["device_timeouts"] == 0

    asyncio.run(run())
