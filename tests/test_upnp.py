"""UPnP NAT traversal (reference p2p/upnp) against an in-proc fake IGD:
a unicast SSDP responder + an HTTP server serving the rootDesc XML and a
SOAP control endpoint. Real gateways don't exist in CI; the fake speaks
the same three actions the reference uses."""

import socket
import threading
from http.server import BaseHTTPRequestHandler, HTTPServer

import pytest

from tendermint_tpu.p2p import upnp

DESC_XML = """<?xml version="1.0"?>
<root xmlns="urn:schemas-upnp-org:device-1-0">
 <device>
  <deviceType>urn:schemas-upnp-org:device:InternetGatewayDevice:1</deviceType>
  <deviceList><device>
   <deviceType>urn:schemas-upnp-org:device:WANDevice:1</deviceType>
   <deviceList><device>
    <deviceType>urn:schemas-upnp-org:device:WANConnectionDevice:1</deviceType>
    <serviceList><service>
     <serviceType>urn:schemas-upnp-org:service:WANIPConnection:1</serviceType>
     <controlURL>/ctl/IPConn</controlURL>
    </service></serviceList>
   </device></deviceList>
  </device></deviceList>
 </device>
</root>"""


class FakeIGD:
    def __init__(self):
        self.mappings = {}
        self.requests = []

        igd = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                if self.path == "/rootDesc.xml":
                    body = DESC_XML.encode()
                    self.send_response(200)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                else:
                    self.send_error(404)

            def do_POST(self):
                body = self.rfile.read(
                    int(self.headers.get("Content-Length", 0))).decode()
                action = self.headers.get("SOAPAction", "").strip('"')
                igd.requests.append((action, body))
                name = action.rsplit("#", 1)[-1]
                if name == "GetExternalIPAddress":
                    inner = ("<NewExternalIPAddress>203.0.113.7"
                             "</NewExternalIPAddress>")
                elif name == "AddPortMapping":
                    import re

                    port = re.search(r"<NewExternalPort>(\d+)", body).group(1)
                    proto = re.search(r"<NewProtocol>(\w+)", body).group(1)
                    igd.mappings[(proto, int(port))] = body
                    inner = ""
                elif name == "DeletePortMapping":
                    import re

                    port = re.search(r"<NewExternalPort>(\d+)", body).group(1)
                    proto = re.search(r"<NewProtocol>(\w+)", body).group(1)
                    if (proto, int(port)) not in igd.mappings:
                        self.send_error(500)
                        return
                    del igd.mappings[(proto, int(port))]
                    inner = ""
                else:
                    self.send_error(500)
                    return
                resp = (f'<?xml version="1.0"?><s:Envelope xmlns:s='
                        f'"http://schemas.xmlsoap.org/soap/envelope/">'
                        f'<s:Body><u:{name}Response xmlns:u='
                        f'"urn:schemas-upnp-org:service:WANIPConnection:1">'
                        f"{inner}</u:{name}Response>"
                        f"</s:Body></s:Envelope>").encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(resp)))
                self.end_headers()
                self.wfile.write(resp)

        self.http = HTTPServer(("127.0.0.1", 0), Handler)
        self.http_port = self.http.server_address[1]
        threading.Thread(target=self.http.serve_forever, daemon=True).start()

        # unicast SSDP responder standing in for the multicast group
        self.ssdp = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self.ssdp.bind(("127.0.0.1", 0))
        self.ssdp_addr = self.ssdp.getsockname()

        def ssdp_loop():
            while True:
                try:
                    data, peer = self.ssdp.recvfrom(4096)
                except OSError:
                    return
                if b"M-SEARCH" in data:
                    resp = ("HTTP/1.1 200 OK\r\n"
                            "ST: urn:schemas-upnp-org:device:"
                            "InternetGatewayDevice:1\r\n"
                            f"LOCATION: http://127.0.0.1:{self.http_port}"
                            "/rootDesc.xml\r\n\r\n").encode()
                    self.ssdp.sendto(resp, peer)

        threading.Thread(target=ssdp_loop, daemon=True).start()

    def stop(self):
        self.http.shutdown()
        self.ssdp.close()


@pytest.fixture
def igd():
    f = FakeIGD()
    yield f
    f.stop()


def test_discover_and_map(igd):
    nat = upnp.discover(timeout=2.0, ssdp_addr=igd.ssdp_addr)
    assert nat.service_type.endswith("WANIPConnection:1")
    assert nat.get_external_address() == "203.0.113.7"
    nat.add_port_mapping("tcp", 26656, 26656, "tmtpu", lease_seconds=0)
    assert ("TCP", 26656) in igd.mappings
    assert "<NewInternalClient>127.0.0.1" in igd.mappings[("TCP", 26656)]
    nat.delete_port_mapping("tcp", 26656)
    assert not igd.mappings
    # deleting a mapping that doesn't exist surfaces as UPnPError
    with pytest.raises(upnp.UPnPError):
        nat.delete_port_mapping("tcp", 26656)


def test_probe_capabilities(igd):
    caps = upnp.probe(int_port=26656, ext_port=26700, timeout=2.0,
                      ssdp_addr=igd.ssdp_addr)
    assert caps == {"external_ip": "203.0.113.7", "port_mapping": True}
    assert not igd.mappings  # probe unmaps after itself


def test_discover_times_out_without_gateway():
    lonely = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    lonely.bind(("127.0.0.1", 0))
    addr = lonely.getsockname()
    lonely.close()  # nobody listening
    with pytest.raises(upnp.UPnPError, match="no UPnP gateway"):
        upnp.discover(timeout=0.3, ssdp_addr=addr, attempts=1)
