"""tools/bench_compare.py: the bench-regression gate — exit codes,
per-metric thresholds, lower-is-better latency gating, driver-format
parsing, and the real BENCH_r*.json history staying machine-checkable."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(__file__))
TOOL = os.path.join(REPO, "tools", "bench_compare.py")


def _mod():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import bench_compare

        return bench_compare
    finally:
        sys.path.pop(0)


def _run(*args):
    return subprocess.run([sys.executable, TOOL, *args],
                          capture_output=True, text=True, timeout=60)


def _write(path, metrics):
    with open(path, "w") as f:
        for m, (v, unit) in metrics.items():
            f.write(json.dumps({"metric": m, "value": v, "unit": unit,
                                "vs_baseline": 1.0}) + "\n")


def test_self_test_passes():
    res = _run("--self-test")
    assert res.returncode == 0, res.stdout + res.stderr
    assert "self-test OK" in res.stdout


def test_real_history_trips_on_pack_share_creep():
    """r04 -> r05 improved every throughput metric BUT let the flagship's
    packing share creep 7% -> 11.1% (+59%) with nothing watching. With the
    pack-share ratio gated lower-is-better, the checked-in history itself
    must now trip exit 1 on exactly that metric — and on nothing else."""
    r04 = os.path.join(REPO, "BENCH_r04.json")
    r05 = os.path.join(REPO, "BENCH_r05.json")
    res = _run(r04, r05)
    assert res.returncode == 1, res.stdout + res.stderr
    fail = next(l for l in res.stdout.splitlines() if l.startswith("FAIL"))
    assert "verify_commit_10k_breakdown_pack_share" in fail
    assert fail.startswith("FAIL: 1 regression(s)"), fail
    # loosening that one metric's threshold restores a clean r04 -> r05
    res2 = _run("--threshold",
                "verify_commit_10k_breakdown_pack_share=0.6", r04, r05)
    assert res2.returncode == 0, res2.stdout
    assert "OK: no regressions" in res2.stdout
    bc = _mod()
    run = bc.load_bench(r05)
    assert run["verify_commit_10k_sigs_per_sec"]["value"] > 150000


def test_degraded_flagship_trips_gate(tmp_path):
    bc = _mod()
    r05 = bc.load_bench(os.path.join(REPO, "BENCH_r05.json"))
    degraded = dict(r05)
    rec = dict(degraded["verify_commit_10k_sigs_per_sec"])
    rec["value"] = rec["value"] * 0.5  # 50% < the 30% default threshold
    degraded["verify_commit_10k_sigs_per_sec"] = rec
    new = str(tmp_path / "new.json")
    with open(new, "w") as f:
        for line in degraded.values():
            f.write(json.dumps(line) + "\n")
    res = _run(os.path.join(REPO, "BENCH_r05.json"), new)
    assert res.returncode == 1, res.stdout
    assert "REGRESSION" in res.stdout
    assert "verify_commit_10k_sigs_per_sec" in res.stdout
    # loosening that one metric's threshold un-trips it
    res2 = _run("--threshold", "verify_commit_10k_sigs_per_sec=0.6",
                os.path.join(REPO, "BENCH_r05.json"), new)
    assert res2.returncode == 0, res2.stdout


def test_latency_gated_lower_is_better(tmp_path):
    old, new = str(tmp_path / "old.json"), str(tmp_path / "new.json")
    _write(old, {"localnet_4node_tx_commit_latency_p50": (1.0, "s")})
    _write(new, {"localnet_4node_tx_commit_latency_p50": (1.6, "s")})
    assert _run(old, new).returncode == 1
    _write(new, {"localnet_4node_tx_commit_latency_p50": (0.5, "s")})
    res = _run(old, new)
    assert res.returncode == 0
    assert "improved" in res.stdout


def test_missing_gated_metric_fails(tmp_path):
    old, new = str(tmp_path / "old.json"), str(tmp_path / "new.json")
    _write(old, {"verify_commit_10k_sigs_per_sec": (100.0, "sigs/s"),
                 "some_breakdown_share": (0.5, "ratio")})
    _write(new, {"some_breakdown_share": (0.9, "ratio")})
    res = _run(old, new)
    assert res.returncode == 1
    assert "MISSING" in res.stdout


def test_trajectory_table_over_history():
    files = [os.path.join(REPO, f"BENCH_r0{i}.json") for i in (3, 4, 5)]
    # the pack-share gate trips on the raw r04 -> r05 pair (see above);
    # loosened here so this test isolates the trajectory rendering
    res = _run("--threshold",
               "verify_commit_10k_breakdown_pack_share=0.6", *files)
    assert res.returncode == 0, res.stdout + res.stderr
    # all three runs' flagship values appear in one row
    line = next(l for l in res.stdout.splitlines()
                if l.startswith("verify_commit_10k_sigs_per_sec "))
    assert "157880" in line and "47384" in line
    # the gated pack share joined the trajectory table
    assert any(l.startswith("verify_commit_10k_breakdown_pack_share")
               for l in res.stdout.splitlines())


def test_parse_error_exits_2(tmp_path):
    bad = str(tmp_path / "bad.json")
    with open(bad, "w") as f:
        f.write("not a bench file\n")
    res = _run(bad, bad)
    assert res.returncode == 2
    assert "no bench metric lines" in res.stderr


def test_json_output(tmp_path):
    old, new = str(tmp_path / "old.json"), str(tmp_path / "new.json")
    _write(old, {"verify_commit_10k_sigs_per_sec": (100.0, "sigs/s")})
    _write(new, {"verify_commit_10k_sigs_per_sec": (10.0, "sigs/s")})
    res = _run("--json", old, new)
    assert res.returncode == 1
    doc = json.loads(res.stdout)
    assert doc["regressions"] == 1
    assert doc["rows"][0]["status"] == "regressed"
