"""The churn-tolerant network plane: live add/remove of in-proc nodes,
departed-node exclusion from the redial loop, sparse topologies, and the
churn orchestrator's deterministic plan (tools/churn.py).

The heavyweight end-to-end churn scenarios (statesync joins under load,
validator rotation across prune boundaries, 32-node chaos) live in the
chaos matrix (churn.flap / churn.rotate / churn.partition32 /
churn.corrupt32) and the bench churn config; this file keeps the tier-1
coverage: membership mechanics on real consensus nets at small N, and the
pure planning/graph functions at every N.
"""

import asyncio
import os
import sys

import pytest

from tendermint_tpu.p2p import InProcNetwork
from tendermint_tpu.p2p.inproc import sparse_edges

from test_consensus_net import make_net, wait_all_height

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))
import churn  # noqa: E402  (tools/churn.py — plan/graph functions)


# -- sparse_edges: the shared persistent-peer graph ---------------------------

def test_sparse_edges_deterministic_connected_bounded():
    ids = [f"n{i:02d}" for i in range(32)]
    e1 = sparse_edges(ids, degree=4, seed=7)
    assert e1 == sparse_edges(ids, degree=4, seed=7)
    assert e1 != sparse_edges(ids, degree=4, seed=8)
    # connected (ring by construction) and near-target average degree
    adj = {}
    for a, b in e1:
        adj.setdefault(a, set()).add(b)
        adj.setdefault(b, set()).add(a)
    seen, stack = {ids[0]}, [ids[0]]
    while stack:
        for nxt in adj[stack.pop()]:
            if nxt not in seen:
                seen.add(nxt)
                stack.append(nxt)
    assert seen == set(ids)
    avg = sum(len(v) for v in adj.values()) / len(adj)
    assert 3.0 <= avg <= 5.0, avg
    # shuffled input produces the same canonical edge list
    import random

    shuffled = list(ids)
    random.Random(1).shuffle(shuffled)
    assert sparse_edges(shuffled, degree=4, seed=7) == e1


def test_sparse_edges_small_inputs():
    assert sparse_edges([], degree=3) == []
    assert sparse_edges(["solo"], degree=3) == []
    assert sparse_edges(["a", "b"], degree=3) == [("a", "b")]


# -- live membership on a real consensus net ----------------------------------

def test_remove_node_clean_leave_and_rejoin():
    """A departed node: links drained, excluded from reconnect_missing,
    survivors keep committing; a later add_node re-admits it and it
    catches back up to the live net."""
    async def run():
        nodes = make_net(4)
        net = InProcNetwork()
        for nd in nodes:
            net.add_switch(nd.switch)
        for nd in nodes:
            await nd.start()
        await net.connect_all()
        try:
            await wait_all_height(nodes, 2)
            # clean leave of node3 (3/4 voting power keeps quorum)
            severed = await net.remove_node("node3")
            assert severed == 3
            assert "node3" in net.departed
            assert "node3" not in net.switches
            assert not any("node3" in k for k in net.links)
            # the redial loop must NOT resurrect it
            assert await net.reconnect_missing() == 0
            survivors = nodes[:3]
            for nd in survivors:
                assert "node3" not in nd.switch.peers
            h0 = min(nd.cs.state.last_block_height for nd in survivors)
            await wait_all_height(survivors, h0 + 2)
            # re-join: add_node wires it back to everyone and it catches up
            await net.add_node(nodes[3].switch)
            assert "node3" not in net.departed
            target = max(nd.cs.state.last_block_height for nd in survivors) + 2
            await wait_all_height(nodes, target, timeout=60)
        finally:
            for nd in nodes:
                await nd.stop()
        common = min(nd.cs.state.last_block_height for nd in nodes) - 1
        hashes = {nd.block_store.load_block_meta(common).header.hash()
                  for nd in nodes}
        assert len(hashes) == 1

    asyncio.run(run())


def test_remove_node_preserves_surviving_link_policies():
    """A leave must not disturb surviving links' chaos policies (their
    seeded RNG streams carry the replay schedule)."""
    async def run():
        nodes = make_net(4)
        net = InProcNetwork()
        for nd in nodes:
            net.add_switch(nd.switch)
        for nd in nodes:
            await nd.start()
        await net.connect_all()
        try:
            net.set_loss(0.05, seed=3)
            pol = net.links[("node0", "node1")].policy
            await net.remove_node("node2")
            assert net.links[("node0", "node1")].policy is pol
            # departed node's policies are gone with its links
            assert not any("node2" in k for k in net.links)
        finally:
            for nd in nodes:
                await nd.stop()

    asyncio.run(run())


def test_reconnect_missing_still_heals_real_failures():
    """The departed-exclusion must not mask REAL link failures: a severed
    (not departed) pair is still redialed."""
    async def run():
        nodes = make_net(3)
        net = InProcNetwork()
        for nd in nodes:
            net.add_switch(nd.switch)
        for nd in nodes:
            await nd.start()
        await net.connect_all()
        try:
            # sever the way chaos does: one side drops the peer for an
            # error (the link registry entry survives, unlike disconnect())
            sw0 = net.switches["node0"]
            await sw0.stop_peer_for_error(sw0.peers["node1"], "test sever")
            assert not net.connected("node0", "node1")
            assert await net.reconnect_missing() == 1
            assert net.connected("node0", "node1")
        finally:
            for nd in nodes:
                await nd.stop()

    asyncio.run(run())


def test_sparse_topology_net_commits():
    """A 6-node ring+chords net (gossip must relay — no direct link
    between every pair) reaches consensus with identical hashes."""
    async def run():
        nodes = make_net(6)
        net = InProcNetwork()
        for nd in nodes:
            net.add_switch(nd.switch)
        for nd in nodes:
            await nd.start()
        pairs = await net.connect_topology("sparse", degree=2, seed=5)
        assert pairs < 15, "sparse graph degenerated into a full mesh"
        try:
            await wait_all_height(nodes, 3, timeout=90)
        finally:
            for nd in nodes:
                await nd.stop()
        hashes = {nd.block_store.load_block_meta(2).header.hash()
                  for nd in nodes}
        assert len(hashes) == 1

    asyncio.run(run())


def test_connect_topology_rejects_unknown():
    async def run():
        net = InProcNetwork()
        with pytest.raises(ValueError):
            await net.connect_topology("star")

    asyncio.run(run())


# -- the churn plan (pure) ----------------------------------------------------

def test_plan_churn_deterministic_and_quorum_safe():
    p1 = churn.plan_churn(11, 4, 8)
    assert p1 == churn.plan_churn(11, 4, 8)
    assert p1 != churn.plan_churn(12, 4, 8)
    vset = set(p1["compositions"][0])
    comp_i = 1
    for ev in p1["events"]:
        # a leave never names a sitting validator, and the anchor (val0,
        # the statesync donor) never rotates out
        assert ev.get("leave") not in vset
        if "rotate_in" in ev:
            assert ev["rotate_out"] != "val0"
            vset = set(p1["compositions"][comp_i])
            comp_i += 1
        assert ev["join"] == f"join{ev['interval']}"
    assert all(len(c) == churn.N_VALIDATORS for c in p1["compositions"])


def test_schedule_fingerprint_excludes_wallclock():
    rep = {"executed": [("leave", "full0"), ("join", "join0")],
           "compositions": [["val0"]], "plan": {"events": []},
           "elapsed_s": 9.9, "blocks_per_min": 14.2,
           "join_caughtup_s": {"join0": 3.3}}
    fp = churn.schedule_fingerprint(rep)
    assert set(fp) == {"executed", "compositions", "plan"}


# -- the full churn scenario (slow tier) --------------------------------------

@pytest.mark.slow
def test_churn_run_n8_with_rotation():
    """The acceptance scenario end to end: a seeded N=8 run — statesync
    join + clean leave per interval under open-loop load, validator
    rotation crossing prune boundaries — completes with all its internal
    invariants (survivor app-hash agreement, joiners caught up, retained
    heights resolvable, bounded book/scoreboard state)."""
    report = churn.run_churn(n_nodes=8, intervals=2, seed=1)
    assert report["rotations"] == 2
    assert set(report["join_caughtup_s"]) == {"join0", "join1"}
    assert report["height_final"] > report["height_initial"]
