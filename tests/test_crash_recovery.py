"""Crash-at-every-WAL-write recovery matrix (the reference's crashingWAL
harness, consensus/replay_test.go): kill the node at the Nth WAL write for
every N until the chain outruns the crash point, restart from the same
storage each time, and prove recovery — heights never regress, the chain
keeps growing, and the validator never double-signs (FilePV state file
enforced across every restart).
"""

import asyncio
import os

import pytest

from tendermint_tpu import crypto
from tendermint_tpu.abci.example.kvstore import KVStoreApplication
from tendermint_tpu.consensus import ConsensusState, WAL
from tendermint_tpu.consensus.config import test_consensus_config
from tendermint_tpu.consensus.replay import Handshaker, catchup_replay
from tendermint_tpu.libs.db import SQLiteDB
from tendermint_tpu.mempool import CListMempool
from tendermint_tpu.privval.file_pv import FilePV
from tendermint_tpu.proxy import AppConns, local_client_creator
from tendermint_tpu.state import BlockExecutor, StateStore, state_from_genesis
from tendermint_tpu.state.execution import EmptyEvidencePool
from tendermint_tpu.store import BlockStore
from tendermint_tpu.types import GenesisDoc, GenesisValidator
from tendermint_tpu.types.event_bus import EventBus

CHAIN = "crash-chain"
TARGET_HEIGHT = 3


class WALCrash(BaseException):
    """Simulated process death at a WAL write. BaseException so the consensus
    receive loop's defensive `except Exception` cannot swallow it — a real
    crash doesn't ask permission (same trick as KeyboardInterrupt)."""


class CrashingWAL(WAL):
    """(consensus/replay_test.go crashingWAL) dies at write number N."""

    def __init__(self, path: str, crash_at: int):
        super().__init__(path)
        self.crash_at = crash_at
        self.writes = 0

    def _maybe_crash(self) -> None:
        self.writes += 1
        if self.writes == self.crash_at:
            raise WALCrash(f"simulated crash at WAL write {self.crash_at}")

    def write_msg_info(self, *a, **k):
        self._maybe_crash()
        return super().write_msg_info(*a, **k)

    def write_timeout(self, *a, **k):
        self._maybe_crash()
        return super().write_timeout(*a, **k)

    def write_end_height(self, *a, **k):
        self._maybe_crash()
        return super().write_end_height(*a, **k)


def _boot(tmp_path, wal):
    """Assemble a node over PERSISTENT stores + pv sign-state file."""
    pv = FilePV.load(str(tmp_path / "pv_key.json"), str(tmp_path / "pv_state.json"))
    genesis = GenesisDoc(chain_id=CHAIN,
                         genesis_time_ns=1_700_000_000_000_000_000,
                         validators=[GenesisValidator(pv.get_pub_key(), 10)])
    app = KVStoreApplication()
    conns = AppConns(local_client_creator(app))
    conns.start()
    state_store = StateStore(SQLiteDB(str(tmp_path / "state.db")))
    block_store = BlockStore(SQLiteDB(str(tmp_path / "blocks.db")))
    state = state_store.load() or state_from_genesis(genesis)
    handshaker = Handshaker(state_store, state, block_store, genesis)
    state = handshaker.handshake(conns.consensus, conns.query)
    state_store.save(state)
    mempool = CListMempool(conns.mempool)
    bus = EventBus()
    bx = BlockExecutor(state_store, conns.consensus, mempool,
                       EmptyEvidencePool(), block_store, bus)
    cs = ConsensusState(test_consensus_config(), state, bx, block_store, wal=wal)
    cs.set_priv_validator(pv)
    cs.set_event_bus(bus)
    return cs


async def _run_until_crash_or_height(cs, target):
    """Drive the machine; return ('crashed'|'done', height)."""
    crash = {}
    orig = cs.receive_routine

    async def guarded():
        try:
            await orig()
        except WALCrash as e:
            crash["err"] = e

    cs.receive_routine = guarded
    await cs.start()
    try:
        for _ in range(600):
            if crash:
                return "crashed", cs.state.last_block_height
            if cs.state.last_block_height >= target:
                return "done", cs.state.last_block_height
            await asyncio.sleep(0.02)
        raise AssertionError(
            f"no progress and no crash (h={cs.state.last_block_height})")
    finally:
        await cs.stop()


def test_crash_at_every_wal_write(tmp_path):
    """For every WAL write position N: crash there, restart, recover."""
    FilePV.generate(str(tmp_path / "pv_key.json"),
                    str(tmp_path / "pv_state.json")).save()

    async def run():
        wal_path = str(tmp_path / "cs.wal")
        crash_at = 1
        last_height = 0
        crashes = 0
        while True:
            wal = CrashingWAL(wal_path, crash_at)
            # WAL catchup replay exactly like the node path
            cs = _boot(tmp_path, wal)
            catchup_replay(cs, cs.rs.height)
            status, height = await _run_until_crash_or_height(cs, TARGET_HEIGHT)
            assert height >= last_height, \
                f"height regressed after crash {crash_at}: {height} < {last_height}"
            last_height = height
            if status == "done":
                break
            crashes += 1
            crash_at += 1
            assert crash_at < 400, "crash matrix did not converge"
        # the matrix must actually have exercised crashes
        assert crashes >= 5, f"only {crashes} crash points before target height"
        assert last_height >= TARGET_HEIGHT

    asyncio.run(run())
