"""On-device differential suite: the batch verifier vs the host spec on the
REAL accelerator backend.

Run with ``TM_ON_DEVICE=1 python -m pytest tests/test_tpu_device.py -q``.
The default suite pins CPU (see conftest.py); these tests exist because the
round-1 kernel returned *wrong answers only on the TPU backend* (a roll-based
column build in field.mul miscompiled under fori_loop) while the CPU suite was
green. Byte-identical accept/reject vs the host spec
(tendermint_tpu/crypto/ed25519.py, mirroring reference
crypto/ed25519/ed25519.go:148-155) is the framework's core claim; it must be
proven per-backend, at many batch shapes, against adversarial inputs.
"""

import os

import numpy as np
import pytest

from tendermint_tpu.crypto import ed25519 as host
from tendermint_tpu.crypto.ed25519_jax import batch_verify

ON_DEVICE = os.environ.get("TM_ON_DEVICE") == "1"

pytestmark = pytest.mark.skipif(
    not ON_DEVICE, reason="set TM_ON_DEVICE=1 to run the on-device suite"
)


def _device_is_accelerator():
    import jax

    return jax.default_backend() != "cpu"


def _corpus(n, seed):
    """n (pk, msg, sig) tuples: ~60% valid, rest adversarial."""
    rng = np.random.default_rng(seed)
    pks, msgs, sigs = [], [], []
    for i in range(n):
        sd = rng.bytes(32)
        msg = rng.bytes(1 + int(rng.integers(0, 64)))
        pk = host.pubkey_from_seed(sd)
        sig = host.sign(sd + pk, msg)
        kind = i % 10
        if kind == 6:  # corrupted R
            sig = bytes([sig[0] ^ 1]) + sig[1:]
        elif kind == 7:  # corrupted s
            sig = sig[:32] + bytes([sig[32] ^ 1]) + sig[33:]
        elif kind == 8:  # non-canonical s (s + L)
            s = int.from_bytes(sig[32:], "little") + host.L
            sig = sig[:32] + s.to_bytes(32, "little")
        elif kind == 9:  # wrong message
            msg = msg + b"!"
        pks.append(pk)
        msgs.append(msg)
        sigs.append(sig)
    return pks, msgs, sigs


@pytest.mark.parametrize("n", [1, 16, 20, 127, 128, 129, 1024])
def test_device_matches_host_spec(n):
    assert _device_is_accelerator(), "suite must run on the accelerator backend"
    pks, msgs, sigs = _corpus(n, seed=n)
    got = np.asarray(batch_verify(pks, msgs, sigs))
    want = np.array(
        [host.verify(p, m, s) for p, m, s in zip(pks, msgs, sigs)], dtype=bool
    )
    mismatch = np.nonzero(got != want)[0]
    assert mismatch.size == 0, f"n={n}: device disagrees at indices {mismatch[:8]}"


def test_device_rejects_x0_sign1_and_noncanonical_y():
    assert _device_is_accelerator()
    bad_pks, msgs, sigs = [], [], []
    # x=0 with sign bit set (y=1 / y=p-1): must reject
    for y in (1, host.P - 1):
        bad_pks.append((y | 1 << 255).to_bytes(32, "little"))
    # y >= p encodings (non-canonical): must reject
    for y in (host.P, host.P + 1):
        bad_pks.append(y.to_bytes(32, "little"))
    s = 7
    sB = host._pt_mul(s, (host.B[0], host.B[1], 1, host.B[0] * host.B[1] % host.P))
    sig = host._pt_encode(sB) + s.to_bytes(32, "little")
    for _ in bad_pks:
        msgs.append(b"forged")
        sigs.append(sig)
    got = np.asarray(batch_verify(bad_pks, msgs, sigs))
    want = np.array(
        [host.verify(p, m, s_) for p, m, s_ in zip(bad_pks, msgs, sigs)], dtype=bool
    )
    assert not got.any()
    assert (got == want).all()


def test_device_field_mul_matches_bigint():
    """Differential field-level check on-device: random mul/freeze vs python ints."""
    assert _device_is_accelerator()
    from tendermint_tpu.crypto.ed25519_jax import field as F

    rng = np.random.default_rng(3)
    n = 128
    a_int = [int(rng.integers(0, 2**63)) ** 4 % F.P_INT for _ in range(n)]
    b_int = [int(rng.integers(0, 2**63)) ** 4 % F.P_INT for _ in range(n)]
    a = np.stack([F.int_to_limbs(x) for x in a_int], axis=1).reshape(F.NLIMBS, 1, n)
    b = np.stack([F.int_to_limbs(x) for x in b_int], axis=1).reshape(F.NLIMBS, 1, n)
    out = np.asarray(F.freeze(F.mul(a, b))).reshape(F.NLIMBS, n)
    for i in range(n):
        assert F.limbs_to_int(out[:, i]) == a_int[i] * b_int[i] % F.P_INT


def test_device_segmented_pipeline_matches_host():
    """The segmented double-buffered stream path (the flagship 10k
    optimization) on the real chip: verdicts must be byte-identical to the
    host spec, including rejects that straddle segment boundaries."""
    assert _device_is_accelerator()
    from tendermint_tpu.crypto.ed25519_jax import verify as V

    n = max(2 * V.SEG_MIN_SIGS, 4 * 2048)
    rng = np.random.default_rng(41)
    base = bytes(rng.bytes(100))
    pks, msgs, sigs = [], [], []
    sd = rng.bytes(32)
    pk = host.pubkey_from_seed(sd)
    for i in range(n):
        m = bytearray(base)
        m[40:48] = int(i).to_bytes(8, "little")  # vote-like: sparse diffs
        m = bytes(m)
        sig = host.sign(sd + pk, m)
        pks.append(pk)
        msgs.append(m)
        sigs.append(sig)
    # rejects at every real segment boundary (derive from _segment_sizes so
    # env overrides of SEG_CHUNKS/SEG_MIN_SIGS keep the coverage honest)
    bad = {0, 1, n // 2, n - 1}
    row = 0
    for size in V._segment_sizes(-(-n // 2048))[:-1]:
        row += size * 2048
        bad |= {row - 1, row, row + 1}
    for i in bad:
        sigs[i] = sigs[i][:32] + bytes(32)
    got = np.asarray(V.batch_verify_stream(pks, msgs, sigs, chunk=2048))
    want = np.ones(n, dtype=bool)
    want[list(bad)] = False
    mismatch = np.nonzero(got != want)[0]
    assert mismatch.size == 0, f"segmented disagrees at {mismatch[:8]}"
