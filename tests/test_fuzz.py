"""Property-based fuzzing (the reference's go-fuzz targets, test/fuzz/:
mempool CheckTx, secret-connection read/write, pubsub query parser, wire
codecs) via hypothesis.
"""

import pytest

pytest.importorskip("hypothesis", reason="property fuzzing needs the optional 'hypothesis' package")
import hypothesis.strategies as st
import pytest
from hypothesis import HealthCheck, given, settings

from tendermint_tpu.libs import protowire as pw

FAST = settings(max_examples=200, deadline=None,
                suppress_health_check=[HealthCheck.too_slow])


@FAST
@given(st.integers(min_value=0, max_value=(1 << 64) - 1))
def test_varint_round_trip(v):
    enc = pw.encode_varint(v)
    dec, pos = pw.decode_varint(enc, 0)
    assert dec == v and pos == len(enc)


@FAST
@given(st.integers(min_value=-(1 << 63), max_value=(1 << 63) - 1))
def test_int64_varint_round_trip(v):
    w = pw.Writer()
    w.varint(1, v)
    fields = pw.fields_dict(w.finish())
    got = pw.varint_to_int64(fields[1][0]) if 1 in fields else 0
    assert got == v


@FAST
@given(st.binary(max_size=512))
def test_iter_fields_never_crashes_on_garbage(data):
    """The wire parser must reject or ignore garbage, never raise unexpected
    exception types (fuzz target: every reactor decodes peer bytes)."""
    try:
        list(pw.iter_fields(data))
    except (ValueError, IndexError):
        pass  # structured rejection is fine


@FAST
@given(st.binary(max_size=256))
def test_pex_decode_never_crashes(data):
    from tendermint_tpu.p2p.pex import decode_pex_msg

    try:
        decode_pex_msg(data)
    except (ValueError, IndexError):
        pass


@FAST
@given(st.binary(max_size=256))
def test_statesync_decode_never_crashes(data):
    from tendermint_tpu.statesync.msgs import decode_msg

    try:
        decode_msg(data)
    except (ValueError, IndexError):
        pass


@FAST
@given(st.text(max_size=80))
def test_query_parser_never_crashes(src):
    """(reference libs/pubsub/query fuzz) parse arbitrary strings; matching
    an arbitrary event set must not raise."""
    from tendermint_tpu.libs.pubsub import Query

    try:
        q = Query(src)
        q.matches({"tm.event": ["Tx"], "tx.height": ["5"]})
    except ValueError:
        pass


@FAST
@given(st.lists(st.binary(min_size=1, max_size=64), max_size=20))
def test_mempool_cache_push_remove(txs):
    """(reference mempool fuzz) cache invariants under arbitrary sequences."""
    from tendermint_tpu.mempool.clist_mempool import TxCache

    cache = TxCache(8)
    for tx in txs:
        first = cache.push(tx)
        again = cache.push(tx)
        assert not again or not first  # second push of same tx never "new"
        cache.remove(tx)
        assert cache.push(tx)  # removable and re-addable
        cache.remove(tx)


@FAST
@given(st.binary(max_size=200), st.integers(min_value=0, max_value=3))
def test_wal_reader_tolerates_corruption(tmp_path_factory, data, cut):
    """(reference consensus/wal_fuzz.go) arbitrary tail corruption must only
    truncate replay, never crash the reader."""
    from tendermint_tpu.consensus.wal import WAL

    tmp = tmp_path_factory.mktemp("walfuzz")
    path = str(tmp / "w.wal")
    wal = WAL(path)
    wal.write("round_step", {"height": 1}, 1)
    wal.close()
    with open(path, "ab") as f:
        f.write(data[:len(data) - cut] if cut else data)
    msgs = list(WAL(path).iter_messages())
    assert len(msgs) >= 2  # ENDHEIGHT 0 + our record always survive


def test_secret_connection_rejects_garbage_frames():
    """(reference test/fuzz/p2p/secret_connection) a peer sending garbage
    ciphertext must produce a clean failure, not a hang or crash."""
    import asyncio

    pytest.importorskip("cryptography", reason="needs the optional 'cryptography' package (absent in slim containers)")
    from tests.test_p2p_tcp import _spawn_pair

    async def run():
        _k1, _k2, sc1, sc2, server = await _spawn_pair()()
        # write garbage straight onto the underlying socket of sc1's writer
        sc1._writer.write(b"\xde\xad" * 600)
        await sc1._writer.drain()
        import cryptography.exceptions

        # a TIMEOUT here would mean the hang this test guards against —
        # only a structured rejection may pass
        with pytest.raises((ValueError, RuntimeError,
                            cryptography.exceptions.InvalidTag)):
            await asyncio.wait_for(sc2.read(), 5)
        server.close()
    asyncio.run(run())
