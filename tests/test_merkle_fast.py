"""Vectorized merkle fast path (crypto/merkle_fast.py + merkle.py):
byte-parity with the hashlib spec, incremental dirty-leaf mode, routing
thresholds, and the MerkleKVStoreApplication integration."""

import hashlib
import os
import random

import pytest

from tendermint_tpu.crypto import merkle_fast as mf
from tendermint_tpu.crypto.merkle import (IncrementalMerkle,
                                          fast_hash_from_byte_slices,
                                          hash_from_byte_slices)

# every sha256 block-boundary edge: empty, 1, 55/56 (length spill into the
# second block), 63/64/65, 119/120/121 (two-block spill), plus a big one
EDGE_LENGTHS = [0, 1, 31, 54, 55, 56, 63, 64, 65, 119, 120, 121, 300, 4096]


def test_sha256_many_np_matches_hashlib():
    for n in EDGE_LENGTHS:
        msgs = [bytes([i % 256]) * n for i in range(5)]
        got = mf.sha256_many_np(msgs)
        want = [hashlib.sha256(m).digest() for m in msgs]
        assert got == want, f"np sha256 diverges at length {n}"


def test_sha256_many_np_bulk_random():
    rng = random.Random(11)
    msgs = [bytes(rng.randrange(256) for _ in range(65)) for _ in range(200)]
    assert mf.sha256_many_np(msgs) == \
        [hashlib.sha256(m).digest() for m in msgs]


def test_sha256_many_device_matches_hashlib():
    if not mf.device_ready():
        pytest.skip("no jax device")
    msgs = [bytes([i % 256]) * 65 for i in range(64)]
    assert mf.sha256_many_device(msgs) == \
        [hashlib.sha256(m).digest() for m in msgs]


@pytest.mark.parametrize("n", [0, 1, 2, 3, 4, 5, 7, 8, 9, 64, 65, 127, 128,
                               129, 1000])
def test_fast_tree_matches_spec(n):
    items = [f"leaf-{i}".encode() * (1 + i % 4) for i in range(n)]
    assert fast_hash_from_byte_slices(items) == hash_from_byte_slices(items)


def test_fast_tree_crosses_np_threshold(monkeypatch):
    # force the numpy batch path on for even tiny trees
    monkeypatch.setenv("TMTPU_MERKLE_NP_MIN", "1")
    items = [f"x{i}".encode() for i in range(37)]
    assert fast_hash_from_byte_slices(items) == hash_from_byte_slices(items)


def test_incremental_merkle_differential():
    """Random update/insert/delete schedule: the incremental root equals
    the spec recomputed from scratch at every step."""
    rng = random.Random(7)
    state = {}
    imt = IncrementalMerkle()

    def leaf_item(k):
        return k.encode() + b"\x00" + state[k].encode()

    for step in range(120):
        dirty = set()
        for _ in range(rng.randrange(1, 6)):
            op = rng.random()
            k = f"k{rng.randrange(40)}"
            if op < 0.70 or not state:
                state[k] = f"v{step}-{rng.random()}"
                dirty.add(k)
            else:
                victim = rng.choice(sorted(state))
                del state[victim]
        keys = sorted(state)
        got = imt.root(keys, leaf_item, dirty)
        want = hash_from_byte_slices([leaf_item(k) for k in keys])
        assert got == want, f"incremental root diverged at step {step}"
    assert imt.patches > 0 and imt.rebuilds > 0  # both paths exercised


def test_incremental_merkle_patch_vs_rebuild_thresholds():
    state = {f"k{i:03d}": "v" for i in range(200)}
    imt = IncrementalMerkle()

    def leaf_item(k):
        return k.encode() + b"\x00" + state[k].encode()

    keys = sorted(state)
    imt.root(keys, leaf_item, None)
    rebuilds0 = imt.rebuilds
    # a small dirty set patches
    state["k000"] = "v2"
    imt.root(keys, leaf_item, {"k000"})
    assert imt.patches == 1 and imt.rebuilds == rebuilds0
    # a huge dirty set (>= n/4) rebuilds
    big = {k for k in keys[:60]}
    for k in big:
        state[k] = "v3"
    imt.root(keys, leaf_item, big)
    assert imt.rebuilds == rebuilds0 + 1


def test_incremental_merkle_empty_and_reset():
    imt = IncrementalMerkle()
    assert imt.root([], lambda k: b"", None) == hash_from_byte_slices([])
    imt.reset()
    assert imt.root(["a"], lambda k: b"a=1", None) == \
        hash_from_byte_slices([b"a=1"])


def test_merkle_kvstore_app_incremental_matches_spec():
    """Commit-by-commit: the app's (incremental) hash equals the spec
    recomputed from the full store, and the kill switch takes the same
    bytes through the hashlib path."""
    from tendermint_tpu.abci import types as abci
    from tendermint_tpu.abci.example.kvstore import MerkleKVStoreApplication

    app = MerkleKVStoreApplication(interval=1)
    spec = MerkleKVStoreApplication(interval=1)
    os.environ["TMTPU_MERKLE_FAST"] = "1"
    try:
        rng = random.Random(13)
        for h in range(1, 8):
            for i in range(rng.randrange(1, 9)):
                tx = f"k{rng.randrange(12)}=v{h}.{i}".encode()
                app.deliver_tx(abci.RequestDeliverTx(tx=tx))
                spec.deliver_tx(abci.RequestDeliverTx(tx=tx))
            fast_hash = app.commit().data
            os.environ["TMTPU_MERKLE_FAST"] = "0"
            try:
                spec_hash = spec.commit().data
            finally:
                os.environ["TMTPU_MERKLE_FAST"] = "1"
            assert fast_hash == spec_hash, f"app hash diverged at height {h}"
    finally:
        os.environ.pop("TMTPU_MERKLE_FAST", None)
