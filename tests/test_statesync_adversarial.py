"""Untrusted-peer statesync (ISSUE 8): lying chunk servers get banned and
the restore completes from honest peers; lying snapshot advertisers get
blamed when the trusted-hash check fails; the discovery loop re-asks the
net instead of sleeping once and giving up; peer selection is seeded and
deterministic; and the SnapshotPool/ChunkQueue edge cases around
remove_peer / reject_format / late chunks / retry_all behave.

The harness is the in-proc Byzantine rig: a real SnapshotKVStoreApplication
pair (server with a multi-chunk snapshot + fresh restore target), a stub
state provider pinning the trusted app hash, and per-peer request_chunk
closures standing in for the p2p reactors.
"""

import asyncio
import random

import pytest

from tendermint_tpu.abci import types as abci
from tendermint_tpu.abci.example.kvstore import SnapshotKVStoreApplication
from tendermint_tpu.libs.faults import faults
from tendermint_tpu.libs.metrics import Registry, StateSyncMetrics
from tendermint_tpu.libs.peerscore import PeerScoreboard
from tendermint_tpu.statesync.chunks import ChunkQueue
from tendermint_tpu.statesync.msgs import ChunkResponse, SnapshotsResponse
from tendermint_tpu.statesync.stateprovider import StateProvider
from tendermint_tpu.statesync.syncer import (
    ErrNoSnapshots,
    SnapshotKey,
    SnapshotPool,
    Syncer,
)

CHUNK_PAYLOAD = "v" * 150


def make_server(n_keys=40):
    """Server app with one multi-chunk snapshot at height 1."""
    app = SnapshotKVStoreApplication(interval=1)
    for i in range(n_keys):
        app.deliver_tx(abci.RequestDeliverTx(
            tx=f"key{i:03d}={CHUNK_PAYLOAD}".encode()))
    app.commit()
    return app


class StubProvider(StateProvider):
    def __init__(self, app_hash):
        self._hash = app_hash

    async def app_hash(self, height):
        return self._hash

    async def commit(self, height):
        return "commit"

    async def state(self, height):
        return "state"


def make_syncer(server, client, request_chunk, *, seed=0, ban_threshold=2,
                metrics=None, chunk_timeout=2.0):
    return Syncer(client, client, StubProvider(server.app_hash),
                  request_chunk, chunk_timeout=chunk_timeout,
                  rng=random.Random(seed),
                  scoreboard=PeerScoreboard(ban_threshold=ban_threshold,
                                            seed=seed),
                  metrics=metrics)


def serve_chunk(server, syncer, peer_id, height, fmt, idx,
                tamper=None, drop=False):
    """Answer one ChunkRequest the way the reactor would."""
    if drop:
        return
    resp = server.load_snapshot_chunk(
        abci.RequestLoadSnapshotChunk(height, fmt, idx))
    chunk = resp.chunk
    if tamper is not None:
        chunk = tamper(chunk)
    syncer.add_chunk(ChunkResponse(height, fmt, idx, chunk, not resp.chunk),
                     peer_id)


def advertise_all(server, syncer, peer_ids):
    snaps = server.list_snapshots(abci.RequestListSnapshots()).snapshots
    for s in snaps:
        for pid in peer_ids:
            syncer.add_snapshot(pid, s)
    return snaps


# -- the Byzantine restore ----------------------------------------------------

def _lying_chunk_restore(seed):
    server = make_server()
    client = SnapshotKVStoreApplication(interval=1)
    metrics = StateSyncMetrics(Registry("t"))
    asked = []

    async def run():
        async def request_chunk(peer_id, height, fmt, idx):
            asked.append((peer_id, idx))
            serve_chunk(server, syncer, peer_id, height, fmt, idx,
                        tamper=(lambda c: b"\x00" + c[1:])
                        if peer_id == "liar" else None)

        syncer = make_syncer(server, client, request_chunk, seed=seed,
                             metrics=metrics)
        syncer.scoreboard.bans_counter = metrics.peer_bans_total
        syncer.scoreboard.retries_counter = metrics.sync_retries_total
        advertise_all(server, syncer, ["honest-a", "honest-b", "liar"])
        state, commit = await syncer.sync_any(discovery_time=0.01)
        assert (state, commit) == ("state", "commit")
        return syncer

    syncer = asyncio.run(run())
    return syncer, metrics, asked


def test_lying_chunk_server_banned_and_restore_completes():
    syncer, metrics, asked = _lying_chunk_restore(seed=4)
    assert syncer.scoreboard.banned("liar")
    assert not syncer.scoreboard.banned("honest-a")
    assert not syncer.scoreboard.banned("honest-b")
    # the rotation really spread fetches across every advertiser
    assert {p for p, _ in asked} == {"honest-a", "honest-b", "liar"}
    # the ban is on the metric the acceptance criteria reads
    assert metrics.peer_bans_total.value("rejected_chunk") >= 1
    assert metrics.chunks_refetched_total.value() >= 1
    assert metrics.restore_duration_seconds.count_value("restored") == 1


def test_lying_chunk_schedule_replays_exactly():
    """Same seed -> identical fetch schedule and identical blame; a chaos
    run with TMTPU_FAULTS_SEED fixed reproduces its injection schedule."""
    s1, _, asked1 = _lying_chunk_restore(seed=9)
    s2, _, asked2 = _lying_chunk_restore(seed=9)
    assert asked1 == asked2
    assert s1.scoreboard.snapshot().keys() == s2.scoreboard.snapshot().keys()
    s3, _, asked3 = _lying_chunk_restore(seed=10)
    assert asked3 != asked1  # a different seed shuffles differently


def test_lying_snapshot_advertiser_blamed_then_honest_restore():
    server = make_server()
    client = SnapshotKVStoreApplication(interval=1)
    metrics = StateSyncMetrics(Registry("t"))

    async def run():
        async def request_chunk(peer_id, height, fmt, idx):
            serve_chunk(server, syncer, peer_id, height, fmt, idx)

        syncer = make_syncer(server, client, request_chunk, seed=1,
                             ban_threshold=3, metrics=metrics)
        syncer.scoreboard.bans_counter = metrics.peer_bans_total
        snaps = advertise_all(server, syncer, [])
        # the liar is first on the scene, advertising a tampered hash
        for s in snaps:
            syncer.add_snapshot("liar", abci.Snapshot(
                s.height, s.format, s.chunks,
                bytes([s.hash[0] ^ 1]) + s.hash[1:], s.metadata))

        def rediscover():
            for s in snaps:
                for pid in ("honest-a", "honest-b"):
                    syncer.add_snapshot(pid, s)

        state, commit = await syncer.sync_any(discovery_time=0.02,
                                              rediscover=rediscover)
        assert (state, commit) == ("state", "commit")
        return syncer

    syncer = asyncio.run(run())
    # advertising a provably-bad snapshot is a severe strike: banned
    assert syncer.scoreboard.banned("liar")
    assert metrics.peer_bans_total.value("bad_snapshot") == 1
    assert metrics.snapshots_rejected_total.value("content") == 1
    assert metrics.discovery_rounds_total.value() >= 1
    assert client.state == server.state


def test_empty_pool_rediscovers_then_gives_up():
    server = make_server()
    client = SnapshotKVStoreApplication(interval=1)
    rounds = []

    async def run():
        async def request_chunk(peer_id, height, fmt, idx):
            raise AssertionError("no chunks should ever be requested")

        syncer = make_syncer(server, client, request_chunk, seed=1)
        with pytest.raises(ErrNoSnapshots):
            await syncer.sync_any(discovery_time=0.01,
                                  rediscover=lambda: rounds.append(1),
                                  discovery_rounds=3)

    asyncio.run(run())
    assert len(rounds) == 2  # re-asked between rounds, then gave up


def test_unresponsive_peer_times_out_strikes_and_restore_completes():
    server = make_server(n_keys=20)
    client = SnapshotKVStoreApplication(interval=1)

    async def run():
        async def request_chunk(peer_id, height, fmt, idx):
            serve_chunk(server, syncer, peer_id, height, fmt, idx,
                        drop=(peer_id == "mute"))

        syncer = make_syncer(server, client, request_chunk, seed=2,
                             ban_threshold=2, chunk_timeout=0.3)
        advertise_all(server, syncer, ["honest-a", "mute"])
        state, commit = await syncer.sync_any(discovery_time=0.01)
        assert (state, commit) == ("state", "commit")
        return syncer

    syncer = asyncio.run(run())
    scores = syncer.scoreboard.snapshot()
    assert scores["mute"]["total_failures"] >= 1
    assert client.state == server.state


def test_all_advertisers_banned_rejects_snapshot():
    """A snapshot whose every advertiser is banned mid-restore must be
    rejected (then ErrNoSnapshots), never wedge the apply loop."""
    server = make_server(n_keys=20)
    client = SnapshotKVStoreApplication(interval=1)

    async def run():
        async def request_chunk(peer_id, height, fmt, idx):
            serve_chunk(server, syncer, peer_id, height, fmt, idx,
                        tamper=lambda c: b"\xff" + c[1:])

        syncer = make_syncer(server, client, request_chunk, seed=3,
                             ban_threshold=1)
        advertise_all(server, syncer, ["liar-a", "liar-b"])
        with pytest.raises(ErrNoSnapshots):
            await syncer.sync_any(discovery_time=0.01, discovery_rounds=1)
        return syncer

    syncer = asyncio.run(run())
    assert syncer.scoreboard.ban_count() >= 1


# -- deterministic peer rotation ----------------------------------------------

def test_peers_of_is_sorted_and_rotation_is_seeded():
    pool = SnapshotPool()
    key_args = (5, 1, 4, b"h" * 32)
    for pid in ("zz", "aa", "mm"):
        pool.add(pid, *key_args, b"")
    key = SnapshotKey(*key_args)
    assert pool.peers_of(key) == ["aa", "mm", "zz"]

    def order(seed):
        s = Syncer(None, None, StubProvider(b""), None,
                   rng=random.Random(seed))
        return s._rotation_order(["aa", "mm", "zz"])

    assert order(1) == order(1)
    assert sorted(order(1)) == ["aa", "mm", "zz"]


# -- SnapshotPool / ChunkQueue edge cases (the satellite checklist) -----------

def test_pool_remove_peer_drops_snapshot_with_last_peer():
    pool = SnapshotPool()
    pool.add("only", 5, 1, 3, b"x" * 32, b"meta")
    pool.add("p1", 6, 1, 3, b"y" * 32, b"meta")
    pool.add("p2", 6, 1, 3, b"y" * 32, b"meta")
    pool.remove_peer("only")
    assert pool.best() == SnapshotKey(6, 1, 3, b"y" * 32)
    pool.remove_peer("p1")
    assert pool.best() == SnapshotKey(6, 1, 3, b"y" * 32)  # p2 still vouches
    pool.remove_peer("p2")
    assert pool.best() is None


def test_pool_reject_format_sweeps_and_blocks_readd():
    pool = SnapshotPool()
    pool.add("p", 4, 1, 2, b"a" * 32, b"")
    pool.add("p", 5, 1, 2, b"b" * 32, b"")
    pool.add("p", 5, 2, 2, b"c" * 32, b"")
    pool.reject_format(1)
    assert pool.best() == SnapshotKey(5, 2, 2, b"c" * 32)
    # a rejected key cannot be re-advertised back in
    assert not pool.add("p2", 5, 1, 2, b"b" * 32, b"")
    assert pool.best() == SnapshotKey(5, 2, 2, b"c" * 32)


def test_pool_best_tiebreak_is_deterministic():
    pool = SnapshotPool()
    pool.add("a", 5, 1, 2, b"\x01" * 32, b"")
    pool.add("b", 5, 1, 2, b"\x02" * 32, b"")
    assert pool.best() == SnapshotKey(5, 1, 2, b"\x02" * 32)


def test_add_chunk_wrong_height_or_format_ignored():
    server = make_server(n_keys=4)
    client = SnapshotKVStoreApplication(interval=1)
    syncer = make_syncer(server, client, None, seed=1)
    snaps = advertise_all(server, syncer, ["p"])
    key = syncer.pool.best()
    syncer._current = key
    syncer.chunks = ChunkQueue(key.chunks)
    # wrong height / wrong format / no restore in flight are all dropped
    syncer.add_chunk(ChunkResponse(key.height + 1, key.format, 0, b"x", False),
                     "p")
    syncer.add_chunk(ChunkResponse(key.height, key.format + 9, 0, b"x", False),
                     "p")
    assert not syncer.chunks.has(0)
    # matching response lands (and counts)
    syncer.add_chunk(ChunkResponse(key.height, key.format, 0, b"x", False), "p")
    assert syncer.chunks.has(0)
    # a late duplicate for the same index is ignored, sender unchanged
    syncer.add_chunk(ChunkResponse(key.height, key.format, 0, b"y", False), "q")
    assert syncer.chunks.get(0) == b"x" and syncer.chunks.sender(0) == "p"
    # out-of-range index ignored
    syncer.add_chunk(ChunkResponse(key.height, key.format, key.chunks + 3,
                                   b"x", False), "p")
    # missing=True discards (so it gets re-fetched elsewhere)
    syncer.add_chunk(ChunkResponse(key.height, key.format, 0, b"", True), "p")
    assert not syncer.chunks.has(0)
    # after the restore tears down, nothing lands
    syncer.chunks = None
    syncer._current = None
    syncer.add_chunk(ChunkResponse(key.height, key.format, 1, b"x", False), "p")


def test_chunk_queue_retry_all_after_app_retry_snapshot():
    q = ChunkQueue(4)
    for i in range(4):
        assert q.allocate() == i
        q.add(i, b"c%d" % i, f"peer{i}")
    assert q.complete()
    q.retry_all()  # the RETRY_SNAPSHOT path re-fetches everything
    assert not q.complete()
    assert all(not q.has(i) for i in range(4))
    assert q.sender(0) == ""
    # indexes are allocatable again, in order
    assert [q.allocate() for _ in range(4)] == [0, 1, 2, 3]
    assert q.allocate() is None


def test_chunk_queue_discard_sender_only_hits_their_chunks():
    q = ChunkQueue(3)
    for i in range(3):
        q.allocate()
    q.add(0, b"a", "alice")
    q.add(1, b"b", "bob")
    q.add(2, b"c", "alice")
    q.discard_sender("alice")
    assert not q.has(0) and q.has(1) and not q.has(2)
    assert q.sender(1) == "bob"


# -- the app-side per-chunk verification (what makes blame attributable) ------

def test_kvstore_metadata_carries_chunk_hashes_and_rejects_tampered_chunk():
    import hashlib
    import json

    server = make_server()
    snap = server.list_snapshots(abci.RequestListSnapshots()).snapshots[0]
    hashes = json.loads(snap.metadata.decode())["chunk_hashes"]
    assert len(hashes) == snap.chunks > 1
    chunk0 = server.load_snapshot_chunk(
        abci.RequestLoadSnapshotChunk(snap.height, snap.format, 0)).chunk
    assert hashlib.sha256(chunk0).hexdigest() == hashes[0]

    client = SnapshotKVStoreApplication(interval=1)
    offer = client.offer_snapshot(abci.RequestOfferSnapshot(
        snapshot=snap, app_hash=server.app_hash))
    assert offer.result == abci.OFFER_SNAPSHOT_ACCEPT
    # a tampered chunk is named-and-shamed, not applied
    r = client.apply_snapshot_chunk(abci.RequestApplySnapshotChunk(
        index=0, chunk=b"\x00" + chunk0[1:], sender="liar"))
    assert r.result == abci.APPLY_SNAPSHOT_CHUNK_RETRY
    assert r.refetch_chunks == [0]
    assert r.reject_senders == ["liar"]
    # the honest chunk then applies
    r = client.apply_snapshot_chunk(abci.RequestApplySnapshotChunk(
        index=0, chunk=chunk0, sender="honest"))
    assert r.result == abci.APPLY_SNAPSHOT_CHUNK_ACCEPT


def test_kvstore_snapshot_without_metadata_still_restores():
    """Backward compat: snapshots with empty/garbled metadata skip the
    per-chunk check and rely on the whole-blob hash, as before."""
    server = make_server(n_keys=6)
    snap = server.list_snapshots(abci.RequestListSnapshots()).snapshots[0]
    bare = abci.Snapshot(snap.height, snap.format, snap.chunks, snap.hash,
                         b"not-json")
    client = SnapshotKVStoreApplication(interval=1)
    assert client.offer_snapshot(abci.RequestOfferSnapshot(
        snapshot=bare, app_hash=server.app_hash)).result \
        == abci.OFFER_SNAPSHOT_ACCEPT
    for i in range(snap.chunks):
        chunk = server.load_snapshot_chunk(
            abci.RequestLoadSnapshotChunk(snap.height, snap.format, i)).chunk
        r = client.apply_snapshot_chunk(abci.RequestApplySnapshotChunk(
            index=i, chunk=chunk, sender="p"))
        assert r.result == abci.APPLY_SNAPSHOT_CHUNK_ACCEPT
    assert client.state == server.state


# -- serving reactor fault seams ----------------------------------------------

class FakePeer:
    def __init__(self, pid="peer-1"):
        self.id = pid
        self.sent = []

    def try_send(self, channel_id, payload):
        self.sent.append((channel_id, payload))
        return True


def test_reactor_serves_lies_only_when_armed():
    from tendermint_tpu.statesync.msgs import ChunkRequest, decode_msg, encode_msg
    from tendermint_tpu.statesync.reactor import StateSyncReactor

    server = make_server(n_keys=8)
    reactor = StateSyncReactor(server, server)
    snap = server.list_snapshots(abci.RequestListSnapshots()).snapshots[0]

    async def ask(msg):
        peer = FakePeer()
        await reactor.receive(0x61, peer, encode_msg(msg))
        return [decode_msg(p) for _, p in peer.sent]

    async def run():
        honest = (await ask(ChunkRequest(snap.height, snap.format, 0)))[0]
        assert not honest.missing
        true_chunk = server.load_snapshot_chunk(abci.RequestLoadSnapshotChunk(
            snap.height, snap.format, 0)).chunk
        assert honest.chunk == true_chunk

        faults.configure("statesync.lying_chunk", seed=5)
        lied = (await ask(ChunkRequest(snap.height, snap.format, 0)))[0]
        assert lied.chunk != true_chunk
        assert len(lied.chunk) == len(true_chunk)
        assert faults.fires("statesync.lying_chunk") == 1
        faults.reset()

        # snapshot advert: honest then tampered-hash
        from tendermint_tpu.statesync.msgs import SnapshotsRequest

        peer = FakePeer()
        await reactor.receive(0x60, peer, encode_msg(SnapshotsRequest()))
        honest_ad = decode_msg(peer.sent[0][1])
        assert honest_ad.hash == snap.hash
        faults.configure("statesync.lying_snapshot", seed=5)
        peer2 = FakePeer()
        await reactor.receive(0x60, peer2, encode_msg(SnapshotsRequest()))
        lying_ad = decode_msg(peer2.sent[0][1])
        assert lying_ad.hash != snap.hash
        assert lying_ad.height == snap.height
        faults.reset()

    asyncio.run(run())


def test_syncer_progress_snapshot_shape():
    server = make_server(n_keys=4)
    client = SnapshotKVStoreApplication(interval=1)
    syncer = make_syncer(server, client, None, seed=1)
    p0 = syncer.progress()
    assert p0["snapshot"] is None and p0["chunks_applied"] == 0
    advertise_all(server, syncer, ["p"])
    key = syncer.pool.best()
    syncer._current = key
    syncer.chunks = ChunkQueue(key.chunks)
    syncer._applied = 1
    syncer.scoreboard.record_failure("q", "timeout")
    p = syncer.progress()
    assert p["snapshot"]["height"] == key.height
    assert p["chunks_applied"] == 1 and p["chunks_total"] == key.chunks
    assert p["peer_scores"]["q"]["total_failures"] == 1
    import json

    json.dumps(p)  # debugdump bundles it verbatim
