"""tools/crashmatrix.py as a test seam: the stdlib self-test and plan
determinism run in tier-1; the kill-at-every-durability-boundary matrix
itself (live in-proc fleet, supervised restarts, app-hash/double-sign
invariants) runs in the slow tier across 2 seeds with determinism
verified — the ISSUE's acceptance gate, as a test."""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOL = os.path.join(REPO, "tools", "crashmatrix.py")


def _cm():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import crashmatrix
    finally:
        sys.path.pop(0)
    return crashmatrix


def test_self_test_subprocess():
    res = subprocess.run([sys.executable, TOOL, "--self-test"],
                         capture_output=True, text=True, timeout=120)
    assert res.returncode == 0, res.stdout + res.stderr
    assert "self-test OK" in res.stdout


def test_plan_determinism_and_shape():
    cm = _cm()
    p1, p2 = cm.plan_crashes(3), cm.plan_crashes(3)
    assert p1 == p2
    assert cm.plan_crashes(4) != p1
    assert {k["boundary"] for k in p1["kills"]} == set(cm.ALL_BOUNDARIES)
    # the joiner boundary runs last; everything else targets the victim
    assert p1["kills"][-1]["target"] == "joiner"
    # the quorum-loss window sits between the victim code-site kills and
    # the joiner (statesync wants an already-healed, committing net)
    pre_joiner = [k["boundary"] for k in p1["kills"][:-1]]
    assert pre_joiner[-len(cm.QUORUM_BOUNDARIES):] == \
        list(cm.QUORUM_BOUNDARIES)
    assert all(k["target"] == cm.VICTIM for k in p1["kills"][:-1])


def test_fingerprint_strips_wall_clock():
    cm = _cm()
    rep = {"plan": cm.plan_crashes(1), "kills": [
        {"boundary": "wal.after_fsync", "target": cm.VICTIM, "killed": True,
         "recovered": True, "restarts": 1, "evidence": 0,
         "double_sign_observed": False, "kill_to_caughtup_s": 1.23,
         "backoff_s": 0.2}]}
    fp = cm.outcome_fingerprint(rep)
    import json

    assert "kill_to_caughtup_s" not in json.dumps(fp)
    assert fp["kills"][0]["killed"] is True


def test_single_boundary_live():
    """One live kill+recover cycle in tier-1: the cheapest boundary,
    proving the whole rig (persistent victim, in-proc SIGKILL semantics,
    supervised rebuild, invariants) end to end without the slow tier."""
    cm = _cm()
    rep = cm.run_matrix(seed=1, boundaries=["wal.after_fsync"])
    assert rep["boundaries_killed"] == ["wal.after_fsync"]
    k = rep["kills"][0]
    assert k["killed"] and k["recovered"]
    assert not k["double_sign_observed"] and k["evidence"] == 0
    assert rep["mempool_wal_idempotent"] is True


def test_quorum_loss_boundary_live():
    """The net.during_quorum_loss window boundary live in tier-1: halt the
    fleet by isolating >1/3 power, kill the majority-side victim at its
    next WAL fsync INSIDE the halted window, heal, and prove the restart
    replays a halt-spanning WAL with no double-sign."""
    cm = _cm()
    rep = cm.run_matrix(seed=1, boundaries=["net.during_quorum_loss"])
    assert rep["boundaries_killed"] == ["net.during_quorum_loss"]
    k = rep["kills"][0]
    assert k["halted"] and k["halt_reason"] == "quorum_lost"
    assert k["killed"] and k["kill_site"] == cm.QUORUM_KILL_SITE
    assert k["recovered"]
    assert not k["double_sign_observed"] and k["evidence"] == 0
    assert k["recovery_records_replayed"] > 0


@pytest.mark.slow
@pytest.mark.parametrize("seed", [1, 2])
def test_full_matrix_two_seeds_deterministic(seed):
    """The acceptance gate: every enumerated durability boundary, killed
    and recovered, same-seed reruns agreeing on schedule + outcomes."""
    cm = _cm()
    r1 = cm.run_matrix(seed=seed)
    assert set(r1["boundaries_killed"]) == set(cm.ALL_BOUNDARIES)
    for k in r1["kills"]:
        assert k["killed"] and k["recovered"], k
        assert not k["double_sign_observed"], k
    assert r1["mempool_wal_idempotent"] is True
    r2 = cm.run_matrix(seed=seed)
    assert cm.outcome_fingerprint(r1) == cm.outcome_fingerprint(r2)
