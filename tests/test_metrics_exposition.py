"""Prometheus text exposition format + registry hygiene
(libs/metrics.py; reference libs/prometheus text format spec).

A malformed exposition line poisons the WHOLE scrape — Prometheus rejects
the body — so escaping and determinism are correctness, not cosmetics.
"""

import pytest

from tendermint_tpu.libs.metrics import (
    BlocksyncMetrics,
    CryptoMetrics,
    Gauge,
    Histogram,
    NodeMetrics,
    Registry,
)


def test_histogram_bucket_sum_count_lines():
    reg = Registry("t")
    h = reg.histogram("sub", "lat", "help.", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    lines = h.render()
    assert "# HELP t_sub_lat help." in lines
    assert "# TYPE t_sub_lat histogram" in lines
    assert 't_sub_lat_bucket{le="0.1"} 1' in lines
    assert 't_sub_lat_bucket{le="1"} 2' in lines
    assert 't_sub_lat_bucket{le="+Inf"} 3' in lines
    assert "t_sub_lat_count 3" in lines
    sum_line = [l for l in lines if l.startswith("t_sub_lat_sum")][0]
    assert abs(float(sum_line.split()[-1]) - 5.55) < 1e-9


def test_histogram_labeled_buckets_le_last_sorted():
    reg = Registry("t")
    h = reg.histogram("sub", "lat", "help.", labels=["route", "plane"],
                      buckets=(1.0,))
    h.labels("device", "light").observe(0.5)
    lines = h.render()
    # label names sorted (plane < route), le ALWAYS last — deterministically
    assert 't_sub_lat_bucket{plane="light",route="device",le="1"} 1' in lines
    assert ('t_sub_lat_bucket{plane="light",route="device",le="+Inf"} 1'
            in lines)
    # sum/count use the same sorted order (one metric, one ordering)
    assert 't_sub_lat_count{plane="light",route="device"} 1' in lines


def test_label_value_escaping():
    reg = Registry("t")
    c = reg.counter("sub", "hits", "help.", labels=["who"])
    c.labels('ba"ck\\slash\nnl').inc()
    line = [l for l in c.render() if not l.startswith("#")][0]
    assert line == 't_sub_hits{who="ba\\"ck\\\\slash\\nnl"} 1'
    h = reg.histogram("sub", "lat", "help.", labels=["who"], buckets=(1.0,))
    h.labels('q"v').observe(0.5)
    bucket = [l for l in h.render() if "_bucket" in l][0]
    assert 'who="q\\"v"' in bucket


def test_duplicate_registration_raises():
    reg = Registry("t")
    reg.counter("sub", "x", "first.")
    with pytest.raises(ValueError, match="already registered"):
        reg.counter("sub", "x", "second.")
    with pytest.raises(ValueError, match="already registered"):
        reg.gauge("sub", "x", "as another type either.")
    # distinct fq names still fine
    reg.counter("sub2", "x", "other subsystem.")


def test_misuse_guards_raise_typeerror():
    reg = Registry("t")
    c = reg.counter("sub", "c", "help.", labels=["a"])
    g = reg.gauge("sub", "g", "help.", labels=["a"])
    h = reg.histogram("sub", "h", "help.", labels=["a"])
    with pytest.raises(TypeError):
        c.labels("x").observe(1.0)
    with pytest.raises(TypeError):
        c.labels("x").set(1.0)
    with pytest.raises(TypeError):
        g.labels("x").observe(1.0)
    with pytest.raises(TypeError):
        h.labels("x").set(1.0)
    with pytest.raises(TypeError):
        h.labels("x").inc()
    with pytest.raises(TypeError):
        h.value("x")  # histograms expose sum_value()/count_value() instead
    with pytest.raises(ValueError):
        c.value()  # accessor arity is checked like labels()
    with pytest.raises(ValueError):
        h.sum_value("x", "extra")
    # the valid operations still work after the failed misuse
    c.labels("x").inc()
    g.labels("x").set(2.0)
    h.labels("x").observe(0.1)
    assert c.value("x") == 1.0
    assert g.value("x") == 2.0
    assert h.count_value("x") == 1 and h.sum_value("x") == 0.1


def test_node_metrics_includes_crypto_and_blocksync_sets():
    nm = NodeMetrics("tendermint")
    assert isinstance(nm.crypto, CryptoMetrics)
    assert isinstance(nm.blocksync, BlocksyncMetrics)
    nm.crypto.routing_decisions_total.labels("device", "light").inc()
    nm.crypto.batch_size.labels("device", "light").observe(1024)
    nm.blocksync.stage_seconds.labels("verify").observe(0.01)
    text = nm.registry.render()
    assert ('tendermint_crypto_routing_decisions_total'
            '{plane="light",route="device"} 1') in text
    assert ('tendermint_crypto_batch_size_bucket'
            '{plane="light",route="device",le="1024"} 1') in text
    assert "# TYPE tendermint_blocksync_stage_seconds histogram" in text
    assert 'tendermint_blocksync_stage_seconds_count{stage="verify"} 1' in text
    # one shared registry: a second NodeMetrics over a fresh registry does
    # not collide, but re-registering on the same one would
    with pytest.raises(ValueError):
        CryptoMetrics(nm.registry)


def test_gauge_still_supports_inc_and_set():
    g = Gauge("g", "help.")
    g.set(5)
    g.inc(2)
    assert g.value() == 7.0
    assert "g 7" in g.render()


def test_histogram_render_empty_is_header_only():
    h = Histogram("h", "help.")
    assert h.render() == ["# HELP h help.", "# TYPE h histogram"]


def test_node_metrics_includes_live_plane_series():
    """The event-driven live-plane series (gossip wakeups/polls, encode
    cache, WAL group commit) render on the shared registry — i.e. they are
    visible on the node's /metrics endpoint."""
    nm = NodeMetrics("tendermint")
    c = nm.consensus
    c.gossip_wakeups_total.labels("votes").inc()
    c.gossip_polls_total.labels("data").inc(3)
    c.encode_cache_hits_total.labels("vote").inc(5)
    c.encode_cache_misses_total.labels("block_part").inc()
    c.wal_fsyncs_total.inc(2)
    c.wal_records_per_fsync.observe(8)
    c.wal_fsync_seconds.observe(0.002)
    text = nm.registry.render()
    assert 'tendermint_consensus_gossip_wakeups_total{routine="votes"} 1' in text
    assert 'tendermint_consensus_gossip_polls_total{routine="data"} 3' in text
    assert 'tendermint_consensus_encode_cache_hits_total{kind="vote"} 5' in text
    assert ('tendermint_consensus_encode_cache_misses_total'
            '{kind="block_part"} 1') in text
    assert "tendermint_consensus_wal_fsyncs_total 2" in text
    assert 'tendermint_consensus_wal_records_per_fsync_bucket{le="8"} 1' in text
    assert "tendermint_consensus_wal_records_per_fsync_sum 8" in text
    assert "# TYPE tendermint_consensus_wal_fsync_seconds histogram" in text
