"""sr25519 (schnorrkel/ristretto255) — reference crypto/sr25519/pubkey.go:10.

Validates the ristretto255 group against RFC 9496 Appendix A vectors and the
schnorrkel sign/verify round trip with adversarial mutations.
"""

import pytest

from tendermint_tpu.crypto import pubkey_from_type_and_bytes, sr25519

# RFC 9496 A.1: encodings of B, 2B (independent pin of the group encoding)
GEN_MULTIPLES = [
    "0000000000000000000000000000000000000000000000000000000000000000",
    "e2f2ae0a6abc4e71a884a961c500515f58e30b6aa582dd8db6a65945e08d2d76",
    "6a493210f7499cd17fecb510ae0cea23a110e8d5b901f8acadd3095c73a3b919",
]

# RFC 9496 A.2: strings that MUST fail decoding
BAD_ENCODINGS = [
    # non-canonical field element
    "00ffffffffffffffffffffffffffffffffffffffffffffffffffffffffffff7f",
    "ffffffffffffffffffffffffffffffffffffffffffffffffffffffffffffff7f",
    # negative field element
    "0100000000000000000000000000000000000000000000000000000000000000",
    "01ffffffffffffffffffffffffffffffffffffffffffffffffffffffffffff7f",
    # non-square x^2
    "26948d35ca62e643e26a83177332e6b6afeb9d08e4268b650f1f5bbd8d81d371",
]


def test_ristretto_generator_multiples():
    from tendermint_tpu.crypto.ed25519 import P, _pt_add

    base = (sr25519._B[0], sr25519._B[1], 1,
            sr25519._B[0] * sr25519._B[1] % P)
    acc = (0, 1, 1, 0)
    for expected in GEN_MULTIPLES:
        enc = sr25519.ristretto_encode(acc)
        assert enc.hex() == expected
        # decode returns a point that re-encodes identically
        pt = sr25519.ristretto_decode(enc)
        assert pt is not None and sr25519.ristretto_encode(pt) == enc
        acc = _pt_add(acc, base)


def test_ristretto_bad_encodings_rejected():
    for bad in BAD_ENCODINGS:
        assert sr25519.ristretto_decode(bytes.fromhex(bad)) is None, bad


def test_sign_verify_round_trip():
    sk = sr25519.Sr25519PrivKey.generate(b"\x11" * 32)
    pk = sk.pub_key()
    msg = b"sr25519 vote sign bytes"
    sig = sk.sign(msg)
    assert len(sig) == 64 and sig[63] & 128
    assert pk.verify_signature(msg, sig)
    # reference test mutation (sr25519_test.go): flip one bit
    bad = bytearray(sig)
    bad[7] ^= 1
    assert not pk.verify_signature(msg, bytes(bad))
    assert not pk.verify_signature(msg + b"x", sig)
    # missing schnorrkel marker bit
    nomark = bytearray(sig)
    nomark[63] &= 127
    assert not pk.verify_signature(msg, bytes(nomark))
    # wrong key
    other = sr25519.Sr25519PrivKey.generate(b"\x12" * 32).pub_key()
    assert not other.verify_signature(msg, sig)


def test_registry_and_address():
    sk = sr25519.Sr25519PrivKey.generate(b"\x13" * 32)
    pk = pubkey_from_type_and_bytes("sr25519", sk.pub_key().bytes())
    assert pk.address() == sk.pub_key().address()
    assert len(pk.address()) == 20
    sig = sk.sign(b"m")
    assert pk.verify_signature(b"m", sig)


def test_params_accept_sr25519():
    from tendermint_tpu.types.params import (
        ValidatorParams,
        default_consensus_params,
    )

    p = default_consensus_params()
    p.validator = ValidatorParams(["ed25519", "sr25519"])
    p.validate_basic()
    bad = default_consensus_params()
    bad.validator = ValidatorParams(["bogus"])
    with pytest.raises(ValueError):
        bad.validate_basic()


# -- cross-implementation KATs -----------------------------------------------
#
# The reference's sr25519 is ChainSafe/go-schnorrkel (crypto/sr25519/
# privkey.go:10,28). No Go toolchain or schnorrkel port exists in this
# environment, so a dependency-GENERATED signature fixture cannot be minted
# here; interop is instead pinned at every deterministic layer:
#   1. ristretto255 group: RFC 9496 A.1/A.2 (above);
#   2. merlin/STROBE transcript: the canonical merlin conformance vector
#      (test_p2p_tcp.py::test_merlin_transcript_matches_upstream_vector,
#      "test protocol"/"some data" -> d5a21972...);
#   3. ExpandEd25519 + ristretto basepoint mul: the known schnorrkel keypair
#      below, produced by the wasm schnorrkel build in polkadot-js's test
#      suite — if our expansion, cofactor division, or encoding diverged in
#      any bit this would not match;
#   4. the signing transcript labels (SigningContext / "" / sign-bytes /
#      proto-name=Schnorr-sig / sign:pk / sign:R / sign:c, 64-byte wide
#      reduction) audited line-by-line against go-schnorrkel's
#      NewSigningContext and Sign (privkey.go:34).
# Signatures themselves are randomized (schnorrkel draws a witness from a
# transcript RNG), so even go-schnorrkel emits different bytes per call —
# there is no canonical signature vector to pin, only the acceptance
# predicate, which layers 1-4 determine completely.

KNOWN_MINI = "fac7959dbfe72f052e5a0c3c8d6530f202b02fd8f9f5ca3580ec8deb7797479e"
KNOWN_PUB = "46ebddef8cd9bb167dc30878d7113b7e168e6f0646beffd77d69d39bad76b47a"


def test_known_schnorrkel_keypair():
    mini = bytes.fromhex(KNOWN_MINI)
    assert sr25519.pubkey_from_mini(mini).hex() == KNOWN_PUB


def test_known_keypair_signs_and_verifies():
    mini = bytes.fromhex(KNOWN_MINI)
    sig = sr25519.sign(mini, b"hello", ctx=b"")
    assert sr25519.verify(bytes.fromhex(KNOWN_PUB), b"hello", sig)


def test_challenge_scalar_frozen_regression():
    """Self-generated (NOT cross-impl) pin of the full signing transcript:
    the challenge k for a fixed (ctx, msg, pk, R). Any future drift in the
    transcript composition — label bytes, framing, wide reduction — changes
    this value. Frozen at round 5."""
    t = sr25519.signing_context(b"ctx", b"msg")
    t.append_message(b"proto-name", b"Schnorr-sig")
    t.append_message(b"sign:pk", bytes.fromhex(KNOWN_PUB))
    t.append_message(b"sign:R", bytes(32))
    k = sr25519._challenge_scalar(t, b"sign:c")
    assert format(k, "064x") == (
        "08bf8b3b227353c0b39d3ba1edebee6da28f8ab5a4aed7c6f9efd194989b5b3a")
