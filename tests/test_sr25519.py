"""sr25519 (schnorrkel/ristretto255) — reference crypto/sr25519/pubkey.go:10.

Validates the ristretto255 group against RFC 9496 Appendix A vectors and the
schnorrkel sign/verify round trip with adversarial mutations.
"""

import pytest

from tendermint_tpu.crypto import pubkey_from_type_and_bytes, sr25519

# RFC 9496 A.1: encodings of B, 2B (independent pin of the group encoding)
GEN_MULTIPLES = [
    "0000000000000000000000000000000000000000000000000000000000000000",
    "e2f2ae0a6abc4e71a884a961c500515f58e30b6aa582dd8db6a65945e08d2d76",
    "6a493210f7499cd17fecb510ae0cea23a110e8d5b901f8acadd3095c73a3b919",
]

# RFC 9496 A.2: strings that MUST fail decoding
BAD_ENCODINGS = [
    # non-canonical field element
    "00ffffffffffffffffffffffffffffffffffffffffffffffffffffffffffff7f",
    "ffffffffffffffffffffffffffffffffffffffffffffffffffffffffffffff7f",
    # negative field element
    "0100000000000000000000000000000000000000000000000000000000000000",
    "01ffffffffffffffffffffffffffffffffffffffffffffffffffffffffffff7f",
    # non-square x^2
    "26948d35ca62e643e26a83177332e6b6afeb9d08e4268b650f1f5bbd8d81d371",
]


def test_ristretto_generator_multiples():
    from tendermint_tpu.crypto.ed25519 import P, _pt_add

    base = (sr25519._B[0], sr25519._B[1], 1,
            sr25519._B[0] * sr25519._B[1] % P)
    acc = (0, 1, 1, 0)
    for expected in GEN_MULTIPLES:
        enc = sr25519.ristretto_encode(acc)
        assert enc.hex() == expected
        # decode returns a point that re-encodes identically
        pt = sr25519.ristretto_decode(enc)
        assert pt is not None and sr25519.ristretto_encode(pt) == enc
        acc = _pt_add(acc, base)


def test_ristretto_bad_encodings_rejected():
    for bad in BAD_ENCODINGS:
        assert sr25519.ristretto_decode(bytes.fromhex(bad)) is None, bad


def test_sign_verify_round_trip():
    sk = sr25519.Sr25519PrivKey.generate(b"\x11" * 32)
    pk = sk.pub_key()
    msg = b"sr25519 vote sign bytes"
    sig = sk.sign(msg)
    assert len(sig) == 64 and sig[63] & 128
    assert pk.verify_signature(msg, sig)
    # reference test mutation (sr25519_test.go): flip one bit
    bad = bytearray(sig)
    bad[7] ^= 1
    assert not pk.verify_signature(msg, bytes(bad))
    assert not pk.verify_signature(msg + b"x", sig)
    # missing schnorrkel marker bit
    nomark = bytearray(sig)
    nomark[63] &= 127
    assert not pk.verify_signature(msg, bytes(nomark))
    # wrong key
    other = sr25519.Sr25519PrivKey.generate(b"\x12" * 32).pub_key()
    assert not other.verify_signature(msg, sig)


def test_registry_and_address():
    sk = sr25519.Sr25519PrivKey.generate(b"\x13" * 32)
    pk = pubkey_from_type_and_bytes("sr25519", sk.pub_key().bytes())
    assert pk.address() == sk.pub_key().address()
    assert len(pk.address()) == 20
    sig = sk.sign(b"m")
    assert pk.verify_signature(b"m", sig)


def test_params_accept_sr25519():
    from tendermint_tpu.types.params import (
        ValidatorParams,
        default_consensus_params,
    )

    p = default_consensus_params()
    p.validator = ValidatorParams(["ed25519", "sr25519"])
    p.validate_basic()
    bad = default_consensus_params()
    bad.validator = ValidatorParams(["bogus"])
    with pytest.raises(ValueError):
        bad.validate_basic()
