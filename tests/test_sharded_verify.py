"""Multi-device sharded verification tests (run on the 8 virtual CPU devices
the conftest pins up). Guards VERDICT round-1 weak #3: multi-chip correctness
must be exercised by tests, on the batch/sublane axis, with uneven batches.
"""

import numpy as np
import pytest

import jax

from tendermint_tpu.crypto import ed25519 as host
from tendermint_tpu.crypto.ed25519_jax.sharded import batch_verify_sharded, make_mesh


def _signed(n, seed=0):
    rng = np.random.default_rng(seed)
    pks, msgs, sigs = [], [], []
    for _ in range(n):
        sd = rng.bytes(32)
        pk = host.pubkey_from_seed(sd)
        msg = rng.bytes(24)
        pks.append(pk)
        msgs.append(msg)
        sigs.append(host.sign(sd + pk, msg))
    return pks, msgs, sigs


def test_eight_virtual_devices_present():
    assert len(jax.devices()) == 8
    assert jax.default_backend() == "cpu"


@pytest.mark.parametrize("n_devices", [2, 8])
def test_sharded_verify_matches_host(n_devices):
    # uneven batch: 37 does not divide the mesh or the lane width
    pks, msgs, sigs = _signed(37, seed=n_devices)
    sigs[5] = bytes([sigs[5][0] ^ 1]) + sigs[5][1:]  # corrupt one
    powers = list(range(1, 38))
    mesh = make_mesh(n_devices)
    verdict, total = batch_verify_sharded(pks, msgs, sigs, powers=powers, mesh=mesh)
    want = np.array(
        [host.verify(p, m, s) for p, m, s in zip(pks, msgs, sigs)], dtype=bool
    )
    assert (verdict == want).all()
    assert total == sum(pw for pw, okk in zip(powers, want) if okk)


def test_sharded_mesh_sizes_agree():
    """Same batch over 2- and 4-device meshes -> identical verdicts."""
    pks, msgs, sigs = _signed(20, seed=9)
    sigs[3] = sigs[3][:-1] + bytes([sigs[3][-1] ^ 0x40])
    v2, t2 = batch_verify_sharded(pks, msgs, sigs, mesh=make_mesh(2))
    v4, t4 = batch_verify_sharded(pks, msgs, sigs, mesh=make_mesh(4))
    assert (v2 == v4).all()
    assert t2 == t4 == int(v2.sum())


def test_make_mesh_too_many_devices_raises():
    with pytest.raises(RuntimeError, match="need 16 devices"):
        make_mesh(16)
