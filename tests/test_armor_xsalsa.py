"""Legacy key-file crypto (reference crypto/armor/armor.go,
crypto/xsalsa20symmetric/symmetric.go): primitive KATs + armor framing +
the encrypted-key round trip."""

import pytest

from tendermint_tpu.crypto import armor, xsalsa20


def test_poly1305_rfc8439_vector():
    key = bytes.fromhex("85d6be7857556d337f4452fe42d506a8"
                        "0103808afb0db2fd4abff6af4149f51b")
    tag = xsalsa20.poly1305(key, b"Cryptographic Forum Research Group")
    assert tag.hex() == "a8061dc1305136c6c22b8baf0c0127a9"


def test_secretbox_nacl_vector():
    """The canonical NaCl secretbox test vector (tests/secretbox.c):
    reproducing its ciphertext pins XSalsa20 (HSalsa20 subkey + Salsa20
    stream) AND the poly1305-over-first-32-stream-bytes layout."""
    k = bytes.fromhex("1b27556473e985d462cd51197a9a46c7"
                      "6009549eac6474f206c4ee0844f68389")
    nonce = bytes.fromhex("69696ee955b62b73cd62bda875fc73d68219e0036b7a0b37")
    m = bytes.fromhex(
        "be075fc53c81f2d5cf141316ebeb0c7b5228c52a4c62cbd44b66849b64244ffc"
        "e5ecbaaf33bd751a1ac728d45e6c61296cdc3c01233561f41db66cce314adb31"
        "0e3be8250c46f06dceea3a7fa1348057e2f6556ad6b1318a024a838f21af1fde"
        "048977eb48f59ffd4924ca1c60902e52f0a089bc76897040e082f93776384864"
        "5e0705")
    c = xsalsa20.secretbox_seal(m, nonce, k)
    assert c[:32].hex() == ("f3ffc7703f9400e52a7dfb4b3d3305d9"
                            "8e993b9f48681273c29650ba32fc76ce")
    assert xsalsa20.secretbox_open(c, nonce, k) == m
    bad = bytearray(c)
    bad[40] ^= 1
    assert xsalsa20.secretbox_open(bytes(bad), nonce, k) is None


def test_symmetric_seam_matches_reference_shape():
    secret = bytes(range(32))
    ct = xsalsa20.encrypt_symmetric(b"legacy key bytes", secret)
    # nonce(24) + overhead(16) + len(pt), like symmetric.go documents
    assert len(ct) == 24 + 16 + len(b"legacy key bytes")
    assert xsalsa20.decrypt_symmetric(ct, secret) == b"legacy key bytes"
    with pytest.raises(ValueError):
        xsalsa20.decrypt_symmetric(ct[:30], secret)
    with pytest.raises(ValueError):
        xsalsa20.decrypt_symmetric(ct, bytes(31))
    wrong = bytes(reversed(range(32)))
    with pytest.raises(ValueError):
        xsalsa20.decrypt_symmetric(ct, wrong)


def test_armor_round_trip_and_framing():
    data = bytes(range(200))
    s = armor.encode_armor("TEST BLOCK", {"Version": "1", "Alg": "x"}, data)
    assert s.startswith("-----BEGIN TEST BLOCK-----\n")
    assert "-----END TEST BLOCK-----" in s
    assert max(len(ln) for ln in s.splitlines()) <= 64 + 12
    bt, headers, out = armor.decode_armor(s)
    assert bt == "TEST BLOCK" and out == data
    assert headers == {"Version": "1", "Alg": "x"}

    # checksum protects the body
    lines = s.splitlines()
    body_idx = next(i for i, ln in enumerate(lines)
                    if ln and not ln.startswith("-") and ":" not in ln)
    corrupted = list(lines)
    corrupted[body_idx] = ("B" + corrupted[body_idx][1:]
                           if corrupted[body_idx][0] != "B"
                           else "C" + corrupted[body_idx][1:])
    with pytest.raises(ValueError, match="CRC24|body"):
        armor.decode_armor("\n".join(corrupted))
    with pytest.raises(ValueError, match="BEGIN"):
        armor.decode_armor("not armor at all")


def test_encrypted_privkey_round_trip():
    priv = bytes(range(64))
    s = armor.encrypt_armor_priv_key(priv, "hunter2", key_type="ed25519")
    assert "TENDERMINT PRIVATE KEY" in s and "salt" in s.lower()
    out, ktype = armor.unarmor_decrypt_priv_key(s, "hunter2")
    assert out == priv and ktype == "ed25519"
    with pytest.raises(ValueError, match="passphrase"):
        armor.unarmor_decrypt_priv_key(s, "wrong")


def test_xchacha20poly1305_hchacha_vector_and_aead():
    """(reference crypto/xchacha20poly1305) HChaCha20 pinned to
    draft-irtf-cfrg-xchacha §2.2.1 (prefix independently recalled, full
    value computed from the spec implementation), plus AEAD round trip
    with associated data."""
    import os

    import pytest

    pytest.importorskip("cryptography", reason="needs the optional 'cryptography' package (absent in slim containers)")

    from tendermint_tpu.crypto import xchacha20poly1305 as X

    key = bytes.fromhex("000102030405060708090a0b0c0d0e0f"
                        "101112131415161718191a1b1c1d1e1f")
    nonce16 = bytes.fromhex("000000090000004a0000000031415927")
    out = X.hchacha20(key, nonce16)
    assert out.hex() == ("82413b4227b27bfed30e42508a877d73"
                         "a0f9e4d58a74a853c12ec41326d3ecdc")

    k, n = os.urandom(32), os.urandom(24)
    ct = X.seal(k, n, b"legacy aead payload", b"hdr")
    assert len(ct) == len(b"legacy aead payload") + X.TAG_SIZE
    assert X.open_(k, n, ct, b"hdr") == b"legacy aead payload"
    assert X.open_(k, n, ct, b"other") is None
    bad = bytearray(ct)
    bad[3] ^= 1
    assert X.open_(k, n, bytes(bad), b"hdr") is None
    with pytest.raises(ValueError):
        X.seal(k, n[:23], b"x")
