"""Byzantine e2e: the ci-adversarial manifest end-to-end (ISSUE 8
acceptance scenario). A maverick validator double-prevotes, one validator
serves corrupted snapshot chunks and flips bits on 10% of its outbound
wire payloads (seeded, bounded), and a fresh node bootstraps via state
sync through that hostility. The run must stay live, honest nodes must
agree on app hash, the double-prevote must surface as committed evidence,
and the victim must have banned the lying chunk server at the statesync
layer (or degraded to the fast-sync-from-genesis fallback — bootstrap
either way, never a fatal wedge).
"""

import base64
import os
import time

import pytest

pytest.importorskip(
    "cryptography",
    reason="the multi-process net's TCP transport needs the optional "
           "'cryptography' package (absent in slim containers)")

from tendermint_tpu.e2e import Manifest, Runner

MANIFESTS = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tendermint_tpu", "e2e", "manifests")


@pytest.mark.slow
def test_manifest_adversarial(tmp_path):
    m = Manifest.load(os.path.join(MANIFESTS, "ci-adversarial.toml"))
    liar = next(n for n in m.nodes if n.faults)
    victim = next(n for n in m.nodes if n.state_sync)
    r = Runner(m, str(tmp_path / "net"), base_port=29220)
    r.setup()
    try:
        r.start()
        # fatten the app state BEFORE the snapshot heights the victim will
        # restore from: >= 8 chunks means the deterministic fetch rotation
        # walks every advertiser, so the victim is guaranteed to meet the
        # liar (and strike it to a ban) instead of dodging it by luck
        pad = "p" * 200
        for i in range(48):
            tx = f"adv{i}={pad}".encode()
            r.rpc_post("validator0", "broadcast_tx_sync",
                       {"tx": base64.b64encode(tx).decode()})
        r.start_fleet_scrape()
        r.start_late_joiners()
        r.wait_all_alive()
        r.load()
        r.wait()
        r.check_heights_agree()
        r.check_app_hashes()       # honest nodes (and the victim) agree
        r.check_txs_everywhere()
        r.check_evidence_committed()

        # the victim survived Byzantine providers: either it banned the
        # liar during restore, or it abandoned state sync for the fast-sync
        # fallback — and in no case died (wait_all_alive above proved that)
        deadline = time.time() + 30
        bans = falls = 0.0
        while time.time() < deadline:
            bans = r.metric_value(
                victim.name, "tendermint_statesync_peer_bans_total")
            falls = r.metric_value(
                victim.name, "tendermint_statesync_fallbacks_total")
            if bans > 0 or falls > 0:
                break
            time.sleep(1.0)
        assert bans > 0 or falls > 0, \
            "victim neither banned the lying peer nor fell back"
        # the liar really injected: its fault counters are on /metrics too
        injected = r.metric_value(
            liar.name, "tendermint_faults_injected_total")
        assert injected > 0, "liar's fault sites never fired"
        # and the bootstrap completed: the victim reached net height
        assert r.height(victim.name) >= 8
    finally:
        r.stop()
