"""ValidatorSet behavior: proposer rotation determinism, updates, and the
batched VerifyCommit{,Light,LightTrusting} variants (reference
types/validator_set.go:107-821).
"""

import pytest

from tendermint_tpu import crypto
from tendermint_tpu.types import (
    BlockID,
    BlockIDFlag,
    PartSetHeader,
    SignedMsgType,
    Validator,
    ValidatorSet,
    ZERO_TIME_NS,
)
from tendermint_tpu.types.block import Commit, CommitSig
from tendermint_tpu.types.errors import (
    ErrInvalidCommitHeight,
    ErrInvalidCommitSignatures,
    ErrNotEnoughVotingPowerSigned,
    ErrWrongSignature,
)
from tendermint_tpu.types.validator import new_validator

CHAIN_ID = "test_chain_id"


def make_vals(n, power=10):
    privs = [crypto.Ed25519PrivKey.generate(bytes([i + 1]) * 32) for i in range(n)]
    vals = [new_validator(p.pub_key(), power) for p in privs]
    by_addr = {p.pub_key().address(): p for p in privs}
    return vals, by_addr


def make_commit(vs: ValidatorSet, privs_by_addr, height=5, round_=0,
                block_id=None, absent=(), nil=(), corrupt=()):
    block_id = block_id or BlockID(b"\x01" * 32, PartSetHeader(1, b"\x02" * 32))
    sigs = []
    ts = 1_700_000_000_000_000_000
    for i, val in enumerate(vs.validators):
        if i in absent:
            sigs.append(CommitSig.new_absent())
            continue
        vote_bid = BlockID() if i in nil else block_id
        flag = BlockIDFlag.NIL if i in nil else BlockIDFlag.COMMIT
        from tendermint_tpu.types.canonical import vote_sign_bytes

        sb = vote_sign_bytes(CHAIN_ID, SignedMsgType.PRECOMMIT, height, round_, vote_bid, ts)
        sig = privs_by_addr[val.address].sign(sb)
        if i in corrupt:
            sig = bytes([sig[0] ^ 1]) + sig[1:]
        sigs.append(CommitSig(flag, val.address, ts, sig))
    return Commit(height, round_, block_id, sigs), block_id


class TestProposerRotation:
    def test_round_robin_equal_power(self):
        vals, _ = make_vals(3)
        vs = ValidatorSet(vals)
        seen = []
        for _ in range(6):
            seen.append(vs.get_proposer().address)
            vs.increment_proposer_priority(1)
        # each validator proposes exactly twice over 2 full rotations
        assert sorted(seen[:3]) == sorted(v.address for v in vs.validators)
        assert seen[:3] == seen[3:6]

    def test_weighted_rotation_frequency(self):
        # powers 1,2,3 → over 60 rounds proposer counts ∝ power
        privs = [crypto.Ed25519PrivKey.generate(bytes([i + 1]) * 32) for i in range(3)]
        vals = [new_validator(p.pub_key(), i + 1) for i, p in enumerate(privs)]
        vs = ValidatorSet(vals)
        counts = {}
        for _ in range(60):
            a = vs.get_proposer().address
            counts[a] = counts.get(a, 0) + 1
            vs.increment_proposer_priority(1)
        by_power = {v.address: v.voting_power for v in vs.validators}
        got = sorted(counts.values())
        assert got == [10, 20, 30], f"{got} vs powers {by_power}"

    def test_deterministic_across_copies(self):
        vals, _ = make_vals(7)
        a, b = ValidatorSet(vals), ValidatorSet(vals)
        for _ in range(20):
            assert a.get_proposer().address == b.get_proposer().address
            a.increment_proposer_priority(1)
            b.increment_proposer_priority(1)

    def test_sorted_by_power_then_address(self):
        privs = [crypto.Ed25519PrivKey.generate(bytes([i + 1]) * 32) for i in range(5)]
        vals = [new_validator(p.pub_key(), [5, 1, 5, 3, 2][i]) for i, p in enumerate(privs)]
        vs = ValidatorSet(vals)
        powers = [v.voting_power for v in vs.validators]
        assert powers == sorted(powers, reverse=True)
        # ties broken by ascending address
        tied = [v.address for v in vs.validators if v.voting_power == 5]
        assert tied == sorted(tied)


class TestUpdates:
    def test_add_update_remove(self):
        vals, _ = make_vals(3)
        vs = ValidatorSet(vals)
        newp = crypto.Ed25519PrivKey.generate(b"\x77" * 32)
        vs.update_with_change_set([new_validator(newp.pub_key(), 42)])
        assert vs.size() == 4
        assert vs.total_voting_power() == 72
        # update power
        vs.update_with_change_set([new_validator(newp.pub_key(), 1)])
        assert vs.total_voting_power() == 31
        # remove
        vs.update_with_change_set([new_validator(newp.pub_key(), 0)])
        assert vs.size() == 3

    def test_remove_unknown_fails(self):
        vals, _ = make_vals(3)
        vs = ValidatorSet(vals)
        ghost = crypto.Ed25519PrivKey.generate(b"\x66" * 32)
        with pytest.raises(ValueError, match="failed to find validator"):
            vs.update_with_change_set([new_validator(ghost.pub_key(), 0)])

    def test_duplicate_changes_fail(self):
        vals, _ = make_vals(3)
        vs = ValidatorSet(vals)
        p = crypto.Ed25519PrivKey.generate(b"\x55" * 32)
        with pytest.raises(ValueError, match="duplicate"):
            vs.update_with_change_set([new_validator(p.pub_key(), 5),
                                       new_validator(p.pub_key(), 6)])

    def test_empty_set_forbidden(self):
        vals, _ = make_vals(1)
        vs = ValidatorSet(vals)
        with pytest.raises(ValueError, match="empty set"):
            vs.update_with_change_set([new_validator(vals[0].pub_key, 0)])


class TestVerifyCommit:
    def test_all_good(self):
        vals, privs = make_vals(10)
        vs = ValidatorSet(vals)
        commit, bid = make_commit(vs, privs)
        vs.verify_commit(CHAIN_ID, bid, 5, commit)
        vs.verify_commit_light(CHAIN_ID, bid, 5, commit)
        vs.verify_commit_light_trusting(CHAIN_ID, commit, (1, 3))

    def test_some_absent_ok(self):
        vals, privs = make_vals(10)
        vs = ValidatorSet(vals)
        commit, bid = make_commit(vs, privs, absent=(1, 2))
        vs.verify_commit(CHAIN_ID, bid, 5, commit)

    def test_nil_votes_verified_but_not_tallied(self):
        vals, privs = make_vals(4)
        vs = ValidatorSet(vals)
        # 3/4 for block (30 > 2/3*40=26.6), one nil — still passes
        commit, bid = make_commit(vs, privs, nil=(3,))
        vs.verify_commit(CHAIN_ID, bid, 5, commit)
        # 2/4 for block → 20 <= 26 fails
        commit, bid = make_commit(vs, privs, nil=(2, 3))
        with pytest.raises(ErrNotEnoughVotingPowerSigned):
            vs.verify_commit(CHAIN_ID, bid, 5, commit)

    def test_corrupt_sig_error_precedence(self):
        vals, privs = make_vals(6)
        vs = ValidatorSet(vals)
        commit, bid = make_commit(vs, privs, corrupt=(4, 2))
        with pytest.raises(ErrWrongSignature) as ei:
            vs.verify_commit(CHAIN_ID, bid, 5, commit)
        assert ei.value.idx == 2  # first bad index wins (validator_set.go:697)

    def test_corrupt_nil_vote_fails_full_but_not_light(self):
        # a bad signature on a nil vote fails VerifyCommit (checks all) but
        # not VerifyCommitLight (skips non-ForBlock) — reference semantics.
        vals, privs = make_vals(5)
        vs = ValidatorSet(vals)
        commit, bid = make_commit(vs, privs, nil=(4,), corrupt=(4,))
        with pytest.raises(ErrWrongSignature):
            vs.verify_commit(CHAIN_ID, bid, 5, commit)
        vs.verify_commit_light(CHAIN_ID, bid, 5, commit)

    def test_light_ignores_bad_sig_after_quorum(self):
        # Light exits at 2/3; a corrupt sig positioned after the quorum point
        # must NOT fail it (validator_set.go:760-768 early return).
        vals, privs = make_vals(10)
        vs = ValidatorSet(vals)
        commit, bid = make_commit(vs, privs, corrupt=(9,))
        vs.verify_commit_light(CHAIN_ID, bid, 5, commit)
        with pytest.raises(ErrWrongSignature):
            vs.verify_commit(CHAIN_ID, bid, 5, commit)

    def test_wrong_height(self):
        vals, privs = make_vals(4)
        vs = ValidatorSet(vals)
        commit, bid = make_commit(vs, privs)
        with pytest.raises(ErrInvalidCommitHeight):
            vs.verify_commit(CHAIN_ID, bid, 6, commit)

    def test_wrong_set_size(self):
        vals, privs = make_vals(4)
        vs = ValidatorSet(vals)
        commit, bid = make_commit(vs, privs)
        commit.signatures.append(CommitSig.new_absent())
        with pytest.raises(ErrInvalidCommitSignatures):
            vs.verify_commit(CHAIN_ID, bid, 5, commit)

    def test_trusting_subset(self):
        # trusted set = subset of signers; 1/3 of trusted power must sign
        vals, privs = make_vals(6)
        full = ValidatorSet(vals)
        commit, bid = make_commit(full, privs)
        trusted = ValidatorSet(vals[:3])
        trusted.verify_commit_light_trusting(CHAIN_ID, commit, (1, 3))

    def test_trusting_insufficient(self):
        vals, privs = make_vals(6)
        full = ValidatorSet(vals)
        commit, bid = make_commit(full, privs, absent=(0, 1, 2))
        trusted = ValidatorSet(vals[:3])  # none of the trusted signed
        with pytest.raises(ErrNotEnoughVotingPowerSigned):
            trusted.verify_commit_light_trusting(CHAIN_ID, commit, (1, 3))


def test_from_existing_preserves_proposer_rotation():
    """(validator_set.go ValidatorSetFromExistingValidators) rebuilding a set
    from live RPC data must NOT re-run NewValidatorSet's increment — the
    statesync e2e manifest caught a synced node disagreeing about every
    proposer and rejecting all proposals."""
    vals, _ = make_vals(5, power=10)
    # unequal powers so rotation is non-trivial
    for i, v in enumerate(vals):
        v.voting_power = 10 + i
    vs = ValidatorSet(vals)
    for _ in range(7):
        vs.increment_proposer_priority(1)
    rebuilt = ValidatorSet.from_existing(
        [v.copy() for v in vs.validators])
    assert rebuilt.get_proposer().address == vs.get_proposer().address
    # and the NEXT rotations agree too
    a, b = vs.copy(), rebuilt.copy()
    for _ in range(10):
        a.increment_proposer_priority(1)
        b.increment_proposer_priority(1)
        assert a.get_proposer().address == b.get_proposer().address
    # the plain constructor (NewValidatorSet) is NOT rotation-preserving
    fresh = ValidatorSet([v.copy() for v in vs.validators])
    assert [v.proposer_priority for v in fresh.validators] != \
        [v.proposer_priority for v in vs.validators]


class TestAddrIndexInvalidation:
    """_addr_index/hash memo staleness (advisor finding at
    validator_set.py:105): the caches must invalidate on the structural
    mutation COUNTER, not just list identity/length, because an in-place
    mutation that preserves both would otherwise serve stale indices into
    commit verification."""

    def test_identity_and_length_preserving_mutation_invalidates(self):
        vals, _ = make_vals(4)
        vs = ValidatorSet(vals)
        # warm both memos
        for v in vs.validators:
            assert vs.get_by_address(v.address)[0] >= 0
        h0 = vs.hash()
        # an in-place reorder that preserves list identity AND length —
        # the exact mutation class the identity/length check misses. Any
        # future structural mutator must pair its mutation with
        # _bump_mutations(); this asserts the memos honor the counter.
        vs.validators.reverse()
        vs._bump_mutations()
        for i, v in enumerate(vs.validators):
            idx, got = vs.get_by_address(v.address)
            assert idx == i, "stale _addr_index after in-place reorder"
            assert got.address == v.address
        assert vs.hash() != h0 or len(vs.validators) == 1

    def test_update_with_change_set_reorders_index_correctly(self):
        # a power update that FLIPS sort order must leave fresh indices
        vals, _ = make_vals(3, power=10)
        vs = ValidatorSet(vals)
        for v in vs.validators:
            vs.get_by_address(v.address)  # warm
        last = vs.validators[-1]
        vs.update_with_change_set([new_validator(last.pub_key, 99)])
        assert vs.validators[0].address == last.address  # power desc
        for i, v in enumerate(vs.validators):
            assert vs.get_by_address(v.address)[0] == i

    def test_priority_rotation_keeps_cache(self):
        # proposer-priority rotation mutates Validator objects only — the
        # memoized index dict must be REUSED (the perf property the memo
        # exists for), and stay correct
        vals, _ = make_vals(5)
        vs = ValidatorSet(vals)
        idx0 = vs._addr_index()
        vs.increment_proposer_priority(3)
        assert vs._addr_index() is idx0
        for i, v in enumerate(vs.validators):
            assert vs.get_by_address(v.address)[0] == i

    def test_copy_propagates_hash_and_stays_fresh(self):
        vals, _ = make_vals(3)
        vs = ValidatorSet(vals)
        h0 = vs.hash()
        c = vs.copy()
        assert c.hash() == h0
        # mutating the copy must not poison the original (and vice versa)
        newp = crypto.Ed25519PrivKey.generate(b"\x44" * 32)
        c.update_with_change_set([new_validator(newp.pub_key(), 7)])
        assert c.hash() != h0
        assert vs.hash() == h0
        assert c.get_by_address(newp.pub_key().address())[0] >= 0
        assert vs.get_by_address(newp.pub_key().address())[0] == -1
