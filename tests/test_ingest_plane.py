"""Ingestion fast path (mempool/ingest.py): signed-tx envelope codec,
tx-side sign-columns, sharded per-sender lanes, MempoolWAL replay through
the lanes, async admission control with reason-labeled shedding, and the
differential contract — batched pre-verification accept/reject is
byte-identical to the scalar CheckTx path, with device failures degrading
through the existing breaker to host fallback with zero lost txs."""

import asyncio
import hashlib

import pytest

from tendermint_tpu import crypto
from tendermint_tpu.abci import types as abci
from tendermint_tpu.abci.client import LocalClient
from tendermint_tpu.abci.example.kvstore import KVStoreApplication
from tendermint_tpu.libs.metrics import MempoolMetrics, Registry
from tendermint_tpu.libs.txlife import STAGES, TxLifecycle
from tendermint_tpu.mempool.clist_mempool import (
    ErrTxInCache,
    MempoolError,
    init_mempool_wal,
)
from tendermint_tpu.mempool.ingest import (
    MALFORMED,
    SIGNED,
    UNSIGNED,
    IngestPipeline,
    ShardedMempool,
    make_signed_tx,
    parse_signed_tx,
    replay_mempool_wal,
    tx_fee,
    tx_sender,
    verify_signed_tx_scalar,
)

KEYS = [crypto.Ed25519PrivKey.generate(bytes([0x40 + i]) * 32)
        for i in range(4)]


def _mk(**kw):
    kw.setdefault("lanes", 4)
    return ShardedMempool(LocalClient(KVStoreApplication()), **kw)


def _flip_sig(tx: bytes) -> bytes:
    return tx[:-1] + bytes([tx[-1] ^ 1])


# --- signed-tx envelope ------------------------------------------------------

class TestEnvelope:
    def test_roundtrip(self):
        tx = make_signed_tx(KEYS[0], b"k=v", nonce=9, fee=42)
        status, stx = parse_signed_tx(tx)
        assert status == SIGNED
        assert stx.pubkey == KEYS[0].pub_key().bytes()
        assert (stx.fee, stx.nonce, stx.payload) == (42, 9, b"k=v")
        assert stx.sign_bytes == tx[:-64] and stx.sig == tx[-64:]
        assert tx_fee(tx) == 42
        assert tx_sender(tx) == KEYS[0].pub_key().bytes().hex()

    def test_classification(self):
        assert parse_signed_tx(b"a=1")[0] == UNSIGNED
        assert parse_signed_tx(b"stx1-too-short")[0] == MALFORMED
        assert parse_signed_tx(b"stx1" + b"\x00" * 111)[0] == MALFORMED
        assert parse_signed_tx(b"stx1" + b"\x00" * 112)[0] == SIGNED

    def test_scalar_verdicts(self):
        good = make_signed_tx(KEYS[0], b"payload", 1, 5)
        assert verify_signed_tx_scalar(good) == (True, "sig")
        assert verify_signed_tx_scalar(_flip_sig(good)) == (False, "sig")
        assert verify_signed_tx_scalar(b"plain") == (True, UNSIGNED)
        assert verify_signed_tx_scalar(b"stx1oops") == (False, MALFORMED)

    def test_unsigned_txs_hash_shard(self):
        # every unsigned tx is its own "sender": per-sender controls can
        # never collapse foreign-format traffic onto one bucket
        assert tx_sender(b"a=1") != tx_sender(b"b=2")


# --- tx-side sign columns ----------------------------------------------------

class TestTxSignColumns:
    def test_reconstructs_byte_identical(self):
        from tendermint_tpu.crypto.signcols import sign_columns_from_rows

        rows = [make_signed_tx(KEYS[i % 2], b"p" * 16, nonce=i,
                               fee=3)[:-64] for i in range(8)]
        cols = sign_columns_from_rows(rows)
        assert cols is not None and len(cols) == 8
        assert cols.rows() == rows
        assert [cols[i] for i in range(8)] == rows
        # nonce bytes vary; the shared magic/fee prefix does not
        assert 0 < cols.cols.shape[0] < len(rows[0]) // 2

    def test_guards(self):
        from tendermint_tpu.crypto.signcols import sign_columns_from_rows

        assert sign_columns_from_rows([b"one"]) is None  # too few
        assert sign_columns_from_rows([b"aa", b"abc"]) is None  # ragged
        import os

        dense = [os.urandom(32) for _ in range(4)]  # no shared structure
        assert sign_columns_from_rows(dense) is None


# --- sharded lanes -----------------------------------------------------------

class TestShardedLanes:
    def test_lane_keying_is_deterministic_per_sender(self):
        mp = _mk()
        a1 = make_signed_tx(KEYS[0], b"a", 1, 0)
        a2 = make_signed_tx(KEYS[0], b"b", 2, 0)
        b1 = make_signed_tx(KEYS[1], b"c", 1, 0)
        assert mp.lane_for(a1) == mp.lane_for(a2)  # same sender, same lane
        for tx in (a1, a2, b1):
            assert mp.check_tx(tx).is_ok()
        assert sum(mp.lane_depths()) == 3
        assert mp.lane_depths()[mp.lane_for(a1)] >= 2

    def test_entries_after_global_admission_order(self):
        mp = _mk()
        txs = [make_signed_tx(KEYS[i % 4], b"x", i, 0) for i in range(8)]
        for tx in txs:
            mp.check_tx(tx)
        entries, cursor = mp.entries_after(0)
        assert cursor == 8
        assert [e.tx for e in entries] == txs  # seq order across lanes
        tail, _ = mp.entries_after(6)
        assert [e.tx for e in tail] == txs[6:]

    def test_dedup_is_global_across_lanes(self):
        mp = _mk()
        tx = make_signed_tx(KEYS[0], b"once", 1, 0)
        assert mp.check_tx(tx, sender="peerA").is_ok()
        with pytest.raises(ErrTxInCache):
            mp.check_tx(tx, sender="peerB")
        entries, _ = mp.entries_after(0)
        assert entries[0].senders == {"peerA", "peerB"}

    def test_depth_gauges_and_bytes(self):
        mp = _mk()
        m = MempoolMetrics(Registry())
        mp.metrics = m
        txs = [b"a=1", b"bb=2", make_signed_tx(KEYS[0], b"x", 1, 0)]
        for tx in txs:
            mp.check_tx(tx)
        assert m.size.value() == 3
        assert m.size_bytes.value() == sum(len(t) for t in txs)
        assert mp.tx_bytes() == sum(len(t) for t in txs)
        mp.flush()
        assert m.size.value() == 0 and mp.size() == 0

    def test_full_rejection_seals_lifecycle_record(self):
        """A capacity rejection AFTER the app accepted must still seal
        the tx's lifecycle record as rejected — never leave it to rot in
        the active map as an eventual 'lost' eviction."""
        mp = _mk(max_txs=1)
        tl = TxLifecycle(sample_rate=1.0)
        mp.txlife = tl
        assert mp.check_tx(b"first=1").is_ok()
        with pytest.raises(MempoolError, match="full"):
            mp.check_tx(b"second=2")
        snap = tl.snapshot(10)
        assert snap["active"] == 1  # only the admitted tx's live record
        sealed = {r["key"]: r for r in snap["records"]}
        k2 = hashlib.sha256(b"second=2").digest().hex()
        assert sealed[k2]["terminal"] == "rejected"

    def test_recheck_reuses_preverification_verdicts(self):
        """Lane-local recheck re-runs the app only: the cached signature
        verdict stands, counted on preverify_cache_hits_total{recheck}."""
        mp = _mk()
        m = MempoolMetrics(Registry())
        mp.metrics = m
        signed = [make_signed_tx(KEYS[i], b"keep", i, 0) for i in range(3)]
        for tx in signed:
            assert mp.check_tx(tx).is_ok()
        assert m.preverified_txs_total.value("scalar") == 3
        mp.lock()
        try:
            mp.update(2, [signed[0]], [abci.ResponseCheckTx(code=0)])
        finally:
            mp.unlock()
        assert mp.size() == 2
        # both survivors recheck against the app, zero new sig verifies
        assert m.preverify_cache_hits_total.value("recheck") == 2
        assert m.preverified_txs_total.value("scalar") == 3


# --- MempoolWAL replay through the lanes ------------------------------------

class TestWALReplay:
    def test_crash_replay_repopulates_lanes_no_dup_admits(self, tmp_path):
        wal_dir = str(tmp_path / "mpwal")
        mp = _mk()
        init_mempool_wal(mp, wal_dir)
        txs = [make_signed_tx(KEYS[i % 4], b"w", i, i) for i in range(6)]
        txs.append(b"plain=tx")
        for tx in txs:
            assert mp.check_tx(tx).is_ok()
        mp._wal.close()  # crash

        fresh = _mk()  # the restarted node's empty lanes
        replayed, skipped = replay_mempool_wal(fresh, wal_dir)
        assert (replayed, skipped) == (7, 0)
        assert fresh.size() == 7
        assert sorted(t.tx for t, in zip(fresh.entries_after(0)[0])) == \
            sorted(txs)
        # lane placement re-derives deterministically
        assert fresh.lane_depths() == mp.lane_depths()
        # replay is idempotent: a second pass admits nothing new
        replayed2, skipped2 = replay_mempool_wal(fresh, wal_dir)
        assert (replayed2, skipped2) == (0, 7)
        assert fresh.size() == 7
        # and replay never re-appends to the log it reads
        lines = open(f"{wal_dir}/wal", "rb").read().splitlines()
        assert len(lines) == 7


# --- async admission control -------------------------------------------------

def _run(coro):
    return asyncio.run(coro)


class TestAdmissionControl:
    def test_queue_full_shed(self):
        async def main():
            mp = _mk()
            m = MempoolMetrics(Registry())
            mp.metrics = m
            pipe = IngestPipeline(mp, batch_deadline_s=0.2, queue_limit=3)
            pipe.metrics = m
            # 3 fill the intake; the 4th sheds with an explicit reason
            futs = [asyncio.ensure_future(pipe.submit(b"q%d=1" % i))
                    for i in range(3)]
            await asyncio.sleep(0)
            shed = await pipe.submit(b"q3=1")
            assert shed.code == 1 and "queue-full" in shed.log
            assert shed.codespace == "ingest"
            assert m.shed_txs_total.value("queue-full") == 1
            await pipe.flush_now()
            assert all(r.is_ok() for r in await asyncio.gather(*futs))
            assert mp.size() == 3
            assert pipe.queue_depth() == 0

        _run(main())

    def test_fee_floor_shed(self):
        async def main():
            mp = _mk()
            pipe = IngestPipeline(mp, batch_deadline_s=0.01, queue_limit=64,
                                  fee_floor=10)
            cheap = make_signed_tx(KEYS[0], b"c", 1, fee=3)
            rich = make_signed_tx(KEYS[0], b"r", 2, fee=10)
            r1 = await pipe.submit(cheap)
            assert r1.code == 1 and "fee-floor" in r1.log
            assert (await pipe.submit(rich)).is_ok()
            # unsigned txs carry fee 0: a fee floor gates them out too
            r3 = await pipe.submit(b"plain")
            assert "fee-floor" in r3.log
            assert pipe.stats["shed_fee-floor"] == 2

        _run(main())

    def test_per_sender_rate_shed(self):
        async def main():
            mp = _mk()
            pipe = IngestPipeline(mp, batch_deadline_s=0.01, queue_limit=64,
                                  per_sender_rate=2.0)
            spam = [make_signed_tx(KEYS[0], b"s", i, 0) for i in range(5)]
            results = [await pipe.submit(tx) for tx in spam]
            sheds = [r for r in results if "sender-rate" in r.log]
            assert len(sheds) == 3  # burst of 2, then throttled
            # an unrelated sender is untouched
            ok = await pipe.submit(make_signed_tx(KEYS[1], b"o", 1, 0))
            assert ok.is_ok()

        _run(main())

    def test_shed_discards_txlife_phantom(self):
        async def main():
            mp = _mk()
            tl = TxLifecycle(sample_rate=1.0)
            mp.txlife = tl
            pipe = IngestPipeline(mp, batch_deadline_s=0.2, queue_limit=1)
            raw0, raw1 = b"keep=1", b"shed=1"
            for raw in (raw0, raw1):
                tl.mark(hashlib.sha256(raw).digest(), "rpc_received")
            fut = asyncio.ensure_future(pipe.submit(raw0))
            await asyncio.sleep(0)
            shed = await pipe.submit(raw1)
            assert shed.code == 1
            await pipe.flush_now()
            assert (await fut).is_ok()
            snap = tl.snapshot(10)
            # the shed tx's front-door phantom is gone, not "lost"
            assert snap["active"] == 1  # only the admitted tx's record
            assert all(r["terminal"] != "lost" for r in snap["records"])

        _run(main())


# --- batched pre-verification: the differential contract ---------------------

def _mixed_batch():
    """valid / bad-sig / malformed / unsigned / duplicate — every
    classification the pre-verifier can meet, in one arrival order."""
    good = [make_signed_tx(KEYS[i % 4], b"p%d" % i, i, i % 3)
            for i in range(6)]
    bad = [_flip_sig(make_signed_tx(KEYS[0], b"evil%d" % i, 100 + i, 0))
           for i in range(2)]
    malformed = [b"stx1short", b"stx1" + b"\x01" * 60]
    unsigned = [b"u%d=v" % i for i in range(3)]
    return good + bad + malformed + unsigned + [good[0]]  # trailing dup


class TestDifferential:
    def test_batched_accept_reject_matches_scalar(self):
        batch = _mixed_batch()

        # SCALAR reference: the inline ShardedMempool path
        scalar = _mk()
        scalar_out = []
        for tx in batch:
            try:
                scalar_out.append(scalar.check_tx(tx).is_ok())
            except (ErrTxInCache, MempoolError):
                scalar_out.append(False)

        # BATCHED: the same arrivals through one pipeline micro-batch
        async def main():
            mp = _mk()
            pipe = IngestPipeline(mp, batch_max=len(batch) + 1,
                                  batch_deadline_s=5.0, queue_limit=256)
            futs = [asyncio.ensure_future(pipe.submit(tx)) for tx in batch]
            await asyncio.sleep(0)
            await pipe.flush_now()
            return [(await f).is_ok() for f in futs], mp

        batched_out, mp = _run(main())
        assert batched_out == scalar_out
        assert pipe_contents(mp) == pipe_contents(scalar)
        assert _run_stats_sigs(batch) > 0

    def test_breaker_degrades_device_to_host_zero_lost_txs(self):
        """A sick device (armed device.batch_verify chaos site) degrades
        through the existing breaker to host fallback: verdicts stay
        byte-identical, every accepted tx is admitted, the breaker saw
        the failures."""
        from tendermint_tpu.crypto.batch import BatchVerifier, stats
        from tendermint_tpu.crypto.breaker import device_breaker
        from tendermint_tpu.libs.faults import faults

        batch = _mixed_batch()
        scalar = _mk()
        scalar_out = []
        for tx in batch:
            try:
                scalar_out.append(scalar.check_tx(tx).is_ok())
            except (ErrTxInCache, MempoolError):
                scalar_out.append(False)

        faults.configure("device.batch_verify@1.0", seed=7)
        errors_before = stats["device_errors"]

        async def main():
            mp = _mk()
            pipe = IngestPipeline(
                mp, batch_max=len(batch) + 1, batch_deadline_s=5.0,
                queue_limit=256,
                verifier_factory=lambda: BatchVerifier(backend="jax",
                                                       plane="ingest"))
            futs = [asyncio.ensure_future(pipe.submit(tx)) for tx in batch]
            await asyncio.sleep(0)
            await pipe.flush_now()
            return [(await f).is_ok() for f in futs], mp

        try:
            batched_out, mp = _run(main())
        finally:
            faults.reset()
        assert batched_out == scalar_out  # byte-identical under failure
        assert pipe_contents(mp) == pipe_contents(scalar)  # zero lost txs
        assert stats["device_errors"] > errors_before
        assert device_breaker.stats["failures"] > 0

    def test_verdict_cache_spares_resubmission(self):
        async def main():
            mp = _mk()
            m = MempoolMetrics(Registry())
            mp.metrics = m
            pipe = IngestPipeline(mp, batch_deadline_s=0.002, queue_limit=64)
            pipe.metrics = m
            tx = make_signed_tx(KEYS[2], b"cached", 1, 0)
            assert (await pipe.submit(tx)).is_ok()
            dup = await pipe.submit(tx)  # same tx again: cache verdict
            assert dup.code == 1 and "cache" in dup.log
            assert m.preverify_cache_hits_total.value("batch") == 1

        _run(main())

    def test_txlife_preverified_stage(self):
        assert "preverified" in STAGES
        assert STAGES.index("preverified") == STAGES.index("rpc_received") + 1

        async def main():
            mp = _mk()
            tl = TxLifecycle(sample_rate=1.0)
            mp.txlife = tl
            pipe = IngestPipeline(mp, batch_deadline_s=0.002, queue_limit=64)
            good = make_signed_tx(KEYS[1], b"ok", 1, 0)
            bad = _flip_sig(make_signed_tx(KEYS[1], b"no", 2, 0))
            for raw in (good, bad):
                tl.mark(hashlib.sha256(raw).digest(), "rpc_received")
            r_good = await pipe.submit(good)
            r_bad = await pipe.submit(bad)
            assert r_good.is_ok() and r_bad.code == 1
            recs = {r["key"]: r for r in tl.snapshot(10)["records"]}
            bad_rec = recs[hashlib.sha256(bad).digest().hex()]
            assert bad_rec["terminal"] == "rejected"
            assert [m[0] for m in bad_rec["marks"]] == \
                ["rpc_received", "preverified"]
            # the admitted tx's live record carries the full front chain
            active_stages = [m[0] for m in tl._active[
                hashlib.sha256(good).digest()]["marks"]]
            assert active_stages == ["rpc_received", "preverified",
                                     "checktx_done", "mempool_admitted"]

        _run(main())


def test_signed_txs_through_pipeline_commit_on_a_live_net():
    """End to end: signed envelope txs → async pipeline (one micro-batch,
    one BatchVerifier call) → sharded lanes → gossip → every node commits
    them in hash-identical blocks. The non-RPC nodes run the plain CList:
    the two mempools interoperate on the same wire."""
    import sys as _sys

    _sys.path.insert(0, "tests")
    from test_consensus_net import make_net, wait_all_height

    from tendermint_tpu.p2p import InProcNetwork

    txs = [make_signed_tx(KEYS[i % 3], b"k%d=v" % i, i, fee=i % 5)
           for i in range(12)]

    async def run():
        nodes = make_net(4)
        sm = ShardedMempool(nodes[0].conns.mempool, lanes=4)
        nodes[0].mempool = sm
        nodes[0].block_exec.mempool = sm
        nodes[0].mp_reactor.mempool = sm
        sm.tx_available_callbacks.append(nodes[0].cs.notify_txs_available)
        pipe = IngestPipeline(sm, batch_deadline_s=0.01, queue_limit=128)
        net = InProcNetwork()
        for nd in nodes:
            net.add_switch(nd.switch)
        for nd in nodes:
            await nd.start()
        await net.connect_all()
        try:
            await wait_all_height(nodes, 2, timeout=60)
            results = await asyncio.gather(*(pipe.submit(tx) for tx in txs))
            assert all(r.is_ok() for r in results)
            assert pipe.stats["batched_sigs"] == 12
            h0 = nodes[0].cs.state.last_block_height
            await wait_all_height(nodes, h0 + 3, timeout=60)
        finally:
            await pipe.stop()
            for nd in nodes:
                await nd.stop()
        committed = set()
        store = nodes[1].block_store  # a NON-ingesting node: gossip proof
        for h in range(1, store.height() + 1):
            for tx in store.load_block(h).data.txs:
                committed.add(bytes(tx))
        assert not [t for t in txs if t not in committed], \
            "signed txs never committed"
        hashes = {nd.block_store.load_block_meta(2).header.hash()
                  for nd in nodes}
        assert len(hashes) == 1

    _run(run())


def pipe_contents(mp) -> set:
    entries, _ = mp.entries_after(0)
    return {e.tx for e in entries}


def _run_stats_sigs(batch) -> int:
    # sanity: the mixed batch really contains signature work
    return sum(1 for tx in batch if parse_signed_tx(tx)[0] == SIGNED)
