"""SQL event sink (reference state/indexer/sink/psql) — schema-parity
writes plus the reference's read predicates, against sqlite."""

import hashlib

from tendermint_tpu.state.sink import SQLEventSink
from tendermint_tpu.state.txindex import TxResult


def _tx(height, index, tx, events):
    return TxResult(height=height, index=index, tx=tx, code=0, data=b"",
                    log="", gas_wanted=0, gas_used=0, events=events)


def test_block_and_tx_round_trip():
    sink = SQLEventSink(":memory:", "sink-chain")
    sink.index_block_events(5, {"block.proposer": ["aa"]})
    assert sink.has_block(5) and not sink.has_block(6)

    tx = b"k=v"
    sink.index_tx_events([_tx(5, 0, tx, {"transfer.amount": ["100"],
                                         "transfer.sender": ["alice"]})])
    got = sink.get_tx_by_hash(hashlib.sha256(tx).digest())
    assert got is not None and got.height == 5 and got.tx == tx
    assert sink.get_tx_by_hash(b"\x00" * 32) is None


def test_search_by_composite_key():
    sink = SQLEventSink(":memory:", "sink-chain")
    sink.index_tx_events([
        _tx(1, 0, b"t1", {"transfer.sender": ["alice"]}),
        _tx(1, 1, b"t2", {"transfer.sender": ["bob"]}),
        _tx(2, 0, b"t3", {"transfer.sender": ["alice"]}),
    ])
    hits = sink.search_tx_events("transfer.sender", "alice")
    assert [h.tx for h in hits] == [b"t1", b"t3"]
    assert sink.search_tx_events("transfer.sender", "carol") == []


def test_block_event_search_and_views():
    sink = SQLEventSink(":memory:", "sink-chain")
    for h in (3, 4, 9):
        sink.index_block_events(h, {"rewards.epoch": ["e1" if h < 9 else "e2"]})
    assert sink.search_block_events("rewards.epoch", "e1") == [3, 4]
    # reference schema views exist and join correctly
    rows = sink._conn.execute(
        "SELECT height, composite_key, value FROM block_events "
        "ORDER BY height").fetchall()
    assert (9, "rewards.epoch", "e2") in rows


def test_reindex_is_idempotent():
    sink = SQLEventSink(":memory:", "sink-chain")
    entry = _tx(7, 0, b"dup", {"k.a": ["1"]})
    sink.index_tx_events([entry])
    sink.index_tx_events([entry])  # reindex-event style second pass
    hits = sink.search_tx_events("k.a", "1")
    assert len({(h.height, h.index) for h in hits}) == 1
    assert sink.has_block(7)


def test_txindex_query_seam():
    """sink.search speaks the same query grammar as the kv indexer (the
    /tx_search RPC seam), equality conditions only."""
    import pytest

    sink = SQLEventSink(":memory:", "sink-chain")
    sink.index_tx_events([
        _tx(5, 0, b"a", {"transfer.sender": ["alice"]}),
        _tx(5, 1, b"b", {"transfer.sender": ["bob"]}),
        _tx(6, 0, b"c", {"transfer.sender": ["alice"]}),
    ])
    # implicit tx.height works like kv.go
    hits = sink.search("tx.height=5")
    assert [h.tx for h in hits] == [b"a", b"b"]
    hits = sink.search("tx.height=5 AND transfer.sender='alice'")
    assert [h.tx for h in hits] == [b"a"]
    with pytest.raises(ValueError):
        sink.search("tx.height>4")


def test_psql_indexer_config_accepted():
    import pytest

    from tendermint_tpu.config import Config

    cfg = Config()
    cfg.tx_index.indexer = "psql"
    cfg.validate_basic()
    cfg.tx_index.indexer = "bogus"
    import pytest

    with pytest.raises(ValueError):
        cfg.validate_basic()
