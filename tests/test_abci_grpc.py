"""ABCI over gRPC: the same round-trip matrix as the socket transport
(reference abci/client/grpc_client.go:22, abci/server/grpc_server.go:13).
"""

import pytest

from tendermint_tpu.abci import types as abci
from tendermint_tpu.abci.example.kvstore import (
    KVStoreApplication,
    SnapshotKVStoreApplication,
)
from tendermint_tpu.abci.grpc import ABCIGrpcServer, GrpcClient
from tendermint_tpu.types.block import Consensus, Header


@pytest.fixture
def server_client():
    app = KVStoreApplication()
    srv = ABCIGrpcServer("tcp://127.0.0.1:0", app)
    srv.start()
    client = GrpcClient(f"127.0.0.1:{srv.bound_port}")
    yield app, client
    client.close()
    srv.stop()


def test_echo_info(server_client):
    app, client = server_client
    assert client.echo("ping") == "ping"
    info = client.info(abci.RequestInfo(version="x"))
    assert info.last_block_height == 0
    client.flush()  # no-op RPC must round-trip


def test_deliver_and_commit(server_client):
    app, client = server_client
    res = client.deliver_tx(abci.RequestDeliverTx(tx=b"grpc=ok"))
    assert res.is_ok()
    assert res.events and res.events[0].type == "app"
    commit = client.commit()
    assert commit.data == (1).to_bytes(8, "big")
    assert app.state["grpc"] == "ok"


def test_begin_block_header_crosses_grpc(server_client):
    app, client = server_client
    seen = {}
    orig = app.begin_block

    def spy(req):
        seen["header"] = req.header
        return orig(req)

    app.begin_block = spy
    header = Header(version=Consensus(11, 0), chain_id="grpc-chain", height=9,
                    validators_hash=b"\x01" * 32,
                    proposer_address=b"\x02" * 20)
    client.begin_block(abci.RequestBeginBlock(
        hash=b"\x03" * 32, header=header,
        last_commit_info=abci.LastCommitInfo(round=1, votes=[
            abci.VoteInfo(abci.ABCIValidator(b"\x04" * 20, 10), True)])))
    got = seen["header"]
    assert isinstance(got, Header)
    assert got.chain_id == "grpc-chain" and got.height == 9


def test_query_roundtrip(server_client):
    app, client = server_client
    client.deliver_tx(abci.RequestDeliverTx(tx=b"k=v"))
    res = client.query(abci.RequestQuery(data=b"k", path="/store"))
    assert res.value == b"v" and res.log == "exists"


def test_snapshots_over_grpc():
    app = SnapshotKVStoreApplication(interval=1)
    srv = ABCIGrpcServer("tcp://127.0.0.1:0", app)
    srv.start()
    client = GrpcClient(f"127.0.0.1:{srv.bound_port}")
    try:
        client.deliver_tx(abci.RequestDeliverTx(tx=b"a=1"))
        client.commit()
        snaps = client.list_snapshots(abci.RequestListSnapshots())
        assert snaps.snapshots and snaps.snapshots[0].chunks >= 1
        chunk = client.load_snapshot_chunk(abci.RequestLoadSnapshotChunk(
            height=snaps.snapshots[0].height, format=1, chunk=0))
        assert chunk.chunk
    finally:
        client.close()
        srv.stop()
