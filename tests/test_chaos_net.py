"""Partition-tolerant chaos suite over the in-proc transport: LinkPolicy
determinism units, then the acceptance scenario — a 4-validator net keeps
committing under seeded 10% message loss, survives a partition (minority
stalls, majority continues), and converges with byte-identical block
hashes after the heal. A wider seed × loss matrix runs under -m slow.
"""

import asyncio
import collections
import os
import subprocess
import sys

import pytest

from tendermint_tpu.libs.faults import faults
from tendermint_tpu.p2p import InProcNetwork
from tendermint_tpu.p2p.inproc import LinkPolicy

from test_consensus_net import make_net, wait_all_height


# -- LinkPolicy units --------------------------------------------------------

def test_link_policy_replays_exactly_per_seed():
    plans = [LinkPolicy("a", "b", seed=7, drop_p=0.1, dup_p=0.05,
                        reorder_p=0.1).plan() for _ in range(1)]
    p1 = LinkPolicy("a", "b", seed=7, drop_p=0.1, dup_p=0.05, reorder_p=0.1)
    p2 = LinkPolicy("a", "b", seed=7, drop_p=0.1, dup_p=0.05, reorder_p=0.1)
    assert [p1.plan() for _ in range(500)] == [p2.plan() for _ in range(500)]
    # the directed link is part of the stream key: a→b ≠ b→a, seed matters
    p3 = LinkPolicy("b", "a", seed=7, drop_p=0.1)
    p4 = LinkPolicy("a", "b", seed=8, drop_p=0.1)
    base = [LinkPolicy("a", "b", seed=7, drop_p=0.1).plan()
            for _ in range(500)]
    assert base != [p3.plan() for _ in range(500)]
    assert base != [p4.plan() for _ in range(500)]


def test_link_policy_fates():
    pol = LinkPolicy("a", "b", seed=1, drop_p=0.1, dup_p=0.1, reorder_p=0.1)
    for _ in range(1000):
        pol.plan()
    # seeded, so exact-ish rates; wide bounds guard the wiring, not the RNG
    assert 50 < pol.stats["dropped"] < 200
    assert 50 < pol.stats["duplicated"] < 200
    assert 50 < pol.stats["reordered"] < 250
    assert pol.stats["delivered"] > 700

    blocked = LinkPolicy("a", "b", blocked=True)
    assert blocked.plan() is None and blocked.stats["blackholed"] == 1
    dup = LinkPolicy("a", "b", seed=2, dup_p=1.0)
    assert len(dup.plan()) == 2  # every message twice
    delayed = LinkPolicy("a", "b", seed=3, delay_s=0.5)
    assert delayed.plan() == [0.5]


def test_net_drop_fault_site_blackholes_sends():
    """The env-armed net.drop site rides the same try_send seam as the
    policies — a drop reports success (a lossy wire gives no feedback)."""
    from tendermint_tpu.p2p.inproc import InProcPeer

    async def run():
        a, b = InProcPeer("a", True), InProcPeer("b", False)
        a._remote, b._remote = b, a
        faults.configure("net.drop@0.5", seed=4)
        for i in range(100):
            assert a.try_send(1, b"m%d" % i)
        return b._recv_queue.qsize()

    got = asyncio.run(run())
    assert 20 < got < 80, got  # ~50% dropped, deterministic per seed
    assert faults.fires("net.drop") == 100 - got


# -- self-healing gossip (PeerState stall refresh) ---------------------------

def _peer_state_with_bitmaps():
    from tendermint_tpu.consensus.reactor import PeerState
    from tendermint_tpu.libs.bits import BitArray

    class _P:
        id = "peer0"

    ps = PeerState(_P())
    prs = ps.prs
    prs.height, prs.round = 7, 2
    prs.proposal = True
    prs.proposal_block_parts = BitArray(8)
    prs.proposal_block_parts.set_index(3, True)
    prs.prevotes = BitArray(4)
    prs.prevotes.set_index(1, True)
    prs.precommits = BitArray(4)
    prs.precommits.set_index(2, True)
    return ps


def test_refresh_if_stalled_clears_delivery_bitmaps_keeps_hrs():
    """Gossip marks delivered-on-send; a silent peer's bitmaps are guesses
    that can wedge the link (the post-heal failure mode this PR fixes).
    After the stall window, the bitmaps clear; height/round — which came
    FROM the peer — survive."""
    ps = _peer_state_with_bitmaps()
    ps.last_recv_t -= 10.0  # silent for 10s
    assert ps.refresh_if_stalled(5.0)
    prs = ps.prs
    assert (prs.height, prs.round) == (7, 2)
    assert prs.proposal is False
    assert prs.proposal_block_parts.size() == 8
    assert prs.proposal_block_parts.pick_random()[1] is False  # all clear
    assert prs.prevotes.pick_random()[1] is False
    assert prs.precommits.pick_random()[1] is False
    # one refresh per silent interval: an immediate re-check is a no-op
    prs.prevotes.set_index(0, True)
    assert not ps.refresh_if_stalled(5.0)
    assert prs.prevotes.pick_random()[1] is True


def test_refresh_disabled_or_live_peer_is_noop():
    ps = _peer_state_with_bitmaps()
    ps.last_recv_t -= 10.0
    assert not ps.refresh_if_stalled(0)       # 0 disables
    assert ps.prs.proposal is True
    ps.note_recv()                             # the peer just spoke
    assert not ps.refresh_if_stalled(5.0)
    assert ps.prs.proposal is True


# -- the acceptance scenario -------------------------------------------------

def _common_hash_heights(nodes, height):
    hashes = {nd.block_store.load_block_meta(height).header.hash()
              for nd in nodes}
    return hashes


def test_chaos_liveness_loss_partition_heal():
    """4-node net: ≥5 further heights under seeded 10% drop, then one
    partition/heal cycle, ending with byte-identical hashes everywhere."""
    async def run():
        nodes = make_net(4)
        net = InProcNetwork()
        for nd in nodes:
            net.add_switch(nd.switch)
        for nd in nodes:
            await nd.start()
        await net.connect_all()
        try:
            # healthy warm-up
            await wait_all_height(nodes, 2, timeout=60)
            # seeded 10% loss on every directed link: liveness must hold
            h0 = min(nd.cs.state.last_block_height for nd in nodes)
            net.set_loss(0.10, seed=42)
            await wait_all_height(nodes, h0 + 5, timeout=120)
            assert net.chaos_stats()["dropped"] > 0, \
                "loss policies never dropped anything — chaos not wired"

            # partition one validator off: 3/4 power keeps committing,
            # the minority must NOT advance past what it already has
            lone = nodes[0].switch.node_id
            net.partition([lone])
            h_cut = nodes[0].cs.state.last_block_height
            h_major = min(nd.cs.state.last_block_height for nd in nodes[1:])
            await wait_all_height(nodes[1:], h_major + 2, timeout=120)
            # the blackhole is total: give the minority a beat, then check
            await asyncio.sleep(0.5)
            assert nodes[0].cs.state.last_block_height <= h_cut + 1, \
                "partitioned node advanced through a blackholed cut"

            # heal: the lone node catches up; everyone converges
            net.heal()
            target = max(nd.cs.state.last_block_height for nd in nodes) + 2
            await wait_all_height(nodes, target, timeout=120)
        finally:
            for nd in nodes:
                await nd.stop()
        # byte-identical block hashes (covers app hashes) at a height all
        # nodes share — committed across loss, partition, and heal
        common = min(nd.cs.state.last_block_height for nd in nodes) - 1
        assert common >= 5
        assert len(_common_hash_heights(nodes, common)) == 1
        assert len(_common_hash_heights(nodes, 2)) == 1

    asyncio.run(run())


def test_chaos_matrix_tool_self_test():
    """tools/chaos_matrix.py --self-test exercises the table plumbing plus
    the wal.fsync and db.write_batch cells in-process (CI guard; the full
    sites × seeds sweep is the tool's default invocation)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "chaos_matrix.py"),
         "--self-test"],
        capture_output=True, text=True, timeout=180, cwd=repo, env=env)
    assert r.returncode == 0, r.stderr
    assert "self-test OK" in r.stdout


@pytest.mark.slow
@pytest.mark.parametrize("seed,drop_p", [(1, 0.1), (2, 0.2), (3, 0.1)])
def test_chaos_matrix_seeded_loss(seed, drop_p):
    """Wider seed × loss sweep (the long arm of tools/chaos_matrix.py):
    every seeded schedule must keep the net live and consistent."""
    async def run():
        nodes = make_net(4)
        net = InProcNetwork()
        for nd in nodes:
            net.add_switch(nd.switch)
        for nd in nodes:
            await nd.start()
        await net.connect_all()
        try:
            await wait_all_height(nodes, 2, timeout=60)
            net.set_loss(drop_p, seed=seed, dup_p=0.05, reorder_p=0.05)
            h0 = min(nd.cs.state.last_block_height for nd in nodes)
            await wait_all_height(nodes, h0 + 4, timeout=180)
        finally:
            for nd in nodes:
                await nd.stop()
        stats = net.chaos_stats()
        assert stats["dropped"] > 0 and stats["delivered"] > 0
        common = min(nd.cs.state.last_block_height for nd in nodes) - 1
        assert len(_common_hash_heights(nodes, common)) == 1

    asyncio.run(run())
