"""Tier-1 soak smoke: a real (small) game day through tools/soak.py — a
2-node fleet under continuous signed load with the corruption window armed
— plus the slow-marked 2-seed determinism diff the --verify-determinism
flag runs. The full 8-node multi-plane game day lives in tools/soak.py
--ci and the chaos matrix's soak.gameday cell; tier-1 proves the plane
end-to-end without the wall-clock bill."""

import json
import os

import pytest

from tendermint_tpu.libs.toolbox import load_tool


def test_soak_smoke_two_nodes(tmp_path, monkeypatch):
    # pin what run_soak would setdefault/export so pytest-process env
    # state is restored after the test
    monkeypatch.setenv("TMTPU_BATCH_BACKEND", "host")
    monkeypatch.setenv("TMTPU_SOAK_REPORT", "")
    soak = load_tool("soak")

    out = str(tmp_path / "soak_report.json")
    plan = soak.plan_gameday(1, n_nodes=2, duration_s=20.0)
    assert [ev["plane"] for ev in plan["events"]] == ["corrupt"]

    rep = soak.run_soak(n_nodes=2, seed=1, duration_s=20.0, out=out)

    # the fleet made progress under load + corruption
    assert rep["heights"]["final"] > rep["heights"]["initial"], rep["heights"]
    assert rep["load"]["sent"] > 0
    assert rep["slo"]["sample_counts"].get("commit_latency", 0) > 0
    # the live run executed exactly the pure plan
    assert rep["schedule_fingerprint"] == soak.schedule_fingerprint(plan)
    assert sorted(p for p, _ in rep["executed"]) == ["corrupt"]
    assert not rep["event_errors"], rep["event_errors"]
    # every breach leaves with an attribution — a named plane or the loud
    # "unattributed", never silence
    for b in rep["slo"]["breaches"]:
        att = b.get("attribution")
        assert att and att.get("plane"), f"silent breach: {b}"
    assert rep["slo"]["unattributed"] == sum(
        1 for b in rep["slo"]["breaches"]
        if b["attribution"]["plane"] == "unattributed")
    # the report landed on disk and round-trips
    assert os.path.exists(out)
    with open(out) as f:
        disk = json.load(f)
    assert disk["breach_fingerprint"] == rep["breach_fingerprint"]
    # per-node process series made it into the fleet rollup
    proc = rep["fleet_rollup"]["process"]
    assert set(proc) == {"val0", "val1"}, proc


@pytest.mark.slow
def test_verify_determinism_across_seeds():
    soak = load_tool("soak")
    res = soak.verify_determinism(seeds=(1, 2))
    assert res["ok"], res
    fps = {s["schedule_fingerprint"] for s in res["seeds"].values()}
    assert len(fps) == 2, "different seeds must draw different schedules"
