"""Block sync (fast sync v0 semantics): pool scheduling, windowed batched
commit verification, and an in-proc e2e where a fresh node fast-syncs a
200-block chain from a peer and joins consensus
(reference blockchain/v0/{pool,reactor}.go; VERDICT round-1 item #4).
"""

import asyncio

import pytest

from tendermint_tpu import crypto
from tendermint_tpu.abci.example.kvstore import KVStoreApplication
from tendermint_tpu.blockchain import BlockchainReactor, BlockPool
from tendermint_tpu.blockchain.msgs import (
    BlockRequest,
    BlockResponse,
    NoBlockResponse,
    StatusRequest,
    StatusResponse,
    decode_msg,
    encode_msg,
)
from tendermint_tpu.consensus import ConsensusState
from tendermint_tpu.consensus.config import test_consensus_config
from tendermint_tpu.consensus.reactor import ConsensusReactor
from tendermint_tpu.libs.db import MemDB
from tendermint_tpu.proxy import AppConns, local_client_creator
from tendermint_tpu.state import BlockExecutor, StateStore, state_from_genesis
from tendermint_tpu.state.execution import EmptyEvidencePool, NoOpMempool
from tendermint_tpu.store import BlockStore
from tendermint_tpu.types import (
    BlockID,
    GenesisDoc,
    GenesisValidator,
    MockPV,
    SignedMsgType,
    Vote,
    VoteSet,
)
from tendermint_tpu.types.block import Commit
from tendermint_tpu.types.validator_set import verify_commit_light_batched
from tendermint_tpu.types.errors import ErrWrongSignature
from tendermint_tpu.p2p import InProcNetwork, Switch

CHAIN_ID = "sync-chain"


# -- chain builder -----------------------------------------------------------

def build_chain(n_blocks, pv, genesis):
    """Hand-build a committed chain: returns (final state, stores, commits)."""
    state = state_from_genesis(genesis)
    app = KVStoreApplication()
    conns = AppConns(local_client_creator(app))
    conns.start()
    state_store = StateStore(MemDB())
    block_store = BlockStore(MemDB())
    state_store.save(state)
    executor = BlockExecutor(state_store, conns.consensus, NoOpMempool(),
                             EmptyEvidencePool(), block_store)
    last_commit = Commit(0, 0, BlockID(), [])
    for h in range(1, n_blocks + 1):
        proposer = state.validators.get_proposer().address
        block, parts = state.make_block(h, [f"h{h}=v".encode()], last_commit,
                                        [], proposer)
        bid = BlockID(block.hash(), parts.header())
        vs = VoteSet(state.chain_id, h, 0, SignedMsgType.PRECOMMIT,
                     state.validators)
        v = Vote(SignedMsgType.PRECOMMIT, h, 0, bid, block.header.time_ns + 1,
                 state.validators.validators[0].address, 0)
        pv.sign_vote(state.chain_id, v)
        vs.add_vote(v)
        seen = vs.make_commit()
        block_store.save_block(block, parts, seen)
        state, _ = executor.apply_block(state, bid, block)
        last_commit = seen
    return state, state_store, block_store, conns, app


@pytest.fixture
def one_val_genesis():
    pv = MockPV(crypto.Ed25519PrivKey.generate(b"\x21" * 32))
    genesis = GenesisDoc(
        chain_id=CHAIN_ID, genesis_time_ns=1_700_000_000_000_000_000,
        validators=[GenesisValidator(pv.get_pub_key(), 10)])
    return pv, genesis


# -- pool unit tests ---------------------------------------------------------

def test_pool_schedule_and_consume():
    pool = BlockPool(start_height=1)
    pool.set_peer_range("p1", 1, 50)
    reqs = pool.schedule_requests()
    heights = sorted(h for _pid, h in reqs)
    assert heights[0] == 1 and len(heights) <= 50
    assert all(pid == "p1" for pid, _h in reqs[:5])
    # per-peer pending cap respected
    assert len(reqs) <= 16
    assert pool.schedule_requests() == []  # nothing new until capacity frees


def test_pool_redo_punishes_provider():
    pool = BlockPool(start_height=1)
    pool.set_peer_range("bad", 1, 10)

    class _B:  # stand-in block
        def __init__(self, h):
            from types import SimpleNamespace

            self.header = SimpleNamespace(height=h)

    for pid, h in pool.schedule_requests():
        pool.add_block(pid, _B(h))
    assert len(pool.peek_window(5)) == 5
    bad = pool.redo(1)
    assert bad == {"bad"}
    assert pool.peek_window(5) == []
    # peer is gone; nothing schedulable until another peer reports in
    assert pool.schedule_requests() == []
    assert not pool.is_caught_up()


def test_pool_caught_up():
    pool = BlockPool(start_height=11)
    pool.set_peer_range("p", 1, 10)
    assert pool.is_caught_up()


# -- wire codec --------------------------------------------------------------

def test_blockchain_msg_roundtrip(one_val_genesis):
    pv, genesis = one_val_genesis
    state, _ss, bs, conns, _app = build_chain(2, pv, genesis)
    blk = bs.load_block(1)
    for msg in (BlockRequest(7), NoBlockResponse(9), StatusRequest(),
                StatusResponse(12, 3), BlockResponse(blk)):
        out = decode_msg(encode_msg(msg))
        if isinstance(msg, BlockResponse):
            assert out.block.hash() == blk.hash()
        else:
            assert out == msg
    conns.stop()


# -- windowed batched verification -------------------------------------------

def test_verify_commit_light_batched_window(one_val_genesis, monkeypatch):
    monkeypatch.setenv("TMTPU_BATCH_BACKEND", "host")
    pv, genesis = one_val_genesis
    state, _ss, bs, conns, _app = build_chain(12, pv, genesis)
    # entries: verify block h's seen commit against the (static) valset
    entries = []
    for h in range(1, 11):
        blk = bs.load_block(h)
        bid = BlockID(blk.hash(), blk.make_part_set().header())
        entries.append((state.validators, CHAIN_ID, bid, h, bs.load_seen_commit(h)))
    results = verify_commit_light_batched(entries)
    assert all(r is None for r in results)

    # corrupt one commit in the middle: only that entry errors
    bad_commit = bs.load_seen_commit(5)
    sig = bytearray(bad_commit.signatures[0].signature)
    sig[0] ^= 1
    bad_commit.signatures[0].signature = bytes(sig)
    entries[4] = (state.validators, CHAIN_ID, entries[4][2], 5, bad_commit)
    results = verify_commit_light_batched(entries)
    assert isinstance(results[4], ErrWrongSignature)
    assert all(r is None for i, r in enumerate(results) if i != 4)
    conns.stop()


def test_verify_commit_light_batched_device_path(one_val_genesis):
    """>=16 sigs in one call routes to the jax kernel; decisions unchanged."""
    pv, genesis = one_val_genesis
    state, _ss, bs, conns, _app = build_chain(20, pv, genesis)
    entries = []
    for h in range(1, 19):
        blk = bs.load_block(h)
        bid = BlockID(blk.hash(), blk.make_part_set().header())
        entries.append((state.validators, CHAIN_ID, bid, h, bs.load_seen_commit(h)))
    results = verify_commit_light_batched(entries)
    assert all(r is None for r in results)
    conns.stop()


# -- e2e: fresh node fast-syncs then joins consensus --------------------------

class SyncNode:
    """A full node wired for fast sync (consensus held back until synced).

    Pass chain=(state, state_store, block_store, conns, app) to start on an
    existing chain (the source node); otherwise starts fresh from genesis.
    """

    def __init__(self, name, genesis, pv=None, fast_sync=True, chain=None,
                 config=None):
        from tendermint_tpu.consensus.replay import Handshaker
        from tendermint_tpu.mempool import CListMempool
        from tendermint_tpu.types.event_bus import EventBus

        if chain is not None:
            self.state, self.state_store, self.block_store, self.conns, self.app = chain
        else:
            self.app = KVStoreApplication()
            self.conns = AppConns(local_client_creator(self.app))
            self.conns.start()
            self.state_store = StateStore(MemDB())
            self.block_store = BlockStore(MemDB())
            self.state = state_from_genesis(genesis)
            self.state_store.save(self.state)
            self.state = Handshaker(self.state_store, self.state, self.block_store,
                                    genesis).handshake(self.conns.consensus,
                                                       self.conns.query)
            self.state_store.save(self.state)
        self.mempool = CListMempool(self.conns.mempool)
        self.event_bus = EventBus()
        self.block_exec = BlockExecutor(self.state_store, self.conns.consensus,
                                        self.mempool, EmptyEvidencePool(),
                                        self.block_store, self.event_bus)
        self.cs = ConsensusState(config or test_consensus_config(), self.state,
                                 self.block_exec, self.block_store)
        if pv is not None:
            self.cs.set_priv_validator(pv)
        self.cs.set_event_bus(self.event_bus)
        self.mempool.tx_available_callbacks.append(self.cs.notify_txs_available)
        self.switch = Switch(name)
        self.cs_reactor = ConsensusReactor(self.cs, wait_sync=fast_sync)
        self.switch.add_reactor("CONSENSUS", self.cs_reactor)
        self.bc_reactor = BlockchainReactor(
            self.state, self.block_exec, self.block_store,
            fast_sync=fast_sync, consensus_reactor=self.cs_reactor)
        self.switch.add_reactor("BLOCKCHAIN", self.bc_reactor)
        self.fast_sync = fast_sync

    async def start(self):
        await self.switch.start()
        if not self.fast_sync:
            await self.cs.start()

    async def stop(self):
        await self.cs.stop()
        await self.switch.stop()
        self.conns.stop()


def test_fast_sync_200_blocks_then_join_consensus(one_val_genesis, monkeypatch):
    monkeypatch.setenv("TMTPU_BATCH_BACKEND", "host")  # keep CPU suite fast
    pv, genesis = one_val_genesis

    async def run():
        # source: 200 pre-built blocks (its app replayed them); its consensus
        # only proposes when txs arrive so it doesn't race ahead of the sync
        from dataclasses import replace

        quiet = replace(test_consensus_config(), create_empty_blocks=False)
        chain = build_chain(200, pv, genesis)
        src = SyncNode("src", genesis, pv=pv, fast_sync=False, chain=chain,
                       config=quiet)
        fresh = SyncNode("fresh", genesis, pv=None, fast_sync=True,
                         config=quiet)

        net = InProcNetwork()
        net.add_switch(src.switch)
        net.add_switch(fresh.switch)
        await src.start()
        await fresh.start()
        await net.connect("src", "fresh")
        try:
            # fresh node must fast-sync the chain and switch to consensus
            await asyncio.wait_for(fresh.bc_reactor.synced.wait(), timeout=90)
            assert fresh.bc_reactor.blocks_synced >= 190
            h_sync = fresh.state_store.load().last_block_height
            assert h_sync >= 199
            # ...then follow live consensus: a tx at the source must commit a
            # new block that the freshly-synced node also applies
            src.mempool.check_tx(b"post=sync")
            deadline = asyncio.get_event_loop().time() + 60
            while asyncio.get_event_loop().time() < deadline:
                if fresh.app.state.get("post") == "sync":
                    break
                await asyncio.sleep(0.1)
            assert fresh.app.state.get("post") == "sync", \
                "fresh node did not join consensus"
            assert fresh.state_store.load().last_block_height >= 201
            # app state agrees with the source chain
            assert fresh.app.state.get("h5") == "v"
        finally:
            await fresh.stop()
            await src.stop()

    asyncio.run(run())


def test_window_precompute_covers_both_planes(one_val_genesis, monkeypatch):
    """The dual-plane window precompute (light seen-commit + LastCommit full
    VerifyCommit) must actually engage and feed apply_block's verify_commit
    through precomputed verdicts — one batched scope per window instead of
    a dispatch per block."""
    import tendermint_tpu.blockchain.reactor as R
    from tendermint_tpu.crypto import batch as crypto_batch
    from tendermint_tpu.libs.db import MemDB
    from tendermint_tpu.state import StateStore, state_from_genesis
    from tendermint_tpu.store import BlockStore

    monkeypatch.setenv("TMTPU_BATCH_BACKEND", "host")
    monkeypatch.setattr(R, "PRECOMPUTE_MIN_SIGS", 2)
    pv, genesis = one_val_genesis
    _state, _ss, src_store, conns, _app = build_chain(12, pv, genesis)

    # fresh replaying node
    app2 = KVStoreApplication()
    conns2 = AppConns(local_client_creator(app2))
    conns2.start()
    state2 = state_from_genesis(genesis)
    ss2 = StateStore(MemDB())
    ss2.save(state2)
    bs2 = BlockStore(MemDB())
    ex2 = BlockExecutor(ss2, conns2.consensus, NoOpMempool(),
                        EmptyEvidencePool(), bs2)
    reactor = R.BlockchainReactor(state2, ex2, bs2, fast_sync=True)
    reactor.pool = R.BlockPool(1)
    reactor.pool.set_peer_range("src", 1, 12)

    before = dict(crypto_batch.stats)

    async def drive():
        while reactor.blocks_synced < 10:
            for pid, h in reactor.pool.schedule_requests():
                reactor.pool.add_block(pid, src_store.load_block(h))
            applied = reactor.blocks_synced
            await reactor._process_window()
            if reactor.blocks_synced == applied:
                break

    asyncio.run(drive())
    assert reactor.blocks_synced >= 10
    pre_sigs = crypto_batch.stats["precomputed_sigs"] - before.get(
        "precomputed_sigs", 0)
    # both planes consumed precomputed verdicts: the light batched call AND
    # apply_block's per-block full verify_commit
    assert pre_sigs > 0, dict(crypto_batch.stats)
    conns.stop()
    conns2.stop()


# -- adversarial: tampered block responses (blocksync.bad_block site) ---------

def test_fast_sync_survives_tampered_block_response(one_val_genesis, monkeypatch):
    """One served BlockResponse gets a bit flipped (the blocksync.bad_block
    serving-side fault site). The victim's verification path must catch it,
    strike the provider on the scoreboard (backoff, not yet ban at one
    offense), redo the window, and finish the sync from the other source —
    never wedge, never apply a tampered block."""
    monkeypatch.setenv("TMTPU_BATCH_BACKEND", "host")
    pv, genesis = one_val_genesis
    from dataclasses import replace

    from tendermint_tpu.libs.faults import faults

    async def run():
        quiet = replace(test_consensus_config(), create_empty_blocks=False)
        # build_chain is deterministic (MockPV + BFT time), so two builds
        # give two independent sources serving byte-identical blocks
        chain_a = build_chain(30, pv, genesis)
        chain_b = build_chain(30, pv, genesis)
        assert chain_a[0].last_block_id == chain_b[0].last_block_id
        src_a = SyncNode("src_a", genesis, pv=pv, fast_sync=False,
                         chain=chain_a, config=quiet)
        src_b = SyncNode("src_b", genesis, pv=None, fast_sync=False,
                         chain=chain_b, config=quiet)
        fresh = SyncNode("fresh", genesis, pv=None, fast_sync=True,
                         config=quiet)
        net = InProcNetwork()
        for nd in (src_a, src_b, fresh):
            net.add_switch(nd.switch)
        await src_a.start()
        await src_b.start()
        # the very next served block response is tampered: exactly one
        # injection, so the test is deterministic for any seed
        faults.configure("blocksync.bad_block*1", seed=6)
        await fresh.start()
        await net.connect("src_a", "fresh")
        await net.connect("src_b", "fresh")
        try:
            await asyncio.wait_for(fresh.bc_reactor.synced.wait(), timeout=90)
            assert fresh.state_store.load().last_block_height >= 29
        finally:
            for nd in (fresh, src_b, src_a):
                await nd.stop()
        assert faults.fires("blocksync.bad_block") == 1
        scores = fresh.bc_reactor.scoreboard.snapshot()
        assert sum(s["total_failures"] for s in scores.values()) >= 1, scores
        # one offense is backoff territory, not a ban
        assert fresh.bc_reactor.scoreboard.ban_count() == 0, scores
        # the synced chain is the honest one
        assert fresh.state_store.load().last_block_id == chain_a[0].last_block_id

    asyncio.run(run())
