"""libs/compilecache.py: the persistent-XLA-cache host fingerprint. A cache
dir built on a machine with different CPU features must produce a loud
startup warning (the cpu_aot_loader SIGILL footgun was previously buried in
stderr — MULTICHIP_r05.json), and the outcome must be visible to debugdump
via status()."""

import json
import os

from tendermint_tpu.libs import compilecache as cc


def test_marker_written_then_matches(tmp_path):
    d = str(tmp_path / "cache")
    assert cc.check_cache_dir(d) is None  # first use: stamps the dir
    marker = os.path.join(d, cc.MARKER_NAME)
    assert os.path.exists(marker)
    doc = json.load(open(marker))
    fp = cc.host_fingerprint()
    assert doc["machine"] == fp["machine"]
    assert doc["flags_sha256"] == fp["flags_sha256"]
    # second process on the same host: clean
    assert cc.check_cache_dir(d) is None
    st = cc.status()
    assert st["cache_dir"] == d and st["mismatch"] is None


def test_foreign_cache_warns_sigill(tmp_path):
    d = str(tmp_path / "cache")
    os.makedirs(d)
    with open(os.path.join(d, cc.MARKER_NAME), "w") as f:
        json.dump({"machine": "imaginary-tpu-vm",
                   "flags_sha256": "deadbeef" * 8, "n_flags": 1}, f)
    warn = cc.check_cache_dir(d)
    assert warn is not None
    assert "SIGILL" in warn and "cpu_aot_loader" in warn
    assert "imaginary-tpu-vm" in warn
    assert cc.status()["mismatch"] == warn
    # the stale marker is NOT silently rewritten: every process on this
    # host keeps warning until the operator clears the cache dir
    assert cc.check_cache_dir(d) is not None


def test_preexisting_markerless_cache_warns_once_then_stamps(tmp_path):
    """A cache dir that already holds entries but no fingerprint (built
    before this feature, or copied from another machine) warns ONCE with
    the SIGILL wording, records the unverifiable origin in the marker, and
    goes quiet afterwards — a cache genuinely built on this host doesn't
    cry wolf forever, and a copied one still got its loud warning."""
    d = str(tmp_path / "cache")
    os.makedirs(d)
    open(os.path.join(d, "jit_foo-abc123-cache"), "w").write("x")
    warn = cc.check_cache_dir(d)
    assert warn is not None and "SIGILL" in warn
    marker = json.load(open(os.path.join(d, cc.MARKER_NAME)))
    assert marker["origin"] == "preexisting-unverified"
    assert cc.check_cache_dir(d) is None  # now fingerprint-matched


def test_torn_marker_restamps_instead_of_going_silent(tmp_path):
    """A half-written marker (concurrent first-start stampede on a shared
    cache dir) must not disable the check forever: it re-stamps as
    unverifiable origin — with the one-time warning — and then matches."""
    d = str(tmp_path / "cache")
    os.makedirs(d)
    open(os.path.join(d, "jit_foo-abc-cache"), "w").write("x")
    open(os.path.join(d, cc.MARKER_NAME), "w").write('{"machine": "tru')
    warn = cc.check_cache_dir(d)
    assert warn is not None and "SIGILL" in warn
    marker = json.load(open(os.path.join(d, cc.MARKER_NAME)))
    assert marker["origin"] == "preexisting-unverified"
    assert cc.check_cache_dir(d) is None


def test_fresh_dir_stamps_silently(tmp_path):
    d = str(tmp_path / "cache")
    assert cc.check_cache_dir(d) is None
    marker = json.load(open(os.path.join(d, cc.MARKER_NAME)))
    assert marker["origin"] == "fresh"


def test_unwritable_dir_degrades_to_no_warning(tmp_path):
    target = tmp_path / "file-not-dir"
    target.write_text("x")  # makedirs/marker write will fail
    assert cc.check_cache_dir(str(target)) is None  # advisory only


def test_enable_compile_cache_configures_jax(tmp_path):
    import jax

    old = jax.config.jax_compilation_cache_dir
    d = str(tmp_path / "c2")
    try:
        assert cc.enable_compile_cache(d) is None
        assert jax.config.jax_compilation_cache_dir == d
        assert os.path.exists(os.path.join(d, cc.MARKER_NAME))
    finally:
        # the suite's shared cache must keep serving later tests
        jax.config.update("jax_compilation_cache_dir", old)
