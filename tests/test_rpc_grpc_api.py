"""gRPC BroadcastAPI (reference rpc/grpc/api.go): Ping + BroadcastTx wire
round trip against a stub environment, without a full node."""

import asyncio
import threading

from tendermint_tpu.abci import types as abci
from tendermint_tpu.rpc.grpc_api import (
    BroadcastAPIClient,
    BroadcastAPIServer,
    _dec_request_broadcast_tx,
    _dec_response_broadcast_tx,
    _enc_request_broadcast_tx,
    _enc_response_broadcast_tx,
)


def test_wire_codecs_round_trip():
    assert _dec_request_broadcast_tx(_enc_request_broadcast_tx(b"k=v")) == b"k=v"
    raw = _enc_response_broadcast_tx(
        abci.ResponseCheckTx(code=0, log="ok", gas_wanted=5),
        abci.ResponseDeliverTx(code=3, data=b"d", log="bad"))
    check, deliver = _dec_response_broadcast_tx(raw)
    assert check.log == "ok" and check.gas_wanted == 5
    assert deliver.code == 3 and deliver.data == b"d"


def test_server_delegates_to_broadcast_tx_commit():
    seen = {}

    class StubEnv:
        async def broadcast_tx_commit(self, tx_b64: str):
            import base64

            seen["tx"] = base64.b64decode(tx_b64)
            return {
                "check_tx": {"code": 0, "log": "checked", "gas_wanted": "7"},
                "deliver_tx": {"code": 0, "data": "aGk=", "log": "delivered"},
                "height": "4",
            }

    loop = asyncio.new_event_loop()
    t = threading.Thread(target=lambda: (asyncio.set_event_loop(loop),
                                         loop.run_forever()), daemon=True)
    t.start()
    server = BroadcastAPIServer("127.0.0.1:0", StubEnv(), loop)
    server.start()
    try:
        client = BroadcastAPIClient(f"127.0.0.1:{server.port}")
        client.ping()
        check, deliver = client.broadcast_tx(b"tx-bytes")
        assert seen["tx"] == b"tx-bytes"
        assert check.code == 0 and check.log == "checked"
        assert check.gas_wanted == 7
        assert deliver.data == b"hi" and deliver.log == "delivered"
        client.close()
    finally:
        server.stop()
        loop.call_soon_threadsafe(loop.stop)
        t.join(timeout=5)
