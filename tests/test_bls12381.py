"""BLS12-381 min-sig foundation: scalar spec self-consistency, aggregate
semantics, proof-of-possession (the rogue-key gate), and the vectorized
backends' verdict parity with the scalar path (numpy + jax limb engines
behind the device breaker)."""

import pytest

from tendermint_tpu.crypto import bls12381 as bls
from tendermint_tpu.crypto.bls12381 import vec


def _keys(n, tag=b"t"):
    sks = [bls.sk_from_seed(tag + bytes([i])) for i in range(n)]
    return sks, [bls.sk_to_pk(sk) for sk in sks]


def test_sign_verify_roundtrip():
    sks, pks = _keys(3)
    msg = b"tendermint-tpu bls"
    for sk, pk in zip(sks, pks):
        sig = bls.sign(sk, msg)
        assert len(sig) == 48  # min-sig: signatures in G1, compressed
        assert len(pk) == 96   # pubkeys in G2, compressed
        assert bls.verify(pk, msg, sig)
        assert not bls.verify(pk, msg + b"!", sig)
    # a signature under one key must not verify under another
    assert not bls.verify(pks[1], msg, bls.sign(sks[0], msg))


def test_keygen_is_deterministic():
    a = bls.sk_from_seed(b"fixed-seed")
    b = bls.sk_from_seed(b"fixed-seed")
    assert a == b
    assert bls.sk_to_pk(a) == bls.sk_to_pk(b)
    assert bls.sk_from_seed(b"other-seed") != a


def test_fast_aggregate_verify_all_signers():
    sks, pks = _keys(5)
    msg = b"one shared zero-timestamp payload"
    agg = bls.aggregate([bls.sign(sk, msg) for sk in sks])
    assert len(agg) == 48  # the whole commit collapses to one G1 point
    assert bls.fast_aggregate_verify(pks, msg, agg)
    # any tampering of the aggregate breaks the pairing
    assert not bls.fast_aggregate_verify(
        pks, msg, bytes([agg[0] ^ 0x01]) + agg[1:])
    # a missing signer's key in the apk breaks it too (bitmap mismatch)
    assert not bls.fast_aggregate_verify(pks[:-1], msg, agg)
    # ... as does an extra key that never signed
    extra = bls.sk_to_pk(bls.sk_from_seed(b"extra"))
    assert not bls.fast_aggregate_verify(pks + [extra], msg, agg)


def test_aggregate_subset_matches_subset_apk():
    sks, pks = _keys(6)
    msg = b"subset"
    idxs = [0, 2, 5]
    agg = bls.aggregate([bls.sign(sks[i], msg) for i in idxs])
    assert bls.fast_aggregate_verify([pks[i] for i in idxs], msg, agg)
    assert not bls.fast_aggregate_verify(pks, msg, agg)


def test_duplicate_signer_in_aggregate_rejected():
    """A signature folded in twice no longer matches the once-per-key apk —
    the differential suite leans on this for duplicate-signer parity."""
    sks, pks = _keys(3)
    msg = b"dup"
    sigs = [bls.sign(sk, msg) for sk in sks]
    doubled = bls.aggregate(sigs + [sigs[0]])
    assert not bls.fast_aggregate_verify(pks, msg, doubled)


def test_pop_prove_verify_and_rogue_key_gate():
    sks, pks = _keys(2, b"p")
    pop0 = bls.pop_prove(sks[0])
    assert bls.pop_verify(pks[0], pop0)
    # a pop is bound to ITS key: replaying it for another fails
    assert not bls.pop_verify(pks[1], pop0)
    # the signing DST must not double as the pop DST (domain separation)
    assert not bls.pop_verify(pks[0], bls.sign(sks[0], pks[0]))
    bls.register_key(pks[0], pop0)
    assert bls.is_registered(pks[0])
    with pytest.raises(ValueError):
        bls.register_key(pks[1], pop0)
    assert not bls.is_registered(pks[1])


def test_decompress_rejects_garbage():
    # malformed / infinity / out-of-subgroup encodings resolve to None...
    assert bls.decompress_pubkey(b"\x00" * 96) is None
    assert bls.decompress_pubkey(b"\xc0" + b"\x00" * 95) is None  # infinity
    # ...and any verify over them is a clean False, never a crash
    assert not bls.verify(b"\x00" * 96, b"m", bls.sign(
        bls.sk_from_seed(b"x"), b"m"))
    assert not bls.verify(bls.sk_to_pk(bls.sk_from_seed(b"x")),
                          b"m", b"\xff" * 48)


@pytest.mark.parametrize("backend", [
    "numpy",
    # the jax tree-reduction kernel takes minutes of XLA compile on a CPU
    # host (same story as the ed25519 verify kernel) — full-path parity
    # stays out of tier-1; the n==1 probe test below and the aggsig.degrade
    # chaos cell keep the jax routing covered there
    pytest.param("jax", marks=pytest.mark.slow),
])
def test_vector_backend_verdict_parity(backend):
    """Both limb engines must return the scalar path's exact verdicts —
    they are an on-ramp for the device plane, never a semantics change."""
    sks, pks = _keys(4, b"v")
    msg = b"parity"
    good = bls.aggregate([bls.sign(sk, msg) for sk in sks])
    bad = bytes([good[0] ^ 0x01]) + good[1:]
    vec.reset_stats()
    assert vec.fast_aggregate_verify_routed(pks, msg, good, backend=backend)
    assert not vec.fast_aggregate_verify_routed(pks, msg, bad, backend=backend)
    assert vec.fast_aggregate_verify_routed(pks, msg, good, backend="scalar")
    used = "device_calls" if backend == "jax" else "host_vec_calls"
    assert vec.stats[used] >= 2, dict(vec.stats)
    assert vec.stats["scalar_calls"] >= 1, dict(vec.stats)


def test_montgomery_limb_roundtrip_both_geometries():
    for cfg in (vec.CFG_NP, vec.CFG_JAX):
        x = 0x1234567890ABCDEF ** 4 % vec.P
        limbs = cfg.to_limbs_np(x)
        back = sum(int(l) << (cfg.limb * i) for i, l in enumerate(limbs))
        assert back == x, (cfg.nlimbs, cfg.limb)


def test_single_key_fast_aggregate_is_plain_verify():
    sks, pks = _keys(1, b"s")
    msg = b"n=1"
    sig = bls.sign(sks[0], msg)
    assert bls.fast_aggregate_verify(pks, msg, sig)
    assert bls.verify(pks[0], msg, sig)


def test_jax_single_key_probe_path():
    """The n==1 jax route (a Montgomery limb roundtrip as device evidence —
    what the breaker's half-open probe rides) must agree with scalar and
    count as a device call; cheap enough for tier-1 unlike the full
    tree-reduction kernel."""
    sks, pks = _keys(1, b"j")
    msg = b"probe"
    sig = bls.sign(sks[0], msg)
    vec.reset_stats()
    assert vec.fast_aggregate_verify_routed(pks, msg, sig, backend="jax")
    assert not vec.fast_aggregate_verify_routed(
        pks, msg, bytes([sig[0] ^ 0x01]) + sig[1:], backend="jax")
    assert vec.stats["device_calls"] >= 2, dict(vec.stats)
