"""Test env: force JAX onto CPU with 8 virtual devices so sharding/multi-chip
paths are exercised without TPU hardware (the driver benches on the real chip).

Must run before any jax import. The image's sitecustomize registers the axon
TPU backend whenever PALLAS_AXON_POOL_IPS is set and the environment pins
JAX_PLATFORMS=axon — both must be overridden (not setdefault'ed) or the whole
suite silently runs on the real chip through the remote-compile relay.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)  # skip axon backend registration
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()
