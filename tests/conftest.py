"""Test env: force JAX onto CPU with 8 virtual devices so sharding/multi-chip
paths are exercised without TPU hardware (the driver benches on the real chip).

The image's sitecustomize (PYTHONPATH=/root/.axon_site) registers the axon TPU
backend and imports jax *at interpreter startup* — before pytest loads this
file — so setting JAX_PLATFORMS here is too late (jax reads it at import).
``jax.config.update("jax_platforms", ...)`` still works because backends
initialize lazily on the first ``jax.devices()`` call; XLA_FLAGS is likewise
read at backend-init time. A hard assertion below makes any regression loud
instead of silently benching the whole suite through the TPU relay.

Set TM_ON_DEVICE=1 to skip the pin and run the on-device differential suite
(tests/test_tpu_device.py) against the real chip.
"""

import os

import pytest

ON_DEVICE = os.environ.get("TM_ON_DEVICE") == "1"


@pytest.fixture(autouse=True)
def _reset_fault_state():
    """Fail-point counters, armed fault sites, and breaker state are
    process-global by design (subprocess nodes arm them from env) — reset
    around every test so one test's chaos can't leak into the next."""
    import sys

    from tendermint_tpu.crypto import phases
    from tendermint_tpu.crypto.breaker import (
        device_breaker,
        reset_lane_breakers,
    )
    from tendermint_tpu.libs import fail
    from tendermint_tpu.libs.faults import faults

    def _reset_all():
        fail.reset()
        faults.reset()
        device_breaker.reset()
        reset_lane_breakers()
        phases.reset()
        phases.set_device_metrics(None)
        # only if a test built the multi-device pool: tear it down so the
        # next test re-resolves it (and re-reads its env knobs)
        md = sys.modules.get("tendermint_tpu.crypto.ed25519_jax.multidevice")
        if md is not None:
            md.reset_pool()
        # scheme registry + BLS caches are likewise process-global; only
        # touch them if a test actually imported those modules
        sch = sys.modules.get("tendermint_tpu.crypto.schemes")
        if sch is not None:
            sch.reset()
        bls = sys.modules.get("tendermint_tpu.crypto.bls12381")
        if bls is not None:
            bls.reset()
        bvec = sys.modules.get("tendermint_tpu.crypto.bls12381.vec")
        if bvec is not None:
            bvec.reset_stats()

    _reset_all()
    yield
    _reset_all()


def pytest_collection_modifyitems(config, items):
    # With the CPU pin disabled, only the on-device suite may run — anything
    # else would silently exercise the TPU relay (and assume 8 devices).
    if ON_DEVICE:
        import pytest

        skip = pytest.mark.skip(reason="TM_ON_DEVICE=1 runs only tests/test_tpu_device.py")
        for item in items:
            if "test_tpu_device" not in str(item.fspath):
                item.add_marker(skip)


_CACHE_DIR = os.path.join(os.path.dirname(os.path.dirname(__file__)), ".jax_cache")

if not ON_DEVICE:
    xla_flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in xla_flags:
        os.environ["XLA_FLAGS"] = (
            xla_flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    # The ed25519 verify kernel takes minutes to compile on CPU; a persistent
    # cache makes repeat suite runs fast (first run still pays the compiles).
    jax.config.update("jax_compilation_cache_dir", _CACHE_DIR)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 2)
    assert jax.default_backend() == "cpu", (
        "CPU pin failed: suite would silently run on "
        f"{jax.default_backend()!r}; jax backends were initialized before "
        "conftest ran"
    )
    assert len(jax.devices()) == 8, (
        f"expected 8 virtual CPU devices, got {len(jax.devices())}"
    )
