"""Test env: force JAX onto CPU with 8 virtual devices so sharding/multi-chip
paths are exercised without TPU hardware (the driver benches on the real chip).
Must run before any jax import."""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()
