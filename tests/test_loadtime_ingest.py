"""The open-loop load harness (tools/loadtime.py) against a REAL
single-validator consensus chain served over a real aiohttp RPCServer:
pre-planned sends land through broadcast_tx_sync, latency percentiles are
recovered from committed blocks, and the /tx_timeline scrape shows the
full rpc_received → committed stage chain with monotonic stamps — the
acceptance criterion's measurement path, minus only the multi-process
localnet bench.py --config ingest drives on full containers."""

import asyncio
import threading
from types import SimpleNamespace

import pytest

pytest.importorskip("aiohttp", reason="RPC server needs aiohttp")

from tendermint_tpu.libs.metrics import NodeMetrics
from tendermint_tpu.libs.toolbox import load_tool
from tendermint_tpu.libs.txlife import TxLifecycle
from tendermint_tpu.rpc.server import RPCServer


def _rpc_node(cs, mempool, block_store, event_bus, genesis, pv):
    """The Environment surface loadtime's report walks (status, block,
    broadcast_tx_sync, num_unconfirmed_txs, tx_timeline)."""
    return SimpleNamespace(
        config=SimpleNamespace(
            rpc=SimpleNamespace(laddr="tcp://127.0.0.1:0",
                                max_body_bytes=1000000, unsafe=False,
                                timeout_broadcast_tx_commit=10.0),
            base=SimpleNamespace(moniker="ingest-test")),
        mempool=mempool,
        block_store=block_store,
        event_bus=event_bus,
        consensus_state=cs,
        genesis=genesis,
        node_key=SimpleNamespace(id="stub-node"),
        node_info=SimpleNamespace(listen_addr="", version="test",
                                  protocol_p2p=8, protocol_block=11,
                                  protocol_app=0),
        priv_validator=pv,
        _fast_sync=False,
    )


def test_open_loop_load_to_commit_with_timeline():
    from test_consensus_single import build_node

    lt = load_tool("loadtime")

    async def run():
        cs, mempool, app, event_bus, pv, extras = build_node()
        _state_store, block_store, genesis, conns = extras
        nm = NodeMetrics()
        tl = TxLifecycle(sample_rate=1.0)
        tl.metrics = nm.mempool
        mempool.metrics = nm.mempool
        mempool.txlife = tl
        node = _rpc_node(cs, mempool, block_store, event_bus, genesis, pv)
        server = RPCServer(node)
        server.metrics = nm.rpc
        await cs.start()
        await server.start("tcp://127.0.0.1:0")
        endpoint = f"http://127.0.0.1:{server.bound_port}"
        try:
            stats = await lt.open_loop_load(endpoint, rate=40.0,
                                            duration=2.0, size=64,
                                            clients=4)
            assert stats["planned"] == 80
            assert stats["accepted"] > 0, stats
            # settle: let the tail commit
            for _ in range(200):
                if mempool.size() == 0:
                    break
                await asyncio.sleep(0.05)
            # report_doc is blocking urllib — run it off-loop against the
            # live server
            doc = await asyncio.get_running_loop().run_in_executor(
                None, lt.report_doc, endpoint)
        finally:
            await server.stop()
            await cs.stop()
            conns.stop()
        assert doc["txs"] >= stats["accepted"] * 0.9, doc
        assert doc["txs_per_sec"] > 0
        lat = doc["latency_s"]
        assert {"p50", "p99", "p99.9"} <= set(lat)
        assert 0 < lat["p50"] <= lat["p99"] <= lat["p99.9"], lat
        # the acceptance probe: a sampled tx's timeline record carries
        # every stage from rpc_received through committed, monotonic
        tlr = doc["tx_timeline"]
        assert tlr["complete_rpc_to_commit_records"] >= 1, tlr
        assert tlr["node_commit_latency_s"]["p50"] > 0
        full = [r for r in tl.tail(500)
                if r["terminal"] == "committed"
                and {"rpc_received", "checktx_done", "mempool_admitted",
                     "proposal_included",
                     "committed"} <= {m[0] for m in r["marks"]}]
        assert full, tl.snapshot()
        times = [t for _, t in full[0]["marks"]]
        assert times == sorted(times)
        # the RPC front door counted the load
        ok_count = nm.rpc.request_seconds.count_value("broadcast_tx_sync",
                                                      "ok")
        assert ok_count == stats["sent"], (ok_count, stats)

    asyncio.run(run())
