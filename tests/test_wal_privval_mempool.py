"""WAL framing/corruption, FilePV double-sign protection, mempool semantics."""

import os

import pytest

from tendermint_tpu import crypto
from tendermint_tpu.abci import types as abci
from tendermint_tpu.abci.example.kvstore import KVStoreApplication
from tendermint_tpu.abci.client import LocalClient
from tendermint_tpu.consensus.wal import WAL, TimeoutInfo
from tendermint_tpu.mempool import CListMempool
from tendermint_tpu.mempool.clist_mempool import ErrTxInCache, MempoolError
from tendermint_tpu.privval import FilePV
from tendermint_tpu.privval.file_pv import DoubleSignError
from tendermint_tpu.types import BlockID, PartSetHeader, SignedMsgType, Vote
from tendermint_tpu.types.proposal import Proposal

BID = BlockID(b"\x01" * 32, PartSetHeader(1, b"\x02" * 32))
OTHER = BlockID(b"\x03" * 32, PartSetHeader(1, b"\x04" * 32))


# --- WAL -------------------------------------------------------------------

class TestWAL:
    def test_roundtrip(self, tmp_path):
        wal = WAL(str(tmp_path / "w.wal"))
        wal.write("round_step", {"height": 1, "round": 0, "step": 1}, 123)
        wal.write_timeout(TimeoutInfo(1.5, 1, 0, 3), 124)
        wal.write_end_height(1, 125)
        wal.close()
        msgs = list(WAL(str(tmp_path / "w.wal")).iter_messages())
        # a fresh WAL self-writes #ENDHEIGHT 0 (wal.go BaseWAL.OnStart)
        assert [m.type for m in msgs] == ["end_height", "round_step",
                                          "timeout", "end_height"]
        assert msgs[0].data["height"] == 0
        assert msgs[2].data["duration_s"] == 1.5

    def test_torn_tail_tolerated(self, tmp_path):
        path = str(tmp_path / "w.wal")
        wal = WAL(path)
        wal.write("round_step", {"height": 1}, 1)
        wal.write("round_step", {"height": 2}, 2)
        wal.close()
        with open(path, "ab") as f:
            f.write(b"\x00\x01\x02")  # torn write
        msgs = list(WAL(path).iter_messages())
        assert len(msgs) == 3  # incl. the auto #ENDHEIGHT 0

    def test_corrupt_crc_stops_replay(self, tmp_path):
        path = str(tmp_path / "w.wal")
        wal = WAL(path)
        wal.write("round_step", {"height": 1}, 1)
        wal.write("round_step", {"height": 2}, 2)
        wal.close()
        raw = bytearray(open(path, "rb").read())
        raw[-1] ^= 0xFF  # corrupt last record's payload
        open(path, "wb").write(bytes(raw))
        msgs = list(WAL(path).iter_messages())
        assert len(msgs) == 2  # ENDHEIGHT 0 + first record; corrupt tail dropped

    def test_search_for_end_height(self, tmp_path):
        wal = WAL(str(tmp_path / "w.wal"))
        wal.write_end_height(5, 1)
        wal.write("vote", {"vote": "00", "peer": "p"}, 2)
        assert wal.search_for_end_height(5)
        assert not wal.search_for_end_height(6)
        after = wal.messages_after_end_height(5)
        assert len(after) == 1 and after[0].type == "vote"

    def test_rotation(self, tmp_path):
        path = str(tmp_path / "w.wal")
        wal = WAL(path, head_size_limit=512)
        for i in range(100):
            wal.write("round_step", {"height": i, "pad": "x" * 50}, i)
        wal.close()
        assert os.path.exists(path + ".0")  # rotated
        msgs = [m for m in WAL(path).iter_messages() if m.type == "round_step"]
        assert len(msgs) == 100  # reads across rotated files
        assert [m.data["height"] for m in msgs] == list(range(100))


# --- FilePV ----------------------------------------------------------------

def mk_vote(h, r, t=SignedMsgType.PREVOTE, bid=BID, ts=1_700_000_000_000_000_000):
    return Vote(t, h, r, bid, ts, b"\xaa" * 20, 0)


class TestFilePV:
    def test_sign_and_persist(self, tmp_path):
        pv = FilePV.generate(str(tmp_path / "key.json"), str(tmp_path / "state.json"),
                             seed=b"\x01" * 32)
        pv.save()
        v = mk_vote(1, 0)
        pv.sign_vote("chain", v)
        assert v.signature
        pv2 = FilePV.load(str(tmp_path / "key.json"), str(tmp_path / "state.json"))
        assert pv2.get_pub_key() == pv.get_pub_key()
        assert pv2.last_sign_state.height == 1

    def test_double_sign_blocked(self, tmp_path):
        pv = FilePV.generate(str(tmp_path / "k.json"), str(tmp_path / "s.json"),
                             seed=b"\x02" * 32)
        v1 = mk_vote(5, 0, bid=BID)
        pv.sign_vote("chain", v1)
        v2 = mk_vote(5, 0, bid=OTHER)
        with pytest.raises(DoubleSignError, match="conflicting data"):
            pv.sign_vote("chain", v2)

    def test_height_regression_blocked(self, tmp_path):
        pv = FilePV.generate(str(tmp_path / "k.json"), str(tmp_path / "s.json"),
                             seed=b"\x03" * 32)
        pv.sign_vote("chain", mk_vote(5, 0))
        with pytest.raises(DoubleSignError, match="height regression"):
            pv.sign_vote("chain", mk_vote(4, 0))

    def test_same_vote_differs_only_by_timestamp_resigned(self, tmp_path):
        pv = FilePV.generate(str(tmp_path / "k.json"), str(tmp_path / "s.json"),
                             seed=b"\x04" * 32)
        v1 = mk_vote(5, 0, ts=1_700_000_000_000_000_000)
        pv.sign_vote("chain", v1)
        v2 = mk_vote(5, 0, ts=1_700_000_000_999_999_999)
        pv.sign_vote("chain", v2)  # allowed: only timestamp differs
        assert v2.signature == v1.signature
        assert v2.timestamp_ns == v1.timestamp_ns  # original timestamp restored

    def test_proposal_double_sign_blocked(self, tmp_path):
        pv = FilePV.generate(str(tmp_path / "k.json"), str(tmp_path / "s.json"),
                             seed=b"\x05" * 32)
        p1 = Proposal(7, 0, -1, BID, 1_700_000_000_000_000_000)
        pv.sign_proposal("chain", p1)
        p2 = Proposal(7, 0, -1, OTHER, 1_700_000_000_000_000_000)
        with pytest.raises(DoubleSignError):
            pv.sign_proposal("chain", p2)

    def test_step_progression_allowed(self, tmp_path):
        pv = FilePV.generate(str(tmp_path / "k.json"), str(tmp_path / "s.json"),
                             seed=b"\x06" * 32)
        pv.sign_proposal("chain", Proposal(7, 0, -1, BID, 1))
        pv.sign_vote("chain", mk_vote(7, 0, SignedMsgType.PREVOTE))
        pv.sign_vote("chain", mk_vote(7, 0, SignedMsgType.PRECOMMIT))
        pv.sign_vote("chain", mk_vote(8, 0, SignedMsgType.PREVOTE))


# --- mempool ---------------------------------------------------------------

class TestMempool:
    def _mk(self, **kw):
        app = KVStoreApplication()
        return CListMempool(LocalClient(app), **kw), app

    def test_check_and_reap(self):
        mp, _ = self._mk()
        mp.check_tx(b"a=1")
        mp.check_tx(b"b=2")
        assert mp.size() == 2
        assert mp.reap_max_bytes_max_gas(-1, -1) == [b"a=1", b"b=2"]
        # byte-limited reap
        assert mp.reap_max_bytes_max_gas(len(b"a=1") + 5, -1) == [b"a=1"]
        # gas-limited reap (kvstore wants 1 gas per tx)
        assert mp.reap_max_txs(1) == [b"a=1"]
        assert mp.reap_max_bytes_max_gas(-1, 1) == [b"a=1"]

    def test_duplicate_rejected_by_cache(self):
        mp, _ = self._mk()
        mp.check_tx(b"a=1")
        with pytest.raises(ErrTxInCache):
            mp.check_tx(b"a=1")

    def test_update_removes_committed(self):
        mp, _ = self._mk()
        mp.check_tx(b"a=1")
        mp.check_tx(b"b=2")
        mp.lock()
        try:
            mp.update(1, [b"a=1"], [abci.ResponseCheckTx(code=0)])
        finally:
            mp.unlock()
        assert mp.size() == 1
        assert mp.reap_max_txs(-1) == [b"b=2"]
        # committed tx stays cached: resubmission rejected
        with pytest.raises(ErrTxInCache):
            mp.check_tx(b"a=1")

    def test_invalid_tx_not_added(self):
        mp, _ = self._mk()
        res = mp.check_tx(b"val:zz!bad")  # malformed validator tx
        assert not res.is_ok()
        assert mp.size() == 0
        # and not cached (can retry)
        res2 = mp.check_tx(b"val:zz!bad")
        assert not res2.is_ok()

    def test_full_mempool_errors(self):
        mp, _ = self._mk(max_txs=2)
        mp.check_tx(b"a=1")
        mp.check_tx(b"b=2")
        with pytest.raises(MempoolError, match="mempool is full"):
            mp.check_tx(b"c=3")

    def test_txs_available_notification(self):
        mp, _ = self._mk()
        fired = []
        mp.tx_available_callbacks.append(lambda: fired.append(1))
        mp.check_tx(b"a=1")
        mp.check_tx(b"b=2")
        assert fired == [1]  # only once until reset by update
        mp.lock()
        try:
            mp.update(1, [b"a=1"], [abci.ResponseCheckTx(code=0)])
        finally:
            mp.unlock()
        assert fired == [1, 1]  # remaining tx re-fires

    def test_sender_tracking(self):
        mp, _ = self._mk()
        mp.check_tx(b"a=1", sender="peer1")
        with pytest.raises(ErrTxInCache):
            mp.check_tx(b"a=1", sender="peer2")
        entries, cursor = mp.entries_after(0)
        assert entries[0].senders == {"peer1", "peer2"}
        assert cursor == 1


# --- WAL group commit ------------------------------------------------------

class TestWALGroupCommit:
    RECORDS = [
        # a proposal-plus-parts-shaped batch: internal (sync-wanted) records
        # mixed with external peer records, as the receive loop drains them
        ("proposal", {"proposal": "aa", "peer": ""}, 10, True),
        ("block_part", {"height": 1, "round": 0, "part": "bb", "peer": ""}, 11, True),
        ("block_part", {"height": 1, "round": 0, "part": "cc", "peer": ""}, 12, True),
        ("vote", {"vote": "dd", "peer": "p1"}, 13, False),
        ("timeout", {"duration_s": 0.5, "height": 1, "round": 0, "step": 3}, 14, False),
        ("vote", {"vote": "ee", "peer": ""}, 15, True),
    ]

    def _write(self, wal):
        for type_, data, ts, sync in self.RECORDS:
            (wal.write_sync if sync else wal.write)(type_, data, ts)

    def test_group_commit_replay_byte_identical(self, tmp_path):
        """A group-committed WAL is BYTE-identical to the per-record-sync
        WAL for the same records — replay (and therefore recovered state)
        cannot differ; only the fsync schedule does."""
        per = WAL(str(tmp_path / "per.wal"))
        self._write(per)
        per.close()
        grp = WAL(str(tmp_path / "grp.wal"))
        with grp.group():
            self._write(grp)
        grp.close()
        per_bytes = open(str(tmp_path / "per.wal"), "rb").read()
        grp_bytes = open(str(tmp_path / "grp.wal"), "rb").read()
        assert per_bytes == grp_bytes and len(per_bytes) > 0
        per_msgs = [(m.type, m.data, m.time_ns)
                    for m in WAL(str(tmp_path / "per.wal")).iter_messages()]
        grp_msgs = [(m.type, m.data, m.time_ns)
                    for m in WAL(str(tmp_path / "grp.wal")).iter_messages()]
        assert per_msgs == grp_msgs
        assert len(per_msgs) == len(self.RECORDS) + 1  # + auto #ENDHEIGHT 0

    def test_group_commit_single_fsync(self, tmp_path, monkeypatch):
        from tendermint_tpu.libs.metrics import NodeMetrics

        import tendermint_tpu.consensus.wal as walmod

        wal = WAL(str(tmp_path / "w.wal"))  # init fsync happens unpatched
        wal.metrics = NodeMetrics("t_gc1").consensus
        calls = []
        monkeypatch.setattr(walmod.os, "fsync", lambda fd: calls.append(fd))
        with wal.group():
            self._write(wal)  # 3 sync-wanted records in the batch
        assert len(calls) == 1, "group commit must coalesce to ONE fsync"
        m = wal.metrics
        assert m.wal_fsyncs_total.value() == 1
        assert m.wal_records_per_fsync.count_value() == 1
        assert m.wal_records_per_fsync.sum_value() == len(self.RECORDS)
        # per-record comparison: same records, one fsync per sync-wanted one
        calls.clear()
        wal2 = WAL(str(tmp_path / "w2.wal"))
        self._write(wal2)
        n_sync = sum(1 for r in self.RECORDS if r[3])
        assert len(calls) == n_sync + 1  # + the fresh-WAL #ENDHEIGHT 0

    def test_group_commit_external_only_respects_deadline(self, tmp_path,
                                                          monkeypatch):
        import tendermint_tpu.consensus.wal as walmod

        wal = WAL(str(tmp_path / "w.wal"))
        calls = []
        monkeypatch.setattr(walmod.os, "fsync", lambda fd: calls.append(fd))
        wal.sync_deadline_s = 3600.0  # never due within the test
        with wal.group():
            wal.write("vote", {"vote": "aa", "peer": "p1"}, 1)
            wal.write("vote", {"vote": "bb", "peer": "p2"}, 2)
        assert calls == [], "peer-only batch must not fsync before deadline"
        wal.sync_deadline_s = 0.0  # always due
        with wal.group():
            wal.write("vote", {"vote": "cc", "peer": "p1"}, 3)
        assert len(calls) == 1, "deadline must bound the async tail's lag"

    def test_batch_crossing_commit_relogs_remainder(self, tmp_path):
        """A commit inside a drained batch writes #ENDHEIGHT AFTER records
        phase 1 already appended; crash replay reads only messages after the
        LAST marker, so the batch's unhandled remainder must be re-logged
        after it — otherwise messages that mutated the live round state
        before a crash would silently vanish from recovery."""
        import asyncio

        from tests.test_consensus_single import build_node

        from tendermint_tpu.consensus.state import VoteMessage, _MsgInfo

        def _vote(h, idx_sig):
            return Vote(SignedMsgType.PREVOTE, h, 0, BID,
                        1_700_000_000_000_000_000, b"\xaa" * 20, 0,
                        bytes([idx_sig]) * 64)

        async def run():
            wal = WAL(str(tmp_path / "t.wal"))
            cs, *_ = build_node(wal=wal)
            assert cs.config.wal_group_commit
            commit_trigger = _MsgInfo(VoteMessage(_vote(1, 1)), "p1")
            straggler = _MsgInfo(VoteMessage(_vote(2, 2)), "p2")

            def fake_handle(mi):
                if mi is commit_trigger:
                    # what finalize-commit does mid-batch: marker + height
                    cs.wal.write_end_height(1, 999)
                    cs.state.last_block_height = 1

            cs._handle_msg = fake_handle
            cs._queue.put_nowait(commit_trigger)
            cs._queue.put_nowait(straggler)
            task = asyncio.get_event_loop().create_task(cs.receive_routine())
            while not cs._queue.empty():
                await asyncio.sleep(0.01)
            await asyncio.sleep(0.05)
            task.cancel()
            wal.close()

        asyncio.run(run())
        replayed = WAL(str(tmp_path / "t.wal")).messages_after_end_height(1)
        votes = [m for m in replayed if m.type == "vote"]
        assert len(votes) == 1, ("straggler record lost across the "
                                 f"#ENDHEIGHT marker: {replayed}")
        assert votes[0].data["vote"] == _vote(2, 2).encode().hex()
        # and the pre-marker copy is still there (phase 1 wrote it first)
        all_votes = [m for m in WAL(str(tmp_path / "t.wal")).iter_messages()
                     if m.type == "vote"]
        assert len(all_votes) == 3  # trigger + straggler + re-logged copy

    def test_own_messages_durable_before_handled(self, tmp_path):
        """The reference durability rule (state.go:754,763) under group
        commit: every internal record is fsynced before its message acts on
        the state machine — and therefore before any transition can expose
        it to gossip sends."""
        import asyncio

        from tests.test_consensus_single import build_node, wait_for_height

        events = []

        class TracingWAL(WAL):
            def write_msg_info(self, msg, peer_id, time_ns, internal):
                events.append(("record", internal))
                super().write_msg_info(msg, peer_id, time_ns, internal)

            def _fsync(self):
                events.append(("fsync",))
                super()._fsync()

        async def run():
            wal = TracingWAL(str(tmp_path / "t.wal"))
            cs, mempool, app, bus, pv, _ = build_node(wal=wal)
            assert cs.config.wal_group_commit
            orig_handle = cs._handle_msg

            def traced(mi):
                events.append(("handle", mi.peer_id == ""))
                orig_handle(mi)

            cs._handle_msg = traced
            await cs.start()
            try:
                await wait_for_height(bus, cs, 2)
            finally:
                await cs.stop()

        asyncio.run(run())
        pending_internal = 0
        batch_sizes = []
        since_sync = 0
        for ev in events:
            if ev[0] == "record":
                since_sync += 1
                if ev[1]:
                    pending_internal += 1
            elif ev[0] == "fsync":
                pending_internal = 0
                if since_sync:
                    batch_sizes.append(since_sync)
                since_sync = 0
            elif ev == ("handle", True):
                assert pending_internal == 0, \
                    "own message handled before its WAL record was fsynced"
        # the proposal + its block part(s) are enqueued together, so at
        # least one fsync must have covered a multi-record batch
        assert batch_sizes and max(batch_sizes) >= 2, batch_sizes
