"""WAL framing/corruption, FilePV double-sign protection, mempool semantics."""

import os

import pytest

from tendermint_tpu import crypto
from tendermint_tpu.abci import types as abci
from tendermint_tpu.abci.example.kvstore import KVStoreApplication
from tendermint_tpu.abci.client import LocalClient
from tendermint_tpu.consensus.wal import WAL, TimeoutInfo
from tendermint_tpu.mempool import CListMempool
from tendermint_tpu.mempool.clist_mempool import ErrTxInCache, MempoolError
from tendermint_tpu.privval import FilePV
from tendermint_tpu.privval.file_pv import DoubleSignError
from tendermint_tpu.types import BlockID, PartSetHeader, SignedMsgType, Vote
from tendermint_tpu.types.proposal import Proposal

BID = BlockID(b"\x01" * 32, PartSetHeader(1, b"\x02" * 32))
OTHER = BlockID(b"\x03" * 32, PartSetHeader(1, b"\x04" * 32))


# --- WAL -------------------------------------------------------------------

class TestWAL:
    def test_roundtrip(self, tmp_path):
        wal = WAL(str(tmp_path / "w.wal"))
        wal.write("round_step", {"height": 1, "round": 0, "step": 1}, 123)
        wal.write_timeout(TimeoutInfo(1.5, 1, 0, 3), 124)
        wal.write_end_height(1, 125)
        wal.close()
        msgs = list(WAL(str(tmp_path / "w.wal")).iter_messages())
        # a fresh WAL self-writes #ENDHEIGHT 0 (wal.go BaseWAL.OnStart)
        assert [m.type for m in msgs] == ["end_height", "round_step",
                                          "timeout", "end_height"]
        assert msgs[0].data["height"] == 0
        assert msgs[2].data["duration_s"] == 1.5

    def test_torn_tail_tolerated(self, tmp_path):
        path = str(tmp_path / "w.wal")
        wal = WAL(path)
        wal.write("round_step", {"height": 1}, 1)
        wal.write("round_step", {"height": 2}, 2)
        wal.close()
        with open(path, "ab") as f:
            f.write(b"\x00\x01\x02")  # torn write
        msgs = list(WAL(path).iter_messages())
        assert len(msgs) == 3  # incl. the auto #ENDHEIGHT 0

    def test_corrupt_crc_stops_replay(self, tmp_path):
        path = str(tmp_path / "w.wal")
        wal = WAL(path)
        wal.write("round_step", {"height": 1}, 1)
        wal.write("round_step", {"height": 2}, 2)
        wal.close()
        raw = bytearray(open(path, "rb").read())
        raw[-1] ^= 0xFF  # corrupt last record's payload
        open(path, "wb").write(bytes(raw))
        msgs = list(WAL(path).iter_messages())
        assert len(msgs) == 2  # ENDHEIGHT 0 + first record; corrupt tail dropped

    def test_search_for_end_height(self, tmp_path):
        wal = WAL(str(tmp_path / "w.wal"))
        wal.write_end_height(5, 1)
        wal.write("vote", {"vote": "00", "peer": "p"}, 2)
        assert wal.search_for_end_height(5)
        assert not wal.search_for_end_height(6)
        after = wal.messages_after_end_height(5)
        assert len(after) == 1 and after[0].type == "vote"

    def test_rotation(self, tmp_path):
        path = str(tmp_path / "w.wal")
        wal = WAL(path, head_size_limit=512)
        for i in range(100):
            wal.write("round_step", {"height": i, "pad": "x" * 50}, i)
        wal.close()
        assert os.path.exists(path + ".0")  # rotated
        msgs = [m for m in WAL(path).iter_messages() if m.type == "round_step"]
        assert len(msgs) == 100  # reads across rotated files
        assert [m.data["height"] for m in msgs] == list(range(100))


# --- FilePV ----------------------------------------------------------------

def mk_vote(h, r, t=SignedMsgType.PREVOTE, bid=BID, ts=1_700_000_000_000_000_000):
    return Vote(t, h, r, bid, ts, b"\xaa" * 20, 0)


class TestFilePV:
    def test_sign_and_persist(self, tmp_path):
        pv = FilePV.generate(str(tmp_path / "key.json"), str(tmp_path / "state.json"),
                             seed=b"\x01" * 32)
        pv.save()
        v = mk_vote(1, 0)
        pv.sign_vote("chain", v)
        assert v.signature
        pv2 = FilePV.load(str(tmp_path / "key.json"), str(tmp_path / "state.json"))
        assert pv2.get_pub_key() == pv.get_pub_key()
        assert pv2.last_sign_state.height == 1

    def test_double_sign_blocked(self, tmp_path):
        pv = FilePV.generate(str(tmp_path / "k.json"), str(tmp_path / "s.json"),
                             seed=b"\x02" * 32)
        v1 = mk_vote(5, 0, bid=BID)
        pv.sign_vote("chain", v1)
        v2 = mk_vote(5, 0, bid=OTHER)
        with pytest.raises(DoubleSignError, match="conflicting data"):
            pv.sign_vote("chain", v2)

    def test_height_regression_blocked(self, tmp_path):
        pv = FilePV.generate(str(tmp_path / "k.json"), str(tmp_path / "s.json"),
                             seed=b"\x03" * 32)
        pv.sign_vote("chain", mk_vote(5, 0))
        with pytest.raises(DoubleSignError, match="height regression"):
            pv.sign_vote("chain", mk_vote(4, 0))

    def test_same_vote_differs_only_by_timestamp_resigned(self, tmp_path):
        pv = FilePV.generate(str(tmp_path / "k.json"), str(tmp_path / "s.json"),
                             seed=b"\x04" * 32)
        v1 = mk_vote(5, 0, ts=1_700_000_000_000_000_000)
        pv.sign_vote("chain", v1)
        v2 = mk_vote(5, 0, ts=1_700_000_000_999_999_999)
        pv.sign_vote("chain", v2)  # allowed: only timestamp differs
        assert v2.signature == v1.signature
        assert v2.timestamp_ns == v1.timestamp_ns  # original timestamp restored

    def test_proposal_double_sign_blocked(self, tmp_path):
        pv = FilePV.generate(str(tmp_path / "k.json"), str(tmp_path / "s.json"),
                             seed=b"\x05" * 32)
        p1 = Proposal(7, 0, -1, BID, 1_700_000_000_000_000_000)
        pv.sign_proposal("chain", p1)
        p2 = Proposal(7, 0, -1, OTHER, 1_700_000_000_000_000_000)
        with pytest.raises(DoubleSignError):
            pv.sign_proposal("chain", p2)

    def test_step_progression_allowed(self, tmp_path):
        pv = FilePV.generate(str(tmp_path / "k.json"), str(tmp_path / "s.json"),
                             seed=b"\x06" * 32)
        pv.sign_proposal("chain", Proposal(7, 0, -1, BID, 1))
        pv.sign_vote("chain", mk_vote(7, 0, SignedMsgType.PREVOTE))
        pv.sign_vote("chain", mk_vote(7, 0, SignedMsgType.PRECOMMIT))
        pv.sign_vote("chain", mk_vote(8, 0, SignedMsgType.PREVOTE))


# --- mempool ---------------------------------------------------------------

class TestMempool:
    def _mk(self, **kw):
        app = KVStoreApplication()
        return CListMempool(LocalClient(app), **kw), app

    def test_check_and_reap(self):
        mp, _ = self._mk()
        mp.check_tx(b"a=1")
        mp.check_tx(b"b=2")
        assert mp.size() == 2
        assert mp.reap_max_bytes_max_gas(-1, -1) == [b"a=1", b"b=2"]
        # byte-limited reap
        assert mp.reap_max_bytes_max_gas(len(b"a=1") + 5, -1) == [b"a=1"]
        # gas-limited reap (kvstore wants 1 gas per tx)
        assert mp.reap_max_txs(1) == [b"a=1"]
        assert mp.reap_max_bytes_max_gas(-1, 1) == [b"a=1"]

    def test_duplicate_rejected_by_cache(self):
        mp, _ = self._mk()
        mp.check_tx(b"a=1")
        with pytest.raises(ErrTxInCache):
            mp.check_tx(b"a=1")

    def test_update_removes_committed(self):
        mp, _ = self._mk()
        mp.check_tx(b"a=1")
        mp.check_tx(b"b=2")
        mp.lock()
        try:
            mp.update(1, [b"a=1"], [abci.ResponseCheckTx(code=0)])
        finally:
            mp.unlock()
        assert mp.size() == 1
        assert mp.reap_max_txs(-1) == [b"b=2"]
        # committed tx stays cached: resubmission rejected
        with pytest.raises(ErrTxInCache):
            mp.check_tx(b"a=1")

    def test_invalid_tx_not_added(self):
        mp, _ = self._mk()
        res = mp.check_tx(b"val:zz!bad")  # malformed validator tx
        assert not res.is_ok()
        assert mp.size() == 0
        # and not cached (can retry)
        res2 = mp.check_tx(b"val:zz!bad")
        assert not res2.is_ok()

    def test_full_mempool_errors(self):
        mp, _ = self._mk(max_txs=2)
        mp.check_tx(b"a=1")
        mp.check_tx(b"b=2")
        with pytest.raises(MempoolError, match="mempool is full"):
            mp.check_tx(b"c=3")

    def test_txs_available_notification(self):
        mp, _ = self._mk()
        fired = []
        mp.tx_available_callbacks.append(lambda: fired.append(1))
        mp.check_tx(b"a=1")
        mp.check_tx(b"b=2")
        assert fired == [1]  # only once until reset by update
        mp.lock()
        try:
            mp.update(1, [b"a=1"], [abci.ResponseCheckTx(code=0)])
        finally:
            mp.unlock()
        assert fired == [1, 1]  # remaining tx re-fires

    def test_sender_tracking(self):
        mp, _ = self._mk()
        mp.check_tx(b"a=1", sender="peer1")
        with pytest.raises(ErrTxInCache):
            mp.check_tx(b"a=1", sender="peer2")
        entries, cursor = mp.entries_after(0)
        assert entries[0].senders == {"peer1", "peer2"}
        assert cursor == 1
