"""Pubsub query language + EventBus routing (reference libs/pubsub, types/event_bus.go)."""

import asyncio

import pytest

from tendermint_tpu.libs.pubsub import PubSubServer, Query, SubscriptionCanceled
from tendermint_tpu.types.event_bus import EventBus
from tendermint_tpu.types import events as tme


def test_query_parsing_and_matching():
    q = Query("tm.event='NewBlock'")
    assert q.matches({"tm.event": ["NewBlock"]})
    assert not q.matches({"tm.event": ["Tx"]})
    assert not q.matches({})

    q = Query("tm.event='Tx' AND tx.height>5")
    assert q.matches({"tm.event": ["Tx"], "tx.height": ["6"]})
    assert not q.matches({"tm.event": ["Tx"], "tx.height": ["5"]})

    q = Query("tx.hash EXISTS")
    assert q.matches({"tx.hash": ["AB"]})
    assert not q.matches({})

    q = Query("app.key CONTAINS 'ell'")
    assert q.matches({"app.key": ["hello"]})
    assert not q.matches({"app.key": ["world"]})

    # any-value semantics over repeated keys
    q = Query("app.key='x'")
    assert q.matches({"app.key": ["y", "x"]})


def test_query_parse_errors():
    with pytest.raises(ValueError):
        Query("tm.event=")
    with pytest.raises(ValueError):
        Query("tm.event='a' OR tm.event='b'")


def test_pubsub_routing():
    async def run():
        srv = PubSubServer()
        sub = srv.subscribe("c1", Query("tm.event='A'"))
        srv.publish("one", {"tm.event": ["A"]})
        srv.publish("two", {"tm.event": ["B"]})
        srv.publish("three", {"tm.event": ["A"]})
        assert (await sub.next()).data == "one"
        assert (await sub.next()).data == "three"
        srv.unsubscribe("c1", Query("tm.event='A'"))
        with pytest.raises(SubscriptionCanceled):
            await sub.next()

    asyncio.run(run())


def test_pubsub_capacity_cancels_slow_subscriber():
    async def run():
        srv = PubSubServer()
        sub = srv.subscribe("slow", Query("tm.event='A'"), out_capacity=2)
        for _ in range(3):
            srv.publish("x", {"tm.event": ["A"]})
        # third publish overflowed → canceled
        await sub.next()
        await sub.next()
        with pytest.raises(SubscriptionCanceled):
            await sub.next()

    asyncio.run(run())


def test_event_bus_tx_events():
    async def run():
        bus = EventBus()
        sub = bus.subscribe("test", "tm.event='Tx' AND tx.height=5")
        from tendermint_tpu.abci.types import ResponseDeliverTx

        bus.publish_event_tx(5, 0, b"hello", ResponseDeliverTx())
        bus.publish_event_tx(6, 0, b"other", ResponseDeliverTx())
        msg = await sub.next()
        assert msg.data.height == 5 and msg.data.tx == b"hello"
        assert sub.queue.empty()

    asyncio.run(run())


def test_event_bus_app_event_keys():
    async def run():
        bus = EventBus()
        sub = bus.subscribe("test", "app.creator='alice'")
        from tendermint_tpu.abci.types import Event, EventAttribute, ResponseDeliverTx

        res = ResponseDeliverTx(events=[Event(type="app", attributes=[
            EventAttribute(b"creator", b"alice", True)])])
        bus.publish_event_tx(1, 0, b"t", res)
        msg = await sub.next()
        assert msg.data.tx == b"t"

    asyncio.run(run())
