"""Restart supervision (libs/supervisor.py): backoff schedule, crash-loop
give-up, never-restart default, healthy-uptime budget reset, the crash-loop
bundle, and the e2e manifest's restart/fail_point keys."""

import json

import pytest

from tendermint_tpu.e2e.manifest import Manifest
from tendermint_tpu.libs.supervisor import (RestartPolicy, RestartSupervisor,
                                            policy_from_manifest,
                                            write_crashloop_bundle)


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def _sup(clock, **kw):
    defaults = dict(policy="on-failure", max_restarts=3, backoff_s=0.5,
                    backoff_max_s=4.0, healthy_uptime_s=10.0)
    defaults.update(kw)
    return RestartSupervisor(RestartPolicy(**defaults), name="n",
                             time_fn=clock)


class TestPolicy:
    def test_backoff_schedule_bounded_doubling(self):
        p = RestartPolicy(policy="on-failure", max_restarts=5,
                          backoff_s=0.5, backoff_max_s=3.0)
        assert p.schedule() == [0.5, 1.0, 2.0, 3.0, 3.0]  # capped

    def test_validation(self):
        with pytest.raises(ValueError, match="unknown restart policy"):
            RestartPolicy(policy="always").validate()
        with pytest.raises(ValueError, match="max_restarts"):
            RestartPolicy(max_restarts=-1).validate()
        with pytest.raises(ValueError, match="backoff"):
            RestartPolicy(backoff_s=0.0).validate()
        with pytest.raises(ValueError, match="backoff"):
            RestartPolicy(backoff_s=2.0, backoff_max_s=1.0).validate()
        RestartPolicy().validate()  # defaults are valid


class TestSupervisor:
    def test_never_restart_default(self):
        clock = FakeClock()
        sup = RestartSupervisor(RestartPolicy(), name="n", time_fn=clock)
        sup.on_launch()
        clock.t += 1.0
        assert sup.on_exit(1) is None
        assert not sup.gave_up            # "never" is a decision, not a loop
        assert sup.history[-1].action == "stop"

    def test_clean_exit_never_restarts(self):
        clock = FakeClock()
        sup = _sup(clock)
        sup.on_launch()
        clock.t += 1.0
        assert sup.on_exit(0) is None
        assert sup.history[-1].action == "clean"
        assert sup.restarts == 0

    def test_crash_loop_walks_schedule_then_gives_up(self):
        clock = FakeClock()
        sup = _sup(clock)
        delays = []
        for _ in range(10):
            sup.on_launch()
            clock.t += 0.01           # instant crasher
            d = sup.on_exit(1)
            if d is None:
                break
            delays.append(d)
        assert delays == [0.5, 1.0, 2.0]
        assert sup.gave_up and sup.restarts == 3
        assert sup.history[-1].action == "give-up"
        # once given up, it stays down
        sup.on_launch()
        clock.t += 0.01
        assert sup.on_exit(1) is None

    def test_healthy_uptime_resets_budget(self):
        clock = FakeClock()
        sup = _sup(clock)
        for _ in range(8):            # crashes forever, but slowly
            sup.on_launch()
            clock.t += 60.0           # > healthy_uptime_s per life
            assert sup.on_exit(1) == 0.5   # backoff stays at base
        assert not sup.gave_up

    def test_signal_exits_labeled(self):
        clock = FakeClock()
        sup = _sup(clock)
        sup.on_launch()
        clock.t += 0.1
        sup.on_exit(-9)               # SIGKILL
        assert sup.history[-1].reason == "signal-9"

    def test_bundle_has_history_and_log_tail(self, tmp_path):
        clock = FakeClock()
        sup = _sup(clock, max_restarts=1)
        for _ in range(3):
            sup.on_launch()
            clock.t += 0.01
            if sup.on_exit(2) is None:
                break
        log = tmp_path / "n.log"
        log.write_text("boot\nboom: the last words\n")
        path = write_crashloop_bundle(str(tmp_path), sup,
                                      extras={"why": "test"},
                                      log_path=str(log))
        doc = json.loads(open(path).read())
        assert doc["crashloop"]["gave_up"] is True
        assert doc["crashloop"]["history"][-1]["action"] == "give-up"
        assert "last words" in doc["log_tail"]
        assert doc["extras"]["why"] == "test"


class TestManifestKeys:
    BASE = {
        "chain_id": "t",
        "node": {
            "v0": {"mode": "validator"},
            "v1": {"mode": "validator"},
        },
    }

    def _doc(self, **node_kw):
        doc = json.loads(json.dumps(self.BASE))
        doc["node"]["v1"].update(node_kw)
        return doc

    def test_roundtrip_defaults(self):
        m = Manifest.from_doc(self._doc())
        nm = [n for n in m.nodes if n.name == "v1"][0]
        assert nm.restart_policy == "never"
        assert nm.fail_point == ""
        pol = policy_from_manifest(nm)
        assert pol.policy == "never"

    def test_restart_keys_parse(self):
        m = Manifest.from_doc(self._doc(restart_policy="on-failure",
                                        max_restarts=5, backoff_s=0.25))
        nm = [n for n in m.nodes if n.name == "v1"][0]
        pol = policy_from_manifest(nm)
        assert (pol.policy, pol.max_restarts, pol.backoff_s) == \
            ("on-failure", 5, 0.25)
        assert pol.schedule()[0] == 0.25

    def test_fail_point_needs_on_failure(self):
        with pytest.raises(ValueError, match="on-failure"):
            Manifest.from_doc(self._doc(fail_point="wal.after_fsync"))
        m = Manifest.from_doc(self._doc(fail_point="wal.after_fsync",
                                        restart_policy="on-failure"))
        nm = [n for n in m.nodes if n.name == "v1"][0]
        assert nm.fail_point == "wal.after_fsync"

    def test_unknown_fail_point_rejected(self):
        with pytest.raises(ValueError, match="unknown fail point"):
            Manifest.from_doc(self._doc(fail_point="wal.no_such_boundary",
                                        restart_policy="on-failure"))

    def test_bad_restart_policy_rejected(self):
        with pytest.raises(ValueError, match="unknown restart policy"):
            Manifest.from_doc(self._doc(restart_policy="sometimes"))

    def test_shipped_manifests_all_load(self):
        """Every checked-in e2e manifest (ci-crash.toml included) parses
        and validates — manifest rot fails tier-1, not the first operator
        who needs it."""
        import os

        mdir = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tendermint_tpu", "e2e", "manifests")
        names = sorted(n for n in os.listdir(mdir) if n.endswith(".toml"))
        assert "ci-crash.toml" in names
        for name in names:
            m = Manifest.load(os.path.join(mdir, name))
            assert m.nodes, name
        crash = Manifest.load(os.path.join(mdir, "ci-crash.toml"))
        crasher = [n for n in crash.nodes if n.name == "crasher"][0]
        assert crasher.fail_point == "wal.after_fsync"
        assert crasher.restart_policy == "on-failure"

    def test_fail_point_env_is_one_shot_across_any_relaunch(self, tmp_path):
        """TMTPU_FAIL_POINT arms only a node's FIRST launch — supervised
        restarts AND perturbation relaunches must drop it, or the node
        dies at the boundary forever."""
        from tendermint_tpu.e2e.runner import Runner

        m = Manifest.from_doc(self._doc(fail_point="wal.after_fsync",
                                        restart_policy="on-failure"))
        r = Runner(m, str(tmp_path))
        nm = [n for n in m.nodes if n.name == "v1"][0]
        env1 = r._env(nm, first_launch="v1" not in r._launched)
        assert env1.get("TMTPU_FAIL_POINT") == "wal.after_fsync"
        r._launched.add("v1")  # what _launch records on every launch
        env2 = r._env(nm, first_launch="v1" not in r._launched,
                      restart_reason="crash")
        assert "TMTPU_FAIL_POINT" not in env2
        assert env2["TMTPU_RESTART_REASON"] == "crash"

    def test_fail_points_cover_crashmatrix_catalog(self):
        """Every code-site boundary the crash matrix enumerates is
        manifest-armable (the subprocess variant of the same matrix).
        Window boundaries (net.during_quorum_loss) are rig-orchestrated
        timing windows, not fail points — but the site each one arms
        INSIDE its window must itself be armable."""
        import os
        import sys

        from tendermint_tpu.libs.fail import KNOWN_FAIL_POINTS

        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tools"))
        try:
            import crashmatrix
        finally:
            sys.path.pop(0)
        code_sites = (set(crashmatrix.ALL_BOUNDARIES)
                      - set(crashmatrix.QUORUM_BOUNDARIES))
        assert code_sites <= KNOWN_FAIL_POINTS
        assert crashmatrix.QUORUM_KILL_SITE in KNOWN_FAIL_POINTS
        assert not set(crashmatrix.QUORUM_BOUNDARIES) & KNOWN_FAIL_POINTS
