"""Unit tests for the segmented double-buffered batch_verify_stream path
(the flagship 10k-validator optimization: segment i+1's pack+transfer
overlaps segment i's device compute through the relay).

The device kernel itself is covered differentially by test_sparse_verify /
test_ed25519_jax; here the dispatch step is faked so the orchestration
(segment sizing, ordering, boundary reassembly, ok-mask merge, pipeline
depth) is tested without compiling segment-shaped XLA kernels on CPU.
"""

import numpy as np
import pytest

from tendermint_tpu.crypto import ed25519 as host
from tendermint_tpu.crypto.ed25519_jax import verify as V


def test_segment_sizes():
    assert V._segment_sizes(1) == [1]
    assert V._segment_sizes(2) == [1, 1]
    assert V._segment_sizes(5) == [3, 2]
    assert V._segment_sizes(10) == [5, 5]
    assert V._segment_sizes(11) == [6, 5]
    assert V._segment_sizes(16) == [8, 8]
    assert V._segment_sizes(30) == [10, 10, 10]
    assert V._segment_sizes(31) == [8, 8, 8, 7]
    for k in range(1, 200):
        sizes = V._segment_sizes(k)
        assert sum(sizes) == k
        assert all(0 < s <= V.SEG_CHUNKS for s in sizes)
        if k > 1:
            assert len(sizes) >= 2  # two segments minimum for overlap
            assert max(sizes) - min(sizes) <= 1  # near-equal


class _FakeDev:
    """Stands in for the device verdict array; np.asarray(fake) works."""

    def __init__(self, arr):
        self._arr = arr

    def __array__(self, dtype=None, copy=None):
        return self._arr


def test_segmented_reassembly_and_ordering(monkeypatch):
    """Verdicts land at the right global offsets regardless of worker
    completion order, and the ok-mask merges per segment."""
    calls = []

    def fake_dispatch(pks, msgs, sigs, chunk):
        calls.append(len(pks))
        # verdict: sig == b"good" + index bytes; ok-mask: pk length valid
        verd = np.array([s[:4] == b"good" for s in sigs])
        ok = np.array([len(p) == 32 for p in pks])
        # pad to whole chunks like the real kernel output
        k = -(-len(pks) // chunk)
        verd = np.pad(verd, (0, k * chunk - len(pks)))
        return _FakeDev(verd), ok

    monkeypatch.setattr(V, "_dispatch_stream", fake_dispatch)
    n = 1000
    chunk = V.LANE  # 128 -> 8 chunks -> segments [4, 4]
    pks = [b"\x01" * 32] * n
    msgs = [b"m"] * n
    sigs = [b"good" + bytes([i % 251]) for i in range(n)]
    bad = {0, 127, 128, 511, 512, 999}
    for i in bad:
        sigs[i] = b"bad!" + bytes(1)
    badpk = {5, 513}
    for i in badpk:
        pks[i] = b"\x01" * 31

    monkeypatch.setattr(V, "SEG_MIN_SIGS", 256)
    out = V._verify_segmented(pks, msgs, sigs, chunk)
    want = np.ones(n, bool)
    for i in bad | badpk:
        want[i] = False
    np.testing.assert_array_equal(out, want)
    assert len(calls) == 2 and sum(calls) == n and calls[0] == 512


def test_stream_entry_routes_large_batches_to_segments(monkeypatch):
    seen = []

    def fake_segmented(pks, msgs, sigs, chunk, t_entry=None):
        seen.append(len(pks))
        return np.ones(len(pks), bool)

    monkeypatch.setattr(V, "_verify_segmented", fake_segmented)
    monkeypatch.setattr(V, "SEG_MIN_SIGS", 300)
    pks = [b"\x01" * 32] * 400
    msgs = [b"same message"] * 400
    sigs = [b"\x02" * 64] * 400
    out = V.batch_verify_stream(pks, msgs, sigs, chunk=V.LANE)
    assert seen == [400] and out.all()


def test_segmented_worker_exception_propagates(monkeypatch):
    def boom(pks, msgs, sigs, chunk):
        raise RuntimeError("relay dropped the connection")

    monkeypatch.setattr(V, "_dispatch_stream", boom)
    with pytest.raises(RuntimeError, match="relay dropped"):
        V._verify_segmented([b"\x01" * 32] * 512, [b"m"] * 512,
                            [b"\x02" * 64] * 512, V.LANE)


def test_dispatch_stream_dense_fallback_shapes():
    """_dispatch_stream's dense branch (dissimilar messages) keeps the
    (K, NBLK, 32, B, LANE) layout contract: verdicts land in row order.
    Small shapes only — the heavy differential coverage is in
    test_sparse_verify (CPU) and test_tpu_device (real chip, segmented)."""
    import pytest

    pytest.importorskip("cryptography", reason="needs the optional 'cryptography' package (absent in slim containers)")
    rng = np.random.default_rng(2)
    pks, msgs, sigs = [], [], []
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PrivateKey,
    )

    for i in range(144):  # > one 128-lane chunk -> K=2 stream kernel
        priv = Ed25519PrivateKey.from_private_bytes(
            bytes(rng.integers(0, 256, 32, dtype=np.uint8)))
        m = bytes(rng.integers(0, 256, 120, dtype=np.uint8))  # dissimilar
        s = priv.sign(m)
        if i in (0, 127, 128, 143):
            s = s[:32] + bytes(32)
        pks.append(priv.public_key().public_bytes_raw())
        msgs.append(m)
        sigs.append(s)
    assert V.prepare_sparse_stream(pks, msgs, sigs, 128) is None
    dev, ok = V._dispatch_stream(pks, msgs, sigs, 128)
    out = np.asarray(dev).reshape(-1)[:144] & ok
    truth = np.array([host.verify(p, m, s)
                      for p, m, s in zip(pks, msgs, sigs)])
    np.testing.assert_array_equal(out, truth)
