"""Device-plane profiler: the ONE supported tool behind PROFILE/MULTICHIP.

Consolidates and retires the eight throwaway scripts that reverse-engineered
PROFILE_r05.json's relay cost model (tools/relay_probe{,2,3}.py,
tools/exp_10k{,_b,_c,_d,_e}.py). Three subcommands, each emitting a
schema-versioned PROFILE JSON (``tmtpu-device-profile/v1`` — the
machine-generated successor to the hand-written PROFILE_r05.json /
MULTICHIP_r0x.json artifacts) plus a markdown table:

* ``cost-model`` — the relay cost model, re-measured: fixed dispatch cost
  (resident input, scalar output), per-thread transfer bandwidth from a
  payload-size ladder, the no-cross-run-dedup check (a near-copy payload
  must pay full price), the no-same-thread-pipelining check (two dispatches
  from one thread cost ~2x one), and the worker-overlap probe (a second
  thread's dispatch DOES overlap an in-flight one — the fact the flagship's
  segmented pipeline is built on). Trivial kernels: measures the relay, not
  ed25519 compute.
* ``sweep`` — chunk-size x SEG_CHUNKS grid through the real
  ``batch_verify_stream`` path -> sigs/s table with pack-share and
  pipeline-overlap from the crypto/phases.py recorder.
* ``scale`` — devices x chunk scaling, one fresh subprocess per device
  count (the forced host-platform CPU mesh makes this dry-runnable on a
  machine with no TPU: ``--host-mesh``). Three modes per cell: the
  ``sharded`` psum path (ed25519_jax/sharded.py), raw ``threads`` x
  devices dense-stream dispatch, and ``multidev`` — the PRODUCTION
  multi-device dispatcher (ed25519_jax/multidevice.py MultiDeviceStream)
  the multichip flagship metric rides. MULTICHIP_r06.json is this
  subcommand's output, checked in.

Workloads: ``--workload ed25519`` runs the real verify kernels;
``--workload synthetic`` swaps in byte-identical-shape stub kernels (same
wire format, same host packing, trivial device compute) so transfer/
dispatch costs are measurable on CPU-only machines without multi-minute
XLA compiles of the verify kernel. ``auto`` (default) picks synthetic on
the CPU backend, ed25519 elsewhere. Signature bytes are random — the
kernels do identical work for invalid signatures, so throughput numbers
are unaffected and no signing keys are needed.

    python tools/device_profile.py cost-model --out PROFILE_rX.json
    python tools/device_profile.py sweep --chunks 1024,2048,4096 --seg-chunks 5,10,20
    python tools/device_profile.py scale --devices 1,2,4,8 --chunks 1024,2048
    python tools/device_profile.py --self-test
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

SCHEMA = "tmtpu-device-profile/v1"
#: synthetic SCALE cells burn this much per-element device work so the
#: dispatch topology — not GIL-bound host packing — is what scales
DEFAULT_SCALE_DEVICE_WORK = 20000
KINDS = ("cost-model", "sweep", "scale")
MB = 1 << 20

#: per-kind required result keys (the schema's load-bearing part)
REQUIRED_RESULTS = {
    "cost-model": ("fixed_dispatch_ms", "transfer", "no_cross_run_dedup",
                   "same_thread_pipelining", "worker_overlap"),
    "sweep": ("workload", "table"),
    "scale": ("workload", "table"),
}
_ROW_KEYS = {
    "sweep": ("chunk", "seg_chunks", "sigs_per_sec"),
    "scale": ("devices", "mode", "sigs_per_sec"),
}


# -- schema -------------------------------------------------------------------

def platform_info() -> Dict:
    info: Dict = {"python": sys.version.split()[0]}
    try:
        import platform as _pf

        info["machine"] = _pf.machine()
    except Exception:
        info["machine"] = "unknown"
    try:
        import jax

        info["backend"] = jax.default_backend()
        devs = jax.devices()
        info["n_devices"] = len(devs)
        info["devices"] = [f"{d.platform}:{d.id}" for d in devs]
    except Exception as e:
        info["backend"] = f"unavailable: {type(e).__name__}"
        info["n_devices"] = 0
        info["devices"] = []
    return info


def make_doc(kind: str, config: Dict, results: Dict) -> Dict:
    return {
        "schema": SCHEMA,
        "kind": kind,
        "generated_by": "tools/device_profile.py",
        "generated_unix": time.time(),
        "platform": platform_info(),
        "config": config,
        "results": results,
    }


def validate_profile(doc) -> List[str]:
    """Schema check for a PROFILE JSON; returns a list of problems (empty
    = valid). Hand-rolled: the toolbox is stdlib-only by contract."""
    errs: List[str] = []
    if not isinstance(doc, dict):
        return [f"document is {type(doc).__name__}, want object"]
    if doc.get("schema") != SCHEMA:
        errs.append(f"schema: want {SCHEMA!r}, got {doc.get('schema')!r}")
    kind = doc.get("kind")
    if kind not in KINDS:
        errs.append(f"kind: want one of {KINDS}, got {kind!r}")
    if not isinstance(doc.get("generated_unix"), (int, float)):
        errs.append("generated_unix: missing or not a number")
    plat = doc.get("platform")
    if not isinstance(plat, dict):
        errs.append("platform: missing or not an object")
    else:
        if not isinstance(plat.get("backend"), str):
            errs.append("platform.backend: missing or not a string")
        if not isinstance(plat.get("n_devices"), int):
            errs.append("platform.n_devices: missing or not an int")
        if not isinstance(plat.get("devices"), list):
            errs.append("platform.devices: missing or not a list")
    if not isinstance(doc.get("config"), dict):
        errs.append("config: missing or not an object")
    res = doc.get("results")
    if not isinstance(res, dict):
        errs.append("results: missing or not an object")
        return errs
    for key in REQUIRED_RESULTS.get(kind, ()):
        if key not in res:
            errs.append(f"results.{key}: missing")
    if kind == "cost-model" and isinstance(res.get("transfer"), dict):
        bw = res["transfer"].get("bandwidth_mbps")
        # None = ladder delta below the noise floor; a non-finite number
        # would serialize as invalid JSON (Infinity/NaN tokens)
        if bw is not None and not (isinstance(bw, (int, float))
                                   and -1e18 < bw < 1e18):
            errs.append(f"results.transfer.bandwidth_mbps: bad value {bw!r}")
    for tkind, row_keys in _ROW_KEYS.items():
        if kind != tkind:
            continue
        table = res.get("table")
        if not isinstance(table, list) or not table:
            errs.append("results.table: missing or empty")
            continue
        for i, row in enumerate(table):
            if not isinstance(row, dict):
                errs.append(f"results.table[{i}]: not an object")
                continue
            for k in row_keys:
                if k not in row:
                    errs.append(f"results.table[{i}].{k}: missing")
            sps = row.get("sigs_per_sec")
            if not (isinstance(sps, (int, float)) and sps >= 0):
                errs.append(f"results.table[{i}].sigs_per_sec: bad value "
                            f"{sps!r}")
    return errs


def to_markdown(doc: Dict) -> str:
    """A compact markdown rendering of the profile (for the PR/README)."""
    kind = doc.get("kind")
    plat = doc.get("platform", {})
    head = (f"### device_profile {kind} — backend {plat.get('backend')}"
            f" ({plat.get('n_devices')} devices)")
    res = doc.get("results", {})
    lines = [head, ""]
    if kind == "cost-model":
        fd = res["fixed_dispatch_ms"]
        tr = res["transfer"]
        bw = tr.get("bandwidth_mbps")
        lines += ["| probe | result |", "|---|---|",
                  f"| fixed dispatch (resident input) | "
                  f"{fd['min']:.2f}/{fd['med']:.2f} ms min/med |",
                  f"| transfer bandwidth (per thread) | "
                  + (f"{bw:.1f} MB/s |" if bw is not None
                     else "n/a (ladder delta below noise floor) |"),
                  f"| cross-run dedup | "
                  f"{'none (full price)' if res['no_cross_run_dedup']['holds'] else 'DETECTED'} |",
                  f"| same-thread pipelining | "
                  f"{'none (2x cost)' if not res['same_thread_pipelining']['pipelined'] else 'DETECTED'} "
                  f"(ratio {res['same_thread_pipelining']['ratio']:.2f}) |",
                  f"| worker-thread overlap | "
                  f"{'works' if res['worker_overlap']['overlaps'] else 'NO OVERLAP'} "
                  f"(ratio {res['worker_overlap']['ratio']:.2f}) |"]
    elif kind == "sweep":
        lines += ["| chunk | SEG_CHUNKS | sigs/s | pack share | overlap |",
                  "|---|---|---|---|---|"]
        for r in res["table"]:
            ov = r.get("overlap_ratio")
            lines.append(
                f"| {r['chunk']} | {r['seg_chunks']} | "
                f"{r['sigs_per_sec']:.0f} | {r.get('pack_share', 0):.3f} | "
                f"{'-' if ov is None else f'{ov:.2f}'} |")
    elif kind == "scale":
        lines += ["| devices | mode | chunk | threads | sigs/s |",
                  "|---|---|---|---|---|"]
        for r in res["table"]:
            lines.append(
                f"| {r['devices']} | {r['mode']} | "
                f"{r.get('chunk') or '-'} | {r.get('threads') or '-'} | "
                f"{r['sigs_per_sec']:.0f} |")
    return "\n".join(lines)


# -- workload -----------------------------------------------------------------

def build_workload(n: int, msg_len: int = 110, seed: int = 7):
    """Commit-shaped synthetic batch: shared message template with 8
    varying 'timestamp' bytes per item (engages the sparse wire format the
    real path uses), random 32-byte pks, random 64-byte sigs with the s
    half's top byte zeroed (s < L, so the host ok-mask passes every row).
    Verdicts will be garbage — the kernels do identical work either way,
    which is all a throughput/cost probe needs."""
    import numpy as np

    rng = np.random.default_rng(seed)
    tpl = rng.integers(0, 256, msg_len, dtype=np.uint8)
    arr = np.broadcast_to(tpl, (n, msg_len)).copy()
    ts = (1_700_000_000_000_000_000 + np.arange(n, dtype=np.uint64))
    for k in range(8):  # 8 varying bytes, big-endian, vote-timestamp-like
        arr[:, 40 + k] = ((ts >> (8 * (7 - k))) & 0xFF).astype(np.uint8)
    msgs = [row.tobytes() for row in arr]
    pks = [b.tobytes() for b in rng.integers(0, 256, (n, 32), dtype=np.uint8)]
    sig_arr = rng.integers(0, 256, (n, 64), dtype=np.uint8)
    sig_arr[:, 63] = 0  # s < L
    sigs = [b.tobytes() for b in sig_arr]
    return pks, msgs, sigs


def resolve_workload(choice: str) -> str:
    if choice != "auto":
        return choice
    try:
        import jax

        return "synthetic" if jax.default_backend() == "cpu" else "ed25519"
    except Exception:
        return "synthetic"


def install_stub_kernels(V, sharded=None, device_work: int = 0):
    """Swap the verify kernels for byte-identical-SHAPE stubs (same wire
    format in, same verdict shape out, trivial compute) and return a
    restore() callable. The host pack/transfer/dispatch path — the thing
    the relay cost model is about — stays 100% real.

    ``device_work`` > 0 burns that many deterministic per-element LCG
    rounds on device before deciding — a stand-in for the real kernel's
    compute so SCALE measurements see a device-bound workload (with
    trivial stubs a multi-device cell measures host packing contention,
    not the dispatch topology it exists to measure). The verdict stays a
    per-item function of the wire bytes, invariant to segmentation."""
    import jax
    import jax.numpy as jnp

    orig = (V._verify_kernel, V._verify_stream_kernel,
            V._verify_sparse_stream_kernel,
            sharded._verify_kernel if sharded is not None else None)

    def _burn(x):
        if not device_work:
            return x
        return jax.lax.fori_loop(
            0, device_work,
            lambda i, acc: acc * jnp.uint32(1664525)
            + jnp.uint32(1013904223), x)

    def _decide(per_item):
        # LCG rounds are a bijection on uint32, so parity of the burned
        # value is as deterministic as parity of the sum itself
        return _burn(per_item) % 2 == 0

    def _kern(blocks, nblk, s_words):
        return _decide(jnp.sum(blocks, axis=(0, 1), dtype=jnp.uint32)
                       + jnp.sum(s_words, axis=0, dtype=jnp.uint32)
                       + nblk.astype(jnp.uint32))

    stub_kernel = jax.jit(_kern)
    stub_kernel.__wrapped__ = _kern  # sharded full_step calls __wrapped__

    @jax.jit
    def stub_stream(blocks, nblk, s_words):
        return _decide(jnp.sum(blocks, axis=(1, 2), dtype=jnp.uint32)
                       + jnp.sum(s_words, axis=1, dtype=jnp.uint32)
                       + nblk.astype(jnp.uint32))

    @jax.jit
    def stub_sparse(templates, diff_cols, diff_vals, mlen, r_b, a_b, s_b):
        # PER-ITEM only (no whole-template/column-set term): the stub
        # verdict must be invariant to how a batch is segmented across
        # dispatches, so multi-device sharding tests can assert verdict
        # parity against the single-device layout
        per = (jnp.sum(diff_vals, axis=1, dtype=jnp.uint32)
               + jnp.sum(r_b, axis=1, dtype=jnp.uint32)
               + jnp.sum(a_b, axis=1, dtype=jnp.uint32)
               + jnp.sum(s_b, axis=1, dtype=jnp.uint32)
               + mlen.astype(jnp.uint32))
        return _decide(per)

    V._verify_kernel = stub_kernel
    V._verify_stream_kernel = stub_stream
    V._verify_sparse_stream_kernel = stub_sparse
    if sharded is not None:
        sharded._verify_kernel = stub_kernel

    def restore():
        (V._verify_kernel, V._verify_stream_kernel,
         V._verify_sparse_stream_kernel) = orig[:3]
        if sharded is not None:
            sharded._verify_kernel = orig[3]

    return restore


# -- cost-model ---------------------------------------------------------------

def _timed_ms(fn, runs: int) -> Dict[str, float]:
    ts = []
    for i in range(runs):
        t0 = time.perf_counter()
        fn(i)
        ts.append((time.perf_counter() - t0) * 1e3)
    return {"min": min(ts), "med": statistics.median(ts),
            "runs_ms": [round(t, 3) for t in ts]}


def run_cost_model(payload_mb: float = 4.0, runs: int = 4) -> Dict:
    """The relay cost model, re-measured with trivial kernels (perturbed
    inputs + fetched outputs everywhere: the relay caches identical repeat
    computations, PROFILE_r05)."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(0)
    nbytes = max(int(payload_mb * MB), 1 << 12)

    touch = jax.jit(lambda a: jnp.sum(a, dtype=jnp.int32))
    base = rng.integers(0, 255, nbytes, dtype=np.uint8)
    np.asarray(touch(base))  # compile

    # 1. fixed dispatch cost: input resident on device, scalar output
    resident = jax.device_put(base)
    fixed = _timed_ms(lambda i: np.asarray(touch(resident)), runs)

    # 2. per-thread transfer bandwidth from a payload ladder (perturbed
    #    fresh bytes each run so no cache can serve them)
    per_size = []
    for frac in (0.125, 0.5, 1.0):
        sz = max(int(nbytes * frac), 1 << 12)

        def one(i, sz=sz):
            a = rng.integers(0, 255, sz, dtype=np.uint8)
            np.asarray(touch(a))

        one(0)  # compile this shape
        t = _timed_ms(one, runs)
        per_size.append({"mb": sz / MB, "min_ms": round(t["min"], 3),
                         "med_ms": round(t["med"], 3)})
    d_ms = per_size[-1]["min_ms"] - per_size[0]["min_ms"]
    d_mb = per_size[-1]["mb"] - per_size[0]["mb"]
    # below the noise floor the ladder measures dispatch jitter, not
    # transfer: report null rather than a garbage (or Infinity — invalid
    # JSON) number
    bandwidth = round(d_mb / (d_ms / 1e3), 2) if d_ms > 0.05 else None

    # 3. cross-run dedup: a near-copy of the previous payload must pay the
    #    same as fresh bytes (relay does NOT delta-compress)
    def fresh(i):
        np.asarray(touch(rng.integers(0, 255, nbytes, dtype=np.uint8)))

    near = base.copy()

    def near_copy(i):
        near[i] ^= 1
        near[nbytes // 2 + i] ^= 1
        np.asarray(touch(near))

    t_fresh = _timed_ms(fresh, runs)
    t_near = _timed_ms(near_copy, runs)
    dedup_ratio = t_near["min"] / max(t_fresh["min"], 1e-6)

    # 4. same-thread pipelining: two independent dispatches from ONE thread,
    #    both fetched at the end — serial relays cost ~2x one
    def two(i):
        a = rng.integers(0, 255, nbytes, dtype=np.uint8)
        b = rng.integers(0, 255, nbytes, dtype=np.uint8)
        ra, rb = touch(a), touch(b)
        np.asarray(ra), np.asarray(rb)

    t_one = t_fresh
    t_two = _timed_ms(two, runs)
    pipe_ratio = t_two["min"] / max(t_one["min"], 1e-6)

    # 5. worker overlap: the same two dispatches from two THREADS — the
    #    overlap the segmented pipeline exploits (913 -> 510 ms on the 61k
    #    commit workload, PROFILE_r05)
    def one_thread_job():
        a = rng.integers(0, 255, nbytes, dtype=np.uint8)
        np.asarray(touch(a))

    def overlapped(i):
        ths = [threading.Thread(target=one_thread_job) for _ in range(2)]
        for t in ths:
            t.start()
        for t in ths:
            t.join()

    t_serial2 = t_two
    t_overlap = _timed_ms(overlapped, runs)
    overlap_ratio = t_overlap["min"] / max(t_serial2["min"], 1e-6)

    return {
        "fixed_dispatch_ms": {"min": round(fixed["min"], 3),
                              "med": round(fixed["med"], 3)},
        "transfer": {"bandwidth_mbps": bandwidth, "per_size": per_size},
        "no_cross_run_dedup": {
            "fresh_min_ms": round(t_fresh["min"], 3),
            "near_copy_min_ms": round(t_near["min"], 3),
            "ratio": round(dedup_ratio, 3),
            # a near-copy at >=70% of fresh cost means no dedup is helping
            "holds": bool(dedup_ratio >= 0.7)},
        "same_thread_pipelining": {
            "one_min_ms": round(t_one["min"], 3),
            "two_min_ms": round(t_two["min"], 3),
            "ratio": round(pipe_ratio, 3),
            # two-for-much-less-than-2x would mean the relay pipelines a
            # single thread's dispatches; 1.5x is the decision boundary
            "pipelined": bool(pipe_ratio < 1.5)},
        "worker_overlap": {
            "serial_two_min_ms": round(t_serial2["min"], 3),
            "overlapped_two_min_ms": round(t_overlap["min"], 3),
            "ratio": round(overlap_ratio, 3),
            "overlaps": bool(overlap_ratio < 0.8)},
    }


# -- sweep --------------------------------------------------------------------

def run_sweep(sigs: int, chunks: List[int], seg_chunks: List[int],
              workload: str, runs: int = 3,
              seg_min_sigs: Optional[int] = None) -> Dict:
    """chunk x SEG_CHUNKS grid through the real batch_verify_stream path;
    sigs/s + pack share + pipeline overlap per cell from crypto/phases.py."""
    from tendermint_tpu.crypto import phases
    from tendermint_tpu.crypto.ed25519_jax import verify as V

    restore = (install_stub_kernels(V) if workload == "synthetic"
               else lambda: None)
    pks, msgs, sigs_b = build_workload(sigs)
    rows = []
    old_sc, old_min = V.SEG_CHUNKS, V.SEG_MIN_SIGS
    try:
        if seg_min_sigs is not None:
            V.SEG_MIN_SIGS = seg_min_sigs
        for chunk in chunks:
            for sc in seg_chunks:
                V.SEG_CHUNKS = sc
                V.batch_verify_stream(pks, msgs, sigs_b, chunk=chunk)  # warm
                phases.reset()
                times = []
                for _ in range(runs):
                    t0 = time.perf_counter()
                    V.batch_verify_stream(pks, msgs, sigs_b, chunk=chunk)
                    times.append(time.perf_counter() - t0)
                tot = phases.phase_totals()
                wall = sum(times)
                fly_sum = tot["inflight_sum_s"]
                rows.append({
                    "chunk": chunk, "seg_chunks": sc, "sigs": sigs,
                    "best_s": round(min(times), 4),
                    "sigs_per_sec": round(sigs / min(times), 1),
                    "pack_share": round(tot["pack_s"] / max(wall, 1e-9), 4),
                    "segments": int(tot["segments"]),
                    "overlap_ratio": (
                        round(tot["inflight_union_s"] / fly_sum, 3)
                        if fly_sum > 0 else None),
                })
    finally:
        V.SEG_CHUNKS, V.SEG_MIN_SIGS = old_sc, old_min
        restore()
    return {"workload": workload, "table": rows}


# -- scale --------------------------------------------------------------------

def run_scale_cell(devices: int, chunks: List[int], sigs: int,
                   workload: str, host_mesh: bool, runs: int = 3,
                   threads: Optional[int] = None,
                   device_work: int = DEFAULT_SCALE_DEVICE_WORK) -> Dict:
    """One device-count cell, meant to run in a FRESH process (the forced
    host-platform device count is fixed at backend init). Measures (a) the
    sharded psum-tally path over the whole mesh and (b) per-chunk rows
    where N threads each dispatch a dense stream shard to their own
    device — the near-linear-scaling claim the multichip dispatcher rests
    on (PROFILE_r05 worker_thread_overlap)."""
    if host_mesh:
        # strip any previous force-count token, then pin ours; works even
        # though sitecustomize imported jax already — backends init lazily
        flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
                 if "xla_force_host_platform_device_count" not in f]
        flags.append(f"--xla_force_host_platform_device_count={devices}")
        os.environ["XLA_FLAGS"] = " ".join(flags)
        import jax

        jax.config.update("jax_platforms", "cpu")
    else:
        import jax

    import numpy as np

    from tendermint_tpu.crypto import phases  # noqa: F401 (recorder active)
    from tendermint_tpu.crypto.ed25519_jax import sharded as S
    from tendermint_tpu.crypto.ed25519_jax import verify as V

    if len(jax.devices()) < devices:
        raise RuntimeError(f"need {devices} devices, have "
                           f"{len(jax.devices())} (use --host-mesh)")
    restore = (install_stub_kernels(V, sharded=S, device_work=device_work)
               if workload == "synthetic" else lambda: None)
    n_threads = threads or devices
    pks, msgs, sigs_b = build_workload(sigs)
    rows = []
    try:
        # (a) sharded mesh: one shard_map dispatch + exact psum tally
        mesh = S.make_mesh(devices)
        S.batch_verify_sharded(pks, msgs, sigs_b, mesh=mesh)  # warm
        times = []
        for _ in range(runs):
            t0 = time.perf_counter()
            S.batch_verify_sharded(pks, msgs, sigs_b, mesh=mesh)
            times.append(time.perf_counter() - t0)
        rows.append({"devices": devices, "mode": "sharded", "chunk": None,
                     "threads": None, "sigs": sigs,
                     "sigs_per_sec": round(sigs / min(times), 1)})

        # (b) threads x devices: thread j packs + dispatches its shard onto
        # device j — the multichip dispatcher's shape (one packing/transfer
        # worker per device, overlapping in-flight execution)
        devs = jax.devices()[:devices]
        per = max(-(-sigs // n_threads) // V.LANE, 1) * V.LANE
        shards = [(pks[a:a + per], msgs[a:a + per], sigs_b[a:a + per])
                  for a in range(0, sigs, per)]
        for chunk in chunks:
            shard_chunk = min(chunk, per)

            def job(j):
                p, m, s = shards[j % len(shards)]
                args, _ok = V._pack_stream_dense(p, m, s, shard_chunk)
                dev_args = [jax.device_put(a, devs[j % devices])
                            for a in args]
                np.asarray(V._verify_stream_kernel(*dev_args))

            used = min(n_threads, len(shards))
            for j in range(used):
                job(j)  # warm every device + shape
            times = []
            for _ in range(runs):
                ths = [threading.Thread(target=job, args=(j,))
                       for j in range(used)]
                t0 = time.perf_counter()
                for t in ths:
                    t.start()
                for t in ths:
                    t.join()
                times.append(time.perf_counter() - t0)
            # actual signatures verified (the tail shard can be short —
            # counting `per * used` would inflate the scaling table)
            done_sigs = sum(len(shards[j % len(shards)][0])
                            for j in range(used))
            rows.append({"devices": devices, "mode": "threads",
                         "chunk": chunk, "threads": used,
                         "sigs": done_sigs,
                         "sigs_per_sec": round(done_sigs / min(times), 1)})

        # (c) the PRODUCTION dispatcher: MultiDeviceStream shards one
        # batch_verify_stream call round-robin across per-device lanes
        # (one packing/transfer worker each, per-device breakers) — the
        # rows the multichip flagship metric is judged against
        from tendermint_tpu.crypto.ed25519_jax import multidevice as MD

        pool = MD.MultiDeviceStream(devices=devs, min_sigs=0)
        try:
            for chunk in chunks:
                c = min(chunk, max(sigs // 2 // V.LANE, 1) * V.LANE)
                pool.verify(pks, msgs, sigs_b, chunk=c)  # warm every lane
                times = []
                for _ in range(runs):
                    t0 = time.perf_counter()
                    pool.verify(pks, msgs, sigs_b, chunk=c)
                    times.append(time.perf_counter() - t0)
                rows.append({"devices": devices, "mode": "multidev",
                             "chunk": c, "threads": devices, "sigs": sigs,
                             "sigs_per_sec": round(sigs / min(times), 1)})
        finally:
            pool.shutdown()
    finally:
        restore()
    return {"devices": devices, "rows": rows}


def run_scale(devices_list: List[int], chunks: List[int], sigs: int,
              workload: str, host_mesh: bool, runs: int,
              threads: Optional[int], timeout_s: float = 600.0,
              device_work: int = DEFAULT_SCALE_DEVICE_WORK) -> Dict:
    """Spawn one _scale-cell subprocess per device count (a process can
    only force one host-platform device count) and merge the tables."""
    rows, errors = [], []
    for d in devices_list:
        cmd = [sys.executable, os.path.abspath(__file__), "_scale-cell",
               "--devices", str(d), "--sigs", str(sigs),
               "--chunks", ",".join(map(str, chunks)),
               "--workload", workload, "--runs", str(runs),
               "--device-work", str(device_work)]
        if host_mesh:
            cmd.append("--host-mesh")
        if threads:
            cmd += ["--threads", str(threads)]
        env = dict(os.environ)
        if host_mesh:
            env["JAX_PLATFORMS"] = "cpu"
            env.pop("PALLAS_AXON_POOL_IPS", None)  # no relay from dry runs
        try:
            res = subprocess.run(cmd, capture_output=True, text=True,
                                 timeout=timeout_s, env=env, cwd=REPO)
        except subprocess.TimeoutExpired:
            errors.append({"devices": d, "error": "timeout"})
            continue
        cell = None
        for line in res.stdout.splitlines():
            if line.startswith("{"):
                try:
                    cell = json.loads(line)
                except json.JSONDecodeError:
                    pass
        if res.returncode != 0 or cell is None:
            errors.append({"devices": d, "rc": res.returncode,
                           "stderr_tail": res.stderr[-800:]})
            continue
        rows.extend(cell["rows"])
    out: Dict = {"workload": workload, "table": rows}
    if errors:
        out["cell_errors"] = errors
    return out


# -- CLI ----------------------------------------------------------------------

def _ints(s: str) -> List[int]:
    return [int(x) for x in s.split(",") if x]


def emit(doc: Dict, out: Optional[str], md: Optional[str]) -> None:
    errs = validate_profile(doc)
    if errs:  # the tool must never write an artifact its own schema rejects
        raise SystemExit("device_profile: generated profile fails its "
                         "schema: " + "; ".join(errs))
    print(to_markdown(doc))
    if out:
        with open(out, "w") as f:
            # allow_nan=False: an Infinity/NaN that slipped past the schema
            # would serialize as tokens strict JSON parsers reject
            json.dump(doc, f, indent=1, allow_nan=False)
        print(f"\nwrote {out}")
    else:
        print()
        print(json.dumps(doc, allow_nan=False))
    if md:
        with open(md, "w") as f:
            f.write(to_markdown(doc) + "\n")


def self_test() -> int:
    import numpy as np  # noqa: F401 — fail fast if the env lacks numpy

    # 1. schema: hand-built minimal docs of each kind validate; mutations
    #    are rejected with pointed messages
    samples = {
        "cost-model": {
            "fixed_dispatch_ms": {"min": 1.0, "med": 2.0},
            "transfer": {"bandwidth_mbps": 10.0, "per_size": []},
            "no_cross_run_dedup": {"holds": True},
            "same_thread_pipelining": {"ratio": 2.0, "pipelined": False},
            "worker_overlap": {"ratio": 0.6, "overlaps": True},
        },
        "sweep": {"workload": "synthetic", "table": [
            {"chunk": 2048, "seg_chunks": 10, "sigs_per_sec": 1000.0,
             "pack_share": 0.1, "overlap_ratio": 0.8}]},
        "scale": {"workload": "synthetic", "table": [
            {"devices": 2, "mode": "sharded", "chunk": None,
             "threads": None, "sigs_per_sec": 500.0}]},
    }
    for kind, res in samples.items():
        doc = make_doc(kind, {"synthetic_sample": True}, res)
        assert validate_profile(doc) == [], (kind, validate_profile(doc))
        assert to_markdown(doc).startswith("### device_profile")
        broken = json.loads(json.dumps(doc))
        del broken["results"][REQUIRED_RESULTS[kind][0]]
        errs = validate_profile(broken)
        assert errs and REQUIRED_RESULTS[kind][0] in errs[0], errs
    assert validate_profile({"schema": "nope"})  # wrong everything
    assert validate_profile([1, 2])  # not even an object
    # bandwidth: null (below noise floor) is valid; Infinity is not JSON
    nf = make_doc("cost-model", {}, json.loads(
        json.dumps(samples["cost-model"])))
    nf["results"]["transfer"]["bandwidth_mbps"] = None
    assert validate_profile(nf) == []
    nf["results"]["transfer"]["bandwidth_mbps"] = float("inf")
    assert any("bandwidth" in e for e in validate_profile(nf))

    # 2. workload builder: template-similar messages (sparse-format
    #    eligible), s < L on every row
    pks, msgs, sigs = build_workload(256)
    assert len({len(m) for m in msgs}) == 1 and len(pks) == 256
    assert all(s[63] == 0 for s in sigs)
    diff_cols = {i for a in msgs[1:4] for i, (x, y)
                 in enumerate(zip(msgs[0], a)) if x != y}
    assert 0 < len(diff_cols) <= 8, diff_cols

    # 3. a real (micro) cost-model run end-to-end through emit's schema
    #    check — trivial kernels, so this is cheap even on cold CPU
    doc = make_doc("cost-model", {"payload_mb": 0.0625, "runs": 2},
                   run_cost_model(payload_mb=0.0625, runs=2))
    assert validate_profile(doc) == [], validate_profile(doc)

    # 4. a micro sweep with stub kernels through the REAL segmented
    #    batch_verify_stream path (chunk=128 -> 4 scan chunks, forced
    #    segmentation) — phases recorder feeds pack share + overlap
    doc = make_doc("sweep", {"sigs": 512}, run_sweep(
        sigs=512, chunks=[128], seg_chunks=[2], workload="synthetic",
        runs=1, seg_min_sigs=0))
    assert validate_profile(doc) == [], validate_profile(doc)
    row = doc["results"]["table"][0]
    assert row["sigs_per_sec"] > 0 and row["segments"] >= 2, row
    assert row["overlap_ratio"] is not None

    # 5. one scale cell in a fresh subprocess on a forced 2-device CPU
    #    mesh: the sharded row, a threads x devices row, AND the
    #    production MultiDeviceStream dispatcher row all land
    doc = make_doc("scale", {"devices": [2]}, run_scale(
        [2], chunks=[128], sigs=256, workload="synthetic", host_mesh=True,
        runs=1, threads=None, timeout_s=300.0))
    errs = validate_profile(doc)
    assert errs == [], (errs, doc["results"].get("cell_errors"))
    modes = {r["mode"] for r in doc["results"]["table"]}
    assert modes == {"sharded", "threads", "multidev"}, \
        doc["results"]["table"]
    md_row = next(r for r in doc["results"]["table"]
                  if r["mode"] == "multidev")
    assert md_row["sigs_per_sec"] > 0 and md_row["devices"] == 2

    print("device_profile self-test OK (schema, workload, cost-model, "
          "sweep, scale cell incl. multidev stream)")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("command", nargs="?",
                    choices=list(KINDS) + ["_scale-cell"])
    ap.add_argument("--out", help="write the PROFILE JSON here "
                                  "(default: print to stdout)")
    ap.add_argument("--md", help="also write the markdown table here")
    ap.add_argument("--runs", type=int, default=3)
    ap.add_argument("--payload-mb", type=float, default=4.0,
                    help="cost-model probe payload size")
    ap.add_argument("--sigs", type=int, default=20480,
                    help="sweep/scale workload size")
    ap.add_argument("--chunks", type=_ints, default=[1024, 2048, 4096],
                    help="comma-separated chunk sizes")
    ap.add_argument("--seg-chunks", type=_ints, default=[5, 10, 20],
                    help="comma-separated SEG_CHUNKS values (sweep)")
    ap.add_argument("--seg-min-sigs", type=int, default=None,
                    help="override SEG_MIN_SIGS for the sweep (0 forces "
                         "the segmented pipeline on)")
    ap.add_argument("--devices", type=_ints, default=[1, 2, 4, 8],
                    help="comma-separated device counts (scale); "
                         "_scale-cell takes a single count")
    ap.add_argument("--threads", type=int, default=None,
                    help="scale: dispatch threads per cell "
                         "(default: one per device)")
    ap.add_argument("--device-work", type=int,
                    default=DEFAULT_SCALE_DEVICE_WORK,
                    help="scale w/ synthetic stubs: per-element LCG rounds "
                         "burned on device so the cell is device-bound "
                         "like the real workload (0 = trivial stubs)")
    ap.add_argument("--workload", choices=("auto", "ed25519", "synthetic"),
                    default="auto",
                    help="real verify kernels, or shape-identical stubs "
                         "(auto: synthetic on the CPU backend)")
    ap.add_argument("--host-mesh", action="store_true",
                    help="scale: force an N-device host-platform CPU mesh "
                         "per cell (auto-enabled on the CPU backend)")
    ap.add_argument("--self-test", action="store_true")
    args = ap.parse_args(argv)
    if args.self_test:
        return self_test()
    if not args.command:
        ap.error("need a subcommand (cost-model | sweep | scale) "
                 "or --self-test")

    if args.command == "_scale-cell":
        cell = run_scale_cell(args.devices[0], args.chunks, args.sigs,
                              resolve_workload(args.workload),
                              args.host_mesh, runs=args.runs,
                              threads=args.threads,
                              device_work=args.device_work)
        print(json.dumps(cell))
        return 0

    workload = resolve_workload(args.workload)
    if args.command == "cost-model":
        doc = make_doc("cost-model",
                       {"payload_mb": args.payload_mb, "runs": args.runs},
                       run_cost_model(args.payload_mb, args.runs))
    elif args.command == "sweep":
        doc = make_doc("sweep",
                       {"sigs": args.sigs, "chunks": args.chunks,
                        "seg_chunks": args.seg_chunks, "runs": args.runs,
                        "workload": workload},
                       run_sweep(args.sigs, args.chunks, args.seg_chunks,
                                 workload, runs=args.runs,
                                 seg_min_sigs=args.seg_min_sigs))
    else:  # scale
        host_mesh = args.host_mesh or workload == "synthetic"
        doc = make_doc("scale",
                       {"devices": args.devices, "chunks": args.chunks,
                        "sigs": args.sigs, "runs": args.runs,
                        "threads": args.threads, "host_mesh": host_mesh,
                        "device_work": args.device_work,
                        "workload": workload},
                       run_scale(args.devices, args.chunks, args.sigs,
                                 workload, host_mesh, args.runs,
                                 args.threads,
                                 device_work=args.device_work))
    emit(doc, args.out, args.md)
    return 0


if __name__ == "__main__":
    sys.exit(main())
