"""Quantify axon-relay dispatch costs: fixed per-call, per-array, per-byte.

Times a trivial jitted reduction over (a) one big array, (b) the same bytes
split across 7 arrays, (c) varying total bytes — always with perturbed
inputs and a fetched output so the relay cannot serve a cached result.

Usage: python tools/relay_probe.py
"""

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

jax.config.update("jax_compilation_cache_dir",
                  os.path.join(os.path.dirname(os.path.dirname(
                      os.path.abspath(__file__))), ".jax_cache"))


@jax.jit
def one(a):
    return jnp.sum(a, dtype=jnp.int32)


@jax.jit
def seven(a, b, c, d, e, f, g):
    return (jnp.sum(a, dtype=jnp.int32) + jnp.sum(b, dtype=jnp.int32)
            + jnp.sum(c, dtype=jnp.int32) + jnp.sum(d, dtype=jnp.int32)
            + jnp.sum(e, dtype=jnp.int32) + jnp.sum(f, dtype=jnp.int32)
            + jnp.sum(g, dtype=jnp.int32))


def timed(fn, mk_args, runs=5):
    ts = []
    for i in range(runs):
        args = mk_args(i)
        t0 = time.perf_counter()
        out = fn(*args)
        np.asarray(out)
        ts.append(time.perf_counter() - t0)
    return min(ts), sorted(ts)[len(ts) // 2]


def main():
    rng = np.random.default_rng(0)

    for total_mb in (0.125, 1, 4):
        nbytes = int(total_mb * (1 << 20))
        base = rng.integers(0, 255, nbytes, dtype=np.uint8)

        def mk_one(i):
            a = base.copy()
            a[0] = i  # perturb so the relay can't cache
            return (a,)

        def mk_seven(i):
            a = base.copy()
            a[0] = i
            return tuple(a[j * (nbytes // 7):(j + 1) * (nbytes // 7)].copy()
                         for j in range(7))

        one(*mk_one(99))          # compile
        seven(*mk_seven(99))      # compile
        t1, m1 = timed(one, mk_one)
        t7, m7 = timed(seven, mk_seven)
        print(f"{total_mb:6.3f} MB  one-array min/med {t1*1e3:7.1f}/{m1*1e3:7.1f} ms"
              f"   seven-array min/med {t7*1e3:7.1f}/{m7*1e3:7.1f} ms", flush=True)

    # zero-transfer dispatch cost: input already on device, output scalar
    dev = jax.device_put(base)

    def mk_dev(i):
        return (dev,)

    t0, m0 = timed(one, mk_dev)
    print(f"resident-input dispatch min/med {t0*1e3:7.1f}/{m0*1e3:7.1f} ms", flush=True)


if __name__ == "__main__":
    main()
