"""Summarize a Chrome trace-event JSON (libs/trace.py / bench.py --trace-out).

Prints per-span count / total / p50 / p99 so a bench trace answers "where
did the window go" without opening Perfetto:

    python tools/trace_summary.py /tmp/bench-trace.json
    python tools/trace_summary.py --json /tmp/bench-trace.json   # machine-readable
    python tools/trace_summary.py trace-*.json --node-prefix     # cluster view
    python tools/trace_summary.py --self-test                    # CI guard

Multiple inputs (per-node traces, or tools/trace_merge.py output alongside
originals) are summarized together; --node-prefix labels each file's spans
``<node>:<span>`` (node id from the trace header, else the file stem) so
per-node asymmetries stay visible in the combined table.

Dependency-free on purpose (stdlib only, no package import): it must run
against a dump bundle on a box that can't import jax.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List


def load_events(path: str) -> List[dict]:
    """Accept both the {"traceEvents": [...]} container and a bare event
    array (both are valid Chrome trace JSON)."""
    return load_labeled(path)[1]


def load_labeled(path: str):
    """(node label, events): label from the tracer's node_id export header
    (libs/trace.py set_identity) when present, else the file stem."""
    import os

    with open(path) as f:
        data = json.load(f)
    label = os.path.splitext(os.path.basename(path))[0]
    if isinstance(data, dict):
        events = data.get("traceEvents", [])
        if data.get("node_id"):
            label = str(data["node_id"])
    elif isinstance(data, list):
        events = data
    else:
        raise ValueError(f"{path}: not a trace-event JSON")
    if not isinstance(events, list):
        raise ValueError(f"{path}: traceEvents is not a list")
    return label, [e for e in events
                   if isinstance(e, dict) and e.get("name")
                   and e.get("ph") != "M"]


def _percentile(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank percentile on a pre-sorted list (no numpy)."""
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, max(0, round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


def summarize(events: List[dict]) -> Dict[str, dict]:
    """name -> {count, total_us, p50_us, p99_us}; complete ("X") events
    contribute their dur, instants count with zero duration."""
    durs: Dict[str, List[float]] = {}
    for e in events:
        durs.setdefault(e["name"], []).append(float(e.get("dur", 0.0)))
    out: Dict[str, dict] = {}
    for name, vals in sorted(durs.items()):
        vals.sort()
        out[name] = {
            "count": len(vals),
            "total_us": round(sum(vals), 1),
            "p50_us": round(_percentile(vals, 0.50), 1),
            "p99_us": round(_percentile(vals, 0.99), 1),
        }
    return out


def by_height(events: List[dict]) -> Dict[int, Dict[str, float]]:
    """height -> {span name -> total_us} for events whose args carry a
    height (``height`` or ``h``). This is the live-plane attribution view:
    where each committed height's wall-clock went — gossip wait
    (``gossip_idle``), WAL sync (``wal_group``/``wal_fsync``), verify
    (``batch_verify``/``verify_window``), apply (``apply_block``)."""
    out: Dict[int, Dict[str, float]] = {}
    for e in events:
        args = e.get("args") or {}
        h = args.get("height", args.get("h"))
        if not isinstance(h, int):
            continue
        per = out.setdefault(h, {})
        per[e["name"]] = per.get(e["name"], 0.0) + float(e.get("dur", 0.0))
    return {h: {n: round(v, 1) for n, v in sorted(per.items())}
            for h, per in sorted(out.items())}


def render_by_height(table: Dict[int, Dict[str, float]]) -> str:
    if not table:
        return "(no height-tagged events)"
    names = sorted({n for per in table.values() for n in per})
    head = "height  " + "  ".join(f"{n:>{max(len(n), 10)}}" for n in names)
    lines = [head]
    for h, per in table.items():
        cells = "  ".join(f"{per.get(n, 0.0) / 1000.0:>{max(len(n), 10)}.2f}"
                          for n in names)
        lines.append(f"{h:>6}  {cells}")
    return "\n".join(lines) + "\n(cells: total ms per height)"


def render(summary: Dict[str, dict]) -> str:
    if not summary:
        return "(no events)"
    name_w = max(len("span"), max(len(n) for n in summary))
    lines = [f"{'span':<{name_w}}  {'count':>7}  {'total_ms':>10}  "
             f"{'p50_us':>9}  {'p99_us':>9}"]
    for name, s in summary.items():
        lines.append(f"{name:<{name_w}}  {s['count']:>7}  "
                     f"{s['total_us'] / 1000.0:>10.2f}  "
                     f"{s['p50_us']:>9.1f}  {s['p99_us']:>9.1f}")
    return "\n".join(lines)


def self_test() -> int:
    """Round-trip a synthetic trace through a temp file: the format this
    tool parses is exactly what libs/trace.py and bench.py emit. Returns 0
    on success (CI runs this under pytest so the tool can't rot)."""
    import os
    import tempfile

    events = []
    t = 1000.0
    for i in range(8):
        for name, dur in (("verify_window", 500.0 + i), ("apply_window", 900.0),
                          ("apply_block", 55.0), ("window_flush", 20.0)):
            events.append({"name": name, "ph": "X", "ts": t, "dur": dur,
                           "pid": 1, "tid": 1, "args": {"i": i}})
            t += dur
    events.append({"name": "vote_flush", "ph": "i", "s": "t", "ts": t,
                   "pid": 1, "tid": 1})
    # height-tagged live-plane spans (consensus state.py / reactor.py emit
    # exactly this shape) for the --by-height view
    for h in (5, 5, 6):
        for name, dur in (("gossip_idle", 40.0), ("wal_group", 3.0),
                          ("apply_block", 55.0)):
            events.append({"name": name, "ph": "X", "ts": t, "dur": dur,
                           "pid": 1, "tid": 1, "args": {"height": h}})
            t += dur
    fd, path = tempfile.mkstemp(suffix=".json")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f)
        loaded = load_events(path)
        summary = summarize(loaded)
        heights = by_height(loaded)
    finally:
        os.unlink(path)
    assert len(summary) == 7, summary
    assert summary["apply_window"]["count"] == 8
    assert summary["apply_window"]["p50_us"] == 900.0
    assert summary["vote_flush"]["total_us"] == 0.0
    assert summary["verify_window"]["p99_us"] >= summary["verify_window"]["p50_us"]
    assert set(heights) == {5, 6}, heights
    assert heights[5]["gossip_idle"] == 80.0
    assert heights[6]["wal_group"] == 3.0
    assert "gossip_idle" in render_by_height(heights)
    # multi-file + --node-prefix composition (merged cluster traces): the
    # node label comes from the export header, metadata events are skipped
    fd2, path2 = tempfile.mkstemp(suffix=".json")
    try:
        with os.fdopen(fd2, "w") as f:
            json.dump({"traceEvents": [
                {"name": "process_name", "ph": "M", "pid": 1,
                 "args": {"name": "nodeX"}},
                {"name": "verify_window", "ph": "X", "ts": 1.0, "dur": 7.0,
                 "pid": 1, "tid": 1}],
                "displayTimeUnit": "ms", "node_id": "nodeX"}, f)
        label, evs = load_labeled(path2)
        assert label == "nodeX" and len(evs) == 1, (label, evs)
        import contextlib
        import io

        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            assert main(["--json", "--node-prefix", path2]) == 0
        assert "nodeX:verify_window" in buf.getvalue()
    finally:
        os.unlink(path2)
    print("trace_summary self-test OK "
          f"({len(summary)} spans, {sum(s['count'] for s in summary.values())}"
          f" events, {len(heights)} heights)")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("trace", nargs="*", help="Chrome trace-event JSON "
                    "path(s); several per-node traces combine into one "
                    "summary")
    ap.add_argument("--json", action="store_true",
                    help="print the summary as JSON instead of a table")
    ap.add_argument("--by-height", action="store_true",
                    help="group height-tagged spans (gossip_idle, wal_group, "
                         "apply_block, verify/apply windows, stage_*) per "
                         "height — the live-plane latency attribution view")
    ap.add_argument("--node-prefix", action="store_true",
                    help="label every span '<node>:<span>' per input file "
                         "(node id from the trace header, else file stem)")
    ap.add_argument("--self-test", action="store_true",
                    help="run the built-in round-trip check and exit")
    args = ap.parse_args(argv)
    if args.self_test:
        return self_test()
    if not args.trace:
        ap.error("trace path required (or --self-test)")
    events = []
    for path in args.trace:
        label, evs = load_labeled(path)
        if args.node_prefix:
            for e in evs:
                e = dict(e)
                e["name"] = f"{label}:{e['name']}"
                events.append(e)
        else:
            events.extend(evs)
    if args.by_height:
        table = by_height(events)
        if args.json:
            print(json.dumps({str(h): per for h, per in table.items()},
                             indent=2))
        else:
            print(render_by_height(table))
        return 0
    summary = summarize(events)
    if args.json:
        print(json.dumps(summary, indent=2))
    else:
        print(render(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
