"""Fleet metrics aggregator: scrape every node's /metrics, roll up cluster
truth.

A 4-node (or 32-node) run reported through node0's /metrics answers "how is
node0", not "how is the cluster". This scraper polls all nodes' Prometheus
endpoints on an interval and emits cluster rollups:

* per-series min / median / max across nodes (last sample),
* cross-node blocks/min: committed-height delta of the cluster MAX between
  the first and last scrape — the chain's real rate, immune to one
  lagging node,
* gossip wakeups-per-peer-link (sum of wakeup deltas / directed links),

as JSON consumed by bench config 4 and the e2e runner (which also exports
the path via TMTPU_FLEET_JSON so node debugdump bundles can include the
snapshot).

    python tools/fleet_scrape.py --ports 28664,28665,28666,28667 \
        --duration 30 --interval 2 --out fleet.json
    python tools/fleet_scrape.py --self-test

Stdlib-only on purpose: it runs inside bench/e2e harnesses and on boxes
that can't import the package.
"""

from __future__ import annotations

import argparse
import concurrent.futures
import json
import sys
import threading
import time
import urllib.request
from typing import Dict, List, Optional, Tuple

DEFAULT_NAMESPACE = "tendermint"


def parse_metrics(text: str) -> Dict[str, float]:
    """Prometheus text exposition -> {series: value}; series is
    ``name`` or ``name{labels}`` verbatim. Histogram bucket lines are
    skipped (the rollup works on sums/counts/gauges/counters)."""
    out: Dict[str, float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        try:
            series, value = line.rsplit(" ", 1)
        except ValueError:
            continue
        name = series.split("{", 1)[0]
        if name.endswith("_bucket"):
            continue
        try:
            out[series] = float(value)
        except ValueError:
            continue
    return out


def scrape_endpoint(url: str, timeout: float = 2.0) -> Dict[str, float]:
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return parse_metrics(r.read().decode())


def _value_by_suffix(sample: Dict[str, float], suffix: str) -> Optional[float]:
    """First series whose bare name ends with ``suffix`` (label-free
    gauges; suffix-matched so per-node registry namespaces don't hide
    them)."""
    for s, v in sample.items():
        if s.split("{", 1)[0].endswith(suffix):
            return v
    return None


def _median(vals: List[float]) -> float:
    s = sorted(vals)
    n = len(s)
    mid = n // 2
    return s[mid] if n % 2 else (s[mid - 1] + s[mid]) / 2.0


class FleetScraper:
    """Poll N /metrics endpoints on an interval; rollup() aggregates."""

    def __init__(self, endpoints: Dict[str, object], interval_s: float = 2.0,
                 namespace: str = DEFAULT_NAMESPACE,
                 out_path: Optional[str] = None):
        """``endpoints`` maps node name -> /metrics URL, or to a CALLABLE
        returning exposition text (in-proc fleets — tools/soak.py passes
        each node's ``registry.render`` so the whole pipeline runs with
        no HTTP servers). ``out_path``, if set, gets a fresh rollup JSON
        after every sweep (the debugdump seam: TMTPU_FLEET_JSON points
        nodes at this file)."""
        self.endpoints = dict(endpoints)
        self.interval_s = interval_s
        self.namespace = namespace
        self.out_path = out_path
        self.first: Dict[str, Tuple[float, Dict[str, float]]] = {}
        self.last: Dict[str, Tuple[float, Dict[str, float]]] = {}
        self.scrapes = 0
        self.errors = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()

    # -- sampling ----------------------------------------------------------

    def add_endpoint(self, name: str, url: str) -> None:
        """Safe while the loop runs (late-joining nodes)."""
        self.endpoints[name] = url

    def remove_endpoint(self, name: str) -> None:
        """Safe while the loop runs (churned-out nodes): a scheduled leave
        must stop counting as a scrape error against the fleet."""
        self.endpoints.pop(name, None)

    def sweep(self) -> int:
        """Scrape every endpoint once, concurrently; returns how many
        answered. Concurrency matters at fleet scale: serially, a few
        wedged-but-listening nodes (2s urlopen timeout each — exactly the
        stall scenario the debugdump snapshot targets) would stretch one
        sweep past interval_s and stale the rollup."""

        def one(name: str, url):
            try:
                if callable(url):
                    return name, parse_metrics(url()), time.time()
                return name, scrape_endpoint(url), time.time()
            except Exception:
                return name, None, 0.0

        ok = 0
        items = list(self.endpoints.items())
        if items:
            with concurrent.futures.ThreadPoolExecutor(
                    max_workers=min(16, len(items))) as ex:
                for name, sample, now in ex.map(lambda kv: one(*kv), items):
                    if sample is None:
                        self.errors += 1
                        continue
                    with self._lock:
                        self.first.setdefault(name, (now, sample))
                        self.last[name] = (now, sample)
                    ok += 1
        self.scrapes += 1
        if self.out_path:
            try:
                self.write(self.out_path)
            except Exception:
                pass
        return ok

    def _run(self) -> None:
        while not self._stop.is_set():
            self.sweep()
            self._stop.wait(self.interval_s)

    def start(self) -> "FleetScraper":
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="fleet-scrape")
        self._thread.start()
        return self

    def stop(self) -> dict:
        """Stop the loop, take one final sweep, return the rollup."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(self.interval_s + 5.0)
            self._thread = None
        self.sweep()
        return self.rollup()

    # -- aggregation -------------------------------------------------------

    def _series_name(self, suffix: str) -> str:
        return f"{self.namespace}_{suffix}" if self.namespace else suffix

    def rollup(self) -> dict:
        with self._lock:
            first = dict(self.first)
            last = dict(self.last)
        nodes = sorted(last)
        series: Dict[str, dict] = {}
        all_names = sorted({s for _, sample in last.values()
                            for s in sample})
        for s in all_names:
            vals = [last[n][1][s] for n in nodes if s in last[n][1]]
            if not vals:
                continue
            series[s] = {"min": min(vals), "median": _median(vals),
                         "max": max(vals), "nodes": len(vals)}
        # cluster blocks/min from the committed-height series: the cluster
        # commits a height when ANY node does, so cluster truth is the MAX
        # across nodes at each sample point
        height_s = self._series_name("consensus_committed_height")
        out = {
            "nodes": nodes,
            "n_nodes": len(nodes),
            "scrapes": self.scrapes,
            "scrape_errors": self.errors,
            "series": series,
        }
        h_first = [first[n][1].get(height_s) for n in nodes
                   if height_s in first[n][1]]
        h_last = [last[n][1].get(height_s) for n in nodes
                  if height_s in last[n][1]]
        if h_first and h_last:
            t_first = min(first[n][0] for n in nodes)
            t_last = max(last[n][0] for n in nodes)
            elapsed = max(1e-9, t_last - t_first)
            blocks = max(h_last) - max(h_first)
            out["elapsed_s"] = round(elapsed, 3)
            out["cluster_height"] = max(h_last)
            out["cluster_blocks_per_min"] = round(blocks / elapsed * 60.0, 3)
        # gossip wakeups per directed peer link, from counter deltas summed
        # across nodes (each of the n nodes runs routines per peer)
        def counter_delta(prefix: str) -> float:
            """Summed last-minus-first deltas across all nodes and label
            sets of one counter family, clamped at 0 per series: a
            restarted node resets its counters (Prometheus rate()-style
            counter-reset handling)."""
            total = 0.0
            for n in nodes:
                for s, v in last[n][1].items():
                    if s.split("{", 1)[0] == prefix:
                        total += max(0.0, v - first[n][1].get(s, 0.0))
            return total

        delta = counter_delta(
            self._series_name("consensus_gossip_wakeups_total"))
        links = max(1, len(nodes) * (len(nodes) - 1))
        out["gossip_wakeups_delta"] = delta
        out["wakeups_per_peer_link"] = round(delta / links, 3)

        # ingestion-plane rollups (mempool + RPC series): counter deltas
        # summed across nodes over the scrape window — the cluster's tx
        # admission/rejection rate and RPC traffic, the fleet view the
        # ingest bench and the mempool_full chaos cell read
        admitted = counter_delta(
            self._series_name("mempool_admitted_txs_total"))
        rejected = counter_delta(self._series_name("mempool_failed_txs"))
        rpc_reqs = counter_delta(
            self._series_name("rpc_request_seconds_count"))
        out["txs_admitted_delta"] = admitted
        out["txs_rejected_delta"] = rejected
        out["rpc_requests_delta"] = rpc_reqs
        # divide by the UNROUNDED window (the rounded elapsed_s is 0.0
        # when only one sweep has landed — cluster_blocks_per_min floors
        # the same way); rates only exist once the window is real
        if nodes:
            window = (max(last[n][0] for n in nodes)
                      - min(first[n][0] for n in nodes))
            if window > 0:
                out["cluster_txs_admitted_per_sec"] = round(
                    admitted / window, 3)
                out["cluster_rpc_requests_per_sec"] = round(
                    rpc_reqs / window, 3)
        # per-node process watermarks (libs/watermark.py sampler): last
        # value + growth slope over the scrape window, clamped at zero
        # (a restarted node resets its gauges — same rate()-style
        # counter-reset handling as counter_delta). Matched by series
        # SUFFIX, not full name: in-proc fleets give every node its own
        # registry namespace, and the leak-slope SLO must still see them.
        process: Dict[str, dict] = {}
        for n in nodes:
            t0, s0 = first[n]
            t1, s1 = last[n]
            window = t1 - t0
            rec = {}
            for suffix in self.PROCESS_SUFFIXES:
                v1 = _value_by_suffix(s1, suffix)
                if v1 is None:
                    continue
                v0 = _value_by_suffix(s0, suffix)
                grown = max(0.0, v1 - (v1 if v0 is None else v0))
                rec[suffix[len("process_"):]] = {
                    "last": v1,
                    "slope_per_s": (round(grown / window, 3)
                                    if window > 0 else 0.0),
                }
            if rec:
                process[n] = rec
        if process:
            out["process"] = process
        return out

    #: the watermark gauge family (ProcessMetrics), namespace-agnostic
    PROCESS_SUFFIXES = ("process_rss_bytes", "process_open_fds",
                        "process_wal_bytes", "process_txlife_ring_depth",
                        "process_metric_series")

    def write(self, path: str) -> str:
        import os
        import tempfile

        doc = self.rollup()
        # unique tmp per call: stop()'s final sweep can race a wedged
        # worker sweep, and two writers on one shared tmp would tear it
        fd, tmp = tempfile.mkstemp(prefix=os.path.basename(path) + ".",
                                   dir=os.path.dirname(path) or ".")
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(doc, f, indent=1)
            os.replace(tmp, path)  # readers (debugdump) never see a tear
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        return path


# -- self-test ----------------------------------------------------------------

def _serve_synthetic(n_nodes: int):
    """Tiny per-node HTTP servers whose /metrics advance on every scrape:
    node i's committed height starts at 10+i and gains 2 per request."""
    import http.server

    servers = []

    def make_handler(state):
        class H(http.server.BaseHTTPRequestHandler):
            def do_GET(self):
                state["hits"] += 1
                h = state["h0"] + 2 * state["hits"]
                body = "\n".join([
                    "# HELP tendermint_consensus_committed_height x",
                    "# TYPE tendermint_consensus_committed_height gauge",
                    f"tendermint_consensus_committed_height {h}",
                    "tendermint_consensus_gossip_wakeups_total"
                    '{routine="data"} ' + str(20 * state["hits"]),
                    "tendermint_mempool_admitted_txs_total "
                    + str(5 * state["hits"]),
                    'tendermint_mempool_failed_txs{reason="full"} '
                    + str(2 * state["hits"]),
                    "tendermint_rpc_request_seconds_count"
                    '{endpoint="broadcast_tx_sync",outcome="ok"} '
                    + str(8 * state["hits"]),
                    "tendermint_consensus_stage_seconds_sum"
                    '{stage="commit_finalized"} 0.5',
                    "tendermint_consensus_stage_seconds_count"
                    '{stage="commit_finalized"} 10',
                    'tendermint_consensus_stage_seconds_bucket'
                    '{le="+Inf",stage="commit_finalized"} 10',
                    # process watermarks: rss ramps (leak-slope subject),
                    # wal SHRINKS (clamped to 0 slope — gauge reset
                    # handling), the rest hold steady
                    "tendermint_process_rss_bytes "
                    + str(1_000_000 + 4096 * state["hits"]),
                    "tendermint_process_open_fds 32",
                    "tendermint_process_wal_bytes "
                    + str(max(0, 16384 - 1000 * state["hits"])),
                    "tendermint_process_txlife_ring_depth 7",
                    "tendermint_process_metric_series 450",
                ]).encode() + b"\n"
                self.send_response(200)
                self.send_header("Content-Type", "text/plain")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        return H

    for i in range(n_nodes):
        state = {"h0": 10 + i, "hits": 0}
        srv = http.server.ThreadingHTTPServer(
            ("127.0.0.1", 0), make_handler(state))
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        servers.append(srv)
    return servers


def self_test() -> int:
    servers = _serve_synthetic(3)
    try:
        endpoints = {f"node{i}": f"http://127.0.0.1:{s.server_address[1]}"
                     "/metrics" for i, s in enumerate(servers)}
        sc = FleetScraper(endpoints, interval_s=0.05)
        assert sc.sweep() == 3
        time.sleep(0.25)
        assert sc.sweep() == 3
        roll = sc.rollup()
        assert roll["n_nodes"] == 3
        assert roll["scrape_errors"] == 0
        hs = roll["series"]["tendermint_consensus_committed_height"]
        # second scrape: node i reports 10+i+4 -> min 14, max 16, median 15
        assert (hs["min"], hs["median"], hs["max"]) == (14.0, 15.0, 16.0), hs
        # bucket lines never enter the rollup
        assert not any(s.startswith(
            "tendermint_consensus_stage_seconds_bucket")
            for s in roll["series"])
        assert "tendermint_consensus_stage_seconds_sum" \
            '{stage="commit_finalized"}' in roll["series"]
        # cluster height is the MAX across nodes: node2's 12+2*2 = 16
        assert roll["cluster_height"] == 16.0, roll
        assert roll["cluster_blocks_per_min"] > 0
        # wakeups: each node +20 per scrape -> delta 3*20 over 6 links
        assert abs(roll["wakeups_per_peer_link"] - 10.0) < 0.001, roll
        # ingestion rollups: one extra scrape per node between first and
        # last -> admitted +5, rejected +2, rpc +8, each summed over 3
        # nodes; the per-second rates divide by the window
        assert roll["txs_admitted_delta"] == 15.0, roll
        assert roll["txs_rejected_delta"] == 6.0, roll
        assert roll["rpc_requests_delta"] == 24.0, roll
        assert roll["cluster_txs_admitted_per_sec"] > 0, roll
        assert roll["cluster_rpc_requests_per_sec"] > 0, roll
        # process watermarks: rss grew 4096 over the window (positive
        # slope), wal SHRANK (slope clamps to 0.0, not negative), and
        # steady gauges report zero slope with a live last value
        proc = roll["process"]["node0"]
        assert proc["rss_bytes"]["last"] == 1_000_000 + 8192, proc
        assert proc["rss_bytes"]["slope_per_s"] > 0, proc
        assert proc["wal_bytes"]["slope_per_s"] == 0.0, proc
        assert proc["open_fds"] == {"last": 32.0, "slope_per_s": 0.0}, proc
        assert proc["txlife_ring_depth"]["last"] == 7.0, proc
        assert proc["metric_series"]["last"] == 450.0, proc
        # threaded mode + out_path freshness
        import os
        import tempfile

        fd, path = tempfile.mkstemp(suffix=".json")
        os.close(fd)
        try:
            sc2 = FleetScraper(endpoints, interval_s=0.05,
                               out_path=path).start()
            time.sleep(0.3)
            roll2 = sc2.stop()
            assert roll2["scrapes"] >= 2
            with open(path) as f:
                on_disk = json.load(f)
            assert on_disk["n_nodes"] == 3
        finally:
            os.unlink(path)
        # a dead endpoint degrades to errors, not a crash
        sc3 = FleetScraper({"gone": "http://127.0.0.1:9/metrics"},
                           interval_s=0.05)
        assert sc3.sweep() == 0 and sc3.errors == 1
        assert sc3.rollup()["n_nodes"] == 0
        # callable endpoints (in-proc fleets, no HTTP): scraped through
        # the same parse path, and the process rollup still finds the
        # watermarks under a per-node registry namespace
        calls = {"n": 0}

        def render():
            calls["n"] += 1
            return (f"churn_val0_12345_process_rss_bytes "
                    f"{100.0 + calls['n']}\n"
                    f"churn_val0_12345_consensus_committed_height 5\n")

        sc4 = FleetScraper({"inproc": render}, interval_s=0.05)
        assert sc4.sweep() == 1
        time.sleep(0.05)
        assert sc4.sweep() == 1
        r4 = sc4.rollup()
        assert r4["process"]["inproc"]["rss_bytes"]["last"] == 102.0, r4
        assert r4["process"]["inproc"]["rss_bytes"]["slope_per_s"] > 0, r4
        # a raising callable counts as a scrape error, not a crash
        def boom():
            raise RuntimeError("down")
        sc5 = FleetScraper({"bad": boom}, interval_s=0.05)
        assert sc5.sweep() == 0 and sc5.errors == 1
    finally:
        for s in servers:
            s.shutdown()
    print("fleet_scrape self-test OK (3 nodes, rollup + cluster rate)")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--endpoints", default="",
                    help="comma-separated name=url pairs (or bare urls)")
    ap.add_argument("--ports", default="",
                    help="comma-separated /metrics ports on --host")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--interval", type=float, default=2.0)
    ap.add_argument("--duration", type=float, default=30.0)
    ap.add_argument("--namespace", default=DEFAULT_NAMESPACE)
    ap.add_argument("--out", default=None, metavar="PATH",
                    help="write the final rollup JSON here "
                         "(and keep it fresh during the run)")
    ap.add_argument("--self-test", action="store_true")
    args = ap.parse_args(argv)
    if args.self_test:
        return self_test()
    endpoints: Dict[str, str] = {}
    for i, part in enumerate(p for p in args.endpoints.split(",") if p):
        name, _, url = part.rpartition("=")
        endpoints[name or f"node{i}"] = url
    for i, port in enumerate(p for p in args.ports.split(",") if p):
        endpoints[f"node{i}"] = f"http://{args.host}:{int(port)}/metrics"
    if not endpoints:
        ap.error("no endpoints (use --endpoints or --ports, or --self-test)")
    sc = FleetScraper(endpoints, interval_s=args.interval,
                      namespace=args.namespace, out_path=args.out).start()
    try:
        time.sleep(args.duration)
    except KeyboardInterrupt:
        pass
    # stop()'s final sweep already refreshed args.out (the out_path seam)
    roll = sc.stop()
    print(json.dumps(roll, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
