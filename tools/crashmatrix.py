"""Crash matrix: SIGKILL one node at EVERY durability boundary, prove it
comes back correct — and never equivocates.

Every durability mechanism in this repo (CRC-framed WAL + repair-on-open,
atomic-rename privval state, transactional KV batches, MempooWAL replay)
exists for exactly one moment: the instant the process dies. This driver
manufactures that moment at every enumerated boundary, deterministically:

* a 4-validator in-proc fleet (3 in-memory survivors + ONE fully
  persistent victim: SQLite block/state stores, file WAL, FilePV sign
  state, MempoolWAL, a durable kvstore app that prunes its own block
  store) commits under open-loop tx load;
* per boundary in a SEEDED order, the victim is killed AT the boundary
  via ``libs.fail.arm_raise`` — the in-proc analog of SIGKILL: a
  BaseException no defensive handler can swallow, scoped (``fail.scope``)
  so boundaries living in shared code paths (execution, commit) kill
  only the victim's tasks. At kill time the victim's buffered file
  bytes are DISCARDED (fds dup2'd onto /dev/null — what the kernel does
  to unflushed buffers on a real SIGKILL) and its sqlite transactions
  roll back (what losing the fd does);
* a ``libs.supervisor.RestartSupervisor`` (policy "on-failure", bounded
  exponential backoff) restarts it: rebuild from the home dir — WAL
  repair-on-open, ABCI handshake replay, WAL catchup replay, FilePV
  reload — rejoin the live net, catch back up via consensus catchup
  gossip;
* the ``statesync.mid_chunk_apply`` boundary kills a fresh statesync
  JOINER mid-restore instead; the retry restores from scratch;
* the ``net.during_quorum_loss`` boundary is a timing WINDOW, not a code
  site: >1/3 of voting power is isolated until consensus halts
  fleet-wide (watchdog ``quorum_lost``), and the victim is then killed
  at its next WAL fsync INSIDE the halted window — proving WAL repair +
  handshake replay across a halt-spanning WAL after the heal.

Invariants per kill: the boundary actually fired; the victim recovers to
a height >= the net's tip at restart; app hashes agree with survivors at
a common height; the sign state never regresses and NO double-sign
evidence appears anywhere (pending or committed) — the restarted
validator re-emits at most timestamp-equivalent votes; and afterwards
the victim's MempoolWAL replay is idempotent (a second replay re-admits
nothing).

Determinism: the kill schedule is a pure function of the seed
(``plan_crashes``), and ``--verify-determinism`` runs the whole matrix
twice, diffing schedule + per-kill outcome fingerprints (wall-clock
fields excluded).

    python tools/crashmatrix.py --seed 1
    python tools/crashmatrix.py --seed 1 --verify-determinism
    python tools/crashmatrix.py --boundaries wal.after_fsync,prune.mid_blocks
    python tools/crashmatrix.py --self-test      # stdlib-only, seconds

Stdlib-only at the top level; repo imports happen inside the run (the
churn.py/chaos_matrix discipline) so --help/--self-test work anywhere —
including slim containers without ``cryptography``, which is the point:
the subprocess-TCP variant of this matrix (e2e manifests with
``fail_point`` + ``restart_policy = "on-failure"``) needs that package,
the in-proc matrix does not.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

TOOLS_DIR = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(TOOLS_DIR)
for p in (REPO, TOOLS_DIR):
    if p not in sys.path:
        sys.path.insert(0, p)

#: boundaries killed on the persistent VICTIM validator, in catalog order
#: (the seeded plan shuffles execution order). Every name must be in
#: libs.fail.KNOWN_FAIL_POINTS — --self-test enforces the subset.
VICTIM_BOUNDARIES = (
    "wal.before_fsync",
    "wal.after_fsync",
    "wal.mid_group_commit",
    "db.mid_window_flush",
    "privval.between_sign_and_save",
    "consensus.commit.before_end_height",
    "execution.before_exec_block",
    "execution.after_state_save",
    "prune.mid_blocks",
)
#: boundaries killed on a fresh statesync JOINER mid-restore
JOINER_BOUNDARIES = ("statesync.mid_chunk_apply",)
#: the degraded-network boundary: NOT a code fail point but a timing
#: window — >1/3 of voting power is isolated until consensus halts
#: fleet-wide (watchdog classifies quorum_lost), and only THEN is the
#: victim killed, at its next WAL fsync (QUORUM_KILL_SITE; gossip
#: stall-refresh re-sends keep peer records flowing through the wedged
#: victim's WAL, so the armed site fires inside the halted window).
#: Proves WAL repair + handshake replay across a halt-spanning WAL.
QUORUM_BOUNDARIES = ("net.during_quorum_loss",)
QUORUM_KILL_SITE = "wal.before_fsync"
ALL_BOUNDARIES = VICTIM_BOUNDARIES + QUORUM_BOUNDARIES + JOINER_BOUNDARIES

VICTIM = "crash"        # the persistent victim's node name
N_SURVIVORS = 3         # val0..val2, in-memory
SNAPSHOT_INTERVAL = 3   # donor snapshots for the joiner boundary
RETAIN_BLOCKS = 6       # victim app's prune window (prune.mid_blocks)

#: scheduling/wall-clock field names stripped from determinism
#: fingerprints (wal_repaired depends on where the io buffer happened to
#: spill mid-frame at kill time — real, but not part of the schedule)
_CLOCK_FIELDS = ("kill_to_caughtup_s", "join_caughtup_s", "backoff_s",
                 "elapsed_s", "recovery_records_replayed",
                 "wal_repaired", "wal_repaired_bytes")


# -- the deterministic plan (pure) -------------------------------------------

def plan_crashes(seed: int, boundaries=None) -> dict:
    """The kill schedule as a pure function of its inputs: a seeded order
    over the requested boundaries (victim kills shuffled, joiner kills
    last — a mid-restore kill needs donors with settled snapshots), each
    with its target node. Two same-seed calls are byte-identical; the
    property --verify-determinism checks end-to-end against two runs."""
    import random
    import zlib

    boundaries = list(boundaries or ALL_BOUNDARIES)
    unknown = [b for b in boundaries if b not in ALL_BOUNDARIES]
    if unknown:
        raise ValueError(f"unknown boundaries {unknown}; "
                         f"known: {list(ALL_BOUNDARIES)}")
    rng = random.Random(zlib.crc32(f"crash|{seed}".encode()))
    victim_kills = [b for b in boundaries if b in VICTIM_BOUNDARIES]
    quorum_kills = [b for b in boundaries if b in QUORUM_BOUNDARIES]
    joiner_kills = [b for b in boundaries if b in JOINER_BOUNDARIES]
    rng.shuffle(victim_kills)
    # the quorum-loss window halts the whole fleet for seconds — run it
    # after the plain victim kills, before the joiner (whose statesync
    # catchup wants an already-healed, committing net)
    kills = ([{"boundary": b, "target": VICTIM} for b in victim_kills]
             + [{"boundary": b, "target": VICTIM} for b in quorum_kills]
             + [{"boundary": b, "target": "joiner"} for b in joiner_kills])
    return {"seed": seed, "kills": kills}


def outcome_fingerprint(report: dict) -> dict:
    """The deterministic slice of a report: the executed kill schedule and
    each kill's boolean outcomes, wall-clock fields excluded — what two
    same-seed runs must agree on."""
    kills = []
    for k in report["kills"]:
        kills.append({key: v for key, v in k.items()
                      if key not in _CLOCK_FIELDS})
    return {"plan": report["plan"], "kills": kills}


# -- the in-proc rig ---------------------------------------------------------

_RIG = None


def _rig():
    """Import-heavy rig pieces, built lazily and memoized (one node class
    per process) — keeps --help/--self-test stdlib-fast."""
    global _RIG
    if _RIG is not None:
        return _RIG

    from tendermint_tpu import crypto
    from tendermint_tpu.abci.example.kvstore import SnapshotKVStoreApplication
    from tendermint_tpu.blockchain.reactor import BlockchainReactor
    from tendermint_tpu.consensus import ConsensusState
    from tendermint_tpu.consensus.config import test_consensus_config
    from tendermint_tpu.consensus.replay import Handshaker, catchup_replay
    from tendermint_tpu.consensus.wal import WAL
    from tendermint_tpu.evidence.pool import EvidencePool
    from tendermint_tpu.libs import fail
    from tendermint_tpu.libs.db import MemDB, SQLiteDB
    from tendermint_tpu.libs.fail import KilledAtFailPoint
    from tendermint_tpu.mempool import CListMempool
    from tendermint_tpu.mempool.clist_mempool import init_mempool_wal
    from tendermint_tpu.mempool.reactor import MempoolReactor
    from tendermint_tpu.p2p import Switch
    from tendermint_tpu.privval.file_pv import FilePV
    from tendermint_tpu.proxy import AppConns, local_client_creator
    from tendermint_tpu.state import (BlockExecutor, StateStore,
                                      state_from_genesis)
    from tendermint_tpu.statesync.reactor import StateSyncReactor
    from tendermint_tpu.store import BlockStore
    from tendermint_tpu.types import GenesisDoc, GenesisValidator, MockPV

    class DurableCrashApp(SnapshotKVStoreApplication):
        """Snapshot kvstore whose state survives process death: every
        commit atomically persists {state, height, ...} so the restarted
        victim's ABCI handshake replays only the block-store suffix —
        which is what lets the victim PRUNE its own block store (the
        prune.mid_blocks boundary) and still restart without
        replay-from-genesis."""

        def __init__(self, path: str, interval: int, retain: int):
            super().__init__(interval=interval)
            self.path = path
            self.retain = retain
            if os.path.exists(path):
                with open(path) as f:
                    doc = json.load(f)
                self.state = dict(doc["state"])
                self.tx_count = doc["tx_count"]
                self.height = doc["height"]
                self.validators = dict(doc["validators"])
                self.app_hash = bytes.fromhex(doc["app_hash"])

        def commit(self):
            resp = super().commit()
            if self.retain:
                resp.retain_height = max(0, self.height - self.retain)
            tmp = self.path + ".tmp"
            with open(tmp, "w") as f:
                json.dump({"state": self.state, "tx_count": self.tx_count,
                           "height": self.height,
                           "validators": self.validators,
                           "app_hash": self.app_hash.hex()}, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)
            return resp

    class CrashRigNode:
        """One in-proc node. home=None → in-memory survivor (MockPV,
        NilWAL, MemDB); home=<dir> → the persistent victim (file WAL with
        repair-on-open, SQLite stores, FilePV, MempoolWAL, durable
        pruning app). Every node carries a REAL EvidencePool so a
        double-signing restarted validator would surface as committed
        evidence, not pass silently."""

        def __init__(self, name, genesis, home=None, pv=None,
                     fast_sync=False):
            self.name = name
            self.home = home
            self.killed_at = None
            self.killed_evt = None  # created at start (needs a loop)
            if home is not None:
                os.makedirs(home, exist_ok=True)
                self.pv = _victim_pv(home)
                self.app = DurableCrashApp(os.path.join(home, "app.json"),
                                           SNAPSHOT_INTERVAL, RETAIN_BLOCKS)
                self._state_db = SQLiteDB(os.path.join(home, "state.db"))
                self._blocks_db = SQLiteDB(os.path.join(home, "blocks.db"))
                wal = WAL(os.path.join(home, "cs.wal"))
            else:
                self.pv = pv
                self.app = SnapshotKVStoreApplication(
                    interval=SNAPSHOT_INTERVAL)
                self._state_db = MemDB()
                self._blocks_db = MemDB()
                wal = None
            self.wal_repairs = getattr(wal, "repairs", 0)
            self.wal_repaired_bytes = getattr(wal, "repaired_bytes", 0)
            self.conns = AppConns(local_client_creator(self.app))
            self.conns.start()
            self.state_store = StateStore(self._state_db)
            self.block_store = BlockStore(self._blocks_db)
            state = state_from_genesis(genesis)
            loaded = self.state_store.load()
            if loaded is not None:
                state = loaded
            state = Handshaker(self.state_store, state, self.block_store,
                               genesis).handshake(self.conns.consensus,
                                                  self.conns.query)
            self.state_store.save(state)
            self.mempool = CListMempool(self.conns.mempool)
            if home is not None:
                init_mempool_wal(self.mempool,
                                 os.path.join(home, "mempool_wal"))
            self.evpool = EvidencePool(MemDB(), self.state_store,
                                       self.block_store)
            self.block_exec = BlockExecutor(self.state_store,
                                            self.conns.consensus,
                                            self.mempool, self.evpool,
                                            self.block_store)
            self.cs = ConsensusState(test_consensus_config(), state,
                                     self.block_exec, self.block_store,
                                     evpool=self.evpool, wal=wal)
            self.cs.set_priv_validator(self.pv)
            self.mempool.tx_available_callbacks.append(
                self.cs.notify_txs_available)
            self.switch = Switch(name)
            from tendermint_tpu.consensus.reactor import ConsensusReactor

            self.cs_reactor = ConsensusReactor(self.cs, wait_sync=fast_sync)
            self.switch.add_reactor("CONSENSUS", self.cs_reactor)
            self.bc_reactor = BlockchainReactor(
                state, self.block_exec, self.block_store, fast_sync=False,
                consensus_reactor=self.cs_reactor)
            self.switch.add_reactor("BLOCKCHAIN", self.bc_reactor)
            self.mp_reactor = MempoolReactor(self.mempool, gossip_sleep=0.01)
            self.switch.add_reactor("MEMPOOL", self.mp_reactor)
            self.ss_reactor = StateSyncReactor(self.app, self.app)
            self.switch.add_reactor("STATESYNC", self.ss_reactor)
            self.fast_sync = fast_sync
            self._started = False
            self.recovery_records_replayed = 0
            # kill guard: a BaseException at a boundary ends the receive
            # loop; record WHERE so the rig can react — the same shape
            # subprocess supervision sees (a dead process + its log line)
            orig = self.cs.receive_routine

            async def guarded():
                try:
                    await orig()
                except KilledAtFailPoint as e:
                    self.killed_at = e.site
                    if self.killed_evt is not None:
                        self.killed_evt.set()

            self.cs.receive_routine = guarded

        @property
        def height(self):
            return self.cs.state.last_block_height

        async def start(self):
            import asyncio

            self._started = True
            self.killed_evt = asyncio.Event()
            # every task the node creates below inherits this scope, so
            # armed boundaries in SHARED code kill only this node's tasks
            token = fail.scope.set(self.name)
            try:
                await self.switch.start()
                if not self.fast_sync:
                    # the node.py non-fast-sync boot path: WAL catchup for
                    # the in-flight height BEFORE the machine runs
                    self.recovery_records_replayed = catchup_replay(
                        self.cs, self.cs.rs.height)
                    await self.cs.start()
            finally:
                fail.scope.reset(token)

        def freeze(self):
            """SIGKILL semantics for an in-proc node: unflushed buffered
            bytes die with the process (dup2 the fds onto /dev/null so
            later GC/close flushes land nowhere) and open sqlite
            transactions roll back (what losing the fd does)."""
            self._devnull = open(os.devnull, "wb")
            wal_f = getattr(self.cs.wal, "_f", None)
            mwal = getattr(self.mempool, "_wal", None)
            for fobj in (wal_f, getattr(mwal, "_f", None)):
                if fobj is None:
                    continue
                try:
                    os.dup2(self._devnull.fileno(), fobj.fileno())
                except (OSError, ValueError):
                    pass
            for db in (self._state_db, self._blocks_db):
                conn = getattr(db, "_conn", None)
                if conn is not None:
                    try:
                        conn.rollback()
                        conn.close()
                    except Exception:
                        pass

        async def stop(self):
            if not self._started:
                return
            self._started = False
            await self.cs.stop()
            await self.switch.stop()
            self.conns.stop()

    def _victim_pv(home):
        key = os.path.join(home, "pv_key.json")
        state = os.path.join(home, "pv_state.json")
        if os.path.exists(key):
            # a corrupt sign state raises here — startup refused, exactly
            # like a real node boot (privval satellite)
            return FilePV.load(key, state)
        pv = FilePV.generate(key, state, seed=(VICTIM.encode() * 32)[:32])
        pv.save()
        return pv

    def make_pv(tag: str):
        return MockPV(crypto.Ed25519PrivKey.generate((tag.encode() * 32)[:32]))

    def make_genesis(pubkeys):
        return GenesisDoc(
            chain_id="crash-chain",
            genesis_time_ns=1_700_000_000_000_000_000,
            validators=[GenesisValidator(pk, 10) for pk in pubkeys])

    _RIG = {"CrashRigNode": CrashRigNode, "make_pv": make_pv,
            "make_genesis": make_genesis, "fail": fail,
            "KilledAtFailPoint": KilledAtFailPoint, "FilePV": FilePV}
    return _RIG


# -- the run ------------------------------------------------------------------

async def _bounded(coro, secs: float, what: str, fatal: bool = True):
    """Every await in the rig is BOUNDED: a wedged stop/teardown must
    surface as a loud failure (fatal) or a logged note (cleanup paths),
    never as a silently hung matrix."""
    import asyncio

    try:
        return await asyncio.wait_for(coro, timeout=secs)
    except asyncio.TimeoutError:
        if fatal:
            raise AssertionError(f"{what} wedged past {secs}s")
        print(f"crashmatrix: {what} wedged past {secs}s (cleanup path, "
              f"continuing)", file=sys.stderr, flush=True)
        return None


async def _run_async(seed: int, boundaries, home_root: str) -> dict:
    import asyncio

    from tendermint_tpu.libs.supervisor import RestartPolicy, RestartSupervisor
    from tendermint_tpu.libs.toolbox import load_tool
    from tendermint_tpu.p2p import InProcNetwork

    # via the toolbox helper, not a bare import: callers that loaded THIS
    # module through load_tool (bench --config crash) have already popped
    # tools/ back off sys.path by the time the run executes
    churn = load_tool("churn")

    rig = _rig()
    fail = rig["fail"]
    CrashRigNode = rig["CrashRigNode"]
    plan = plan_crashes(seed, boundaries)
    victim_home = os.path.join(home_root, VICTIM)

    survivor_names = [f"val{i}" for i in range(N_SURVIVORS)]
    pvs = {n: rig["make_pv"](n) for n in survivor_names}
    # the victim's FilePV key is deterministic (seeded) so genesis can name
    # it before the node object exists
    victim_pub = rig["FilePV"].generate(
        "", "", seed=(VICTIM.encode() * 32)[:32]).get_pub_key()
    genesis = rig["make_genesis"](
        [pvs[n].get_pub_key() for n in survivor_names] + [victim_pub])

    nodes = {n: CrashRigNode(n, genesis, pv=pvs[n]) for n in survivor_names}
    nodes[VICTIM] = CrashRigNode(VICTIM, genesis, home=victim_home)
    net = InProcNetwork()
    for nd in nodes.values():
        net.add_switch(nd.switch)
    for nd in nodes.values():
        await nd.start()
    await net.connect_all()

    rewire_task = asyncio.create_task(churn.rewire_loop(net, interval=0.2))

    async def load():
        import itertools

        loop = asyncio.get_running_loop()
        t0 = loop.time() + 0.1
        for i in itertools.count():
            target = t0 + i / 10.0
            now = loop.time()
            if target > now:
                await asyncio.sleep(target - now)
            live = [nd for n, nd in nodes.items()
                    if nd._started and not nd.fast_sync]
            if not live:
                continue
            try:
                # fat values: the app blob must span several snapshot
                # chunks quickly, or the mid-chunk-apply boundary (which
                # needs >=1 chunk already applied) can never fire
                live[i % len(live)].mempool.check_tx(
                    b"crash-%d-%d=" % (seed, i) + b"x" * 120)
            except Exception:
                pass  # a full mempool under kills is load, not failure

    load_task = asyncio.create_task(load())
    t_run0 = time.monotonic()
    kills = []
    try:
        await churn._wait_heights(list(nodes.values()), 2, timeout=120)

        for kill in plan["kills"]:
            boundary = kill["boundary"]
            print(f"crashmatrix: arming {boundary} "
                  f"(h={max(nd.height for nd in nodes.values())}, "
                  f"t+{time.monotonic() - t_run0:.0f}s)",
                  file=sys.stderr, flush=True)
            if kill["target"] == "joiner":
                kills.append(await _joiner_kill(net, nodes, genesis, seed,
                                                boundary, churn, rig))
                continue
            if boundary in QUORUM_BOUNDARIES:
                kills.append(await _quorum_loss_kill(
                    net, nodes, genesis, survivor_names, victim_home,
                    churn, rig))
                continue

            victim = nodes[VICTIM]
            sup = RestartSupervisor(
                RestartPolicy(policy="on-failure", max_restarts=3,
                              backoff_s=0.2, backoff_max_s=2.0,
                              healthy_uptime_s=5.0), name=VICTIM,
                time_fn=time.monotonic)
            sup.on_launch()
            lss_before = victim.pv.last_sign_state.height
            fail.arm_raise(boundary, scope_token=VICTIM)
            t_kill0 = time.monotonic()
            try:
                await asyncio.wait_for(victim.killed_evt.wait(), timeout=150)
            except asyncio.TimeoutError:
                raise AssertionError(
                    f"boundary {boundary!r} never fired on {VICTIM} "
                    f"(heights={ {n: nd.height for n, nd in nodes.items()} })")
            assert victim.killed_at == boundary, (victim.killed_at, boundary)
            assert fail.killed_at() == boundary
            # freeze disk state the way a SIGKILL would, then tear the
            # carcass down (task/switch cleanup is rig hygiene — the
            # durable state is already frozen)
            victim.freeze()
            await _bounded(net.remove_node(VICTIM), 30, "remove_node(victim)")
            await _bounded(victim.stop(), 30, "dead victim stop",
                           fatal=False)
            del nodes[VICTIM]

            backoff = sup.on_exit(1)
            assert backoff is not None and not sup.gave_up
            await asyncio.sleep(backoff)

            # survivors must have kept committing while the victim was down
            live = [nodes[n] for n in survivor_names]
            h_down = max(nd.height for nd in live)
            await churn._wait_heights(live, h_down + 1, timeout=60)

            # restart: rebuild from the home dir (WAL repair-on-open +
            # handshake replay + WAL catchup replay + FilePV reload)
            restarted = CrashRigNode(VICTIM, genesis, home=victim_home)
            nodes[VICTIM] = restarted
            sup.on_launch()
            tip = max(nd.height for nd in live)
            await _bounded(restarted.start(), 60, "restarted victim start")
            await _bounded(net.add_node(restarted.switch,
                                        connect_to=survivor_names),
                           30, "add_node(restarted victim)")
            await churn._wait_heights([restarted], tip + 1, timeout=120)
            kill_to_caughtup = time.monotonic() - t_kill0

            # -- per-kill invariants ------------------------------------
            common = min(nd.height for nd in nodes.values()) - 1
            hashes = {n: nd.block_store.load_block_meta(common).header.app_hash
                      for n, nd in nodes.items()}
            assert len(set(hashes.values())) == 1, \
                f"app hashes diverged after {boundary}: {hashes}"
            lss_after = restarted.pv.last_sign_state.height
            assert lss_after >= lss_before, \
                f"sign state regressed after {boundary}: " \
                f"{lss_before} -> {lss_after}"
            double_sign = _evidence_observed(nodes.values(), common)
            assert not double_sign, \
                f"double-sign evidence after {boundary}: {double_sign}"
            kills.append({
                "boundary": boundary, "target": VICTIM, "killed": True,
                "recovered": True, "restarts": sup.restarts,
                "evidence": 0, "double_sign_observed": False,
                "wal_repaired": bool(restarted.wal_repairs),
                "wal_repaired_bytes": restarted.wal_repaired_bytes,
                "recovery_records_replayed":
                    restarted.recovery_records_replayed,
                "kill_to_caughtup_s": round(kill_to_caughtup, 3),
                "backoff_s": backoff,
            })
    except BaseException:
        rewire_task.cancel()
        load_task.cancel()
        for nd in nodes.values():
            try:
                await _bounded(nd.stop(), 20, f"{nd.name} stop",
                               fatal=False)
            except Exception:
                pass
        raise
    finally:
        rewire_task.cancel()
        load_task.cancel()

    # settle + final teardown
    try:
        final = max(nd.height for nd in nodes.values()) + 1
        await churn._wait_heights(list(nodes.values()), final, timeout=120)
        victim = nodes.get(VICTIM)
        mempool_wal_idempotent = None
        if victim is not None:
            await _bounded(victim.stop(), 30, "final victim stop",
                           fatal=False)
            nodes.pop(VICTIM)
            mempool_wal_idempotent = _check_mempool_wal_idempotent(
                os.path.join(victim_home, "mempool_wal"))
    finally:
        for nd in nodes.values():
            try:
                await _bounded(nd.stop(), 20, f"{nd.name} stop",
                               fatal=False)
            except Exception:
                pass

    return {
        "seed": seed, "plan": plan, "kills": kills,
        "boundaries_killed": [k["boundary"] for k in kills],
        "mempool_wal_idempotent": mempool_wal_idempotent,
        "elapsed_s": round(time.monotonic() - t_run0, 2),
    }


async def _joiner_kill(net, nodes, genesis, seed, boundary, churn, rig):
    """The statesync boundary: a fresh joiner dies mid-chunk-apply, the
    supervised retry restores from scratch and catches up."""
    import asyncio

    from tendermint_tpu.libs.supervisor import RestartPolicy, RestartSupervisor

    fail = rig["fail"]
    CrashRigNode = rig["CrashRigNode"]
    donor = nodes["val0"]
    sup = RestartSupervisor(
        RestartPolicy(policy="on-failure", max_restarts=3, backoff_s=0.2,
                      backoff_max_s=2.0, healthy_uptime_s=5.0),
        name="joiner", time_fn=time.monotonic)
    neighbors = sorted(nodes)
    # a mid-apply kill needs a MULTI-chunk snapshot (>=1 chunk applied,
    # restore incomplete); the fat load txs get the donor there quickly
    deadline = time.monotonic() + 90
    while time.monotonic() < deadline:
        if any(len(c) >= 2 for c in donor.app._snapshots.values()):
            break
        await asyncio.sleep(0.2)
    else:
        raise AssertionError("donor never produced a multi-chunk snapshot")
    t0 = time.monotonic()

    print(f"crashmatrix: joiner restoring from donor snapshots "
          f"{sorted(donor.app._snapshots)} (armed {boundary})",
          file=sys.stderr, flush=True)
    jn = CrashRigNode("joiner", genesis, pv=rig["make_pv"]("joiner"),
                      fast_sync=True)
    sup.on_launch()
    fail.arm_raise(boundary, scope_token="joiner")
    token = fail.scope.set("joiner")
    killed = False
    try:
        # join_statesync bounds its phases internally; the outer bound
        # catches any wedge in its switch wiring / reactor teardown
        await _bounded(churn.join_statesync(net, jn, donor, neighbors, seed),
                       300, "armed joiner statesync")
    except rig["KilledAtFailPoint"] as e:
        assert e.site == boundary
        killed = True
    finally:
        fail.scope.reset(token)
    print(f"crashmatrix: joiner killed at {boundary}: {killed}",
          file=sys.stderr, flush=True)
    assert killed, f"boundary {boundary!r} never fired on the joiner"
    await _bounded(net.remove_node("joiner"), 30, "remove_node(joiner)")
    try:
        await _bounded(jn.stop(), 20, "killed joiner stop", fatal=False)
    except Exception:
        pass
    nodes.pop("joiner", None)

    backoff = sup.on_exit(1)
    assert backoff is not None
    await asyncio.sleep(backoff)

    # the retry: a FRESH node (a half-restored app is untrusted torso —
    # the app restore machinery re-derives everything from chunk 0)
    retry = CrashRigNode("joiner", genesis, pv=rig["make_pv"]("joiner"),
                         fast_sync=True)
    nodes["joiner"] = retry
    sup.on_launch()
    caught = await _bounded(
        churn.join_statesync(net, retry, donor, neighbors, seed),
        300, "joiner retry statesync")
    common = min(nd.height for nd in nodes.values()) - 1
    hashes = {nd.block_store.load_block_meta(common).header.app_hash
              for nd in nodes.values()
              if nd.block_store.load_block_meta(common) is not None}
    assert len(hashes) == 1, "joiner diverged from the fleet"
    return {"boundary": boundary, "target": "joiner", "killed": True,
            "recovered": True, "restarts": sup.restarts, "evidence": 0,
            "double_sign_observed": False,
            "kill_to_caughtup_s": round(time.monotonic() - t0, 3),
            "backoff_s": backoff, "join_caughtup_s": caught}


async def _quorum_loss_kill(net, nodes, genesis, survivor_names,
                            victim_home, churn, rig):
    """The net.during_quorum_loss boundary: WAL + handshake replay across
    a quorum-loss halt. Two survivor validators (>1/3 of voting power)
    are isolated until consensus halts fleet-wide and a survivor's
    watchdog classifies the episode ``quorum_lost``; the victim — wedged
    in the MAJORITY partition — is then killed at its next WAL fsync
    (gossip stall-refresh re-sends keep peer records flowing through its
    WAL, so the armed site fires while the window is still halted). The
    partition heals and the victim rebuilds from its home dir: WAL
    repair-on-open + handshake replay spanning the halted window, rejoin,
    and the full fleet commits past the halt height — never
    double-signing."""
    import asyncio

    from tendermint_tpu.consensus.watchdog import ConsensusWatchdog
    from tendermint_tpu.libs.supervisor import RestartPolicy, RestartSupervisor

    fail = rig["fail"]
    CrashRigNode = rig["CrashRigNode"]
    victim = nodes[VICTIM]
    isolate = ["val1", "val2"]  # 20/40 power: >1/3, victim stays majority
    # the recovery clock: bitmap refresh -> vote re-send (see
    # tools/quorum_loss.py) — also what keeps peer records flowing
    # through the wedged victim's WAL so the armed kill site fires
    for nd in nodes.values():
        nd.cs.config.gossip_stall_refresh_s = 1.0
    observer = nodes["val0"]
    wd = ConsensusWatchdog(observer.cs, stall_timeout_s=1.2,
                           check_interval_s=0.3,
                           height_fn=lambda: observer.height)
    await wd.start()
    sup = RestartSupervisor(
        RestartPolicy(policy="on-failure", max_restarts=3, backoff_s=0.2,
                      backoff_max_s=2.0, healthy_uptime_s=5.0), name=VICTIM,
        time_fn=time.monotonic)
    sup.on_launch()
    lss_before = victim.pv.last_sign_state.height
    t_kill0 = time.monotonic()
    try:
        net.partition(isolate)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and not wd.stalls:
            await asyncio.sleep(0.1)
        assert wd.stalls, "fleet never halted under >1/3 isolation"
        assert wd.last_halt_reason == "quorum_lost", \
            f"halt misclassified: {wd.last_halt_reason!r}"
        h_halt = max(nd.height for nd in nodes.values())

        fail.arm_raise(QUORUM_KILL_SITE, scope_token=VICTIM)
        # the armed site needs WAL traffic on the wedged victim. A PREVOTE
        # wedge (no polka) gets it from gossip stall-refresh re-sends; a
        # PRECOMMIT wedge keeps every link chatty with maj23 queries —
        # never silent, so nothing is re-sent and nothing is WAL'd.
        # Re-deliver one duplicate vote into the victim's queue (exactly
        # what a stall-refresh re-send is): receive_routine WALs every
        # peer record before applying it, and the group commit's deadline
        # fsync fires the kill regardless of which step the wedge hit.
        from tendermint_tpu.consensus.state import VoteMessage
        deadline = time.monotonic() + 60
        while (not victim.killed_evt.is_set()
               and time.monotonic() < deadline):
            rs = victim.cs.rs
            vs = rs.votes.prevotes(rs.round) if rs.votes else None
            votes = vs.list_votes() if vs is not None else []
            if votes:
                await victim.cs.add_peer_msg(VoteMessage(votes[0]), "val0")
            await asyncio.sleep(0.1)
        assert victim.killed_evt.is_set(), \
            f"{QUORUM_KILL_SITE!r} never fired on {VICTIM} during the " \
            f"halted window"
        assert victim.killed_at == QUORUM_KILL_SITE
        # the kill landed INSIDE the window: no commit since halt detection
        h_at_kill = max(nd.height for nd in nodes.values())
        assert h_at_kill == h_halt, \
            f"height advanced during the halt: {h_halt} -> {h_at_kill}"
        victim.freeze()
        await _bounded(net.remove_node(VICTIM), 30, "remove_node(victim)")
        await _bounded(victim.stop(), 30, "dead victim stop", fatal=False)
        del nodes[VICTIM]

        backoff = sup.on_exit(1)
        assert backoff is not None and not sup.gave_up
        await asyncio.sleep(backoff)

        # heal and restart the victim immediately: the property under
        # test is the restart replaying a halt-spanning WAL and
        # rejoining, not 3-of-4 progress (the plain victim boundaries
        # prove survivors commit while one validator is down) — and a
        # full 40/40 post-heal fleet recovers exactly like the proven
        # tools/quorum_loss.py window, where 30/40 with a dead proposer
        # in the rotation can wedge on rare post-heal vote states
        net.heal(group_a=isolate)
        restarted = CrashRigNode(VICTIM, genesis, home=victim_home)
        nodes[VICTIM] = restarted
        sup.on_launch()
        await _bounded(restarted.start(), 60, "restarted victim start")
        await _bounded(net.add_node(restarted.switch,
                                    connect_to=survivor_names),
                       30, "add_node(restarted victim)")
        await churn._wait_heights(list(nodes.values()), h_halt + 1,
                                  timeout=120)
    finally:
        await wd.stop()
    kill_to_caughtup = time.monotonic() - t_kill0

    common = min(nd.height for nd in nodes.values()) - 1
    hashes = {n: nd.block_store.load_block_meta(common).header.app_hash
              for n, nd in nodes.items()}
    assert len(set(hashes.values())) == 1, \
        f"app hashes diverged after the quorum-loss kill: {hashes}"
    lss_after = nodes[VICTIM].pv.last_sign_state.height
    assert lss_after >= lss_before, \
        f"sign state regressed across the halt: {lss_before} -> {lss_after}"
    double_sign = _evidence_observed(nodes.values(), common)
    assert not double_sign, \
        f"double-sign evidence after the quorum-loss kill: {double_sign}"
    return {
        "boundary": QUORUM_BOUNDARIES[0], "target": VICTIM,
        "kill_site": QUORUM_KILL_SITE, "killed": True, "halted": True,
        "halt_reason": wd.last_halt_reason, "recovered": True,
        "restarts": sup.restarts, "evidence": 0,
        "double_sign_observed": False,
        "wal_repaired": bool(nodes[VICTIM].wal_repairs),
        "wal_repaired_bytes": nodes[VICTIM].wal_repaired_bytes,
        "recovery_records_replayed":
            nodes[VICTIM].recovery_records_replayed,
        "kill_to_caughtup_s": round(kill_to_caughtup, 3),
        "backoff_s": backoff,
    }


def _evidence_observed(nodes, up_to_height: int):
    """Any pending or committed DuplicateVoteEvidence anywhere — the
    on-the-wire observable of a double-sign."""
    found = []
    for nd in nodes:
        pending, _ = nd.evpool.pending_evidence(1 << 20)
        found.extend((nd.name, "pending", type(e).__name__) for e in pending)
        for h in range(max(1, up_to_height - 20), up_to_height + 1):
            blk = nd.block_store.load_block(h)
            ev = getattr(getattr(blk, "evidence", None), "evidence", None) \
                if blk is not None else None
            if ev:
                found.extend((nd.name, f"committed@{h}",
                              type(e).__name__) for e in ev)
    return found


def _check_mempool_wal_idempotent(wal_dir: str) -> bool:
    """Replay the victim's MempoolWAL TWICE into one fresh mempool: the
    second pass must re-admit nothing (every line a cache dup/skip)."""
    from tendermint_tpu.abci.example.kvstore import KVStoreApplication
    from tendermint_tpu.mempool import CListMempool
    from tendermint_tpu.mempool.ingest import replay_mempool_wal
    from tendermint_tpu.proxy import AppConns, local_client_creator

    conns = AppConns(local_client_creator(KVStoreApplication()))
    conns.start()
    try:
        mp = CListMempool(conns.mempool, max_txs=100000)
        replayed1, _ = replay_mempool_wal(mp, wal_dir)
        replayed2, skipped2 = replay_mempool_wal(mp, wal_dir)
        assert replayed2 == 0, \
            f"MempoolWAL replay not idempotent: 2nd pass admitted {replayed2}"
        assert replayed1 == 0 or skipped2 >= replayed1
        return True
    finally:
        conns.stop()


def run_matrix(seed: int = 1, boundaries=None) -> dict:
    """One full matrix run; returns the report dict (asserts on failure).
    Pure-python ed25519 keeps the rig independent of device kernels."""
    import asyncio
    import tempfile

    os.environ.setdefault("TMTPU_BATCH_BACKEND", "host")
    home_root = tempfile.mkdtemp(prefix=f"crashmatrix-{seed}-")
    from tendermint_tpu.libs import fail

    fail.reset()
    try:
        return asyncio.run(_run_async(seed, boundaries, home_root))
    finally:
        fail.reset()


# -- self-test (stdlib + cheap libs: plan, schema, catalog, supervisor) ------

def self_test() -> int:
    from tendermint_tpu.libs.fail import KNOWN_FAIL_POINTS
    from tendermint_tpu.libs.supervisor import RestartPolicy

    # the code-site boundary catalog is a subset of the production fail
    # points — a drifting name would make that cell pass vacuously. The
    # quorum-loss boundary is a timing WINDOW, not a code site; the site
    # it arms inside the window must itself be real
    assert (set(VICTIM_BOUNDARIES + JOINER_BOUNDARIES)
            <= set(KNOWN_FAIL_POINTS)), \
        sorted(set(VICTIM_BOUNDARIES + JOINER_BOUNDARIES)
               - set(KNOWN_FAIL_POINTS))
    assert QUORUM_KILL_SITE in KNOWN_FAIL_POINTS
    assert not set(QUORUM_BOUNDARIES) & set(KNOWN_FAIL_POINTS)
    # plan determinism + shape
    p1 = plan_crashes(7)
    p2 = plan_crashes(7)
    assert p1 == p2, "same-seed plans diverged"
    assert plan_crashes(8) != p1, "seed does not vary the plan"
    assert len(p1["kills"]) == len(ALL_BOUNDARIES)
    assert {k["boundary"] for k in p1["kills"]} == set(ALL_BOUNDARIES)
    # joiner boundaries always run last (donors need settled snapshots),
    # the quorum-loss window just before them (it halts the whole fleet)
    targets = [k["target"] for k in p1["kills"]]
    assert targets[-len(JOINER_BOUNDARIES):] == ["joiner"] * len(
        JOINER_BOUNDARIES)
    assert all(t == VICTIM for t in targets[:-len(JOINER_BOUNDARIES)])
    pre_joiner = [k["boundary"] for k in p1["kills"]][:-len(JOINER_BOUNDARIES)]
    assert pre_joiner[-len(QUORUM_BOUNDARIES):] == list(QUORUM_BOUNDARIES)
    # subset + unknown rejection
    sub = plan_crashes(1, ["wal.after_fsync"])
    assert [k["boundary"] for k in sub["kills"]] == ["wal.after_fsync"]
    try:
        plan_crashes(1, ["no.such.boundary"])
        raise AssertionError("unknown boundary accepted")
    except ValueError:
        pass
    # fingerprint strips wall-clock fields but keeps the invariant schema
    fake = {"plan": p1, "kills": [{
        "boundary": "wal.after_fsync", "target": VICTIM, "killed": True,
        "recovered": True, "restarts": 1, "evidence": 0,
        "double_sign_observed": False, "wal_repaired": False,
        "wal_repaired_bytes": 0, "recovery_records_replayed": 3,
        "kill_to_caughtup_s": 4.5, "backoff_s": 0.2}],
        "elapsed_s": 9.9}
    fp = outcome_fingerprint(fake)
    s = json.dumps(fp)
    assert "kill_to_caughtup_s" not in s and "backoff_s" not in s
    for key in ("killed", "recovered", "evidence", "double_sign_observed"):
        assert key in fp["kills"][0], key
    # the supervisor's backoff schedule is the bounded doubling the
    # README documents
    assert RestartPolicy(policy="on-failure", max_restarts=3,
                         backoff_s=0.5).schedule() == [0.5, 1.0, 2.0]
    print("crashmatrix self-test OK (catalog, plan determinism, schema)")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--seed", type=int, default=1)
    ap.add_argument("--boundaries", default="",
                    help="comma-separated subset of: "
                         + ", ".join(ALL_BOUNDARIES))
    ap.add_argument("--verify-determinism", action="store_true",
                    help="run TWICE with the same seed and assert identical "
                         "kill schedules + recovery outcomes")
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--self-test", action="store_true")
    args = ap.parse_args(argv)
    if args.self_test:
        return self_test()
    if os.environ.get("TMTPU_CRASHMATRIX_DUMP_S"):
        # debug aid for a wedged matrix: dump every thread's stack to
        # stderr on an interval (a blocked event loop shows the same
        # synchronous frame dump after dump)
        import faulthandler

        faulthandler.dump_traceback_later(
            float(os.environ["TMTPU_CRASHMATRIX_DUMP_S"]), repeat=True)

    boundaries = [b.strip() for b in args.boundaries.split(",")
                  if b.strip()] or None
    r1 = run_matrix(args.seed, boundaries)
    if args.verify_determinism:
        r2 = run_matrix(args.seed, boundaries)
        f1, f2 = outcome_fingerprint(r1), outcome_fingerprint(r2)
        if f1 != f2:
            print("DETERMINISM FAIL:\n" + json.dumps(f1, indent=2)
                  + "\nvs\n" + json.dumps(f2, indent=2), file=sys.stderr)
            return 1
        r1["determinism_verified"] = True
    if args.json:
        print(json.dumps(r1, indent=2))
    else:
        worst = max((k["kill_to_caughtup_s"] for k in r1["kills"]),
                    default=0.0)
        print(f"crashmatrix OK: seed={r1['seed']} "
              f"{len(r1['kills'])}/{len(r1['plan']['kills'])} boundaries "
              f"killed+recovered, worst kill→caught-up {worst}s, "
              f"mempool WAL idempotent={r1['mempool_wal_idempotent']}"
              + (" [determinism verified]"
                 if r1.get("determinism_verified") else ""))
    return 0


if __name__ == "__main__":
    sys.exit(main())
