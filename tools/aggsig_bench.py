"""Aggregate-signature A/B bench: ed25519 CommitSig-list verification vs
one BLS fast-aggregate-verify pairing, at configurable validator counts.

    python tools/aggsig_bench.py                  # 150- and 1000-validator A/B
    python tools/aggsig_bench.py --vals 64,256    # custom sizes
    python tools/aggsig_bench.py --self-test

Delegates to bench.py's aggsig helpers so this tool and
``python bench.py --config aggsig`` measure the IDENTICAL code path
(ValidatorSet.verify_commit with the scheme registry dispatching per
chain). Rows use the same JSONL contract as bench.py; the BLS rows'
vs_baseline is the A/B ratio against the ed25519-batched rate at the same
scale. The self-test runs a miniature A/B (8 validators, host-scalar
regime on both sides) asserting accept/reject parity and the wire-size
collapse — fast enough for tools/selfcheck.py's per-tool timeout.

Stdlib + the package; no OpenSSL binding required (keys come from the
package's own crypto plane).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def _emit(metric: str, value: float, unit: str, vs_baseline: float, **extra):
    line = {"metric": metric, "value": round(value, 3), "unit": unit,
            "vs_baseline": round(vs_baseline, 3)}
    line.update(extra)
    print(json.dumps(line), flush=True)


def _timed(fn, warm: int = 1, runs: int = 3) -> float:
    for _ in range(warm):
        fn()
    best = float("inf")
    for _ in range(runs):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


def run_ab(val_counts, warm: int = 1, runs: int = 3) -> int:
    import bench
    from tendermint_tpu.crypto import schemes

    try:
        for n_vals in val_counts:
            ed_chain = f"aggsig-tool-ed-{n_vals}"
            vs_ed, c_ed, bid_ed = bench._mk_ed25519_commit_local(
                n_vals, ed_chain)
            ed_best = _timed(lambda: vs_ed.verify_commit(
                ed_chain, bid_ed, 100, c_ed), warm, runs)
            ed_rate = 1.0 / ed_best
            _emit(f"verify_commit_{n_vals}val_ed25519_batched_commits_per_sec",
                  ed_rate, "commits/s", 1.0, n_vals=n_vals)

            bls_chain = f"aggsig-tool-bls-{n_vals}"
            vs_b, c_b, bid_b = bench._mk_bls_aggregated_commit(
                n_vals, bls_chain)
            bls_best = _timed(lambda: vs_b.verify_commit(
                bls_chain, bid_b, 100, c_b), warm, runs)
            bls_rate = 1.0 / bls_best
            _emit(f"verify_commit_{n_vals}val_bls_aggregated_commits_per_sec",
                  bls_rate, "commits/s", bls_rate / ed_rate, n_vals=n_vals)
            _emit(f"aggregated_commit_{n_vals}val_bytes",
                  float(len(c_b.encode())), "bytes", 0.0,
                  ed25519_commit_bytes=len(c_ed.encode()),
                  compression_ratio=round(
                      len(c_ed.encode()) / len(c_b.encode()), 1))
    finally:
        schemes.reset()
    return 0


def self_test() -> int:
    import bench
    from tendermint_tpu.crypto import schemes
    from tendermint_tpu.libs.bits import BitArray
    from tendermint_tpu.types.block import AggregatedCommit, Commit
    from tendermint_tpu.types.errors import (
        ErrNotEnoughVotingPowerSigned,
        ErrWrongSignature,
    )

    n = 8
    try:
        # ed25519 side: valid commit accepted, tampered signature rejected
        vs_ed, c_ed, bid_ed = bench._mk_ed25519_commit_local(n, "st-ed")
        vs_ed.verify_commit("st-ed", bid_ed, 100, c_ed)
        bad = Commit(c_ed.height, c_ed.round, c_ed.block_id,
                     list(c_ed.signatures))
        cs = bad.signatures[0]
        bad.signatures[0] = type(cs)(cs.block_id_flag, cs.validator_address,
                                     cs.timestamp_ns,
                                     bytes(64))
        try:
            vs_ed.verify_commit("st-ed", bid_ed, 100, bad)
            raise AssertionError("tampered ed25519 commit accepted")
        except ErrWrongSignature:
            pass

        # BLS side: valid aggregated commit accepted on all three verify
        # modes, tampered aggregate rejected, sub-quorum bitmap rejected
        vs_b, c_b, bid_b = bench._mk_bls_aggregated_commit(n, "st-bls")
        vs_b.verify_commit("st-bls", bid_b, 100, c_b)
        vs_b.verify_commit_light("st-bls", bid_b, 100, c_b)
        vs_b.verify_commit_light_trusting("st-bls", c_b, (1, 3),
                                          commit_vals=vs_b)
        tampered = AggregatedCommit(
            c_b.height, c_b.round, c_b.block_id, [], signers=c_b.signers,
            agg_sig=bytes([c_b.agg_sig[0] ^ 0x01]) + c_b.agg_sig[1:],
            timestamp_ns=c_b.timestamp_ns)
        try:
            vs_b.verify_commit("st-bls", bid_b, 100, tampered)
            raise AssertionError("tampered aggregate accepted")
        except ErrWrongSignature:
            pass
        sub = BitArray(n)
        sub.set_index(0, True)
        sub.set_index(1, True)  # 2/8 voting power: below the 2/3 quorum
        subq = AggregatedCommit(
            c_b.height, c_b.round, c_b.block_id, [], signers=sub,
            agg_sig=c_b.agg_sig, timestamp_ns=c_b.timestamp_ns)
        try:
            vs_b.verify_commit("st-bls", bid_b, 100, subq)
            raise AssertionError("sub-quorum bitmap accepted")
        except (ErrWrongSignature, ErrNotEnoughVotingPowerSigned):
            # the mismatched bitmap fails the pairing first; either error
            # is a rejection — parity with the ed25519 sub-quorum outcome
            pass

        # wire-size collapse: fixed-size aggregate vs n CommitSig entries
        assert len(c_b.encode()) < len(c_ed.encode()), (
            len(c_b.encode()), len(c_ed.encode()))
    finally:
        schemes.reset()
    print(f"aggsig_bench self-test OK (A/B parity at {n} validators, "
          f"agg {len(c_b.encode())} B vs ed25519 {len(c_ed.encode())} B)")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--vals", default="150,1000",
                    help="comma-separated validator counts for the A/B")
    ap.add_argument("--runs", type=int, default=3)
    ap.add_argument("--warm", type=int, default=1)
    ap.add_argument("--self-test", action="store_true")
    args = ap.parse_args(argv)
    if args.self_test:
        return self_test()
    try:
        counts = [int(v) for v in args.vals.split(",") if v]
    except ValueError:
        ap.error(f"--vals wants comma-separated integers, got {args.vals!r}")
    return run_ab(counts, args.warm, args.runs)


if __name__ == "__main__":
    sys.exit(main())
