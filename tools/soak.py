"""Game-day soak: every chaos plane at once, judged by SLOs.

Each chaos cell arms ONE hostile condition for ~2 minutes and asserts
invariants. This driver is the "game day" the ROADMAP calls for: an
8-16 node in-proc fleet (churn.py's rig) under continuous open-loop
SIGNED load at a measured fraction of admission capacity, with FIVE
planes armed concurrently from ONE seed:

* churn    — a full node leaves, a fresh one statesync-joins (plan_churn);
* crash    — a victim is killed AT a durability boundary (libs/fail
             arm_raise, crashmatrix's kill machinery), then rebuilt and
             rejoined, kill-to-caught-up on the clock;
* corrupt  — seeded bit flips on in-flight payloads (faults net.corrupt);
* partition— a node black-holed from the fleet for a window, then healed;
* quorum_loss — >1/3 of validator power isolated for a bounded window
             (tools/quorum_loss.py's planner, the deferred ROADMAP
             cell): commits halt BY DESIGN, and any SLO breach inside
             the window attributes to this plane, not to a mystery.

The run is judged by a declarative SLOSpec (libs/slo.py): p99 commit
latency, kill/join-to-caught-up, zero queue-full sheds under capacity,
bounded RSS/WAL/sealed-ring growth slopes, bounded metric-series
cardinality — evaluated over sliding windows from streams the repo
already emits (txlife sealed records, ProcessMetrics watermarks,
FleetScraper rollups over in-proc registries, consensus stage
timelines). Every breach is ATTRIBUTED by intersecting its window with
the armed chaos windows plus the slowest-stage timeline: each SLO miss
names a plane, a node and a stage — with ``unattributed`` as a loud
first-class outcome (that's how slow leaks surface).

Determinism: the schedule is a PURE function of (seed, n_nodes,
duration) — ``plan_gameday`` — and ``--verify-determinism`` replays the
pure half (plan + seeded synthetic streams through the SLO engine) twice
per seed, diffing chaos-schedule AND breach fingerprints.

    python tools/soak.py --nodes 8 --duration 120 --seed 1
    python tools/soak.py --ci                  # 5-minute CI shape
    python tools/soak.py --verify-determinism --seeds 1,2
    python tools/soak.py --self-test           # stdlib-only, seconds

Stdlib-only at the top level; repo imports happen inside the run (the
churn.py/chaos_matrix.py pattern) so --help/--self-test work anywhere.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

TOOLS_DIR = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(TOOLS_DIR)
for p in (REPO, TOOLS_DIR):
    if p not in sys.path:
        sys.path.insert(0, p)

#: load rate = min(RATE_CAP, PER_FLEET_BUDGET/n, max(RATE_FLOOR,
#: fraction * measured capacity / n)) — all n nodes, their gossip, their
#: consensus AND the load generator share one event loop, so the
#: sustainable whole-fleet rate shrinks as the fleet grows
DEFAULT_RATE_FRACTION = 0.2
DEFAULT_RATE_CAP = 50.0
PER_FLEET_BUDGET = 100.0
RATE_FLOOR = 5.0

#: boundaries a MemDB in-proc node reaches every block (subset of
#: libs/fail.KNOWN_FAIL_POINTS — WAL/db boundaries need file stores,
#: which the soak fleet trades away for scale)
CRASH_BOUNDARIES = ("execution.before_exec_block",
                    "consensus.commit.before_end_height")


def _churn_mod():
    # toolbox.load_tool() pops TOOLS_DIR from sys.path after importing
    # this module; sibling imports must re-assert it
    if TOOLS_DIR not in sys.path:
        sys.path.insert(0, TOOLS_DIR)
    import churn
    return churn


def _slo_mod():
    from tendermint_tpu.libs import slo
    return slo


def _quorum_loss_mod():
    if TOOLS_DIR not in sys.path:
        sys.path.insert(0, TOOLS_DIR)
    import quorum_loss
    return quorum_loss


# -- the deterministic plan (pure) -------------------------------------------

def plan_gameday(seed: int, n_nodes: int, duration_s: float,
                 n_validators: int = 4) -> dict:
    """The multi-plane chaos schedule as a pure function of its inputs:
    offset-timestamped armed windows, one per plane. Victims of the
    churn/crash/partition planes are drawn only from full nodes; the
    quorum_loss plane is the ONE deliberate exception — it exists to
    isolate >1/3 of validator power (quorum_loss.plan_quorum_loss picks
    the seeded subset) and arms only when the fleet carries a full
    4-validator quorum. Small fleets degrade gracefully: with no spare
    fulls only the corruption plane arms — which is exactly the tier-1
    smoke shape (2 nodes, one armed site)."""
    import random
    import zlib

    churn = _churn_mod()
    rng = random.Random(zlib.crc32(
        f"soak|{seed}|{n_nodes}|{int(duration_s)}".encode()))
    d = float(duration_s)
    vals, fulls = churn.node_names(n_nodes, n_validators)
    events = []

    def window(frac0, frac1):
        return round(d * frac0, 3), round(d * frac1, 3)

    # corruption: always armed (every fleet size has links to corrupt)
    c0, c1 = window(0.25, 0.55)
    events.append({"t0": c0, "t1": c1, "plane": "corrupt",
                   "kind": "net.corrupt", "node": None,
                   "detail": f"net.corrupt@0.05 seed={seed}"})

    spares = list(fulls)
    # churn: plan_churn picks the leaver/joiner (same namespace as the
    # churn plane everywhere else); rotations stay out of the soak
    if spares:
        cev = churn.plan_churn(seed, 1, n_nodes, n_validators)["events"][0]
        leaver, joiner = cev.get("leave"), cev["join"]
        if leaver in spares:
            spares.remove(leaver)
        t0, t1 = window(0.12, 0.62)
        events.append({"t0": t0, "t1": t1, "plane": "churn",
                       "kind": "leave_join", "node": leaver,
                       "join": joiner,
                       "detail": f"leave {leaver}, statesync-join {joiner}"})
    # crash: kill a spare full AT a boundary, supervised rebuild + rejoin
    if spares:
        victim = spares.pop(rng.randrange(len(spares)))
        boundary = rng.choice(CRASH_BOUNDARIES)
        t0, t1 = window(0.45, 0.9)
        events.append({"t0": t0, "t1": t1, "plane": "crash",
                       "kind": "kill_restart", "node": victim,
                       "boundary": boundary,
                       "detail": f"arm_raise {boundary} on {victim}, "
                                 f"rebuild + statesync rejoin"})
    # partition: black-hole one remaining spare full for a window
    if spares:
        iso = spares[rng.randrange(len(spares))]
        t0, t1 = window(0.65, 0.85)
        events.append({"t0": t0, "t1": t1, "plane": "partition",
                       "kind": "blackhole", "node": iso,
                       "detail": f"partition {iso} from the fleet, "
                                 f"heal at window end"})
    # quorum loss: isolate >1/3 of validator power for a bounded window
    # (the seeded subset from quorum_loss.plan_quorum_loss) — kept clear
    # of the corrupt window so a commit-latency breach inside the halt
    # attributes to THIS plane, never smeared onto the bit flips
    if min(n_validators, n_nodes) >= 4:
        ql = _quorum_loss_mod()
        qev = ql.plan_quorum_loss(
            seed, 1, n_validators=min(n_validators, n_nodes))["events"][0]
        t0, t1 = window(0.68, 0.8)
        events.append({"t0": t0, "t1": t1, "plane": "quorum_loss",
                       "kind": "net.quorum_loss", "node": None,
                       "isolate": qev["isolate"],
                       "isolated_power": qev["isolated_power"],
                       "total_power": qev["total_power"],
                       "detail": f"isolate {'+'.join(qev['isolate'])} "
                                 f"({qev['isolated_power']}/"
                                 f"{qev['total_power']} power, >1/3), "
                                 f"heal at window end"})
    events.sort(key=lambda e: (e["t0"], e["plane"]))
    return {"seed": seed, "n_nodes": n_nodes,
            "duration_s": round(d, 3),
            "n_validators": min(n_validators, n_nodes),
            "events": events}


def schedule_fingerprint(plan: dict) -> str:
    return _slo_mod().schedule_fingerprint(plan["events"])


# -- the pure half: synthetic streams through the engine ----------------------

def synthetic_gameday(seed: int, n_nodes: int = 8, duration_s: float = 120.0,
                      inject: bool = True, leak: bool = True,
                      spec_text=None) -> dict:
    """Seeded synthetic streams derived from the plan, pushed through the
    real SLO engine: commit latency spikes INSIDE the corruption window
    on one node and inside the quorum-loss window on another (each
    injected regression must attribute to ITS armed plane — the windows
    are disjoint by construction) and a monotone RSS ramp spanning the
    whole run (the slow leak — must stay loudly unattributed). The
    backbone of --verify-determinism and the attribution self-test."""
    import random
    import zlib

    slo = _slo_mod()
    churn = _churn_mod()
    plan = plan_gameday(seed, n_nodes, duration_s)
    rng = random.Random(zlib.crc32(f"soak-synth|{seed}".encode()))
    spec = slo.SLOSpec.parse(spec_text) if spec_text else slo.SLOSpec.default()
    engine = slo.SLOEngine(spec)
    corrupt = [ev for ev in plan["events"] if ev["plane"] == "corrupt"]
    qloss = [ev for ev in plan["events"] if ev["plane"] == "quorum_loss"]
    vals = churn.node_names(n_nodes)[0]
    node, qnode = vals[0], vals[-1]
    t = 0.0
    while t < duration_s:
        lat = 0.3 + 0.2 * rng.random()
        if inject and any(ev["t0"] <= t <= ev["t1"] for ev in corrupt):
            lat = 30.0 + rng.random()
        engine.feed("commit_latency", t, lat, node=node)
        if qloss:
            # the halted quorum: commits stop inside the window, which a
            # sliding p99 reads as a latency wall on the observing node
            qlat = 0.3 + 0.2 * rng.random()
            if inject and any(ev["t0"] <= t <= ev["t1"] for ev in qloss):
                qlat = 30.0 + rng.random()
            engine.feed("commit_latency", t, qlat, node=qnode)
        if leak:
            # 64 MB/s against an 8 MB/s bound: unmistakably a leak
            engine.feed("rss_bytes", t, 1e8 + t * 64e6, node=node)
        else:
            engine.feed("rss_bytes", t, 1e8, node=node)
        t += 1.0
    breaches = slo.attribute_all(engine.evaluate(), plan["events"],
                                 total_span=duration_s)
    return {
        "plan": plan,
        "breaches": breaches,
        "unattributed": sum(1 for b in breaches
                            if b["attribution"]["plane"] == "unattributed"),
        "schedule_fingerprint": slo.schedule_fingerprint(plan["events"]),
        "breach_fingerprint": slo.breach_fingerprint(breaches),
    }


def verify_determinism(seeds=(1, 2), n_nodes: int = 8,
                       duration_s: float = 120.0) -> dict:
    """Per seed, run the pure half TWICE and diff chaos-schedule and
    breach fingerprints. Returns {"ok": bool, "seeds": {...}}."""
    out = {"ok": True, "seeds": {}}
    for seed in seeds:
        a = synthetic_gameday(seed, n_nodes, duration_s)
        b = synthetic_gameday(seed, n_nodes, duration_s)
        ok = (a["schedule_fingerprint"] == b["schedule_fingerprint"]
              and a["breach_fingerprint"] == b["breach_fingerprint"])
        out["seeds"][str(seed)] = {
            "ok": ok,
            "schedule_fingerprint": a["schedule_fingerprint"],
            "breach_fingerprint": a["breach_fingerprint"],
            "breaches": len(a["breaches"]),
        }
        out["ok"] = out["ok"] and ok
    return out


# -- the in-proc rig ----------------------------------------------------------

_SOAK_RIG = None


def _soak_rig():
    """churn's ChurnNode grown the soak extras: the crashmatrix kill
    guard (scoped arm_raise + killed_evt), ingest-plane txlife wiring,
    and the watermark sampler — memoized, one class per process."""
    global _SOAK_RIG
    if _SOAK_RIG is not None:
        return _SOAK_RIG
    churn = _churn_mod()
    rig = churn._rig()
    Base = rig["ChurnNode"]
    from tendermint_tpu.libs import fail
    from tendermint_tpu.libs.fail import KilledAtFailPoint
    from tendermint_tpu.libs.txlife import TxLifecycle
    from tendermint_tpu.libs.watermark import ResourceWatermarks

    class SoakNode(Base):
        def __init__(self, name, genesis, pv, fast_sync=False):
            super().__init__(name, genesis, pv, fast_sync=fast_sync)
            # ChurnNode wires only the consensus metric set; the soak
            # judges ingest + resource streams too, and reads the
            # slowest-stage timeline out of stage_seconds (the timeline
            # seals into the histogram only when its metrics are wired)
            self.cs.timeline.metrics = self.metrics.consensus
            self.mempool.metrics = self.metrics.mempool
            self.txlife = TxLifecycle()
            self.txlife.metrics = self.metrics.mempool
            self.mempool.txlife = self.txlife
            self.watermarks = ResourceWatermarks(
                self.metrics.process, txlife=self.txlife,
                registry=self.metrics.registry)
            self.killed_at = None
            self.killed_evt = None  # created at start (needs a loop)
            # kill guard (crashmatrix pattern): a BaseException at an
            # armed boundary ends the receive loop; record WHERE
            orig = self.cs.receive_routine

            async def guarded():
                try:
                    await orig()
                except KilledAtFailPoint as e:
                    self.killed_at = e.site
                    if self.killed_evt is not None:
                        self.killed_evt.set()

            self.cs.receive_routine = guarded

        async def start(self):
            import asyncio

            self.killed_evt = asyncio.Event()
            # tasks created below inherit this scope: armed boundaries in
            # SHARED code (execution, commit) kill only this node's tasks
            token = fail.scope.set(self.name)
            try:
                await super().start()
            finally:
                fail.scope.reset(token)

        def render_metrics(self) -> str:
            """Callable /metrics endpoint for the in-proc FleetScraper:
            sample watermarks, then render — same order as node.py's
            HTTP handler."""
            try:
                self.watermarks.sample()
            except Exception:
                pass
            return self.metrics.registry.render()

    _SOAK_RIG = {"SoakNode": SoakNode, "fail": fail,
                 "KilledAtFailPoint": KilledAtFailPoint}
    return _SOAK_RIG


def _queue_full_count(nd) -> float:
    """Cumulative queue-full sheds on one node: failed_txs{reason~full}
    plus every admission-control shed."""
    total = 0.0
    try:
        for lv, v in nd.metrics.mempool.failed_txs._values.items():
            if any("full" in part for part in lv):
                total += v
    except Exception:
        pass
    try:
        total += sum(nd.metrics.mempool.shed_txs_total._values.values())
    except Exception:
        pass
    return total


# -- the live run -------------------------------------------------------------

async def _run_async(n_nodes: int, seed: int, duration_s: float,
                     rate_fraction: float, rate_cap: float,
                     spec_text, out_path, sample_interval: float,
                     topology: str, degree: int) -> dict:
    import asyncio

    # re-assert the tools dir: toolbox.load_tool() pops it from sys.path
    # after importing THIS module, so sibling imports deferred to run time
    # must put it back
    if TOOLS_DIR not in sys.path:
        sys.path.insert(0, TOOLS_DIR)
    import loadtime

    from tendermint_tpu.libs.faults import faults
    from tendermint_tpu.p2p import InProcNetwork

    from fleet_scrape import FleetScraper

    churn = _churn_mod()
    slo = _slo_mod()
    srig = _soak_rig()
    crig = churn._rig()
    SoakNode = srig["SoakNode"]
    fail = srig["fail"]

    plan = plan_gameday(seed, n_nodes, duration_s)
    spec = (slo.SLOSpec.parse(spec_text) if spec_text
            else slo.SLOSpec.default())
    engine = slo.SLOEngine(spec)

    vals, fulls = churn.node_names(n_nodes)
    pvs = {name: crig["make_pv"](name) for name in vals + fulls}
    genesis = crig["make_genesis"]([pvs[v] for v in vals], [10] * len(vals))
    nodes = {name: SoakNode(name, genesis, pvs[name])
             for name in vals + fulls}
    net = InProcNetwork()
    for nd in nodes.values():
        net.add_switch(nd.switch)
    for nd in nodes.values():
        await nd.start()
        # a healed quorum-loss window recovers through the gossip
        # self-heal (bitmap refresh -> vote re-send); the default 10s
        # refresh would dominate every recovery inside a short soak
        nd.cs.config.gossip_stall_refresh_s = 2.0
    await net.connect_topology(topology, degree=degree, seed=seed)

    scraper = FleetScraper(
        {name: nd.render_metrics for name, nd in nodes.items()},
        interval_s=max(1.0, sample_interval))

    armed_windows = []   # ACTUAL armed chaos windows (wall clock)
    stage_windows = []   # slowest-stage per sample interval (wall clock)
    joins, kills, event_errors, executed = [], [], {}, []
    done = asyncio.Event()
    loop = asyncio.get_running_loop()

    await churn._wait_heights(list(nodes.values()), 2)

    # capacity probe BEFORE chaos arms: open-loop rate is a fraction of
    # what admission measured, so "zero sheds while under capacity" is an
    # honest objective rather than a tautology. The probe uses SIGNED txs
    # (admission pays a host ed25519 verify each) and the measured
    # per-node rate is divided by fleet size: mempool gossip re-verifies
    # every admitted tx on every peer, so fleet capacity is per-node
    # capacity over n, not per-node capacity
    probe_txs = loadtime.make_signed_txs(
        96, [time.time_ns()] * 50, n_keys=4)
    t0p = time.perf_counter()
    for tx in probe_txs:
        try:
            nodes[vals[0]].mempool.check_tx(tx)
        except Exception:
            pass
    capacity = len(probe_txs) / max(time.perf_counter() - t0p, 1e-6)
    n = max(1, len(nodes))
    rate = min(rate_cap, PER_FLEET_BUDGET / n,
               max(RATE_FLOOR, capacity * rate_fraction / n))

    t_start_wall = time.time()
    t_start = loop.time()
    t_end = t_start + duration_s

    def survivors():
        return [nd for nd in nodes.values()
                if nd.name not in net.departed and not nd.fast_sync]

    # -- continuous open-loop signed load (loadtime discipline) ----------
    async def load_task():
        import itertools

        sent = 0
        chunk = []
        t0 = loop.time() + 0.1
        for i in itertools.count():
            if loop.time() >= t_end:
                break
            target = t0 + i / rate
            delay = target - loop.time()
            if delay > 0:
                await asyncio.sleep(delay)
            if not chunk:
                # pre-sign in a worker thread, small batches: pure-python
                # ed25519 is ~2 ms/tx and holds the GIL, so a big batch
                # would starve consensus and read back as node latency
                scheds = [time.time_ns() + int(1e9 * j / rate)
                          for j in range(50)]
                chunk = await loop.run_in_executor(
                    None, lambda: loadtime.make_signed_txs(
                        96, scheds, n_keys=16))
                chunk.reverse()
            tx = chunk.pop()
            live = survivors()
            if not live:
                continue
            try:
                live[i % len(live)].mempool.check_tx(tx)
                sent += 1
            except Exception:
                pass
        return sent

    # -- the SLO sampler: streams out of the running fleet ---------------
    async def sampler():
        consumed = {}      # node -> sealed records already consumed
        shed_seen = {}     # node -> cumulative shed count
        stage_sums = {}    # stage -> cumulative sum across nodes
        prev_t = time.time()
        tick = 0
        while not done.is_set():
            try:
                await asyncio.wait_for(done.wait(), timeout=sample_interval)
            except asyncio.TimeoutError:
                pass
            now = time.time()
            for name, nd in list(nodes.items()):
                try:
                    tl = nd.txlife
                    new = tl.sealed_total - consumed.get(name, 0)
                    if new > 0:
                        consumed[name] = tl.sealed_total
                        for rec in tl.tail(min(new, tl.ring_capacity)):
                            if (rec.get("terminal") == "committed"
                                    and rec.get("total_s") is not None):
                                engine.feed(
                                    "commit_latency",
                                    rec["t0_wall"] + rec["total_s"],
                                    rec["total_s"], node=name)
                    shed = _queue_full_count(nd)
                    d = shed - shed_seen.get(name, 0.0)
                    shed_seen[name] = shed
                    if d > 0:
                        engine.feed("queue_full_sheds", now, d, node=name)
                    w = nd.watermarks.sample()
                    engine.feed("rss_bytes", now, w["rss_bytes"], node=name)
                    engine.feed("wal_bytes", now, w["wal_bytes"], node=name)
                    engine.feed("ring_depth", now, w["ring_depth"],
                                node=name)
                    engine.feed("metric_series", now, w["metric_series"],
                                node=name)
                except Exception:
                    continue
            # slowest consensus stage this interval, summed across nodes
            try:
                sums = {}
                for nd in list(nodes.values()):
                    for lv, s in nd.metrics.consensus.stage_seconds. \
                            _sums.items():
                        sums[lv[0]] = sums.get(lv[0], 0.0) + s
                deltas = {st: v - stage_sums.get(st, 0.0)
                          for st, v in sums.items()}
                stage_sums = sums
                pos = {st: d for st, d in deltas.items() if d > 1e-9}
                if pos:
                    slowest = max(sorted(pos), key=lambda st: pos[st])
                    stage_windows.append(
                        {"t0": prev_t, "t1": now, "stage": slowest})
            except Exception:
                pass
            prev_t = now
            tick += 1
            if out_path and tick % 10 == 0:
                _write_report(out_path, {
                    "in_flight": True, "seed": seed, "plan": plan,
                    "armed_windows": armed_windows,
                    "elapsed_s": round(now - t_start_wall, 1)})

    # -- plane executors --------------------------------------------------
    async def do_corrupt(ev):
        cap = 400
        t0 = time.time()
        faults.configure(f"net.corrupt@0.05*{cap}", seed=seed)
        try:
            await asyncio.sleep(max(0.0, ev["t1"] - ev["t0"]))
        finally:
            faults.reset()
        armed_windows.append({"t0": t0, "t1": time.time(),
                              "plane": "corrupt", "node": None,
                              "detail": ev["detail"],
                              "fires": faults.fires("net.corrupt")})

    async def do_partition(ev):
        iso = ev["node"]
        t0 = time.time()
        net.partition([iso])
        try:
            await asyncio.sleep(max(0.0, ev["t1"] - ev["t0"]))
        finally:
            # heal exactly THIS cut: a global heal() would also erase a
            # concurrently armed quorum-loss window
            net.heal(group_a=[iso])
        armed_windows.append({"t0": t0, "t1": time.time(),
                              "plane": "partition", "node": iso,
                              "detail": ev["detail"]})

    async def do_quorum_loss(ev):
        isolate = list(ev["isolate"])
        t0 = time.time()
        h_cut = max((nd.height for nd in survivors()), default=0)
        net.partition(isolate)
        try:
            await asyncio.sleep(max(0.0, ev["t1"] - ev["t0"]))
        finally:
            net.heal(group_a=isolate)
        armed_windows.append({"t0": t0, "t1": time.time(),
                              "plane": "quorum_loss", "node": None,
                              "detail": ev["detail"],
                              "height_at_cut": h_cut,
                              "height_at_heal": max(
                                  (nd.height for nd in survivors()),
                                  default=0)})

    async def do_churn(ev):
        leaver, joiner = ev.get("node"), ev["join"]
        t0 = time.time()
        if leaver and leaver in nodes:
            nd = nodes.pop(leaver)
            scraper.remove_endpoint(leaver)
            await net.remove_node(leaver)
            await asyncio.wait_for(nd.stop(), timeout=30)
        jn = SoakNode(joiner, genesis, crig["make_pv"](joiner),
                      fast_sync=True)
        pvs[joiner] = jn.pv
        nodes[joiner] = jn
        secs = await asyncio.wait_for(
            churn.join_statesync(net, jn, nodes[vals[0]],
                                 [n for n in nodes if n != joiner], seed),
            timeout=150)
        scraper.add_endpoint(joiner, jn.render_metrics)
        engine.feed("caughtup", time.time(), secs, node=joiner)
        joins.append({"leave": leaver, "join": joiner, "caughtup_s": secs})
        armed_windows.append({"t0": t0, "t1": time.time(),
                              "plane": "churn", "node": leaver or joiner,
                              "detail": ev["detail"]})

    async def do_crash(ev):
        victim, boundary = ev["node"], ev["boundary"]
        nd = nodes.get(victim)
        if nd is None or nd.fast_sync:
            return
        t0 = time.time()
        fail.arm_raise(boundary, scope_token=victim)
        try:
            await asyncio.wait_for(nd.killed_evt.wait(), timeout=60)
        except asyncio.TimeoutError:
            fail.reset()
            kills.append({"node": victim, "boundary": boundary,
                          "fired": False})
            return
        t_kill = time.monotonic()
        rec = {"node": victim, "boundary": boundary, "fired": True,
               "killed_at": nd.killed_at}
        kills.append(rec)  # the kill is on the record even if rejoin fails
        nodes.pop(victim, None)
        scraper.remove_endpoint(victim)
        await net.remove_node(victim)
        try:
            await asyncio.wait_for(nd.stop(), timeout=20)
        except Exception:
            pass
        await asyncio.sleep(0.25)  # supervised-restart backoff (bounded)
        # two rejoin attempts, each with a freshly built node: the first
        # can race a concurrently armed partition window and time out
        last_err = None
        for attempt in range(2):
            fresh = SoakNode(victim, genesis, pvs[victim], fast_sync=True)
            nodes[victim] = fresh
            try:
                await asyncio.wait_for(
                    churn.join_statesync(
                        net, fresh, nodes[vals[0]],
                        [n for n in nodes if n != victim], seed),
                    timeout=150)
                break
            except Exception as e:
                last_err = e
                rec["rejoin_retries"] = attempt + 1
                nodes.pop(victim, None)
                await net.remove_node(victim)
                try:
                    await asyncio.wait_for(fresh.stop(), timeout=10)
                except Exception:
                    pass
                await asyncio.sleep(2.0)
        else:
            raise last_err
        scraper.add_endpoint(victim, fresh.render_metrics)
        caught = round(time.monotonic() - t_kill, 3)
        engine.feed("caughtup", time.time(), caught, node=victim)
        rec["kill_to_caughtup_s"] = caught
        armed_windows.append({"t0": t0, "t1": time.time(),
                              "plane": "crash", "node": victim,
                              "detail": ev["detail"]})

    EXEC = {"corrupt": do_corrupt, "partition": do_partition,
            "churn": do_churn, "crash": do_crash,
            "quorum_loss": do_quorum_loss}

    async def run_event(ev):
        delay = ev["t0"] - (loop.time() - t_start)
        if delay > 0:
            await asyncio.sleep(delay)
        executed.append([ev["plane"], ev.get("node")])
        try:
            await EXEC[ev["plane"]](ev)
        except Exception as e:  # an executor failure is data, not a wedge
            event_errors[f"{ev['plane']}:{ev.get('node')}"] = repr(e)

    h_initial = max(nd.height for nd in nodes.values())
    rewire_task = asyncio.create_task(churn.rewire_loop(net))
    sampler_task = asyncio.create_task(sampler())
    load_fut = asyncio.create_task(load_task())
    scraper.start()
    event_tasks = [asyncio.create_task(run_event(ev))
                   for ev in plan["events"]]
    try:
        sent = await load_fut
        # events normally end inside the run; the bound only exists so a
        # wedged rejoin (worst case: kill wait + two statesync attempts)
        # cannot hang the report
        await asyncio.wait_for(
            asyncio.gather(*event_tasks, return_exceptions=True),
            timeout=duration_s + 420.0)
    finally:
        done.set()
        faults.reset()
        fail.reset()
        net.heal()
        rewire_task.cancel()
        for t in event_tasks:
            t.cancel()
        try:
            await asyncio.wait_for(sampler_task, timeout=10)
        except Exception:
            pass
        rollup = scraper.stop()
        h_final = max((nd.height for nd in survivors()), default=0)
        for nd in list(nodes.values()):
            try:
                await asyncio.wait_for(nd.stop(), timeout=20)
            except Exception:
                pass

    breaches = slo.attribute_all(engine.evaluate(), armed_windows,
                                 stage_windows, total_span=duration_s)
    # headline observations for bench rows: one number each, derived from
    # the same streams the SLO engine judged (not a parallel measurement)
    lat_vals = [v for _, v, _ in engine._streams.get("commit_latency", [])]
    caught_vals = [v for _, v, _ in engine._streams.get("caughtup", [])]
    observed = {
        "commit_p99_s": (round(slo._percentile(lat_vals, 99.0), 4)
                         if lat_vals else None),
        "commit_samples": len(lat_vals),
        "caughtup_max_s": (round(max(caught_vals), 2)
                           if caught_vals else None),
    }
    report = {
        "seed": seed, "n_nodes": n_nodes,
        "duration_s": round(duration_s, 3), "topology": topology,
        "plan": plan,
        "schedule_fingerprint": slo.schedule_fingerprint(plan["events"]),
        "executed": executed,
        "armed_windows": armed_windows,
        "event_errors": event_errors,
        "load": {"capacity_probe_txs_per_s": round(capacity, 1),
                 "rate_txs_per_s": round(rate, 2),
                 "rate_fraction": rate_fraction, "sent": sent},
        "heights": {"initial": h_initial, "final": h_final},
        "joins": joins, "kills": kills,
        "observed": observed,
        "slo": {
            "objectives": spec.as_dicts(),
            "sample_counts": engine.sample_counts(),
            "breaches": breaches,
            "unattributed": sum(
                1 for b in breaches
                if b["attribution"]["plane"] == "unattributed"),
        },
        "breach_fingerprint": slo.breach_fingerprint(breaches),
        "fleet_rollup": {k: rollup.get(k) for k in
                         ("n_nodes", "cluster_height",
                          "cluster_blocks_per_min", "txs_admitted_delta",
                          "process")},
        "elapsed_s": round(time.time() - t_start_wall, 2),
    }
    return report


def _write_report(path: str, doc: dict) -> str:
    import tempfile

    fd, tmp = tempfile.mkstemp(prefix=os.path.basename(path) + ".",
                               dir=os.path.dirname(path) or ".")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(doc, f, indent=1, default=str)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return path


def history_rows(report: dict) -> list:
    """The game day reduced to its gated bench rows (same metric names
    and units as ``bench.py --config soak``) for the cross-run trend
    file. Pure: derives everything from the report dict."""
    sl = report["slo"]
    rows = [{"metric": "inproc_soak_slo_breaches",
             "value": float(len(sl["breaches"])), "unit": "breaches",
             "unattributed": sl["unattributed"]}]
    obs = report.get("observed", {})
    if obs.get("commit_samples"):
        rows.append({"metric": "inproc_soak_commit_p99_s",
                     "value": float(obs["commit_p99_s"]), "unit": "s",
                     "commit_samples": obs["commit_samples"]})
    else:
        rows.append({"metric": "inproc_soak_commit_p99_s",
                     "value": 0.0, "unit": "error",
                     "error": "no commit latency samples observed"})
    planned = {ev["plane"] for ev in report["plan"]["events"]}
    recoveries = [k["kill_to_caughtup_s"] for k in report.get("kills", [])
                  if k.get("kill_to_caughtup_s") is not None]
    if recoveries:
        rows.append({"metric": "inproc_soak_kill_caughtup_s",
                     "value": float(max(recoveries)), "unit": "s",
                     "kills": len(report["kills"])})
    elif "crash" in planned:
        # the crash plane armed but never completed a kill->rejoin
        # cycle: an errored row the trend gate must see, not a silently
        # absent one (small fleets with NO crash plane omit the row —
        # same-shape runs stay comparable)
        rows.append({"metric": "inproc_soak_kill_caughtup_s",
                     "value": 0.0, "unit": "error",
                     "error": "no completed kill->rejoin cycle"})
    return rows


def append_history(path: str, report: dict, label=None) -> dict:
    """Append ONE line to the cross-run trend file (JSONL — the format
    tools/bench_compare.py --history gates): {"label", "metrics"}."""
    entry = {
        "label": label or (f"seed{report['seed']}"
                           f"-n{report['n_nodes']}"
                           f"-{int(report['duration_s'])}s"),
        "seed": report["seed"],
        "schedule_fingerprint": report.get("schedule_fingerprint"),
        "breach_fingerprint": report.get("breach_fingerprint"),
        "metrics": history_rows(report),
    }
    with open(path, "a") as f:
        f.write(json.dumps(entry, default=str) + "\n")
    return entry


def run_soak(n_nodes: int = 8, seed: int = 1, duration_s: float = 120.0,
             rate_fraction: float = DEFAULT_RATE_FRACTION,
             rate_cap: float = DEFAULT_RATE_CAP,
             spec_text=None, out=None, sample_interval: float = 1.0,
             topology: str = "full_mesh", degree: int = 3) -> dict:
    """One game day; returns the attributed report (and writes it to
    ``out``, default ``soak_report.json`` in the cwd, exporting
    TMTPU_SOAK_REPORT so in-proc debugdump bundles pick it up)."""
    import asyncio

    os.environ.setdefault("TMTPU_BATCH_BACKEND", "host")
    out = out or os.path.abspath("soak_report.json")
    os.environ["TMTPU_SOAK_REPORT"] = out
    report = asyncio.run(_run_async(
        n_nodes, seed, duration_s, rate_fraction, rate_cap, spec_text,
        out, sample_interval, topology, degree))
    report["report_path"] = _write_report(out, report)
    return report


# -- self-test (stdlib-only: spec grammar, window math, attribution) ----------

def self_test() -> int:
    slo = _slo_mod()

    # spec grammar: parse, defaults, loud rejects
    spec = slo.SLOSpec.parse(
        "commit_latency p99 <= 2.5 window=30\ncaughtup max <= 60\n")
    assert [o.name for o in spec.objectives] == [
        "commit_latency_p99", "caughtup_max"]
    assert spec.objectives[0].window_s == 30.0
    assert len(slo.SLOSpec.default().objectives) == 7
    for bad in ("x p99 <=\n", "x p42 <= 1\n", "x p99 ~ 1\n",
                "x p99 <= one\n", "x p99 <= 1 win=3\n"):
        try:
            slo.SLOSpec.parse(bad)
        except ValueError:
            pass
        else:
            raise AssertionError(f"spec {bad!r} parsed")

    # window math: p99 over sliding windows trips only where the spike is
    eng = slo.SLOEngine(slo.SLOSpec.parse(
        "lat p99 <= 1.0 window=10\nevents count <= 0\n"))
    for t in range(60):
        eng.feed("lat", float(t), 5.0 if 20 <= t < 30 else 0.2, node="n0")
    b = eng.evaluate()
    assert len(b) == 1 and b[0]["objective"] == "lat_p99", b
    assert b[0]["node"] == "n0" and b[0]["observed"] >= 5.0
    w0, w1 = b[0]["window"]
    assert w0 <= 20 <= w1 and w1 < 45, b  # merged run hugs the spike
    # count: no samples -> no breach; one event -> breach
    eng.feed("events", 10.0, 1.0, node="n1")
    b2 = eng.evaluate()
    assert any(x["objective"] == "events_count" and x["node"] == "n1"
               for x in b2), b2

    # slope: monotone ramp trips, flat line doesn't, dips clamp to zero
    eng = slo.SLOEngine(slo.SLOSpec.parse("rss slope <= 100.0\n"))
    for t in range(30):
        eng.feed("rss", float(t), 1000.0 + 500.0 * t, node="leaky")
        eng.feed("rss", float(t), 5000.0 - 10.0 * t, node="fine")
    b = eng.evaluate()
    assert [x["node"] for x in b] == ["leaky"], b

    # attribution: injected breach -> its armed plane/node/stage; a
    # barely-overlapping event does NOT claim a long breach
    sched = [{"t0": 20.0, "t1": 35.0, "plane": "corrupt", "node": None,
              "detail": "bitflips"},
             {"t0": 0.0, "t1": 2.0, "plane": "churn", "node": "full0",
              "detail": "早"}]
    stages = [{"t0": 18.0, "t1": 36.0, "stage": "commit_finalized"}]
    att = slo.attribute({"window": [22.0, 33.0], "node": "val1"},
                        sched, stages)
    assert att == {"plane": "corrupt", "node": "val1",
                   "stage": "commit_finalized", "detail": "bitflips"}, att
    # whole-run leak window: corrupt covers <50% of it -> unattributed
    att2 = slo.attribute({"window": [0.0, 120.0], "node": "val1"}, sched)
    assert att2["plane"] == "unattributed", att2
    # point breach (caughtup event) inside a crash window -> attributed
    att3 = slo.attribute(
        {"window": [25.0, 25.0], "node": "full1"},
        [{"t0": 20.0, "t1": 40.0, "plane": "crash", "node": "full1"}])
    assert att3["plane"] == "crash" and att3["node"] == "full1", att3
    # concurrent planes: the nested, more specific window wins the broad
    # one armed across it
    att4 = slo.attribute(
        {"window": [28.0, 40.0], "node": "val0"},
        [{"t0": 0.0, "t1": 60.0, "plane": "churn", "node": "full0"},
         {"t0": 27.0, "t1": 41.0, "plane": "corrupt", "node": None}])
    assert att4["plane"] == "corrupt", att4

    # plan: pure, seeded, quorum-safe (except the one plane built to
    # take the quorum)
    p1 = plan_gameday(7, 8, 120)
    assert p1 == plan_gameday(7, 8, 120), "same-seed plans diverged"
    assert p1 != plan_gameday(8, 8, 120), "seed does not vary the plan"
    planes = {ev["plane"] for ev in p1["events"]}
    assert planes == {"corrupt", "churn", "crash", "partition",
                      "quorum_loss"}, planes
    vals = {f"val{i}" for i in range(4)}
    for ev in p1["events"]:
        assert ev.get("node") not in vals, f"quorum touched: {ev}"
        assert 0 <= ev["t0"] <= ev["t1"] <= 120
    # the quorum-loss window round-trips the quorum_loss planner: same
    # seeded isolation subset, >1/3 of the power, never every validator
    ql = _quorum_loss_mod()
    qev = next(ev for ev in p1["events"] if ev["plane"] == "quorum_loss")
    qplan = ql.plan_quorum_loss(7, 1, n_validators=4)["events"][0]
    assert qev["isolate"] == qplan["isolate"], (qev, qplan)
    assert qev["isolated_power"] == qplan["isolated_power"]
    assert qev["isolated_power"] * 3 > qev["total_power"], qev
    assert set(qev["isolate"]) < vals, qev
    # ...and stays clear of the corrupt window (attribution clarity)
    cev = next(ev for ev in p1["events"] if ev["plane"] == "corrupt")
    assert qev["t0"] >= cev["t1"] or qev["t1"] <= cev["t0"], (qev, cev)
    # small fleets degrade to the corrupt-only smoke shape; a full
    # quorum (>= 4 validators) always gets its loss window
    assert [ev["plane"] for ev in plan_gameday(1, 2, 30)["events"]] \
        == ["corrupt"]
    assert {ev["plane"] for ev in plan_gameday(1, 5, 30)["events"]} \
        == {"corrupt", "churn", "quorum_loss"}

    # the pure half: each injected regression attributes to ITS armed
    # plane, the leak stays loudly unattributed, fingerprints replay.
    # The latency objective runs a tighter sliding window here: the
    # default 30s window smears a breach well past the short quorum-loss
    # window, dropping the true cause below the attribution cover floor
    g = synthetic_gameday(
        3, 8, 120,
        spec_text="commit_latency p99 <= 20.0 window=10\n"
                  "rss_bytes slope <= 8388608\n")
    lat = [b for b in g["breaches"]
           if b["objective"] == "commit_latency_p99"]
    lat_planes = {b["attribution"]["plane"] for b in lat}
    assert lat and lat_planes == {"corrupt", "quorum_loss"}, lat
    leaks = [b for b in g["breaches"] if b["objective"] == "rss_bytes_slope"]
    assert leaks and all(b["attribution"]["plane"] == "unattributed"
                         for b in leaks), leaks
    assert g["unattributed"] == len(leaks)
    clean = synthetic_gameday(3, 8, 120, inject=False, leak=False)
    assert clean["breaches"] == [], clean["breaches"]
    assert clean["schedule_fingerprint"] == g["schedule_fingerprint"]
    assert clean["breach_fingerprint"] != g["breach_fingerprint"]
    vd = verify_determinism(seeds=(1, 2), duration_s=90)
    assert vd["ok"], vd

    # fingerprints strip wall-clock: observed/window never enter
    b1 = [{"objective": "o", "node": "n", "window": [1.0, 2.0],
           "observed": 9.9, "attribution": {"plane": "p", "stage": "s"}}]
    b2 = [{"objective": "o", "node": "n", "window": [50.0, 60.0],
           "observed": 1.1, "attribution": {"plane": "p", "stage": "s"}}]
    assert slo.breach_fingerprint(b1) == slo.breach_fingerprint(b2)

    # cross-run trend file: history_rows mirrors bench.py's gated soak
    # rows (names AND units), append_history writes one JSONL line per
    # run, and the file round-trips through bench_compare --history
    import tempfile

    fake = {"seed": 3, "n_nodes": 6, "duration_s": 60.0,
            "plan": {"events": [{"plane": "corrupt"}, {"plane": "crash"}]},
            "observed": {"commit_p99_s": 1.25, "commit_samples": 40},
            "kills": [{"kill_to_caughtup_s": 12.5}],
            "slo": {"breaches": [{"objective": "x"}], "unattributed": 1},
            "schedule_fingerprint": "s", "breach_fingerprint": "b"}
    rows = {r["metric"]: r for r in history_rows(fake)}
    assert rows["inproc_soak_slo_breaches"]["value"] == 1.0
    assert rows["inproc_soak_slo_breaches"]["unit"] == "breaches"
    assert rows["inproc_soak_commit_p99_s"] \
        == {"metric": "inproc_soak_commit_p99_s", "value": 1.25,
            "unit": "s", "commit_samples": 40}
    assert rows["inproc_soak_kill_caughtup_s"]["value"] == 12.5
    # armed-but-unfinished crash plane -> errored row, never absent
    stuck = dict(fake, kills=[{"fired": False}])
    rows = {r["metric"]: r for r in history_rows(stuck)}
    assert rows["inproc_soak_kill_caughtup_s"]["unit"] == "error"
    # no crash plane planned (small fleet) -> the row is legitimately out
    small = dict(fake, plan={"events": [{"plane": "corrupt"}]}, kills=[])
    assert "inproc_soak_kill_caughtup_s" not in {
        r["metric"] for r in history_rows(small)}
    d = tempfile.mkdtemp(prefix="soak-selftest-")
    try:
        hist = os.path.join(d, "trend.jsonl")
        e1 = append_history(hist, fake)
        assert e1["label"] == "seed3-n6-60s"
        worse = dict(fake, slo={"breaches": [{}, {}, {}, {}],
                                "unattributed": 0})
        append_history(hist, worse, label="worse")
        with open(hist) as f:
            entries = [json.loads(line) for line in f]
        assert [e["label"] for e in entries] == ["seed3-n6-60s", "worse"]
        assert all(e["metrics"] for e in entries)
        import bench_compare
        labels, runs = bench_compare.load_history(hist)
        assert labels == ["seed3-n6-60s", "worse"]
        verdict = {r["metric"]: r for r in bench_compare.compare(
            runs[-2], runs[-1], {})}
        assert verdict["inproc_soak_slo_breaches"]["status"] == "regressed"
    finally:
        import shutil

        shutil.rmtree(d, ignore_errors=True)

    print("soak self-test OK (spec grammar, window math, attribution, "
          "plan determinism, injected-regression + leak outcomes, "
          "cross-run trend rows)")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--nodes", type=int, default=8)
    ap.add_argument("--seed", type=int, default=1)
    ap.add_argument("--duration", type=float, default=120.0)
    ap.add_argument("--ci", action="store_true",
                    help="the CI shape: 8 nodes, 300 s")
    ap.add_argument("--topology", choices=("full_mesh", "sparse"),
                    default="full_mesh")
    ap.add_argument("--degree", type=int, default=3)
    ap.add_argument("--rate-fraction", type=float,
                    default=DEFAULT_RATE_FRACTION,
                    help="open-loop rate as a fraction of probed capacity")
    ap.add_argument("--rate-cap", type=float, default=DEFAULT_RATE_CAP)
    ap.add_argument("--sample-interval", type=float, default=1.0)
    ap.add_argument("--spec", default=None, metavar="PATH",
                    help="SLO spec file (default: libs/slo.py DEFAULT_SPEC)")
    ap.add_argument("--out", default=None, metavar="PATH",
                    help="report path (default ./soak_report.json)")
    ap.add_argument("--history", default=None, metavar="PATH",
                    help="append this run's gated rows to a cross-run "
                         "JSONL trend file (gate the trajectory with "
                         "tools/bench_compare.py --history PATH)")
    ap.add_argument("--seeds", default="1,2",
                    help="seeds for --verify-determinism")
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--self-test", action="store_true")
    ap.add_argument("--verify-determinism", action="store_true",
                    help="replay the pure half twice per seed and diff "
                         "chaos-schedule + breach fingerprints")
    args = ap.parse_args(argv)
    if args.self_test:
        return self_test()
    if args.verify_determinism:
        seeds = tuple(int(s) for s in args.seeds.split(",") if s)
        vd = verify_determinism(seeds=seeds, n_nodes=args.nodes,
                                duration_s=args.duration)
        print(json.dumps(vd, indent=2))
        print("determinism " + ("OK" if vd["ok"] else "FAIL")
              + f" over seeds {seeds}")
        return 0 if vd["ok"] else 1

    if args.ci:
        args.nodes, args.duration = max(args.nodes, 8), 300.0
    spec_text = None
    if args.spec:
        with open(args.spec) as f:
            spec_text = f.read()
    report = run_soak(
        n_nodes=args.nodes, seed=args.seed, duration_s=args.duration,
        rate_fraction=args.rate_fraction, rate_cap=args.rate_cap,
        spec_text=spec_text, out=args.out,
        sample_interval=args.sample_interval, topology=args.topology,
        degree=args.degree)
    if args.history:
        entry = append_history(args.history, report)
        print(f"history += {entry['label']} -> {args.history} "
              f"({len(entry['metrics'])} rows)")
    if args.json:
        print(json.dumps(report, indent=2, default=str))
    else:
        s = report["slo"]
        print(f"soak OK: N={report['n_nodes']} seed={report['seed']} "
              f"{report['duration_s']}s h {report['heights']['initial']}→"
              f"{report['heights']['final']} "
              f"load {report['load']['rate_txs_per_s']}/s "
              f"({report['load']['sent']} sent) "
              f"breaches={len(s['breaches'])} "
              f"unattributed={s['unattributed']} "
              f"joins={len(report['joins'])} kills={len(report['kills'])} "
              f"-> {report['report_path']}")
        for b in s["breaches"]:
            a = b["attribution"]
            print(f"  BREACH {b['objective']} node={b['node']} "
                  f"observed={b['observed']} (bound {b['op']} "
                  f"{b['threshold']}) -> plane={a['plane']} "
                  f"node={a['node']} stage={a['stage']}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
