"""Device-plane profiler for the batched verifier (VERDICT r3 weak #6:
"you can't push further without knowing where the µs/sig go").

Runs a jax.profiler trace around one sparse-stream verification and prints
the device-op time breakdown plus the host-side stage split (pack /
dispatch+transfer+compute / fetch). Works through the axon relay — device
op durations in the trace are trustworthy even though wall-clock timings of
individual dispatches are not (the relay pipelines and caches).

Usage: python tools/profile_verify.py [--n 8192] [--chunk 2048]
"""

import argparse
import collections
import glob
import gzip
import json
import os
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_batch(n: int):
    from bench import build_batch as bb

    return bb(n)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=8192)
    ap.add_argument("--chunk", type=int, default=2048)
    ap.add_argument("--trace-dir", default="")
    args = ap.parse_args()

    import jax

    from tendermint_tpu.crypto.ed25519_jax import verify as V

    pks, msgs, sigs, _pubs = build_batch(args.n)

    # stage split (wall clock; includes relay costs)
    t0 = time.perf_counter()
    sp = V.prepare_sparse_stream(pks, msgs, sigs, chunk=args.chunk)
    t_pack = time.perf_counter() - t0
    path = "sparse" if sp is not None else "dense"

    out = V.batch_verify_stream(pks, msgs, sigs, chunk=args.chunk)  # compile
    assert np.asarray(out).all()
    t0 = time.perf_counter()
    out = V.batch_verify_stream(pks, msgs, sigs, chunk=args.chunk)
    t_total = time.perf_counter() - t0

    trace_dir = args.trace_dir or tempfile.mkdtemp(prefix="verify-trace-")
    with jax.profiler.trace(trace_dir):
        np.asarray(V.batch_verify_stream(pks, msgs, sigs, chunk=args.chunk))

    files = sorted(glob.glob(
        os.path.join(trace_dir, "plugins/profile/*/*.trace.json.gz")))
    if not files:
        print("no trace captured (profiler unsupported on this backend)")
        return 1
    with gzip.open(files[-1]) as f:
        doc = json.load(f)
    pids = {e["pid"]: e["args"].get("name", "")
            for e in doc["traceEvents"]
            if e.get("ph") == "M" and e.get("name") == "process_name"}
    dev_pids = {p for p, nm in pids.items() if "TPU" in nm or "GPU" in nm
                or "/device" in nm}
    tot = collections.Counter()
    for e in doc["traceEvents"]:
        if e.get("ph") == "X" and e.get("pid") in dev_pids:
            tot[e["name"]] += e.get("dur", 0)
    dev_total_us = max(
        (d for nm, d in tot.items() if nm.startswith("jit_")), default=0)

    print(f"path: {path}   n={args.n} chunk={args.chunk}")
    print(f"host pack:          {t_pack * 1e3:8.1f} ms "
          f"({t_pack / args.n * 1e6:6.2f} us/sig)")
    print(f"end-to-end:         {t_total * 1e3:8.1f} ms "
          f"({t_total / args.n * 1e6:6.2f} us/sig)")
    print(f"device compute:     {dev_total_us / 1e3:8.1f} ms "
          f"({dev_total_us / args.n:6.2f} us/sig)")
    transfer = t_total - t_pack - dev_total_us / 1e6
    print(f"transfer+dispatch:  {transfer * 1e3:8.1f} ms (residual)")
    print("\ntop device ops:")
    for name, dur in tot.most_common(12):
        print(f"  {dur / 1e3:9.2f} ms  {name[:90]}")
    from tendermint_tpu.crypto.batch import device_threshold

    print(f"\nBatchVerifier break-even threshold: {device_threshold()} sigs")
    return 0


if __name__ == "__main__":
    sys.exit(main())
