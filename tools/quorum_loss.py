"""Quorum-loss windows: seeded >1/3 isolation over a live fleet.

Tendermint's liveness argument concedes exactly one regime: when more
than 1/3 of voting power is unreachable, height advance MUST halt — and
nothing else may go wrong. Safety (no conflicting commits, no
double-sign evidence) has to hold through the window, the watchdog has
to attribute the halt to the missing power (``halt_reason =
"quorum_lost"``, not a generic stall), and once the power returns the
fleet has to re-form a quorum and commit within a bound. This driver
makes that whole contract a seeded, asserted, gated scenario:

* ``plan_quorum_loss`` — a PURE function of (seed, windows,
  n_validators, powers): each window shuffles the validator set with a
  seeded RNG and isolates the shortest prefix whose power exceeds 1/3
  of the total (falling back to the single >2/3 whale when only the
  full set would qualify — survivors must exist to observe the halt),
  plus a seeded hold duration;
* the executor runs each planned window over a live 4-validator in-proc
  fleet (churn.py's rig): partition the isolated set, assert the height
  freezes, assert a survivor's ConsensusWatchdog classifies the episode
  ``quorum_lost`` with the isolated validators absent from the round's
  vote bitmaps, assert zero equivocations observed anywhere, then
  ``heal()`` exactly the cut and clock heal→next-commit (the worst
  window feeds the gated ``inproc_quorumloss_recover_s`` bench row);
* ``run_wan`` — the same fleet under the ``wan`` link profile
  (seeded base+jitter latency, light loss, reorder on every directed
  link), commit throughput on the clock (the gated
  ``inproc_wan4_commits_per_min`` row);
* ``outcome_fingerprint`` strips wall-clock so two same-seed runs can
  be diffed structurally (``--verify-determinism``).

    python tools/quorum_loss.py --seed 1 --windows 2
    python tools/quorum_loss.py --wan --blocks 12
    python tools/quorum_loss.py --verify-determinism
    python tools/quorum_loss.py --self-test   # stdlib-only, instant

Stdlib-only at the top level; repo imports happen inside the run (the
churn.py/chaos_matrix.py pattern) so --help/--self-test work anywhere.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import sys
import time

TOOLS_DIR = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(TOOLS_DIR)
for p in (REPO, TOOLS_DIR):
    if p not in sys.path:
        sys.path.insert(0, p)

N_VALIDATORS = 4
#: heal -> next committed height, worst window (the gated bound)
RECOVER_BOUND_S = 30.0
#: the executor tightens the gossip self-heal interval: the post-heal
#: recovery path is bitmap refresh -> vote re-send, so the refresh
#: interval IS the recovery clock's dominant term (default 10s would
#: make every recover_s sample mostly measure an idle timer)
GOSSIP_REFRESH_S = 1.0


def _churn_mod():
    if TOOLS_DIR not in sys.path:
        sys.path.insert(0, TOOLS_DIR)
    import churn
    return churn


# -- the deterministic plan (pure) -------------------------------------------

def plan_quorum_loss(seed: int, windows: int = 1,
                     n_validators: int = N_VALIDATORS,
                     powers=None) -> dict:
    """Seeded isolation windows as a pure function of the inputs. Each
    event names the isolated validators (>1/3 of total power, never the
    whole set), the isolated/total power, and a seeded hold duration."""
    import random
    import zlib

    powers = list(powers) if powers is not None else [10] * n_validators
    if len(powers) != n_validators:
        raise ValueError("one power per validator")
    total = sum(powers)
    rng = random.Random(zlib.crc32(
        ("quorumloss|%d|%d|%d|%s" % (
            seed, windows, n_validators,
            ",".join(map(str, powers)))).encode()))
    events = []
    for w in range(windows):
        order = list(range(n_validators))
        rng.shuffle(order)
        isolate, power = [], 0
        for i in order:
            isolate.append(i)
            power += powers[i]
            if power * 3 > total:
                break
        if len(isolate) == n_validators:
            # only reachable when the last-shuffled validator alone holds
            # >2/3 (every proper prefix summed <=1/3): isolating just the
            # whale already kills quorum AND leaves survivors to observe
            isolate, power = [order[-1]], powers[order[-1]]
        isolate.sort()
        events.append({
            "window": w,
            "isolate": ["val%d" % i for i in isolate],
            "isolated_power": power,
            "total_power": total,
            "hold_s": round(rng.uniform(2.5, 4.0), 3),
        })
    return {"seed": seed, "windows": windows,
            "n_validators": n_validators, "powers": powers,
            "events": events}


def plan_fingerprint(plan: dict) -> str:
    return hashlib.sha256(
        json.dumps(plan, sort_keys=True).encode()).hexdigest()[:16]


def outcome_fingerprint(report: dict) -> str:
    """Structural outcome only — wall-clock fields (recover_s, heights
    reached, elapsed) never enter, so two same-seed runs fingerprint
    identically whenever the CONTRACT held the same way."""
    core = {
        "plan": report["plan"],
        "windows": [
            {k: w[k] for k in ("window", "isolate", "halted",
                               "halt_reason", "recovered")}
            for w in report["windows_run"]],
        "hash_identical": report["hash_identical"],
        "equivocations": report["equivocations"],
    }
    return hashlib.sha256(
        json.dumps(core, sort_keys=True).encode()).hexdigest()[:16]


# -- the live executor -------------------------------------------------------

async def _run_async(seed: int, windows: int,
                     stall_timeout_s: float = 1.2,
                     recover_bound_s: float = RECOVER_BOUND_S) -> dict:
    import asyncio

    from tendermint_tpu.consensus.watchdog import ConsensusWatchdog

    churn = _churn_mod()
    plan = plan_quorum_loss(seed, windows)
    net, nodes, pvs, genesis = await churn.build_fleet(
        N_VALIDATORS, seed=seed)
    equivocations = {name: 0 for name in nodes}
    addr_of = {name: pvs[name].get_pub_key().address().hex()
               for name in nodes}
    for name, nd in nodes.items():
        nd.cs.config.gossip_stall_refresh_s = GOSSIP_REFRESH_S

        def _on_equivocation(_vote, _n=name):
            equivocations[_n] += 1

        nd.cs.equivocation_listeners.append(_on_equivocation)
    windows_run = []
    t0_run = time.monotonic()
    try:
        await churn._wait_heights(list(nodes.values()), 2)
        for ev in plan["events"]:
            isolate = ev["isolate"]
            survivors = [nd for n, nd in nodes.items() if n not in isolate]
            observer = survivors[0]
            wd = ConsensusWatchdog(
                observer.cs, stall_timeout_s,
                check_interval_s=stall_timeout_s / 4,
                height_fn=lambda o=observer: o.height)
            await wd.start()
            net.partition(isolate)
            t_cut = time.monotonic()
            # settle: messages already in flight at the cut may finish the
            # current height — the freeze assertion starts after them
            await asyncio.sleep(min(1.0, ev["hold_s"] / 3.0))
            h_frozen = max(nd.height for nd in nodes.values())
            remain = ev["hold_s"] - (time.monotonic() - t_cut)
            if remain > 0:
                await asyncio.sleep(remain)
            # the watchdog must have fired by the window's end (its stall
            # timeout is well inside hold_s); give a bounded grace so a
            # slow CI box never flips the verdict
            deadline = time.monotonic() + 4 * stall_timeout_s
            while wd.stalls == 0 and time.monotonic() < deadline:
                await asyncio.sleep(stall_timeout_s / 4)
            h_end = max(nd.height for nd in nodes.values())
            halted = (h_end == h_frozen)
            assert halted, (
                f"height advanced {h_frozen}->{h_end} with "
                f"{ev['isolated_power']}/{ev['total_power']} power isolated")
            assert wd.stalls > 0, "watchdog never noticed the halt"
            reason, detail = wd.last_halt_reason, wd.last_halt_detail
            assert reason == "quorum_lost", (
                f"halt misclassified as {reason!r}: {detail}")
            assert detail["missing_power"] * 3 > detail["total_power"], detail
            # the isolated validators must be the ones absent from the
            # blocking stage's vote bitmap (matched by address:
            # validator-set order is not name order) — a cut landing
            # between the quorums legitimately leaves their PREVOTES in
            # the round, but never their precommits
            stage = detail["blocking_stage"]
            absent = {row["address"] for row in detail["validators"]
                      if not row[stage]}
            for name in isolate:
                assert addr_of[name] in absent, (
                    f"{name} {stage}d during its own isolation window: "
                    f"{detail}")
            assert sum(equivocations.values()) == 0, equivocations
            t_heal = time.monotonic()
            net.heal(group_a=isolate)
            await churn._wait_heights(list(nodes.values()), h_end + 1,
                                      timeout=recover_bound_s)
            recover_s = round(time.monotonic() - t_heal, 3)
            await wd.stop()
            windows_run.append({
                "window": ev["window"], "isolate": isolate,
                "hold_s": ev["hold_s"], "halted": True,
                "halt_height": h_end, "halt_reason": reason,
                "missing_power": detail["missing_power"],
                "total_power": detail["total_power"],
                "recovered": True, "recover_s": recover_s,
            })
        # post-run settle + whole-history agreement among all nodes
        final = max(nd.height for nd in nodes.values()) + 1
        await churn._wait_heights(list(nodes.values()), final)
        common = min(nd.height for nd in nodes.values()) - 1
        base = max(nd.block_store.base() for nd in nodes.values())
        hash_identical = True
        for h in range(max(1, base), common + 1):
            hashes = {nd.block_store.load_block_meta(h).header.app_hash
                      for nd in nodes.values()}
            assert len(hashes) == 1, f"conflicting commits at height {h}"
        assert sum(equivocations.values()) == 0, equivocations
        for nd in nodes.values():
            evpool = getattr(nd.block_exec, "evpool", None)
            if evpool is not None and hasattr(evpool, "pending_evidence"):
                evs, _ = evpool.pending_evidence(1 << 20)
                assert not evs, f"double-sign evidence on {nd.name}: {evs}"
    finally:
        for nd in nodes.values():
            try:
                await nd.stop()
            except Exception:
                pass
    report = {
        "seed": seed, "windows": windows, "plan": plan,
        "plan_fingerprint": plan_fingerprint(plan),
        "windows_run": windows_run,
        "recover_max_s": max(w["recover_s"] for w in windows_run),
        "final_height": common,
        "hash_identical": hash_identical,
        "equivocations": sum(equivocations.values()),
        "elapsed_s": round(time.monotonic() - t0_run, 2),
    }
    report["outcome_fingerprint"] = outcome_fingerprint(report)
    return report


def run_quorum_loss(seed: int = 1, windows: int = 1,
                    recover_bound_s: float = RECOVER_BOUND_S) -> dict:
    """The net.quorum_loss scenario; returns its report (asserts on
    failure). Host signing backend: the scenario measures consensus
    mechanics, not signature throughput."""
    import asyncio

    os.environ.setdefault("TMTPU_BATCH_BACKEND", "host")
    return asyncio.run(_run_async(seed, windows,
                                  recover_bound_s=recover_bound_s))


# -- WAN throughput (the other gated row) ------------------------------------

async def _wan_async(seed: int, blocks: int) -> dict:
    churn = _churn_mod()
    net, nodes, _pvs, _genesis = await churn.build_fleet(
        N_VALIDATORS, seed=seed)
    try:
        applied = net.apply_profile("wan", seed=seed)
        await churn._wait_heights(list(nodes.values()), 2, timeout=120)
        h0 = max(nd.height for nd in nodes.values())
        t0 = time.monotonic()
        await churn._wait_heights(list(nodes.values()), h0 + blocks,
                                  timeout=600)
        dt = time.monotonic() - t0
        common = min(nd.height for nd in nodes.values()) - 1
        hashes = {nd.block_store.load_block_meta(common).header.app_hash
                  for nd in nodes.values()}
        assert len(hashes) == 1, "hashes diverged under the wan profile"
    finally:
        for nd in nodes.values():
            try:
                await nd.stop()
            except Exception:
                pass
    return {"seed": seed, "blocks": blocks,
            "applied_links": applied,
            "elapsed_s": round(dt, 3),
            "commits_per_min": round(blocks * 60.0 / dt, 2)}


def run_wan(seed: int = 1, blocks: int = 12) -> dict:
    """4 validators under the ``wan`` link profile, commit throughput on
    the clock — feeds ``inproc_wan4_commits_per_min``."""
    import asyncio

    os.environ.setdefault("TMTPU_BATCH_BACKEND", "host")
    return asyncio.run(_wan_async(seed, blocks))


def verify_determinism(seed: int = 1, windows: int = 1) -> dict:
    """Two live same-seed runs must agree on the structural outcome."""
    a = run_quorum_loss(seed, windows)
    b = run_quorum_loss(seed, windows)
    return {"ok": a["outcome_fingerprint"] == b["outcome_fingerprint"],
            "fingerprints": [a["outcome_fingerprint"],
                             b["outcome_fingerprint"]],
            "recover_s": [a["recover_max_s"], b["recover_max_s"]]}


# -- self-test (stdlib-only, instant) ----------------------------------------

def self_test() -> int:
    # the planner is pure and seed-sensitive
    p1 = plan_quorum_loss(7, windows=3)
    assert p1 == plan_quorum_loss(7, windows=3), "same-seed plans diverged"
    assert p1 != plan_quorum_loss(8, windows=3), "seed does not vary plan"
    assert plan_fingerprint(p1) == plan_fingerprint(
        plan_quorum_loss(7, windows=3))
    # every window isolates >1/3 but never everyone, across power shapes
    for powers in (None, [10, 10, 10, 10], [1, 1, 1, 97], [30, 5, 5, 5],
                   [7, 11, 13, 17]):
        for seed in range(1, 9):
            plan = plan_quorum_loss(seed, windows=2, powers=powers)
            total = sum(plan["powers"])
            for ev in plan["events"]:
                assert 0 < len(ev["isolate"]) < plan["n_validators"], ev
                assert ev["isolated_power"] * 3 > total, ev
                assert ev["total_power"] == total
                assert all(n.startswith("val") for n in ev["isolate"])
                assert 2.5 <= ev["hold_s"] <= 4.0
    try:
        plan_quorum_loss(1, powers=[10, 10])
    except ValueError:
        pass
    else:
        raise AssertionError("power/validator length mismatch accepted")
    # the outcome fingerprint strips wall-clock
    base = {"plan": plan_quorum_loss(3),
            "windows_run": [{"window": 0, "isolate": ["val1", "val3"],
                             "halted": True, "halt_reason": "quorum_lost",
                             "recovered": True, "recover_s": 1.5}],
            "hash_identical": True, "equivocations": 0}
    slower = dict(base, windows_run=[
        dict(base["windows_run"][0], recover_s=9.9, halt_height=42)])
    assert outcome_fingerprint(base) == outcome_fingerprint(slower)
    worse = dict(base, windows_run=[
        dict(base["windows_run"][0], halt_reason="stalled")])
    assert outcome_fingerprint(base) != outcome_fingerprint(worse)
    print("quorum_loss self-test OK (planner determinism, >1/3 floor, "
          "never-total isolation, fingerprint wall-clock independence)")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--seed", type=int, default=1)
    ap.add_argument("--windows", type=int, default=1)
    ap.add_argument("--wan", action="store_true",
                    help="run the wan-profile throughput scenario instead")
    ap.add_argument("--blocks", type=int, default=12,
                    help="blocks on the clock for --wan")
    ap.add_argument("--verify-determinism", action="store_true")
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--self-test", action="store_true")
    args = ap.parse_args(argv)
    if args.self_test:
        return self_test()
    if args.verify_determinism:
        vd = verify_determinism(args.seed, args.windows)
        print(json.dumps(vd, indent=1))
        return 0 if vd["ok"] else 1
    if args.wan:
        rep = run_wan(args.seed, args.blocks)
    else:
        rep = run_quorum_loss(args.seed, args.windows)
    if args.json:
        print(json.dumps(rep, indent=1))
    elif args.wan:
        print(f"wan4: {rep['commits_per_min']} commits/min over "
              f"{rep['blocks']} blocks ({rep['elapsed_s']}s, "
              f"{rep['applied_links']} degraded links)")
    else:
        print(f"quorum_loss: {len(rep['windows_run'])} window(s), "
              f"worst recover {rep['recover_max_s']}s, "
              f"outcome {rep['outcome_fingerprint']}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
