"""Run every tools/*.py --self-test in a fresh subprocess; fail loud.

The tools directory is the operator's toolbox (trace_summary, trace_merge,
fleet_scrape, bench_compare, chaos_matrix, device_profile, loadtime,
churn, crashmatrix, aggsig_bench) and each carries
a built-in --self-test. This runner discovers them (any tools/*.py whose source
mentions --self-test) and executes each in a subprocess — argument
parsing, imports, and exit codes included — so a refactor that rots a tool
is caught by pytest (tests/test_tools_selfcheck.py), not by the first
operator who needs it during an incident:

    python tools/selfcheck.py            # run them all
    python tools/selfcheck.py --list     # show what would run
    python tools/selfcheck.py --only trace_merge,bench_compare
    python tools/selfcheck.py --self-test

Stdlib-only; subprocesses inherit a CPU-pinned JAX env so a tool that
imports the package never touches the TPU relay.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
import time
from typing import List

TOOLS_DIR = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(TOOLS_DIR)
PER_TOOL_TIMEOUT_S = 180


def discover(tools_dir: str = TOOLS_DIR) -> List[str]:
    """Tool filenames (sorted) that advertise a --self-test flag."""
    out = []
    for name in sorted(os.listdir(tools_dir)):
        if not name.endswith(".py") or name == os.path.basename(__file__):
            continue
        try:
            with open(os.path.join(tools_dir, name)) as f:
                src = f.read()
        except OSError:
            continue
        if "--self-test" in src:
            out.append(name)
    return out


def _env() -> dict:
    env = dict(os.environ)
    # mirror conftest's CPU pin: a tool that imports the package must not
    # stall on (or bench through) the TPU relay during a test run
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.setdefault("TMTPU_JAX_CACHE", os.path.join(REPO, ".jax_cache"))
    return env


def run_tool(name: str, timeout_s: float = PER_TOOL_TIMEOUT_S) -> dict:
    return run_tool_at(TOOLS_DIR, name, timeout_s)


def run_tool_at(tools_dir: str, name: str,
                timeout_s: float = PER_TOOL_TIMEOUT_S) -> dict:
    """run_tool against an arbitrary directory (self-test seam)."""
    path = os.path.join(tools_dir, name)
    t0 = time.time()
    try:
        res = subprocess.run([sys.executable, path, "--self-test"],
                             capture_output=True, text=True,
                             timeout=timeout_s, env=_env(), cwd=REPO)
        rc, out = res.returncode, (res.stdout + res.stderr)
    except subprocess.TimeoutExpired:
        rc, out = -1, f"timed out after {timeout_s}s"
    return {"tool": name, "rc": rc, "seconds": round(time.time() - t0, 2),
            "output_tail": out[-2000:]}


def self_test() -> int:
    tools = discover()
    # the whole point is catching rot in the known toolbox — if discovery
    # stops seeing these, THIS tool rotted
    for expected in ("trace_summary.py", "trace_merge.py",
                     "fleet_scrape.py", "bench_compare.py",
                     "chaos_matrix.py", "device_profile.py",
                     "loadtime.py", "churn.py", "crashmatrix.py",
                     "aggsig_bench.py", "soak.py",
                     "lightserve_bench.py"):
        assert expected in tools, (expected, tools)
    assert os.path.basename(__file__) not in tools  # no recursion
    # prove the runner distinguishes pass from fail without running the
    # real (slow) toolbox: a known-good and a known-bad synthetic tool
    import tempfile

    d = tempfile.mkdtemp(prefix="selfcheck-")
    try:
        good = os.path.join(d, "good.py")
        with open(good, "w") as f:
            f.write("import sys\nprint('ok')  # --self-test\nsys.exit(0)\n")
        bad = os.path.join(d, "bad.py")
        with open(bad, "w") as f:
            f.write("import sys\nsys.exit(3)  # --self-test\n")
        assert discover(d) == ["bad.py", "good.py"]
        results = [run_tool_at(d, "good.py"), run_tool_at(d, "bad.py")]
        assert results[0]["rc"] == 0 and results[1]["rc"] == 3, results
    finally:
        import shutil

        shutil.rmtree(d, ignore_errors=True)
    print(f"selfcheck self-test OK ({len(tools)} tools discovered)")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--only", default="",
                    help="comma-separated tool names (with or without .py)")
    ap.add_argument("--list", action="store_true")
    ap.add_argument("--timeout", type=float, default=PER_TOOL_TIMEOUT_S)
    ap.add_argument("--self-test", action="store_true")
    args = ap.parse_args(argv)
    if args.self_test:
        return self_test()
    tools = discover()
    if args.only:
        want = {t if t.endswith(".py") else t + ".py"
                for t in args.only.split(",") if t}
        missing = want - set(tools)
        if missing:
            print(f"selfcheck: unknown tools {sorted(missing)} "
                  f"(have {tools})", file=sys.stderr)
            return 2
        tools = [t for t in tools if t in want]
    if args.list:
        print("\n".join(tools))
        return 0
    failed = []
    for name in tools:
        r = run_tool(name, args.timeout)
        status = "PASS" if r["rc"] == 0 else "FAIL"
        print(f"{status} {name} ({r['seconds']}s)")
        if r["rc"] != 0:
            failed.append(name)
            print(r["output_tail"])
    if failed:
        print(f"selfcheck: {len(failed)}/{len(tools)} failed: {failed}")
        return 1
    print(f"selfcheck: {len(tools)}/{len(tools)} tools OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
