"""Probe 2: can relay transfers be parallelized, and does dispatch overlap?

1. serial jax.device_put of 4 x 1MB vs threaded device_put of the same
2. device_put of one 4MB buffer (baseline bandwidth)
3. two async heavy-compute dispatches back-to-back: pipelined or serial?
"""

import os
import sys
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

jax.config.update("jax_compilation_cache_dir",
                  os.path.join(os.path.dirname(os.path.dirname(
                      os.path.abspath(__file__))), ".jax_cache"))

rng = np.random.default_rng(0)
MB = 1 << 20


def bench(label, fn, runs=4):
    ts = []
    for i in range(runs):
        t0 = time.perf_counter()
        fn(i)
        ts.append(time.perf_counter() - t0)
    print(f"{label:46s} min {min(ts)*1e3:7.1f} ms  med {sorted(ts)[len(ts)//2]*1e3:7.1f} ms",
          flush=True)


def main():
    chunks = [rng.integers(0, 255, MB, dtype=np.uint8) for _ in range(4)]
    big = rng.integers(0, 255, 4 * MB, dtype=np.uint8)
    pool = ThreadPoolExecutor(max_workers=4)

    def serial_put(i):
        for c in chunks:
            c[0] = i
            jax.device_put(c).block_until_ready()

    def threaded_put(i):
        for c in chunks:
            c[0] = i
        futs = [pool.submit(lambda a: jax.device_put(a).block_until_ready(), c)
                for c in chunks]
        [f.result() for f in futs]

    def one_put(i):
        big[0] = i
        jax.device_put(big).block_until_ready()

    bench("serial device_put 4x1MB", serial_put)
    bench("threaded device_put 4x1MB", threaded_put)
    bench("single device_put 4MB", one_put)

    # heavy compute kernel ~100ms device: iterate matmul
    @jax.jit
    def heavy(a):
        def step(x, _):
            return jnp.tanh(x @ x), None
        out, _ = jax.lax.scan(step, a, None, length=40)
        return jnp.sum(out)

    a = rng.standard_normal((1024, 1024), dtype=np.float32)
    heavy(a).block_until_ready()

    def one_heavy(i):
        a[0, 0] = i
        np.asarray(heavy(a))

    def two_heavy_async(i):
        a[0, 0] = i
        b = a.copy()
        b[0, 1] = i + 1
        r1 = heavy(a)
        r2 = heavy(b)
        np.asarray(r1), np.asarray(r2)

    bench("one heavy dispatch", one_heavy)
    bench("two heavy dispatches (async overlap?)", two_heavy_async)

    # dispatch on resident data (no transfer): pure fixed+compute
    da = jax.device_put(a)

    def resident_heavy(i):
        np.asarray(heavy(da))

    bench("heavy dispatch, resident input", resident_heavy)


if __name__ == "__main__":
    main()
