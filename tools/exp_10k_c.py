"""Validate the segmented pipelined batch_verify_stream on TPU:
correctness against host verdicts (rejects crossing segment boundaries)
plus perf on the flagship shapes."""

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_compilation_cache_dir",
                  os.path.join(os.path.dirname(os.path.dirname(
                      os.path.abspath(__file__))), ".jax_cache"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 2)

from bench import _mk_val_set, _sign_commit
from tendermint_tpu.crypto.ed25519_jax import verify as V


def main():
    n_vals, n_commits = 10240, 6
    vs, keys = _mk_val_set(n_vals)
    chain = "bench-10k"
    commits = [_sign_commit(vs, keys, h, chain)[0]
               for h in range(1, n_commits + 1)]
    pks, msgs, sigs = [], [], []
    for c in commits:
        pks += [v.pub_key.bytes() for v in vs.validators]
        msgs += [c.vote_sign_bytes(chain, i) for i in range(n_vals)]
        sigs += [cs.signature for cs in c.signatures]
    n = len(pks)
    print("setup done", flush=True)

    # correctness: corrupt a scattering of sigs, incl. at segment boundaries
    bad = sorted({0, 1, 20479, 20480, 40959, 40960, n - 1, 777, 30000})
    sigs_bad = list(sigs)
    for i in bad:
        sigs_bad[i] = sigs_bad[i][:32] + bytes(32)
    out = V.batch_verify_stream(pks, msgs, sigs_bad, chunk=2048)
    want = np.ones(n, bool)
    want[bad] = False
    assert (out == want).all(), np.nonzero(out != want)[0][:20]
    print("correctness (61,440 sigs, segmented, boundary rejects): OK",
          flush=True)

    def timed(fn, runs=3, warm=1):
        for _ in range(warm):
            fn()
        best = 1e9
        for _ in range(runs):
            t0 = time.perf_counter()
            fn()
            best = min(best, time.perf_counter() - t0)
        return best

    t = timed(lambda: V.batch_verify_stream(pks, msgs, sigs, chunk=2048).all())
    print(f"sustained 61,440: {t*1e3:7.1f} ms -> {n/t:8.0f} sigs/s "
          f"({n/t/5888:.2f}x est)", flush=True)

    one = pks[:n_vals], msgs[:n_vals], sigs[:n_vals]
    t = timed(lambda: V.batch_verify_stream(*one, chunk=2048).all())
    print(f"one-shot 10,240:  {t*1e3:7.1f} ms -> {n_vals/t:8.0f} sigs/s "
          f"({n_vals/t/5888:.2f}x est)", flush=True)


if __name__ == "__main__":
    main()
