"""Experiment: where do the 21 us/sig go in the 10k commit-shaped path?

Variants over the same 6-commit x 10,240-validator workload:
  V1 window=3 (2 dispatches of 15 chunks)  -- current bench shape
  V2 window=6 (1 dispatch of 30 chunks)
  V3 window=2 (3 dispatches of 10 chunks)
Each timed with per-window stage split (pack / dispatch+fetch).
"""

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.path.join(os.path.dirname(os.path.dirname(
                          os.path.abspath(__file__))), ".jax_cache"))

import jax

jax.config.update("jax_compilation_cache_dir",
                  os.environ["JAX_COMPILATION_CACHE_DIR"])
jax.config.update("jax_persistent_cache_min_compile_time_secs", 2)

from bench import _mk_val_set, _sign_commit
from tendermint_tpu.crypto.ed25519_jax import verify as V

CHUNK = 2048


def main():
    n_vals, n_commits = 10240, 6
    t0 = time.perf_counter()
    vs, keys = _mk_val_set(n_vals)
    chain = "bench-10k"
    commits = [_sign_commit(vs, keys, h, chain)[0]
               for h in range(1, n_commits + 1)]
    per_commit = []
    for c in commits:
        pks = [v.pub_key.bytes() for v in vs.validators]
        msgs = [c.vote_sign_bytes(chain, i) for i in range(n_vals)]
        sigs = [cs.signature for cs in c.signatures]
        per_commit.append((pks, msgs, sigs))
    print(f"setup {time.perf_counter()-t0:.1f}s", flush=True)

    def flat(cs):
        return ([p for c in cs for p in c[0]],
                [m for c in cs for m in c[1]],
                [s for c in cs for s in c[2]])

    # inspect sparse format stats for the window=3 shape
    pks, msgs, sigs = flat(per_commit[:3])
    sp = V.prepare_sparse_stream(pks, msgs, sigs, CHUNK)
    assert sp is not None
    args, ok = sp
    total_bytes = sum(np.asarray(a).nbytes for a in args)
    print(f"window=3: K={args[2].shape[0]} C_pad={args[1].shape[0]} "
          f"wire={total_bytes/2**20:.2f} MB "
          f"({total_bytes/len(pks):.1f} B/sig incl cached pk "
          f"{np.asarray(args[5]).nbytes/2**20:.2f} MB)", flush=True)

    for label, window in (("V1 window=3", 3), ("V2 window=6", 6),
                          ("V3 window=2", 2)):
        def run_pass():
            t_pack = t_disp = 0.0
            for i in range(0, n_commits, window):
                pks, msgs, sigs = flat(per_commit[i:i + window])
                t0 = time.perf_counter()
                sp = V.prepare_sparse_stream(pks, msgs, sigs, CHUNK)
                args, ok = sp
                t1 = time.perf_counter()
                out = np.asarray(V._verify_sparse_stream_kernel(*args))
                assert out.reshape(-1)[:len(pks)].all() and ok.all()
                t2 = time.perf_counter()
                t_pack += t1 - t0
                t_disp += t2 - t1
            return t_pack, t_disp

        t0 = time.perf_counter()
        run_pass()  # compile + pk cache warm
        print(f"{label}: warm pass {time.perf_counter()-t0:.1f}s", flush=True)
        best = (1e9, 0, 0)
        for _ in range(3):
            t0 = time.perf_counter()
            tp, td = run_pass()
            tt = time.perf_counter() - t0
            if tt < best[0]:
                best = (tt, tp, td)
        tt, tp, td = best
        n = n_commits * n_vals
        print(f"{label}: total {tt*1e3:7.1f} ms  pack {tp*1e3:6.1f}  "
              f"dispatch+fetch {td*1e3:7.1f}  -> {n/tt:8.0f} sigs/s "
              f"({n/tt/5888:.2f}x est)", flush=True)


if __name__ == "__main__":
    main()
