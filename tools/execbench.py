"""execbench: serial-vs-parallel block execution A/B on an in-proc
4-validator fleet under open-loop firehose load.

The rig runs the SAME pre-planned workload twice — once with
``execution.version = "v0"`` (the serial DeliverTx spec) and once with
``"v1"`` (state/parallel.py optimistic parallel execution) — and reports
committed txs/sec for each. The payload is built so execution dominates
block time: large values (sha256 of a >2 KiB value releases the GIL, so
speculative workers hash in real parallel) across disjoint keys (every tx
its own conflict group — maximum speculation, zero re-execution). On a
multi-core host the serial run visibly saturates first; on a 1-core host
the two rates converge (ParallelExecutor caps its workers at the core
count) and the report says so via ``n_cpus``.

Load discipline is tools/loadtime.py's: send times pre-planned on a fixed
rate grid (coordinated omission can't hide stalls), fired into the
validators' mempools round-robin; the run measures first-send →
everything-committed wall time at node 0.

    python tools/execbench.py --self-test
    python tools/execbench.py --seed 1 --json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

N_VALIDATORS = 4
DEFAULT_TXS = 360
DEFAULT_VALUE_SIZE = 4096
DEFAULT_RATE = 4000.0

_RIG = None


def _rig():
    """Import-heavy fleet pieces, built lazily and memoized."""
    global _RIG
    if _RIG is not None:
        return _RIG

    from tendermint_tpu import crypto
    from tendermint_tpu.abci.example.kvstore import MerkleKVStoreApplication
    from tendermint_tpu.config import ExecutionConfig
    from tendermint_tpu.consensus import ConsensusState
    from tendermint_tpu.consensus.config import test_consensus_config
    from tendermint_tpu.consensus.reactor import ConsensusReactor
    from tendermint_tpu.consensus.replay import Handshaker
    from tendermint_tpu.libs.db import MemDB
    from tendermint_tpu.mempool import CListMempool
    from tendermint_tpu.mempool.reactor import MempoolReactor
    from tendermint_tpu.p2p import Switch
    from tendermint_tpu.proxy import AppConns, local_client_creator
    from tendermint_tpu.state import StateStore, state_from_genesis
    from tendermint_tpu.state.execution import (BlockExecutor,
                                                EmptyEvidencePool)
    from tendermint_tpu.store import BlockStore
    from tendermint_tpu.types import GenesisDoc, GenesisValidator, MockPV

    class ExecNode:
        """One in-proc validator: merkle kvstore app + consensus + mempool
        reactors, BlockExecutor wired to the A/B's execution config."""

        def __init__(self, idx, pv, genesis, exec_config):
            self.idx = idx
            self.pv = pv
            self.app = MerkleKVStoreApplication()
            self.conns = AppConns(local_client_creator(self.app))
            self.conns.start()
            self.state_store = StateStore(MemDB())
            self.block_store = BlockStore(MemDB())
            state = state_from_genesis(genesis)
            state = Handshaker(
                self.state_store, state, self.block_store, genesis,
                exec_config=exec_config).handshake(self.conns.consensus,
                                                   self.conns.query)
            self.state_store.save(state)
            self.mempool = CListMempool(self.conns.mempool,
                                        max_txs_bytes=1 << 30)
            self.block_exec = BlockExecutor(
                self.state_store, self.conns.consensus, self.mempool,
                EmptyEvidencePool(), self.block_store,
                exec_config=exec_config)
            self.cs = ConsensusState(test_consensus_config(), state,
                                     self.block_exec, self.block_store)
            self.cs.set_priv_validator(pv)
            self.mempool.tx_available_callbacks.append(
                self.cs.notify_txs_available)
            self.switch = Switch(f"exec{idx}")
            self.cs_reactor = ConsensusReactor(self.cs)
            self.switch.add_reactor("CONSENSUS", self.cs_reactor)
            self.mp_reactor = MempoolReactor(self.mempool,
                                             gossip_sleep=0.005)
            self.switch.add_reactor("MEMPOOL", self.mp_reactor)

        async def start(self):
            await self.switch.start()
            await self.cs.start()

        async def stop(self):
            await self.cs.stop()
            await self.switch.stop()

    def make_fleet(exec_config, seed):
        pvs = [MockPV(crypto.Ed25519PrivKey.generate(bytes([0x30 + i]) * 32))
               for i in range(N_VALIDATORS)]
        genesis = GenesisDoc(
            chain_id=f"execbench-{seed}",
            genesis_time_ns=1_700_000_000_000_000_000,
            validators=[GenesisValidator(pv.get_pub_key(), 10)
                        for pv in pvs])
        return [ExecNode(i, pv, genesis, exec_config)
                for i, pv in enumerate(pvs)]

    _RIG = {"ExecNode": ExecNode, "make_fleet": make_fleet,
            "ExecutionConfig": ExecutionConfig}
    return _RIG


def make_workload(seed: int, n_txs: int, value_size: int):
    """Disjoint-key large-value txs: every tx its own conflict group, and
    sha256 of the value is big enough to release the GIL during
    speculation. Deterministic in (seed, n_txs, value_size)."""
    import random

    rng = random.Random(seed)
    unit = value_size // 8 or 1
    return [b"e%d.%06d=" % (seed, i)
            + (b"%08x" % rng.getrandbits(32)) * unit
            for i in range(n_txs)]


async def _run_fleet(version: str, seed: int, n_txs: int, value_size: int,
                     rate: float, timeout_s: float) -> dict:
    import asyncio

    rig = _rig()
    exec_config = rig["ExecutionConfig"](version=version)
    nodes = rig["make_fleet"](exec_config, seed)

    from tendermint_tpu.p2p import InProcNetwork

    net = InProcNetwork()
    for nd in nodes:
        net.add_switch(nd.switch)
    for nd in nodes:
        await nd.start()
    await net.connect_all()

    txs = make_workload(seed, n_txs, value_size)
    try:
        # let the net reach steady state before the firehose opens
        deadline = time.monotonic() + timeout_s
        while min(nd.cs.state.last_block_height for nd in nodes) < 1:
            if time.monotonic() > deadline:
                raise TimeoutError("fleet never reached height 1")
            await asyncio.sleep(0.05)

        loop = asyncio.get_running_loop()
        wall_t0 = time.perf_counter()
        t0 = loop.time() + 0.05
        pending = list(txs)
        i = 0
        while pending:
            target = t0 + i / rate
            now = loop.time()
            if target > now:
                await asyncio.sleep(target - now)
            tx = pending[0]
            try:
                nodes[i % N_VALIDATORS].mempool.check_tx(tx)
                pending.pop(0)
            except Exception:
                await asyncio.sleep(0.01)  # mempool full: retry the same tx
            i += 1

        # drain: every workload tx committed at node 0
        app0 = nodes[0].app
        while app0.tx_count < n_txs:
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"only {app0.tx_count}/{n_txs} txs committed")
            await asyncio.sleep(0.02)
        wall_t1 = time.perf_counter()
    finally:
        for nd in nodes:
            await nd.stop()

    # exec-plane phase decomposition over the measured window (the
    # per-block plane="exec" segments state/execution.py records)
    from tendermint_tpu.blockchain.reactor import BlockchainReactor

    breakdown = BlockchainReactor.exec_phase_breakdown(wall_t0, wall_t1)
    elapsed = wall_t1 - wall_t0
    heights = [nd.cs.state.last_block_height for nd in nodes]
    hashes = {nd.state_store.load().app_hash for nd in nodes}
    assert len(hashes) == 1, "fleet diverged on app hash"
    stats = {"groups": 0, "conflicted": 0}
    for nd in nodes:
        p = nd.block_exec._parallel
        if p is not None:
            stats["groups"] = max(stats["groups"], p.last_groups)
            stats["conflicted"] += p.last_conflicted
    return {
        "version": version,
        "txs_per_sec": n_txs / elapsed,
        "elapsed_s": elapsed,
        "committed": int(nodes[0].app.tx_count),
        "heights": heights,
        "app_hash": hashes.pop().hex(),
        "exec_phase": {k: round(v, 4) for k, v in breakdown.items()},
        "parallel": stats,
    }


def run_exec_ab(seed: int = 1, n_txs: int = DEFAULT_TXS,
                value_size: int = DEFAULT_VALUE_SIZE,
                rate: float = DEFAULT_RATE,
                timeout_s: float = 180.0) -> dict:
    """The A/B: same seed/workload, serial then parallel. Returns both
    runs plus the speedup; both fleets must land on the same app hash
    (the byte-parity invariant observed end-to-end)."""
    import asyncio

    from tendermint_tpu.crypto import phases

    runs = {}
    for version in ("v0", "v1"):
        phases.reset()  # each run's exec segments decompose its own window
        runs[version] = asyncio.run(_run_fleet(
            version, seed, n_txs, value_size, rate, timeout_s))
    assert runs["v0"]["app_hash"] == runs["v1"]["app_hash"], \
        "serial and parallel fleets diverged"
    return {
        "seed": seed, "n_txs": n_txs, "value_size": value_size,
        "rate": rate, "n_cpus": os.cpu_count() or 1,
        "serial": runs["v0"], "parallel": runs["v1"],
        "speedup": runs["v1"]["txs_per_sec"] / runs["v0"]["txs_per_sec"],
    }


def self_test() -> int:
    rep = run_exec_ab(seed=1, n_txs=40, value_size=512, rate=2000.0,
                      timeout_s=120.0)
    assert rep["serial"]["committed"] == 40
    assert rep["parallel"]["committed"] == 40
    assert rep["serial"]["txs_per_sec"] > 0
    assert rep["parallel"]["txs_per_sec"] > 0
    assert rep["serial"]["app_hash"] == rep["parallel"]["app_hash"]
    assert rep["parallel"]["parallel"]["groups"] > 0  # v1 really speculated
    assert "accounted_share" in rep["parallel"]["exec_phase"]
    print("execbench self-test: OK "
          f"(speedup={rep['speedup']:.2f} on {rep['n_cpus']} cpu)")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--self-test", action="store_true")
    ap.add_argument("--seed", type=int, default=1)
    ap.add_argument("--txs", type=int, default=DEFAULT_TXS)
    ap.add_argument("--value-size", type=int, default=DEFAULT_VALUE_SIZE)
    ap.add_argument("--rate", type=float, default=DEFAULT_RATE)
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)
    if args.self_test:
        return self_test()
    rep = run_exec_ab(seed=args.seed, n_txs=args.txs,
                      value_size=args.value_size, rate=args.rate)
    if args.json:
        print(json.dumps(rep, indent=2))
    else:
        print(f"serial   : {rep['serial']['txs_per_sec']:,.0f} txs/s")
        print(f"parallel : {rep['parallel']['txs_per_sec']:,.0f} txs/s")
        print(f"speedup  : {rep['speedup']:.2f}x on {rep['n_cpus']} cpu")
    return 0


if __name__ == "__main__":
    sys.exit(main())
