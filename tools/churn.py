"""Churn orchestrator: live join/leave + validator rotation under load.

Every net-level claim in this repo used to rest on static full meshes; this
driver makes membership change the steady state. It runs an N-node in-proc
net (4 validators + N-4 full nodes over ``InProcNetwork``, full-mesh or
sparse ring+chords topology) under open-loop tx load (the loadtime
fixed-rate grid) and executes a SEEDED, DETERMINISTIC churn plan:

* each interval, ONE node leaves cleanly (``InProcNetwork.remove_node`` —
  departed switches drained, survivors' link policies untouched, the
  redial loop never re-adds it) and ONE fresh node joins — via a real
  snapshot restore over the statesync wire channels (the *normal* entry
  path: block stores are pruned, so replay-from-genesis is impossible by
  construction), then fast-syncs to the tip and follows live consensus;
* each interval, the validator set ROTATES via kvstore ``val:`` update
  txs — one full node's key in, the longest-serving rotatable validator
  out — so the prune-checkpointed validator storage (state/store.py prune
  floor + change pointers) is stressed by continuous set changes across
  prune boundaries (the app sets ``retain_height``, so the REAL consensus
  prune path runs at every commit on every node).

Assertions after the run: liveness (the net kept committing through every
event), app-hash agreement among survivors, every joiner reached
caught-up (join-to-caught-up seconds reported), ``load_validators``
resolves at every retained height, and AddrBook/peerscore state stays
bounded by the number of nodes that ever existed.

Determinism: the plan is a PURE function of (seed, n_nodes, intervals) —
``plan_churn`` — and the run executes it in plan order, so two same-seed
runs produce the identical join/leave event sequence and the identical
validator-set composition sequence (``--verify-determinism`` runs twice
and diffs both).

    python tools/churn.py --nodes 8 --intervals 2 --seed 1
    python tools/churn.py --nodes 8 --seed 1 --verify-determinism
    python tools/churn.py --nodes 16 --topology sparse --degree 3
    python tools/churn.py --self-test        # stdlib-only, seconds

Stdlib-only at the top level; repo imports happen inside the run (the
pattern chaos_matrix.py uses) so --help/--self-test work anywhere.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

TOOLS_DIR = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(TOOLS_DIR)
for p in (REPO, TOOLS_DIR):
    if p not in sys.path:
        sys.path.insert(0, p)

#: how many blocks between churn events — long enough for a statesync
#: join (snapshot every SNAPSHOT_INTERVAL heights) to land inside it
BLOCKS_PER_INTERVAL = 5
SNAPSHOT_INTERVAL = 3
#: app-driven retain window (ResponseCommit.retain_height = h - RETAIN):
#: must cover at least one snapshot so joiners can restore + fast-sync
RETAIN_BLOCKS = 12
N_VALIDATORS = 4


# -- the deterministic plan (pure) -------------------------------------------

def node_names(n_nodes: int, n_validators: int = N_VALIDATORS):
    """Initial roster: val0..val{V-1} are genesis validators, full{i} the
    genesis full nodes."""
    n_validators = min(n_validators, n_nodes)
    vals = [f"val{i}" for i in range(n_validators)]
    fulls = [f"full{i}" for i in range(n_nodes - n_validators)]
    return vals, fulls


def plan_churn(seed: int, intervals: int, n_nodes: int,
               n_validators: int = N_VALIDATORS):
    """The churn schedule as a pure function of its inputs: a list of
    per-interval event dicts, plus the validator-set composition after
    each rotation. Two same-seed calls are byte-identical — the property
    --verify-determinism checks end-to-end against two real runs.

    Membership simulation: each interval leaves one running full node
    (never a current validator, never the anchor val0's peers), joins one
    fresh statesync node, and rotates (in: the longest-running full node
    outside the set; out: the longest-serving validator except val0, the
    anchor/donor)."""
    import random
    import zlib

    rng = random.Random(zlib.crc32(f"churn|{seed}|{n_nodes}".encode()))
    vals, fulls = node_names(n_nodes, n_validators)
    vset = list(vals)              # current validator composition
    running_fulls = list(fulls)    # non-validator nodes currently up
    # seniority: genesis validators in roster order, rotated-in members by
    # the interval they entered the set — "longest-serving" is its min
    seniority = {v: (-1, i) for i, v in enumerate(vals)}
    events, compositions = [], [list(vset)]
    for i in range(intervals):
        ev = {"interval": i}
        # leave: a running full node outside the current set (quorum-safe)
        leavable = sorted(set(running_fulls) - set(vset))
        if leavable:
            ev["leave"] = rng.choice(leavable)
            running_fulls.remove(ev["leave"])
        # join: a fresh node, statesync entry
        joiner = f"join{i}"
        ev["join"] = joiner
        # rotate: in = longest-running full not in the set (joined BEFORE
        # this interval), out = longest-serving rotatable validator
        rotatable_in = [f for f in running_fulls if f not in vset]
        if rotatable_in:
            rot_in = rotatable_in[0]
            rot_out = min((v for v in vset if v != "val0"),
                          key=lambda v: seniority[v])
            ev["rotate_in"], ev["rotate_out"] = rot_in, rot_out
            seniority[rot_in] = (i, 0)
            vset[vset.index(rot_out)] = rot_in
            compositions.append(list(vset))
        running_fulls.append(joiner)  # caught-up by the interval's end
        events.append(ev)
    return {"events": events, "compositions": compositions}


# -- the in-proc rig ---------------------------------------------------------

_RIG = None


def _rig():
    """Import-heavy rig pieces, built lazily (keeps --help/--self-test
    stdlib-fast) and memoized (one ChurnNode class per process)."""
    global _RIG
    if _RIG is not None:
        return _RIG
    import asyncio  # noqa: F401  (re-exported pattern guard)

    from tendermint_tpu import crypto
    from tendermint_tpu.abci import types as abci
    from tendermint_tpu.abci.example.kvstore import SnapshotKVStoreApplication
    from tendermint_tpu.blockchain.reactor import BlockchainReactor
    from tendermint_tpu.consensus import ConsensusState
    from tendermint_tpu.consensus.config import test_consensus_config
    from tendermint_tpu.consensus.reactor import ConsensusReactor
    from tendermint_tpu.consensus.replay import Handshaker
    from tendermint_tpu.libs.db import MemDB
    from tendermint_tpu.libs.metrics import NodeMetrics
    from tendermint_tpu.mempool import CListMempool
    from tendermint_tpu.mempool.reactor import MempoolReactor
    from tendermint_tpu.p2p import Switch
    from tendermint_tpu.p2p.pex import AddrBook, NetAddress
    from tendermint_tpu.proxy import AppConns, local_client_creator
    from tendermint_tpu.state import (BlockExecutor, StateStore,
                                      state_from_genesis)
    from tendermint_tpu.state.execution import EmptyEvidencePool
    from tendermint_tpu.statesync.reactor import StateSyncReactor
    from tendermint_tpu.statesync.stateprovider import StateProvider
    from tendermint_tpu.store import BlockStore
    from tendermint_tpu.types import GenesisDoc, GenesisValidator, MockPV

    class ChurnApp(SnapshotKVStoreApplication):
        """Snapshot-taking kvstore whose commit also declares a retain
        height — so the REAL consensus prune path (block store + state
        store) runs on every node at every commit, and validator-change
        pointers keep crossing the moving prune floor."""

        def __init__(self, interval: int, retain: int):
            super().__init__(interval=interval)
            self.retain = retain

        def commit(self):
            resp = super().commit()
            if self.retain:
                resp.retain_height = max(0, self.height - self.retain)
            return resp

    class ChurnNode:
        """One in-proc node: snapshot app, consensus + blocksync +
        statesync + mempool reactors, per-node metric registry (gossip
        wakeups), an AddrBook sharing the blocksync scoreboard."""

        def __init__(self, name, genesis, pv, fast_sync=False):
            self.name = name
            self.pv = pv
            self.app = ChurnApp(SNAPSHOT_INTERVAL, RETAIN_BLOCKS)
            self.conns = AppConns(local_client_creator(self.app))
            self.conns.start()
            self.state_store = StateStore(MemDB())
            self.block_store = BlockStore(MemDB())
            state = state_from_genesis(genesis)
            state = Handshaker(self.state_store, state, self.block_store,
                               genesis).handshake(self.conns.consensus,
                                                  self.conns.query)
            self.state_store.save(state)
            self.mempool = CListMempool(self.conns.mempool)
            self.block_exec = BlockExecutor(self.state_store,
                                            self.conns.consensus,
                                            self.mempool, EmptyEvidencePool(),
                                            self.block_store)
            self.cs = ConsensusState(test_consensus_config(), state,
                                     self.block_exec, self.block_store)
            self.cs.set_priv_validator(pv)
            self.mempool.tx_available_callbacks.append(
                self.cs.notify_txs_available)
            self.switch = Switch(name)
            self.metrics = NodeMetrics(f"churn_{name}_{time.monotonic_ns()}")
            # wakeup/poll counters read through cs.metrics (the reactor's
            # _gossip_idle), encode-cache counters through set_metrics
            self.cs.metrics = self.metrics.consensus
            self.cs_reactor = ConsensusReactor(self.cs, wait_sync=fast_sync)
            self.cs_reactor.set_metrics(self.metrics.consensus)
            self.switch.add_reactor("CONSENSUS", self.cs_reactor)
            self.bc_reactor = BlockchainReactor(
                state, self.block_exec, self.block_store, fast_sync=False,
                consensus_reactor=self.cs_reactor)
            self.switch.add_reactor("BLOCKCHAIN", self.bc_reactor)
            self.mp_reactor = MempoolReactor(self.mempool, gossip_sleep=0.01)
            self.switch.add_reactor("MEMPOOL", self.mp_reactor)
            self.ss_reactor = StateSyncReactor(self.app, self.app)
            self.switch.add_reactor("STATESYNC", self.ss_reactor)
            self.addr_book = AddrBook(strict=False,
                                      scoreboard=self.bc_reactor.scoreboard)
            self.fast_sync = fast_sync
            self._started = False

        @property
        def height(self):
            return self.cs.state.last_block_height

        async def start(self):
            self._started = True
            await self.switch.start()
            if not self.fast_sync:
                await self.cs.start()

        async def stop(self):
            if not self._started:
                return
            self._started = False
            await self.cs.stop()
            await self.switch.stop()
            self.conns.stop()

        def wakeups(self):
            m = self.metrics.consensus.gossip_wakeups_total
            return sum(m.value(r) for r in ("data", "votes"))

        def encode_cache(self):
            """(hits, misses) summed across kinds — the wire-encode cache
            is what keeps per-link gossip cost flat as peers multiply."""
            c = self.metrics.consensus
            return (sum(c.encode_cache_hits_total._values.values()),
                    sum(c.encode_cache_misses_total._values.values()))

    class DirectStateProvider(StateProvider):
        """Orchestrator-trusted provider for in-proc joins: reads headers,
        commits and validator sets straight from a live survivor's stores
        (the wire-level chunk fetch + per-chunk verification still runs;
        PR 7's adversarial suite covers UNTRUSTED providers — churn
        measures membership mechanics)."""

        def __init__(self, donor, timeout=90.0):
            self.donor = donor
            self.timeout = timeout

        async def _meta(self, height):
            import asyncio

            deadline = time.monotonic() + self.timeout
            while time.monotonic() < deadline:
                meta = self.donor.block_store.load_block_meta(height)
                if meta is not None:
                    return meta
                await asyncio.sleep(0.05)
            raise TimeoutError(f"donor never reached height {height}")

        async def app_hash(self, height):
            return (await self._meta(height + 1)).header.app_hash

        async def commit(self, height):
            import asyncio

            deadline = time.monotonic() + self.timeout
            while time.monotonic() < deadline:
                blk = self.donor.block_store.load_block(height + 1)
                if blk is not None:
                    return blk.last_commit
                await asyncio.sleep(0.05)
            raise TimeoutError(f"donor never served block {height + 1}")

        async def state(self, height):
            from tendermint_tpu.state.state import State
            from tendermint_tpu.types.params import ConsensusParams

            last = (await self._meta(height)).header
            cur = (await self._meta(height + 1)).header
            await self._meta(height + 2)  # h+2's vals = next of h+1
            ss = self.donor.state_store
            return State(
                chain_id=cur.chain_id,
                initial_height=1,
                last_block_height=height,
                last_block_id=cur.last_block_id,
                last_block_time_ns=last.time_ns,
                last_validators=ss.load_validators(height),
                validators=ss.load_validators(height + 1),
                next_validators=ss.load_validators(height + 2),
                last_height_validators_changed=height + 1,
                consensus_params=self.donor.cs.state.consensus_params
                or ConsensusParams(),
                last_height_consensus_params_changed=1,
                app_hash=cur.app_hash,
                last_results_hash=cur.last_results_hash,
            )

    def make_genesis(pvs, powers):
        return GenesisDoc(
            chain_id="churn-chain",
            genesis_time_ns=1_700_000_000_000_000_000,
            validators=[GenesisValidator(pv.get_pub_key(), p)
                        for pv, p in zip(pvs, powers)])

    def make_pv(tag: str):
        seed = (tag.encode() * 32)[:32]
        return MockPV(crypto.Ed25519PrivKey.generate(seed))

    _RIG = {
        "ChurnNode": ChurnNode,
        "DirectStateProvider": DirectStateProvider,
        "make_genesis": make_genesis,
        "make_pv": make_pv,
        "NetAddress": NetAddress,
        "abci": abci,
    }
    return _RIG


# -- the run ------------------------------------------------------------------

async def join_statesync(net, jn, donor, neighbors, seed: int,
                         timeout: float = 120.0) -> float:
    """The statesync entry path, end to end: wait for a donor snapshot,
    wire the started node into the live net, restore over the wire
    channels, bootstrap stores, fast-sync to the tip, switch to live
    consensus. Returns join-to-caught-up seconds (clock starts when the
    node enters the net). Shared by run_churn and the chaos flap cell."""
    import asyncio

    rig = _rig()
    deadline = time.monotonic() + 60
    while not donor.app._snapshots and time.monotonic() < deadline:
        await asyncio.sleep(0.1)
    assert donor.app._snapshots, "donor never produced a snapshot"
    t0 = time.monotonic()
    catch_target = donor.height
    await jn.start()
    await net.add_node(jn.switch, connect_to=neighbors)
    provider = rig["DirectStateProvider"](donor)
    state, commit = await asyncio.wait_for(
        jn.ss_reactor.sync(provider, discovery_time=0.3, chunk_timeout=5.0,
                           seed=seed, discovery_rounds=20),
        timeout=timeout)
    jn.state_store.bootstrap(state)
    jn.block_store.save_seen_commit(state.last_block_height, commit)
    await jn.bc_reactor.switch_to_fast_sync(state)
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if jn.bc_reactor.synced.is_set() and jn.height >= catch_target:
            break
        await asyncio.sleep(0.1)
    else:
        raise TimeoutError(f"{jn.name} never caught up")
    jn.fast_sync = False  # now a live follower
    return round(time.monotonic() - t0, 3)


async def _wait_heights(nodes, target, timeout=150.0):
    import asyncio

    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if all(nd.height >= target for nd in nodes):
            return
        await asyncio.sleep(0.1)
    raise TimeoutError(
        f"height {target} not reached: "
        f"{ {nd.name: nd.height for nd in nodes} }")


async def rewire_loop(net, interval: float = 0.3) -> None:
    """Persistent-peer redial loop: re-heal real link failures forever
    (reconnect_missing never touches departed nodes). Run as a task,
    cancel at teardown — shared by the churn/flap drivers and the chaos
    corruption cells."""
    import asyncio

    while True:
        await asyncio.sleep(interval)
        await net.reconnect_missing()


async def _run_async(n_nodes: int, intervals: int, seed: int,
                     topology: str, degree: int, rate: float) -> dict:
    import asyncio

    from tendermint_tpu.p2p import InProcNetwork

    rig = _rig()
    ChurnNode = rig["ChurnNode"]
    plan = plan_churn(seed, intervals, n_nodes)
    vals, fulls = node_names(n_nodes)
    pvs = {name: rig["make_pv"](name) for name in vals + fulls}
    genesis = rig["make_genesis"]([pvs[v] for v in vals], [10] * len(vals))

    nodes = {name: ChurnNode(name, genesis, pvs[name]) for name in vals + fulls}
    all_ever = dict(nodes)          # every node that ever existed
    net = InProcNetwork()
    for nd in nodes.values():
        net.add_switch(nd.switch)
    for nd in nodes.values():
        await nd.start()
    await net.connect_topology(topology, degree=degree, seed=seed)

    # survivors' address books learn everyone at wiring time (the in-proc
    # analog of PEX discovery) — the bounded-state assertion's subject
    def book_learns(name):
        port = 20000 + len(all_ever)
        for nd in nodes.values():
            if nd.name != name:
                nd.addr_book.add_address(
                    rig["NetAddress"](name, "127.0.0.1", port), src_id="churn")
    for name in list(nodes):
        book_learns(name)

    executed = []       # the run's own (action, node) event log
    join_stats = {}     # joiner -> seconds to caught-up
    rotations_done = []

    rewire_task = asyncio.create_task(rewire_loop(net))

    # open-loop tx load for the whole run (the loadtime harness
    # discipline: the i-th send fires at t0 + i/rate no matter how slow
    # the net answers — computed lazily, the run uses a few hundred slots)
    async def load():
        import itertools

        loop = asyncio.get_running_loop()
        t0 = loop.time() + 0.1
        for i in itertools.count():
            target = t0 + i / rate
            now = loop.time()
            if target > now:
                await asyncio.sleep(target - now)
            survivors = [nd for nd in nodes.values()
                         if nd.name not in net.departed and not nd.fast_sync]
            if not survivors:
                continue
            nd = survivors[i % len(survivors)]
            try:
                nd.mempool.check_tx(b"churn-%d-%d=x" % (seed, i))
            except Exception:
                pass  # full mempool under churn is load, not failure

    load_task = asyncio.create_task(load())

    t_run0 = time.monotonic()
    try:
        await _wait_heights(list(nodes.values()), 2)
        h0 = max(nd.height for nd in nodes.values())
        wak0 = {name: nd.wakeups() for name, nd in nodes.items()}

        for ev in plan["events"]:
            i = ev["interval"]
            target_h = h0 + (i + 1) * BLOCKS_PER_INTERVAL

            # -- leave: clean departure, survivors must not redial it
            leaver = ev.get("leave")
            if leaver and leaver in nodes:
                nd = nodes.pop(leaver)
                await net.remove_node(leaver)
                await nd.stop()
                for s in nodes.values():   # book sees the departure
                    s.addr_book.mark_attempt(
                        rig["NetAddress"](leaver, "127.0.0.1", 1))
                executed.append(("leave", leaver))

            # -- join: statesync restore over the wire, then fast sync
            joiner = ev["join"]
            jpv = rig["make_pv"](joiner)
            pvs[joiner] = jpv
            jn = ChurnNode(joiner, genesis, jpv, fast_sync=True)
            nodes[joiner] = jn
            all_ever[joiner] = jn
            donor = nodes["val0"]
            # sparse entry: connect to a few neighbors only; mesh: everyone
            neighbors = sorted(n for n in nodes if n != joiner)
            if topology == "sparse":
                neighbors = neighbors[:max(2, degree)]
            join_stats[joiner] = await join_statesync(
                net, jn, donor, neighbors, seed)
            book_learns(joiner)
            executed.append(("join", joiner))

            # -- rotate: val: txs flip the set across a prune boundary
            if "rotate_in" in ev:
                rin, rout = ev["rotate_in"], ev["rotate_out"]
                in_hex = pvs[rin].get_pub_key().bytes().hex()
                out_hex = pvs[rout].get_pub_key().bytes().hex()
                donor.mempool.check_tx(f"val:{in_hex}!10".encode())
                donor.mempool.check_tx(f"val:{out_hex}!0".encode())
                executed.append(("rotate", f"{rin}>{rout}"))
                rotations_done.append((rin, rout))

            await _wait_heights(
                [nd for nd in nodes.values() if not nd.fast_sync], target_h)

        # settle: everyone (joiners included) reaches a common height
        final_target = max(nd.height for nd in nodes.values()) + 2
        await _wait_heights(list(nodes.values()), final_target)
    except BaseException:
        # failed runs must still tear the net down (leaked consensus tasks
        # wedge asyncio.run's cleanup) — stop everything, then re-raise
        rewire_task.cancel()
        load_task.cancel()
        for nd in nodes.values():
            try:
                await nd.stop()
            except Exception:
                pass
        raise
    finally:
        rewire_task.cancel()
        load_task.cancel()

    elapsed = time.monotonic() - t_run0
    survivors = list(nodes.values())
    try:
        h_final = min(nd.height for nd in survivors)

        # -- invariants ------------------------------------------------------
        # survivor app-hash agreement at a common height
        common = h_final - 1
        hashes = {nd.name:
                  nd.block_store.load_block_meta(common).header.app_hash
                  for nd in survivors}
        assert len(set(hashes.values())) == 1, \
            f"survivor app hashes diverged at {common}: {hashes}"
        # the rotation actually took: the final set differs from genesis
        # when the plan rotated, and matches the plan's final composition
        if rotations_done:
            set_keys = {v.pub_key.bytes()
                        for v in survivors[0].cs.state.validators.validators}
            final_names = {name for name, pv in pvs.items()
                           if pv.get_pub_key().bytes() in set_keys}
            assert final_names == set(plan["compositions"][-1]), \
                (sorted(final_names), plan["compositions"][-1])
        # every retained height's validator set resolves (the
        # prune-checkpoint path under continuous churn)
        anchor = nodes["val0"]
        floor = max(1, anchor.app.height - RETAIN_BLOCKS)
        unresolved = [h for h in range(floor, anchor.height + 1)
                      if anchor.state_store.load_validators(h) is None]
        assert not unresolved, f"unresolvable retained heights: {unresolved}"
        # bounded AddrBook / peerscore state: no growth beyond the roster
        for nd in survivors:
            assert nd.addr_book.size() <= len(all_ever), \
                (nd.name, nd.addr_book.size(), len(all_ever))
            assert len(nd.bc_reactor.scoreboard.snapshot()) <= len(all_ever)

        # -- wakeup accounting (sublinearity evidence) ----------------------
        wak_delta = sum(nd.wakeups() - wak0.get(nd.name, 0.0)
                        for nd in survivors)
        links = max(1, len(net.links))
        blocks = max(1, h_final - h0)
    finally:
        # a FAILED invariant must still tear the net down (leaked
        # consensus tasks wedge asyncio.run's cleanup and the caller
        # never sees the diagnostic)
        for nd in survivors:
            try:
                await nd.stop()
            except Exception:
                pass

    return {
        "n_nodes": n_nodes, "seed": seed, "intervals": intervals,
        "topology": topology, "degree": degree,
        "plan": plan, "executed": executed,
        "compositions": plan["compositions"],
        "height_initial": h0, "height_final": h_final,
        "blocks_per_min": round(blocks / elapsed * 60.0, 2),
        "join_caughtup_s": join_stats,
        "wakeups_per_link_per_block": round(wak_delta / links / blocks, 3),
        "directed_links": links,
        "rotations": len(rotations_done),
        "prune_floor": floor,
        "survivor_app_hash": next(iter(hashes.values())).hex(),
        "elapsed_s": round(elapsed, 2),
    }


async def build_fleet(n_nodes: int, topology: str = "full_mesh",
                      degree: int = 3, seed: int = 0,
                      n_validators: int = N_VALIDATORS):
    """A started static fleet (4 validators + fulls) wired per topology:
    (net, nodes dict, pvs, genesis). Chaos cells build on this."""
    from tendermint_tpu.p2p import InProcNetwork

    rig = _rig()
    vals, fulls = node_names(n_nodes, n_validators)
    pvs = {name: rig["make_pv"](name) for name in vals + fulls}
    genesis = rig["make_genesis"]([pvs[v] for v in vals], [10] * len(vals))
    nodes = {name: rig["ChurnNode"](name, genesis, pvs[name])
             for name in vals + fulls}
    net = InProcNetwork()
    for nd in nodes.values():
        net.add_switch(nd.switch)
    for nd in nodes.values():
        await nd.start()
    await net.connect_topology(topology, degree=degree, seed=seed)
    return net, nodes, pvs, genesis


async def _flap_async(cycles: int, seed: int) -> dict:
    """One node repeatedly leaving and re-joining (fresh stores each time,
    so every re-entry is a full statesync restore) while 4 validators + a
    stable full node keep committing. Asserts per cycle: the survivors
    never hold a peer object for the departed node (reconnect_missing must
    skip it), the rejoin catches up, and hashes stay identical."""
    import asyncio

    rig = _rig()
    net, nodes, pvs, genesis = await build_fleet(6, seed=seed)
    flapper = "full1"
    rejoin_s = []

    rewire_task = asyncio.create_task(rewire_loop(net, interval=0.2))
    try:
        await _wait_heights(list(nodes.values()), 2)
        for cycle in range(cycles):
            nd = nodes.pop(flapper)
            await net.remove_node(flapper)
            await nd.stop()
            survivors = list(nodes.values())
            h0 = max(s.height for s in survivors)
            await _wait_heights(survivors, h0 + 2)
            # several rewire passes ran while the flapper was away: no
            # survivor may have re-acquired it, and its id is marked
            assert flapper in net.departed
            for s in survivors:
                assert flapper not in s.switch.peers, \
                    f"{s.name} redialed departed {flapper} (cycle {cycle})"
            fresh = rig["ChurnNode"](flapper, genesis, pvs[flapper],
                                     fast_sync=True)
            nodes[flapper] = fresh
            rejoin_s.append(await join_statesync(
                net, fresh, nodes["val0"],
                [n for n in nodes if n != flapper], seed))
            assert flapper not in net.departed
        final = max(nd.height for nd in nodes.values()) + 2
        await _wait_heights(list(nodes.values()), final)
        h_common = min(nd.height for nd in nodes.values()) - 1
        hashes = {nd.block_store.load_block_meta(h_common).header.app_hash
                  for nd in nodes.values()}
        assert len(hashes) == 1, "hashes diverged under flapping"
        for nd in nodes.values():
            # the flapper's comings and goings must not bloat peer state
            assert len(nd.bc_reactor.scoreboard.snapshot()) <= len(nodes)
    finally:
        # one teardown for run AND invariant failures alike — leaked
        # consensus tasks would wedge asyncio.run's cleanup
        rewire_task.cancel()
        for nd in nodes.values():
            try:
                await nd.stop()
            except Exception:
                pass
    return {"cycles": cycles, "rejoin_caughtup_s": rejoin_s,
            "final_height": h_common + 1}


def run_flap(cycles: int = 3, seed: int = 1) -> dict:
    """The churn.flap scenario; returns its report (asserts on failure)."""
    import asyncio

    os.environ.setdefault("TMTPU_BATCH_BACKEND", "host")
    return asyncio.run(_flap_async(cycles, seed))


async def _gossip_async(n: int, blocks: int, topology: str, degree: int,
                        seed: int) -> dict:
    net, nodes, _pvs, _genesis = await build_fleet(
        n, topology=topology, degree=degree, seed=seed)
    try:
        await _wait_heights(list(nodes.values()), 2, timeout=300)
        h0 = max(nd.height for nd in nodes.values())
        t0 = time.monotonic()
        wak0 = sum(nd.wakeups() for nd in nodes.values())
        ec0 = [nd.encode_cache() for nd in nodes.values()]
        await _wait_heights(list(nodes.values()), h0 + blocks,
                            timeout=60.0 * blocks)
        elapsed = max(0.001, time.monotonic() - t0)
        wak = sum(nd.wakeups() for nd in nodes.values()) - wak0
        hits = sum(nd.encode_cache()[0] for nd in nodes.values()) \
            - sum(h for h, _ in ec0)
        miss = sum(nd.encode_cache()[1] for nd in nodes.values()) \
            - sum(m for _, m in ec0)
    finally:
        for nd in nodes.values():
            try:
                await nd.stop()
            except Exception:
                pass
    links = max(1, len(net.links))
    return {
        "n_nodes": n, "topology": topology, "directed_links": links,
        "blocks": blocks, "elapsed_s": round(elapsed, 2),
        # the rate is the scaling evidence (fleet_scrape's convention:
        # wakeup deltas over wall time per directed link) — per-BLOCK
        # numbers mislead at scale because block cadence slows with N
        "wakeups_per_link_per_s": round(wak / links / elapsed, 3),
        "wakeups_total_per_s": round(wak / elapsed, 3),
        "wakeups_per_link_per_block": round(wak / links / blocks, 3),
        "encode_cache_hit_ratio": round(hits / max(1.0, hits + miss), 3),
    }


def measure_gossip(n: int = 8, blocks: int = 3, topology: str = "sparse",
                   degree: int = 4, seed: int = 1) -> dict:
    """Gossip cost at size N: a static sparse fleet commits ``blocks``
    heights; reports the wakeup RATE per directed peer-link (plus the
    wire-encode cache hit ratio) — the bench's sublinearity evidence at
    N=8/16/32: a flat-or-falling per-link rate means each node's gossip
    cost tracks its DEGREE, not the fleet size."""
    import asyncio

    os.environ.setdefault("TMTPU_BATCH_BACKEND", "host")
    return asyncio.run(_gossip_async(n, blocks, topology, degree, seed))


def run_churn(n_nodes: int = 8, intervals: int = 2, seed: int = 1,
              topology: str = "full_mesh", degree: int = 3,
              rate: float = 10.0) -> dict:
    """One full churn run; returns the report dict (asserts on failure).
    Pure-python ed25519 keeps the rig independent of device kernels (and
    a join/leave per interval is mempool/gossip-bound, not verify-bound)."""
    import asyncio

    os.environ.setdefault("TMTPU_BATCH_BACKEND", "host")
    if n_nodes < N_VALIDATORS + 1:
        raise ValueError(f"need at least {N_VALIDATORS + 1} nodes")
    return asyncio.run(_run_async(n_nodes, intervals, seed, topology,
                                  degree, rate))


def schedule_fingerprint(report: dict) -> dict:
    """The deterministic slice of a report: the executed join/leave/rotate
    event order and the validator-set composition sequence (wall-clock
    fields excluded) — what two same-seed runs must agree on."""
    return {"executed": [list(e) for e in report["executed"]],
            "compositions": report["compositions"],
            "plan": report["plan"]}


# -- self-test (stdlib-only: plan + schema, the net runs live in chaos/bench) -

def self_test() -> int:
    # plan determinism + shape
    p1 = plan_churn(7, 3, 8)
    p2 = plan_churn(7, 3, 8)
    assert p1 == p2, "same-seed plans diverged"
    assert plan_churn(8, 3, 8) != p1, "seed does not vary the plan"
    assert len(p1["events"]) == 3
    for ev in p1["events"]:
        assert ev["join"].startswith("join")
        assert ev.get("leave", "full").startswith(("full", "join"))
        if "rotate_in" in ev:
            assert ev["rotate_out"] != "val0", "anchor must never rotate out"
    # compositions: constant size, change only on rotation
    sizes = {len(c) for c in p1["compositions"]}
    assert sizes == {N_VALIDATORS}, sizes
    n_rot = sum(1 for ev in p1["events"] if "rotate_in" in ev)
    assert len(p1["compositions"]) == 1 + n_rot
    # quorum safety: a leave never names a current validator
    vset = set(p1["compositions"][0])
    for ev, comp in zip(p1["events"],
                        p1["compositions"][1:] + [p1["compositions"][-1]]):
        assert ev.get("leave") not in vset, ev
        vset = set(comp)
    # roster helper
    vals, fulls = node_names(8)
    assert len(vals) == N_VALIDATORS and len(fulls) == 4
    vals, fulls = node_names(3)
    assert len(vals) == 3 and fulls == []
    # fingerprint strips wall-clock fields
    fake = {"executed": [("join", "join0")], "compositions": [["a"]],
            "plan": {"events": []}, "elapsed_s": 1.23,
            "join_caughtup_s": {"join0": 4.5}}
    fp = schedule_fingerprint(fake)
    assert "elapsed_s" not in json.dumps(fp)
    assert fp["executed"] == [["join", "join0"]]
    # the retain window must cover a snapshot (joiners depend on it)
    assert RETAIN_BLOCKS > 2 * SNAPSHOT_INTERVAL
    print("churn self-test OK (plan determinism, quorum safety, schema)")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--nodes", type=int, default=8)
    ap.add_argument("--intervals", type=int, default=2)
    ap.add_argument("--seed", type=int, default=1)
    ap.add_argument("--topology", choices=("full_mesh", "sparse"),
                    default="full_mesh")
    ap.add_argument("--degree", type=int, default=3)
    ap.add_argument("--rate", type=float, default=10.0,
                    help="open-loop tx rate during the run")
    ap.add_argument("--verify-determinism", action="store_true",
                    help="run TWICE with the same seed and assert identical "
                         "join/leave/commit schedules")
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--self-test", action="store_true")
    args = ap.parse_args(argv)
    if args.self_test:
        return self_test()

    r1 = run_churn(args.nodes, args.intervals, args.seed, args.topology,
                   args.degree, args.rate)
    if args.verify_determinism:
        r2 = run_churn(args.nodes, args.intervals, args.seed, args.topology,
                       args.degree, args.rate)
        f1, f2 = schedule_fingerprint(r1), schedule_fingerprint(r2)
        if f1 != f2:
            print("DETERMINISM FAIL:\n" + json.dumps(f1, indent=2)
                  + "\nvs\n" + json.dumps(f2, indent=2), file=sys.stderr)
            return 1
        r1["determinism_verified"] = True
    if args.json:
        print(json.dumps(r1, indent=2))
    else:
        print(f"churn OK: N={r1['n_nodes']} seed={r1['seed']} "
              f"{r1['topology']} h {r1['height_initial']}→"
              f"{r1['height_final']} "
              f"({r1['blocks_per_min']} blocks/min) "
              f"joins={r1['join_caughtup_s']} rotations={r1['rotations']} "
              f"wakeups/link/block={r1['wakeups_per_link_per_block']}"
              + (" [determinism verified]"
                 if r1.get("determinism_verified") else ""))
    return 0


if __name__ == "__main__":
    sys.exit(main())
