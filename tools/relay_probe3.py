"""Probe 3: is the relay delta-compressing near-identical transfers?

Times a small-compute kernel over 4MB payloads:
  A. one dispatch, fresh random payload each run
  B. two async dispatches, both fresh independent random payloads
  C. two async dispatches, second = copy of first with 2 bytes changed
  D. one dispatch, payload = previous run's payload with 2 bytes changed
"""

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

jax.config.update("jax_compilation_cache_dir",
                  os.path.join(os.path.dirname(os.path.dirname(
                      os.path.abspath(__file__))), ".jax_cache"))

rng = np.random.default_rng(0)
MB = 1 << 20
N = 4 * MB


@jax.jit
def touch(a):
    return jnp.sum(a, dtype=jnp.int32)


def bench(label, fn, runs=4):
    ts = []
    for i in range(runs):
        t0 = time.perf_counter()
        fn(i)
        ts.append(time.perf_counter() - t0)
    print(f"{label:52s} min {min(ts)*1e3:7.1f} ms  med {sorted(ts)[len(ts)//2]*1e3:7.1f} ms",
          flush=True)


def main():
    touch(rng.integers(0, 255, N, dtype=np.uint8))  # compile

    def fresh_one(i):
        a = rng.integers(0, 255, N, dtype=np.uint8)
        np.asarray(touch(a))

    def fresh_two(i):
        a = rng.integers(0, 255, N, dtype=np.uint8)
        b = rng.integers(0, 255, N, dtype=np.uint8)
        r1, r2 = touch(a), touch(b)
        np.asarray(r1), np.asarray(r2)

    def near_two(i):
        a = rng.integers(0, 255, N, dtype=np.uint8)
        b = a.copy()
        b[0] ^= 1
        b[N // 2] ^= 1
        r1, r2 = touch(a), touch(b)
        np.asarray(r1), np.asarray(r2)

    base = rng.integers(0, 255, N, dtype=np.uint8)

    def delta_one(i):
        base[i] ^= 1
        base[N // 2 + i] ^= 1
        np.asarray(touch(base))

    bench("A one dispatch, fresh 4MB", fresh_one)
    bench("B two dispatches, independent 4MB each", fresh_two)
    bench("C two dispatches, second is near-copy", near_two)
    bench("D one dispatch, near-copy of previous run", delta_one)


if __name__ == "__main__":
    main()
